"""BERT-based biencoder for learned retrieval (ICT / REALM / DPR-style).

Reference: megatron/model/biencoder_model.py (BiEncoderModel:71,
PretrainedBertModel:255 — CLS pooling + optional linear projection) and the
ICT in-batch contrastive loss of pretrain_ict.py:76-118.

TPU-native redesign of the loss: the reference all-gathers query/context
embeddings across the data-parallel group with a hand-written autograd
collective (pretrain_ict.py AllgatherFromDataParallelRegion:47-73) so every
rank scores against the *global* batch. Under SPMD the global batch is one
logical array sharded over ``dp`` — writing ``scores = q @ c.T`` makes XLA
insert exactly that all-gather (and its transpose in the backward), so the
whole apparatus reduces to a matmul.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from megatron_llm_tpu.models.bert import padding_bias
from megatron_llm_tpu.models.language_model import embed_tokens, init_model_params
from megatron_llm_tpu.models.transformer import transformer_forward
from megatron_llm_tpu.ops.norms import norm

Params = Dict[str, Any]


def _init_tower(cfg, key: jax.Array) -> Params:
    tower = init_model_params(cfg, key)
    tower.pop("lm_head", None)  # encoder only — no vocab head
    proj_dim = cfg.retriever.biencoder_projection_dim
    if proj_dim > 0:
        h = cfg.model.hidden_size
        tower["projection"] = {
            "kernel": cfg.model.init_method_std
            * jax.random.normal(jax.random.fold_in(key, 11), (h, proj_dim),
                                jnp.float32),
            "bias": jnp.zeros((proj_dim,), jnp.float32),
        }
    return tower


def init_biencoder_params(cfg, key: jax.Array) -> Params:
    """Two towers, or one shared (biencoder_shared_query_context_model).
    With cfg.retriever.bert_load set, the encoder weights of every tower are
    warm-started from that BERT checkpoint (init_state_dict_from_bert,
    biencoder_model.py:189-254); projections stay freshly initialized."""
    if cfg.retriever.biencoder_shared_query_context_model:
        params = {"shared_model": _init_tower(cfg, key)}
    else:
        kq, kc = jax.random.split(key)
        params = {"query_model": _init_tower(cfg, kq),
                  "context_model": _init_tower(cfg, kc)}
    if cfg.retriever.bert_load:
        bert = _load_bert_encoder(cfg.retriever.bert_load)
        for tower in params.values():
            for k in ("embedding", "layers", "final_norm"):
                tower[k] = jax.tree.map(jnp.asarray, bert[k])
    return params


def _load_bert_encoder(load_dir: str) -> Params:
    """Encoder subtree (embedding/layers/final_norm) of a saved BERT
    checkpoint (pretrain_bert.py output layout)."""
    import os

    import orbax.checkpoint as ocp

    from megatron_llm_tpu.checkpointing import checkpoint_dir, read_tracker

    iteration, release = read_tracker(load_dir)
    path = checkpoint_dir(os.path.abspath(load_dir), iteration or 0, release)
    params = ocp.StandardCheckpointer().restore(os.path.join(path, "params"))
    missing = {"embedding", "layers", "final_norm"} - set(params)
    if missing:
        raise ValueError(f"{load_dir}: not a BERT checkpoint "
                         f"(missing {sorted(missing)})")
    return params


def _towers(params: Params) -> Tuple[Params, Params]:
    if "shared_model" in params:
        return params["shared_model"], params["shared_model"]
    return params["query_model"], params["context_model"]


def biencoder_embed(
    cfg,
    tower: Params,
    tokens: jax.Array,        # [b, s]
    padding_mask: jax.Array,  # [b, s] 1=real
    tokentype_ids: Optional[jax.Array] = None,
    dropout_key: Optional[jax.Array] = None,
    deterministic: bool = True,
) -> jax.Array:
    """Embed a batch of texts -> [b, proj_dim or hidden] (CLS pooling,
    biencoder_model.py:298-310)."""
    m = cfg.model
    hidden = embed_tokens(cfg, tower, tokens, tokentype_ids=tokentype_ids)
    hidden, _, _moe_aux = transformer_forward(
        cfg, tower["layers"], hidden,
        attn_bias=padding_bias(padding_mask),
        dropout_key=dropout_key, deterministic=deterministic,
    )
    hidden = norm(hidden, tower["final_norm"], m.layernorm_epsilon,
                  m.use_rms_norm)
    pooled = hidden[:, 0]  # [CLS]
    if "projection" in tower:
        pooled = (pooled @ tower["projection"]["kernel"].astype(pooled.dtype)
                  + tower["projection"]["bias"].astype(pooled.dtype))
    return pooled.astype(jnp.float32)


def biencoder_forward(cfg, params: Params, batch: Dict[str, jax.Array], *,
                      dropout_key=None, deterministic=True):
    """Returns (query_embeds [b, d], context_embeds [b, d])."""
    qt, ct = _towers(params)
    kq = kc = None
    if dropout_key is not None:
        kq, kc = jax.random.split(dropout_key)
    q = biencoder_embed(cfg, qt, batch["query_tokens"],
                        batch["query_pad_mask"], dropout_key=kq,
                        deterministic=deterministic)
    c = biencoder_embed(cfg, ct, batch["context_tokens"],
                        batch["context_pad_mask"], dropout_key=kc,
                        deterministic=deterministic)
    return q, c


def ict_loss_from_batch(cfg, params: Params, batch: Dict[str, jax.Array], *,
                        dropout_key=None, deterministic=True,
                        rope_cache=None, sp_constraint=None):
    """In-batch contrastive retrieval loss (pretrain_ict.py loss_func:76-118):
    NLL of the matching context under softmax over all contexts in the global
    batch, plus top-k retrieval accuracies."""
    del rope_cache, sp_constraint  # bidirectional towers; absolute/none pos
    q, c = biencoder_forward(cfg, params, batch, dropout_key=dropout_key,
                             deterministic=deterministic)
    scores = q @ c.T  # [gbs, gbs]; XLA all-gathers the dp-sharded c
    if cfg.retriever.retriever_score_scaling:
        scores = scores / jnp.sqrt(jnp.float32(cfg.model.hidden_size))
    logp = jax.nn.log_softmax(scores, axis=-1)
    gbs = scores.shape[0]
    labels = jnp.arange(gbs)
    loss = -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()

    # top-k retrieval accuracy metrics (retriever_report_topk_accuracies)
    ranks = jnp.argsort(-scores, axis=-1)
    match = ranks == labels[:, None]  # [gbs, gbs] one-hot at the true rank
    metrics = {"lm loss": loss}
    for k in cfg.retriever.retriever_report_topk_accuracies:
        if k <= gbs:
            metrics[f"top{k}_acc"] = match[:, :k].any(axis=-1).mean() * 100.0
    return loss, metrics
