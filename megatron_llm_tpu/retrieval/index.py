"""Block-embedding store + maximum-inner-product search index.

Reference: megatron/data/realm_index.py — ``OpenRetreivalDataStore`` (pickled
dict of fp16 block embeddings + shard merge) and ``FaissMIPSIndex`` (faiss
IndexFlatIP behind ADD/SEARCH). This rebuild replaces faiss with an exact
MIPS on device: at REALM/ORQA evidence scale (~20M blocks x 128 dims fp16 =
~5 GB) a single TPU chip's HBM holds the whole matrix, and one
[queries, dim] @ [dim, blocks] matmul + top_k IS the flat-IP index — on the
MXU it is faster than an approximate CPU index, with none of the training/
quantization machinery. Shardable over a mesh axis for larger stores (the
matmul contraction stays local; top-k merges per shard).
"""

from __future__ import annotations

import os
import pickle
from typing import Dict, Optional, Tuple

import numpy as np


class BlockEmbedStore:
    """Serializable block-id -> embedding map (OpenRetreivalDataStore
    analog; fp16 storage, shard save/merge for multi-host index builds)."""

    def __init__(self, embedding_path: Optional[str] = None,
                 load_from_path: bool = False, rank: Optional[int] = None):
        self.embed_data: Dict[int, np.ndarray] = {}
        self.meta_data: Dict[int, np.ndarray] = {}
        self.embedding_path = embedding_path
        self.rank = rank
        if load_from_path and embedding_path:
            self.load_from_file()

    def add_block_data(self, row_ids, block_embeds, block_metas=None,
                       allow_overwrite: bool = False) -> None:
        for i, (rid, emb) in enumerate(zip(row_ids, block_embeds)):
            rid = int(rid)
            if not allow_overwrite and rid in self.embed_data:
                raise ValueError(f"duplicate block id {rid}")
            self.embed_data[rid] = np.asarray(emb, np.float16)
            if block_metas is not None:
                self.meta_data[rid] = np.asarray(block_metas[i])

    def __len__(self) -> int:
        return len(self.embed_data)

    def state(self) -> dict:
        return {"embed_data": self.embed_data, "meta_data": self.meta_data}

    def save(self, path: Optional[str] = None) -> None:
        path = path or self.embedding_path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "wb") as f:
            pickle.dump(self.state(), f)

    def save_shard(self) -> str:
        base, _ = os.path.splitext(self.embedding_path)
        os.makedirs(base + "_tmp", exist_ok=True)
        path = os.path.join(base + "_tmp", f"{self.rank or 0}.pkl")
        with open(path, "wb") as f:
            pickle.dump(self.state(), f)
        return path

    def merge_shards_and_save(self) -> None:
        """Combine every saved shard into one store file (the reference's
        consolidation step), then remove the shard directory."""
        base, _ = os.path.splitext(self.embedding_path)
        tmp = base + "_tmp"
        for name in sorted(os.listdir(tmp)):
            with open(os.path.join(tmp, name), "rb") as f:
                state = pickle.load(f)
            overlap = self.embed_data.keys() & state["embed_data"].keys()
            if overlap:
                raise ValueError(f"shard {name} overlaps {len(overlap)} ids")
            self.embed_data.update(state["embed_data"])
            self.meta_data.update(state.get("meta_data", {}))
        self.save()
        for name in os.listdir(tmp):
            os.remove(os.path.join(tmp, name))
        os.rmdir(tmp)

    def load_from_file(self) -> None:
        with open(self.embedding_path, "rb") as f:
            state = pickle.load(f)
        self.embed_data = state["embed_data"]
        self.meta_data = state.get("meta_data", {})

    def clear(self) -> None:
        """Free the embeddings only. meta_data intentionally survives — it
        is small and still needed to map block ids back to documents after
        the index is built (reference OpenRetreivalDataStore.clear,
        realm_index.py:41-47)."""
        self.embed_data = {}


class MIPSIndex:
    """Exact maximum-inner-product search (FaissMIPSIndex analog)."""

    def __init__(self, embed_size: int, store: Optional[BlockEmbedStore] = None,
                 use_device: bool = True):
        self.embed_size = embed_size
        self.use_device = use_device
        self._ids = np.zeros((0,), np.int64)
        self._matrix = np.zeros((0, embed_size), np.float32)
        self._device_matrix = None
        if store is not None and len(store):
            self.add_from_store(store)

    def __len__(self) -> int:
        return self._matrix.shape[0]

    def add(self, row_ids, embeds) -> None:
        embeds = np.asarray(embeds, np.float32)
        assert embeds.shape[1] == self.embed_size, embeds.shape
        self._ids = np.concatenate([self._ids, np.asarray(row_ids, np.int64)])
        self._matrix = np.concatenate([self._matrix, embeds], axis=0)
        self._device_matrix = None  # re-upload lazily

    def add_from_store(self, store: BlockEmbedStore) -> None:
        ids = sorted(store.embed_data)
        self.add(ids, np.stack([store.embed_data[i] for i in ids]))

    def search_mips_index(self, query_embeds, top_k: int,
                          reconstruct: bool = False
                          ) -> Tuple[np.ndarray, np.ndarray]:
        """Returns (scores [q, k], block_ids [q, k]) — faiss search contract.
        With reconstruct=True the second result is the embeddings [q, k, d]."""
        assert len(self) > 0, "empty index"
        q = np.asarray(query_embeds, np.float32)
        top_k = min(top_k, len(self))
        if self.use_device:
            import jax
            import jax.numpy as jnp

            if self._device_matrix is None:
                self._device_matrix = jax.device_put(self._matrix.T)
            scores = jnp.asarray(q) @ self._device_matrix
            vals, idx = jax.lax.top_k(scores, top_k)
            vals, idx = np.asarray(vals), np.asarray(idx)
        else:
            scores = q @ self._matrix.T
            idx = np.argsort(-scores, axis=-1)[:, :top_k]
            vals = np.take_along_axis(scores, idx, axis=-1)
        if reconstruct:
            return vals, self._matrix[idx]
        return vals, self._ids[idx]
