"""shard_map compatibility layer — the ONLY module allowed to import jax's
shard_map directly (tools/linter.py enforces this).

The parallel/ and ops/ code is written against the modern shard_map API:

* ``jax.shard_map(f, mesh=..., in_specs=..., out_specs=..., axis_names={...},
  check_vma=False)`` — partial-manual regions declared by ``axis_names``;
* ``jax.sharding.get_abstract_mesh()`` — the tracing-context mesh, whose
  ``manual_axes`` tell a nested region which axes an enclosing shard_map has
  already manualized (ops/attention._flash_sharded, parallel/ring.cp_is_manual).

The pinned jax 0.4.37 has neither: only ``jax.experimental.shard_map`` with
the older ``auto=``/``check_rep=`` spelling, and no context-mesh accessor.
This module bridges the gap:

* :func:`shard_map` accepts the modern signature and translates —
  ``axis_names`` becomes its complement ``auto``, ``check_vma`` becomes
  ``check_rep``, and an abstract-mesh argument (ours or jax's) resolves to
  the concrete mesh it wraps.
* :func:`get_abstract_mesh` emulates the context accessor with a
  thread-local stack pushed while a compat shard_map body is being traced.
* :func:`axis_index` works around ``lax.axis_index`` lowering to a
  ``PartitionId`` op that XLA's SPMD lowering rejects inside PARTIAL-manual
  regions on 0.4.37 (UNIMPLEMENTED under both GSPMD and shardy): for every
  partial-manual region, :func:`shard_map` appends one hidden
  ``jnp.arange(size)`` input per manual axis, sharded ``P(axis)``, so each
  shard receives its own coordinate as data; :func:`axis_index` returns
  that carried value when available and falls back to ``lax.axis_index``
  (full-manual regions, or modern jax) otherwise.

Partitioner note: 0.4.37's default GSPMD partitioner hard-crashes (CHECK
failure in spmd_partitioner.cc:512) on ``ppermute`` inside partial-manual
regions — the exact shape of the pipeline engine. The shardy partitioner
handles every composition this repo uses — but globally flipping it
perturbs reduction order in the plain pjit TP path (a bitwise-parity
regression in tests/test_tensor_parallel.py), so the flip is scoped:
:func:`mesh_needs_shardy` says whether a mesh layout reaches partial-manual
code (pp > 1 or cp > 1), and ``parallel_state.set_global_mesh`` /
``global_mesh`` call :func:`enable_partitioner_for` to flip (and restore)
``jax_use_shardy_partitioner`` accordingly. Meshes that only use dp/ep/tp
stay on GSPMD and keep today's bitwise behavior. ``MLT_NO_SHARDY=1`` opts
out entirely for debugging.

Residual-sharding patch: 0.4.37's ``_shard_map_partial_eval`` names vjp
residuals over ALL mesh axes, which is rejected when the shard_map nests
inside an enclosing manual region (the axes already manual cannot appear in
a GSPMD spec). Fixed upstream in later jax; here
:func:`_patch_partial_eval_residuals` subtracts the enclosing compat
region's manual axes, which restores the exact upstream behavior for the
compositions this repo uses (inner regions bind every remaining axis).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

__all__ = [
    "shard_map",
    "get_abstract_mesh",
    "axis_index",
    "axis_size",
    "mesh_needs_shardy",
    "enable_partitioner_for",
    "HAS_NATIVE_SHARD_MAP",
]

# Modern jax exposes the new API at the top level; 0.4.37 does not.
HAS_NATIVE_SHARD_MAP = hasattr(jax, "shard_map")

if not HAS_NATIVE_SHARD_MAP:
    from jax.experimental.shard_map import shard_map as _legacy_shard_map


def mesh_needs_shardy(mesh) -> bool:
    """True when this mesh layout reaches partial-manual shard_map code
    (the pipeline engine and the ring-attention paths): on 0.4.37 those
    must compile under the shardy partitioner (see module docstring)."""
    if HAS_NATIVE_SHARD_MAP or os.environ.get("MLT_NO_SHARDY"):
        return False
    shape = getattr(mesh, "shape", {})
    return shape.get("pp", 1) > 1 or shape.get("cp", 1) > 1


def enable_partitioner_for(mesh) -> bool:
    """Flip ``jax_use_shardy_partitioner`` if ``mesh`` needs it. Returns the
    PREVIOUS flag value so ``parallel_state.global_mesh`` can restore it
    (the flag participates in jit trace keys, so flipping is safe — cached
    executables for the other partitioner are simply not reused)."""
    prev = bool(jax.config.jax_use_shardy_partitioner)
    if mesh_needs_shardy(mesh) and not prev:
        jax.config.update("jax_use_shardy_partitioner", True)
    return prev


def restore_partitioner(prev: bool) -> None:
    if bool(jax.config.jax_use_shardy_partitioner) != prev:
        jax.config.update("jax_use_shardy_partitioner", prev)


# ---------------------------------------------------------------------------
# Context-mesh emulation
# ---------------------------------------------------------------------------


class CompatAbstractMesh:
    """Duck-type of ``jax.sharding.AbstractMesh`` for the legacy path.

    Carries the concrete mesh plus the axes manualized by the enclosing
    compat shard_map regions; also usable as the ``mesh=`` argument of a
    nested :func:`shard_map` (the modern nested-manual idiom).
    """

    def __init__(self, mesh: Optional[Mesh], manual_axes, index_vals=None):
        self._mesh = mesh
        self.manual_axes = frozenset(manual_axes)
        # axis name -> per-shard coordinate scalar (partial-manual regions)
        self._axis_index_vals = dict(index_vals or {})

    @property
    def empty(self) -> bool:
        return self._mesh is None

    @property
    def axis_names(self):
        return self._mesh.axis_names if self._mesh is not None else ()

    @property
    def shape(self):
        return self._mesh.shape if self._mesh is not None else {}

    def __repr__(self):
        return (f"CompatAbstractMesh({self._mesh!r}, "
                f"manual_axes={sorted(self.manual_axes)})")


_EMPTY_MESH = CompatAbstractMesh(None, ())


class _TraceContext(threading.local):
    def __init__(self):
        self.stack = []


_trace_ctx = _TraceContext()


def get_abstract_mesh():
    """The mesh of the innermost shard_map region being traced (modern:
    jax.sharding.get_abstract_mesh; legacy: the compat-tracked context).
    ``.empty`` is True outside any region."""
    if HAS_NATIVE_SHARD_MAP:
        return jax.sharding.get_abstract_mesh()
    return _trace_ctx.stack[-1] if _trace_ctx.stack else _EMPTY_MESH


def axis_index(name: str) -> jax.Array:
    """``lax.axis_index`` that also works inside legacy partial-manual
    regions (see module docstring). Identical semantics otherwise."""
    if not HAS_NATIVE_SHARD_MAP and _trace_ctx.stack:
        carried = _trace_ctx.stack[-1]._axis_index_vals.get(name)
        if carried is not None:
            return carried
    return jax.lax.axis_index(name)


def axis_size(name: str) -> int:
    """``lax.axis_size`` (modern) — on 0.4.37 resolved from the compat
    tracing context, falling back to ``psum(1, name)`` (which jax folds to
    a constant) for regions bound by non-compat machinery."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(name)
    if _trace_ctx.stack:
        top = _trace_ctx.stack[-1]
        if name in top.shape:
            return top.shape[name]
    return jax.lax.psum(1, name)


# ---------------------------------------------------------------------------
# shard_map
# ---------------------------------------------------------------------------


def _resolve_mesh(mesh: Any) -> Mesh:
    if isinstance(mesh, CompatAbstractMesh):
        assert mesh._mesh is not None, "shard_map over an empty mesh"
        return mesh._mesh
    return mesh


def shard_map(
    f,
    *,
    mesh,
    in_specs,
    out_specs,
    axis_names=None,
    check_vma: Optional[bool] = None,
    check_rep: Optional[bool] = None,
):
    """Modern-signature shard_map resolved against the running jax.

    ``axis_names`` — axes THIS region manualizes (default: every axis of
    ``mesh``); the rest stay auto (GSPMD-partitioned). ``mesh`` may be a
    concrete Mesh, a modern AbstractMesh, or the CompatAbstractMesh from
    :func:`get_abstract_mesh` when nesting inside an enclosing region.
    ``check_vma`` (modern) / ``check_rep`` (legacy) are aliases.
    """
    if HAS_NATIVE_SHARD_MAP:
        kwargs = {}
        if axis_names is not None:
            kwargs["axis_names"] = set(axis_names)
        if check_vma is not None or check_rep is not None:
            kwargs["check_vma"] = bool(
                check_vma if check_vma is not None else check_rep
            )
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )

    concrete = _resolve_mesh(mesh)
    all_names = frozenset(concrete.axis_names)
    manual = frozenset(axis_names) if axis_names is not None else all_names
    unknown = manual - all_names
    assert not unknown, f"axis_names {unknown} not in mesh {all_names}"
    # Legacy spelling: ``auto`` is the complement of what this region binds.
    # Axes an ENCLOSING region already manualized also belong in auto —
    # 0.4.37 resolves them from the tracing context (verified by the nested
    # compositions in tests/test_flash_sharded.py).
    auto = all_names - manual
    rep = check_vma if check_vma is not None else check_rep
    # Partial-manual + replication checking is unsupported on 0.4.37; every
    # caller passes False anyway.
    rep = bool(rep) if rep is not None and not auto else False

    outer = _trace_ctx.stack[-1] if _trace_ctx.stack else None
    outer_manual = outer.manual_axes if outer is not None else frozenset()
    outer_vals = outer._axis_index_vals if outer is not None else {}
    region_manual = manual | outer_manual

    # Hidden data-carried axis coordinates for partial-manual regions (the
    # lax.axis_index workaround): one [size]-arange per newly-manual axis,
    # sharded over that axis, so each shard's slice holds its coordinate.
    partial = bool(auto)
    idx_axes = tuple(sorted(manual)) if partial else ()

    # NB: PartitionSpec subclasses tuple on 0.4.37 — test it first, or a
    # bare spec would be exploded into its axis entries.
    if isinstance(in_specs, P) or not isinstance(in_specs, (tuple, list)):
        in_specs = (in_specs,)
    full_in_specs = tuple(in_specs) + tuple(P(ax) for ax in idx_axes)

    def wrapped(*args):
        vals = dict(outer_vals)
        if idx_axes:
            n = len(idx_axes)
            idx_args = args[-n:]
            args = args[:-n]
            vals.update({
                ax: idx_args[i][0] for i, ax in enumerate(idx_axes)
            })
        ctx = CompatAbstractMesh(concrete, region_manual, vals)
        _trace_ctx.stack.append(ctx)
        try:
            return f(*args)
        finally:
            _trace_ctx.stack.pop()

    mapped = _legacy_shard_map(
        wrapped, concrete, in_specs=full_in_specs, out_specs=out_specs,
        check_rep=rep, auto=frozenset(auto),
    )

    def call(*args):
        if idx_axes:
            extra = tuple(
                jnp.arange(concrete.shape[ax], dtype=jnp.int32)
                for ax in idx_axes
            )
            return mapped(*args, *extra)
        return mapped(*args)

    return call


# ---------------------------------------------------------------------------
# 0.4.37 residual-sharding patch (see module docstring)
# ---------------------------------------------------------------------------


def _patch_partial_eval_residuals() -> None:
    """0.4.37 names vjp/remat residuals ``{0: all_mesh_axes}`` — i.e. the
    stacked-shards dim sharded over EVERY axis, including the region's auto
    axes. For partial-manual regions that emits illegal shardings (manual
    axes trailing free axes in the sdy annotation; outright rejected when
    the region nests inside another manual region). Upstream later fixed
    residual names to cover only the region's manual axes; this reproduces
    that by threading each region's ``auto`` set through a contextvar into
    ``_all_mesh_names_except_spmd``."""
    import contextvars

    from jax.experimental import shard_map as _sm_mod

    cur_auto = contextvars.ContextVar("mlt_shard_map_auto",
                                      default=frozenset())

    orig_helper = _sm_mod._all_mesh_names_except_spmd

    def helper(mesh, trace=None):
        auto = cur_auto.get()
        return tuple(n for n in orig_helper(mesh, trace) if n not in auto)

    _sm_mod._all_mesh_names_except_spmd = helper

    orig_pe = _sm_mod._shard_map_partial_eval

    def pe_wrap(trace, shard_map_p, f, tracers, mesh, in_names,
                out_names_thunk, check_rep, rewrite, auto):
        token = cur_auto.set(frozenset(auto))
        try:
            return orig_pe(trace, shard_map_p, f, tracers, mesh, in_names,
                           out_names_thunk, check_rep, rewrite, auto)
        finally:
            cur_auto.reset(token)

    _sm_mod._shard_map_partial_eval = pe_wrap
    # process_shard_map captured the original function object — rebind it.
    _sm_mod.pe.JaxprTrace.process_shard_map = pe_wrap

    orig_pcp = _sm_mod._pe_custom_params

    def pcp_wrap(unks_in, inst_in, kept_outs_known, kept_outs_staged,
                 in_fwd, out_fwd, which, params_known, params_staged):
        token = cur_auto.set(
            frozenset(params_known.get("auto", frozenset()))
        )
        try:
            return orig_pcp(unks_in, inst_in, kept_outs_known,
                            kept_outs_staged, in_fwd, out_fwd, which,
                            params_known, params_staged)
        finally:
            cur_auto.reset(token)

    _sm_mod._pe_custom_params = pcp_wrap


if not HAS_NATIVE_SHARD_MAP:
    _patch_partial_eval_residuals()
