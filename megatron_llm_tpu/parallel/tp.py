"""Tensor-parallel + sequence-parallel sharding rules.

This module is the TPU-native replacement of the reference's explicit TP layer
classes (megatron/core/tensor_parallel/layers.py: ColumnParallelLinear:410,
RowParallelLinear:566, VocabParallelEmbedding:128) and its conjugate-pair
autograd collectives (mappings.py:13-278). Instead of classes issuing NCCL
calls, parallelism is *data placement*: every parameter gets a
``PartitionSpec`` over the (dp, pp, cp, tp) mesh and XLA inserts exactly the
collectives the reference hand-codes —

* column-parallel linear  = kernel sharded on its output axis (`tp`);
  forward needs no comm (identity copy, mappings.py:253-254)
* row-parallel linear     = kernel sharded on its input axis; the contraction
  produces the all-reduce (mappings.py:257) or, with sequence parallelism,
  a reduce-scatter onto the seq-sharded result (layers.py:292)
* vocab-parallel embedding/head = table sharded on the vocab axis; the lookup
  masked-gather + all-reduce (layers.py:187-210) is XLA's gather lowering
* sequence parallelism    = activation sharding constraint putting the seq
  axis on `tp` between blocks (scatter/gather regions, mappings.py:191-247)

Shardings are derived from parameter-path rules, not stored per-layer, so the
same tree works for any tp/pp/dp and for checkpoint resharding.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from megatron_llm_tpu.core.parallel_state import (
    CP_AXIS,
    DATA_AXES,
    DP_AXIS,
    EP_AXIS,
    PP_AXIS,
    TP_AXIS,
)

# Grad accumulation / FSDP-style extra sharding could compose here later.


def _spec_for_path(path: tuple, ndim: int, stacked: bool) -> P:
    """Sharding rule for one parameter, keyed on its tree path.

    ``stacked`` marks per-layer parameters carrying a leading layer axis
    (from init_stacked_layers / scan).
    """
    names = [getattr(k, "key", getattr(k, "name", str(k))) for k in path]
    # stacked per-layer params carry the layer axis first; sharding it over
    # ``pp`` IS pipeline stage placement (pp=1 meshes make it a no-op)
    lead = (PP_AXIS,) if stacked else ()

    def spec(*rest):
        return P(*lead, *rest)

    if "word_embeddings" in names:
        return P(TP_AXIS, None)  # vocab-parallel (VocabParallelEmbedding)
    if "position_embeddings" in names or "tokentype_embeddings" in names:
        return P(None, None)
    if "lm_head_bias" in names or "vocab_bias" in names:
        return P(TP_AXIS)  # vocab-parallel logits bias (BERT/T5 heads)
    if "mlm_head" in names or "pooler" in names or "binary_head" in names:
        # BERT transform/pooler/binary heads: small [h, h]-ish, replicated
        return P(*lead, *([None] * (ndim - len(lead))))
    if "lm_head" in names:
        return P(None, TP_AXIS)  # column-parallel output head
    if "qkv" in names:
        if names[-1] in ("kernel", "kernel_q"):
            return spec(None, TP_AXIS)  # column-parallel: shard fused head dim
        return spec(TP_AXIS)  # bias
    if "cross_attention" in names and names[-2] in ("q", "kv"):
        # T5 decoder inter-attention projections: column-parallel over heads
        if names[-1] in ("kernel", "kernel_q"):
            return spec(None, TP_AXIS)
        return spec(TP_AXIS)
    if "dense" in names:
        if names[-1] in ("kernel", "kernel_q"):
            return spec(TP_AXIS, None)  # row-parallel: shard input (head) dim
        return spec(None)  # row-parallel bias is replicated (added post-reduce)
    if "router" in names:
        # MoE router [h, E]: small, fp32, replicated (models/moe.py)
        return spec(*([None] * (ndim - len(lead))))
    if "experts" in names:
        # MoE expert FFN stacks: leading expert axis sharded over ep, the
        # ffn axis over tp — each (ep, tp) shard holds E/ep experts' tp-slice
        # (column/row-parallel per expert, exactly the dense fc1/fc2 rule).
        if "fc1" in names:
            if names[-1] in ("kernel", "kernel_q"):
                # [E, h, 2, ffn] (GLU) or [E, h, ffn]
                return (spec(EP_AXIS, None, None, TP_AXIS)
                        if ndim == 4 + len(lead) else spec(EP_AXIS, None, TP_AXIS))
            # bias [E, 2, ffn] or [E, ffn]
            return (spec(EP_AXIS, None, TP_AXIS)
                    if ndim == 3 + len(lead) else spec(EP_AXIS, TP_AXIS))
        if "fc2" in names:
            if names[-1] in ("kernel", "kernel_q"):
                return spec(EP_AXIS, TP_AXIS, None)  # [E, ffn, h] row-parallel
            return spec(EP_AXIS, None)  # [E, h] added post-reduce
    if "fc1" in names:
        if names[-1] in ("kernel", "kernel_q"):
            # [h, 2, ffn] (GLU) or [h, ffn]: shard the ffn axis
            return spec(None, None, TP_AXIS) if ndim == 3 + len(lead) else spec(None, TP_AXIS)
        return spec(None, TP_AXIS) if ndim == 2 + len(lead) else spec(TP_AXIS)
    if "fc2" in names:
        if names[-1] in ("kernel", "kernel_q"):
            return spec(TP_AXIS, None)  # row-parallel
        return spec(None)
    # norms, everything else: replicated (layer-stacked keeps lead axis)
    return P(*lead, *([None] * (ndim - len(lead))))


def param_partition_specs(params: Any) -> Any:
    """Build a PartitionSpec pytree mirroring ``params``.

    Works on a params tree or a tree of ShapeDtypeStruct (for eval_shape-based
    initialization without materializing).
    """

    def rule(path, leaf):
        names = [getattr(k, "key", getattr(k, "name", str(k))) for k in path]
        stacked = "layers" in names or "decoder_layers" in names
        return _spec_for_path(path, leaf.ndim, stacked)

    return jax.tree_util.tree_map_with_path(rule, params)


def param_shardings(mesh: Mesh, params: Any) -> Any:
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), param_partition_specs(params)
    )


# ---------------------------------------------------------------------------
# Activation sharding
# ---------------------------------------------------------------------------


def batch_spec(sequence_parallel: bool, context_parallel: bool = False) -> P:
    """Spec for [batch, seq, ...] activations on the residual stream.

    Sequence parallelism (reference §2.1 SP row: scatter along seq between TP
    ranks in LN/dropout regions) = putting the seq axis on `tp` here; XLA then
    emits the all-gather before column-linears and the reduce-scatter after
    row-linears exactly as layers.py:225-296 does by hand.

    Context parallelism stacks on top: the seq axis is sharded over cp always
    (ring attention, parallel/ring.py) and additionally over tp in the
    LN/dropout regions when SP is also on.
    """
    if context_parallel:
        seq = (CP_AXIS, TP_AXIS) if sequence_parallel else CP_AXIS
    else:
        seq = TP_AXIS if sequence_parallel else None
    return P(DATA_AXES, seq, None)


def data_spec(context_parallel: bool = False) -> P:
    """Spec for integer batch tensors [batch, seq]: batch over (dp, ep), and
    the seq axis over cp when context parallelism is active."""
    return P(DATA_AXES, CP_AXIS if context_parallel else None)


def batch_shardings(cfg, mesh: Mesh, batch: Any) -> Any:
    """Per-key shardings for a batch dict: [b, s] tensors get the data spec,
    rank-1 per-sample tensors (e.g. BERT ``is_random``) shard over dp only,
    and ``token_idx`` (the [s] zigzag index vector) shards over cp."""
    import numpy as np

    cp = cfg.parallel.context_parallel_size > 1
    d = NamedSharding(mesh, data_spec(cp))
    per_sample = NamedSharding(mesh, P(DATA_AXES))
    idx = NamedSharding(mesh, P(CP_AXIS) if cp else P(None))

    def spec_for(k, v):
        if k == "token_idx":
            return idx
        ndim = getattr(v, "ndim", None)
        if ndim is None:
            ndim = np.asarray(v).ndim
        return per_sample if ndim == 1 else d

    return {k: spec_for(k, v) for k, v in batch.items()}


def make_sp_constraint(cfg, mesh: Optional[Mesh] = None):
    """Return a callable constraining residual-stream activations, or None."""
    spec = batch_spec(
        cfg.parallel.sequence_parallel,
        cfg.parallel.context_parallel_size > 1,
    )

    def constrain(x):
        return jax.lax.with_sharding_constraint(x, spec)

    return constrain


def logits_spec() -> P:
    """Logits [b, s, vocab]: vocab sharded over tp (vocab-parallel CE)."""
    return P(DATA_AXES, None, TP_AXIS)


# ---------------------------------------------------------------------------
# Overlap-aware apply functions (parallel/overlap.py)
# ---------------------------------------------------------------------------
#
# The spec rules above tell XLA *where* tensors live; these apply functions
# are the explicit interception point for *how* the TP collectives run.
# The transformer sublayers route their row/column projections through
# them: inactive (the default --tp_overlap off, tp == 1, pp/cp layouts,
# quantized/fp8 kernels) they ARE the plain projection, byte for byte;
# active, the projection becomes the chunked collective-matmul ring that
# pipelines the all-reduce/reduce-scatter (row) or all-gather (column+SP)
# against its own GEMM.  Lazy import keeps tp.py free of a hard overlap
# dependency for spec-only users (checkpoint resharding tools).


def apply_row_parallel(cfg, p, x, linear):
    """Row-parallel projection (attention ``dense``, ``fc2``)."""
    from megatron_llm_tpu.parallel import overlap

    return overlap.row_parallel(cfg, p, x, linear)


def apply_column_parallel(cfg, p, x, linear):
    """Column-parallel projection (``qkv``, ``fc1``)."""
    from megatron_llm_tpu.parallel import overlap

    return overlap.column_parallel(cfg, p, x, linear)
