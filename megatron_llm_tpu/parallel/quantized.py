"""EQuARX-style quantized data-parallel gradient all-reduce.

Motivation (PAPERS.md, "EQuARX: Efficient Quantized AllReduce in XLA"):
DP gradient sync moves every parameter's gradient across the dp axis each
step — at bf16 that is 2 bytes/param/step of interconnect traffic that
the step cannot hide once the model is large relative to the per-step
compute.  Quantizing the wire format to int8 with per-chunk scales
recovers roughly half of that bandwidth at a bounded numerical cost.

Scheme (:func:`quantized_allreduce_mean`), the classic quantized
reduce-scatter + all-gather decomposition:

1. **chunk + quantize** — the flat gradient pads to ``dp`` equal chunks;
   each rank quantizes every chunk with its own symmetric absmax scale
   (int8 wire format, one fp32 scale per chunk).
2. **reduce-scatter** (``all_to_all``) — chunk ``r`` of every rank lands
   on rank ``r``, still quantized: the wire moves 1 byte/element.
3. **dequant-accumulate** — rank ``r`` dequantizes the ``dp`` versions of
   its chunk with their senders' scales and sums in fp32, then divides by
   ``dp``.  Each contribution is quantized exactly ONCE — no per-hop
   requantization error compounding (the advantage over a quantized ring).
4. **requantize + all-gather** — the mean chunk requantizes under a fresh
   scale and gathers back to every rank (1 byte/element again), then
   dequantizes into the gradient dtype.

Error bound: each element suffers at most one sender-side and one
result-side rounding, ``<= s_in/2 + s_out/2`` with ``s = chunk
absmax/127`` — the figure the loss-delta gate in
tests/test_kv_quant.py measures against a bf16-sync baseline
(docs/guide/quantization.md "Quantized collectives" documents the
accepted delta and when NOT to enable this).

Small leaves (norm scales, biases — ``size < min_quant_size``) keep the
exact ``pmean``: their bytes are negligible and their gradients are the
precision-sensitive ones.

Integration (:func:`make_quantized_dp_grad_fn`): the whole
forward/backward/accumulate runs inside ONE full-manual
``parallel/compat.shard_map`` region over the mesh — each dp rank
computes grads on its local batch shard, then the explicit quantized sync
above replaces the all-reduce XLA would otherwise emit implicitly from
the replicated-params/sharded-batch contraction.  Like the reference's
DDP, the loss is the dp-mean of per-rank masked means (identical to the
global mean whenever shards carry equal loss-mask counts).  Scope:
dp-pure meshes (tp == pp == cp == ep == 1) — the row-parallel tp
all-reduces live inside the forward where XLA owns them; quantizing those
is future work under the same flag family.  ``--quantized_grad_allreduce``
is OFF by default; the bf16-sync path is bitwise untouched.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from megatron_llm_tpu.core.parallel_state import DP_AXIS
from megatron_llm_tpu.parallel import compat

# leaves smaller than this sync exactly (pmean): quantizing a [h] norm
# gradient saves nothing on the wire and costs the most precision
MIN_QUANT_SIZE = 4096

_EPS = 1e-20


def _quant_chunks(x32: jax.Array, n: int):
    """[n, c] fp32 -> (int8 values, [n] fp32 scales), symmetric absmax."""
    s = jnp.max(jnp.abs(x32), axis=1) / 127.0
    q = jnp.clip(jnp.round(x32 / jnp.maximum(s, _EPS)[:, None]),
                 -127.0, 127.0).astype(jnp.int8)
    return q, s


def quantized_allreduce_mean(x: jax.Array, axis_name: str, axis_size: int,
                             min_quant_size: int = MIN_QUANT_SIZE
                             ) -> jax.Array:
    """dp-mean of ``x`` with int8 chunk-quantized traffic (module
    docstring).  Must run inside a manual region binding ``axis_name``;
    returns the mean in ``x``'s dtype, identical bytes on every rank."""
    if axis_size == 1:
        return x
    if x.size < min_quant_size:
        return jax.lax.pmean(x, axis_name)
    flat = x.astype(jnp.float32).reshape(-1)
    pad = (-flat.size) % axis_size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    c = flat.size // axis_size
    q, s = _quant_chunks(flat.reshape(axis_size, c), axis_size)
    # reduce-scatter: chunk r of every rank -> rank r (quantized wire)
    q_x = jax.lax.all_to_all(q, axis_name, 0, 0)            # [dp, c]
    s_x = jax.lax.all_to_all(s.reshape(axis_size, 1), axis_name, 0, 0)
    acc = jnp.sum(q_x.astype(jnp.float32) * s_x, axis=0) / axis_size
    # requantize the mean chunk, gather quantized, dequantize locally
    s_out = jnp.max(jnp.abs(acc)) / 127.0
    q_out = jnp.clip(jnp.round(acc / jnp.maximum(s_out, _EPS)),
                     -127.0, 127.0).astype(jnp.int8)
    q_g = jax.lax.all_gather(q_out, axis_name, axis=0)      # [dp, c]
    s_g = jax.lax.all_gather(s_out, axis_name, axis=0)      # [dp]
    out = (q_g.astype(jnp.float32) * s_g[:, None]).reshape(-1)
    if pad:
        out = out[:-pad]
    return out.reshape(x.shape).astype(x.dtype)


def quantized_dp_supported(cfg, mesh) -> bool:
    """Is the quantized DP sync applicable to this (cfg, mesh)?  dp-pure
    meshes only; anything else keeps the implicit XLA all-reduce."""
    if mesh is None:
        return False
    shape = dict(mesh.shape)
    if shape.get(DP_AXIS, 1) <= 1:
        return False
    others = {k: v for k, v in shape.items() if k != DP_AXIS}
    return all(v == 1 for v in others.values())


def make_quantized_dp_grad_fn(cfg, mesh: Mesh, loss_fn: Callable,
                              num_micro: int, fwd_scope: str = "forward"):
    """Build ``qdp(params, batch, base_key, scale) -> ((loss, metrics),
    grads)`` — the drop-in replacement for the train step's
    grad-accumulation branch when ``--quantized_grad_allreduce`` is on.

    ``loss_fn`` is the family loss (signature of
    models/language_model.loss_from_batch).  The returned callable builds
    the full-manual shard_map at trace time (the batch's pytree structure
    picks the per-leaf input specs), so it composes with jit exactly like
    the branches it replaces."""
    assert quantized_dp_supported(cfg, mesh), (
        "--quantized_grad_allreduce needs a dp-pure mesh (dp > 1, "
        "tp == pp == cp == ep == 1); the tp/pp collectives are emitted "
        "inside the forward where XLA owns them")
    names = set(mesh.axis_names)
    N = int(dict(mesh.shape)[DP_AXIS])
    deterministic = (cfg.model.hidden_dropout == 0.0
                     and cfg.model.attention_dropout == 0.0)

    def body(params, batch, base_key, scale):
        from megatron_llm_tpu.models.language_model import make_rope_cache

        rope = make_rope_cache(cfg)
        rank = compat.axis_index(DP_AXIS)

        def scaled(p, mb, k):
            with jax.named_scope(fwd_scope):
                loss, mets = loss_fn(
                    cfg, p, mb, dropout_key=k,
                    deterministic=deterministic, rope_cache=rope,
                    sp_constraint=None)
            return loss * jax.lax.stop_gradient(scale), mets

        gfn = jax.value_and_grad(scaled, has_aux=True)

        def key_for(idx):
            if deterministic:
                return None
            # per-rank, per-microbatch dropout streams (the baseline's
            # fold_in(base, idx), further folded by dp coordinate so
            # shards never share a pattern)
            return jax.random.fold_in(jax.random.fold_in(base_key, idx),
                                      rank)

        if num_micro == 1:
            (loss, mets), grads = gfn(params, batch, key_for(0))
        else:
            from megatron_llm_tpu.training_step import _split_microbatches

            mbs = _split_microbatches(batch, num_micro)
            first_mb = jax.tree.map(lambda a: a[0], mbs)
            mets0 = jax.tree.map(
                jnp.zeros_like,
                jax.eval_shape(lambda p, mb: scaled(p, mb, key_for(0))[1],
                               params, first_mb))
            zeros = jax.tree.map(
                lambda p: jnp.zeros(p.shape, p.dtype), params)

            def accum(carry, xs):
                g_sum, l_sum, m_sum = carry
                mb, idx = xs
                (l, mets), g = gfn(params, mb, key_for(idx))
                return (jax.tree.map(jnp.add, g_sum, g), l_sum + l,
                        jax.tree.map(jnp.add, m_sum, mets)), None

            (g_sum, l_sum, m_sum), _ = jax.lax.scan(
                accum, (zeros, jnp.zeros((), jnp.float32), mets0),
                (mbs, jnp.arange(num_micro)))
            inv = 1.0 / num_micro
            grads = jax.tree.map(lambda g: g * inv, g_sum)
            loss = l_sum * inv
            mets = jax.tree.map(lambda m: m * inv, m_sum)

        # THE quantized sync: int8 reduce-scatter + all-gather per leaf
        with jax.named_scope("quantized-dp-allreduce"):
            grads = jax.tree.map(
                lambda g: quantized_allreduce_mean(g, DP_AXIS, N), grads)
        loss = jax.lax.pmean(loss, DP_AXIS)
        mets = jax.tree.map(lambda m: jax.lax.pmean(m, DP_AXIS), mets)
        return (loss, mets), grads

    def qdp(params, batch, base_key, scale):
        bspecs = {k: (P() if k == "token_idx" else P(DP_AXIS))
                  for k in batch}
        mapped = compat.shard_map(
            body, mesh=mesh,
            in_specs=(P(), bspecs, P(), P()),
            out_specs=((P(), P()), P()),
            axis_names=names, check_vma=False)
        return mapped(params, batch, base_key, scale)

    return qdp
