"""Pipeline parallelism: collective-permute microbatch pipelining inside jit.

Replaces the reference's pipeline engine (megatron/schedules.py:606-722 1F1B,
p2p_communication.py isend/irecv) with the TPU-native formulation:

* stage placement is *data placement*: the stacked layer axis [L, ...] is
  sharded over the ``pp`` mesh axis (each stage holds L/pp contiguous layers)
  — no per-stage module classes, and checkpoint resharding over pp is a
  resharding no-op.
* stage transfer is ``lax.ppermute`` over ``pp`` inside a ``lax.scan`` over
  microbatch "ticks" — XLA lowers it to ICI collective-permute, the hardware
  analog of the reference's batched isend/irecv (p2p_communication.py:205-231).
* the schedule: every stage computes each tick; tick t feeds microbatch t into
  stage 0; the last stage emits microbatch t-(pp-1) at tick t. Total ticks
  M + pp - 1 — the same bubble as the reference's warmup(pp-rank-1)/steady/
  cooldown accounting (schedules.py:648-720).
* backward is autodiff through the scan: ppermute transposes to the reverse
  permute, giving the mirrored cooldown. This is a GPipe-style schedule
  (all-forward-then-all-backward per jit step) with per-stage remat; a true
  interleaved 1F1B with jax.vjp staging is an optimization slot for later
  rounds.
* only ``pp`` is manual (shard_map axis_names={'pp'}): dp/tp/sp shardings
  inside the stage body stay under GSPMD exactly as in the pp=1 path.

Embedding, final norm, and the LM head run outside the pipelined region,
replicated over pp (their grads psum over pp automatically under pjit) —
which also implements the reference's first/last-stage embedding tying
(module.py:52-121) without an explicit embedding group.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from megatron_llm_tpu.core import rng as rng_mod
from megatron_llm_tpu.core.parallel_state import CP_AXIS, PP_AXIS
from megatron_llm_tpu.models import language_model as lm
from megatron_llm_tpu.models.transformer import transformer_forward
from megatron_llm_tpu.ops.cross_entropy import softmax_cross_entropy
from megatron_llm_tpu.ops.norms import norm


def _stage_body(cfg, layers_local, x, aux, token_idx, dropout_key,
                deterministic, rope):
    """Run this stage's local layers on one microbatch of hidden states."""
    pp = jax.lax.axis_size(PP_AXIS)
    stage = jax.lax.axis_index(PP_AXIS)
    if dropout_key is not None:
        # distinct dropout streams per cp seq-chunk (analog of the reference's
        # per-TP-rank RNG fork inside parallel regions, random.py:144-172)
        dropout_key = jax.random.fold_in(
            dropout_key, jax.lax.axis_index(CP_AXIS)
        )
    layers_per_stage = jax.tree_util.tree_leaves(layers_local)[0].shape[0]
    hidden, _ = transformer_forward(
        cfg, layers_local, x,
        rope=rope,
        position_ids=aux.get("position_ids"),
        segment_ids=aux.get("segment_ids"),
        token_idx=token_idx,
        dropout_key=dropout_key,
        deterministic=deterministic,
        layer_offset=stage * layers_per_stage,
    )
    return hidden


def pipeline_apply(cfg, mesh, stacked_layers, hidden_mb: jax.Array,
                   aux_mb: Dict[str, jax.Array], dropout_key, deterministic,
                   rope, token_idx: Optional[jax.Array] = None):
    """Run the pipelined transformer body.

    hidden_mb: [M, mb, s, h] embedded microbatches; aux_mb leaves [M, mb, s];
    token_idx: optional [s] zigzag index vector (parallel/ring.py).
    Returns [M, mb, s, h] final hidden states (replicated over pp).
    """
    pp = cfg.parallel.pipeline_model_parallel_size
    M = hidden_mb.shape[0]
    if token_idx is None:
        # constant placeholder so the shard_map signature is static; the
        # sentinel -1 row is never read (selected below)
        token_idx_arr = jnp.full((hidden_mb.shape[2],), -1, jnp.int32)
    else:
        token_idx_arr = token_idx

    def body(layers_local, hidden_mb, aux_mb, token_idx_local):
        stage = jax.lax.axis_index(PP_AXIS)
        perm = [(i, (i + 1) % pp) for i in range(pp)]

        def tick(carry, t):
            recv = carry
            mb_idx = jnp.clip(t, 0, M - 1)
            x_in = jax.tree.map(lambda a: a[mb_idx], hidden_mb)
            aux = jax.tree.map(lambda a: a[mb_idx], aux_mb)
            inp = jnp.where(stage == 0, x_in, recv)
            dk = (
                None if dropout_key is None
                else jax.random.fold_in(dropout_key, t)
            )
            out = _stage_body(
                cfg, layers_local, inp, aux,
                token_idx_local if token_idx is not None else None,
                dk, deterministic, rope,
            )
            nxt = jax.lax.ppermute(out, PP_AXIS, perm)
            # last stage's output for microbatch t-(pp-1), zero elsewhere
            y = jnp.where(stage == pp - 1, out, jnp.zeros_like(out))
            return nxt, y

        init = jnp.zeros_like(hidden_mb[0])
        _, ys = jax.lax.scan(tick, init, jnp.arange(M + pp - 1))
        outs = ys[pp - 1:]  # [M, mb, s, h], valid only on the last stage
        # broadcast last-stage results to every stage (psum of one-hot data);
        # transpose of this psum routes dLoss back to the last stage only.
        return jax.lax.psum(outs, PP_AXIS)

    # cp joins pp as a manual axis: hidden/aux seq dims are cp-local inside
    # the body, and the attention dispatch takes the ring_attention_manual
    # path (parallel/ring.py) — one shard_map, no nesting.
    P = jax.sharding.PartitionSpec
    hidden_spec = P(None, None, CP_AXIS, None)  # [M, mb, s, h]
    aux_spec = P(None, None, CP_AXIS)           # [M, mb, s]
    fn = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(
            jax.tree.map(lambda _: P(PP_AXIS), stacked_layers),
            hidden_spec,
            jax.tree.map(lambda _: aux_spec, aux_mb),
            P(CP_AXIS),
        ),
        out_specs=hidden_spec,
        axis_names={PP_AXIS, CP_AXIS},
        check_vma=False,
    )
    return fn(stacked_layers, hidden_mb, aux_mb, token_idx_arr)


def pipeline_loss_fn(cfg, mesh, params, batch: Dict[str, jax.Array], *,
                     dropout_key=None, deterministic=True, rope=None,
                     sp_constraint=None, num_micro=None):
    """Full pipelined loss over the global batch (microbatched).

    batch leaves [gbs, s]; gbs = M * mb. Embedding/head run outside the
    pipeline (see module docstring).
    """
    M = num_micro or cfg.parallel.num_micro_batches or 1
    gbs = batch["tokens"].shape[0]
    assert gbs % M == 0
    mb = gbs // M

    def split(x):
        return x.reshape(M, mb, *x.shape[1:])

    tokens = split(batch["tokens"])
    labels = split(batch["labels"])
    loss_mask = split(batch["loss_mask"])
    aux_mb = {}
    for k in ("position_ids", "segment_ids"):
        if batch.get(k) is not None:
            aux_mb[k] = split(batch[k])
    token_idx = batch.get("token_idx")  # [s], batch-invariant (zigzag cp)

    if rope is None:
        rope = lm.make_rope_cache(cfg)

    # [M, mb, s, h] embeddings (vocab-parallel over tp under pjit)
    hidden = jax.vmap(lambda t: lm.embed_tokens(cfg, params, t, None))(tokens)
    if dropout_key is not None and not deterministic:
        k_embed, dropout_key = jax.random.split(dropout_key)
        hidden = rng_mod.dropout(k_embed, cfg.model.hidden_dropout, hidden)

    hidden = pipeline_apply(
        cfg, mesh, params["layers"], hidden, aux_mb, dropout_key,
        deterministic, rope, token_idx=token_idx,
    )

    hidden = norm(hidden, params["final_norm"], cfg.model.layernorm_epsilon,
                  cfg.model.use_rms_norm)
    logits = lm.compute_logits(cfg, params, hidden)  # [M, mb, s, v]
    per_token = softmax_cross_entropy(logits, labels)
    mask = loss_mask.astype(jnp.float32)
    loss = (per_token * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    return loss, {"lm loss": loss}
