"""Pipeline parallelism: collective-permute microbatch pipelining inside jit.

Replaces the reference's pipeline engine (megatron/schedules.py:606-722 1F1B,
p2p_communication.py isend/irecv) with the TPU-native formulation:

* stage placement is *data placement*: the stacked layer axis [L, ...] is
  sharded over the ``pp`` mesh axis (each stage holds L/pp contiguous layers)
  — no per-stage module classes, and checkpoint resharding over pp is a
  resharding no-op.
* stage transfer is ``lax.ppermute`` over ``pp`` inside a ``lax.scan`` over
  microbatch "ticks" — XLA lowers it to ICI collective-permute, the hardware
  analog of the reference's batched isend/irecv (p2p_communication.py:205-231).
* the schedule: every stage computes each tick; tick t feeds microbatch t into
  stage 0; the last stage emits microbatch t-(pp-1) at tick t. Total ticks
  M + pp - 1 — the same bubble as the reference's warmup(pp-rank-1)/steady/
  cooldown accounting (schedules.py:648-720).
* backward is autodiff through the scan: ppermute transposes to the reverse
  permute, giving the mirrored cooldown. This GPipe-style schedule
  (all-forward-then-all-backward per jit step) coexists with the true 1F1B
  (grads inside the tick loop, O(pp) activations —
  :func:`pipeline_1f1b_loss_and_grads`) and its interleaved variant
  (:func:`pipeline_1f1b_interleaved_loss_and_grads`).
* only ``pp`` is manual (shard_map axis_names={'pp'}): dp/tp/sp shardings
  inside the stage body stay under GSPMD exactly as in the pp=1 path.

Embedding, final norm, and the LM head run outside the pipelined region,
replicated over pp (their grads psum over pp automatically under pjit) —
which also implements the reference's first/last-stage embedding tying
(module.py:52-121) without an explicit embedding group.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from megatron_llm_tpu.core import rng as rng_mod
from megatron_llm_tpu.parallel import compat
from megatron_llm_tpu.core.parallel_state import CP_AXIS, PP_AXIS
from megatron_llm_tpu.models import language_model as lm
from megatron_llm_tpu.models.transformer import transformer_forward
from megatron_llm_tpu.ops.cross_entropy import (
    chunked_softmax_cross_entropy_from_hidden,
    softmax_cross_entropy,
)
from megatron_llm_tpu.ops.norms import norm


def _stage_body(cfg, layers_local, x, aux, token_idx, dropout_key,
                deterministic, rope, layer_offset=None):
    """Run this stage's local layers on one microbatch of hidden states.

    ``dropout_key`` is the per-microbatch key (the same one the pp=1 path
    hands to transformer_forward, which folds it per *global* layer index) —
    so with cp=1, pipelined dropout is bit-identical to the pp=1 run.

    Returns (hidden, moe_aux[2]) — the stage-local MoE router losses
    (zeros for dense models). The GPipe schedule accumulates them through
    the tick scan; the 1F1B schedules fold them into the per-stage vjp's
    aux output (see _1f1b_setup's aux_scalar).
    """
    stage = compat.axis_index(PP_AXIS)
    if dropout_key is not None and cfg.parallel.context_parallel_size > 1:
        # distinct dropout streams per cp seq-chunk (analog of the reference's
        # per-TP-rank RNG fork inside parallel regions, random.py:144-172)
        dropout_key = jax.random.fold_in(
            dropout_key, compat.axis_index(CP_AXIS)
        )
    layers_per_stage = jax.tree_util.tree_leaves(layers_local)[0].shape[0]
    if layer_offset is None:
        layer_offset = stage * layers_per_stage
    # encoder-decoder stages (models/t5.py:t5_pipeline_loss_fn): the encoder
    # output and the (caller-precomputed) cross-attention bias ride the aux
    # dict to every stage — the engine stays model-agnostic
    encoder_hidden = aux.get("encoder_hidden")
    enc_bias = aux.get("enc_bias")
    hidden, _, moe_aux = transformer_forward(
        cfg, layers_local, x,
        rope=rope,
        position_ids=aux.get("position_ids"),
        segment_ids=aux.get("segment_ids"),
        token_idx=token_idx,
        encoder_hidden=encoder_hidden,
        enc_bias=enc_bias,
        dropout_key=dropout_key,
        deterministic=deterministic,
        layer_offset=layer_offset,
    )
    return hidden, moe_aux


def microbatch_keys(base_key, M: int):
    """Per-microbatch (embed_key, layers_key) pairs, matching the pp=1
    grad-accumulation path exactly: fold_in(base, mb) then split for the
    embedding dropout (model_forward:150-152)."""
    if base_key is None:
        return None, None
    keys = jax.vmap(
        lambda i: jax.random.split(jax.random.fold_in(base_key, i))
    )(jnp.arange(M))
    return keys[:, 0], keys[:, 1]  # [M, keydata] each


def num_pipeline_ticks(M: int, pp: int, v: int) -> int:
    """Tick count of the (interleaved) schedule; v=1 is plain GPipe order.

    Virtual pipelining runs microbatches in groups of pp; a group occupies a
    stage for v*pp consecutive ticks (chunk-major: chunk c of all pp members
    before chunk c+1, ref schedules.py:253-344 model-chunk ordering), and
    each tick does 1/v of a stage's layers — so the pipeline-fill bubble
    shrinks from (pp-1) full-stage ticks to (pp-1) chunk ticks.
    """
    if v == 1:
        return M + pp - 1
    m_pad = -(-M // pp) * pp  # groups are pp-strided; pad the last group
    return m_pad * v + pp - 1


def pipeline_bubble_fraction(M: int, pp: int, v: int = 1) -> float:
    """Idle fraction of the tick schedule: (T - M*v) / T.

    Reference accounting (Megatron SC21 paper; schedules.py warmup/cooldown
    math): bubble = (pp-1)/(M+pp-1) non-interleaved, ~(pp-1)/(M*v+pp-1)
    interleaved."""
    t = num_pipeline_ticks(M, pp, v)
    return (t - M * v) / t


def pipeline_apply(cfg, mesh, stacked_layers, hidden_mb: jax.Array,
                   aux_mb: Dict[str, jax.Array], dropout_key, deterministic,
                   rope, token_idx: Optional[jax.Array] = None,
                   mb_keys: Optional[jax.Array] = None):
    """Run the pipelined transformer body.

    hidden_mb: [M, mb, s, h] embedded microbatches; aux_mb leaves [M, mb, s];
    token_idx: optional [s] zigzag index vector (parallel/ring.py);
    mb_keys: optional [M, ...] per-microbatch dropout keys (microbatch_keys).
    Returns [M, mb, s, h] final hidden states (replicated over pp).

    With cfg.parallel.virtual_pipeline_model_parallel_size = v > 1, each
    stage holds v layer chunks (virtual stage k = c*pp + s holds layers
    [k*L/(v*pp), (k+1)*L/(v*pp))) and a microbatch traverses the stage ring
    v times — the interleaved schedule of ref schedules.py:253-502, which
    cuts the pipeline-fill bubble by v (see pipeline_bubble_fraction).
    """
    pp = cfg.parallel.pipeline_model_parallel_size
    v = cfg.parallel.virtual_pipeline_model_parallel_size or 1
    M = hidden_mb.shape[0]
    L = jax.tree_util.tree_leaves(stacked_layers)[0].shape[0]
    assert L % (pp * v) == 0, (L, pp, v)
    chunk_layers = L // (pp * v)
    T = num_pipeline_ticks(M, pp, v)
    if mb_keys is None and dropout_key is not None and not deterministic:
        # direct callers passing only dropout_key get the per-microbatch
        # derivation (the keys pipeline_loss_fn would have passed)
        _, mb_keys = microbatch_keys(dropout_key, M)
    use_dropout = mb_keys is not None and not deterministic

    if token_idx is None:
        # constant placeholder so the shard_map signature is static; the
        # sentinel -1 row is never read (selected below)
        token_idx_arr = jnp.full((hidden_mb.shape[2],), -1, jnp.int32)
    else:
        token_idx_arr = token_idx
    if mb_keys is None:
        mb_keys = jnp.zeros((M, 2), jnp.uint32)  # static-signature dummy

    # [L, ...] -> [v, pp, Lc, ...]: axis 1 shards over pp, so stage s locally
    # holds [v, Lc, ...] = chunks {c*pp + s}. For v=1 this is the old
    # contiguous L/pp split.
    def chunked(a):
        return a.reshape(v, pp, chunk_layers, *a.shape[1:])

    layers_chunked = jax.tree.map(chunked, stacked_layers)

    def body(layers_local, hidden_mb, aux_mb, token_idx_local, mb_keys_local):
        stage = compat.axis_index(PP_AXIS)
        perm = [(i, (i + 1) % pp) for i in range(pp)]
        layers_local = jax.tree.map(lambda a: a[:, 0], layers_local)  # [v, Lc, ...]

        def tick(carry, t):
            recv, out_buf, aux_acc = carry
            # schedule position: stage s at tick t serves chain position
            # u = t - s; groups of pp microbatches, chunk-major within group
            u = t - stage
            w = u % (v * pp)
            c = jnp.clip(w // pp, 0, v - 1)
            mbi = (u // (v * pp)) * pp + w % pp
            valid = jnp.logical_and(u >= 0, mbi < M)
            mb_idx = jnp.clip(mbi, 0, M - 1)

            x_in = jax.tree.map(lambda a: a[mb_idx], hidden_mb)
            aux = jax.tree.map(lambda a: a[mb_idx], aux_mb)
            first_hop = jnp.logical_and(stage == 0, c == 0)
            inp = jnp.where(first_hop, x_in, recv)
            dk = mb_keys_local[mb_idx] if use_dropout else None
            chunk_params = jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(a, c, 0, keepdims=False),
                layers_local,
            )
            out, moe_aux = _stage_body(
                cfg, chunk_params, inp, aux,
                token_idx_local if token_idx is not None else None,
                dk, deterministic, rope,
                layer_offset=(c * pp + stage) * chunk_layers,
            )
            # each (stage, chunk) serves a valid microbatch exactly once, so
            # gating on `valid` counts every layer's router loss once
            aux_acc = aux_acc + jnp.where(valid, moe_aux, 0.0)
            # final output for this microbatch leaves from the last virtual
            # stage (stage pp-1, chunk v-1)
            emit = jnp.logical_and(
                jnp.logical_and(stage == pp - 1, c == v - 1), valid
            )
            prev = jax.lax.dynamic_index_in_dim(out_buf, mb_idx, 0,
                                                keepdims=False)
            out_buf = jax.lax.dynamic_update_index_in_dim(
                out_buf, jnp.where(emit, out, prev), mb_idx, 0
            )
            nxt = jax.lax.ppermute(out, PP_AXIS, perm)
            return (nxt, out_buf, aux_acc), None

        init = (jnp.zeros_like(hidden_mb[0]), jnp.zeros_like(hidden_mb),
                jnp.zeros((2,), jnp.float32))
        (_, out_buf, aux_acc), _ = jax.lax.scan(tick, init, jnp.arange(T))
        # broadcast last-stage results to every stage (psum of one-hot data);
        # transpose of this psum routes dLoss back to the last stage only.
        # MoE router losses: each stage holds its own layers' sum -> psum
        # over pp gives the all-layer total (differentiable: the GPipe
        # backward carries d(aux)/d(router) through the scan transpose).
        return jax.lax.psum(out_buf, PP_AXIS), jax.lax.psum(aux_acc, PP_AXIS)

    # cp joins pp as a manual axis: hidden/aux seq dims are cp-local inside
    # the body, and the attention dispatch takes the ring_attention_manual
    # path (parallel/ring.py) — one shard_map, no nesting.
    P = jax.sharding.PartitionSpec
    hidden_spec = P(None, None, CP_AXIS, None)  # [M, mb, s, h]
    fn = compat.shard_map(
        body,
        mesh=mesh,
        in_specs=(
            jax.tree.map(lambda _: P(None, PP_AXIS), layers_chunked),
            hidden_spec,
            _aux_specs(aux_mb),
            P(CP_AXIS),
            P(),
        ),
        out_specs=(hidden_spec, P()),
        axis_names={PP_AXIS, CP_AXIS},
        check_vma=False,
    )
    return fn(layers_chunked, hidden_mb, aux_mb, token_idx_arr, mb_keys)


# ---------------------------------------------------------------------------
# True 1F1B: gradients computed inside the tick loop, O(pp) activation memory
# ---------------------------------------------------------------------------


def _aux_data_spec(leaf):
    """shard_map in-spec for one [M, mb, ...] aux leaf: the seq axis (dim 2)
    shards over cp; per-sample leaves (e.g. BERT is_random [M, mb]) replicate."""
    P = jax.sharding.PartitionSpec
    if leaf.ndim >= 3:
        return P(None, None, CP_AXIS)
    return P(*([None] * leaf.ndim))


def _aux_specs(aux_mb):
    """Key-aware aux specs: cross-attention KEYS stay replicated over cp —
    every cp-local decoder query chunk attends the FULL encoder sequence
    (models/t5.py), so sharding encoder_hidden/enc_bias over cp would
    silently truncate cross-attention to 1/cp of the keys."""
    P = jax.sharding.PartitionSpec
    return {
        k: (P() if k in ("encoder_hidden", "enc_bias")
            else _aux_data_spec(v))
        for k, v in aux_mb.items()
    }


def microbatched_head_loss(head_loss_fn, outer, hidden, labels, loss_mask,
                           aux_mb):
    """Sum per-microbatch head-loss contributions over [M, ...] arrays.

    One microbatch at a time: materializing [M, mb, s, v] logits for the
    whole global batch (vocab 32k, seq 4k, M=16 -> tens of GB) would defeat
    microbatching; the remat keeps the scan VJP from saving each
    iteration's logits as residuals (the same footprint again). Shared by
    pipeline_loss_fn and family-owned pipelines (models/t5.py).
    """

    @functools.partial(jax.checkpoint, policy=None)
    def head_mb(hid, lbl, msk, i):
        aux = jax.tree.map(lambda a: a[i], aux_mb)
        return head_loss_fn(outer, hid, lbl, msk, aux)

    def acc_mb(loss_sum, inp):
        hid, lbl, msk, i = inp
        return loss_sum + head_mb(hid, lbl, msk, i), None

    loss, _ = jax.lax.scan(
        acc_mb, jnp.float32(0.0),
        (hidden, labels, loss_mask, jnp.arange(hidden.shape[0])),
    )
    return loss


def _split_extra_keys(batch, split):
    """Microbatch-split every batch key outside the engine's positional
    tokens/labels/loss_mask/token_idx contract — they reach the stage body
    (segment_ids gates attention) and the embed/head hooks as ``aux``."""
    return {
        k: split(v) for k, v in batch.items()
        if k not in ("tokens", "labels", "loss_mask", "token_idx")
        and v is not None
    }


def _default_gpt_fns(cfg, batch, use_dropout):
    """Default GPT-family hooks shared by every schedule: embedding (+optional
    dropout) and final-norm + LM head + globally-normalized masked CE.
    head_loss_fn returns the UNSCALED per-microbatch contribution."""
    denom = jnp.maximum(batch["loss_mask"].astype(jnp.float32).sum(), 1.0)

    def embed_fn(outer_p, tok, aux, ke):
        h = lm.embed_tokens(cfg, outer_p, tok, aux.get("position_ids"))
        if use_dropout and ke is not None:
            h = rng_mod.dropout(ke, cfg.model.hidden_dropout, h)
        return h

    def head_loss_fn(outer_p, hidden, lbl, msk, aux):
        h = norm(hidden, outer_p["final_norm"], cfg.model.layernorm_epsilon,
                 cfg.model.use_rms_norm)
        if cfg.model.ce_vocab_chunks:
            # same vocab-chunked head fusion as the pp=1 path (model_forward)
            per_token = chunked_softmax_cross_entropy_from_hidden(
                h, lm.head_weight(cfg, outer_p).astype(h.dtype), lbl,
                cfg.model.ce_vocab_chunks,
            )
        else:
            logits = lm.compute_logits(cfg, outer_p, h)
            per_token = softmax_cross_entropy(logits, lbl)
        return (per_token * msk.astype(jnp.float32)).sum() / denom

    return embed_fn, head_loss_fn


def _1f1b_setup(cfg, batch, num_micro, dropout_key, embed_fn, head_loss_fn,
                loss_scale, rope):
    """Shared preamble of both 1F1B schedules: microbatch splits, dropout
    keys, params split, compute dtype, and the default GPT embed/head fns.

    ``head_loss_fn(outer_p, hidden, labels, mask, aux)`` returns the
    UNSCALED loss contribution of one microbatch (normalizers are closures
    over the full batch); the engine applies the fp16 loss scale. Custom
    families (e.g. BERT, models/bert.py:bert_pipeline_hooks) override both
    fns; every batch key other than tokens/labels/loss_mask/token_idx is
    microbatch-split into ``aux`` and reaches both hooks and the stage body
    (where ``segment_ids`` gates attention).
    """
    M = num_micro or cfg.parallel.num_micro_batches or 1
    gbs = batch["tokens"].shape[0]
    assert gbs % M == 0
    s = {"M": M, "mb": gbs // M}
    s["rope"] = rope if rope is not None else lm.make_rope_cache(cfg)
    s["scale"] = loss_scale if loss_scale is not None else jnp.float32(1.0)

    def split(x):
        return x.reshape(M, gbs // M, *x.shape[1:])

    s["tokens"] = split(batch["tokens"])
    s["labels"] = split(batch["labels"])
    s["loss_mask"] = split(batch["loss_mask"]).astype(jnp.float32)
    s["aux_mb"] = _split_extra_keys(batch, split)
    s["token_idx"] = batch.get("token_idx")
    s["denom"] = jnp.maximum(s["loss_mask"].sum(), 1.0)
    s["dtype"] = (
        jnp.bfloat16 if cfg.training.params_dtype == "bfloat16"
        else jnp.float16 if cfg.training.params_dtype == "float16"
        else jnp.float32
    )

    use_dropout = (
        dropout_key is not None
        and (cfg.model.hidden_dropout > 0.0 or cfg.model.attention_dropout > 0.0)
    )
    s["use_dropout"] = use_dropout
    embed_keys, layer_keys = microbatch_keys(
        dropout_key if use_dropout else None, M
    )
    if embed_keys is None:  # static shard_map signature
        embed_keys = jnp.zeros((M, 2), jnp.uint32)
        layer_keys = jnp.zeros((M, 2), jnp.uint32)
    s["embed_keys"], s["layer_keys"] = embed_keys, layer_keys

    # pp-vocab-parallel head (cfg.parallel.pp_vocab_parallel_head): in
    # lockstep SPMD a "last-stage-only" head is structurally impossible —
    # every stage executes every tick — so instead of pp-1 stages computing
    # a masked-out FULL head, the vocab is sharded over pp and every stage
    # computes a USEFUL 1/pp of it (logits chunk + the 3-psum
    # vocab-parallel CE over the pp axis; ops/cross_entropy.py). Only for
    # the default GPT head; the padded vocab must divide pp.
    pp_ = cfg.parallel.pipeline_model_parallel_size
    s["pp_head"] = (
        cfg.parallel.pp_vocab_parallel_head
        and head_loss_fn is None
        and pp_ > 1
        and lm.padded_vocab_size(cfg.model.vocab_size, cfg) % pp_ == 0
        # an explicit ce_vocab_chunks bound wins: the pp head materializes
        # an unchunked [mb, s, V/pp] logits block, which can exceed the
        # memory budget chunking was configured to enforce — keep the
        # replicated chunked head (which _default_gpt_fns honors) instead
        and not cfg.model.ce_vocab_chunks
    )
    if s["pp_head"]:
        from megatron_llm_tpu.ops.cross_entropy import (
            vocab_parallel_cross_entropy,
        )

        denom_ = s["denom"]
        scale_ = s["scale"]

        def pp_head_loss_fn(outer_p, hidden, lbl, msk, aux):
            """SCALED per-microbatch loss from this stage's vocab chunk.

            ``hidden`` is the last stage's output broadcast to every stage
            (psum of a one-hot selection); the psums inside the
            vocab-parallel CE make the returned value identical on every
            stage — the caller counts it once and psums the partial
            weight/hidden grads."""
            h = norm(hidden, outer_p["final_norm"],
                     cfg.model.layernorm_epsilon, cfg.model.use_rms_norm)
            w = lm.head_weight(cfg, outer_p).astype(h.dtype)
            vc = w.shape[1] // pp_
            rank = compat.axis_index(PP_AXIS)
            wc = jax.lax.dynamic_slice_in_dim(w, rank * vc, vc, axis=1)
            per_token = vocab_parallel_cross_entropy(
                h @ wc, lbl, axis_name=PP_AXIS)
            return ((per_token * msk.astype(jnp.float32)).sum()
                    / denom_ * scale_)

        s["pp_head_loss_fn"] = pp_head_loss_fn

    default_embed, default_head = _default_gpt_fns(cfg, batch, use_dropout)
    if embed_fn is None:
        embed_fn = default_embed
    if head_loss_fn is None:
        head_loss_fn = default_head

    # the engine owns the fp16 loss scale so hooks stay scale-agnostic
    scale = s["scale"]
    unscaled = head_loss_fn

    def scaled_head(outer_p, hidden, lbl, msk, aux):
        return unscaled(outer_p, hidden, lbl, msk, aux) * scale

    s["embed_fn"], s["head_loss_fn"] = embed_fn, scaled_head
    s["token_idx_arr"] = (
        jnp.full((s["tokens"].shape[2],), -1, jnp.int32)
        if s["token_idx"] is None else s["token_idx"]
    )

    # MoE router aux losses under 1F1B: the aux term is stage-LOCAL (each
    # stage's routers see only that stage's layers), so its gradient never
    # crosses stage boundaries through dy — seeding the aux output of the
    # per-stage vjp with the loss scale at the stage's own backward tick
    # recovers exactly the gradient GPipe gets through the scan transpose.
    # The /M matches the pp=1 grad-accum mean (pipeline_loss_fn does the
    # same division).
    s["has_moe"] = cfg.model.num_experts is not None
    if s["has_moe"]:
        from megatron_llm_tpu.models.moe import aux_loss_coeffs

        c_bal, c_z = aux_loss_coeffs(cfg)
        M_ = s["M"]

        def aux_scalar(moe_aux):
            return (c_bal * moe_aux[0] + c_z * moe_aux[1]) / M_
    else:
        def aux_scalar(moe_aux):
            del moe_aux
            return jnp.float32(0.0)
    s["aux_scalar"] = aux_scalar
    return s


def _pp_head_tick(st, pp, outer_p, y, labels, loss_mask, aux_at,
                  use_head, emitted, e_idx, loss_acc, acc_outer):
    """Shared pp-vocab-head step of the 1F1B ticks (both engines).

    Broadcasts the emitting stage's output, runs THIS stage's vocab-chunk
    head vjp, and returns the updated (loss_acc, acc_outer, dy_total).
    ``emitted``/``e_idx`` are tick-derived and identical on every stage
    (each engine computes them from its own schedule); ``use_head`` is the
    emitting stage's own flag. vjp seed is 1/pp: inside shard_map a
    replicated cotangent of 1.0 per rank counts pp times through the CE's
    internal psums (verified with a 2-rank psum-vjp probe that returned
    2x the chunk partials); 1/pp makes each rank's vjp the clean chunk
    partial, which the psums assemble.
    """
    y_b = jax.lax.psum(
        jnp.where(use_head, y, jnp.zeros_like(y)), PP_AXIS)
    loss_f, head_vjp = jax.vjp(
        lambda op, yy: st["pp_head_loss_fn"](
            op, yy, labels[e_idx], loss_mask[e_idx], aux_at(e_idx)),
        outer_p, y_b,
    )
    d_outer_head, dy_p = head_vjp(jnp.float32(1.0 / pp))
    # loss_f is already the GLOBAL value on every stage (CE psums
    # internally) — count it once (the emitting stage)
    loss_acc = loss_acc + jnp.where(use_head, loss_f, 0.0)
    acc_outer = jax.tree.map(
        lambda a, g: a + jnp.where(emitted, g, jnp.zeros_like(g)),
        acc_outer, d_outer_head,
    )
    return loss_acc, acc_outer, jax.lax.psum(dy_p, PP_AXIS)


def _1f1b_metrics(st, loss_ce, aux_tot):
    """Reporting dict for the 1F1B engines (``with_metrics=True``): bare CE
    as "lm loss" — matching loss_from_batch / pipeline_loss_fn, so the
    metric means the same thing under every schedule — plus the combined
    coeff-weighted router aux for MoE. Values are UNSCALED (the engine's
    accumulators carry the fp16 loss scale; the train step's convention is
    raw metrics, training_step.py:136)."""
    inv = 1.0 / st["scale"]
    mets = {"lm loss": loss_ce * inv}
    if st["has_moe"]:
        mets["moe aux total"] = aux_tot * inv
    return mets


def pipeline_1f1b_loss_and_grads(
    cfg, mesh, params, batch: Dict[str, jax.Array], *,
    rope=None, loss_scale=None, num_micro=None, dropout_key=None,
    embed_fn=None, head_loss_fn=None, with_metrics=False,
):
    """One-forward-one-backward pipeline schedule (schedules.py:606-722).

    Unlike :func:`pipeline_loss_fn` (GPipe-style: autodiff through the tick
    scan, which saves one stage-input per tick — O(M) activation memory),
    this computes gradients INSIDE the loop: at tick t, stage s runs the
    forward for microbatch ``t - s`` and the backward (via ``jax.vjp`` on the
    saved stage input — rematerialized, the recompute analog of the
    reference's activation checkpointing) for microbatch ``t - 2(pp-1) + s``.
    Saved inputs live in a ring buffer of depth 2*pp — the O(pp) in-flight
    memory discipline the reference gets from deallocate_output_tensor +
    1F1B ordering (schedules.py:36-88,648-720).

    The embedding, final norm, LM head and loss run inside the loop on their
    owning stages (first/last); every stage computes them SPMD-style and the
    unused results are masked — the head matmul on non-final stages is the
    price of lockstep SPMD (~h*v/(12*h^2*L/pp) of a tick, a few percent).

    Dropout: per-microbatch keys (``microbatch_keys``) make the vjp-recompute
    reproduce the forward's dropout exactly — the jax analog of the
    reference's RNG-state snapshot around activation recompute
    (random.py:175-245). Pass ``dropout_key`` to enable.

    Custom model families can override ``embed_fn(outer_params, tokens, aux,
    key)`` and ``head_loss_fn(outer_params, hidden, labels, mask, aux) ->
    UNSCALED per-microbatch loss contribution`` — the engine applies the
    fp16 loss scale itself; normalizers should be closures over the full
    batch (defaults implement the GPT/Llama family; BERT:
    models/bert.py:bert_pipeline_hooks).

    Returns (loss, grads) with grads matching the params tree.
    """
    assert (cfg.parallel.virtual_pipeline_model_parallel_size or 1) == 1, (
        "this is the non-interleaved schedule; with "
        "virtual_pipeline_model_parallel_size > 1 use "
        "pipeline_1f1b_interleaved_loss_and_grads"
    )
    pp = cfg.parallel.pipeline_model_parallel_size
    st = _1f1b_setup(cfg, batch, num_micro, dropout_key, embed_fn,
                     head_loss_fn, loss_scale, rope)
    M, mb = st["M"], st["mb"]
    rope = st["rope"]
    tokens, labels, loss_mask = st["tokens"], st["labels"], st["loss_mask"]
    aux_mb, token_idx = st["aux_mb"], st["token_idx"]
    use_dropout = st["use_dropout"]
    embed_keys, layer_keys = st["embed_keys"], st["layer_keys"]
    embed_fn, head_loss_fn = st["embed_fn"], st["head_loss_fn"]

    # params split: layers are pp-sharded; everything else ("outer": embedding,
    # final_norm, lm_head if untied) is replicated and used at the ends.
    layers = params["layers"]
    outer = {k: v for k, v in params.items() if k != "layers"}

    def body(layers_local, outer_p, tokens, labels, loss_mask, aux_mb,
             token_idx_local, embed_keys, layer_keys):
        stage = compat.axis_index(PP_AXIS)
        last = pp - 1
        perm_fwd = [(i, (i + 1) % pp) for i in range(pp)]
        perm_bwd = [(i, (i - 1) % pp) for i in range(pp)]
        depth = 2 * pp
        s_local = tokens.shape[2]
        h = cfg.model.hidden_size
        dtype = st["dtype"]

        def stage_fwd(L, x, aux, dk):
            y, moe_aux = _stage_body(
                cfg, L, x, aux,
                token_idx_local if token_idx is not None else None,
                dk if use_dropout else None, not use_dropout, rope,
            )
            # (hidden, stage-local scaled-down aux loss); the aux output's
            # vjp seed at the backward tick carries the router gradient
            return y, st["aux_scalar"](moe_aux)

        def aux_at(i):
            return jax.tree.map(lambda a: a[i], aux_mb)

        def tick(carry, t):
            x_recv, g_recv, saved, acc_L, acc_outer, loss_acc, aux_acc = carry
            f_mb = t - stage
            b_mb = t - 2 * (pp - 1) + stage
            do_f = jnp.logical_and(f_mb >= 0, f_mb < M)
            do_b = jnp.logical_and(b_mb >= 0, b_mb < M)
            f_idx = jnp.clip(f_mb, 0, M - 1)
            b_idx = jnp.clip(b_mb, 0, M - 1)

            # ---- forward: embed on stage 0, else the ppermuted stream ----
            x_emb = embed_fn(outer_p, tokens[f_idx], aux_at(f_idx),
                             embed_keys[f_idx] if use_dropout else None)
            x_in = jnp.where(stage == 0, x_emb, x_recv).astype(dtype)
            # guard the save: during cooldown f_idx clips to M-1, whose slot
            # may still be awaiting its backward
            saved_upd = jax.lax.dynamic_update_index_in_dim(
                saved, x_in, f_idx % depth, 0
            )
            saved = jnp.where(do_f, saved_upd, saved)
            y, aux_f = stage_fwd(layers_local, x_in, aux_at(f_idx),
                                 layer_keys[f_idx])
            # every stage adds its own (already /M) router aux once per
            # valid microbatch — into the SEPARATE aux accumulator so the
            # reported "lm loss" is bare CE like every other path's
            # (aux_acc psums over pp below and rejoins the total loss)
            aux_acc = aux_acc + jnp.where(do_f, aux_f * st["scale"], 0.0)

            # ---- head + loss on the last stage's fresh output ----
            use_head = jnp.logical_and(stage == last, do_f)
            if st["pp_head"]:
                # pp-vocab head (_pp_head_tick): every stage computes its
                # vocab chunk's partial CE + grads (USEFUL work, 1/pp of
                # the head each). emitted/e_idx are tick-derived — the
                # EMITTED microbatch, identical on all stages (f_idx is
                # stage-specific and differs on non-last stages)
                emitted = jnp.logical_and(t - last >= 0, t - last < M)
                e_idx = jnp.clip(t - last, 0, M - 1)
                loss_acc, acc_outer, dy = _pp_head_tick(
                    st, pp, outer_p, y, labels, loss_mask, aux_at,
                    use_head, emitted, e_idx, loss_acc, acc_outer)
            else:
                loss_f, head_vjp = jax.vjp(
                    lambda op, yy: head_loss_fn(op, yy, labels[f_idx],
                                                loss_mask[f_idx],
                                                aux_at(f_idx)),
                    outer_p, y,
                )
                d_outer_head, dy = head_vjp(jnp.float32(1.0))
                loss_acc = loss_acc + jnp.where(use_head, loss_f, 0.0)
                acc_outer = jax.tree.map(
                    lambda a, g: a + jnp.where(use_head, g,
                                               jnp.zeros_like(g)),
                    acc_outer, d_outer_head,
                )

            # ---- backward for the older microbatch (remat from saved x) ----
            g_in = jnp.where(stage == last, dy.astype(dtype), g_recv)
            x_saved = jax.lax.dynamic_index_in_dim(
                saved, b_idx % depth, 0, keepdims=False
            )
            _, stage_vjp = jax.vjp(
                lambda L, xx: stage_fwd(L, xx, aux_at(b_idx),
                                        layer_keys[b_idx]),
                layers_local, x_saved,
            )
            # aux cotangent = loss scale: the router-aux gradient enters
            # here (stage-local); for dense models the aux output is a
            # constant 0 and the seed is a no-op
            dlayers, dx = stage_vjp((g_in, st["scale"]))
            acc_L = jax.tree.map(
                lambda a, g: a + jnp.where(do_b, g, jnp.zeros_like(g)),
                acc_L, dlayers,
            )

            # ---- embedding backward on stage 0 ----
            _, emb_vjp = jax.vjp(
                lambda op: embed_fn(op, tokens[b_idx], aux_at(b_idx),
                                    embed_keys[b_idx] if use_dropout else None),
                outer_p,
            )
            (d_outer_emb,) = emb_vjp(dx)
            use_emb = jnp.logical_and(stage == 0, do_b)
            acc_outer = jax.tree.map(
                lambda a, g: a + jnp.where(use_emb, g, jnp.zeros_like(g)),
                acc_outer, d_outer_emb,
            )

            x_next = jax.lax.ppermute(y.astype(dtype), PP_AXIS, perm_fwd)
            g_next = jax.lax.ppermute(dx, PP_AXIS, perm_bwd)
            return (x_next, g_next, saved, acc_L, acc_outer, loss_acc,
                    aux_acc), None

        zero_x = jnp.zeros((mb, s_local, h), dtype)
        init = (
            zero_x,
            zero_x,
            jnp.zeros((depth, mb, s_local, h), dtype),
            jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                         layers_local),
            jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), outer_p),
            jnp.float32(0.0),
            jnp.float32(0.0),
        )
        (_, _, _, acc_L, acc_outer, loss_acc, aux_acc), _ = jax.lax.scan(
            tick, init, jnp.arange(M + 2 * (pp - 1))
        )
        # cp shards contribute partial sums over their seq chunks; pp stages
        # hold zeros for params they do not own (outer) — psum both.
        acc_L = jax.lax.psum(acc_L, CP_AXIS)
        acc_outer = jax.lax.psum(
            jax.lax.psum(acc_outer, PP_AXIS), CP_AXIS
        )
        loss_acc = jax.lax.psum(jax.lax.psum(loss_acc, PP_AXIS), CP_AXIS)
        aux_acc = jax.lax.psum(jax.lax.psum(aux_acc, PP_AXIS), CP_AXIS)
        return acc_L, acc_outer, loss_acc, aux_acc

    P = jax.sharding.PartitionSpec
    data_spec = P(None, None, CP_AXIS)
    fn = compat.shard_map(
        body,
        mesh=mesh,
        in_specs=(
            jax.tree.map(lambda _: P(PP_AXIS), layers),
            jax.tree.map(lambda _: P(), outer),
            data_spec, data_spec, data_spec,
            _aux_specs(aux_mb),
            P(CP_AXIS),
            P(), P(),
        ),
        out_specs=(
            jax.tree.map(lambda _: P(PP_AXIS), layers),
            jax.tree.map(lambda _: P(), outer),
            P(), P(),
        ),
        axis_names={PP_AXIS, CP_AXIS},
        check_vma=False,
    )
    grads_L, grads_outer, loss_ce, aux_tot = fn(
        layers, outer, tokens, labels, loss_mask, aux_mb, st["token_idx_arr"],
        embed_keys, layer_keys,
    )
    grads = dict(grads_outer)
    grads["layers"] = grads_L
    loss = loss_ce + aux_tot
    if with_metrics:
        return loss, grads, _1f1b_metrics(st, loss_ce, aux_tot)
    return loss, grads


def pipeline_1f1b_interleaved_loss_and_grads(
    cfg, mesh, params, batch: Dict[str, jax.Array], *,
    rope=None, loss_scale=None, num_micro=None, dropout_key=None,
    embed_fn=None, head_loss_fn=None, with_metrics=False,
):
    """Interleaved (virtual-pipeline) 1F1B: grads inside the tick loop with
    v layer chunks per stage (reference schedules.py:253-502 +
    parallel_state.py:406-421 virtual ranks).

    Schedule: virtual stage k = c*pp + s; V = v*pp hops per microbatch.
    Microbatches run in pp-sized groups, chunk-major (the same forward
    mapping as the interleaved gpipe schedule in :func:`pipeline_apply`);
    the backward is its time-shifted mirror — at tick t stage s runs
      forward  of chain position u = t - s          (chunk u%(v*pp)//pp),
      backward of chain position j ≡ (V-1-s) mod pp (virtual stage V-1-j),
    one fwd and one bwd chunk-step per stage per tick, so the pipeline-fill
    bubble shrinks by v while in-flight activations stay O(V) (ring buffer
    of depth 2V+2pp saved chunk inputs) instead of the gpipe autodiff's
    O(M*v) tick residuals.

    The last stage's head vjp runs at the microbatch's final forward tick;
    dy is held one tick in a depth-pp ring until its backward starts.

    Lockstep cost note: as in the non-interleaved 1F1B, every stage computes
    the (masked-out) head and embedding vjps every tick. Each interleaved
    tick does only 1/v of a stage's layers, so that fixed overhead is ~v x
    larger relative to useful work than non-interleaved — with a very large
    vocab and few layers per chunk, prefer smaller v (or the gpipe schedule,
    whose head runs outside the pipelined region).
    """
    pp = cfg.parallel.pipeline_model_parallel_size
    v = cfg.parallel.virtual_pipeline_model_parallel_size or 1
    V = v * pp
    st = _1f1b_setup(cfg, batch, num_micro, dropout_key, embed_fn,
                     head_loss_fn, loss_scale, rope)
    M, mb = st["M"], st["mb"]
    rope = st["rope"]
    tokens, labels, loss_mask = st["tokens"], st["labels"], st["loss_mask"]
    aux_mb, token_idx = st["aux_mb"], st["token_idx"]
    use_dropout = st["use_dropout"]
    embed_keys, layer_keys = st["embed_keys"], st["layer_keys"]
    embed_fn, head_loss_fn = st["embed_fn"], st["head_loss_fn"]
    m_groups = -(-M // pp)
    T = (m_groups - 1) * v * pp + (pp - 1) + 2 * V
    depth = 2 * V + 2 * pp

    layers = params["layers"]
    outer = {k: x for k, x in params.items() if k != "layers"}
    L = jax.tree_util.tree_leaves(layers)[0].shape[0]
    assert L % V == 0, (L, pp, v)
    chunk_layers = L // V

    def chunked(a):
        return a.reshape(v, pp, chunk_layers, *a.shape[1:])

    layers_chunked = jax.tree.map(chunked, layers)

    def body(layers_local, outer_p, tokens, labels, loss_mask, aux_mb,
             token_idx_local, embed_keys, layer_keys):
        stage = compat.axis_index(PP_AXIS)
        last = pp - 1
        perm_fwd = [(i, (i + 1) % pp) for i in range(pp)]
        perm_bwd = [(i, (i - 1) % pp) for i in range(pp)]
        layers_local = jax.tree.map(lambda a: a[:, 0], layers_local)  # [v, Lc]
        s_local = tokens.shape[2]
        h = cfg.model.hidden_size
        dtype = st["dtype"]

        def chunk_at(c):
            return jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(a, c, 0, keepdims=False),
                layers_local,
            )

        def stage_fwd(ch_params, x, aux, dk, layer_offset):
            y, moe_aux = _stage_body(
                cfg, ch_params, x, aux,
                token_idx_local if token_idx is not None else None,
                dk if use_dropout else None, not use_dropout, rope,
                layer_offset=layer_offset,
            )
            return y, st["aux_scalar"](moe_aux)

        def aux_at(i):
            return jax.tree.map(lambda a: a[i], aux_mb)

        def add_chunk(acc, g, c, valid):
            def upd(a, gg):
                prev = jax.lax.dynamic_index_in_dim(a, c, 0, keepdims=False)
                new = prev + jnp.where(valid, gg, jnp.zeros_like(gg))
                return jax.lax.dynamic_update_index_in_dim(a, new, c, 0)

            return jax.tree.map(upd, acc, g)

        def tick(carry, t):
            (x_recv, g_recv, saved, dybuf, acc_L, acc_outer, loss_acc,
             aux_acc) = carry

            # ---- forward mapping (shared with the gpipe interleaved path) --
            u = t - stage
            w = u % V
            c_f = jnp.clip(w // pp, 0, v - 1)
            f_mb_raw = (u // V) * pp + w % pp
            do_f = jnp.logical_and(u >= 0, f_mb_raw < M)
            f_idx = jnp.clip(f_mb_raw, 0, M - 1)
            first_hop = jnp.logical_and(stage == 0, c_f == 0)
            last_hop = jnp.logical_and(stage == last, c_f == v - 1)

            x_emb = embed_fn(outer_p, tokens[f_idx], aux_at(f_idx),
                             embed_keys[f_idx] if use_dropout else None)
            x_in = jnp.where(first_hop, x_emb, x_recv).astype(dtype)
            slot_f = jnp.where(do_f, u % depth, depth - 1)
            saved_upd = jax.lax.dynamic_update_index_in_dim(
                saved, x_in, slot_f, 0
            )
            saved = jnp.where(do_f, saved_upd, saved)
            y, aux_f = stage_fwd(chunk_at(c_f), x_in, aux_at(f_idx),
                                 layer_keys[f_idx],
                                 (c_f * pp + stage) * chunk_layers)
            # each (stage, chunk) hop adds its own (already /M) router aux
            # once per valid microbatch into the SEPARATE aux accumulator
            # (bare-CE reporting, see _1f1b_metrics); psum over pp totals
            # the layers
            aux_acc = aux_acc + jnp.where(do_f, aux_f * st["scale"], 0.0)

            # ---- head vjp at the final forward hop; dy parked one tick ----
            use_head = jnp.logical_and(last_hop, do_f)
            if st["pp_head"]:
                # pp-vocab head (_pp_head_tick); the emission condition of
                # the LAST stage's final hop, derived from t alone so it is
                # identical on every stage
                u_l = t - last
                w_l = u_l % V
                mb_l = (u_l // V) * pp + w_l % pp
                emitted = jnp.logical_and(
                    jnp.logical_and(u_l >= 0, w_l // pp == v - 1), mb_l < M)
                e_idx = jnp.clip(mb_l, 0, M - 1)
                loss_acc, acc_outer, dy = _pp_head_tick(
                    st, pp, outer_p, y, labels, loss_mask, aux_at,
                    use_head, emitted, e_idx, loss_acc, acc_outer)
            else:
                loss_f, head_vjp = jax.vjp(
                    lambda op, yy: head_loss_fn(op, yy, labels[f_idx],
                                                loss_mask[f_idx],
                                                aux_at(f_idx)),
                    outer_p, y,
                )
                d_outer_head, dy = head_vjp(jnp.float32(1.0))
                loss_acc = loss_acc + jnp.where(use_head, loss_f, 0.0)
                acc_outer = jax.tree.map(
                    lambda a, g: a + jnp.where(use_head, g,
                                               jnp.zeros_like(g)),
                    acc_outer, d_outer_head,
                )
            dy_prev = jax.lax.dynamic_index_in_dim(
                dybuf, f_idx % pp, 0, keepdims=False)
            dybuf = jax.lax.dynamic_update_index_in_dim(
                dybuf, jnp.where(use_head, dy.astype(dtype), dy_prev),
                f_idx % pp, 0,
            )

            # ---- backward mapping: j = (V-1-s) % pp + pp*a ----
            base = (V - 1 - stage) % pp
            z = t - V - base
            w2 = z % V
            a2 = w2 // pp
            b_mb_raw = (z // V) * pp + w2 % pp
            j = base + pp * a2
            k_b = V - 1 - j
            c_b = jnp.clip(k_b // pp, 0, v - 1)
            do_b = jnp.logical_and(z >= 0, b_mb_raw < M)
            b_idx = jnp.clip(b_mb_raw, 0, M - 1)
            bwd_first = j == 0            # head's dy enters here
            bwd_last = k_b == 0           # embedding vjp leaves here

            dy_in = jax.lax.dynamic_index_in_dim(
                dybuf, b_idx % pp, 0, keepdims=False)
            g_in = jnp.where(bwd_first, dy_in, g_recv)
            slot_b = ((b_idx // pp) * V + b_idx % pp + c_b * pp) % depth
            x_saved = jax.lax.dynamic_index_in_dim(saved, slot_b, 0,
                                                   keepdims=False)
            _, stage_vjp = jax.vjp(
                lambda ch, xx: stage_fwd(ch, xx, aux_at(b_idx),
                                         layer_keys[b_idx],
                                         (c_b * pp + stage) * chunk_layers),
                chunk_at(c_b), x_saved,
            )
            # aux cotangent = loss scale (router grads; no-op for dense)
            dchunk, dx = stage_vjp((g_in, st["scale"]))
            acc_L = add_chunk(acc_L, dchunk, c_b, do_b)

            # ---- embedding backward at the last backward hop ----
            _, emb_vjp = jax.vjp(
                lambda op: embed_fn(op, tokens[b_idx], aux_at(b_idx),
                                    embed_keys[b_idx] if use_dropout else None),
                outer_p,
            )
            (d_outer_emb,) = emb_vjp(dx)
            use_emb = jnp.logical_and(bwd_last, do_b)
            acc_outer = jax.tree.map(
                lambda a, g: a + jnp.where(use_emb, g, jnp.zeros_like(g)),
                acc_outer, d_outer_emb,
            )

            x_next = jax.lax.ppermute(y.astype(dtype), PP_AXIS, perm_fwd)
            g_next = jax.lax.ppermute(dx.astype(dtype), PP_AXIS, perm_bwd)
            return (x_next, g_next, saved, dybuf, acc_L, acc_outer,
                    loss_acc, aux_acc), None

        zero_x = jnp.zeros((mb, s_local, h), dtype)
        init = (
            zero_x,
            zero_x,
            jnp.zeros((depth, mb, s_local, h), dtype),
            jnp.zeros((pp, mb, s_local, h), dtype),
            jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                         layers_local),
            jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), outer_p),
            jnp.float32(0.0),
            jnp.float32(0.0),
        )
        (_, _, _, _, acc_L, acc_outer, loss_acc, aux_acc), _ = jax.lax.scan(
            tick, init, jnp.arange(T)
        )
        acc_L = jax.lax.psum(acc_L, CP_AXIS)
        acc_outer = jax.lax.psum(jax.lax.psum(acc_outer, PP_AXIS), CP_AXIS)
        loss_acc = jax.lax.psum(jax.lax.psum(loss_acc, PP_AXIS), CP_AXIS)
        aux_acc = jax.lax.psum(jax.lax.psum(aux_acc, PP_AXIS), CP_AXIS)
        return acc_L, acc_outer, loss_acc, aux_acc

    P = jax.sharding.PartitionSpec
    data_spec = P(None, None, CP_AXIS)
    fn = compat.shard_map(
        body,
        mesh=mesh,
        in_specs=(
            jax.tree.map(lambda _: P(None, PP_AXIS), layers_chunked),
            jax.tree.map(lambda _: P(), outer),
            data_spec, data_spec, data_spec,
            _aux_specs(aux_mb),
            P(CP_AXIS),
            P(), P(),
        ),
        out_specs=(
            jax.tree.map(lambda _: P(None, PP_AXIS), layers_chunked),
            jax.tree.map(lambda _: P(), outer),
            P(), P(),
        ),
        axis_names={PP_AXIS, CP_AXIS},
        check_vma=False,
    )
    grads_Lc, grads_outer, loss_ce, aux_tot = fn(
        layers_chunked, outer, tokens, labels, loss_mask, aux_mb,
        st["token_idx_arr"], embed_keys, layer_keys,
    )
    # the out-spec gather concatenates stage shards into axis 1: leaves come
    # back [v, pp*Lc, ...] (chunk-major, then stage, then local layer) —
    # exactly the chunked() order, so one reshape restores [L, ...]
    grads_L = jax.tree.map(
        lambda a: a.reshape(L, *a.shape[2:]), grads_Lc
    )
    grads = dict(grads_outer)
    grads["layers"] = grads_L
    loss = loss_ce + aux_tot
    if with_metrics:
        return loss, grads, _1f1b_metrics(st, loss_ce, aux_tot)
    return loss, grads


def pipeline_loss_fn(cfg, mesh, params, batch: Dict[str, jax.Array], *,
                     dropout_key=None, deterministic=True, rope=None,
                     sp_constraint=None, num_micro=None,
                     embed_fn=None, head_loss_fn=None):
    """Full pipelined loss over the global batch (microbatched).

    batch leaves [gbs, s]; gbs = M * mb. Embedding/head run outside the
    pipeline (see module docstring). ``embed_fn``/``head_loss_fn`` follow the
    1F1B hook contract (_1f1b_setup): unscaled per-microbatch contributions,
    normalizers closed over the full batch; defaults implement the GPT
    family.
    """
    M = num_micro or cfg.parallel.num_micro_batches or 1
    gbs = batch["tokens"].shape[0]
    assert gbs % M == 0
    mb = gbs // M

    def split(x):
        return x.reshape(M, mb, *x.shape[1:])

    tokens = split(batch["tokens"])
    labels = split(batch["labels"])
    loss_mask = split(batch["loss_mask"])
    aux_mb = _split_extra_keys(batch, split)
    token_idx = batch.get("token_idx")  # [s], batch-invariant (zigzag cp)

    if rope is None:
        rope = lm.make_rope_cache(cfg)

    use_dropout = dropout_key is not None and not deterministic
    embed_keys, layer_keys = microbatch_keys(
        dropout_key if use_dropout else None, M
    )

    outer = {k: v for k, v in params.items() if k != "layers"}
    default_embed, default_head = _default_gpt_fns(cfg, batch, use_dropout)
    if embed_fn is None:
        embed_fn = default_embed
    if head_loss_fn is None:
        head_loss_fn = default_head

    # [M, mb, s, h] embeddings (vocab-parallel over tp under pjit); dropout
    # keys per microbatch, matching the pp=1 path (model_forward:149-152)
    if embed_keys is not None:
        hidden = jax.vmap(
            lambda t, a, ke: embed_fn(outer, t, a, ke)
        )(tokens, aux_mb, embed_keys)
    else:
        hidden = jax.vmap(lambda t, a: embed_fn(outer, t, a, None))(tokens, aux_mb)

    hidden, moe_aux = pipeline_apply(
        cfg, mesh, params["layers"], hidden, aux_mb, dropout_key,
        deterministic, rope, token_idx=token_idx, mb_keys=layer_keys,
    )

    loss = microbatched_head_loss(
        head_loss_fn, outer, hidden, labels, loss_mask, aux_mb
    )
    metrics = {"lm loss": loss}
    if cfg.model.num_experts is not None:
        from megatron_llm_tpu.models.moe import aux_loss_coeffs

        # aux_acc summed every microbatch; the pp=1 path averages the
        # per-microbatch aux (loss_from_batch + grad-accum mean) — match it
        balance, z = moe_aux[0] / M, moe_aux[1] / M
        c_bal, c_z = aux_loss_coeffs(cfg)
        loss = loss + c_bal * balance + c_z * z
        metrics["moe aux loss"] = balance
        if c_z:
            metrics["router z loss"] = z  # matches loss_from_batch reporting
    return loss, metrics
