"""Pipeline parallelism: collective-permute microbatch pipelining inside jit.

Replaces the reference's pipeline engine (megatron/schedules.py:606-722 1F1B,
p2p_communication.py isend/irecv) with the TPU-native formulation:

* stage placement is *data placement*: the stacked layer axis [L, ...] is
  sharded over the ``pp`` mesh axis (each stage holds L/pp contiguous layers)
  — no per-stage module classes, and checkpoint resharding over pp is a
  resharding no-op.
* stage transfer is ``lax.ppermute`` over ``pp`` inside a ``lax.scan`` over
  microbatch "ticks" — XLA lowers it to ICI collective-permute, the hardware
  analog of the reference's batched isend/irecv (p2p_communication.py:205-231).
* the schedule: every stage computes each tick; tick t feeds microbatch t into
  stage 0; the last stage emits microbatch t-(pp-1) at tick t. Total ticks
  M + pp - 1 — the same bubble as the reference's warmup(pp-rank-1)/steady/
  cooldown accounting (schedules.py:648-720).
* backward is autodiff through the scan: ppermute transposes to the reverse
  permute, giving the mirrored cooldown. This is a GPipe-style schedule
  (all-forward-then-all-backward per jit step) with per-stage remat; a true
  interleaved 1F1B with jax.vjp staging is an optimization slot for later
  rounds.
* only ``pp`` is manual (shard_map axis_names={'pp'}): dp/tp/sp shardings
  inside the stage body stay under GSPMD exactly as in the pp=1 path.

Embedding, final norm, and the LM head run outside the pipelined region,
replicated over pp (their grads psum over pp automatically under pjit) —
which also implements the reference's first/last-stage embedding tying
(module.py:52-121) without an explicit embedding group.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from megatron_llm_tpu.core import rng as rng_mod
from megatron_llm_tpu.core.parallel_state import CP_AXIS, PP_AXIS
from megatron_llm_tpu.models import language_model as lm
from megatron_llm_tpu.models.transformer import transformer_forward
from megatron_llm_tpu.ops.cross_entropy import softmax_cross_entropy
from megatron_llm_tpu.ops.norms import norm


def _stage_body(cfg, layers_local, x, aux, token_idx, dropout_key,
                deterministic, rope):
    """Run this stage's local layers on one microbatch of hidden states."""
    pp = jax.lax.axis_size(PP_AXIS)
    stage = jax.lax.axis_index(PP_AXIS)
    if dropout_key is not None:
        # distinct dropout streams per cp seq-chunk (analog of the reference's
        # per-TP-rank RNG fork inside parallel regions, random.py:144-172)
        dropout_key = jax.random.fold_in(
            dropout_key, jax.lax.axis_index(CP_AXIS)
        )
    layers_per_stage = jax.tree_util.tree_leaves(layers_local)[0].shape[0]
    hidden, _ = transformer_forward(
        cfg, layers_local, x,
        rope=rope,
        position_ids=aux.get("position_ids"),
        segment_ids=aux.get("segment_ids"),
        token_idx=token_idx,
        dropout_key=dropout_key,
        deterministic=deterministic,
        layer_offset=stage * layers_per_stage,
    )
    return hidden


def pipeline_apply(cfg, mesh, stacked_layers, hidden_mb: jax.Array,
                   aux_mb: Dict[str, jax.Array], dropout_key, deterministic,
                   rope, token_idx: Optional[jax.Array] = None):
    """Run the pipelined transformer body.

    hidden_mb: [M, mb, s, h] embedded microbatches; aux_mb leaves [M, mb, s];
    token_idx: optional [s] zigzag index vector (parallel/ring.py).
    Returns [M, mb, s, h] final hidden states (replicated over pp).
    """
    pp = cfg.parallel.pipeline_model_parallel_size
    M = hidden_mb.shape[0]
    if token_idx is None:
        # constant placeholder so the shard_map signature is static; the
        # sentinel -1 row is never read (selected below)
        token_idx_arr = jnp.full((hidden_mb.shape[2],), -1, jnp.int32)
    else:
        token_idx_arr = token_idx

    def body(layers_local, hidden_mb, aux_mb, token_idx_local):
        stage = jax.lax.axis_index(PP_AXIS)
        perm = [(i, (i + 1) % pp) for i in range(pp)]

        def tick(carry, t):
            recv = carry
            mb_idx = jnp.clip(t, 0, M - 1)
            x_in = jax.tree.map(lambda a: a[mb_idx], hidden_mb)
            aux = jax.tree.map(lambda a: a[mb_idx], aux_mb)
            inp = jnp.where(stage == 0, x_in, recv)
            dk = (
                None if dropout_key is None
                else jax.random.fold_in(dropout_key, t)
            )
            out = _stage_body(
                cfg, layers_local, inp, aux,
                token_idx_local if token_idx is not None else None,
                dk, deterministic, rope,
            )
            nxt = jax.lax.ppermute(out, PP_AXIS, perm)
            # last stage's output for microbatch t-(pp-1), zero elsewhere
            y = jnp.where(stage == pp - 1, out, jnp.zeros_like(out))
            return nxt, y

        init = jnp.zeros_like(hidden_mb[0])
        _, ys = jax.lax.scan(tick, init, jnp.arange(M + pp - 1))
        outs = ys[pp - 1:]  # [M, mb, s, h], valid only on the last stage
        # broadcast last-stage results to every stage (psum of one-hot data);
        # transpose of this psum routes dLoss back to the last stage only.
        return jax.lax.psum(outs, PP_AXIS)

    # cp joins pp as a manual axis: hidden/aux seq dims are cp-local inside
    # the body, and the attention dispatch takes the ring_attention_manual
    # path (parallel/ring.py) — one shard_map, no nesting.
    P = jax.sharding.PartitionSpec
    hidden_spec = P(None, None, CP_AXIS, None)  # [M, mb, s, h]
    aux_spec = P(None, None, CP_AXIS)           # [M, mb, s]
    fn = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(
            jax.tree.map(lambda _: P(PP_AXIS), stacked_layers),
            hidden_spec,
            jax.tree.map(lambda _: aux_spec, aux_mb),
            P(CP_AXIS),
        ),
        out_specs=hidden_spec,
        axis_names={PP_AXIS, CP_AXIS},
        check_vma=False,
    )
    return fn(stacked_layers, hidden_mb, aux_mb, token_idx_arr)


# ---------------------------------------------------------------------------
# True 1F1B: gradients computed inside the tick loop, O(pp) activation memory
# ---------------------------------------------------------------------------


def pipeline_1f1b_loss_and_grads(
    cfg, mesh, params, batch: Dict[str, jax.Array], *,
    rope=None, loss_scale=None, num_micro=None,
):
    """One-forward-one-backward pipeline schedule (schedules.py:606-722).

    Unlike :func:`pipeline_loss_fn` (GPipe-style: autodiff through the tick
    scan, which saves one stage-input per tick — O(M) activation memory),
    this computes gradients INSIDE the loop: at tick t, stage s runs the
    forward for microbatch ``t - s`` and the backward (via ``jax.vjp`` on the
    saved stage input — rematerialized, the recompute analog of the
    reference's activation checkpointing) for microbatch ``t - 2(pp-1) + s``.
    Saved inputs live in a ring buffer of depth 2*pp — the O(pp) in-flight
    memory discipline the reference gets from deallocate_output_tensor +
    1F1B ordering (schedules.py:36-88,648-720).

    The embedding, final norm, LM head and loss run inside the loop on their
    owning stages (first/last); every stage computes them SPMD-style and the
    unused results are masked — the head matmul on non-final stages is the
    price of lockstep SPMD (~h*v/(12*h^2*L/pp) of a tick, a few percent).

    Deterministic path only (dropout=0 — the Llama/Falcon/Mistral finetune
    default). Returns (loss, grads) with grads matching the params tree.
    """
    assert cfg.model.hidden_dropout == 0.0 and cfg.model.attention_dropout == 0.0, (
        "1f1b schedule currently supports deterministic training only; "
        "use pipeline_schedule='gpipe' with dropout"
    )
    pp = cfg.parallel.pipeline_model_parallel_size
    M = num_micro or cfg.parallel.num_micro_batches or 1
    gbs = batch["tokens"].shape[0]
    assert gbs % M == 0
    mb = gbs // M
    if rope is None:
        rope = lm.make_rope_cache(cfg)
    scale = loss_scale if loss_scale is not None else jnp.float32(1.0)

    def split(x):
        return x.reshape(M, mb, *x.shape[1:])

    tokens = split(batch["tokens"])
    labels = split(batch["labels"])
    loss_mask = split(batch["loss_mask"]).astype(jnp.float32)
    aux_mb = {}
    for k in ("position_ids", "segment_ids"):
        if batch.get(k) is not None:
            aux_mb[k] = split(batch[k])
    token_idx = batch.get("token_idx")
    denom = jnp.maximum(loss_mask.sum(), 1.0)  # global token count

    # params split: layers are pp-sharded; everything else ("outer": embedding,
    # final_norm, lm_head if untied) is replicated and used at the ends.
    layers = params["layers"]
    outer = {k: v for k, v in params.items() if k != "layers"}

    def embed_fn(outer_p, tok, aux):
        return lm.embed_tokens(cfg, outer_p, tok, aux.get("position_ids"))

    def head_loss_fn(outer_p, hidden, lbl, msk):
        h = norm(hidden, outer_p["final_norm"], cfg.model.layernorm_epsilon,
                 cfg.model.use_rms_norm)
        logits = lm.compute_logits(cfg, outer_p, h)
        per_token = softmax_cross_entropy(logits, lbl)
        return (per_token * msk).sum() / denom * scale

    def body(layers_local, outer_p, tokens, labels, loss_mask, aux_mb,
             token_idx_local):
        stage = jax.lax.axis_index(PP_AXIS)
        last = pp - 1
        perm_fwd = [(i, (i + 1) % pp) for i in range(pp)]
        perm_bwd = [(i, (i - 1) % pp) for i in range(pp)]
        depth = 2 * pp
        s_local = tokens.shape[2]
        h = cfg.model.hidden_size
        dtype = (
            jnp.bfloat16 if cfg.training.params_dtype == "bfloat16"
            else jnp.float16 if cfg.training.params_dtype == "float16"
            else jnp.float32
        )

        def stage_fwd(L, x, aux):
            return _stage_body(
                cfg, L, x, aux,
                token_idx_local if token_idx is not None else None,
                None, True, rope,
            )

        def aux_at(i):
            return jax.tree.map(lambda a: a[i], aux_mb)

        def tick(carry, t):
            x_recv, g_recv, saved, acc_L, acc_outer, loss_acc = carry
            f_mb = t - stage
            b_mb = t - 2 * (pp - 1) + stage
            do_f = jnp.logical_and(f_mb >= 0, f_mb < M)
            do_b = jnp.logical_and(b_mb >= 0, b_mb < M)
            f_idx = jnp.clip(f_mb, 0, M - 1)
            b_idx = jnp.clip(b_mb, 0, M - 1)

            # ---- forward: embed on stage 0, else the ppermuted stream ----
            x_emb = embed_fn(outer_p, tokens[f_idx], aux_at(f_idx))
            x_in = jnp.where(stage == 0, x_emb, x_recv).astype(dtype)
            # guard the save: during cooldown f_idx clips to M-1, whose slot
            # may still be awaiting its backward
            saved_upd = jax.lax.dynamic_update_index_in_dim(
                saved, x_in, f_idx % depth, 0
            )
            saved = jnp.where(do_f, saved_upd, saved)
            y = stage_fwd(layers_local, x_in, aux_at(f_idx))

            # ---- head + loss on the last stage's fresh output ----
            loss_f, head_vjp = jax.vjp(
                lambda op, yy: head_loss_fn(op, yy, labels[f_idx],
                                            loss_mask[f_idx]),
                outer_p, y,
            )
            use_head = jnp.logical_and(stage == last, do_f)
            d_outer_head, dy = head_vjp(jnp.float32(1.0))
            loss_acc = loss_acc + jnp.where(use_head, loss_f, 0.0)
            acc_outer = jax.tree.map(
                lambda a, g: a + jnp.where(use_head, g, jnp.zeros_like(g)),
                acc_outer, d_outer_head,
            )

            # ---- backward for the older microbatch (remat from saved x) ----
            g_in = jnp.where(stage == last, dy.astype(dtype), g_recv)
            x_saved = jax.lax.dynamic_index_in_dim(
                saved, b_idx % depth, 0, keepdims=False
            )
            _, stage_vjp = jax.vjp(
                lambda L, xx: stage_fwd(L, xx, aux_at(b_idx)),
                layers_local, x_saved,
            )
            dlayers, dx = stage_vjp(g_in)
            acc_L = jax.tree.map(
                lambda a, g: a + jnp.where(do_b, g, jnp.zeros_like(g)),
                acc_L, dlayers,
            )

            # ---- embedding backward on stage 0 ----
            _, emb_vjp = jax.vjp(
                lambda op: embed_fn(op, tokens[b_idx], aux_at(b_idx)), outer_p
            )
            (d_outer_emb,) = emb_vjp(dx)
            use_emb = jnp.logical_and(stage == 0, do_b)
            acc_outer = jax.tree.map(
                lambda a, g: a + jnp.where(use_emb, g, jnp.zeros_like(g)),
                acc_outer, d_outer_emb,
            )

            x_next = jax.lax.ppermute(y.astype(dtype), PP_AXIS, perm_fwd)
            g_next = jax.lax.ppermute(dx, PP_AXIS, perm_bwd)
            return (x_next, g_next, saved, acc_L, acc_outer, loss_acc), None

        zero_x = jnp.zeros((mb, s_local, h), dtype)
        init = (
            zero_x,
            zero_x,
            jnp.zeros((depth, mb, s_local, h), dtype),
            jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                         layers_local),
            jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), outer_p),
            jnp.float32(0.0),
        )
        (_, _, _, acc_L, acc_outer, loss_acc), _ = jax.lax.scan(
            tick, init, jnp.arange(M + 2 * (pp - 1))
        )
        # cp shards contribute partial sums over their seq chunks; pp stages
        # hold zeros for params they do not own (outer) — psum both.
        acc_L = jax.lax.psum(acc_L, CP_AXIS)
        acc_outer = jax.lax.psum(
            jax.lax.psum(acc_outer, PP_AXIS), CP_AXIS
        )
        loss_acc = jax.lax.psum(jax.lax.psum(loss_acc, PP_AXIS), CP_AXIS)
        return acc_L, acc_outer, loss_acc

    P = jax.sharding.PartitionSpec
    data_spec = P(None, None, CP_AXIS)
    fn = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(
            jax.tree.map(lambda _: P(PP_AXIS), layers),
            jax.tree.map(lambda _: P(), outer),
            data_spec, data_spec, data_spec,
            jax.tree.map(lambda _: data_spec, aux_mb),
            P(CP_AXIS),
        ),
        out_specs=(
            jax.tree.map(lambda _: P(PP_AXIS), layers),
            jax.tree.map(lambda _: P(), outer),
            P(),
        ),
        axis_names={PP_AXIS, CP_AXIS},
        check_vma=False,
    )
    if token_idx is None:
        token_idx_arr = jnp.full((tokens.shape[2],), -1, jnp.int32)
    else:
        token_idx_arr = token_idx
    grads_L, grads_outer, loss = fn(
        layers, outer, tokens, labels, loss_mask, aux_mb, token_idx_arr
    )
    grads = dict(grads_outer)
    grads["layers"] = grads_L
    return loss, grads


def pipeline_loss_fn(cfg, mesh, params, batch: Dict[str, jax.Array], *,
                     dropout_key=None, deterministic=True, rope=None,
                     sp_constraint=None, num_micro=None):
    """Full pipelined loss over the global batch (microbatched).

    batch leaves [gbs, s]; gbs = M * mb. Embedding/head run outside the
    pipeline (see module docstring).
    """
    M = num_micro or cfg.parallel.num_micro_batches or 1
    gbs = batch["tokens"].shape[0]
    assert gbs % M == 0
    mb = gbs // M

    def split(x):
        return x.reshape(M, mb, *x.shape[1:])

    tokens = split(batch["tokens"])
    labels = split(batch["labels"])
    loss_mask = split(batch["loss_mask"])
    aux_mb = {}
    for k in ("position_ids", "segment_ids"):
        if batch.get(k) is not None:
            aux_mb[k] = split(batch[k])
    token_idx = batch.get("token_idx")  # [s], batch-invariant (zigzag cp)

    if rope is None:
        rope = lm.make_rope_cache(cfg)

    # [M, mb, s, h] embeddings (vocab-parallel over tp under pjit)
    hidden = jax.vmap(lambda t: lm.embed_tokens(cfg, params, t, None))(tokens)
    if dropout_key is not None and not deterministic:
        k_embed, dropout_key = jax.random.split(dropout_key)
        hidden = rng_mod.dropout(k_embed, cfg.model.hidden_dropout, hidden)

    hidden = pipeline_apply(
        cfg, mesh, params["layers"], hidden, aux_mb, dropout_key,
        deterministic, rope, token_idx=token_idx,
    )

    # Head + CE one microbatch at a time: materializing [M, mb, s, v] logits
    # for the whole global batch (vocab 32k, seq 4k, M=16 -> tens of GB)
    # would defeat microbatching. Matches the non-pp path's discipline
    # (training_step.py grad-accumulation scan).
    # remat: without it the scan's VJP saves each iteration's logits as
    # residuals — cumulatively the same [M, mb, s, v] footprint again
    @functools.partial(jax.checkpoint, policy=None)
    def ce_loss_sum(hid, lbl, msk):
        h = norm(hid, params["final_norm"], cfg.model.layernorm_epsilon,
                 cfg.model.use_rms_norm)
        logits = lm.compute_logits(cfg, params, h)  # [mb, s, v]
        per_token = softmax_cross_entropy(logits, lbl)
        return (per_token * msk.astype(jnp.float32)).sum()

    def ce_mb(carry, inp):
        hid, lbl, msk = inp
        loss_sum, mask_sum = carry
        return (loss_sum + ce_loss_sum(hid, lbl, msk),
                mask_sum + msk.astype(jnp.float32).sum()), None

    (loss_sum, mask_sum), _ = jax.lax.scan(
        ce_mb, (jnp.float32(0.0), jnp.float32(0.0)), (hidden, labels, loss_mask)
    )
    loss = loss_sum / jnp.maximum(mask_sum, 1.0)
    return loss, {"lm loss": loss}
