"""Ring attention — context parallelism over the ``cp`` mesh axis.

The reference has **no** context parallelism (SURVEY §2.1: long context is
served by FlashAttention-2 + RoPE scaling + sliding window only); this module
is the TPU-native extension that makes sequence length a first-class sharded
dimension, the way the reference makes hidden/vocab dims sharded via TP.

Design (blockwise ring attention, Liu et al. 2023 style, TPU-native):

* the sequence axis of Q/K/V is sharded over ``cp``; each device holds a
  contiguous (or zigzag-permuted) chunk.
* K/V chunks rotate around the cp ring with ``lax.ppermute`` (one ICI hop per
  step — the collective rides the torus neighbour links), while each device
  accumulates its local Q against every K/V chunk with the online-softmax
  recurrence (running max ``m``, normalizer ``l``, unnormalized output ``o``)
  — the same accumulation the Pallas flash kernel uses per block, lifted to
  the inter-chip level.
* causal masking is computed from explicit *token indices* carried (and
  rotated) alongside K/V, so arbitrary sequence permutations work. That is
  what makes **zigzag load balancing** a pure data transform: device ``i``
  holds chunks ``i`` and ``2*cp-1-i`` of the sequence, so every device sees
  the same amount of unmasked causal work (a contiguous split leaves device 0
  nearly idle and device cp-1 doing all of it).
* the whole loop is a differentiable ``lax.scan``; the backward pass is
  autodiff through the scan, with ``ppermute``'s transpose providing the
  reverse rotation — no hand-written bwd collectives.

GQA is computed grouped (no K/V head expansion), matching ops/attention.py.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from megatron_llm_tpu.core import parallel_state as ps
from megatron_llm_tpu.parallel import compat
from megatron_llm_tpu.parallel.compat import shard_map
from megatron_llm_tpu.ops.attention import NEG_INF

# Row-blocking of the ring online softmax (see _ring_attention_local):
# local seqs above the threshold process Q rows in blocks of this size.
_Q_BLOCK_THRESHOLD = 4096
_Q_BLOCK_ROWS = 2048
_Q_BLOCK_MIN = 256        # floor: below this the scan is latency-bound
_Q_BLOCK_OVER = 4 * _Q_BLOCK_ROWS  # ceiling for the fall-UP path


def _choose_q_block(sq: int) -> int:
    """Pick the Q-row block size for the ring online-softmax scan.

    Blocks must divide sq exactly (the scan reshapes [sq] -> [nb, blk]).
    The largest divisor in [_Q_BLOCK_MIN, _Q_BLOCK_ROWS] wins; for
    non-smooth sq (e.g. prime, or 2*p) whose only small divisors are tiny,
    falling DOWN toward blk=1 would turn one ring step into up to sq
    sequential checkpointed iterations — a severe compile/runtime cliff —
    so we instead fall UP to the smallest divisor above the budget (score
    temps grow proportionally but stay bounded by _Q_BLOCK_OVER). If even
    that would exceed 4x the budget, the config is pathological and we
    refuse with guidance rather than silently compile something terrible.
    """
    if sq <= _Q_BLOCK_THRESHOLD:
        return sq
    divs = [d for d in range(_Q_BLOCK_MIN, _Q_BLOCK_ROWS + 1) if sq % d == 0]
    if divs:
        return max(divs)
    over = min(
        (d for d in range(_Q_BLOCK_ROWS + 1, _Q_BLOCK_OVER + 1)
         if sq % d == 0),
        default=None,
    )
    if over is not None:
        return over
    raise ValueError(
        f"ring attention: local seq length {sq} has no divisor in "
        f"[{_Q_BLOCK_MIN}, {_Q_BLOCK_OVER}] to use as a Q-row block; "
        f"choose seq_len / (2*cp) with a power-of-two (or otherwise "
        f"smooth) factor so the online softmax can be row-blocked."
    )


# ---------------------------------------------------------------------------
# Zigzag load balancing (pure data transform)
# ---------------------------------------------------------------------------


def zigzag_permutation(seq_len: int, cp: int) -> np.ndarray:
    """Permutation p so that tokens p[chunk_i] land on cp-rank i balanced.

    Splits the sequence into 2*cp chunks; rank i holds chunks (i, 2*cp-1-i).
    Under causal masking every rank then attends to the same number of
    unmasked (q, k) pairs.
    """
    assert seq_len % (2 * cp) == 0, (
        f"seq_len {seq_len} must be divisible by 2*cp = {2 * cp} for zigzag"
    )
    c = seq_len // (2 * cp)
    chunks = np.arange(seq_len).reshape(2 * cp, c)
    order = []
    for i in range(cp):
        order.append(chunks[i])
        order.append(chunks[2 * cp - 1 - i])
    return np.concatenate(order)


def apply_zigzag(batch: Dict[str, np.ndarray], cp: int) -> Dict[str, np.ndarray]:
    """Permute every per-token tensor of a batch for zigzag CP sharding.

    Adds ``token_idx`` (the original sequence index of each permuted slot) so
    ring attention can reconstruct the causal structure. Per-token CE loss is
    permutation-invariant under the matching label/mask permutation, so the
    training loss is unchanged.
    """
    seq_keys = ("tokens", "labels", "loss_mask", "position_ids", "segment_ids")
    some = next(v for k, v in batch.items() if k in seq_keys)
    perm = zigzag_permutation(some.shape[1], cp)
    out = dict(batch)
    for k in seq_keys:
        if k in batch and batch[k] is not None:
            out[k] = np.ascontiguousarray(np.asarray(batch[k])[:, perm])
    if "position_ids" not in out or out.get("position_ids") is None:
        # RoPE must still see original positions after the permutation.
        out["position_ids"] = np.broadcast_to(
            perm[None, :], some.shape[:2]
        ).astype(np.int32)
    out["token_idx"] = perm.astype(np.int32)
    return out


# ---------------------------------------------------------------------------
# Flash-in-ring: the Pallas kernel computes each (Q-chunk, KV-chunk) pair
# ---------------------------------------------------------------------------
#
# The jnp ring loop below materializes [.., blk, skv] fp32 score tensors in
# HBM between the two matmuls of every ring step — XLA cannot fuse a matmul
# -> softmax -> matmul chain the way a flash kernel tiles it through VMEM.
# For the CONTIGUOUS chunk layout (token_idx=None; zigzag is opt-in), each
# ring step's masking structure collapses to one of exactly three cases per
# (Q-chunk i, KV-chunk src) pair (equal chunk sizes):
#     src > i   entirely above the causal diagonal  -> skip (lse = -inf)
#     src == i  the diagonal chunk                  -> flash with causal=True
#     src < i   entirely below                      -> flash with causal=False
# so the unmodified kernel covers every case, chunk results merge by their
# log-sum-exp, and the BACKWARD is exact per chunk: FlashAttention's bwd
# only needs the GLOBAL per-row lse and delta = rowsum(do*o) — both of
# which the forward merge produces — so each KV chunk's (dq+, dk, dv)
# contribution is one _bwd kernel call with the global residuals, with dk/dv
# accumulators riding the same ppermute ring home to their owner chip.
# Sliding windows span chunk boundaries at offsets the kernel cannot
# express and fall back to the jnp path. The zigzag layout IS kernelized —
# the striped variant further below (declared via the ``zigzag`` contract
# flag); non-causal permuted batches need no striping at all (their
# masking is order-independent) and use this contiguous ring directly.
# See _dispatch_local for the routing table.


def _flash_ring_blocks(s: int, d: int) -> tuple:
    # the kernel module's single block policy: VMEM cap by head_dim AND the
    # MLT_FLASH_BLOCK_Q/KV sweep overrides (a retune sweep must reach the
    # ring path too, not just plain flash_attention)
    from megatron_llm_tpu.ops.pallas.flash_attention import pick_blocks

    return pick_blocks(s, s, d)


def _ring_perm(cp: int) -> list:
    """The KV-rotation permutation — shared by fwd and bwd so the two ring
    directions can never diverge silently."""
    return [(j, (j + 1) % cp) for j in range(cp)]


def _ring_case_index(src, i, causal):
    """skip(0) / causal-diagonal(1) / unmasked(2) classification of a
    (Q-chunk i, KV-chunk src) pair — THE masking policy of the flash ring,
    shared by forward and backward (a divergence would be a silent
    wrong-gradient bug, not a crash)."""
    if not causal:
        return jnp.int32(2)
    return jnp.where(src == i, jnp.int32(1),
                     jnp.where(src < i, jnp.int32(2), jnp.int32(0)))


def _flash_shapes_ok(s: int, d: int) -> bool:
    if d not in (64, 128, 256) or s < 128 or s % 128 != 0:
        return False
    try:
        from megatron_llm_tpu.ops.pallas import flash_attention  # noqa: F401
    except ImportError:
        return False
    return True


def _merge_chunk(acc, m_run, l_run, out_t, lse_t):
    """Log-sum-exp merge of one chunk's (normalized out, lse) into the
    running (acc fp32, max, normalizer) — shared by the contiguous and
    striped rings. Guards the all-masked-so-far rows (lse at NEG_INF;
    exp of NEG-NEG would be 1 and poison the merge)."""
    m_new = jnp.maximum(m_run, lse_t)
    alpha = jnp.where(m_run <= NEG_INF * 0.5, 0.0, jnp.exp(m_run - m_new))
    beta = jnp.where(lse_t <= NEG_INF * 0.5, 0.0, jnp.exp(lse_t - m_new))
    acc = acc * alpha[..., None] + out_t * beta[..., None]
    return acc, m_new, l_run * alpha + beta


def _flash_ring_fwd_impl(qh, kh, vh, sq3, skv3, i, scale, causal, bq, bkv,
                         interpret, axis_name):
    """Returns (out [b,n,s,d] in qh.dtype, global lse [b,n,s,1] fp32).

    ``i`` is this device's cp coordinate, computed by the CALLER outside
    any nested shard_map: lax.axis_index lowers to its own
    manual-computation op, and emitting it where cp is not part of the
    innermost manual set double-binds the axis (sdy verifier error).
    ppermute does not have that problem — it stays inside."""
    from megatron_llm_tpu.ops.pallas.flash_attention import _fwd

    cp = compat.axis_size(axis_name)
    b, n, s, d = qh.shape
    perm = _ring_perm(cp)

    def chunk_cases(kh_t, vh_t, skv3_t):
        def skip():
            # fp32 partials: each chunk output is merged across cp steps,
            # and rounding every partial to bf16 first would add up to cp
            # roundings per element (the jnp ring accumulates fp32 too)
            return (jnp.zeros(qh.shape, jnp.float32),
                    jnp.full((b, n, s, 1), NEG_INF, jnp.float32))

        def diag():
            return tuple(_fwd(qh, kh_t, vh_t, sq3, skv3_t, scale, True,
                              None, bq, bkv, interpret,
                              out_dtype=jnp.float32))

        def full():
            return tuple(_fwd(qh, kh_t, vh_t, sq3, skv3_t, scale, False,
                              None, bq, bkv, interpret,
                              out_dtype=jnp.float32))

        return skip, diag, full

    def step(carry, _):
        acc, m_run, l_run, kh_t, vh_t, skv3_t, src = carry
        out_t, lse_t = lax.switch(_ring_case_index(src, i, causal),
                                  chunk_cases(kh_t, vh_t, skv3_t))
        acc, m_run, l_run = _merge_chunk(acc, m_run, l_run, out_t,
                                         lse_t[..., 0])
        kh_t = lax.ppermute(kh_t, axis_name, perm)
        vh_t = lax.ppermute(vh_t, axis_name, perm)
        if skv3_t is not None:
            skv3_t = lax.ppermute(skv3_t, axis_name, perm)
        return (acc, m_run, l_run, kh_t, vh_t, skv3_t,
                (src - 1) % cp), None

    acc0 = jnp.zeros((b, n, s, d), jnp.float32)
    m0 = jnp.full((b, n, s), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, n, s), jnp.float32)
    (acc, m_run, l_run, *_), _ = lax.scan(
        step, (acc0, m0, l0, kh, vh, skv3, i), None, length=cp)
    l_safe = jnp.where(l_run == 0.0, 1.0, l_run)
    out = (acc / l_safe[..., None]).astype(qh.dtype)
    lse = (m_run + jnp.log(l_safe))[..., None]
    return out, lse


@partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10, 11))
def _flash_ring(qh, kh, vh, sq3, skv3, i, scale, causal, bq, bkv, interpret,
                axis_name):
    out, _ = _flash_ring_fwd_impl(qh, kh, vh, sq3, skv3, i, scale, causal,
                                  bq, bkv, interpret, axis_name)
    return out


def _flash_ring_fwd(qh, kh, vh, sq3, skv3, i, scale, causal, bq, bkv,
                    interpret, axis_name):
    out, lse = _flash_ring_fwd_impl(qh, kh, vh, sq3, skv3, i, scale, causal,
                                    bq, bkv, interpret, axis_name)
    return out, (qh, kh, vh, sq3, skv3, i, out, lse)


def _flash_ring_bwd(scale, causal, bq, bkv, interpret, axis_name,
                    residuals, do):
    from megatron_llm_tpu.ops.pallas.flash_attention import _bwd

    qh, kh, vh, sq3, skv3, i, out, lse = residuals
    cp = compat.axis_size(axis_name)
    perm = _ring_perm(cp)
    # delta = rowsum(do * o) is loop-invariant — computed ONCE here (XLA
    # cannot CSE across scan iterations; recomputing it per ring step would
    # waste cp-1 full-tensor passes), fp32 kernel outputs for the same
    # one-rounding accumulation policy as the forward
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)

    def chunk_cases(kh_t, vh_t, skv3_t):
        def run(causal_flag):
            dq, dk, dv, _, _ = _bwd(
                scale, causal_flag, None, bq, bkv, interpret,
                (qh, kh_t, vh_t, out, lse, sq3, skv3_t), (do,),
                delta=delta, out_dtype=jnp.float32)
            return dq, dk, dv

        def skip():
            return (jnp.zeros(qh.shape, jnp.float32),
                    jnp.zeros(kh.shape, jnp.float32),
                    jnp.zeros(vh.shape, jnp.float32))

        return skip, lambda: run(True), lambda: run(False)

    def step(carry, _):
        dq_acc, dk_acc, dv_acc, kh_t, vh_t, skv3_t, src = carry
        dq_t, dk_t, dv_t = lax.switch(_ring_case_index(src, i, causal),
                                      chunk_cases(kh_t, vh_t, skv3_t))
        dq_acc = dq_acc + dq_t
        # dk/dv accumulators ride the ring WITH their chunk: after cp
        # permutes each chunk's accumulated gradient is back at its owner
        dk_acc = dk_acc + dk_t
        dv_acc = dv_acc + dv_t
        kh_t = lax.ppermute(kh_t, axis_name, perm)
        dk_acc = lax.ppermute(dk_acc, axis_name, perm)
        vh_t = lax.ppermute(vh_t, axis_name, perm)
        dv_acc = lax.ppermute(dv_acc, axis_name, perm)
        if skv3_t is not None:
            skv3_t = lax.ppermute(skv3_t, axis_name, perm)
        return (dq_acc, dk_acc, dv_acc, kh_t, vh_t, skv3_t,
                (src - 1) % cp), None

    (dq, dk, dv, *_), _ = lax.scan(
        step,
        (jnp.zeros(qh.shape, jnp.float32), jnp.zeros(kh.shape, jnp.float32),
         jnp.zeros(vh.shape, jnp.float32), kh, vh, skv3, i),
        None, length=cp)
    return (dq.astype(qh.dtype), dk.astype(kh.dtype), dv.astype(vh.dtype),
            None, None, None)


_flash_ring.defvjp(_flash_ring_fwd, _flash_ring_bwd)


# ---------------------------------------------------------------------------
# Striped flash ring: the zigzag layout, kernelized (round 5)
# ---------------------------------------------------------------------------
#
# Under the standard zigzag layout (apply_zigzag: device j holds global
# chunks j and 2cp-1-j of 2cp chunks, concatenated [A_j, B_j]) every
# (q-sub, kv-sub) pair is again a contiguous block pair, so the kernel
# covers it at half-chunk granularity. With causal masking only THREE of
# the four pairs are ever live:
#     A_i vs A_src   the contiguous 3-way case on (src, i)
#     B_i vs A_src   q chunk 2cp-1-i >= cp > src      -> always unmasked
#     B_i vs B_src   compares (2cp-1-i, 2cp-1-src)    -> the 3-way case
#                    with the roles of src and i SWAPPED
#     A_i vs B_src   kv chunk 2cp-1-src >= cp > i     -> always masked
# which is what makes zigzag balanced: each device does ~1.5 half-chunk
# kernels per step regardless of its rank, vs the contiguous layout where
# step t idles every device below rank t. (Callers declare the layout via
# the ``zigzag`` contract flag — token order is runtime data; non-causal
# permuted batches need no striping at all since their masking is
# order-independent and the plain flash ring is used.)


def _zz_cases(i, src, causal):
    case_aa = _ring_case_index(src, i, causal)
    case_bb = _ring_case_index(i, src, causal)
    return case_aa, case_bb


def _split_half(x, axis):
    c = x.shape[axis] // 2
    return (lax.slice_in_dim(x, 0, c, axis=axis),
            lax.slice_in_dim(x, c, 2 * c, axis=axis))


def _flash_ring_zz_fwd_impl(qh, kh, vh, sq3, skv3, i, scale, causal, bq,
                            bkv, interpret, axis_name):
    from megatron_llm_tpu.ops.pallas.flash_attention import _fwd

    assert causal, "striped ring is causal-only (see module note)"
    cp = compat.axis_size(axis_name)
    b, n, s, d = qh.shape
    c = s // 2
    perm = _ring_perm(cp)
    qA, qB = _split_half(qh, 2)
    sqA, sqB = _split_half(sq3, 2) if sq3 is not None else (None, None)

    def fwd_pair(q_, k_, v_, sq_, skv_, causal_flag):
        return tuple(_fwd(q_, k_, v_, sq_, skv_, scale, causal_flag, None,
                          bq, bkv, interpret, out_dtype=jnp.float32))

    def skip_out():
        return (jnp.zeros((b, n, c, d), jnp.float32),
                jnp.full((b, n, c, 1), NEG_INF, jnp.float32))

    def step(carry, _):
        accA, mA, lA, accB, mB, lB, kh_t, vh_t, skv3_t, src = carry
        kA, kB = _split_half(kh_t, 2)
        vA, vB = _split_half(vh_t, 2)
        skvA, skvB = (_split_half(skv3_t, 2) if skv3_t is not None
                      else (None, None))
        case_aa, case_bb = _zz_cases(i, src, causal)
        outAA, lseAA = lax.switch(case_aa, (
            skip_out,
            lambda: fwd_pair(qA, kA, vA, sqA, skvA, True),
            lambda: fwd_pair(qA, kA, vA, sqA, skvA, False)))
        accA, mA, lA = _merge_chunk(accA, mA, lA, outAA, lseAA[..., 0])
        outBA, lseBA = fwd_pair(qB, kA, vA, sqB, skvA, False)
        accB, mB, lB = _merge_chunk(accB, mB, lB, outBA, lseBA[..., 0])
        outBB, lseBB = lax.switch(case_bb, (
            skip_out,
            lambda: fwd_pair(qB, kB, vB, sqB, skvB, True),
            lambda: fwd_pair(qB, kB, vB, sqB, skvB, False)))
        accB, mB, lB = _merge_chunk(accB, mB, lB, outBB, lseBB[..., 0])
        kh_t = lax.ppermute(kh_t, axis_name, perm)
        vh_t = lax.ppermute(vh_t, axis_name, perm)
        if skv3_t is not None:
            skv3_t = lax.ppermute(skv3_t, axis_name, perm)
        return (accA, mA, lA, accB, mB, lB, kh_t, vh_t, skv3_t,
                (src - 1) % cp), None

    z = lambda: jnp.zeros((b, n, c, d), jnp.float32)  # noqa: E731
    mneg = lambda: jnp.full((b, n, c), NEG_INF, jnp.float32)  # noqa: E731
    l0 = lambda: jnp.zeros((b, n, c), jnp.float32)  # noqa: E731
    (accA, mA, lA, accB, mB, lB, *_), _ = lax.scan(
        step, (z(), mneg(), l0(), z(), mneg(), l0(), kh, vh, skv3, i),
        None, length=cp)

    def fin(acc, m_run, l_run):
        l_safe = jnp.where(l_run == 0.0, 1.0, l_run)
        return (acc / l_safe[..., None]).astype(qh.dtype), \
            (m_run + jnp.log(l_safe))[..., None]

    outA, lseA = fin(accA, mA, lA)
    outB, lseB = fin(accB, mB, lB)
    return (jnp.concatenate([outA, outB], axis=2),
            jnp.concatenate([lseA, lseB], axis=2))


@partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10, 11))
def _flash_ring_zz(qh, kh, vh, sq3, skv3, i, scale, causal, bq, bkv,
                   interpret, axis_name):
    out, _ = _flash_ring_zz_fwd_impl(qh, kh, vh, sq3, skv3, i, scale,
                                     causal, bq, bkv, interpret, axis_name)
    return out


def _flash_ring_zz_fwd(qh, kh, vh, sq3, skv3, i, scale, causal, bq, bkv,
                       interpret, axis_name):
    out, lse = _flash_ring_zz_fwd_impl(qh, kh, vh, sq3, skv3, i, scale,
                                       causal, bq, bkv, interpret,
                                       axis_name)
    return out, (qh, kh, vh, sq3, skv3, i, out, lse)


def _flash_ring_zz_bwd(scale, causal, bq, bkv, interpret, axis_name,
                       residuals, do):
    from megatron_llm_tpu.ops.pallas.flash_attention import _bwd

    qh, kh, vh, sq3, skv3, i, out, lse = residuals
    cp = compat.axis_size(axis_name)
    b, n, s, d = qh.shape
    nkv = kh.shape[1]
    c = s // 2
    perm = _ring_perm(cp)
    qA, qB = _split_half(qh, 2)
    sqA, sqB = _split_half(sq3, 2) if sq3 is not None else (None, None)
    outA, outB = _split_half(out, 2)
    lseA, lseB = _split_half(lse, 2)
    doA, doB = _split_half(do, 2)
    # loop-invariant delta, computed once per q-sub (same rationale as the
    # contiguous bwd)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)
    deltaA, deltaB = _split_half(delta, 2)

    def run_pair(q_, k_, v_, o_, lse_, do_, delta_, sq_, skv_, causal_flag):
        dq, dk, dv, _, _ = _bwd(
            scale, causal_flag, None, bq, bkv, interpret,
            (q_, k_, v_, o_, lse_, sq_, skv_), (do_,),
            delta=delta_, out_dtype=jnp.float32)
        return dq, dk, dv

    def zeros3():
        return (jnp.zeros((b, n, c, d), jnp.float32),
                jnp.zeros((b, nkv, c, d), jnp.float32),
                jnp.zeros((b, nkv, c, d), jnp.float32))

    def step(carry, _):
        dqA, dqB, dk_acc, dv_acc, kh_t, vh_t, skv3_t, src = carry
        kA, kB = _split_half(kh_t, 2)
        vA, vB = _split_half(vh_t, 2)
        skvA, skvB = (_split_half(skv3_t, 2) if skv3_t is not None
                      else (None, None))
        case_aa, case_bb = _zz_cases(i, src, causal)
        dqAA, dkAA, dvAA = lax.switch(case_aa, (
            zeros3,
            lambda: run_pair(qA, kA, vA, outA, lseA, doA, deltaA,
                             sqA, skvA, True),
            lambda: run_pair(qA, kA, vA, outA, lseA, doA, deltaA,
                             sqA, skvA, False)))
        dqBA, dkBA, dvBA = run_pair(qB, kA, vA, outB, lseB, doB, deltaB,
                                    sqB, skvA, False)
        dqBB, dkBB, dvBB = lax.switch(case_bb, (
            zeros3,
            lambda: run_pair(qB, kB, vB, outB, lseB, doB, deltaB,
                             sqB, skvB, True),
            lambda: run_pair(qB, kB, vB, outB, lseB, doB, deltaB,
                             sqB, skvB, False)))
        dqA = dqA + dqAA
        dqB = dqB + dqBA + dqBB
        dk_acc = dk_acc + jnp.concatenate([dkAA + dkBA, dkBB], axis=2)
        dv_acc = dv_acc + jnp.concatenate([dvAA + dvBA, dvBB], axis=2)
        kh_t = lax.ppermute(kh_t, axis_name, perm)
        dk_acc = lax.ppermute(dk_acc, axis_name, perm)
        vh_t = lax.ppermute(vh_t, axis_name, perm)
        dv_acc = lax.ppermute(dv_acc, axis_name, perm)
        if skv3_t is not None:
            skv3_t = lax.ppermute(skv3_t, axis_name, perm)
        return (dqA, dqB, dk_acc, dv_acc, kh_t, vh_t, skv3_t,
                (src - 1) % cp), None

    (dqA, dqB, dk, dv, *_), _ = lax.scan(
        step,
        (jnp.zeros((b, n, c, d), jnp.float32),
         jnp.zeros((b, n, c, d), jnp.float32),
         jnp.zeros(kh.shape, jnp.float32), jnp.zeros(vh.shape, jnp.float32),
         kh, vh, skv3, i),
        None, length=cp)
    dq = jnp.concatenate([dqA, dqB], axis=2)
    return (dq.astype(qh.dtype), dk.astype(kh.dtype), dv.astype(vh.dtype),
            None, None, None)


_flash_ring_zz.defvjp(_flash_ring_zz_fwd, _flash_ring_zz_bwd)


def _ring_attention_flash_core(q, k, v, seg_q, seg_kv, i, *, axis_name,
                               scale, causal, interpret, striped=False):
    """[b, s, n, d] wrapper over the kernel-layout ring (see module note).
    Every mesh axis must already be manual in the calling context; ``i``
    is the cp coordinate computed where cp was bound (see
    _flash_ring_fwd_impl's docstring); ``striped`` selects the zigzag
    half-chunk variant."""
    qh = q.transpose(0, 2, 1, 3)
    kh = k.transpose(0, 2, 1, 3)
    vh = v.transpose(0, 2, 1, 3)
    sq3 = seg_q.astype(jnp.int32)[:, None, :] if seg_q is not None else None
    skv3 = (seg_kv.astype(jnp.int32)[:, None, :]
            if seg_kv is not None else None)
    sub = 2 if striped else 1
    bq, bkv = _flash_ring_blocks(q.shape[1] // sub, q.shape[-1])
    ring = _flash_ring_zz if striped else _flash_ring
    out = ring(qh, kh, vh, sq3, skv3, i, scale, causal, bq, bkv,
               interpret, axis_name)
    return out.transpose(0, 2, 1, 3)


def _ring_attention_flash(q, k, v, seg_q, seg_kv, *, axis_name, scale,
                          causal, interpret, striped=False):
    """Dispatch the flash ring, manualizing any remaining auto mesh axes.

    From pjit-land the enclosing ring shard_map is full-manual and the
    kernels run directly; from the pipeline body only {pp, cp} are manual,
    and Mosaic kernels reject being left under ANY auto axis — so the
    whole ring loop (kernels + ppermutes; cp stays bound from the outer
    context) nests one shard_map over the rest, batch on (dp, ep), heads
    on tp (same composition as ops/attention._flash_sharded)."""
    abstract = compat.get_abstract_mesh()
    auto = set()
    if abstract is not None and not abstract.empty and abstract.manual_axes:
        auto = set(abstract.axis_names) - set(abstract.manual_axes)
    kw = dict(axis_name=axis_name, scale=scale, causal=causal,
              interpret=interpret, striped=striped)
    # the cp coordinate is computed HERE — where the caller's context binds
    # cp — and passed in: lax.axis_index emitted inside the nested
    # shard_map would double-bind the axis (sdy verifier error)
    i = compat.axis_index(axis_name)
    if not auto:
        return _ring_attention_flash_core(q, k, v, seg_q, seg_kv, i, **kw)
    qs = P(ps.DATA_AXES, None, ps.TP_AXIS, None)
    segs = P(ps.DATA_AXES, None)
    if seg_q is None:
        fn = shard_map(
            lambda q_, k_, v_, i_: _ring_attention_flash_core(
                q_, k_, v_, None, None, i_, **kw),
            mesh=abstract, in_specs=(qs, qs, qs, P()), out_specs=qs,
            axis_names=auto, check_vma=False)
        return fn(q, k, v, i)
    fn = shard_map(
        lambda q_, k_, v_, sq_, skv_, i_: _ring_attention_flash_core(
            q_, k_, v_, sq_, skv_, i_, **kw),
        mesh=abstract, in_specs=(qs, qs, qs, segs, segs, P()), out_specs=qs,
        axis_names=auto, check_vma=False)
    return fn(q, k, v, seg_q, seg_kv, i)


# ---------------------------------------------------------------------------
# The ring loop (runs inside shard_map; cp axis is manual)
# ---------------------------------------------------------------------------


def _ring_attention_local(
    q: jax.Array,  # [b, sq_loc, n, d]
    k: jax.Array,  # [b, skv_loc, nkv, d]
    v: jax.Array,  # [b, skv_loc, nkv, d]
    q_idx: jax.Array,    # [sq_loc] global token indices of local Q rows
    kv_idx: jax.Array,   # [skv_loc] global token indices of local K/V rows
    seg_q: Optional[jax.Array],   # [b, sq_loc] or None
    seg_kv: Optional[jax.Array],  # [b, skv_loc] or None
    *,
    axis_name: str,
    scale: float,
    causal: bool,
    sliding_window: Optional[int],
) -> jax.Array:
    cp = compat.axis_size(axis_name)
    b, sq, n, d = q.shape
    nkv = k.shape[2]
    g = n // nkv
    qg = (q.astype(jnp.float32) * scale).reshape(b, sq, nkv, g, d)

    # Row-block the online softmax: a full [.., sq, skv] fp32 score tensor
    # is ~8.6 GiB per layer at the 32K/cp=2 BASELINE config (heads 8, 16K x
    # 16K) and OOMs v5p during backward (tools/aot_scale_check.py found
    # this). Q rows are independent in online softmax, so scanning blocks
    # of rows inside each ring step bounds the live score temps to
    # [.., blk, skv] with bitwise-identical results.
    blk = _choose_q_block(sq)
    nb = sq // blk

    # send chunk i -> i+1 each step; after t steps a device holds the K/V
    # chunk of cp-rank (i - t) % cp. The rotated kv_idx tracks that for us.
    perm = [(i, (i + 1) % cp) for i in range(cp)]

    def allowed_mask(qi_b, kv_idx_t, seg_q_b, seg_kv_t):
        # [1 or b, blk, skv] for one row block
        ok = jnp.ones((1, qi_b.shape[0], k.shape[1]), dtype=bool)
        qi = qi_b[:, None]
        ki = kv_idx_t[None, :]
        if causal:
            ok &= (qi >= ki)[None]
        if sliding_window is not None:
            ok &= (qi - ki < sliding_window)[None]
        if seg_q is not None:
            ok = ok & (seg_q_b[:, :, None] == seg_kv_t[:, None, :])
        return ok

    def step(carry, _):
        o, m, l, k_t, v_t, kv_idx_t, seg_kv_t = carry
        kf = k_t.astype(jnp.float32)
        vf = v_t.astype(jnp.float32)

        def row_block(_, xs):
            qg_b, qi_b, seg_q_b, o_b, m_b, l_b = xs
            # scores [b, nkv, g, blk, skv] in fp32
            s = jnp.einsum("bqhgd,bkhd->bhgqk", qg_b, kf)
            ok = allowed_mask(qi_b, kv_idx_t, seg_q_b, seg_kv_t)[:, None, None]
            s_masked = jnp.where(ok, s, NEG_INF)
            m_new = jnp.maximum(m_b, s_masked.max(axis=-1))
            # mask applied to p directly — never rely on exp(-inf - -inf)
            p = jnp.where(ok, jnp.exp(s - m_new[..., None]), 0.0)
            alpha = jnp.exp(m_b - m_new)
            l_new = l_b * alpha + p.sum(axis=-1)
            o_new = o_b * alpha[..., None] + jnp.einsum(
                "bhgqk,bkhd->bhgqd", p, vf
            )
            return None, (o_new, m_new, l_new)

        def rows(x, axis):  # [.., sq, ..] -> [nb, .., blk, ..] for scan xs
            return jnp.moveaxis(
                x.reshape(*x.shape[:axis], nb, blk, *x.shape[axis + 1:]),
                axis, 0)

        qg_r = rows(qg, 1)                       # [nb, b, blk, nkv, g, d]
        qi_r = q_idx.reshape(nb, blk)
        seg_q_r = (rows(seg_q, 1) if seg_q is not None
                   else jnp.zeros((nb, 1, blk), jnp.int32))
        o_r = rows(o, 3)                         # [nb, b, nkv, g, blk, d]
        m_r = rows(m, 3)
        l_r = rows(l, 3)
        # checkpoint per block: without it, autodiff-of-scan STACKS every
        # block's [.., blk, skv] probability tensor as residuals — 16 GiB
        # at the 32K config, defeating the blocking. Recomputing scores in
        # the backward is the same FLOPs-for-memory trade flash attention
        # makes.
        _, (o2, m2, l2) = lax.scan(
            jax.checkpoint(row_block), None,
            (qg_r, qi_r, seg_q_r, o_r, m_r, l_r))

        def back(x, axis, tail):  # [nb, .., blk, ..] -> [.., sq, ..]
            y = jnp.moveaxis(x, 0, axis)
            return y.reshape(*y.shape[:axis], sq, *y.shape[axis + 2:]) \
                if tail else y.reshape(*y.shape[:axis], sq)

        o = back(o2, 3, True)
        m = back(m2, 3, False)
        l = back(l2, 3, False)
        k_t = lax.ppermute(k_t, axis_name, perm)
        v_t = lax.ppermute(v_t, axis_name, perm)
        kv_idx_t = lax.ppermute(kv_idx_t, axis_name, perm)
        if seg_kv_t is not None:
            seg_kv_t = lax.ppermute(seg_kv_t, axis_name, perm)
        return (o, m, l, k_t, v_t, kv_idx_t, seg_kv_t), None

    o0 = jnp.zeros((b, nkv, g, sq, d), jnp.float32)
    m0 = jnp.full((b, nkv, g, sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, nkv, g, sq), jnp.float32)
    (o, _, l, *_), _ = lax.scan(
        step, (o0, m0, l0, k, v, kv_idx, seg_kv), None, length=cp
    )
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = (o / l_safe[..., None]).transpose(0, 3, 1, 2, 4)  # [b, sq, nkv, g, d]
    return out.reshape(b, sq, n, d).astype(q.dtype)


def _local_indices(token_idx: Optional[jax.Array], s_local: int, axis_name: str):
    """Global token indices of this device's chunk (contiguous by default)."""
    if token_idx is not None:
        return token_idx
    return compat.axis_index(axis_name) * s_local + jnp.arange(s_local)


# ---------------------------------------------------------------------------
# Public entry: shard_map over the (dp, cp, tp) mesh
# ---------------------------------------------------------------------------


def ring_attention_manual(
    q: jax.Array,  # [b, s_local, n, d] — cp-LOCAL shards
    k: jax.Array,
    v: jax.Array,
    *,
    segment_ids: Optional[jax.Array] = None,  # [b, s_local]
    token_idx: Optional[jax.Array] = None,    # [s_local]
    causal: bool = True,
    sliding_window: Optional[int] = None,
    scale: Optional[float] = None,
    zigzag: bool = False,
) -> jax.Array:
    """Ring attention for callers already inside a shard_map that manualizes
    ``cp`` (e.g. the pipeline body, parallel/pipeline.py): operates on local
    seq shards directly, no inner shard_map."""
    scale = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    return _dispatch_local(
        q, k, v, segment_ids, token_idx,
        axis_name=ps.CP_AXIS, scale=scale, causal=causal,
        sliding_window=sliding_window, zigzag=zigzag,
    )


def _dispatch_local(q, k, v, seg, tok, *, axis_name, scale, causal,
                    sliding_window, zigzag=False):
    """Route a cp-local attention call to the fastest correct path:

    * contiguous chunks (no token_idx)         -> flash ring
    * permuted order but NON-causal            -> flash ring (order-
      independent masking: causal off, segments compare by value)
    * causal + declared standard zigzag layout -> striped flash ring
    * anything else (sliding windows, custom permutations, off-tile
      shapes, non-TPU targets)                 -> jnp online-softmax ring

    ``zigzag`` is a CONTRACT flag (cfg --cp_zigzag / apply_zigzag): token
    order is runtime data, so the caller declares the standard layout
    rather than the dispatcher inspecting it.
    """
    from megatron_llm_tpu.core.parallel_state import target_platform

    if target_platform() == "tpu" and sliding_window is None:
        if tok is None and _flash_shapes_ok(q.shape[1], q.shape[-1]):
            return _ring_attention_flash(
                q, k, v, seg, seg, axis_name=axis_name, scale=scale,
                causal=causal, interpret=False)
        if (tok is not None and not causal
                and _flash_shapes_ok(q.shape[1], q.shape[-1])):
            return _ring_attention_flash(
                q, k, v, seg, seg, axis_name=axis_name, scale=scale,
                causal=False, interpret=False)
        if (tok is not None and causal and zigzag and q.shape[1] % 2 == 0
                and _flash_shapes_ok(q.shape[1] // 2, q.shape[-1])):
            return _ring_attention_flash(
                q, k, v, seg, seg, axis_name=axis_name, scale=scale,
                causal=True, interpret=False, striped=True)
    idx = _local_indices(tok, q.shape[1], axis_name)
    return _ring_attention_local(
        q, k, v, idx, idx, seg, seg,
        axis_name=axis_name, scale=scale, causal=causal,
        sliding_window=sliding_window,
    )


def cp_is_manual() -> bool:
    """True when tracing inside a shard_map that already binds the cp axis."""
    abstract = compat.get_abstract_mesh()
    return (
        abstract is not None
        and not abstract.empty
        and ps.CP_AXIS in set(abstract.manual_axes)
    )


def ring_attention(
    q: jax.Array,  # [b, s, n, d] — global (pjit-land) arrays
    k: jax.Array,
    v: jax.Array,
    *,
    segment_ids: Optional[jax.Array] = None,  # [b, s]
    token_idx: Optional[jax.Array] = None,    # [s] original indices (zigzag)
    causal: bool = True,
    sliding_window: Optional[int] = None,
    scale: Optional[float] = None,
    mesh: Optional[Mesh] = None,
    zigzag: bool = False,
) -> jax.Array:
    """Context-parallel attention: seq over ``cp``, heads over ``tp``,
    batch over ``dp``.

    Called from the ops/attention dispatcher when the active mesh has cp > 1.
    From pjit-land it wraps the ring loop in shard_map; from inside an
    enclosing shard_map that already manualizes cp it runs locally.
    ``zigzag`` declares the standard apply_zigzag layout (see
    _dispatch_local).
    """
    if cp_is_manual():
        return ring_attention_manual(
            q, k, v, segment_ids=segment_ids, token_idx=token_idx,
            causal=causal, sliding_window=sliding_window, scale=scale,
            zigzag=zigzag,
        )
    mesh = mesh or ps.get_global_mesh()
    cp = mesh.shape.get(ps.CP_AXIS, 1)
    scale = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    assert q.shape[1] % cp == 0, (
        f"seq_len {q.shape[1]} not divisible by cp {cp}"
    )

    qs = P(ps.DATA_AXES, ps.CP_AXIS, ps.TP_AXIS, None)
    segs = P(ps.DATA_AXES, ps.CP_AXIS)
    idxs = P(ps.CP_AXIS)
    s_local = q.shape[1] // cp

    kw = dict(axis_name=ps.CP_AXIS, scale=scale, causal=causal,
              sliding_window=sliding_window, zigzag=zigzag)

    def local(q_, k_, v_, seg_=None, tok_=None):
        return _dispatch_local(q_, k_, v_, seg_, tok_, **kw)

    in_specs = [qs, qs, qs]
    args = [q, k, v]
    fn = local
    if segment_ids is not None and token_idx is not None:
        fn = lambda q_, k_, v_, s_, t_: local(q_, k_, v_, seg_=s_, tok_=t_)
        in_specs += [segs, idxs]
        args += [segment_ids, token_idx]
    elif segment_ids is not None:
        fn = lambda q_, k_, v_, s_: local(q_, k_, v_, seg_=s_)
        in_specs += [segs]
        args += [segment_ids]
    elif token_idx is not None:
        fn = lambda q_, k_, v_, t_: local(q_, k_, v_, tok_=t_)
        in_specs += [idxs]
        args += [token_idx]

    mapped = shard_map(
        fn, mesh=mesh, in_specs=tuple(in_specs), out_specs=qs, check_vma=False
    )
    return mapped(*args)
