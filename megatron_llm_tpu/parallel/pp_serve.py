"""Pipeline-parallel serving: the engine tick across a ``pp`` mesh axis.

Training has pp>1 (parallel/pipeline.py: 1F1B microbatches over
``collective_permute``) but until ISSUE 20 the serving engine was TP-only,
so a served model had to fit one host's chips.  This module extends the
engine's forward across pipeline stages:

* **Layer placement**: the stacked ``[L, ...]`` layer params and the paged
  K/V pools (``[L, pages, page, nkv, d]``) are sharded ``P(pp)`` on the
  layer dim — each stage holds ``L/pp`` layers and ONLY its own layers'
  K/V pages (the servable-model-size multiplier: per-stage pool bytes are
  ``1/pp`` of the tp-only pool).  Block tables, the page trie, the
  allocator and the commitment ledger stay host-side and stage-agnostic:
  page ids address the same slot of every stage's pool slice, so nothing
  in generation/ scheduling changes.
* **Microbatching**: a decode/ragged tick of ``R`` rows (``s == 1``)
  splits into ``M = pp`` contiguous row-range microbatches pumped through
  the stages on a ``T = M + pp - 1`` tick scan — decode is the
  steady-state-full pipeline the 1F1B schedule likes (every tick all
  stages run a GEMM, one microbatch apart).  Chunked prefill feeds
  ``[1, chunk]`` (one sequence), which cannot split by rows: it runs
  ``M = 1`` (stages sequential; prefill is not latency-critical and
  stays schedulable against decode ticks).  Contiguous row ranges keep
  intra-tick causality: row ``r1 > r0`` of one request lands in
  microbatch ``m1 >= m0``, and stage ``s`` runs ``m0`` at scan tick
  ``s + m0 < s + m1`` — writes land before the later rows attend.
* **Overlap**: the stage-boundary ``ppermute`` (named scope
  ``stage-permute``) is data-independent of the next tick's own GEMMs
  until the received activation is consumed, so XLA's latency-hiding
  scheduler runs the DMA behind the adjacent stage compute — PR 15's
  ring thesis applied one level up (T3, PAPERS.md).
* **Validity routing**: pipeline fill/drain ticks where ``t - stage`` is
  outside ``[0, M)`` must not touch live pages.  Invalid ticks are
  null-routed through page 0 (the engine's reserved NULL page): per-row
  block tables are zeroed, compressed ``table_index`` rows point at the
  prepended null table and ``horizons`` drop to 0 — garbage compute,
  discarded output, no state mutation.  The same trick the ragged tick
  uses for dead padding rows (ISSUE 11).

Like overlap.py, activation is a trace-time context: the engine's tick
builders wrap their bodies in :func:`activate`, and
``models/language_model.model_forward`` routes the transformer stack
through :func:`pipelined_transformer` when a context is live and the call
carries paged K/V.  ``serve_params`` returns None on pp==1 meshes, so an
inert ``--pp 1`` engine traces byte-for-byte today's program.

jax 0.4.37 note: ``ppermute`` inside a partial-manual region crashes the
GSPMD partitioner (spmd_partitioner.cc:512) — pp>1 engines flip to the
shardy partitioner via ``compat.enable_partitioner_for`` (the flag
participates in jit trace keys, so tp-only executables are never reused;
see ``_mesh_statics``).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from megatron_llm_tpu.core.parallel_state import PP_AXIS, TP_AXIS
from megatron_llm_tpu.parallel import compat

# Named scope wrapping every stage-boundary ppermute — device profiles
# attribute the hop DMA separately from the stage GEMMs (ISSUE 20
# observability satellite; asserted in HLO by tests and bench --mode pp).
STAGE_PERMUTE_SCOPE = "stage-permute"


class ServeParams:
    """Static pipeline-serving parameters captured at engine build."""

    __slots__ = ("mesh", "pp", "tp")

    def __init__(self, mesh, pp: int, tp: int):
        self.mesh = mesh
        self.pp = pp
        self.tp = tp


def serve_params(cfg, mesh) -> Optional[ServeParams]:
    """Resolve the pipeline-serving context, or None when inert.

    None whenever there is no mesh or the mesh's pp axis is 1 — an engine
    built with ``--pp 1`` (flag set but inert) takes the None path and is
    bitwise today's TP-only program.
    """
    if mesh is None:
        return None
    pp = dict(mesh.shape).get(PP_AXIS, 1)
    if pp <= 1:
        return None
    return ServeParams(mesh, pp, dict(mesh.shape).get(TP_AXIS, 1))


_state = threading.local()


@contextmanager
def activate(ctx: Optional[ServeParams]):
    """Trace-time activation — engine tick builders wrap their bodies."""
    prev = getattr(_state, "ctx", None)
    _state.ctx = ctx
    try:
        yield
    finally:
        _state.ctx = prev


def current() -> Optional[ServeParams]:
    return getattr(_state, "ctx", None)


def _null_route(paged, valid):
    """Point invalid rows at the NULL page so fill/drain ticks are inert.

    ``valid`` is a scalar bool (whole-microbatch validity).  Compressed
    form: index 0 is the prepended null table and horizon 0 means "touch
    no page" (ragged.py's dead-row convention).  Per-row form: page 0 is
    the engine's reserved NULL page, so a zeroed block table writes (and
    reads) only scratch.
    """
    if paged.table_index is not None:
        return paged._replace(
            horizons=jnp.where(valid, paged.horizons, 0),
            table_index=jnp.where(valid, paged.table_index, 0),
        )
    return paged._replace(
        block_tables=jnp.where(valid, paged.block_tables, 0))


def pipelined_transformer(cfg, ctx: ServeParams, stacked_layers, hidden, *,
                          rope, position_ids, kv_caches, paged):
    """Run the layer stack as a pp-stage pipeline over microbatched rows.

    Args mirror the ``transformer_forward`` call in model_forward;
    ``kv_caches`` is the stacked paged pool pair (``[L, ...]`` leaves,
    sharded ``P(pp)`` on the layer dim by ``PagedKVPool``).  Returns
    ``(hidden, new_kv_caches)`` — MoE aux is not plumbed (serving is
    deterministic inference; the engine discards it).
    """
    from megatron_llm_tpu.models.transformer import transformer_forward
    from megatron_llm_tpu.ops.paged_attention import PagedState

    pp = ctx.pp
    b, s = hidden.shape[0], hidden.shape[1]
    # Rows microbatch only in the one-token-per-row regime (decode /
    # ragged / verify ticks): s == 1 and the row count splits evenly.
    # Chunked prefill ([1, chunk]) and odd row counts run M = 1 —
    # sequential stages, correct but bubbled.
    M = pp if (s == 1 and b >= pp and b % pp == 0) else 1
    mbs = b // M
    compressed = paged.table_index is not None

    hidden_mb = hidden.reshape(M, mbs, *hidden.shape[1:])
    pos_mb = position_ids.reshape(M, mbs, *position_ids.shape[1:])
    kv_pos_mb = paged.positions.reshape(M, mbs)
    if compressed:
        # block_tables is the COMPRESSED per-tick table set [T, W] shared
        # by all rows — replicated; per-row index/horizon arrays split.
        meta_mb = (paged.block_tables,
                   paged.horizons.reshape(M, mbs),
                   paged.table_index.reshape(M, mbs))
    else:
        meta_mb = (paged.block_tables.reshape(M, mbs, -1),)

    layer_spec = jax.tree.map(lambda _: P(PP_AXIS), stacked_layers)
    pool_spec = jax.tree.map(lambda _: P(PP_AXIS), kv_caches)
    repl = jax.tree.map(lambda _: P(), (hidden_mb, pos_mb, kv_pos_mb,
                                        meta_mb, rope))

    def body(layers_local, pools_local, x_mb, p_mb, kvp_mb, meta, rp):
        stage = compat.axis_index(PP_AXIS)
        n_local = jax.tree_util.tree_leaves(layers_local)[0].shape[0]
        perm = [(i, (i + 1) % pp) for i in range(pp)]

        def tick(carry, t):
            recv, out_buf, pools = carry
            u = t - stage
            valid = (u >= 0) & (u < M)
            mb = jnp.clip(u, 0, M - 1)
            take = lambda a: jax.lax.dynamic_index_in_dim(
                a, mb, 0, keepdims=False)
            inp = jnp.where(stage == 0, take(x_mb), recv)
            if compressed:
                tbl, hor, idx = meta
                pg = PagedState(tbl, take(kvp_mb),
                                horizons=take(hor), table_index=take(idx))
            else:
                pg = PagedState(take(meta[0]), take(kvp_mb))
            pg = _null_route(pg, valid)

            # Fill/drain ticks (u outside [0, M)) skip the stage forward
            # entirely: on a serialized backend the bubble would otherwise
            # burn real GEMM time on discarded output, and on TPU the
            # stage sits idle either way.  The null-routing above stays as
            # defense in depth should the conditional ever be lowered to
            # a select (both branches evaluated): writes still land on
            # the reserved NULL page, never on live state.
            def _run(op):
                inp_, pg_, pools_ = op
                out_, pools_, _ = transformer_forward(
                    cfg, layers_local, inp_,
                    rope=rp, position_ids=take(p_mb),
                    kv_caches=pools_, paged=pg_,
                    layer_offset=stage * n_local,
                )
                return out_, pools_

            def _skip(op):
                inp_, _, pools_ = op
                return jnp.zeros_like(inp_), pools_

            out, pools = jax.lax.cond(valid, _run, _skip,
                                      (inp, pg, pools))
            emit = valid & (stage == pp - 1)
            prev = jax.lax.dynamic_index_in_dim(out_buf, mb, 0,
                                                keepdims=False)
            out_buf = jax.lax.dynamic_update_index_in_dim(
                out_buf, jnp.where(emit, out, prev), mb, 0)
            with jax.named_scope(STAGE_PERMUTE_SCOPE):
                nxt = jax.lax.ppermute(out, PP_AXIS, perm)
            return (nxt, out_buf, pools), None

        zeros = jnp.zeros(x_mb.shape[1:], x_mb.dtype)
        out_buf = jnp.zeros_like(x_mb)
        (_, out_buf, pools_local), _ = jax.lax.scan(
            tick, (zeros, out_buf, pools_local),
            jnp.arange(M + pp - 1))
        # Only the last stage wrote out_buf (zeros elsewhere): psum over
        # pp broadcasts the emitted activations to every stage.
        return jax.lax.psum(out_buf, PP_AXIS), pools_local

    out_mb, new_caches = compat.shard_map(
        body, mesh=ctx.mesh,
        in_specs=(layer_spec, pool_spec) + repl,
        out_specs=(P(), pool_spec),
        axis_names={PP_AXIS}, check_vma=False,
    )(stacked_layers, kv_caches, hidden_mb, pos_mb, kv_pos_mb,
      meta_mb, rope)
    return out_mb.reshape(hidden.shape), new_caches
