"""Fine-grained compute/collective overlap: chunked collective matmuls.

Motivation (ROADMAP item 3; PAPERS.md "T3: Transparent Tracking &
Triggering for Fine-grained Overlap of Compute & Collectives"; the TPU
collective-matmul construction from "Overlap Communication with Dependent
Computation via Decomposition in Large Deep Learning Models"): the TP
collectives PR 6 introduced are emitted implicitly by XLA from sharding
constraints, as ONE all-reduce after each row-parallel contraction — the
interconnect sits idle while the GEMM runs, then the MXU sits idle while
the all-reduce runs.  This module makes the decomposition explicit so the
two pipelines overlap:

* **row-parallel** (attention ``dense``, ``fc2``; the contraction dim is
  tp-sharded) becomes a *reduce-scatter matmul ring*: the GEMM splits
  into ``tp`` output chunks inside a full-manual ``compat.shard_map``
  region; at every ring step the accumulator travels one hop
  (``ppermute``) WHILE the next chunk's partial product is computed —
  the two are data-independent, so XLA's latency-hiding scheduler runs
  the collective-permute DMA concurrently with the MXU work.  Under
  sequence parallelism the result stays seq-sharded (the reduce-scatter
  the reference hand-codes, layers.py:292); otherwise a tiled
  ``all_gather`` restores the replicated activation (together: the
  all-reduce, now pipelined against its own GEMM).
* **column-parallel + SP** (``qkv``, ``fc1`` on a seq-sharded residual
  stream) gets the mirrored *all-gather matmul ring*: each rank GEMMs
  the seq chunk it holds while ``ppermute`` brings in the next one.
  Without SP a column-parallel forward needs no communication, so there
  is nothing to overlap and the plain path is kept.

Ring schedule (row): rank ``q`` at step ``t`` computes the partial
product for output chunk ``c(q, t) = (q + tp - 1 - t) mod tp`` and adds
it to the accumulator in flight; accumulators move ``q -> q+1`` each
step, so after ``tp - 1`` hops rank ``r`` holds ``sum_q partial_q[chunk
r]`` — its own contribution added last, locally, in full precision.

Activation is a *trace-time* context (:func:`activate`): the train step
and the engine wrap their forward bodies, and the transformer sublayers
route row/column projections through :func:`row_parallel` /
:func:`column_parallel`, which fall back to the plain projection
whenever the context is inactive or the operand is ineligible
(quantized int8 / fp8 kernels, indivisible shapes).  ``--tp_overlap
off`` (the default) never enters the context at all — the forward is
byte-for-byte today's XLA-inserted-collective program.

Wire quantization (``--quantized_tp_collectives``, closing the PR 13
named follow-on): the row ring's in-flight accumulator chunks are int8
on the wire — symmetric absmax, one f32 scale per wire chunk, f32
scale applied on receipt, local partials accumulated in the compute
dtype (the EQuARX recipe of parallel/quantized.py applied to the
FORWARD collective).  Unlike the dp sync, a ring re-quantizes the
accumulator at every hop: a contribution entering at step ``t``
crosses ``tp - 1 - t`` hops and suffers one rounding ``<= scale/2``
per hop, so the worst-case element error is ``(tp - 1) * max_hop_scale
/ 2`` — bounded, and gated by tests/test_tp_overlap.py against the f32
ring.  The backward is a straight-through custom_vjp (gradients cross
the wire exactly, quantization is forward-only noise).

Why parity is a tolerance, not bitwise (unlike PR 11's ragged tick):
chunked-GEMM reduce-scatter REASSOCIATES the floating-point sum — the
plain path sums ``tp`` full partial products in one all-reduce; the
ring adds them one hop at a time interleaved with chunk GEMMs, and the
non-SP path additionally splits each GEMM row block at chunk
boundaries.  Same math, different association order, last-bits
different — so the contract is training loss rel <= 1e-4, engine
greedy tokens identical, per-token log-probs <= 5e-6 (bench_tp.py
overlap arm + tests/test_tp_overlap.py), while ``--tp_overlap off``
stays pinned bitwise.

jax 0.4.37 note: the region is FULL-manual (``axis_names`` = every mesh
axis) because partial-manual + ``ppermute`` hard-crashes the GSPMD
partitioner (spmd_partitioner.cc:512 — the compat.py story).  That is
also why overlap is gated to pp == cp == 1 meshes: pipeline/ring-
attention code owns its own manual regions and the two must not nest.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from megatron_llm_tpu.core.parallel_state import (
    CP_AXIS,
    DATA_AXES,
    DP_AXIS,
    EP_AXIS,
    PP_AXIS,
    TP_AXIS,
)
from megatron_llm_tpu.parallel import compat

__all__ = [
    "OVERLAP_MODES",
    "OverlapParams",
    "overlap_mode",
    "overlap_params",
    "activate",
    "current",
    "row_parallel",
    "column_parallel",
    "vocab_parallel",
    "overlap_scope_name",
    "vocab_scope_name",
]

OVERLAP_MODES = ("off", "ring")

_EPS = 1e-20


class OverlapParams:
    """Resolved overlap decision for one (cfg, mesh) pair — everything the
    ring builders need, captured once so traced closures never re-read
    config state."""

    __slots__ = ("mesh", "tp", "data", "sequence_parallel", "quantized",
                 "ring_rows", "vocab_ring")

    def __init__(self, mesh: Mesh, tp: int, data: int,
                 sequence_parallel: bool, quantized: bool,
                 ring_rows: bool = True, vocab_ring: bool = False):
        self.mesh = mesh
        self.tp = tp
        self.data = data  # dp * ep (batch-dim divisor inside the region)
        self.sequence_parallel = sequence_parallel
        self.quantized = quantized
        # which rings this context enables (ISSUE 20): row/column layer
        # rings need a pp==cp==1 mesh (they nest no other manual region);
        # the vocab head ring runs OUTSIDE the pp region and so composes
        # with pipeline-parallel serving.
        self.ring_rows = ring_rows
        self.vocab_ring = vocab_ring

    def __repr__(self):
        return (f"OverlapParams(tp={self.tp}, sp={self.sequence_parallel}, "
                f"quantized={self.quantized}, ring_rows={self.ring_rows}, "
                f"vocab_ring={self.vocab_ring})")


def overlap_mode(cfg) -> str:
    """The configured ``--tp_overlap`` mode ('off' when absent)."""
    mode = getattr(cfg.parallel, "tp_overlap", "off") or "off"
    assert mode in OVERLAP_MODES, f"unknown --tp_overlap mode {mode!r}"
    return mode


def overlap_scope_name(tp: int) -> str:
    """The named scope stamped on ring HLO (and the tracer span name the
    engine emits per overlapped tick): ``forward-tp{N}-overlap``."""
    return f"forward-tp{tp}-overlap"


def vocab_scope_name(tp: int) -> str:
    """Named scope stamped on the vocab head ring's HLO:
    ``vocab-ring-tp{N}`` — the ppermute chain the bench and tests assert
    lives under this scope (mechanism checked, not assumed)."""
    return f"vocab-ring-tp{tp}"


def overlap_params(cfg, mesh: Optional[Mesh]) -> Optional["OverlapParams"]:
    """Resolve (cfg, mesh) to ring parameters, or None when overlap does
    not apply: no mesh, tp == 1 (single-chip degradation — the flags are
    silently inert), an fp8 forward (its GEMMs carry their own scaling
    protocol), or nothing enabled.  The row/column layer rings
    (``--tp_overlap ring``) additionally require a pp == cp == 1 layout
    (pipeline/ring-attention own manual regions the full-manual ring must
    not nest inside); the vocab head ring (``--vocab_ring``, ISSUE 20)
    runs outside the pp region so pp > 1 is allowed — only cp (which
    wraps the whole forward) excludes it."""
    if mesh is None:
        return None
    shape = dict(mesh.shape)
    tp = shape.get(TP_AXIS, 1)
    if tp <= 1:
        return None
    if getattr(cfg.model, "fp8", None) is not None:
        return None
    flat = shape.get(PP_AXIS, 1) == 1 and shape.get(CP_AXIS, 1) == 1
    ring_rows = overlap_mode(cfg) == "ring" and flat
    vocab_ring = (bool(getattr(cfg.parallel, "vocab_ring", False))
                  and shape.get(CP_AXIS, 1) == 1)
    if not (ring_rows or vocab_ring):
        return None
    data = shape.get(DP_AXIS, 1) * shape.get(EP_AXIS, 1)
    return OverlapParams(
        mesh, tp, data,
        bool(getattr(cfg.parallel, "sequence_parallel", False)),
        bool(getattr(cfg.parallel, "quantized_tp_collectives", False)),
        ring_rows=ring_rows, vocab_ring=vocab_ring,
    )


# ---------------------------------------------------------------------------
# Trace-time activation context
# ---------------------------------------------------------------------------


class _State(threading.local):
    def __init__(self):
        self.stack = []


_state = _State()


@contextlib.contextmanager
def activate(ovl: Optional[OverlapParams]):
    """Enable ring interception for code traced inside this block.

    Pure trace-time state (like ``jax.named_scope``): entering with None
    is a no-op, so callers write ``with overlap.activate(maybe_none):``
    unconditionally and the off mode costs nothing."""
    if ovl is None:
        yield
        return
    _state.stack.append(ovl)
    try:
        yield
    finally:
        _state.stack.pop()


def current() -> Optional[OverlapParams]:
    return _state.stack[-1] if _state.stack else None


# ---------------------------------------------------------------------------
# Eligibility
# ---------------------------------------------------------------------------


def _eligible_common(ovl: OverlapParams, p, x) -> bool:
    # a vocab_ring-only context does not intercept the layer projections
    if not ovl.ring_rows:
        return False
    # int8 weight-only trees carry kernel_q/kernel_scale (ops/quant.py) —
    # their dequant-inside-GEMM contract stays on the plain path
    if "kernel" not in p or getattr(x, "ndim", 0) != 3:
        return False
    if x.shape[0] % ovl.data:
        return False
    # a nested manual region (pipeline/ring-attention/qdp) must not wrap
    # another shard_map — the gate in overlap_params covers the config
    # cases, this covers direct callers inside foreign regions
    if not compat.get_abstract_mesh().empty:
        return False
    return True


def _row_eligible(ovl: OverlapParams, p, x) -> bool:
    if not _eligible_common(ovl, p, x):
        return False
    k = p["kernel"]
    if k.ndim != 2 or x.shape[-1] != k.shape[0] or k.shape[0] % ovl.tp:
        return False
    if ovl.sequence_parallel and x.shape[1] % ovl.tp:
        return False
    return True


def _col_eligible(ovl: OverlapParams, p, x) -> bool:
    if not _eligible_common(ovl, p, x):
        return False
    k = p["kernel"]
    if k.ndim not in (2, 3) or x.shape[-1] != k.shape[0]:
        return False
    if k.shape[-1] % ovl.tp or x.shape[1] % ovl.tp:
        return False
    return True


# ---------------------------------------------------------------------------
# The rings
# ---------------------------------------------------------------------------


def _ring_perm(tp: int):
    return tuple((i, (i + 1) % tp) for i in range(tp))


def _inv_perm(perm):
    return tuple((j, i) for i, j in perm)


def _quantized_wire_hop(perm):
    """int8 wire hop with straight-through gradients.

    Forward: quantize the accumulator chunk (symmetric absmax, one f32
    scale per wire chunk), ppermute the int8 payload + its scale,
    dequantize on receipt.  Backward: the exact inverse ppermute — the
    rounding is treated as forward-only noise (``jnp.round`` has a zero
    gradient, which would silently kill training; the straight-through
    rule keeps the wire differentiable and exact in the backward)."""

    def fwd_value(acc):
        a32 = acc.astype(jnp.float32)
        scale = jnp.maximum(jnp.max(jnp.abs(a32)) / 127.0, _EPS)
        q = jnp.clip(jnp.round(a32 / scale), -127.0, 127.0).astype(jnp.int8)
        q = jax.lax.ppermute(q, TP_AXIS, perm)
        scale = jax.lax.ppermute(scale, TP_AXIS, perm)
        return (q.astype(jnp.float32) * scale).astype(acc.dtype)

    @jax.custom_vjp
    def hop(acc):
        return fwd_value(acc)

    def hop_fwd(acc):
        return fwd_value(acc), None

    def hop_bwd(_, g):
        return (jax.lax.ppermute(g, TP_AXIS, _inv_perm(perm)),)

    hop.defvjp(hop_fwd, hop_bwd)
    return hop


def _wire_hop(ovl: OverlapParams):
    perm = _ring_perm(ovl.tp)
    if ovl.quantized:
        return _quantized_wire_hop(perm)
    return lambda acc: jax.lax.ppermute(acc, TP_AXIS, perm)


def _mod(c, tp: int):
    # jnp.mod follows the divisor's sign: non-negative for positive tp,
    # so (r - t) mod tp is a valid chunk index even when r < t
    return jnp.mod(c, tp)


def row_parallel(cfg, p, x, fallback: Callable[[Any, Any], Any]):
    """Row-parallel projection ([.., k] @ [k, n], k tp-sharded): the
    reduce-scatter matmul ring when overlap is active, else
    ``fallback(p, x)`` (the plain projection, byte for byte)."""
    ovl = current()
    if ovl is None or not _row_eligible(ovl, p, x):
        return fallback(p, x)
    mesh, tp = ovl.mesh, ovl.tp
    b, s, _ = x.shape
    kernel = p["kernel"]
    hop = _wire_hop(ovl)
    sp = ovl.sequence_parallel

    def body_sp(xl, wl):
        # xl [b/data, s, k/tp] -> acc [b/data, s/tp, n]: rank r finishes
        # holding seq chunk r fully reduced — the reduce-scatter result
        # the SP residual stream wants, no gather needed.
        wl = wl.astype(xl.dtype)
        r = compat.axis_index(TP_AXIS)
        s_c = s // tp

        def chunk(c):
            return jax.lax.dynamic_slice_in_dim(xl, c * s_c, s_c, axis=1)

        acc = chunk(_mod(r + (tp - 1), tp)) @ wl
        for t in range(1, tp):
            acc = hop(acc) + chunk(_mod(r + (tp - 1 - t), tp)) @ wl
        return acc

    def body(xl, wl):
        # no SP: chunk the flattened [b_local * s] row block (pads to a
        # tp multiple so decode's s == 1 rows still chunk), ring-reduce,
        # then a tiled all_gather restores the replicated activation —
        # together, the all-reduce, pipelined against its own GEMM.
        wl = wl.astype(xl.dtype)
        r = compat.axis_index(TP_AXIS)
        bl = xl.shape[0]
        rows = bl * s
        xf = xl.reshape(rows, xl.shape[-1])
        rows_c = -(-rows // tp)
        pad = rows_c * tp - rows
        if pad:
            xf = jnp.concatenate(
                [xf, jnp.zeros((pad, xf.shape[-1]), xf.dtype)])

        def chunk(c):
            return jax.lax.dynamic_slice_in_dim(xf, c * rows_c, rows_c,
                                                axis=0)

        acc = chunk(_mod(r + (tp - 1), tp)) @ wl
        for t in range(1, tp):
            acc = hop(acc) + chunk(_mod(r + (tp - 1 - t), tp)) @ wl
        y = jax.lax.all_gather(acc, TP_AXIS, axis=0, tiled=True)
        if pad:
            y = y[:rows]
        return y.reshape(bl, s, -1)

    out_spec = (P(DATA_AXES, TP_AXIS, None) if sp
                else P(DATA_AXES, None, None))
    with jax.named_scope(overlap_scope_name(tp)):
        y = compat.shard_map(
            body_sp if sp else body, mesh=mesh,
            in_specs=(P(DATA_AXES, None, TP_AXIS), P(TP_AXIS, None)),
            out_specs=out_spec,
            axis_names=set(mesh.axis_names), check_vma=False,
        )(x, kernel)
    if "bias" in p:
        # row-parallel bias is replicated and added post-reduce
        # (mappings.py:257 semantics — matches tp.py's spec rule)
        y = y + p["bias"].astype(y.dtype)
    return y


def column_parallel(cfg, p, x, fallback: Callable[[Any, Any], Any]):
    """Column-parallel projection ([.., h] @ [h, n], n tp-sharded) on a
    seq-sharded (SP) residual stream: the all-gather matmul ring.  Without
    SP a column-parallel forward has no collective to overlap, so the
    plain path is always kept."""
    ovl = current()
    if (ovl is None or not ovl.sequence_parallel
            or not _col_eligible(ovl, p, x)):
        return fallback(p, x)
    mesh, tp = ovl.mesh, ovl.tp
    b, s, _ = x.shape
    kernel = p["kernel"]
    perm = _ring_perm(tp)
    glu = kernel.ndim == 3  # GLU fc1 [h, 2, ffn]: tp shards the ffn axis

    def body(xl, wl):
        # xl [b/data, s/tp, h] (this rank's seq chunk), wl [h, n/tp].
        # GEMM the chunk in hand while ppermute brings in the next; each
        # arriving chunk lands at its own seq offset.
        wl2 = wl.reshape(wl.shape[0], -1).astype(xl.dtype)
        r = compat.axis_index(TP_AXIS)
        bl, s_c, _ = xl.shape
        y = jnp.zeros((bl, s_c * tp, wl2.shape[-1]), xl.dtype)
        buf = xl
        y = jax.lax.dynamic_update_slice_in_dim(y, buf @ wl2, r * s_c,
                                                axis=1)
        for t in range(1, tp):
            buf = jax.lax.ppermute(buf, TP_AXIS, perm)
            c = _mod(r - t, tp)
            y = jax.lax.dynamic_update_slice_in_dim(y, buf @ wl2,
                                                    c * s_c, axis=1)
        if glu:
            return y.reshape(bl, s_c * tp, *wl.shape[1:])
        return y

    out_spec = (P(DATA_AXES, None, None, TP_AXIS) if glu
                else P(DATA_AXES, None, TP_AXIS))
    w_spec = P(None, None, TP_AXIS) if glu else P(None, TP_AXIS)
    with jax.named_scope(overlap_scope_name(tp)):
        y = compat.shard_map(
            body, mesh=mesh,
            in_specs=(P(DATA_AXES, TP_AXIS, None), w_spec),
            out_specs=out_spec,
            axis_names=set(mesh.axis_names), check_vma=False,
        )(x, kernel)
    if "bias" in p:
        y = y + p["bias"].astype(y.dtype)
    return y


# ---------------------------------------------------------------------------
# Vocab-parallel head ring (ISSUE 20)
# ---------------------------------------------------------------------------


def _vocab_eligible(ovl: OverlapParams, w, x) -> bool:
    if not ovl.vocab_ring:
        return False
    if getattr(x, "ndim", 0) != 3 or getattr(w, "ndim", 0) != 2:
        return False
    if x.shape[-1] != w.shape[0]:
        return False
    # each rank's vocab shard splits into tp sub-chunks: V % tp**2 == 0
    # (padded_vocab_size pads to a multiple of 128 * tp, so this holds
    # for every practical tp; tiny toy vocabs fall back)
    if w.shape[1] % (ovl.tp * ovl.tp):
        return False
    # never nest inside another manual region (the pp stage region in
    # particular: the head runs AFTER pipelined_transformer returns)
    if not compat.get_abstract_mesh().empty:
        return False
    return True


def vocab_parallel(cfg, w, x, fallback: Callable[[Any, Any], Any]):
    """Vocab-parallel head projection ([R, s, h] @ [h, V], V tp-sharded):
    the all-gather matmul ring when ``--vocab_ring`` is active, else
    ``fallback(w, x)`` (the plain GEMM + XLA-inserted all-gather).

    At serving time the head GEMM is the single largest collective per
    tick — the logits all-gather moves ``R * V`` elements EVERY decode
    step.  The ring decomposes each rank's ``[h, V/tp]`` shard into
    ``tp`` column sub-chunks: at step ``t`` the rank GEMMs sub-chunk
    ``t`` while the previously computed sub-chunks travel one hop
    (``ppermute``) — compute and wire are data-independent, so the
    latency-hiding scheduler overlaps them.  After ``2*tp - 2`` hops
    every rank holds all ``tp**2`` (owner, sub) blocks and assembles the
    replicated ``[R, s, V]`` logits.

    Unlike the row ring this does NOT reassociate any floating-point
    sum — the split is along output columns, the contraction dim stays
    intact, and the wire is never quantized — but XLA may still tile the
    sub-GEMMs differently from the fused one, so the contract is the
    tolerance one (greedy tokens identical, log-probs <= 5e-6), not
    bitwise.
    """
    ovl = current()
    if ovl is None or not _vocab_eligible(ovl, w, x):
        return fallback(w, x)
    mesh, tp = ovl.mesh, ovl.tp
    R, s, h = x.shape
    V = w.shape[1]
    u = V // (tp * tp)  # sub-chunk width (vc = V/tp per rank, tp subs)
    perm = _ring_perm(tp)

    def body(xl, wl):
        # xl [R, s, h] replicated, wl [h, V/tp] this rank's column shard.
        wl = wl.astype(xl.dtype)
        r = compat.axis_index(TP_AXIS)
        rows = R * s
        xf = xl.reshape(rows, h)
        # y4[o, j] = owner o's sub-chunk j — assembled as blocks arrive.
        y4 = jnp.zeros((tp, tp, rows, u), xl.dtype)
        live = {}  # sub index -> in-flight block (computed at step j)
        for t in range(2 * tp - 1):
            # 1) hop everything in flight: ONE ppermute on the stacked
            #    payload (sub j has hopped t - j times after this)
            if live:
                js = sorted(live)
                payload = jnp.stack([live[j] for j in js])
                payload = jax.lax.ppermute(payload, TP_AXIS, perm)
                for i, j in enumerate(js):
                    live[j] = payload[i]
            # 2) GEMM sub-chunk t locally — data-independent of the hop
            #    above, so the DMA hides behind this MXU work
            if t < tp:
                live[t] = xf @ jax.lax.dynamic_slice_in_dim(
                    wl, t * u, u, axis=1)
            # 3) place every in-flight block: after ``t - j`` hops rank r
            #    holds owner ``(r - (t - j)) mod tp``'s sub j
            for j in list(live):
                hops = t - j
                o = _mod(r - hops, tp)
                y4 = jax.lax.dynamic_update_slice(
                    y4, live[j][None, None], (o, jnp.int32(j), 0, 0))
                if hops == tp - 1:  # visited every rank — done
                    del live[j]
        # owner-major (o, j, u) block order == global column order
        return y4.transpose(2, 0, 1, 3).reshape(R, s, V)

    with jax.named_scope(vocab_scope_name(tp)):
        return compat.shard_map(
            body, mesh=mesh,
            in_specs=(P(), P(None, TP_AXIS)),
            out_specs=P(),
            axis_names=set(mesh.axis_names), check_vma=False,
        )(x, w)
