"""Full language model: embedding + transformer + output head + loss.

Replaces megatron/model/language_model.py (Embedding:133,
TransformerLanguageModel:329, parallel_lm_logits:24) and
megatron/model/gpt_model.py (post_language_model_processing:18).

Under pjit the vocab dimension of the embedding table / LM head carries a
``tp`` sharding (vocab-parallel, VocabParallelEmbedding semantics) and XLA
inserts the all-reduces the reference issues by hand (layers.py:187-210).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from megatron_llm_tpu.core import rng as rng_mod
from megatron_llm_tpu.models.transformer import (
    init_stacked_layers,
    transformer_forward,
)
from megatron_llm_tpu.ops.cross_entropy import (
    chunked_softmax_cross_entropy_from_hidden,
    softmax_cross_entropy,
)
from megatron_llm_tpu.ops.norms import init_norm_params, norm
from megatron_llm_tpu.ops.rope import precompute_freqs
from megatron_llm_tpu.parallel import overlap as tp_overlap_mod
from megatron_llm_tpu.parallel import pp_serve as pp_serve_mod

Params = Dict[str, Any]


def pad_vocab(vocab_size: int, divisible_by: int, tp: int) -> int:
    """Pad vocab to a multiple of ``divisible_by * tp``
    (reference tokenizer.py:_vocab_size_with_padding:49-62)."""
    multiple = divisible_by * tp
    return multiple * ((vocab_size + multiple - 1) // multiple)


def padded_vocab_size(vocab_size: int, cfg) -> int:
    return pad_vocab(
        vocab_size,
        cfg.model.make_vocab_size_divisible_by,
        cfg.parallel.tensor_model_parallel_size,
    )


def init_model_params(cfg, key: jax.Array) -> Params:
    m = cfg.model
    assert m.vocab_size is not None, "cfg.model.vocab_size must be set"
    v = padded_vocab_size(m.vocab_size, cfg)
    h = m.hidden_size
    k_emb, k_layers, k_head, k_pos = jax.random.split(key, 4)
    params: Params = {
        "embedding": {
            "word_embeddings": m.init_method_std
            * jax.random.normal(k_emb, (v, h), jnp.float32)
        },
        "layers": init_stacked_layers(cfg, k_layers),
        "final_norm": init_norm_params(h, m.use_rms_norm),
    }
    if m.position_embedding_type == "absolute":
        params["embedding"]["position_embeddings"] = m.init_method_std * (
            jax.random.normal(k_pos, (m.max_position_embeddings, h), jnp.float32)
        )
    if m.num_tokentypes > 0:
        # BERT segment embeddings (reference Embedding tokentype path,
        # language_model.py:173-183)
        k_tt = jax.random.fold_in(k_pos, 1)
        params["embedding"]["tokentype_embeddings"] = m.init_method_std * (
            jax.random.normal(k_tt, (m.num_tokentypes, h), jnp.float32)
        )
    if not m.tie_embed_logits:
        # untied lm_head (language_model.py:436-457)
        params["lm_head"] = {
            "kernel": m.init_method_std
            * jax.random.normal(k_head, (h, v), jnp.float32)
        }
    return params


def make_rope_cache(cfg) -> Optional[Tuple[jax.Array, jax.Array]]:
    m = cfg.model
    if m.position_embedding_type != "rotary":
        return None
    return precompute_freqs(
        m.kv_channels,
        m.max_position_embeddings,
        theta=m.rope_theta,
        scaling_factor=m.rope_scaling_factor,
        scaling_type=m.rope_scaling_type,
        llama3_params=dict(
            low_freq_factor=m.rope_llama3_low_freq_factor,
            high_freq_factor=m.rope_llama3_high_freq_factor,
            original_max_position=m.rope_llama3_original_max_position,
        ),
    )


@functools.lru_cache(maxsize=None)
def _take_rows_matmul_bwd(rows: int, chunk: int, table_dtype: str):
    """``take(table, ids, axis=0)`` whose BACKWARD is a one-hot matmul
    (``dtable = one_hot(ids).T @ g``, token-chunked) instead of the take
    transpose's scatter-add.

    Two TPU reasons: (1) scatter is the one op class the MXU cannot touch;
    (2) XLA's scatter *partitioner* CHECK-crashes
    (spmd_partitioner_util.cc:506, ExpandDeviceGroupsWithIota) when this
    scatter-add sits inside the 1F1B tick loop under the pipeline's
    partial-manual shard_map with a nested-manual flash region and
    dp-sharded ZeRO-1 state — the round-4 "pp x dp>1 x tp>1 flash
    fallback" root cause (tools/flash_nested_repro.py). The forward is the
    unchanged gather; only the vjp differs (same additive semantics,
    accumulated in the cotangent dtype like the scatter it replaces).
    """
    import numpy as np

    @jax.custom_vjp
    def take(table, ids):
        return jnp.take(table, ids, axis=0)

    def fwd(table, ids):
        return jnp.take(table, ids, axis=0), ids

    def bwd(res, g):
        ids, tdt = res, jnp.dtype(table_dtype)
        h = g.shape[-1]
        n = int(np.prod(ids.shape))
        gf = g.reshape(n, h)
        idf = ids.reshape(n)
        # largest divisor of n that fits the chunk budget — requiring exact
        # divisibility by 4096 would silently fall back to one unbounded
        # [n, rows] one-hot for e.g. n=6144 (the transient this bounds)
        c = next((d for d in range(min(chunk, n), 0, -1) if n % d == 0), n)
        if c < n:
            # bound the [n, rows] one-hot transient (1 GiB at n=4096,
            # vocab 128k, bf16) by accumulating over token chunks
            def body(acc, xs):
                i_c, g_c = xs
                oh = jax.nn.one_hot(i_c, rows, dtype=g_c.dtype)
                return acc + jnp.matmul(
                    oh.T, g_c, preferred_element_type=acc.dtype), None

            acc0 = jnp.zeros((rows, h), g.dtype)
            dtable, _ = jax.lax.scan(
                body, acc0,
                (idf.reshape(n // c, c), gf.reshape(n // c, c, h)))
        else:
            oh = jax.nn.one_hot(idf, rows, dtype=gf.dtype)
            dtable = jnp.matmul(oh.T, gf, preferred_element_type=gf.dtype)
        return dtable.astype(tdt), np.zeros(ids.shape, jax.dtypes.float0)

    take.defvjp(fwd, bwd)
    return take


# Bound on the [chunk, rows] one-hot transient in the matmul backward of
# the 1F1B embedding path (sized for the fp32 worst case regardless of
# table dtype — the transient is built in the COTANGENT dtype, which a
# generic caller may keep wider than the table): chunk 512 at vocab 32k,
# 128 at 128k. 64 MiB keeps the per-tick bwd transient small next to the
# full-logits footprint the pipelined CE is certified against
# (tests/test_pipeline.py::test_gpipe_ce_memory_bounded) while still
# giving the MXU large tiles.
_EMBED_BWD_ONE_HOT_CAP_BYTES = 64 * 2 ** 20


def _embed_take(cfg, table: jax.Array, ids: jax.Array) -> jax.Array:
    """Embedding-table row lookup.

    Under the 1F1B schedules the gradient is the matmul form
    (:func:`_take_rows_matmul_bwd`): their per-tick vjp puts the take
    transpose's scatter-add inside the pp shard_map's tick loop, where
    XLA's scatter partitioner CHECK-crashes (the round-4 pp x dp>1 x tp>1
    blocker). GPipe keeps the plain take/scatter — its whole-batch
    embedding sits outside the tick loop, partitions fine (verified by
    the round-5 bisection), and the scatter is cheaper in memory than
    even a chunked one-hot."""
    if (cfg.parallel.pipeline_model_parallel_size > 1
            and cfg.parallel.pipeline_schedule != "gpipe"):
        rows = table.shape[0]
        c = max(128, _EMBED_BWD_ONE_HOT_CAP_BYTES // (rows * 4))
        c = 1 << (int(c).bit_length() - 1)  # power of two: stable divisors
        return _take_rows_matmul_bwd(rows, c, str(table.dtype))(table, ids)
    return jnp.take(table, ids, axis=0)


def embed_tokens(
    cfg, params: Params, tokens: jax.Array,
    position_ids: Optional[jax.Array] = None,
    tokentype_ids: Optional[jax.Array] = None,
) -> jax.Array:
    emb = params["embedding"]["word_embeddings"]
    hidden = _embed_take(cfg, emb, tokens)
    if cfg.model.position_embedding_type == "absolute":
        pos = position_ids if position_ids is not None else jnp.arange(tokens.shape[1])[None]
        hidden = hidden + _embed_take(
            cfg, params["embedding"]["position_embeddings"], pos)
    if tokentype_ids is not None:
        hidden = hidden + _embed_take(
            cfg, params["embedding"]["tokentype_embeddings"], tokentype_ids
        )
    return hidden.astype(_compute_dtype(cfg))


def head_weight(cfg, params: Params) -> jax.Array:
    """The LM-head kernel [h, v]: the transposed tied embedding table or the
    untied lm_head (language_model.py:24-53 tie handling) — single source of
    truth for every head consumer (compute_logits, chunked CE, pipeline)."""
    if cfg.model.tie_embed_logits:
        return params["embedding"]["word_embeddings"].T
    return params["lm_head"]["kernel"]


def compute_logits(cfg, params: Params, hidden: jax.Array) -> jax.Array:
    """parallel_lm_logits analog (language_model.py:24-53): tied or untied head.

    With ``--vocab_ring`` active (parallel/overlap.py:vocab_parallel) the
    head GEMM + logits all-gather run as an all-gather matmul ring;
    inactive/ineligible calls take the plain fallback byte for byte."""
    return tp_overlap_mod.vocab_parallel(
        cfg, head_weight(cfg, params), hidden,
        lambda w, x: x @ w.astype(x.dtype))


def _compute_dtype(cfg):
    return {
        "float32": jnp.float32,
        "bfloat16": jnp.bfloat16,
        "float16": jnp.float16,
    }[cfg.training.params_dtype]


def model_forward(
    cfg,
    params: Params,
    tokens: jax.Array,  # [b, s] int32
    *,
    position_ids: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
    token_idx: Optional[jax.Array] = None,
    labels: Optional[jax.Array] = None,
    loss_mask: Optional[jax.Array] = None,
    dropout_key: Optional[jax.Array] = None,
    deterministic: bool = True,
    rope_cache: Optional[Tuple[jax.Array, jax.Array]] = None,
    kv_caches=None,
    cache_index=None,
    paged=None,
    sp_constraint=None,
    logits_postprocess=True,
    return_aux=False,
):
    """GPTModel.forward analog (gpt_model.py:45-124).

    ``paged`` (ops/paged_attention.PagedState): ``kv_caches`` is the stacked
    [L, num_pages, page_size, nkv, d] page pool instead of a dense cache, and
    every batch row decodes one token at its own ``paged.positions`` entry
    (the serving engine's fused tick, generation/engine.py).

    With ``labels``: returns per-token fp32 loss [b, s] (masked mean is the
    caller's job, matching the reference loss_func split). Without: logits.
    Returns (output, new_kv_caches), or (output, new_kv_caches, moe_aux[2])
    when ``return_aux`` (MoE router losses, models/moe.py).
    """
    hidden = embed_tokens(cfg, params, tokens, position_ids)
    if dropout_key is not None and not deterministic:
        k_embed, dropout_key = jax.random.split(dropout_key)
        hidden = rng_mod.dropout(k_embed, cfg.model.hidden_dropout, hidden)
    if sp_constraint is not None:
        hidden = sp_constraint(hidden)

    if rope_cache is None:
        rope_cache = make_rope_cache(cfg)

    ppc = pp_serve_mod.current()
    if ppc is not None and paged is not None and kv_caches is not None:
        # Pipeline-parallel serving tick (parallel/pp_serve.py, ISSUE 20):
        # the layer stack runs as pp stages over microbatched rows, each
        # stage reading/writing only its own layers' slice of the paged
        # pool.  MoE aux is not plumbed (deterministic inference).
        hidden, new_caches = pp_serve_mod.pipelined_transformer(
            cfg, ppc, params["layers"], hidden,
            rope=rope_cache, position_ids=position_ids,
            kv_caches=kv_caches, paged=paged,
        )
        moe_aux = jnp.zeros((2,), jnp.float32)
    else:
        hidden, new_caches, moe_aux = transformer_forward(
            cfg, params["layers"], hidden,
            rope=rope_cache, position_ids=position_ids, segment_ids=segment_ids,
            token_idx=token_idx,
            dropout_key=dropout_key, deterministic=deterministic,
            kv_caches=kv_caches, cache_index=cache_index, paged=paged,
            sp_constraint=sp_constraint,
        )

    hidden = norm(hidden, params["final_norm"], cfg.model.layernorm_epsilon,
                  cfg.model.use_rms_norm)

    def ret(out):
        return (out, new_caches, moe_aux) if return_aux else (out, new_caches)

    if not logits_postprocess:
        return ret(hidden)

    if labels is not None and cfg.model.ce_vocab_chunks:
        # head matmul fused into a vocab-chunked CE: the [b, s, vocab] fp32
        # logits are never materialized (large-vocab memory lever)
        loss = chunked_softmax_cross_entropy_from_hidden(
            hidden, head_weight(cfg, params).astype(hidden.dtype), labels,
            cfg.model.ce_vocab_chunks,
        )
        return ret(loss)

    logits = compute_logits(cfg, params, hidden)
    if labels is None:
        return ret(logits)

    loss = softmax_cross_entropy(logits, labels)  # fp32 per-token
    return ret(loss)


def loss_from_batch(cfg, params, batch: Dict[str, jax.Array], *,
                    dropout_key=None, deterministic=True, rope_cache=None,
                    sp_constraint=None) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Standard LM loss over a batch dict with keys
    tokens/labels/loss_mask[/position_ids/segment_ids].

    Mirrors the reference loss_func (finetune.py:139-190): masked mean of the
    per-token CE. MoE models add the weighted router losses (models/moe.py)
    to the trained total while still reporting "lm loss" as the bare CE.
    """
    moe = cfg.model.num_experts is not None
    out = model_forward(
        cfg, params, batch["tokens"],
        position_ids=batch.get("position_ids"),
        segment_ids=batch.get("segment_ids"),
        token_idx=batch.get("token_idx"),
        labels=batch["labels"],
        dropout_key=dropout_key,
        deterministic=deterministic,
        rope_cache=rope_cache,
        sp_constraint=sp_constraint,
        return_aux=moe,
    )
    per_token = out[0]
    mask = batch["loss_mask"].astype(jnp.float32)
    denom = jnp.maximum(mask.sum(), 1.0)
    loss = (per_token * mask).sum() / denom
    metrics = {"lm loss": loss}
    if moe:
        from megatron_llm_tpu.models.moe import aux_loss_coeffs

        balance, z = out[2][0], out[2][1]
        c_bal, c_z = aux_loss_coeffs(cfg)
        total = loss + c_bal * balance + c_z * z
        metrics["moe aux loss"] = balance
        if c_z:
            metrics["router z loss"] = z
        return total, metrics
    return loss, metrics
