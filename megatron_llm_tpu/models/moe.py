"""Mixture-of-Experts layer with expert parallelism — beyond-reference feature.

The reference (xingyaoww/Megatron-LLM) has **no MoE**: its parallel_state.py
carves only TP/PP/DP/embedding groups (SURVEY §2.1 "EP: absent"). This module
adds the capability TPU-first, in the GShard/Switch/Mixtral lineage:

* **Routing** is a dense top-k softmax gate computed in fp32 with a
  load-balancing auxiliary loss (Switch Transformer) and an optional router
  z-loss (ST-MoE) — both standard published formulations.
* **Dispatch/combine are einsums** against one-hot capacity tensors — no
  scatter/gather, no dynamic shapes, so everything lands on the MXU and the
  all-to-all between data- and expert-sharded layouts is *inferred by XLA*
  from sharding constraints (the same way our TP all-reduces replace NCCL
  calls, parallel/tp.py).
* **Expert parallelism is a mesh axis** (``ep``, carved out of dp — the
  ep | dp convention Megatron-LM upstream uses): expert weight stacks
  [E, ...] shard their expert axis over ``ep``, dispatched activations are
  sharding-constrained from batch-sharded [G:(dp,ep), T, h] to
  expert-sharded [G:dp, E:ep, C, h], and XLA emits the all-to-all over the
  ICI ring. TP composes: the per-expert FFN hidden axis shards over ``tp``
  exactly like the dense MLP (column- then row-parallel, parallel/tp.py).
* **Capacity-based token dropping**: each expert processes at most
  C = ceil(topk * T * capacity_factor / E) tokens per group; overflow tokens
  fall through to the residual stream (their combine weight is zero), which
  keeps every shape static for XLA.

Parameter schema (per layer; stacked on a leading layer axis under scan):

    {'router':  {'kernel': [h, E]}                        # fp32, replicated
     'experts': {'fc1': {'kernel': [E, h, 2, ffn] | [E, h, ffn], 'bias'?},
                 'fc2': {'kernel': [E, ffn, h], 'bias'?}}}
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from megatron_llm_tpu.ops.activations import GLU_BASE_ACTIVATIONS, get_mlp_activation

Params = Dict[str, Any]


def moe_capacity(cfg, tokens_per_group: int) -> int:
    """Expert capacity C for one routing group of T tokens (token-choice:
    ceil(topk * T * cf / E), GShard convention)."""
    m = cfg.model
    cap = int(-(-m.moe_router_topk * tokens_per_group * m.moe_capacity_factor
                // m.num_experts))  # ceil
    return max(cap, m.moe_min_capacity)


def moe_capacity_expert_choice(cfg, tokens_per_group: int) -> int:
    """Expert-choice capacity: ceil(T * cf / E) (Zhou et al. definition —
    no topk factor; that knob is token-choice-only), clamped to T because
    an expert cannot select more tokens than the group holds."""
    m = cfg.model
    cap = int(-(-tokens_per_group * m.moe_capacity_factor // m.num_experts))
    return min(max(cap, m.moe_min_capacity), tokens_per_group)


def init_moe_params(cfg, key: jax.Array) -> Params:
    m = cfg.model
    h, f, e = m.hidden_size, m.ffn_hidden_size, m.num_experts
    glu = m.glu_activation is not None
    std = m.init_method_std
    out_std = std / (2.0 * m.num_layers) ** 0.5 if m.use_scaled_init_method else std
    kr, k1, k2 = jax.random.split(key, 3)
    # per-expert independent init: one key per expert, same distribution as
    # the dense MLP (transformer.init_layer_params)
    fc1_shape = (e, h, 2, f) if glu else (e, h, f)
    p: Params = {
        "router": {"kernel": std * jax.random.normal(kr, (h, e), jnp.float32)},
        "experts": {
            "fc1": {"kernel": std * jax.random.normal(k1, fc1_shape, jnp.float32)},
            "fc2": {"kernel": out_std * jax.random.normal(k2, (e, f, h), jnp.float32)},
        },
    }
    if m.use_bias:
        p["experts"]["fc1"]["bias"] = jnp.zeros((e, 2, f) if glu else (e, f),
                                                jnp.float32)
        p["experts"]["fc2"]["bias"] = jnp.zeros((e, h), jnp.float32)
    return p


def _expert_kernel(p_lin: Params, dt) -> Tuple[jax.Array, Any]:
    """Expert weight + optional int8 per-channel scale (the shared
    quantized-leaf contract, ops/quant.py:resolve_kernel)."""
    from megatron_llm_tpu.ops.quant import resolve_kernel

    return resolve_kernel(p_lin, dt)


def _ep_constraint(x: jax.Array, expert_axis: int) -> jax.Array:
    """Constrain an [G, E, C, ...] dispatched tensor so G rides dp and E rides
    ep — the boundary where XLA inserts the data<->expert all-to-all."""
    from megatron_llm_tpu.core import parallel_state as ps
    from jax.sharding import PartitionSpec as P

    if not ps.mesh_is_initialized():
        return x
    mesh = ps.get_global_mesh()
    if ps.EP_AXIS not in mesh.shape:
        return x
    spec = [None] * x.ndim
    spec[0] = ps.DP_AXIS
    spec[expert_axis] = ps.EP_AXIS
    return jax.lax.with_sharding_constraint(x, P(*spec))


def _router_z_loss(router_logits: jax.Array) -> jax.Array:
    """ST-MoE router z-loss: mean(logsumexp(logits)^2) keeps logits small."""
    return jnp.mean(jax.nn.logsumexp(router_logits, axis=-1) ** 2)


def route_expert_choice(
    cfg, router_logits: jax.Array, capacity: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Expert-choice routing (Zhou et al. 2022): each expert selects its
    top-C tokens by router affinity — perfectly balanced by construction,
    so no load-balance aux loss is needed (only the optional z-loss).

    Note: within a routing group, experts compare tokens across positions,
    which leaks future-token information into the selection — fine for
    encoders/bidirectional models and for research runs; causal-LM training
    should prefer the default top-k token-choice routing.

    Returns (combine [G,T,E,C], dispatch bool, aux[2]) like route_tokens.
    """
    g_, t_, e_ = router_logits.shape
    probs = jax.nn.softmax(router_logits, axis=-1)  # token-over-experts affinity
    # experts pick tokens: top-C over the T axis of [G, E, T]
    vals, idx = jax.lax.top_k(probs.transpose(0, 2, 1), capacity)  # [G,E,C]
    sel = jax.nn.one_hot(idx, t_, dtype=jnp.float32)  # [G,E,C,T]
    combine = (sel * vals[..., None]).transpose(0, 3, 1, 2)  # [G,T,E,C]
    dispatch = combine > 0.0
    # The Switch balance loss is identically at its optimum under EC (every
    # expert serves exactly C tokens), so reporting it would be a constant.
    # The balance slot instead carries EC's real health signal: the
    # DROPPED-TOKEN fraction (tokens selected by no expert). 0.0 = full
    # coverage. Metric-only: aux_loss_coeffs zeroes the balance coefficient
    # for expert_choice, so this never enters the training loss.
    covered = dispatch.any(axis=(2, 3))  # [G, T]
    dropped = 1.0 - covered.mean().astype(jnp.float32)
    aux = jnp.stack([dropped, _router_z_loss(router_logits)])
    return combine, dispatch, aux


def route_tokens(
    cfg, router_logits: jax.Array, capacity: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Top-k routing with per-expert capacity.

    ``router_logits``: [G, T, E] fp32. Returns:
      combine  [G, T, E, C] fp32 — gate weight of token t in expert e slot c
      dispatch [G, T, E, C] bool — combine != 0
      aux      [2] fp32 — (load-balance loss, router z-loss), unweighted
    """
    m = cfg.model
    g_, t_, e_ = router_logits.shape
    k_ = m.moe_router_topk

    probs = jax.nn.softmax(router_logits, axis=-1)  # fp32
    gate, idx = jax.lax.top_k(probs, k_)  # [G, T, K]
    if m.moe_normalize_gates:
        # Mixtral convention: renormalize the selected gates to sum to 1
        gate = gate / jnp.maximum(gate.sum(-1, keepdims=True), 1e-9)

    mask = jax.nn.one_hot(idx, e_, dtype=jnp.float32)  # [G, T, K, E]

    # Position of each (token, slot) in its expert's buffer. Priority order is
    # (slot, token): all first choices are seated before any second choice —
    # the GShard convention, so capacity pressure drops k=2 traffic first.
    mk = mask.transpose(0, 2, 1, 3).reshape(g_, k_ * t_, e_)
    pos = (jnp.cumsum(mk, axis=1) - mk).reshape(g_, k_, t_, e_).transpose(0, 2, 1, 3)
    pos_tk = (pos * mask).sum(-1).astype(jnp.int32)  # [G,T,K] pos in expert
    fits = pos_tk < capacity

    # load-balance aux loss (Switch eq. 4, generalized to top-k): fraction of
    # tokens dispatched to e (all slots, /k so it sums to 1) x mean router
    # prob for e, scaled by E — equals 1.0 under perfectly uniform routing.
    frac_tokens = mask.sum(2).mean((0, 1)) / k_    # [E]
    frac_probs = probs.mean((0, 1))                # [E]
    balance = e_ * jnp.sum(frac_tokens * frac_probs)
    aux = jnp.stack([balance, _router_z_loss(router_logits)])

    gate_kept = gate * fits.astype(gate.dtype)                  # [G, T, K]
    slot = jax.nn.one_hot(pos_tk, capacity, dtype=jnp.float32)  # [G, T, K, C]
    combine = jnp.einsum("gtk,gtke,gtkc->gtec", gate_kept, mask, slot)
    dispatch = combine > 0.0
    return combine, dispatch, aux


def moe_sublayer(cfg, p: Params, x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """MoE FFN over [b, s, h]; tokens route in per-sequence-chunk groups of
    ``moe_group_size``. Returns (out, aux[2]).

    Replaces mlp_sublayer (transformer.py) on MoE layers; the dense path's
    GLU chunk-2 convention (glu_activations.py:14-16) is preserved per expert.
    """
    m = cfg.model
    b, s, h = x.shape
    # GShard grouping: route fixed-size chunks of the sequence independently
    # so dispatch/combine stay O(group * capacity), not O(seq^2) — at 32K seq
    # an ungrouped [s, E, C~s] one-hot would be gigabytes per sample.
    gsz = min(s, m.moe_group_size)
    assert s % gsz == 0, (
        f"seq_length {s} not a multiple of moe_group_size {gsz}"
    )
    x = x.reshape(b * (s // gsz), gsz, h)

    w_router = p["router"]["kernel"]  # fp32
    router_logits = x.astype(jnp.float32) @ w_router  # [G, T, E]
    if m.moe_router_type == "expert_choice":
        combine, dispatch, aux = route_expert_choice(
            cfg, router_logits, moe_capacity_expert_choice(cfg, gsz)
        )
    elif m.moe_router_type == "topk":
        combine, dispatch, aux = route_tokens(
            cfg, router_logits, moe_capacity(cfg, gsz)
        )
    else:  # loud failure for configs that bypassed finalize validation
        raise ValueError(f"unknown moe_router_type {m.moe_router_type!r}")

    dt = x.dtype
    xe = jnp.einsum("gtec,gth->gech", dispatch.astype(dt), x)  # [b, E, C, h]
    xe = _ep_constraint(xe, 1)

    experts = p["experts"]
    fc1, s1 = _expert_kernel(experts["fc1"], dt)
    glu = m.glu_activation is not None
    # [g,e,c,(2,)f]; the bias broadcast [1,e,1,(2,)f] covers both layouts
    y = jnp.einsum("gech,ehuf->gecuf" if glu else "gech,ehf->gecf", xe, fc1)
    if s1 is not None:  # int8 per-channel scale (same broadcast as bias)
        y = y * s1.astype(dt)[None, :, None]
    if "bias" in experts["fc1"]:
        y = y + experts["fc1"]["bias"].astype(dt)[None, :, None]
    if glu:
        act = GLU_BASE_ACTIVATIONS[m.glu_activation]
        inter = y[..., 0, :] * act(y[..., 1, :])
    else:
        inter = get_mlp_activation(None, m.activation)(y)
    fc2, s2 = _expert_kernel(experts["fc2"], dt)
    out_e = jnp.einsum("gecf,efh->gech", inter, fc2)
    if s2 is not None:
        out_e = out_e * s2.astype(dt)[None, :, None]
    if "bias" in experts["fc2"]:
        out_e = out_e + experts["fc2"]["bias"].astype(dt)[None, :, None]
    out_e = _ep_constraint(out_e, 1)

    out = jnp.einsum("gech,gtec->gth", out_e, combine.astype(dt))
    return out.reshape(b, s, h), aux


def zero_aux() -> jax.Array:
    """Aux-loss placeholder for dense layers (keeps scan carries uniform)."""
    return jnp.zeros((2,), jnp.float32)


def aux_loss_coeffs(cfg) -> Tuple[float, float]:
    """(balance_coeff, z_coeff) to apply to the summed aux pair.

    Expert-choice routing is balanced by construction, so it has no
    balance LOSS; its aux[0] slot instead reports the dropped-token
    fraction (route_expert_choice) as a metric. That value is
    piecewise-constant in the router weights (gradient-free) and must NOT
    enter the trained loss — the balance coefficient stays zeroed for EC
    regardless of what the slot reports.
    """
    m = cfg.model
    balance = 0.0 if m.moe_router_type == "expert_choice" else m.moe_aux_loss_coeff
    return balance, m.moe_z_loss_coeff
