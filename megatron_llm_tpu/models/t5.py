"""T5: encoder-decoder span-corruption model.

Reference: megatron/model/t5_model.py — ``T5LMHead``:40 (tied-embedding
logits + bias), ``T5Model``:70 (encoder + decoder with cross-attention,
attention masks from t5_model.py:21-37). TPU-native: the encoder and decoder
are two stacked-layer scans sharing one embedding table; masking is explicit
additive biases (bidirectional+pad for the encoder, causal+pad for the
decoder self-attention, pad-only for cross attention).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from megatron_llm_tpu.models.bert import padding_bias
from megatron_llm_tpu.models.language_model import (
    embed_tokens,
    init_model_params,
)
from megatron_llm_tpu.models.transformer import (
    init_stacked_layers,
    transformer_forward,
)
from megatron_llm_tpu.ops.attention import NEG_INF
from megatron_llm_tpu.ops.cross_entropy import softmax_cross_entropy
from megatron_llm_tpu.ops.norms import init_norm_params, norm

Params = Dict[str, Any]


def init_t5_params(cfg, key: jax.Array) -> Params:
    """Encoder stack reuses init_model_params' layers; the decoder stack is a
    second scan with cross-attention blocks."""
    m = cfg.model
    params = init_model_params(cfg, key)
    k_dec, k_head = jax.random.split(jax.random.fold_in(key, 11))
    dec_layers = m.decoder_num_layers or m.num_layers
    params["decoder_layers"] = init_stacked_layers(
        cfg, k_dec, num_layers=dec_layers, cross_attention=True
    )
    params["decoder_final_norm"] = init_norm_params(
        m.hidden_size, m.use_rms_norm
    )
    v = params["embedding"]["word_embeddings"].shape[0]
    # T5LMHead bias (t5_model.py:40-66); logits via tied embedding
    params["lm_head_bias"] = jnp.zeros((v,), jnp.float32)
    return params


def causal_padding_bias(padding_mask: jax.Array) -> jax.Array:
    """[b, s] -> additive bias [b, 1, s, s]: causal AND non-pad
    (t5_model.py:21-30 attention mask composition)."""
    s = padding_mask.shape[1]
    causal = jnp.tril(jnp.ones((s, s), bool))
    keep = causal[None] & padding_mask.astype(bool)[:, None, :]
    return jnp.where(keep[:, None], 0.0, NEG_INF).astype(jnp.float32)


def cross_bias(enc_mask: jax.Array) -> jax.Array:
    """[b, se] -> [b, 1, 1, se]: decoder queries attend non-pad encoder keys.

    Pad decoder QUERIES are not masked here — their outputs are discarded by
    the loss mask downstream (same asymmetry as the reference's
    enc_dec_attn_mask, t5_model.py:21-37).
    """
    keep = enc_mask.astype(bool)[:, None, None, :]
    return jnp.where(keep, 0.0, NEG_INF).astype(jnp.float32)


def t5_forward(
    cfg,
    params: Params,
    encoder_tokens: jax.Array,   # [b, se]
    decoder_tokens: jax.Array,   # [b, sd]
    encoder_padding_mask: jax.Array,  # [b, se] 1=real
    decoder_padding_mask: jax.Array,  # [b, sd]
    dropout_key: Optional[jax.Array] = None,
    deterministic: bool = True,
) -> jax.Array:
    """Returns decoder lm_logits [b, sd, v]."""
    m = cfg.model
    if dropout_key is not None:
        dk_enc, dk_dec = jax.random.split(dropout_key)
    else:
        dk_enc = dk_dec = None

    # ---- encoder (bidirectional + pad bias) ----
    enc_hidden = embed_tokens(cfg, params, encoder_tokens)
    enc_hidden, _, _enc_aux = transformer_forward(
        cfg, params["layers"], enc_hidden,
        attn_bias=padding_bias(encoder_padding_mask),
        dropout_key=dk_enc, deterministic=deterministic,
    )
    enc_hidden = norm(enc_hidden, params["final_norm"], m.layernorm_epsilon,
                      m.use_rms_norm)

    # ---- decoder (causal self-attn + cross-attn over encoder) ----
    dec_hidden = embed_tokens(cfg, params, decoder_tokens)
    dec_hidden, _, _dec_aux = transformer_forward(
        cfg, params["decoder_layers"], dec_hidden,
        attn_bias=causal_padding_bias(decoder_padding_mask),
        encoder_hidden=enc_hidden,
        enc_bias=cross_bias(encoder_padding_mask),
        dropout_key=dk_dec, deterministic=deterministic,
    )
    dec_hidden = norm(dec_hidden, params["decoder_final_norm"],
                      m.layernorm_epsilon, m.use_rms_norm)

    emb = params["embedding"]["word_embeddings"].astype(dec_hidden.dtype)
    return dec_hidden @ emb.T + params["lm_head_bias"].astype(dec_hidden.dtype)


def t5_loss_from_batch(cfg, params, batch: Dict[str, jax.Array], *,
                       dropout_key=None, deterministic=True,
                       rope_cache=None, sp_constraint=None):
    """pretrain_t5.py loss: CE over decoder targets at loss-masked positions."""
    logits = t5_forward(
        cfg, params,
        batch["text_enc"], batch["text_dec"],
        batch["enc_mask"], batch["dec_mask"],
        dropout_key=dropout_key, deterministic=deterministic,
    )
    per_token = softmax_cross_entropy(logits, batch["labels"])
    mask = batch["loss_mask"].astype(jnp.float32)
    loss = (per_token * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    return loss, {"lm loss": loss}
