"""T5: encoder-decoder span-corruption model.

Reference: megatron/model/t5_model.py — ``T5LMHead``:40 (tied-embedding
logits + bias), ``T5Model``:70 (encoder + decoder with cross-attention,
attention masks from t5_model.py:21-37). TPU-native: the encoder and decoder
are two stacked-layer scans sharing one embedding table; masking is explicit
additive biases (bidirectional+pad for the encoder, causal+pad for the
decoder self-attention, pad-only for cross attention).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from megatron_llm_tpu.models.bert import padding_bias
from megatron_llm_tpu.models.language_model import (
    embed_tokens,
    init_model_params,
)
from megatron_llm_tpu.models.transformer import (
    init_stacked_layers,
    transformer_forward,
)
from megatron_llm_tpu.ops.attention import NEG_INF
from megatron_llm_tpu.ops.cross_entropy import softmax_cross_entropy
from megatron_llm_tpu.ops.norms import init_norm_params, norm

Params = Dict[str, Any]


def init_t5_params(cfg, key: jax.Array) -> Params:
    """Encoder stack reuses init_model_params' layers; the decoder stack is a
    second scan with cross-attention blocks."""
    m = cfg.model
    params = init_model_params(cfg, key)
    k_dec, k_head = jax.random.split(jax.random.fold_in(key, 11))
    dec_layers = m.decoder_num_layers or m.num_layers
    params["decoder_layers"] = init_stacked_layers(
        cfg, k_dec, num_layers=dec_layers, cross_attention=True
    )
    params["decoder_final_norm"] = init_norm_params(
        m.hidden_size, m.use_rms_norm
    )
    v = params["embedding"]["word_embeddings"].shape[0]
    # T5LMHead bias (t5_model.py:40-66); logits via tied embedding
    params["lm_head_bias"] = jnp.zeros((v,), jnp.float32)
    return params


def causal_padding_bias(padding_mask: jax.Array) -> jax.Array:
    """[b, s] -> additive bias [b, 1, s, s]: causal AND non-pad
    (t5_model.py:21-30 attention mask composition)."""
    s = padding_mask.shape[1]
    causal = jnp.tril(jnp.ones((s, s), bool))
    keep = causal[None] & padding_mask.astype(bool)[:, None, :]
    return jnp.where(keep[:, None], 0.0, NEG_INF).astype(jnp.float32)


def cross_bias(enc_mask: jax.Array) -> jax.Array:
    """[b, se] -> [b, 1, 1, se]: decoder queries attend non-pad encoder keys.

    Pad decoder QUERIES are not masked here — their outputs are discarded by
    the loss mask downstream (same asymmetry as the reference's
    enc_dec_attn_mask, t5_model.py:21-37).
    """
    keep = enc_mask.astype(bool)[:, None, None, :]
    return jnp.where(keep, 0.0, NEG_INF).astype(jnp.float32)


def t5_forward(
    cfg,
    params: Params,
    encoder_tokens: jax.Array,   # [b, se]
    decoder_tokens: jax.Array,   # [b, sd]
    encoder_padding_mask: jax.Array,  # [b, se] 1=real
    decoder_padding_mask: jax.Array,  # [b, sd]
    dropout_key: Optional[jax.Array] = None,
    deterministic: bool = True,
) -> jax.Array:
    """Returns decoder lm_logits [b, sd, v]."""
    m = cfg.model
    if dropout_key is not None:
        dk_enc, dk_dec = jax.random.split(dropout_key)
    else:
        dk_enc = dk_dec = None

    # ---- encoder (bidirectional + pad bias) ----
    enc_hidden = embed_tokens(cfg, params, encoder_tokens)
    enc_hidden, _, _enc_aux = transformer_forward(
        cfg, params["layers"], enc_hidden,
        attn_bias=padding_bias(encoder_padding_mask),
        dropout_key=dk_enc, deterministic=deterministic,
    )
    enc_hidden = norm(enc_hidden, params["final_norm"], m.layernorm_epsilon,
                      m.use_rms_norm)

    # ---- decoder (causal self-attn + cross-attn over encoder) ----
    dec_hidden = embed_tokens(cfg, params, decoder_tokens)
    dec_hidden, _, _dec_aux = transformer_forward(
        cfg, params["decoder_layers"], dec_hidden,
        attn_bias=causal_padding_bias(decoder_padding_mask),
        encoder_hidden=enc_hidden,
        enc_bias=cross_bias(encoder_padding_mask),
        dropout_key=dk_dec, deterministic=deterministic,
    )
    dec_hidden = norm(dec_hidden, params["decoder_final_norm"],
                      m.layernorm_epsilon, m.use_rms_norm)

    emb = params["embedding"]["word_embeddings"].astype(dec_hidden.dtype)
    return dec_hidden @ emb.T + params["lm_head_bias"].astype(dec_hidden.dtype)


def t5_pipeline_loss_fn(cfg, mesh, params, batch: Dict[str, jax.Array], *,
                        num_micro: Optional[int] = None, dropout_key=None):
    """Pipelined T5 loss: encoder and decoder stacks each run through the
    GPipe engine (parallel/pipeline.pipeline_apply), the TPU-native analog
    of the reference's --pipeline_model_parallel_split_rank two-phase
    encoder/decoder placement (parallel_state.py + schedules.py encoder_and_
    decoder handling).

    Design: both stacks shard their layer axis over the SAME pp ring (each
    stage holds L_enc/pp encoder + L_dec/pp decoder layers, rather than the
    reference's disjoint stage ranges) — two pipelined phases per step, with
    the normed encoder output riding the aux dict into every decoder stage
    for cross-attention. Self-attention padding is expressed as segment ids
    (loss-equivalent to the additive-bias form for real rows — see
    bert_pipeline_hooks); the encoder phase runs under a bidirectional
    config copy.

    Dropout: per-microbatch keys split into (encoder, decoder) streams,
    matching t5_forward's dk_enc/dk_dec split of the per-microbatch
    fold_in key — with cp == 1 pipelined dropout is bit-identical to the
    pp=1 grad-accumulation path. Context parallelism (cp > 1): both
    stacks' self-attention runs cp-sharded (ring attention, bidirectional
    for the encoder); cross-attention keys (encoder_hidden/enc_bias) stay
    REPLICATED over cp (parallel/pipeline._aux_specs) so every cp-local
    decoder query chunk sees the full encoder sequence.
    """
    import copy

    from megatron_llm_tpu.parallel.pipeline import (
        microbatched_head_loss,
        pipeline_apply,
    )

    m = cfg.model
    assert m.num_experts is None  # finalize enforces; belt and braces
    M = num_micro or cfg.parallel.num_micro_batches or 1
    gbs = batch["text_enc"].shape[0]
    assert gbs % M == 0
    mb = gbs // M

    def split(x):
        return x.reshape(M, mb, *x.shape[1:])

    enc_tok, dec_tok = split(batch["text_enc"]), split(batch["text_dec"])
    enc_mask, dec_mask = split(batch["enc_mask"]), split(batch["dec_mask"])
    labels = split(batch["labels"])
    loss_mask = split(batch["loss_mask"]).astype(jnp.float32)

    # per-microbatch dropout keys: fold_in(base, i) then split to the
    # (encoder, decoder) streams — exactly t5_forward's split of the key
    # the pp=1 grad-accumulation path passes per microbatch
    use_dropout = dropout_key is not None and (
        m.hidden_dropout > 0.0 or m.attention_dropout > 0.0
    )
    if use_dropout:
        keys = jax.vmap(
            lambda i: jax.random.split(jax.random.fold_in(dropout_key, i))
        )(jnp.arange(M))
        enc_keys, dec_keys = keys[:, 0], keys[:, 1]
    else:
        enc_keys = dec_keys = None

    # ---- encoder phase: bidirectional self-attention, pads as segments ----
    cfg_enc = copy.deepcopy(cfg)
    cfg_enc.model.bidirectional = True
    enc_h0 = jax.vmap(lambda t: embed_tokens(cfg, params, t))(enc_tok)
    enc_aux = {"segment_ids": 1 - enc_mask.astype(jnp.int32)}
    enc_out, _ = pipeline_apply(
        cfg_enc, mesh, params["layers"], enc_h0, enc_aux, None,
        not use_dropout, None, mb_keys=enc_keys,
    )
    enc_out = norm(enc_out, params["final_norm"], m.layernorm_epsilon,
                   m.use_rms_norm)

    # ---- decoder phase: causal self-attention + cross-attention ----
    dec_h0 = jax.vmap(lambda t: embed_tokens(cfg, params, t))(dec_tok)
    dec_aux = {
        "segment_ids": 1 - dec_mask.astype(jnp.int32),
        "encoder_hidden": enc_out,
        # cross-attention bias precomputed here (the engine forwards aux
        # keys generically): [M, mb, 1, 1, se] masking padded encoder keys
        "enc_bias": jax.vmap(cross_bias)(enc_mask),
    }
    dec_out, _ = pipeline_apply(
        cfg, mesh, params["decoder_layers"], dec_h0, dec_aux, None,
        not use_dropout, None, mb_keys=dec_keys,
    )

    # ---- head + CE per microbatch (shared remat-scan discipline) ----
    denom = jnp.maximum(loss_mask.sum(), 1.0)

    def head_loss(outer_p, hid, lbl, msk, aux):
        h = norm(hid, outer_p["decoder_final_norm"], m.layernorm_epsilon,
                 m.use_rms_norm)
        emb = outer_p["embedding"]["word_embeddings"].astype(h.dtype)
        logits = h @ emb.T + outer_p["lm_head_bias"].astype(h.dtype)
        per_token = softmax_cross_entropy(logits, lbl)
        return (per_token * msk).sum() / denom

    loss = microbatched_head_loss(
        head_loss, params, dec_out, labels, loss_mask, {}
    )
    return loss, {"lm loss": loss}


def t5_loss_from_batch(cfg, params, batch: Dict[str, jax.Array], *,
                       dropout_key=None, deterministic=True,
                       rope_cache=None, sp_constraint=None):
    """pretrain_t5.py loss: CE over decoder targets at loss-masked positions."""
    logits = t5_forward(
        cfg, params,
        batch["text_enc"], batch["text_dec"],
        batch["enc_mask"], batch["dec_mask"],
        dropout_key=dropout_key, deterministic=deterministic,
    )
    per_token = softmax_cross_entropy(logits, batch["labels"])
    mask = batch["loss_mask"].astype(jnp.float32)
    loss = (per_token * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    return loss, {"lm loss": loss}
