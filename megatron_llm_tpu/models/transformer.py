"""The transformer stack — TPU-native redesign of megatron/model/transformer.py.

Differences from the reference (transformer.py:77-1347), by design:

* **Functional, not stateful**: parameters are a nested-dict pytree; the
  forward is a pure function — required for jit/pjit/shard_map/checkpoint.
* **Layers are stacked and scanned** (``lax.scan``) instead of a Python
  module list (transformer.py:1331-1337): one compiled block regardless of
  depth, which keeps XLA compile time flat at 80 layers.
* **GQA without K/V expansion**: the reference broadcast-expands K/V heads
  (transformer.py:459-466); we keep K/V at n_kv_heads and group queries.
* **Fused QKV projection** sized ``kv_channels * (n_heads + 2*n_kv_heads)``
  with *group-major* layout — for each KV head: its G query heads, then K,
  then V.  This matches the reference's interleaved qkv convention
  (transformer.py:325-343, weights_conversion/utils/permute_qkv.py) and makes
  TP sharding a clean split over KV groups.
* **Activation recompute** is ``jax.checkpoint`` with a policy, not an RNG
  state-juggling reimplementation (random.py:175-245): functional PRNG makes
  recompute-identical dropout automatic.

Layer params schema (one layer; stacked on axis 0 when scanned):

    {'input_norm':  {'scale': [h], 'bias'?: [h]},
     'attention':   {'qkv':   {'kernel': [h, (n+2*nkv)*d], 'bias'?},
                     'dense': {'kernel': [n*d, h],          'bias'?}},
     'post_norm':   {...},    # absent when parallel_attn
     'mlp_norm':    {...},    # Falcon-40B parallel_layernorm only
     'mlp':         {'fc1': {'kernel': [h, ffn*(2 if glu else 1)], 'bias'?},
                     'fc2': {'kernel': [ffn, h],                   'bias'?}}}
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from megatron_llm_tpu.core import rng as rng_mod
from megatron_llm_tpu.ops import attention as attn_ops
from megatron_llm_tpu.ops.activations import GLU_BASE_ACTIVATIONS, get_mlp_activation
from megatron_llm_tpu.ops.norms import init_norm_params, norm
from megatron_llm_tpu.ops.rope import apply_rotary_emb

Params = Dict[str, Any]


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------


def _normal(key, shape, std, dtype=jnp.float32):
    return std * jax.random.normal(key, shape, dtype=dtype)


def init_layer_params(cfg, key: jax.Array, cross_attention: bool = False) -> Params:
    m = cfg.model
    h = m.hidden_size
    d = m.kv_channels
    n, nkv = m.num_attention_heads, m.num_attention_heads_kv
    ffn = m.ffn_hidden_size
    glu = m.glu_activation is not None
    std = m.init_method_std
    # scaled init for output projections: std / sqrt(2 * num_layers)
    # (reference model/utils.py scaled_init_method_normal)
    out_std = std / (2.0 * m.num_layers) ** 0.5 if m.use_scaled_init_method else std

    k = jax.random.split(key, 7)
    p: Params = {
        "input_norm": init_norm_params(h, m.use_rms_norm),
        "attention": {
            "qkv": {"kernel": _normal(k[0], (h, (n + 2 * nkv) * d), std)},
            "dense": {"kernel": _normal(k[1], (n * d, h), out_std)},
        },
    }
    if m.num_experts is not None:
        # MoE layer: router + expert FFN stack replaces the dense MLP
        # (beyond-reference — see models/moe.py)
        from megatron_llm_tpu.models.moe import init_moe_params

        p["moe"] = init_moe_params(cfg, jax.random.fold_in(k[2], 0))
    else:
        p["mlp"] = {
            # GLU fc1 is [h, 2, ffn] (value half at [:,0,:], gated half at
            # [:,1,:]) so a tp sharding on the ffn axis never splits across
            # the gate/value boundary — the flat reference layout would force
            # a resharding at the chunk-2 split under GSPMD.
            "fc1": {"kernel": _normal(k[2], (h, 2, ffn) if glu else (h, ffn), std)},
            "fc2": {"kernel": _normal(k[3], (ffn, h), out_std)},
        }
    if not m.parallel_attn:
        p["post_norm"] = init_norm_params(h, m.use_rms_norm)
    if m.parallel_layernorm:
        p["mlp_norm"] = init_norm_params(h, m.use_rms_norm)
    if cross_attention:
        # T5 decoder inter-attention (reference t5_model.py via
        # ParallelAttention attn_type=cross, transformer.py:280): separate Q
        # and fused-KV projections over the encoder output.
        p["cross_attention"] = {
            "q": {"kernel": _normal(k[4], (h, n * d), std)},
            "kv": {"kernel": _normal(k[5], (h, 2 * nkv * d), std)},
            "dense": {"kernel": _normal(k[6], (n * d, h), out_std)},
        }
        p["cross_norm"] = init_norm_params(h, m.use_rms_norm)
        if m.use_bias:
            p["cross_attention"]["q"]["bias"] = jnp.zeros((n * d,), jnp.float32)
            p["cross_attention"]["kv"]["bias"] = jnp.zeros((2 * nkv * d,), jnp.float32)
            p["cross_attention"]["dense"]["bias"] = jnp.zeros((h,), jnp.float32)
    if m.use_bias or m.add_qkv_bias:
        # add_qkv_bias: Qwen2-style QKV-only bias (dense/mlp stay bias-free)
        p["attention"]["qkv"]["bias"] = jnp.zeros(((n + 2 * nkv) * d,), jnp.float32)
    if m.use_bias:
        p["attention"]["dense"]["bias"] = jnp.zeros((h,), jnp.float32)
        if "mlp" in p:
            p["mlp"]["fc1"]["bias"] = jnp.zeros((2, ffn) if glu else (ffn,), jnp.float32)
            p["mlp"]["fc2"]["bias"] = jnp.zeros((h,), jnp.float32)
    return p


def init_stacked_layers(cfg, key: jax.Array, num_layers: Optional[int] = None,
                        cross_attention: bool = False) -> Params:
    """Stack per-layer params on axis 0 (for lax.scan / per-stage pipelines)."""
    L = num_layers if num_layers is not None else cfg.model.num_layers
    keys = jax.random.split(key, L)
    return jax.vmap(
        lambda kk: init_layer_params(cfg, kk, cross_attention=cross_attention)
    )(keys)


# ---------------------------------------------------------------------------
# Sublayers
# ---------------------------------------------------------------------------


def _linear(p: Params, x: jax.Array) -> jax.Array:
    # weight-only int8 support (the shared quantized-leaf contract,
    # ops/quant.py:resolve_kernel): HBM reads int8, the convert fuses into
    # the GEMM; the per-channel scale applies to the output (after the GLU
    # chunk-axis restore)
    from megatron_llm_tpu.ops.quant import resolve_kernel

    kernel, scale = resolve_kernel(p, x.dtype)
    if kernel.ndim == 3:
        # GLU fc1 [h, 2, ffn]: flatten for one GEMM, restore the chunk axis
        # (same contract as ops/fp8.fp8_linear)
        y = x @ kernel.reshape(kernel.shape[0], -1)
        y = y.reshape(*y.shape[:-1], *kernel.shape[1:])
    else:
        y = x @ kernel
    if scale is not None:
        y = y * scale.astype(y.dtype)
    if "bias" in p:
        y = y + p["bias"].astype(x.dtype)
    return y


def _linear_impl(cfg):
    """The projection implementation for this config: plain bf16/fp32
    matmul, or fp8 GEMMs when cfg.model.fp8 is set (ops/fp8.py — the
    TransformerEngine-path analog; embedding/logits/softmax stay in high
    precision exactly as TE keeps them out of fp8)."""
    from megatron_llm_tpu.ops.fp8 import linear_for_config

    return linear_for_config(cfg) or _linear


def split_qkv(
    qkv: jax.Array, n_heads: int, n_kv_heads: int, head_dim: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Split group-major fused QKV [..., (n+2*nkv)*d] into q/k/v head tensors."""
    g = n_heads // n_kv_heads
    *lead, _ = qkv.shape
    grouped = qkv.reshape(*lead, n_kv_heads, g + 2, head_dim)
    q = grouped[..., :g, :].reshape(*lead, n_heads, head_dim)
    k = grouped[..., g, :]
    v = grouped[..., g + 1, :]
    return q, k, v


def attention_sublayer(
    cfg,
    p: Params,
    x: jax.Array,  # [b, s, h] (post input-norm)
    rope: Optional[Tuple[jax.Array, jax.Array]],
    position_ids: Optional[jax.Array],
    segment_ids: Optional[jax.Array],
    dropout_key: Optional[jax.Array],
    deterministic: bool,
    kv_cache: Optional[Tuple[jax.Array, jax.Array]] = None,
    cache_index: Optional[jax.Array] = None,
    token_idx: Optional[jax.Array] = None,
    attn_bias: Optional[jax.Array] = None,
    paged=None,
):
    """ParallelAttention analog (transformer.py:280-657).

    ``paged`` (ops/paged_attention.PagedState) switches the incremental-decode
    branch to the block-table page pool: ``kv_cache`` is then the per-layer
    [num_pages, page_size, nkv, d] pair and each row writes/attends at its own
    position — the continuous-batching engine's fused tick.

    Returns (output [b, s, h], new_kv_cache).
    """
    m = cfg.model
    b, s, _ = x.shape
    n, nkv, d = m.num_attention_heads, m.num_attention_heads_kv, m.kv_channels

    from megatron_llm_tpu.parallel.tp import (
        apply_column_parallel,
        apply_row_parallel,
    )

    linear = _linear_impl(cfg)
    qkv = apply_column_parallel(cfg, p["qkv"], x, linear)
    q, k, v = split_qkv(qkv, n, nkv, d)

    if rope is not None:
        cos, sin = rope
        q = apply_rotary_emb(q, cos, sin, position_ids)
        k = apply_rotary_emb(k, cos, sin, position_ids)

    # apply_query_key_layer_scaling (reference CoreAttention:158-176) divides
    # QK^T by layer_number and multiplies back inside an fp32 softmax purely to
    # avoid fp16 overflow — a mathematical identity. Our softmax is always
    # computed in fp32 (attention.py softmax_fp32), so the flag needs no code.
    scale = 1.0 / (d ** 0.5)

    new_cache = None
    if paged is not None:
        # Continuous-batching paged path. s == 1 is the decode tick: one
        # token per row, each at its own position. s > 1 is a prefill CHUNK:
        # the block of tokens occupies positions positions[b] ..
        # positions[b] + s - 1 of each row. Either way: write k/v through
        # the block table, then attend over the block table
        # (ops/paged_attention.py). Inactive slots' block tables point at
        # the reserved null page 0, so their writes land in garbage that is
        # never attended.
        from megatron_llm_tpu.ops import kv_quant
        from megatron_llm_tpu.ops.paged_attention import (
            paged_attention_decode,
            paged_attention_prefill,
            paged_attention_ragged,
        )

        pk, pv = kv_cache
        page_size = kv_quant.page_size_of(pk)
        pos = paged.positions
        # ragged compressed tables (ISSUE 11): block_tables holds the
        # tick's UNIQUE tables and table_index maps rows onto them; the
        # K/V write needs per-row tables, a [rows, pages] int gather
        row_tables = paged.block_tables
        if paged.table_index is not None:
            row_tables = row_tables[paged.table_index]
        wpos = pos[:, None] + jnp.arange(s)[None, :]       # [b, s]
        # clip: idle slots' device-side positions keep advancing between
        # engine re-uploads, and a chunk's garbage padding rows may run past
        # the table; clipped lookups resolve to null-page (or
        # decode-overwritten) entries, so the stray writes are never attended
        page_slot = jnp.clip(wpos // page_size, 0,
                             row_tables.shape[1] - 1)
        page_ids = jnp.take_along_axis(row_tables, page_slot, axis=1)
        offs = wpos % page_size
        # plain pools: the original scatter, byte for byte; quantized
        # pools (--kv_dtype int8/fp8): page-granular quantizing write
        # with per-page, per-head scales (ops/kv_quant.paged_write)
        pk = kv_quant.paged_write(pk, page_ids, offs, k)
        pv = kv_quant.paged_write(pv, page_ids, offs, v)
        new_cache = (pk, pv)
        if s == 1 and paged.horizons is not None:
            # ragged tick (ISSUE 11): one launch for a mixed
            # decode/verify/prefill row batch; each row carries its own
            # data-carried kv horizon (0 = dead padding row) and an index
            # into the tick's unique block tables
            ctx = paged_attention_ragged(
                q, pk, pv, paged.block_tables, paged.table_index, pos,
                paged.horizons,
                scale=scale, sliding_window=m.sliding_window_size,
                use_kernel=cfg.training.use_flash_attn,
            )
        elif s == 1:
            ctx = paged_attention_decode(
                q, pk, pv, paged.block_tables, pos, scale=scale,
                sliding_window=m.sliding_window_size,
                use_kernel=cfg.training.use_flash_attn,
            )
        else:
            ctx = paged_attention_prefill(
                q, pk, pv, paged.block_tables, pos, scale=scale,
                sliding_window=m.sliding_window_size,
                use_kernel=cfg.training.use_flash_attn,
            )
    elif kv_cache is not None:
        # Incremental decode: write current k/v at cache_index, attend to the
        # full cache prefix (InferenceParams semantics, text_generation/
        # forward_step.py:17 + transformer.py:413-506).
        ck, cv = kv_cache
        ck = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype), (0, cache_index, 0, 0))
        cv = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype), (0, cache_index, 0, 0))
        new_cache = (ck, cv)
        kv_len = ck.shape[1]
        q_pos = cache_index + jnp.arange(s)[:, None]
        kv_pos = jnp.arange(kv_len)[None, :]
        allowed = q_pos >= kv_pos
        if m.sliding_window_size is not None:
            allowed &= q_pos - kv_pos < m.sliding_window_size
        bias = jnp.where(allowed, 0.0, attn_ops.NEG_INF).astype(jnp.float32)[None, None]
        ctx = attn_ops.xla_attention(q, ck, cv, bias=bias, scale=scale)
    else:
        ctx = attn_ops.attention(
            q, k, v,
            causal=not m.bidirectional,
            sliding_window=m.sliding_window_size,
            segment_ids=segment_ids,
            token_idx=token_idx,
            bias=attn_bias,
            scale=scale,
            use_flash=cfg.training.use_flash_attn,
            dropout_rate=0.0 if deterministic else m.attention_dropout,
            dropout_key=dropout_key,
            zigzag=cfg.parallel.cp_zigzag,
        )

    # named so remat policies can save the attention output and skip
    # recomputing the (custom-vjp) flash kernel forward in the backward pass
    from jax.ad_checkpoint import checkpoint_name

    ctx = checkpoint_name(ctx, "attn_out")
    out = apply_row_parallel(cfg, p["dense"], ctx.reshape(b, s, n * d),
                             linear)
    return out, new_cache


def cross_attention_sublayer(
    cfg,
    p: Params,
    x: jax.Array,            # [b, sq, h] (post cross-norm)
    encoder_hidden: jax.Array,  # [b, skv, h]
    enc_bias: Optional[jax.Array],  # [b or 1, 1, sq, skv] additive bias
    dropout_key: Optional[jax.Array],
    deterministic: bool,
):
    """T5 decoder inter-attention (reference ParallelAttention with
    attn_type=cross_attn, transformer.py:280-343): Q from the decoder stream,
    K/V from the encoder output, full (non-causal) attention."""
    m = cfg.model
    b, sq, _ = x.shape
    n, nkv, d = m.num_attention_heads, m.num_attention_heads_kv, m.kv_channels
    linear = _linear_impl(cfg)
    q = linear(p["q"], x).reshape(b, sq, n, d)
    kv = linear(p["kv"], encoder_hidden)
    skv = encoder_hidden.shape[1]
    kv = kv.reshape(b, skv, nkv, 2, d)
    k, v = kv[..., 0, :], kv[..., 1, :]
    ctx = attn_ops.xla_attention(
        q, k, v, bias=enc_bias, scale=1.0 / (d ** 0.5),
        dropout_rate=0.0 if deterministic else m.attention_dropout,
        dropout_key=dropout_key,
    )
    return linear(p["dense"], ctx.reshape(b, sq, n * d))


def ffn_sublayer(cfg, p: Params, x: jax.Array):
    """Dense MLP or MoE, depending on the layer params. Returns (out, aux[2])
    where aux is the (load-balance, z) router loss pair (zeros for dense)."""
    from megatron_llm_tpu.models import moe as moe_mod

    if "moe" in p:
        return moe_mod.moe_sublayer(cfg, p["moe"], x)
    return mlp_sublayer(cfg, p["mlp"], x), moe_mod.zero_aux()


def mlp_sublayer(cfg, p: Params, x: jax.Array) -> jax.Array:
    """ParallelMLP analog (transformer.py:77-142): fc1 -> activation -> fc2.

    GLU path: fc1 kernel is [h, 2, ffn]; one GEMM computes both halves, the
    gate is x1 * act(x2) matching the reference chunk-2 convention
    (glu_activations.py:14-16).
    """
    from megatron_llm_tpu.parallel.tp import (
        apply_column_parallel,
        apply_row_parallel,
    )

    m = cfg.model
    linear = _linear_impl(cfg)
    if m.glu_activation is not None:
        act = GLU_BASE_ACTIVATIONS[m.glu_activation]
        # [..., 2, ffn] (both impls restore the axis)
        y = apply_column_parallel(cfg, p["fc1"], x, linear)
        gated = y[..., 0, :] * act(y[..., 1, :])
        return apply_row_parallel(cfg, p["fc2"], gated, linear)
    act = get_mlp_activation(None, m.activation)
    h = act(apply_column_parallel(cfg, p["fc1"], x, linear))
    return apply_row_parallel(cfg, p["fc2"], h, linear)


# ---------------------------------------------------------------------------
# Block
# ---------------------------------------------------------------------------


def block_forward(
    cfg,
    p: Params,
    hidden: jax.Array,  # [b, s, h]
    *,
    rope=None,
    position_ids=None,
    segment_ids=None,
    token_idx=None,
    attn_bias=None,
    encoder_hidden=None,
    enc_bias=None,
    dropout_key=None,
    deterministic: bool = True,
    hidden_dropout_rate: Optional[float] = None,
    kv_cache=None,
    cache_index=None,
    paged=None,
    sp_constraint=None,
):
    """One transformer layer (ParallelTransformerLayer, transformer.py:659-894).

    Pre-LN residual block; ``parallel_attn`` runs attention and MLP from the
    same normed input and sums both into the residual (Falcon,
    transformer.py:851-886). ``sp_constraint`` is an optional callable applying
    the sequence-parallel sharding constraint to residual-stream tensors.
    """
    m = cfg.model
    eps = m.layernorm_epsilon
    rate = m.hidden_dropout if hidden_dropout_rate is None else hidden_dropout_rate
    if dropout_key is not None:
        dk_attn, dk_h1, dk_h2, dk_x, dk_hx = jax.random.split(dropout_key, 5)
    else:
        dk_attn = dk_h1 = dk_h2 = dk_x = dk_hx = None
    _sp = sp_constraint if sp_constraint is not None else (lambda t: t)

    ln1 = norm(hidden, p["input_norm"], eps, m.use_rms_norm)
    attn_out, new_cache = attention_sublayer(
        cfg, p["attention"], ln1, rope, position_ids, segment_ids,
        dk_attn, deterministic, kv_cache, cache_index, token_idx=token_idx,
        attn_bias=attn_bias, paged=paged,
    )

    if m.parallel_attn:
        assert "cross_attention" not in p, (
            "cross-attention layers (T5 decoder) require the sequential "
            "block; parallel_attn would silently skip the encoder attention"
        )
        mlp_in = norm(hidden, p["mlp_norm"], eps, m.use_rms_norm) if m.parallel_layernorm else ln1
        mlp_out, aux = ffn_sublayer(cfg, p, mlp_in)
        out = hidden + rng_mod.dropout(dk_h1, rate, attn_out, deterministic or dk_h1 is None) \
            + rng_mod.dropout(dk_h2, rate, mlp_out, deterministic or dk_h2 is None)
        out = _sp(out)
    else:
        resid = hidden + rng_mod.dropout(dk_h1, rate, attn_out, deterministic or dk_h1 is None)
        resid = _sp(resid)
        if "cross_attention" in p:
            # decoder inter-attention block (LayerType.decoder,
            # transformer.py:838-850)
            lnx = norm(resid, p["cross_norm"], eps, m.use_rms_norm)
            x_out = cross_attention_sublayer(
                cfg, p["cross_attention"], lnx, encoder_hidden, enc_bias,
                dk_x, deterministic,
            )
            resid = resid + rng_mod.dropout(
                dk_hx, rate, x_out, deterministic or dk_hx is None
            )
            resid = _sp(resid)
        ln2 = norm(resid, p["post_norm"], eps, m.use_rms_norm)
        mlp_out, aux = ffn_sublayer(cfg, p, ln2)
        out = resid + rng_mod.dropout(dk_h2, rate, mlp_out, deterministic or dk_h2 is None)
        out = _sp(out)
    return out, new_cache, aux


def _lima_rates(cfg, num_layers: int) -> jax.Array:
    """LIMA per-layer dropout ramp 0 -> hidden_dropout (transformer.py:1041-1048)."""
    m = cfg.model
    if not m.lima_dropout or num_layers <= 1:
        return jnp.full((num_layers,), m.hidden_dropout, jnp.float32)
    return jnp.linspace(0.0, m.hidden_dropout, num_layers)


def _remat_policy(name: str):
    policies = {
        "none": None,
        "full": jax.checkpoint_policies.nothing_saveable,
        "save_dots_except_logits": jax.checkpoint_policies.checkpoint_dots,
        # 'selective' ~ reference selective recompute: save everything except
        # the attention internals (we approximate with save-only-dot-products).
        "selective": jax.checkpoint_policies.dots_saveable,
        # dots + the named attention outputs: the backward reuses the saved
        # flash result instead of re-running the kernel forward
        "save_dots_and_attn": jax.checkpoint_policies.save_from_both_policies(
            jax.checkpoint_policies.checkpoint_dots,
            jax.checkpoint_policies.save_only_these_names("attn_out"),
        ),
        # near-full recompute, but keep the flash-attention outputs: the one
        # tensor whose recompute is a whole Pallas kernel run. Memory close
        # to 'full' (enables the largest micro-batches), backward cost close
        # to 'selective'.
        "save_attn_only": jax.checkpoint_policies.save_only_these_names(
            "attn_out"
        ),
    }
    return policies.get(name, jax.checkpoint_policies.checkpoint_dots)


def transformer_forward(
    cfg,
    stacked_layers: Params,
    hidden: jax.Array,
    *,
    rope=None,
    position_ids=None,
    segment_ids=None,
    token_idx=None,
    attn_bias=None,
    encoder_hidden=None,
    enc_bias=None,
    dropout_key=None,
    deterministic: bool = True,
    kv_caches=None,        # stacked [L, ...] pair, or None
    cache_index=None,
    paged=None,
    sp_constraint=None,
    layer_offset: int = 0,
):
    """Run the stacked layers (ParallelTransformer, transformer.py:974-1347).

    When ``cfg.training.scan_layers`` (default), layers are scanned with an
    optional remat policy; otherwise a Python loop (useful for debugging and
    per-layer inspection).
    Returns (hidden, new_kv_caches, aux) — ``aux`` is the summed MoE router
    loss pair [2] (load-balance, z), zeros for dense models.
    """
    num_layers = jax.tree_util.tree_leaves(stacked_layers)[0].shape[0]
    rates = _lima_rates(cfg, cfg.model.num_layers)

    def one_layer(carry_hidden, xs):
        layer_params, layer_idx, cache = xs
        dk = None if dropout_key is None else rng_mod.fold_layer(dropout_key, layer_idx)
        rate = rates[layer_idx]
        out, new_cache, aux = block_forward(
            cfg, layer_params, carry_hidden,
            rope=rope, position_ids=position_ids, segment_ids=segment_ids,
            token_idx=token_idx,
            attn_bias=attn_bias,
            encoder_hidden=encoder_hidden, enc_bias=enc_bias,
            dropout_key=dk, deterministic=deterministic,
            hidden_dropout_rate=rate,
            kv_cache=cache, cache_index=cache_index, paged=paged,
            sp_constraint=sp_constraint,
        )
        return out, (new_cache, aux)

    layer_ids = jnp.arange(num_layers) + layer_offset

    if cfg.training.scan_layers:
        granularity = cfg.parallel.recompute_granularity
        policy = _remat_policy(
            "full" if granularity == "full" else cfg.training.remat_policy
            if granularity else "none"
        )
        body = one_layer
        if granularity is not None:
            body = jax.checkpoint(one_layer, policy=policy, prevent_cse=False)
        hidden, (new_caches, aux_stack) = jax.lax.scan(
            body, hidden, (stacked_layers, layer_ids, kv_caches)
        )
        return hidden, new_caches, aux_stack.sum(0)
    else:
        new_caches = []
        aux_total = jnp.zeros((2,), jnp.float32)
        for i in range(num_layers):
            layer_p = jax.tree.map(lambda a: a[i], stacked_layers)
            cache = None if kv_caches is None else jax.tree.map(lambda a: a[i], kv_caches)
            hidden, (nc, aux) = one_layer(hidden, (layer_p, layer_ids[i], cache))
            new_caches.append(nc)
            aux_total = aux_total + aux
        if kv_caches is not None:
            new_caches = jax.tree.map(lambda *xs: jnp.stack(xs), *new_caches)
        else:
            new_caches = None
        return hidden, new_caches, aux_total
