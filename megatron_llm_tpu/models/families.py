"""Model families — flag-bundle wrappers over the shared language model.

The reference implements these as thin subclasses of GPTModel that assert the
architecture's flag bundle (model/llama_model.py:22-30, falcon_model.py:18-29,
mistral_model.py:30). Here a family is a validated Config plus the shared
functional model; construction helpers below mirror those assertions.
"""

from __future__ import annotations

from megatron_llm_tpu.config.arguments import Config, apply_architecture


def _check(cond: bool, msg: str) -> None:
    if not cond:
        raise ValueError(msg)


def validate_family(cfg: Config) -> Config:
    m = cfg.model
    name = cfg.model_name
    if name in ("llama", "llama2", "codellama", "llama3"):
        # llama_model.py:22-30
        _check(m.position_embedding_type == "rotary", "llama requires rotary embeddings")
        _check(m.glu_activation == "swiglu", "llama requires swiglu")
        _check(m.use_rms_norm, "llama requires RMSNorm")
        _check(not m.use_bias, "llama has no biases")
        if name != "llama3":  # Llama-3.2 small models tie embeddings
            _check(not m.tie_embed_logits, "llama uses untied embeddings")
    elif name == "falcon":
        # falcon_model.py:18-29
        _check(m.parallel_attn, "falcon requires parallel_attn")
        _check(m.position_embedding_type == "rotary", "falcon requires rotary embeddings")
        _check(not m.use_rms_norm, "falcon uses LayerNorm, not RMSNorm")
    elif name == "mistral":
        # mistral_model.py:30 pins 4096; we only require a window to be set so
        # HF checkpoints with other window sizes convert cleanly
        _check(m.sliding_window_size is not None,
               "mistral requires sliding_window_size")
        _check(m.use_rms_norm and m.glu_activation == "swiglu", "mistral uses llama block")
    elif name == "mixtral":
        _check(m.num_experts is not None and m.num_experts > 1,
               "mixtral requires num_experts > 1")
        _check(m.use_rms_norm and m.glu_activation == "swiglu",
               "mixtral uses the llama block")
        _check(not m.use_bias, "mixtral has no biases")
    elif name == "qwen2":
        # beyond-reference: llama block + QKV-only bias
        _check(m.position_embedding_type == "rotary",
               "qwen2 requires rotary embeddings")
        _check(m.use_rms_norm and m.glu_activation == "swiglu",
               "qwen2 uses the llama block")
        _check(not m.use_bias, "qwen2 has no global biases")
        _check(m.add_qkv_bias, "qwen2 requires add_qkv_bias")
    return cfg


def make_config(model_name: str, **overrides) -> Config:
    """Build a finalized family Config; overrides are flat flag names."""
    from megatron_llm_tpu.config.arguments import _set_flag

    cfg = Config()
    apply_architecture(cfg, model_name)
    for k, v in overrides.items():
        _set_flag(cfg, k, v)
    cfg.finalize()
    return validate_family(cfg)
