"""BERT: bidirectional masked-LM with NSP/SOP binary head.

Reference: megatron/model/bert_model.py — ``BertLMHead``:47 (dense h->h +
gelu + LN + tied-embedding logits + vocab bias), ``BertModel``:125 (pooler +
binary head, bert_extended_attention_mask), loss in pretrain_bert.py
(masked-LM CE + sentence-order binary CE). TPU-native: pure functions over a
params pytree; padding is an explicit additive attention bias (no 4D byte
mask materialization).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from megatron_llm_tpu.models.language_model import (
    _compute_dtype,
    embed_tokens,
    init_model_params,
)
from megatron_llm_tpu.models.transformer import transformer_forward
from megatron_llm_tpu.ops.attention import NEG_INF
from megatron_llm_tpu.ops.cross_entropy import softmax_cross_entropy
from megatron_llm_tpu.ops.norms import init_norm_params, norm

Params = Dict[str, Any]


def init_bert_params(cfg, key: jax.Array) -> Params:
    m = cfg.model
    params = init_model_params(cfg, key)
    h = m.hidden_size
    v = params["embedding"]["word_embeddings"].shape[0]
    k1, k2, k3 = jax.random.split(jax.random.fold_in(key, 7), 3)
    std = m.init_method_std
    # BertLMHead (bert_model.py:47-90): transform + LN + vocab bias; logits
    # come through the tied word-embedding matrix.
    params["mlm_head"] = {
        "dense": {
            "kernel": std * jax.random.normal(k1, (h, h), jnp.float32),
            "bias": jnp.zeros((h,), jnp.float32),
        },
        "norm": init_norm_params(h, m.use_rms_norm),
        "vocab_bias": jnp.zeros((v,), jnp.float32),
    }
    if m.bert_binary_head:
        # Pooler (language_model.py pooler) + binary head (bert_model.py:162)
        params["pooler"] = {
            "kernel": std * jax.random.normal(k2, (h, h), jnp.float32),
            "bias": jnp.zeros((h,), jnp.float32),
        }
        params["binary_head"] = {
            "kernel": std * jax.random.normal(k3, (h, 2), jnp.float32),
            "bias": jnp.zeros((2,), jnp.float32),
        }
    return params


def padding_bias(padding_mask: jax.Array) -> jax.Array:
    """[b, s] 1=real/0=pad -> additive bias [b, 1, 1, s]: every query may
    attend to every non-pad key (bert_extended_attention_mask semantics)."""
    keep = padding_mask.astype(bool)[:, None, None, :]
    return jnp.where(keep, 0.0, NEG_INF).astype(jnp.float32)


def bert_forward(
    cfg,
    params: Params,
    tokens: jax.Array,             # [b, s]
    padding_mask: jax.Array,       # [b, s] 1=real token
    tokentype_ids: Optional[jax.Array] = None,
    dropout_key: Optional[jax.Array] = None,
    deterministic: bool = True,
) -> Tuple[jax.Array, Optional[jax.Array]]:
    """Returns (lm_logits [b, s, v], binary_logits [b, 2] or None)."""
    m = cfg.model
    hidden = embed_tokens(cfg, params, tokens, tokentype_ids=tokentype_ids)
    bias = padding_bias(padding_mask)
    hidden, _, _moe_aux = transformer_forward(
        cfg, params["layers"], hidden,
        attn_bias=bias,
        dropout_key=dropout_key, deterministic=deterministic,
    )
    hidden = norm(hidden, params["final_norm"], m.layernorm_epsilon,
                  m.use_rms_norm)

    # MLM head
    head = params["mlm_head"]
    x = hidden @ head["dense"]["kernel"].astype(hidden.dtype)
    x = x + head["dense"]["bias"].astype(hidden.dtype)
    x = jax.nn.gelu(x, approximate=False)
    x = norm(x, head["norm"], m.layernorm_epsilon, m.use_rms_norm)
    emb = params["embedding"]["word_embeddings"].astype(x.dtype)
    lm_logits = x @ emb.T + head["vocab_bias"].astype(x.dtype)

    binary_logits = None
    if m.bert_binary_head:
        pooled = jnp.tanh(
            hidden[:, 0] @ params["pooler"]["kernel"].astype(hidden.dtype)
            + params["pooler"]["bias"].astype(hidden.dtype)
        )
        binary_logits = (
            pooled @ params["binary_head"]["kernel"].astype(pooled.dtype)
            + params["binary_head"]["bias"].astype(pooled.dtype)
        )
    return lm_logits, binary_logits


def bert_loss_from_batch(cfg, params, batch: Dict[str, jax.Array], *,
                         dropout_key=None, deterministic=True,
                         rope_cache=None, sp_constraint=None):
    """pretrain_bert.py loss: masked-LM CE over masked positions + binary
    sentence-order CE (forward_step at pretrain_bert.py:40-80)."""
    lm_logits, binary_logits = bert_forward(
        cfg, params, batch["text"], batch["padding_mask"],
        tokentype_ids=batch.get("types"),
        dropout_key=dropout_key, deterministic=deterministic,
    )
    per_token = softmax_cross_entropy(lm_logits, batch["labels"])
    mask = batch["loss_mask"].astype(jnp.float32)
    lm_loss = (per_token * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    metrics = {"lm loss": lm_loss}
    loss = lm_loss
    if binary_logits is not None and "is_random" in batch:
        logp = jax.nn.log_softmax(binary_logits.astype(jnp.float32), axis=-1)
        sop = -jnp.take_along_axis(
            logp, batch["is_random"][:, None].astype(jnp.int32), axis=-1
        ).mean()
        metrics["sop loss"] = sop
        loss = loss + sop
    metrics["loss"] = loss
    return loss, metrics
