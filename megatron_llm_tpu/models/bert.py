"""BERT: bidirectional masked-LM with NSP/SOP binary head.

Reference: megatron/model/bert_model.py — ``BertLMHead``:47 (dense h->h +
gelu + LN + tied-embedding logits + vocab bias), ``BertModel``:125 (pooler +
binary head, bert_extended_attention_mask), loss in pretrain_bert.py
(masked-LM CE + sentence-order binary CE). TPU-native: pure functions over a
params pytree; padding is an explicit additive attention bias (no 4D byte
mask materialization).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from megatron_llm_tpu.models.language_model import (
    _compute_dtype,
    embed_tokens,
    init_model_params,
)
from megatron_llm_tpu.models.transformer import transformer_forward
from megatron_llm_tpu.ops.attention import NEG_INF
from megatron_llm_tpu.ops.cross_entropy import softmax_cross_entropy
from megatron_llm_tpu.ops.norms import init_norm_params, norm

Params = Dict[str, Any]


def init_bert_params(cfg, key: jax.Array) -> Params:
    m = cfg.model
    params = init_model_params(cfg, key)
    h = m.hidden_size
    v = params["embedding"]["word_embeddings"].shape[0]
    k1, k2, k3 = jax.random.split(jax.random.fold_in(key, 7), 3)
    std = m.init_method_std
    # BertLMHead (bert_model.py:47-90): transform + LN + vocab bias; logits
    # come through the tied word-embedding matrix.
    params["mlm_head"] = {
        "dense": {
            "kernel": std * jax.random.normal(k1, (h, h), jnp.float32),
            "bias": jnp.zeros((h,), jnp.float32),
        },
        "norm": init_norm_params(h, m.use_rms_norm),
        "vocab_bias": jnp.zeros((v,), jnp.float32),
    }
    if m.bert_binary_head:
        # Pooler (language_model.py pooler) + binary head (bert_model.py:162)
        params["pooler"] = {
            "kernel": std * jax.random.normal(k2, (h, h), jnp.float32),
            "bias": jnp.zeros((h,), jnp.float32),
        }
        params["binary_head"] = {
            "kernel": std * jax.random.normal(k3, (h, 2), jnp.float32),
            "bias": jnp.zeros((2,), jnp.float32),
        }
    return params


def padding_bias(padding_mask: jax.Array) -> jax.Array:
    """[b, s] 1=real/0=pad -> additive bias [b, 1, 1, s]: every query may
    attend to every non-pad key (bert_extended_attention_mask semantics)."""
    keep = padding_mask.astype(bool)[:, None, None, :]
    return jnp.where(keep, 0.0, NEG_INF).astype(jnp.float32)


def mlm_head_logits(cfg, params: Params, hidden: jax.Array) -> jax.Array:
    """BertLMHead (bert_model.py:47-90) over final-normed hidden states:
    dense h->h + gelu + LN + tied-embedding logits + vocab bias."""
    m = cfg.model
    head = params["mlm_head"]
    x = hidden @ head["dense"]["kernel"].astype(hidden.dtype)
    x = x + head["dense"]["bias"].astype(hidden.dtype)
    x = jax.nn.gelu(x, approximate=False)
    x = norm(x, head["norm"], m.layernorm_epsilon, m.use_rms_norm)
    emb = params["embedding"]["word_embeddings"].astype(x.dtype)
    return x @ emb.T + head["vocab_bias"].astype(x.dtype)


def binary_head_logits(cfg, params: Params, hidden: jax.Array) -> jax.Array:
    """Pooler (tanh over CLS) + NSP/SOP binary head (bert_model.py:125,162)."""
    pooled = jnp.tanh(
        hidden[:, 0] @ params["pooler"]["kernel"].astype(hidden.dtype)
        + params["pooler"]["bias"].astype(hidden.dtype)
    )
    return (
        pooled @ params["binary_head"]["kernel"].astype(pooled.dtype)
        + params["binary_head"]["bias"].astype(pooled.dtype)
    )


def bert_forward(
    cfg,
    params: Params,
    tokens: jax.Array,             # [b, s]
    padding_mask: jax.Array,       # [b, s] 1=real token
    tokentype_ids: Optional[jax.Array] = None,
    dropout_key: Optional[jax.Array] = None,
    deterministic: bool = True,
) -> Tuple[jax.Array, Optional[jax.Array]]:
    """Returns (lm_logits [b, s, v], binary_logits [b, 2] or None)."""
    m = cfg.model
    hidden = embed_tokens(cfg, params, tokens, tokentype_ids=tokentype_ids)
    bias = padding_bias(padding_mask)
    hidden, _, _moe_aux = transformer_forward(
        cfg, params["layers"], hidden,
        attn_bias=bias,
        dropout_key=dropout_key, deterministic=deterministic,
    )
    hidden = norm(hidden, params["final_norm"], m.layernorm_epsilon,
                  m.use_rms_norm)
    lm_logits = mlm_head_logits(cfg, params, hidden)
    binary_logits = (
        binary_head_logits(cfg, params, hidden) if m.bert_binary_head else None
    )
    return lm_logits, binary_logits


def bert_pipeline_hooks(cfg, batch: Dict[str, jax.Array]):
    """Pipeline-parallel hooks for BERT (training_step pipeline_hooks
    contract): maps the BERT batch onto the pipeline engine's
    tokens/labels/loss_mask/aux layout and supplies embed/head fns.

    The reference runs BERT under its loss-agnostic schedules via
    forward_step_func (pretrain_bert.py + schedules.py); here the engine is
    loss-agnostic via these hooks instead.

    Padding is expressed as segment ids (pad positions get segment 1, real
    positions 0) rather than the additive bias bert_forward uses: the
    per-row attention outputs of REAL tokens are identical under either
    formulation (a real token attends exactly to the real tokens both
    ways), and only real-token rows reach the loss (MLM mask, CLS pooler) —
    so pipelined losses match bert_loss_from_batch exactly.
    """
    m = cfg.model
    if (cfg.parallel.context_parallel_size > 1
            and cfg.parallel.pipeline_schedule == "1f1b"):
        # the SOP pooler reads hidden[:, 0], which is cp-LOCAL inside the
        # 1F1B shard_map (each cp rank holds a seq chunk) and the engine
        # psums the loss over cp — the CLS term would be multiply-counted
        # from garbage tokens. GPipe runs the head outside the shard_map on
        # the full sequence, so it composes fine.
        raise ValueError(
            "BERT pipeline parallelism with context_parallel_size > 1 "
            "requires pipeline_schedule='gpipe' (the 1F1B head is cp-local)"
        )
    pipe_batch = {
        "tokens": batch["text"],
        "labels": batch["labels"],
        "loss_mask": batch["loss_mask"],
        # segment 0 = real tokens, 1 = padding: attention() blocks
        # cross-segment pairs, reproducing padding_bias for real rows
        "segment_ids": 1 - batch["padding_mask"].astype(jnp.int32),
    }
    if batch.get("types") is not None:
        pipe_batch["types"] = batch["types"]
    if batch.get("is_random") is not None:
        pipe_batch["is_random"] = batch["is_random"]

    mlm_denom = jnp.maximum(batch["loss_mask"].astype(jnp.float32).sum(), 1.0)
    gbs = batch["text"].shape[0]

    def embed_fn(outer_p, tok, aux, ke):
        # no embedding dropout: matches bert_forward (the pp=1 path), so
        # pipeline_model_parallel_size does not change regularization
        return embed_tokens(cfg, outer_p, tok, tokentype_ids=aux.get("types"))

    def head_loss_fn(outer_p, hidden, lbl, msk, aux):
        hidden = norm(hidden, outer_p["final_norm"], m.layernorm_epsilon,
                      m.use_rms_norm)
        lm_logits = mlm_head_logits(cfg, outer_p, hidden)
        per_token = softmax_cross_entropy(lm_logits, lbl)
        loss = (per_token * msk.astype(jnp.float32)).sum() / mlm_denom
        if m.bert_binary_head and "is_random" in aux:
            binary_logits = binary_head_logits(cfg, outer_p, hidden)
            logp = jax.nn.log_softmax(binary_logits.astype(jnp.float32), -1)
            sop_sum = -jnp.take_along_axis(
                logp, aux["is_random"][:, None].astype(jnp.int32), axis=-1
            ).sum()
            loss = loss + sop_sum / gbs
        return loss

    return pipe_batch, embed_fn, head_loss_fn


def bert_loss_from_batch(cfg, params, batch: Dict[str, jax.Array], *,
                         dropout_key=None, deterministic=True,
                         rope_cache=None, sp_constraint=None):
    """pretrain_bert.py loss: masked-LM CE over masked positions + binary
    sentence-order CE (forward_step at pretrain_bert.py:40-80)."""
    lm_logits, binary_logits = bert_forward(
        cfg, params, batch["text"], batch["padding_mask"],
        tokentype_ids=batch.get("types"),
        dropout_key=dropout_key, deterministic=deterministic,
    )
    per_token = softmax_cross_entropy(lm_logits, batch["labels"])
    mask = batch["loss_mask"].astype(jnp.float32)
    lm_loss = (per_token * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    metrics = {"lm loss": lm_loss}
    loss = lm_loss
    if binary_logits is not None and "is_random" in batch:
        logp = jax.nn.log_softmax(binary_logits.astype(jnp.float32), axis=-1)
        sop = -jnp.take_along_axis(
            logp, batch["is_random"][:, None].astype(jnp.int32), axis=-1
        ).mean()
        metrics["sop loss"] = sop
        loss = loss + sop
    metrics["loss"] = loss
    return loss, metrics
