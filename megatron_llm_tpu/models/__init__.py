from megatron_llm_tpu.models.families import make_config, validate_family
from megatron_llm_tpu.models.language_model import (
    init_model_params,
    make_rope_cache,
    model_forward,
    loss_from_batch,
    padded_vocab_size,
)
