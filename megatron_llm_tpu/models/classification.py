"""Sequence classification and multiple-choice heads over the BERT backbone.

Reference: megatron/model/classification.py (Classification:~30 — BERT
backbone + pooler + dropout + [h, num_classes] head) and
megatron/model/multiple_choice.py (MultipleChoice — flatten [b, choices, s],
score each choice with a [h, 1] head). Used by the tasks/ harness (GLUE,
RACE finetuning, tasks/finetune_utils.py:309).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from megatron_llm_tpu.models.bert import padding_bias
from megatron_llm_tpu.models.language_model import (
    embed_tokens,
    init_model_params,
)
from megatron_llm_tpu.models.transformer import transformer_forward
from megatron_llm_tpu.ops.norms import norm

Params = Dict[str, Any]


def init_classification_params(cfg, key: jax.Array, num_classes: int) -> Params:
    """BERT backbone + pooler + classification head (classification.py)."""
    m = cfg.model
    params = init_model_params(cfg, key)
    h = m.hidden_size
    k1, k2 = jax.random.split(jax.random.fold_in(key, 13))
    std = m.init_method_std
    params["pooler"] = {
        "kernel": std * jax.random.normal(k1, (h, h), jnp.float32),
        "bias": jnp.zeros((h,), jnp.float32),
    }
    params["classification_head"] = {
        "kernel": std * jax.random.normal(k2, (h, num_classes), jnp.float32),
        "bias": jnp.zeros((num_classes,), jnp.float32),
    }
    return params


def _pooled(cfg, params, tokens, padding_mask, tokentype_ids,
            dropout_key, deterministic):
    m = cfg.model
    hidden = embed_tokens(cfg, params, tokens, tokentype_ids=tokentype_ids)
    hidden, _, _moe_aux = transformer_forward(
        cfg, params["layers"], hidden,
        attn_bias=padding_bias(padding_mask),
        dropout_key=dropout_key, deterministic=deterministic,
    )
    hidden = norm(hidden, params["final_norm"], m.layernorm_epsilon,
                  m.use_rms_norm)
    return jnp.tanh(
        hidden[:, 0] @ params["pooler"]["kernel"].astype(hidden.dtype)
        + params["pooler"]["bias"].astype(hidden.dtype)
    )


def classification_forward(
    cfg,
    params: Params,
    tokens: jax.Array,        # [b, s]
    padding_mask: jax.Array,  # [b, s]
    tokentype_ids: Optional[jax.Array] = None,
    dropout_key: Optional[jax.Array] = None,
    deterministic: bool = True,
) -> jax.Array:
    """Returns class logits [b, num_classes]."""
    pooled = _pooled(cfg, params, tokens, padding_mask, tokentype_ids,
                     dropout_key, deterministic)
    head = params["classification_head"]
    return (pooled @ head["kernel"].astype(pooled.dtype)
            + head["bias"].astype(pooled.dtype)).astype(jnp.float32)


def multiple_choice_forward(
    cfg,
    params: Params,
    tokens: jax.Array,        # [b, num_choices, s]
    padding_mask: jax.Array,  # [b, num_choices, s]
    tokentype_ids: Optional[jax.Array] = None,
    dropout_key: Optional[jax.Array] = None,
    deterministic: bool = True,
) -> jax.Array:
    """Score every choice with the [h, 1] head; returns [b, num_choices]
    (multiple_choice.py flatten-and-score)."""
    b, c, s = tokens.shape
    flat = lambda x: None if x is None else x.reshape(b * c, s)
    logits = classification_forward(
        cfg, params, flat(tokens), flat(padding_mask), flat(tokentype_ids),
        dropout_key, deterministic,
    )  # [b*c, 1]
    return logits.reshape(b, c)


def classification_loss_from_batch(cfg, params, batch, *, dropout_key=None,
                                   deterministic=True, rope_cache=None,
                                   sp_constraint=None):
    """CE over class logits; batch keys text/types/padding_mask/label
    (finetune_utils.py _cross_entropy_forward_step)."""
    if batch["text"].ndim == 3:
        logits = multiple_choice_forward(
            cfg, params, batch["text"], batch["padding_mask"],
            batch.get("types"), dropout_key, deterministic,
        )
    else:
        logits = classification_forward(
            cfg, params, batch["text"], batch["padding_mask"],
            batch.get("types"), dropout_key, deterministic,
        )
    logp = jax.nn.log_softmax(logits, axis=-1)
    labels = batch["label"].astype(jnp.int32)
    loss = -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()
    acc = (jnp.argmax(logits, -1) == labels).astype(jnp.float32).mean()
    return loss, {"lm loss": loss, "accuracy": acc}
