"""Stream event shapes + the SSE wire encoding shared by all three tiers.

The wire format is plain Server-Sent Events (one ``event:`` line, one
``data:`` line holding a JSON object, a blank line):

* ``event: token`` — ``{"tokens": [...], "text": "...", "logprobs":
  [...]}``: one freshly-applied token batch (chained dispatch retires
  several per flush, so a single event may carry several tokens).
* ``event: dropped`` — ``{"dropped_events": n}``: the consumer fell
  behind the bounded emission queue and *incremental* events were shed;
  the terminal ``done`` body is still complete (drop-to-terminal).
* ``event: done`` — the full buffered-response body (``{"text",
  "segments", "logprobs", "timing"}``): byte-identical to what the same
  request would have returned with ``"stream": false``.
* ``event: error`` — ``{"error": msg, ...}``: structured terminal
  failure (engine error, shed, or mid-stream replica death at the
  router).  A well-formed stream ALWAYS ends in ``done`` or ``error``;
  an EOF without one is a truncation (``sse_scan_terminal``).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, Iterable, Iterator, List, Tuple

__all__ = [
    "SSE_CONTENT_TYPE",
    "StreamEvent",
    "iter_sse_events",
    "parse_sse",
    "sse_encode",
    "sse_scan_terminal",
]

SSE_CONTENT_TYPE = "text/event-stream"

# terminal markers at line starts — ``data:`` payloads are single-line
# JSON (json.dumps escapes newlines), so a raw b"\nevent: " can only be
# a real SSE field line, never generated text
_TERMINAL_MARKERS = (b"\nevent: done\n", b"\nevent: error\n")
# longest marker, minus one: how much stream tail must be re-scanned so
# a marker split across two chunks is still seen
SSE_TAIL_KEEP = max(len(m) for m in _TERMINAL_MARKERS) - 1


@dataclasses.dataclass
class StreamEvent:
    """One emission-queue entry (engine tier; the SSE lines are the
    serialized form the replica tier writes)."""

    kind: str  # "token" | "done" | "error"
    tokens: List[int] = dataclasses.field(default_factory=list)
    log_probs: List[float] = dataclasses.field(default_factory=list)
    data: Dict = dataclasses.field(default_factory=dict)

    @property
    def terminal(self) -> bool:
        return self.kind in ("done", "error")


def sse_encode(event: str, data: dict) -> bytes:
    """One SSE frame: ``event:`` + single-line JSON ``data:`` + blank."""
    return (f"event: {event}\ndata: {json.dumps(data)}\n\n").encode()


def sse_scan_terminal(tail: bytes, chunk: bytes) -> Tuple[bool, bytes]:
    """Incremental terminal detection for a pass-through proxy.

    Feed each forwarded chunk with the ``tail`` returned by the previous
    call (start with ``b"\\n"`` so a marker at byte 0 matches); returns
    ``(saw_terminal, new_tail)``.  Once a terminal frame has been seen
    the stream may legally EOF; an EOF before that is a truncation."""
    buf = tail + chunk
    seen = any(m in buf for m in _TERMINAL_MARKERS)
    return seen, buf[-SSE_TAIL_KEEP:] if len(buf) > SSE_TAIL_KEEP else buf


def parse_sse(raw: bytes) -> List[Tuple[str, dict]]:
    """Decode a complete SSE byte stream into ``(event, data)`` pairs —
    the client-side helper tests and bench_decode use.  Frames with
    undecodable data become ``(event, {"raw": ...})`` rather than
    raising: a truncated final frame must not mask the truncation."""
    out: List[Tuple[str, dict]] = []
    for frame in raw.split(b"\n\n"):
        if not frame.strip():
            continue
        event, data = "message", None
        for line in frame.split(b"\n"):
            if line.startswith(b"event: "):
                event = line[len(b"event: "):].decode(errors="replace")
            elif line.startswith(b"data: "):
                try:
                    data = json.loads(line[len(b"data: "):])
                except ValueError:
                    data = {"raw": line[len(b"data: "):].decode(
                        errors="replace")}
        out.append((event, data if isinstance(data, dict) else {}))
    return out


def iter_sse_events(chunks: Iterable[bytes]) -> Iterator[Tuple[str, dict]]:
    """Incremental variant of :func:`parse_sse`: yields each complete
    frame as soon as its blank-line delimiter arrives (what a live
    streaming client wants; ``parse_sse`` needs the whole body)."""
    buf = b""
    for chunk in chunks:
        buf += chunk
        while b"\n\n" in buf:
            frame, _, buf = buf.partition(b"\n\n")
            for pair in parse_sse(frame + b"\n\n"):
                yield pair
    if buf.strip():
        for pair in parse_sse(buf):
            yield pair
