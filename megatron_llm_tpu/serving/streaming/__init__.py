"""Token streaming: the per-request emission path from engine to client.

Three tiers share this package's wire shapes (ISSUE 18):

* **Engine tier** — ``ContinuousBatchingEngine.submit_stream`` attaches a
  :class:`StreamQueue` to the request at enqueue time; the chained /
  speculative / depth-0 apply paths publish freshly-retired token batches
  into it under the engine lock, and ``_retire``/``_fail_locked``/
  ``_shed_locked`` publish the terminal event (carrying the flight-record
  timing payload).  The queue is bounded and never blocks the publisher:
  a slow consumer loses *incremental* events (counted, surfaced in the
  terminal event) but always receives the terminal — drop-to-terminal,
  never engine backpressure.
* **Replica tier** — ``MegatronServer`` turns the queue into an SSE
  response for ``"stream": true`` requests (``event: token`` per batch,
  ``event: done`` carrying the exact buffered-response body, ``event:
  error`` on failure), flushing the first byte the moment the
  ``X-MLT-TTFT-S`` stamp says the token existed.
* **Router tier** — ``ForwardingProxy.forward_stream`` pumps the bytes
  through verbatim, failing over only during the connect phase and
  replacing a mid-stream replica death with a structured terminal
  ``error`` event (``sse_scan_terminal`` is how it knows a stream ended
  without one).

Guide: docs/guide/serving.md "Streaming".
"""

from megatron_llm_tpu.serving.streaming.events import (  # noqa: F401
    SSE_CONTENT_TYPE,
    StreamEvent,
    parse_sse,
    sse_encode,
    sse_scan_terminal,
)
from megatron_llm_tpu.serving.streaming.queue import StreamQueue  # noqa: F401
