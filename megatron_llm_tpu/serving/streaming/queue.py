"""StreamQueue: the bounded per-request emission queue (engine tier).

One queue per ``submit_stream`` request.  The publisher side
(``publish_tokens`` / ``publish_terminal``) is called by the engine's
apply/retire paths WHILE HOLDING ``ContinuousBatchingEngine._lock`` — so
it must never block and never acquire anything beyond this queue's own
leaf lock (lock-order edge ``ContinuousBatchingEngine._lock ->
StreamQueue._lock``, committed in tools/graftcheck/lockorder.json; the
same discipline as the engine→FlightRecorder edge).

Overflow policy is drop-to-terminal: a consumer that falls behind the
bounded queue loses *incremental* token events (counted, reported in the
terminal event's ``dropped_events``), but the terminal event is always
accepted — the tick loop never waits on a slow HTTP client, and the
client always learns how the request ended.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Iterator, Optional, Sequence

from megatron_llm_tpu.serving.streaming.events import StreamEvent

__all__ = ["StreamQueue"]


class StreamQueue:
    """Bounded single-producer single-consumer event queue."""

    def __init__(self, maxsize: int = 256):
        assert maxsize >= 1
        self.maxsize = maxsize
        self._lock = threading.Lock()
        self._ready = threading.Condition(self._lock)
        self._events = collections.deque()  # guarded by _lock
        self._terminal: Optional[StreamEvent] = None  # guarded by _lock
        self._terminal_taken = False  # guarded by _lock
        self._dropped = 0  # incremental events shed — guarded by _lock
        self._abandoned = False  # consumer gone — guarded by _lock

    # ---- publisher side (engine, holding its own _lock) -----------------
    # Method names are deliberately unique repo-wide (not `put`/`close`):
    # the engine reaches the queue through an untyped `req._stream`, so
    # graftcheck's lock-order pass resolves these calls by name.

    def publish_tokens(self, tokens: Sequence[int],
                       log_probs: Optional[Sequence[float]] = None) -> int:
        """Append one incremental token batch; NEVER blocks.  Returns the
        number of events shed by this call (0 or 1) so the engine can
        bump ``mlt_engine_stream_dropped_events_total``."""
        with self._ready:
            if self._terminal is not None:
                return 1  # post-terminal publish: late, count as shed
            if self._abandoned or len(self._events) >= self.maxsize:
                self._dropped += 1
                return 1
            self._events.append(StreamEvent(
                "token", tokens=list(tokens),
                log_probs=list(log_probs or [])))
            self._ready.notify()
            return 0

    def publish_terminal(self, event: StreamEvent) -> None:
        """Deliver the terminal event; always accepted (first one wins).
        Stamps the running drop count into the event so the consumer can
        tell a complete incremental stream from a shed one."""
        assert event.terminal, event.kind
        with self._ready:
            if self._terminal is None:
                event.data.setdefault("dropped_events", self._dropped)
                self._terminal = event
            self._ready.notify_all()

    # ---- consumer side (HTTP handler thread / bench client) -------------

    def next_event(self, timeout: Optional[float] = None
                   ) -> Optional[StreamEvent]:
        """Block for the next event.  The terminal event is returned
        exactly once, after every queued incremental event; afterwards
        (or on timeout) returns None."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._ready:
            while True:
                if self._abandoned:
                    return None  # abandon() wakes and dries the consumer
                if self._events:
                    return self._events.popleft()
                if self._terminal is not None:
                    if self._terminal_taken:
                        return None
                    self._terminal_taken = True
                    return self._terminal
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._ready.wait(remaining):
                        return None
                else:
                    self._ready.wait()

    def iter_events(self, timeout: Optional[float] = None
                    ) -> Iterator[StreamEvent]:
        """Yield events until (and including) the terminal one.  A
        ``timeout`` bounds each *gap* between events, not the total."""
        while True:
            ev = self.next_event(timeout=timeout)
            if ev is None:
                return
            yield ev
            if ev.terminal:
                return

    def abandon(self) -> None:
        """Consumer walked away (client disconnect): future publishes
        are shed immediately instead of filling a queue nobody reads."""
        with self._ready:
            self._abandoned = True
            self._events.clear()
            self._ready.notify_all()

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped
