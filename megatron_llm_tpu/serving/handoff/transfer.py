"""Push client for cross-replica KV page transfer.

The prefill replica calls :func:`push_pages` after exporting a
request's pages: one ``POST {target}/admin/kv_push`` carrying the wire
blob (serving/handoff/wire.py), the trace id riding the same
``X-MLT-Trace-Id`` header every other tier uses.  The decode replica
answers with a JSON import receipt (pages installed / deduped), or an
error status this module maps onto :class:`KVPushError` — a 503 keeps
the replica's ``Retry-After`` so the caller can degrade to unified
serving with an honest backoff.

Lock discipline matches the rest of the serving tier (graftcheck's
lock rules + lockorder.json): :class:`HandoffStats` is a leaf lock —
it never calls out while held, so it can be taken under any engine or
server lock without ordering risk.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
from typing import Dict, Optional

__all__ = ["HandoffStats", "KVPushError", "STATS", "push_pages"]


class KVPushError(RuntimeError):
    """A KV push that did not install pages on the target.

    ``status`` is the HTTP status when the target answered (None for
    connect/transport failures); ``retry_after`` carries the target's
    backoff hint when it said 503 (pool pressure is transient — the
    router falls back to unified serving rather than queueing the
    hop)."""

    def __init__(self, msg: str, status: Optional[int] = None,
                 retry_after: Optional[float] = None):
        super().__init__(msg)
        self.status = status
        self.retry_after = retry_after


class HandoffStats:
    """Process-wide push accounting (a leaf lock; see module doc)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.pushes = 0        # completed pushes — guarded by _lock
        self.failures = 0      # raised pushes — guarded by _lock
        self.pages_sent = 0    # pages installed or deduped — guarded by _lock
        self.bytes_sent = 0    # wire bytes shipped — guarded by _lock

    def note_push(self, pages: int, nbytes: int) -> None:
        with self._lock:
            self.pushes += 1
            self.pages_sent += int(pages)
            self.bytes_sent += int(nbytes)

    def note_failure(self) -> None:
        with self._lock:
            self.failures += 1

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {
                "pushes": self.pushes,
                "failures": self.failures,
                "pages_sent": self.pages_sent,
                "bytes_sent": self.bytes_sent,
            }


STATS = HandoffStats()


def push_pages(target_url: str, blob: bytes, *, trace_id: str = "",
               timeout_s: float = 60.0,
               stats: Optional[HandoffStats] = None) -> dict:
    """POST a handoff blob to ``{target_url}/admin/kv_push``.

    Returns the decode replica's import receipt (parsed JSON).  Raises
    :class:`KVPushError` on any failure; the caller decides whether to
    fall back (router) or surface it (tests)."""
    stats = STATS if stats is None else stats
    url = target_url.rstrip("/") + "/admin/kv_push"
    req = urllib.request.Request(
        url, data=blob, method="POST",
        headers={"Content-Type": "application/octet-stream",
                 "X-MLT-Trace-Id": trace_id})
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            receipt = json.loads(resp.read().decode("utf-8"))
    except urllib.error.HTTPError as e:
        stats.note_failure()
        retry_after = None
        try:
            body = json.loads(e.read().decode("utf-8"))
            retry_after = body.get("retry_after")
            detail = body.get("error") or body.get("message") or ""
        except Exception:  # noqa: BLE001 — error body is best-effort
            detail = ""
        raise KVPushError(
            f"kv_push to {url} failed: HTTP {e.code} {detail}".rstrip(),
            status=e.code, retry_after=retry_after) from e
    except Exception as e:  # noqa: BLE001 — transport/connect failures
        stats.note_failure()
        raise KVPushError(f"kv_push to {url} failed: {e}") from e
    stats.note_push(int(receipt.get("pages", 0)), len(blob))
    return receipt
