"""Length-prefixed wire format for exported KV pages.

A handoff blob carries a request's page-aligned prompt KV exactly as
it sits in the sending pool: the storage leaves byte for byte (bf16
values, or int8/fp8 values plus their float32 per-page scale rows, plus
the draft-model leaves when the sender speculates).  The receiver
installs the bytes verbatim — **never** re-quantizes — so a migrated
page is bit-identical to the page the sender prefilled, and the
decode replica's bitwise chunked-prefill contract extends across the
hop (tests/test_handoff.py round-trips every kv_dtype).

Layout (all integers little-endian):

=========  ==============================================================
bytes      content
=========  ==============================================================
8          magic ``b"MLTKV1\\0\\n"``
8          u64 — JSON header length ``H``
H          UTF-8 JSON header: ``{"version", "kv_dtype", "page_size",
           "tokens", "leaves": [{"name", "dtype", "shape"}, ...]}``
per leaf   u64 byte length, then the leaf's raw C-order bytes, in
           header order
=========  ==============================================================

``tokens`` is the page-aligned token prefix the pages hold (length ==
``n_pages * page_size``) — the receiving :class:`PrefixCache` keys its
trie nodes on exactly these ids.  Leaf names are the pool attributes
(``k``/``v``/``draft_k``/``draft_v``), with ``.q`` / ``.scale``
suffixes for quantized containers; every leaf's page axis is axis 1
(``[L, n_pages, ...]``).
"""

from __future__ import annotations

import json
import struct
from typing import Dict, List, NamedTuple, Sequence

import ml_dtypes
import numpy as np

MAGIC = b"MLTKV1\0\n"
_U64 = struct.Struct("<Q")

# dtype names that appear on the wire; ml_dtypes (a jax dependency)
# registers the non-standard ones with numpy
_EXTENDED_DTYPES = {
    "bfloat16": ml_dtypes.bfloat16,
    "float8_e4m3fn": ml_dtypes.float8_e4m3fn,
    "float8_e5m2": ml_dtypes.float8_e5m2,
}


def _np_dtype(name: str) -> np.dtype:
    return np.dtype(_EXTENDED_DTYPES.get(name, name))


class HandoffPayload(NamedTuple):
    """A decoded handoff blob: the trie key tokens + the raw leaves."""

    tokens: List[int]
    page_size: int
    kv_dtype: str
    leaves: Dict[str, np.ndarray]

    @property
    def n_pages(self) -> int:
        return len(self.tokens) // self.page_size if self.page_size else 0


def encode_pages(tokens: Sequence[int], page_size: int, kv_dtype: str,
                 leaves: Dict[str, np.ndarray]) -> bytes:
    """Serialize exported page leaves into one handoff blob.

    ``tokens`` must be page-aligned (the full pages' token ids) and
    every leaf's page axis (axis 1) must hold ``len(tokens) //
    page_size`` pages — the invariants the receiver's trie insert
    depends on, checked here so a malformed export fails at the sender.
    """
    tokens = [int(t) for t in tokens]
    if page_size <= 0:
        raise ValueError("page_size must be positive")
    if len(tokens) % page_size != 0:
        raise ValueError(
            f"tokens not page-aligned: {len(tokens)} ids, page {page_size}")
    n_pages = len(tokens) // page_size
    header = {
        "version": 1,
        "kv_dtype": str(kv_dtype),
        "page_size": int(page_size),
        "tokens": tokens,
        "leaves": [],
    }
    blocks: List[bytes] = []
    for name, arr in leaves.items():
        arr = np.ascontiguousarray(arr)
        if arr.ndim < 2 or arr.shape[1] != n_pages:
            raise ValueError(
                f"leaf {name!r} holds {arr.shape[1] if arr.ndim > 1 else 0} "
                f"pages on axis 1, expected {n_pages}")
        header["leaves"].append({
            "name": str(name),
            "dtype": str(arr.dtype),
            "shape": [int(s) for s in arr.shape],
        })
        blocks.append(arr.tobytes())
    hj = json.dumps(header, separators=(",", ":")).encode("utf-8")
    out = [MAGIC, _U64.pack(len(hj)), hj]
    for b in blocks:
        out.append(_U64.pack(len(b)))
        out.append(b)
    return b"".join(out)


def decode_pages(blob: bytes) -> HandoffPayload:
    """Parse a handoff blob back into its token key + leaf arrays.

    Every structural claim the header makes (magic, version, lengths,
    per-leaf shape x dtype vs. block size) is validated before any
    array is built — the decode replica calls this on bytes from the
    network."""
    if len(blob) < len(MAGIC) + _U64.size or blob[:len(MAGIC)] != MAGIC:
        raise ValueError("not a KV handoff blob (bad magic)")
    off = len(MAGIC)
    (hlen,) = _U64.unpack_from(blob, off)
    off += _U64.size
    if off + hlen > len(blob):
        raise ValueError("truncated handoff header")
    header = json.loads(blob[off:off + hlen].decode("utf-8"))
    off += hlen
    if header.get("version") != 1:
        raise ValueError(f"unsupported handoff version {header.get('version')}")
    page_size = int(header["page_size"])
    tokens = [int(t) for t in header["tokens"]]
    if page_size <= 0 or len(tokens) % page_size != 0:
        raise ValueError("handoff header tokens not page-aligned")
    n_pages = len(tokens) // page_size
    leaves: Dict[str, np.ndarray] = {}
    for spec in header["leaves"]:
        if off + _U64.size > len(blob):
            raise ValueError("truncated handoff leaf table")
        (blen,) = _U64.unpack_from(blob, off)
        off += _U64.size
        if off + blen > len(blob):
            raise ValueError(f"truncated handoff leaf {spec.get('name')!r}")
        dtype = _np_dtype(spec["dtype"])
        shape = tuple(int(s) for s in spec["shape"])
        if len(shape) < 2 or shape[1] != n_pages:
            raise ValueError(
                f"leaf {spec.get('name')!r} shape {shape} does not hold "
                f"{n_pages} pages on axis 1")
        expect = int(np.prod(shape)) * dtype.itemsize
        if expect != blen:
            raise ValueError(
                f"leaf {spec.get('name')!r}: {blen} bytes on the wire, "
                f"shape x dtype needs {expect}")
        arr = np.frombuffer(blob, dtype=dtype, count=int(np.prod(shape)),
                            offset=off).reshape(shape)
        leaves[str(spec["name"])] = arr
        off += blen
    if off != len(blob):
        raise ValueError(f"{len(blob) - off} trailing bytes in handoff blob")
    return HandoffPayload(tokens=tokens, page_size=page_size,
                          kv_dtype=str(header["kv_dtype"]), leaves=leaves)
