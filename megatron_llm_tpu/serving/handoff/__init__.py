"""Cross-replica KV page handoff (ISSUE 19).

Disaggregated prefill/decode serving splits a request across two
replicas: a prefill-role replica runs chunked prefill, exports the
prompt's full KV pages (quantized bytes + per-page scale rows + draft
KV when speculating) and pushes them to a decode-role replica, which
installs them as a :class:`~megatron_llm_tpu.generation.engine.PrefixCache`
insert — a migrated prefix is indistinguishable from a locally-cached
one, so COW / refcount / eviction invariants hold unchanged.

* :mod:`wire` — the length-prefixed wire format (:func:`encode_pages`
  / :func:`decode_pages`); byte-exact round-trip for every kv_dtype.
* :mod:`transfer` — the push client (:func:`push_pages` →
  ``POST /admin/kv_push``) and its lock-disciplined stats.

Routing lives in ``serving/router`` (the ``disagg`` policy); the
replica endpoints in ``generation/server.py``.
"""

from megatron_llm_tpu.serving.handoff.wire import (
    HandoffPayload,
    decode_pages,
    encode_pages,
)
from megatron_llm_tpu.serving.handoff.transfer import (
    STATS,
    HandoffStats,
    KVPushError,
    push_pages,
)

__all__ = [
    "HandoffPayload",
    "HandoffStats",
    "KVPushError",
    "STATS",
    "decode_pages",
    "encode_pages",
    "push_pages",
]
