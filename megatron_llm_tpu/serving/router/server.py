"""RouterServer: the HTTP tier gluing registry + policy + proxy together.

Endpoints (one port, same layout as the replica server so dashboards and
probes point at either tier identically):

* ``PUT /api`` — route + forward.  The body is forwarded verbatim; the
  router only *reads* ``prompts[0]``/``priority``/``ttft_deadline_ms``
  for the routing decision, so the wire contract stays the replica's.
  A ``"stream": true`` body switches to streaming pass-through (ISSUE
  18): the proxy connects (connect-phase failures still fail over),
  relays the replica's SSE bytes verbatim as they arrive, and — once
  the first body byte has been forwarded — NEVER retries.  A replica
  dying mid-stream yields a structured terminal ``event: error`` frame
  (plus a breaker failure record), never a silent truncation.
* ``POST /admin/register`` — elastic replica discovery (ISSUE 18;
  requires ``allow_registration``): replicas started with
  ``--register_url`` heartbeat ``{"replica": url}`` here.  A new url
  is polled synchronously (immediately routable), merged with the
  static fleet, and expires through the same suspect→eject breaker as
  everything else; a restarted replica on a new port simply registers
  the new url.
* ``GET /health`` — fleet summary (per-replica breaker state, view age,
  queue/pages snapshot, restart counts) + router identity.
* ``GET /metrics`` — Prometheus text: per-replica up/queue/pages gauges
  refreshed at scrape time, routing-decision / retry / failover / shed
  counters, per-replica TTFT histograms — **first-token honest** since
  ISSUE 12: each replica stamps its measured server-side first-token
  time into the ``X-MLT-TTFT-S`` response header and the histogram
  observes that, falling back to client-observed time-to-response only
  for replicas that don't stamp it.
* ``GET /debug/requests`` — fleet-aggregated flight records: every
  replica's ``/debug/requests`` (observability/flight.py) keyed by url,
  with ``?trace_id=`` / ``?n=`` passed through.
* ``POST /admin/drain`` / ``POST /admin/undrain`` — operator drain
  (body: ``{"replica": "<url>"}``); the breaker keeps polling a draining
  replica but no new traffic reaches it.

Distributed tracing (ISSUE 12): ``PUT /api`` accepts (or mints) an
``X-MLT-Trace-Id``, threads it through the forwarded request into the
replica's engine, and echoes it in the response — one id correlates the
router's spans, the replica's spans, and both tiers' flight records.

Tracer spans (observability/trace.py): ``router-route`` around the
policy decision, ``router-forward`` per attempt (proxy.py) — both
carrying ``trace_id`` attrs so Perfetto dumps from router and replica
processes correlate into per-request tracks — and ``router-poll`` per
scrape (registry.py).
"""

from __future__ import annotations

import json
import socket
import threading
import time
import urllib.error
import urllib.request
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional
from urllib.parse import parse_qs

from megatron_llm_tpu.observability.registry import get_registry
from megatron_llm_tpu.observability.trace import span
from megatron_llm_tpu.serving.router.admission import (
    AdmissionOverflow,
    AdmissionQueue,
)
from megatron_llm_tpu.serving.router.policy import (
    FleetOverloaded,
    RouteRequest,
    RouterPolicy,
    get_router_policy,
)
from megatron_llm_tpu.serving.router.proxy import (
    ForwardingProxy,
    StreamHandle,
)
from megatron_llm_tpu.serving.router.registry import (
    HealthPoller,
    Replica,
    ReplicaRegistry,
)

__all__ = ["RouterServer"]

# TTFT through a router spans ~ms (warm single-tick) to minutes (cold
# compile on a fresh replica) — wider-than-default buckets on both ends
_TTFT_BUCKETS = (0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0,
                 60.0, 300.0, float("inf"))


class RouterServer:
    """Front N generation-server replicas with one routing policy."""

    def __init__(self, replica_urls: List[str], *,
                 policy: str = "least_loaded",
                 policy_kwargs: Optional[dict] = None,
                 poll_interval: float = 1.0,
                 poll_timeout_s: float = 5.0,
                 max_staleness_s: float = 10.0,
                 suspect_after: int = 1,
                 eject_after: int = 3,
                 forward_timeout_s: float = 300.0,
                 max_retries: int = 2,
                 allow_registration: bool = False,
                 admission_depth: int = 0,
                 admission_limit: int = 0,
                 admission_timeout_s: float = 10.0):
        self.router_id = uuid.uuid4().hex
        self._t_start = time.monotonic()
        self.allow_registration = allow_registration
        self.registry = ReplicaRegistry(
            replica_urls, suspect_after=suspect_after,
            eject_after=eject_after, max_staleness_s=max_staleness_s,
            allow_empty=allow_registration)
        self.policy: RouterPolicy = get_router_policy(policy)(
            **(policy_kwargs or {}))
        self.proxy = ForwardingProxy(
            self.registry, timeout_s=forward_timeout_s,
            max_retries=max_retries)
        self.poller = HealthPoller(
            self.registry, interval=poll_interval,
            timeout_s=poll_timeout_s, on_poll=self._on_poll)
        self._httpd: Optional[ThreadingHTTPServer] = None
        reg = get_registry()
        self._routed = reg.counter(
            "mlt_router_requests_total",
            "requests routed, by policy")
        self._failovers = reg.counter(
            "mlt_router_failovers_total",
            "mid-request replica exclusions after connect failures")
        self._retries = reg.counter(
            "mlt_router_retries_total",
            "Retry-After-honoring retry rounds over saturated replicas")
        self._shed = reg.counter(
            "mlt_router_shed_total",
            "requests 503'd by the router itself (no routable replica / "
            "slo_aware found none feasible)")
        self._poll_failures = reg.counter(
            "mlt_router_poll_failures_total", "failed /health scrapes")
        # disaggregated prefill/decode (ISSUE 19): KV handoff hops the
        # disagg policy inserted before the decode forward, and the ones
        # that failed (the request then fell back to unified serving)
        self._handoffs = reg.counter(
            "mlt_router_handoffs_total",
            "prefill-to-decode KV handoffs completed before forwarding")
        self._handoff_failures = reg.counter(
            "mlt_router_handoff_failures_total",
            "KV handoff attempts that failed; the request fell back to "
            "unified serving on the decode candidate")
        # admission queue (ISSUE 18): depth 0 keeps it off entirely.
        # limit 0 = auto: recomputed from the routable fleet's summed
        # max_slots before each wait, so an elastic fleet growing
        # mid-burst widens admission without a restart.
        self.admission: Optional[AdmissionQueue] = None
        self._admission_auto = admission_limit == 0
        if admission_depth > 0:
            self.admission = AdmissionQueue(
                limit=admission_limit if admission_limit > 0 else 1,
                depth=admission_depth, timeout_s=admission_timeout_s)
        self._m_adm_depth = reg.gauge(
            "mlt_router_admission_queue_depth",
            "requests waiting in the router admission queue")
        self._m_adm_wait = reg.histogram(
            "mlt_router_admission_wait_seconds",
            "seconds a request waited for admission before forwarding")

    # ---- observability hooks -------------------------------------------

    def _on_poll(self, rep: Replica, ok: bool) -> None:
        if not ok:
            self._poll_failures.inc()
        self._publish_replica_gauges(rep)

    def _publish_replica_gauges(self, rep: Replica) -> None:
        reg = get_registry()
        labels = {"replica": rep.url}
        state = rep.state
        reg.gauge("mlt_router_replica_up",
                  "1 = routable (healthy/suspect), 0 = ejected/draining",
                  labels=labels).set(
            1.0 if rep.routable(self.registry.max_staleness_s) else 0.0)
        v = rep.view
        if v is None:
            return
        reg.gauge("mlt_router_replica_queued", labels=labels).set(v.queued)
        reg.gauge("mlt_router_replica_active_slots",
                  labels=labels).set(v.active_slots)
        reg.gauge("mlt_router_replica_pages_cached",
                  labels=labels).set(v.pages_cached)
        reg.gauge("mlt_router_replica_view_age_s", labels=labels).set(
            round(v.age_s(), 3))
        reg.gauge("mlt_router_replica_state_code",
                  "0 healthy / 1 suspect / 2 ejected / 3 draining",
                  labels=labels).set(
            {"healthy": 0, "suspect": 1, "ejected": 2,
             "draining": 3}.get(state, -1))

    def _observe_ttft(self, replica_url: str, seconds: float) -> None:
        # first-token-honest since ISSUE 12: the replica's own
        # X-MLT-TTFT-S stamp when it sends one (router.route falls back
        # to time-to-response only for pre-tracing replicas)
        get_registry().histogram(
            "mlt_router_ttft_seconds",
            "server-reported first-token seconds per replica "
            "(time-to-response fallback for replicas that don't stamp "
            "X-MLT-TTFT-S)",
            labels={"replica": replica_url},
            buckets=_TTFT_BUCKETS).observe(seconds)

    # ---- admission (ISSUE 18) ------------------------------------------

    def admit(self, payload: dict) -> Optional[float]:
        """Gate one request through the admission queue.

        Returns the seconds waited (0.0 when no queue is configured —
        the request is always "admitted" then, but ``admitted_release``
        stays safe to call).  Returns None when the wait timed out, and
        raises :class:`AdmissionOverflow` when the bounded queue is
        full — both map to 503 in the handler."""
        adm = self.admission
        if adm is None:
            return 0.0
        if self._admission_auto:
            views = self.registry.routable_views()
            if views:
                adm.limit = max(1, sum(v.max_slots for v in views))
        deadline = None
        v = payload.get("ttft_deadline_ms")
        if isinstance(v, (int, float)) and not isinstance(v, bool) and v > 0:
            # deadline-aware: never wait past the point where admission
            # alone would blow the caller's TTFT deadline
            deadline = min(adm.timeout_s, float(v) / 1e3)
        try:
            waited = adm.try_admit(deadline)
        finally:
            self._m_adm_depth.set(adm.queued())
        if waited is not None:
            self._m_adm_wait.observe(waited)
        return waited

    def admitted_release(self) -> None:
        adm = self.admission
        if adm is None:
            return
        adm.release()
        self._m_adm_depth.set(adm.queued())

    # ---- elastic discovery (ISSUE 18) ----------------------------------

    def register_replica(self, url: str):
        """``POST /admin/register`` backend: merge ``url`` into the
        fleet.  A first-contact replica is polled synchronously (so it
        is routable before its next heartbeat lands) and handed to the
        running poller; a known url is a heartbeat no-op — liveness is
        the poller's job, not the heartbeat's."""
        rep, added = self.registry.register(url)
        if added:
            self.poller.poll_once(rep)
            self.poller.watch(rep)
            self._publish_replica_gauges(rep)
        return rep, added

    # ---- request handling ----------------------------------------------

    def _maybe_handoff(self, request: RouteRequest, views, candidates,
                       payload: dict, trace_id: str = "") -> bool:
        """Phase-aware prefill hop (ISSUE 19, serving/handoff/).

        Asks the policy's ``prefill_candidates`` hook (only the disagg
        policy has one) whether this request should be prefilled on a
        prefill-role replica first.  When it should, the router sends the
        request there with ``"handoff_to": <decode url>`` — the prefill
        replica runs chunked prefill, exports the KV pages and pushes
        them to the decode candidate — and then lets the normal forward
        proceed: the decode replica finds the prompt trie-hot, so its
        prefill collapses to the refeed token.  The SAME trace id rides
        the hop, the push and the decode forward, and the streamed
        response (with its ``X-MLT-TTFT-S`` stamp) comes from the decode
        replica via the ordinary proxy path — honesty preserved end to
        end.  Any failure is metered and swallowed: the request falls
        back to unified serving on the decode candidate, never half-
        served.  Returns True when the hop completed."""
        picker = getattr(self.policy, "prefill_candidates", None)
        if picker is None or not candidates:
            return False
        try:
            prefill = picker(request, views)
        except Exception:
            return False
        if not prefill:
            return False
        decode_url = candidates[0].url
        pre = next((p for p in prefill if p.url != decode_url), None)
        if pre is None:
            return False  # the decode target IS the only prefill replica
        hop = dict(payload)
        hop.pop("stream", None)
        hop["handoff_to"] = decode_url
        data = json.dumps(hop).encode()
        req = urllib.request.Request(
            pre.url.rstrip("/") + "/api", data=data, method="PUT",
            headers={"Content-Type": "application/json",
                     "X-MLT-Trace-Id": trace_id})
        try:
            with span("router-handoff", trace_id=trace_id,
                      prefill=pre.url, decode=decode_url):
                with urllib.request.urlopen(
                        req, timeout=self.proxy.timeout_s) as resp:
                    receipt = json.loads(resp.read())
        except Exception as e:
            # 5xx from the prefill replica (including a failed push to
            # the decode side) and transport failures land here; the
            # decode forward below still serves the request unified
            self._handoff_failures.inc()
            if not isinstance(e, urllib.error.HTTPError):
                # transport-level failure rides the same breaker as a
                # failed forward, so a dead prefill replica ejects
                # promptly; an HTTP error is an *answer*, not deadness
                self.registry.record_forward_failure(
                    pre.url, f"handoff: {type(e).__name__}: {e}")
            return False
        if not isinstance(receipt, dict) or "handoff" not in receipt:
            self._handoff_failures.inc()
            return False
        self._handoffs.inc()
        return True

    def route(self, payload: dict, body: bytes, trace_id: str = ""):
        """Decide + forward.  Returns (status, body_bytes, headers).

        ``trace_id`` (minted by the HTTP handler when the caller sent no
        ``X-MLT-Trace-Id``) rides the router spans, the forwarded
        request and the response headers — the one id that correlates
        the router's and the serving replica's trace dumps and flight
        records."""
        request = RouteRequest.from_payload(payload)
        views = self.registry.routable_views()
        if not views:
            self._shed.inc()
            fleet = self.registry.summary()["fleet"]
            return 503, json.dumps({
                "error": "no routable replica (fleet: %s)" % fleet,
                "retry_after": 1.0, "fleet": fleet,
            }).encode(), {"Retry-After": "1"}
        try:
            with span("router-route", policy=self.policy.name,
                      trace_id=trace_id):
                candidates = self.policy.order(request, views)
        except FleetOverloaded as fo:
            self._shed.inc()
            return 503, json.dumps({
                "error": str(fo), "retry_after": fo.retry_after,
                "shed": True, **fo.info,
            }).encode(), {"Retry-After": str(max(1, int(fo.retry_after)))}
        self._maybe_handoff(request, views, candidates, payload,
                            trace_id=trace_id)
        t0 = time.monotonic()
        out = self.proxy.forward(
            [v.url for v in candidates], body,
            headers={"X-MLT-Trace-Id": trace_id} if trace_id else None)
        if out.replica_url is not None and out.status == 200:
            # honest TTFT (ISSUE 12): prefer the replica's own
            # first-token stamp over client-observed time-to-response
            self._observe_ttft(out.replica_url,
                               out.ttft_s if out.ttft_s is not None
                               else time.monotonic() - t0)
        self._routed.inc()
        if out.failovers:
            self._failovers.inc(out.failovers)
        if out.retries:
            self._retries.inc(out.retries)
        get_registry().counter(
            "mlt_router_decisions_total",
            "forwards that reached a replica, by policy and replica",
            labels={"policy": self.policy.name,
                    "replica": out.replica_url or "none"}).inc()
        headers = {}
        if trace_id:
            headers["X-MLT-Trace-Id"] = trace_id
        if out.status == 503 and out.retry_after is not None:
            headers["Retry-After"] = str(max(1, int(out.retry_after)))
        return out.status, out.body, headers

    def route_stream(self, payload: dict, body: bytes, trace_id: str = ""):
        """Streaming variant of :meth:`route` (ISSUE 18).

        Same decision phase; the proxy stops after the connect phase.
        Returns a :class:`StreamHandle` (headers arrived, body unread —
        the handler relays bytes via :meth:`pump`) or the usual
        ``(status, body_bytes, headers)`` tuple when no stream opened
        (shed / saturated / terminal replica error)."""
        request = RouteRequest.from_payload(payload)
        views = self.registry.routable_views()
        if not views:
            self._shed.inc()
            fleet = self.registry.summary()["fleet"]
            return 503, json.dumps({
                "error": "no routable replica (fleet: %s)" % fleet,
                "retry_after": 1.0, "fleet": fleet,
            }).encode(), {"Retry-After": "1"}
        try:
            with span("router-route", policy=self.policy.name,
                      trace_id=trace_id):
                candidates = self.policy.order(request, views)
        except FleetOverloaded as fo:
            self._shed.inc()
            return 503, json.dumps({
                "error": str(fo), "retry_after": fo.retry_after,
                "shed": True, **fo.info,
            }).encode(), {"Retry-After": str(max(1, int(fo.retry_after)))}
        self._maybe_handoff(request, views, candidates, payload,
                            trace_id=trace_id)
        t0 = time.monotonic()
        out = self.proxy.forward_stream(
            [v.url for v in candidates], body,
            headers={"X-MLT-Trace-Id": trace_id} if trace_id else None)
        if isinstance(out, StreamHandle):
            self._routed.inc()
            if out.failovers:
                self._failovers.inc(out.failovers)
            if out.retries:
                self._retries.inc(out.retries)
            get_registry().counter(
                "mlt_router_decisions_total",
                "forwards that reached a replica, by policy and replica",
                labels={"policy": self.policy.name,
                        "replica": out.url}).inc()
            # the stream's headers carry the replica's first-token
            # stamp — the client is already receiving bytes by now
            self._observe_ttft(out.url,
                               out.ttft_s if out.ttft_s is not None
                               else time.monotonic() - t0)
            return out
        self._routed.inc()
        if out.failovers:
            self._failovers.inc(out.failovers)
        if out.retries:
            self._retries.inc(out.retries)
        get_registry().counter(
            "mlt_router_decisions_total",
            "forwards that reached a replica, by policy and replica",
            labels={"policy": self.policy.name,
                    "replica": out.replica_url or "none"}).inc()
        headers = {}
        if trace_id:
            headers["X-MLT-Trace-Id"] = trace_id
        if out.status == 503 and out.retry_after is not None:
            headers["Retry-After"] = str(max(1, int(out.retry_after)))
        return out.status, out.body, headers

    def pump(self, handle: StreamHandle, write) -> dict:
        """Relay an accepted stream's body to ``write``; see
        ``ForwardingProxy.pump_stream`` for the truncation contract."""
        return self.proxy.pump_stream(handle, write)

    def health(self) -> dict:
        info = self.registry.summary()
        info.update(
            status="ok",
            role="router",
            router_id=self.router_id,
            policy=self.policy.name,
            uptime_s=round(time.monotonic() - self._t_start, 3),
        )
        return info

    def metrics_text(self) -> str:
        # scrape-time pull, same idiom as the replica server: refresh the
        # per-replica gauges from the registry's live breaker state
        for rep in self.registry.replicas():
            self._publish_replica_gauges(rep)
        return get_registry().render()

    def drain(self, url: str, on: bool) -> bool:
        ok = self.registry.drain(url, on)
        if ok:
            self._publish_replica_gauges(self.registry.get(url))
        return ok

    def debug_requests(self, n: Optional[int] = None,
                       trace_id: Optional[str] = None) -> dict:
        """Fleet-aggregating ``GET /debug/requests``: scrape every
        replica's flight-record endpoint (ejected/draining ones too — a
        request stuck on a sick replica is exactly what an operator is
        hunting) and key the results by replica url.  A replica that
        fails to answer contributes an ``error`` entry, never a router
        failure."""
        qs = []
        if n is not None:
            qs.append(f"n={int(n)}")
        if trace_id:
            qs.append(f"trace_id={trace_id}")
        suffix = "/debug/requests" + ("?" + "&".join(qs) if qs else "")
        fleet = {}
        for rep in self.registry.replicas():
            try:
                with urllib.request.urlopen(
                        rep.url.rstrip("/") + suffix,
                        timeout=self.poller.timeout_s) as resp:
                    fleet[rep.url] = json.loads(resp.read())
            except Exception as e:  # a dead replica must not 500 this
                fleet[rep.url] = {
                    "error": f"{type(e).__name__}: {e}",
                    "state": rep.state,
                }
        return {"role": "router", "router_id": self.router_id,
                "fleet": fleet}

    # ---- HTTP plumbing --------------------------------------------------

    def _make_handler(router):  # noqa: N805 — `router` is the enclosing object
        class Handler(BaseHTTPRequestHandler):
            def _send(self, code: int, data: bytes,
                      content_type="application/json", headers=None):
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)

            def _send_json(self, code: int, body: dict, headers=None):
                self._send(code, json.dumps(body).encode(), headers=headers)

            def _begin_stream(self, code: int, content_type: str,
                              headers=None):
                # streamed write path: no Content-Length (EOF-delimited
                # via Connection: close) + TCP_NODELAY so each relayed
                # SSE frame leaves the socket without Nagle batching
                self.connection.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Connection", "close")
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()

            def _write_chunk(self, data: bytes):
                self.wfile.write(data)
                self.wfile.flush()

            def do_PUT(self):
                if self.path.rstrip("/") != "/api":
                    return self._send_json(404, {"error": "not found"})
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = self.rfile.read(length) or b"{}"
                    payload = json.loads(body)
                except (ValueError, json.JSONDecodeError):
                    return self._send_json(400, {"error": "invalid JSON"})
                if not isinstance(payload, dict):
                    return self._send_json(
                        400, {"error": "request body must be a JSON object"})
                trace_id = (self.headers.get("X-MLT-Trace-Id", "").strip()
                            or uuid.uuid4().hex)
                admitted = False
                try:
                    try:
                        waited = router.admit(payload)
                    except AdmissionOverflow as ao:
                        router._shed.inc()
                        return self._send_json(503, {
                            "error": str(ao),
                            "retry_after": ao.retry_after,
                            "admission_overflow": True,
                        }, headers={
                            "Retry-After": str(max(1, int(ao.retry_after)))})
                    if waited is None:
                        router._shed.inc()
                        return self._send_json(503, {
                            "error": "admission wait timed out "
                                     "(fleet saturated)",
                            "retry_after": 1.0, "shed": True,
                        }, headers={"Retry-After": "1"})
                    admitted = True
                    if payload.get("stream"):
                        return self._route_stream(payload, body, trace_id)
                    try:
                        code, data, headers = router.route(
                            payload, body, trace_id=trace_id)
                    except Exception as e:  # must answer the client
                        return self._send_json(500, {
                            "error":
                                f"router error: {type(e).__name__}: {e}"})
                    return self._send(code, data, headers=headers)
                finally:
                    if admitted:
                        router.admitted_release()

            def _route_stream(self, payload, body, trace_id):
                try:
                    out = router.route_stream(payload, body,
                                              trace_id=trace_id)
                except Exception as e:  # must answer the client
                    return self._send_json(500, {
                        "error": f"router error: {type(e).__name__}: {e}"})
                if not isinstance(out, StreamHandle):
                    code, data, headers = out
                    return self._send(code, data, headers=headers)
                hdrs = {"X-MLT-Trace-Id": trace_id}
                if out.ttft_s is not None:
                    hdrs["X-MLT-TTFT-S"] = str(out.ttft_s)
                try:
                    self._begin_stream(200, out.content_type, headers=hdrs)
                    router.pump(out, self._write_chunk)
                except (BrokenPipeError, ConnectionError, OSError):
                    pass  # client gone; pump already avoided breaker blame

            def do_POST(self):
                path = self.path.rstrip("/")
                if path == "/admin/register":
                    if not router.allow_registration:
                        return self._send_json(403, {
                            "error": "registration disabled (start the "
                                     "router with --allow_registration)"})
                    try:
                        length = int(self.headers.get("Content-Length", 0))
                        payload = json.loads(self.rfile.read(length) or b"{}")
                        url = payload["replica"]
                    except (ValueError, KeyError, json.JSONDecodeError):
                        return self._send_json(
                            400, {"error": 'body must be {"replica": url}'})
                    if not isinstance(url, str) or not url.startswith("http"):
                        return self._send_json(
                            400, {"error": "replica must be an http url"})
                    rep, added = router.register_replica(url)
                    return self._send_json(
                        200, {"replica": url, "state": rep.state,
                              "added": added})
                if path in ("/admin/drain", "/admin/undrain"):
                    try:
                        length = int(self.headers.get("Content-Length", 0))
                        payload = json.loads(self.rfile.read(length) or b"{}")
                        url = payload["replica"]
                    except (ValueError, KeyError, json.JSONDecodeError):
                        return self._send_json(
                            400, {"error": 'body must be {"replica": url}'})
                    if not router.drain(url, on=path.endswith("/drain")):
                        return self._send_json(
                            404, {"error": f"unknown replica {url}"})
                    return self._send_json(
                        200, {"replica": url,
                              "state": router.registry.get(url).state})
                return self.do_PUT()  # /api convenience, replica parity

            def do_GET(self):
                path, _, query = self.path.partition("?")
                path = path.rstrip("/")
                if path == "/health":
                    return self._send_json(200, router.health())
                if path == "/metrics":
                    return self._send(
                        200, router.metrics_text().encode(),
                        "text/plain; version=0.0.4; charset=utf-8")
                if path == "/debug/requests":
                    qs = parse_qs(query)
                    try:
                        n = int(qs["n"][0]) if "n" in qs else None
                    except ValueError:
                        return self._send_json(
                            400, {"error": "n must be an integer"})
                    tid = qs.get("trace_id", [None])[0]
                    return self._send_json(
                        200, router.debug_requests(n=n, trace_id=tid))
                return self._send_json(404, {"error": "not found"})

            def log_message(self, fmt, *args):  # quiet by default
                pass

        return Handler

    def bind(self, host: str = "0.0.0.0", port: int = 0) -> int:
        """Bind (port 0 = ephemeral) and return the bound port."""
        self._httpd = ThreadingHTTPServer((host, port), self._make_handler())
        return self._httpd.server_address[1]

    def serve(self):
        assert self._httpd is not None, "call bind() first"
        self.poller.start()
        self._httpd.serve_forever()

    def start_background(self, host: str = "127.0.0.1", port: int = 0,
                         warm: bool = True) -> int:
        """Bind + poll every replica once synchronously (``warm`` — the
        first request must not race the first poll) + serve in a daemon
        thread; returns the bound port."""
        bound = self.bind(host, port)
        if warm:
            for rep in self.registry.replicas():
                self.poller.poll_once(rep)
        self.poller.start()
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t.start()
        return bound

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        self.poller.stop()
