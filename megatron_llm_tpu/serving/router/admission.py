"""Router-level admission queue (ISSUE 18).

A saturated fleet used to answer a burst with replica 503s (bounded
proxy retries, then ``fleet_saturated``).  The admission queue puts a
bounded FIFO *in front of* the forwarding data plane instead: at most
``limit`` requests are in flight fleet-wide, arrivals beyond that wait
their turn (deadline-aware — a request carrying ``ttft_deadline_ms``
never waits past the point where admission alone would blow its
deadline), and only *queue overflow* is an immediate
:class:`FleetOverloaded`-style 503.  A short burst therefore drains at
the fleet's pace with 0 dropped requests (bench_decode.py --mode
streaming, admission arm).

Fairness is strict FIFO via a deque of per-waiter events; a waiter that
times out unlinks itself, and the grant path (``release``) hands slots
to the queue head.  The lock is a leaf: nothing is called while holding
it (the waiter blocks on its own event OUTSIDE the lock).

Metrics (owned by the RouterServer, which sees the return values):
``mlt_router_admission_queue_depth`` + ``mlt_router_admission_wait_seconds``.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Optional

__all__ = ["AdmissionOverflow", "AdmissionQueue"]


class AdmissionOverflow(Exception):
    """The bounded admission queue is full — the only condition that
    503s immediately (the router's FleetOverloaded analog)."""

    def __init__(self, msg: str, retry_after: float = 1.0, depth: int = 0):
        super().__init__(msg)
        self.retry_after = retry_after
        self.depth = depth


class _Waiter:
    __slots__ = ("event", "granted")

    def __init__(self):
        self.event = threading.Event()
        self.granted = False


class AdmissionQueue:
    """Bounded-FIFO concurrency gate; see the module docstring."""

    def __init__(self, *, limit: int, depth: int,
                 timeout_s: float = 10.0):
        assert limit >= 1 and depth >= 1
        self.limit = limit          # concurrent in-flight forwards
        self.depth = depth          # waiters beyond that before overflow
        self.timeout_s = timeout_s  # default cap on one waiter's wait
        self._lock = threading.Lock()
        self._inflight = 0  # guarded by _lock
        self._waiters = collections.deque()  # guarded by _lock
        self._timeouts = 0  # guarded by _lock
        self._overflows = 0  # guarded by _lock

    def try_admit(self, deadline_s: Optional[float] = None
                  ) -> Optional[float]:
        """Admit one request, waiting FIFO behind earlier arrivals.

        ``deadline_s`` caps THIS request's wait (deadline-aware: the
        handler passes ``min(timeout_s, ttft_deadline)``); None uses the
        queue default.  Returns the seconds waited on admission, or None
        when the wait timed out (the fleet stayed saturated for the
        whole window).  Raises :class:`AdmissionOverflow` when the
        bounded queue itself is full.  Callers MUST ``release()`` after
        the forward completes iff admission succeeded."""
        cap = self.timeout_s if deadline_s is None else deadline_s
        t0 = time.monotonic()
        with self._lock:
            if self._inflight < self.limit and not self._waiters:
                self._inflight += 1
                return 0.0
            if len(self._waiters) >= self.depth:
                self._overflows += 1
                raise AdmissionOverflow(
                    f"admission queue full ({self.depth} waiting)",
                    retry_after=1.0, depth=self.depth)
            w = _Waiter()
            self._waiters.append(w)
        if not w.event.wait(cap):
            with self._lock:
                if w.granted:
                    # granted in the race window between timeout and
                    # unlink: keep the slot, the caller proceeds
                    return time.monotonic() - t0
                try:
                    self._waiters.remove(w)
                except ValueError:
                    pass
                self._timeouts += 1
            return None
        return time.monotonic() - t0

    def release(self) -> None:
        """One in-flight forward finished: hand its slot to the queue
        head (strict FIFO)."""
        with self._lock:
            self._inflight -= 1
            assert self._inflight >= 0, "release() without try_admit()"
            while self._waiters and self._inflight < self.limit:
                w = self._waiters.popleft()
                w.granted = True
                self._inflight += 1
                w.event.set()

    def queued(self) -> int:
        with self._lock:
            return len(self._waiters)

    def stats(self) -> dict:
        with self._lock:
            return {"limit": self.limit, "depth": self.depth,
                    "inflight": self._inflight,
                    "queued": len(self._waiters),
                    "timeouts": self._timeouts,
                    "overflows": self._overflows}
