"""RouterPolicy — the routing-decision interface, mirroring the
SchedulerPolicy shape (generation/scheduling/policy.py): decisions on
immutable snapshots, a name registry behind ``--policy <name>``, and the
mechanisms (forwarding, breaker bookkeeping, metrics) kept out of the
policies entirely.

A policy answers ONE question: given a request and the current routable
:class:`ReplicaView` snapshots, in what order should the proxy try
replicas?  Returning an *ordered list* (not a single choice) is what
makes failover a data-plane mechanism rather than a policy concern — the
proxy walks the list, skipping replicas that fail mid-request.

A policy may instead raise :class:`FleetOverloaded` when, by its own
criteria, no replica should take the request now (slo_aware does this
when no replica's predicted wait meets the TTFT deadline); the router
maps it to a structured 503 carrying the fleet-minimum Retry-After.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Type

from megatron_llm_tpu.serving.router.registry import ReplicaView

__all__ = [
    "FleetOverloaded",
    "RouteRequest",
    "RouterPolicy",
    "available_router_policies",
    "get_router_policy",
    "register_router_policy",
]


class FleetOverloaded(RuntimeError):
    """No replica should take this request right now.

    ``retry_after`` is the fleet-minimum drain estimate (the soonest any
    replica predicts it could serve), ``info`` the per-replica predictions
    behind it — the router serializes both into the 503 body so a client
    sees *why* and *when to come back*, same contract as the single-replica
    EngineOverloaded/RequestShed 503s."""

    def __init__(self, msg: str, retry_after: float = 1.0,
                 info: Optional[dict] = None):
        super().__init__(msg)
        self.retry_after = retry_after
        self.info = info or {}


@dataclasses.dataclass(frozen=True)
class RouteRequest:
    """What a policy may know about a request before forwarding it.

    ``prefix_text`` is the first prompt's text (affinity input);
    ``ttft_deadline_ms``/``priority`` are the scheduling fields the
    replicas already accept (generation/server.py validation)."""

    prefix_text: str = ""
    n_prompts: int = 1
    priority: int = 1
    ttft_deadline_ms: Optional[float] = None
    # disaggregated serving (ISSUE 19): logprobs requests bypass the
    # decode replica's prefix trie, so the disagg policy never spends a
    # prefill hop on them
    logprobs: bool = False

    @staticmethod
    def from_payload(payload: dict) -> "RouteRequest":
        prompts = payload.get("prompts")
        if not isinstance(prompts, list) or not prompts:
            prompts = [""]
        first = prompts[0] if isinstance(prompts[0], str) else ""
        pri = payload.get("priority", 1)
        ttft = payload.get("ttft_deadline_ms")
        return RouteRequest(
            prefix_text=first,
            n_prompts=len(prompts),
            priority=pri if isinstance(pri, int) else 1,
            ttft_deadline_ms=(float(ttft) if isinstance(ttft, (int, float))
                              and not isinstance(ttft, bool) else None),
            logprobs=payload.get("logprobs") is True,
        )


class RouterPolicy:
    """Base policy; subclasses order candidates.  Policies must be
    side-effect free with respect to the fleet — they see snapshots and
    return an order; internal counters (round_robin's cursor) are the only
    state they may keep."""

    name = "base"

    def order(self, request: RouteRequest,
              views: Sequence[ReplicaView]) -> List[ReplicaView]:
        """Routable views in the order the proxy should try them.  ``views``
        arrives in stable fleet order and is never empty (the router
        answers "no healthy replicas" 503s before consulting the policy)."""
        return list(views)


# ---------------------------------------------------------------------------
# Registry (the SchedulerPolicy registration idiom)
# ---------------------------------------------------------------------------

_ROUTER_POLICIES: Dict[str, Type[RouterPolicy]] = {}


def register_router_policy(cls: Type[RouterPolicy]) -> Type[RouterPolicy]:
    """Class decorator: make ``cls`` reachable as --policy <name>."""
    if not cls.name or cls.name == "base":
        raise ValueError("router policy classes must set a unique `name`")
    _ROUTER_POLICIES[cls.name] = cls
    return cls


def get_router_policy(name: str) -> Type[RouterPolicy]:
    try:
        return _ROUTER_POLICIES[name]
    except KeyError:
        raise ValueError(
            f"unknown router policy {name!r}; available: "
            f"{', '.join(sorted(_ROUTER_POLICIES))}") from None


def available_router_policies() -> List[str]:
    return sorted(_ROUTER_POLICIES)
