"""Cross-replica request router (tools/run_router.py fronts it).

Four parts, one module each:

* registry.py — ReplicaView parsing, circuit-breaker lifecycle
  (healthy/suspect/ejected/draining), background /health pollers
* policy.py + policies.py — the RouterPolicy interface and the five
  policies: round_robin, least_loaded, prefix_affinity, slo_aware,
  disagg (phase-aware prefill/decode steering, serving/handoff/)
* proxy.py — the forwarding data plane: timeouts, failover, bounded
  Retry-After-honoring retries, never-retry-partial-streams
* server.py — the HTTP tier: PUT /api, GET /health (fleet summary),
  GET /metrics, POST /admin/drain

Guide: docs/guide/serving.md "Cross-replica routing".
"""

from megatron_llm_tpu.serving.router.policies import (  # noqa: F401
    DisaggPolicy,
    LeastLoadedPolicy,
    PrefixAffinityPolicy,
    RoundRobinPolicy,
    SloAwarePolicy,
    prefix_key,
)
from megatron_llm_tpu.serving.router.policy import (  # noqa: F401
    FleetOverloaded,
    RouteRequest,
    RouterPolicy,
    available_router_policies,
    get_router_policy,
    register_router_policy,
)
from megatron_llm_tpu.serving.router.proxy import (  # noqa: F401
    ForwardingProxy,
    ForwardOutcome,
)
from megatron_llm_tpu.serving.router.registry import (  # noqa: F401
    DRAINING,
    EJECTED,
    HEALTHY,
    SUSPECT,
    HealthPoller,
    Replica,
    ReplicaRegistry,
    ReplicaView,
)
from megatron_llm_tpu.serving.router.server import RouterServer  # noqa: F401

__all__ = [
    "DRAINING",
    "EJECTED",
    "HEALTHY",
    "SUSPECT",
    "DisaggPolicy",
    "FleetOverloaded",
    "ForwardOutcome",
    "ForwardingProxy",
    "HealthPoller",
    "LeastLoadedPolicy",
    "PrefixAffinityPolicy",
    "Replica",
    "ReplicaRegistry",
    "ReplicaView",
    "RoundRobinPolicy",
    "RouteRequest",
    "RouterPolicy",
    "RouterServer",
    "SloAwarePolicy",
    "available_router_policies",
    "get_router_policy",
    "prefix_key",
    "register_router_policy",
]
