"""Forwarding proxy: the router's data plane.

Walks a policy's candidate list forwarding ``PUT /api`` bodies verbatim.
The failure semantics are the whole point:

* **Connect-phase failure** (refused / DNS / timeout before any response
  byte): the replica never saw a parseable request — safe to fail over.
  The failure is reported into the registry breaker
  (``record_forward_failure``) so the data plane ejects a dead replica
  without waiting for the next poll tick, and the replica is excluded
  for the remainder of THIS request.
* **Response-phase failure** (status line received, then the body dies):
  the replica may have executed the generation — a retry would re-run a
  non-idempotent request (burn pages/compute, and for seeded sampling
  produce a second stream).  Never retried: surfaced as a structured 502.
* **503 from a replica** (EngineOverloaded / RequestShed): honored, not
  hammered — the replica's ``Retry-After`` is recorded, the proxy tries
  the next candidate, and only when every candidate is saturated does it
  back off (bounded by ``max_retries`` rounds, sleeping the fleet-minimum
  Retry-After capped at ``backoff_cap_s``) before re-walking the 503'd
  replicas.  Exhaustion returns an aggregated 503 whose Retry-After is
  the fleet minimum.
* **4xx / 200**: terminal either way — forwarded verbatim (a validation
  error on replica A is a validation error on replica B too).
"""

from __future__ import annotations

import http.client
import json
import socket
import time
import urllib.error
import urllib.request
from typing import Callable, List, Optional, Sequence, Tuple

from megatron_llm_tpu.serving.router.registry import ReplicaRegistry
from megatron_llm_tpu.serving.streaming import sse_encode, sse_scan_terminal

__all__ = ["ForwardOutcome", "ForwardingProxy", "StreamHandle"]


class ForwardOutcome:
    """What the router handler needs to answer the client: status, JSON-
    encodable body (or raw bytes), optional Retry-After, the replica that
    answered, and the failure trail for observability."""

    def __init__(self, status: int, body: bytes,
                 replica_url: Optional[str] = None,
                 retry_after: Optional[float] = None,
                 attempts: int = 1,
                 failovers: int = 0,
                 retries: int = 0,
                 ttft_s: Optional[float] = None):
        self.status = status
        self.body = body
        self.replica_url = replica_url
        self.retry_after = retry_after
        self.attempts = attempts
        self.failovers = failovers
        self.retries = retries
        # replica-reported first-token seconds (X-MLT-TTFT-S): the
        # honest TTFT signal; None from pre-tracing replicas
        self.ttft_s = ttft_s


def _err_body(msg: str, **extra) -> bytes:
    return json.dumps({"error": msg, **extra}).encode()


class StreamHandle:
    """An ACCEPTED upstream stream (ISSUE 18): the replica's status line
    and headers arrived — for a streaming replica that means the first
    token exists — but the body is unread.  From this point on the
    request is committed to this replica: ``pump_stream`` relays the
    body and mid-stream death becomes a structured terminal SSE error
    event, never a retry (the never-retry-mid-body rule) and never a
    silent truncation."""

    def __init__(self, resp, url: str, *, content_type: str,
                 ttft_s: Optional[float], attempts: int, failovers: int,
                 retries: int):
        self.resp = resp  # open http response, body unread
        self.url = url
        self.content_type = content_type
        self.ttft_s = ttft_s  # the replica's X-MLT-TTFT-S stamp
        self.attempts = attempts
        self.failovers = failovers
        self.retries = retries


class ForwardingProxy:
    """Forward one request body along a candidate list (see module doc)."""

    def __init__(self, registry: ReplicaRegistry, *,
                 timeout_s: float = 300.0,
                 max_retries: int = 2,
                 backoff_cap_s: float = 5.0,
                 sleep: Callable[[float], None] = time.sleep):
        self.registry = registry
        self.timeout_s = timeout_s
        self.max_retries = max_retries
        self.backoff_cap_s = backoff_cap_s
        self._sleep = sleep  # injectable so tests don't wall-clock wait

    # ---- single attempt -------------------------------------------------

    def _connect(self, url: str, body: bytes,
                 headers: Optional[dict] = None):
        """The connect phase shared by buffered and streamed forwards:
        send the request, classify everything up to (and including) the
        status line + headers.  Returns (kind, status, error_body,
        retry_after, resp): ``resp`` is the OPEN response (body unread)
        iff the replica accepted — every other kind is a pre-body
        failure ('overloaded'/'terminal'/'partial'/'connect_fail') and
        is safe to fail over or forward verbatim."""
        hdrs = {"Content-Type": "application/json"}
        hdrs.update(headers or {})
        req = urllib.request.Request(
            url.rstrip("/") + "/api", data=body,
            headers=hdrs, method="PUT")
        try:
            resp = urllib.request.urlopen(req, timeout=self.timeout_s)
        except urllib.error.HTTPError as e:
            # a status line arrived — the replica spoke; read its body
            # (itself a response-phase read that may die)
            try:
                data = e.read()
            except Exception:
                return ("partial", 502,
                        _err_body(f"replica {url} dropped mid-error-body"),
                        None, None)
            if e.code == 503:
                ra = e.headers.get("Retry-After")
                try:
                    retry_after = float(ra) if ra is not None else None
                except ValueError:
                    retry_after = None
                if retry_after is None:
                    try:
                        retry_after = float(
                            json.loads(data).get("retry_after", 1.0))
                    except (ValueError, AttributeError):
                        retry_after = 1.0
                return ("overloaded", 503, data, retry_after, None)
            return ("terminal", e.code, data, None, None)
        except (urllib.error.URLError, socket.timeout, ConnectionError,
                OSError) as e:
            # no status line: the request never started executing
            return ("connect_fail", 0,
                    _err_body(f"{type(e).__name__}: {e}"), None, None)
        return ("accepted", resp.status, b"", None, resp)

    def _attempt(self, url: str, body: bytes,
                 headers: Optional[dict] = None
                 ) -> Tuple[str, int, bytes, Optional[float],
                            Optional[float]]:
        """One forward to one replica.

        Returns (kind, status, body, retry_after, ttft_s) with kind in
        {'ok', 'overloaded', 'terminal', 'connect_fail', 'partial'};
        ``headers`` (the trace-id propagation path) merge into the
        forwarded request, and ``ttft_s`` is the replica's own
        ``X-MLT-TTFT-S`` first-token stamp when it sent one."""
        kind, status, payload, ra, resp = self._connect(url, body, headers)
        if resp is None:
            return (kind, status, payload, ra, None)
        with resp:
            try:
                data = resp.read()
            except (http.client.IncompleteRead, ConnectionError,
                    socket.timeout, OSError) as e:
                # response-phase death AFTER the replica accepted the
                # request: non-idempotent, never retried (module doc)
                return ("partial", 502,
                        _err_body(
                            f"replica {url} dropped mid-response "
                            f"({type(e).__name__}); not retried — the "
                            f"generation may have executed"), None, None)
            try:
                ttft = float(resp.headers.get("X-MLT-TTFT-S"))
            except (TypeError, ValueError):
                ttft = None
            return ("ok", resp.status, data, None, ttft)

    # ---- candidate walk -------------------------------------------------

    def forward(self, candidate_urls: Sequence[str], body: bytes,
                headers: Optional[dict] = None) -> ForwardOutcome:
        """Walk candidates with failover, then bounded Retry-After-honoring
        retry rounds over the saturated ones.  ``headers`` ride every
        attempt (trace-id propagation: the router's ``X-MLT-Trace-Id``
        reaches whichever replica finally serves the request)."""
        from megatron_llm_tpu.observability.trace import span

        trace_id = (headers or {}).get("X-MLT-Trace-Id", "")
        excluded: set = set()   # connect-failed: out for this request
        attempts = failovers = retries = 0
        saturated: List[Tuple[str, float]] = []
        last_503: Optional[Tuple[bytes, float]] = None

        def walk(urls: Sequence[str]) -> Optional[ForwardOutcome]:
            nonlocal attempts, failovers, last_503
            saturated.clear()
            for url in urls:
                if url in excluded:
                    continue
                attempts += 1
                with span("router-forward", url=url, trace_id=trace_id):
                    kind, status, data, ra, ttft = self._attempt(
                        url, body, headers)
                if kind == "ok" or kind == "terminal":
                    return ForwardOutcome(
                        status, data, replica_url=url, attempts=attempts,
                        failovers=failovers, retries=retries,
                        ttft_s=ttft)
                if kind == "partial":
                    return ForwardOutcome(
                        status, data, replica_url=url, attempts=attempts,
                        failovers=failovers, retries=retries)
                if kind == "connect_fail":
                    excluded.add(url)
                    failovers += 1
                    self.registry.record_forward_failure(
                        url, data.decode(errors="replace"))
                    continue
                # overloaded: remember for the retry rounds
                saturated.append((url, ra if ra is not None else 1.0))
                last_503 = (data, ra if ra is not None else 1.0)
            return None

        out = walk(candidate_urls)
        rounds = 0
        while out is None and saturated and rounds < self.max_retries:
            rounds += 1
            retries += 1
            # honor the fleet-minimum Retry-After (bounded: a router thread
            # sleeping 60s per 503 would be its own outage)
            self._sleep(min(min(ra for _, ra in saturated),
                            self.backoff_cap_s))
            out = walk([u for u, _ in saturated])
        if out is not None:
            return out
        if last_503 is not None:
            data, ra = last_503
            if saturated:  # aggregate: the soonest any replica reopens
                ra = min(r for _, r in saturated)
            try:
                parsed = json.loads(data)
            except ValueError:
                parsed = {"error": "fleet saturated"}
            parsed.setdefault("error", "fleet saturated")
            parsed["fleet_saturated"] = True
            return ForwardOutcome(
                503, json.dumps(parsed).encode(), retry_after=ra,
                attempts=attempts, failovers=failovers, retries=retries)
        return ForwardOutcome(
            502, _err_body("no replica reachable",
                           tried=list(dict.fromkeys(candidate_urls))),
            attempts=attempts, failovers=failovers, retries=retries)

    # ---- streaming pass-through (ISSUE 18) ------------------------------

    def forward_stream(self, candidate_urls: Sequence[str], body: bytes,
                       headers: Optional[dict] = None):
        """Connect phase of a streamed forward: exactly ``forward``'s
        failure semantics — fail over on connect failure, bounded
        Retry-After rounds over saturated replicas, terminal 4xx
        forwarded verbatim — but a replica that ACCEPTS (status line +
        headers, i.e. its first token exists) returns an open
        :class:`StreamHandle` instead of a read body.  From that point
        ``pump_stream`` owns the never-retry-mid-body rule."""
        from megatron_llm_tpu.observability.trace import span

        trace_id = (headers or {}).get("X-MLT-Trace-Id", "")
        excluded: set = set()
        attempts = failovers = retries = 0
        saturated: List[Tuple[str, float]] = []
        last_503: Optional[Tuple[bytes, float]] = None

        def walk(urls: Sequence[str]):
            nonlocal attempts, failovers, last_503
            saturated.clear()
            for url in urls:
                if url in excluded:
                    continue
                attempts += 1
                with span("router-forward-stream", url=url,
                          trace_id=trace_id):
                    kind, status, payload, ra, resp = self._connect(
                        url, body, headers)
                if kind == "accepted":
                    try:
                        ttft = float(resp.headers.get("X-MLT-TTFT-S"))
                    except (TypeError, ValueError):
                        ttft = None
                    return StreamHandle(
                        resp, url,
                        content_type=resp.headers.get(
                            "Content-Type", "text/event-stream"),
                        ttft_s=ttft, attempts=attempts,
                        failovers=failovers, retries=retries)
                if kind in ("terminal", "partial"):
                    return ForwardOutcome(
                        status, payload, replica_url=url, attempts=attempts,
                        failovers=failovers, retries=retries)
                if kind == "connect_fail":
                    excluded.add(url)
                    failovers += 1
                    self.registry.record_forward_failure(
                        url, payload.decode(errors="replace"))
                    continue
                saturated.append((url, ra if ra is not None else 1.0))
                last_503 = (payload, ra if ra is not None else 1.0)
            return None

        out = walk(candidate_urls)
        rounds = 0
        while out is None and saturated and rounds < self.max_retries:
            rounds += 1
            retries += 1
            self._sleep(min(min(ra for _, ra in saturated),
                            self.backoff_cap_s))
            out = walk([u for u, _ in saturated])
        if out is not None:
            return out
        if last_503 is not None:
            data, ra = last_503
            if saturated:
                ra = min(r for _, r in saturated)
            try:
                parsed = json.loads(data)
            except ValueError:
                parsed = {"error": "fleet saturated"}
            parsed.setdefault("error", "fleet saturated")
            parsed["fleet_saturated"] = True
            return ForwardOutcome(
                503, json.dumps(parsed).encode(), retry_after=ra,
                attempts=attempts, failovers=failovers, retries=retries)
        return ForwardOutcome(
            502, _err_body("no replica reachable",
                           tried=list(dict.fromkeys(candidate_urls))),
            attempts=attempts, failovers=failovers, retries=retries)

    def pump_stream(self, handle: StreamHandle,
                    write: Callable[[bytes], None]) -> dict:
        """Relay an accepted stream's body to ``write`` (the router
        handler's flushing chunk writer), enforcing the two streamed
        response-phase guarantees:

        * never retried — the generation is executing on ``handle.url``;
        * never silently truncated — an SSE stream must end in a
          terminal ``done``/``error`` frame (``sse_scan_terminal``
          watches the forwarded bytes), so an upstream death or an EOF
          without one is replaced by a structured terminal ``error``
          frame and reported into the breaker.

        Returns ``{"bytes", "truncated", "error", "client_gone"}``."""
        resp = handle.resp
        is_sse = handle.content_type.startswith("text/event-stream")
        tail = b"\n"
        terminal_seen = not is_sse  # only SSE promises a terminal frame
        n = 0
        error = None
        with resp:
            while True:
                try:
                    chunk = resp.read1(65536)
                except (http.client.IncompleteRead, ConnectionError,
                        socket.timeout, OSError) as e:
                    error = f"{type(e).__name__}: {e}"
                    break
                if not chunk:
                    break
                if not terminal_seen:
                    terminal_seen, tail = sse_scan_terminal(tail, chunk)
                try:
                    write(chunk)
                except OSError:
                    # the CLIENT went away: stop reading, but the
                    # replica did nothing wrong — no breaker record
                    return {"bytes": n, "truncated": False,
                            "error": "client disconnected",
                            "client_gone": True}
                n += len(chunk)
        truncated = error is not None or not terminal_seen
        if truncated:
            self.registry.record_forward_failure(
                handle.url,
                error or f"replica {handle.url} closed its stream "
                         f"without a terminal event")
            if is_sse:
                try:
                    write(sse_encode("error", {
                        "error": f"replica {handle.url} died mid-stream; "
                                 f"not retried — the generation may have "
                                 f"executed",
                        "replica": handle.url,
                        "truncated": True}))
                except OSError:
                    pass  # client is gone too; nothing left to tell
        return {"bytes": n, "truncated": truncated, "error": error,
                "client_gone": False}
