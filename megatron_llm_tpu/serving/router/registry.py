"""Replica registry + health poller + circuit breaker.

The router's picture of the fleet is built entirely from each replica's
``GET /health`` payload (generation/server.py — schema documented in
docs/guide/serving.md "/health payload").  One background poller thread
per replica scrapes it on an interval and parses it into a
:class:`ReplicaView` — an immutable, staleness-tracked snapshot that the
routing policies consume.  Nothing here talks to the data plane; forward
failures are *reported into* the registry by the proxy
(serving/router/proxy.py) and feed the same breaker.

Circuit-breaker lifecycle (per replica)::

    HEALTHY --consecutive failures >= suspect_after--> SUSPECT
    SUSPECT --consecutive failures >= eject_after----> EJECTED
    SUSPECT/EJECTED --successful poll----------------> HEALTHY
    any state --operator drain(True)-----------------> DRAINING (sticky)

SUSPECT replicas still route (their view may just be stale); EJECTED
replicas receive no traffic but keep being probed at a slower cadence
(``recovery_interval``) until a probe succeeds.  DRAINING is an operator
decision (POST /admin/drain on the router): the replica finishes what it
has but gets no new requests, and only an operator undrain brings it
back — poll results never override it.

Restart + reordering detection: a payload whose ``replica_id`` differs
from the last seen one is a replica restart (new process) — the breaker
resets and the per-replica ``seq`` tracking starts over.  A payload with
the *same* ``replica_id`` but ``seq`` <= the last applied one is stale or
reordered (overlapping polls racing) and is discarded rather than
overwriting a fresher view.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

__all__ = [
    "DRAINING",
    "EJECTED",
    "HEALTHY",
    "SUSPECT",
    "HealthPoller",
    "Replica",
    "ReplicaRegistry",
    "ReplicaView",
]

HEALTHY = "healthy"
SUSPECT = "suspect"
EJECTED = "ejected"
DRAINING = "draining"


@dataclasses.dataclass(frozen=True)
class ReplicaView:
    """One parsed ``/health`` payload, frozen at fetch time.

    Policies only ever see these snapshots (never live Replica objects),
    mirroring the SchedulerPolicy/SchedulerState contract from
    generation/scheduling/policy.py: decisions on immutable state, the
    registry applies the consequences under its own locks."""

    url: str
    fetched_at: float               # time.monotonic() when parsed
    replica_id: str = ""
    seq: int = 0
    uptime_s: float = 0.0
    active_slots: int = 0
    max_slots: int = 1
    queued: int = 0
    prefilling: int = 0
    free_pages: int = 0
    total_pages: int = 0
    pages_cached: int = 0
    prefix_hit_tokens: int = 0
    prefix_miss_tokens: int = 0
    page_size: int = 0
    ticks: int = 0
    # quantized paged KV (ISSUE 13): the replica's KV storage mode and
    # byte budget — free_pages on an int8 replica are half-width, so
    # capacity-aware policies compare byte headroom (free_kv_bytes),
    # never raw page counts across mixed-dtype fleets
    kv_dtype: str = "bf16"
    kv_pool_bytes: int = 0
    kv_scale_bytes: int = 0
    # streaming serving tier (ISSUE 18): does this replica serve SSE
    # token streams ("stream": true), and did it start with
    # --register_url (heartbeat-discovered rather than static config)
    streaming: bool = False
    registered: bool = False
    # disaggregated prefill/decode (ISSUE 19): the replica's advertised
    # serving role; the disagg policy steers long prompts prefill-first
    # when the fleet has both roles, and degrades to least_loaded when
    # it doesn't ("unified" is the pre-disagg default)
    role: str = "unified"
    # pipeline-parallel serving (ISSUE 20): the replica's stage count —
    # a pp=4 replica spans 4 chips but drops into the fleet as one
    # opaque /health endpoint; the fields are informational (dashboards,
    # capacity math), not a routing input.  "stages" mirrors "pp".
    pp: int = 1
    stages: int = 1
    # scheduler control-plane payload (engine.scheduler_stats())
    policy: str = ""
    retry_after_s: Optional[float] = None
    ema_tick_s: Optional[float] = None
    ema_retire_s: Optional[float] = None
    # measured submit-to-first-token EMA (ISSUE 12): the replica's real
    # TTFT including queue + prefill — the honest base for slo_aware's
    # wait predictions (the tick EMA only covers one decode step)
    ttft_ema_s: Optional[float] = None
    queued_by_priority: Tuple[Tuple[str, int], ...] = ()
    # speculative decoding payload (engine.spec_stats()), when present
    spec_acceptance: Optional[float] = None

    @staticmethod
    def parse(url: str, payload: dict,
              now: Optional[float] = None) -> "ReplicaView":
        """Build a view from a ``/health`` JSON payload; absent fields keep
        conservative defaults so a pre-router replica still routes."""
        now = time.monotonic() if now is None else now
        sched = payload.get("scheduler") or {}
        spec = payload.get("spec") or {}

        def _ms(key):
            v = sched.get(key)
            return None if v is None else float(v) / 1e3

        return ReplicaView(
            url=url,
            fetched_at=now,
            replica_id=str(payload.get("replica_id", "")),
            seq=int(payload.get("seq", 0)),
            uptime_s=float(payload.get("uptime_s", 0.0)),
            active_slots=int(payload.get("active_slots", 0)),
            max_slots=max(int(payload.get("max_slots", 1)), 1),
            queued=int(payload.get("queued", 0)),
            prefilling=int(payload.get("prefilling", 0)),
            free_pages=int(payload.get("free_pages", 0)),
            total_pages=int(payload.get("total_pages", 0)),
            pages_cached=int(payload.get("pages_cached", 0)),
            prefix_hit_tokens=int(payload.get("prefix_hit_tokens", 0)),
            prefix_miss_tokens=int(payload.get("prefix_miss_tokens", 0)),
            page_size=int(payload.get("page_size", 0)),
            ticks=int(payload.get("ticks", 0)),
            kv_dtype=str(payload.get("kv_dtype", "bf16")),
            kv_pool_bytes=int(payload.get("kv_pool_bytes", 0)),
            kv_scale_bytes=int(payload.get("kv_scale_bytes", 0)),
            streaming=bool(payload.get("streaming", False)),
            registered=bool(payload.get("registered", False)),
            role=str(payload.get("role", "unified")),
            pp=max(int(payload.get("pp", 1)), 1),
            stages=max(int(payload.get("stages", 1)), 1),
            policy=str(sched.get("policy", "")),
            retry_after_s=(None if sched.get("retry_after_s") is None
                           else float(sched["retry_after_s"])),
            ema_tick_s=_ms("ema_tick_ms"),
            ema_retire_s=_ms("ema_retire_ms"),
            ttft_ema_s=_ms("ttft_ema_ms"),
            queued_by_priority=tuple(
                sorted((str(k), int(v)) for k, v in
                       (sched.get("queued_by_priority") or {}).items())),
            spec_acceptance=(None if spec.get("acceptance_rate") is None
                             else float(spec["acceptance_rate"])),
        )

    # ---- derived signals the policies share -----------------------------

    def age_s(self, now: Optional[float] = None) -> float:
        return (time.monotonic() if now is None else now) - self.fetched_at

    @property
    def depth(self) -> int:
        """Requests ahead of a new arrival: queued + occupied slots."""
        return self.queued + self.active_slots

    @property
    def load(self) -> float:
        """Occupancy fraction; > 1 means a backlog beyond the slots."""
        return self.depth / self.max_slots

    @property
    def free_kv_bytes(self) -> Optional[float]:
        """KV byte headroom: free pages x bytes per page (ISSUE 13).
        Comparable ACROSS kv_dtype modes — an int8 replica's page is half
        a bf16 replica's — where raw free_pages is not.  None until the
        replica publishes its pool byte budget."""
        if not self.kv_pool_bytes or not self.total_pages:
            return None
        return self.free_pages * (self.kv_pool_bytes / self.total_pages)

    def drain_score(self) -> float:
        """Predicted seconds of work ahead of a new arrival: queue depth x
        the replica's retirement EMA (tick EMA as a coarse floor before the
        first retirement — the same fallback engine._drain_eta uses).  With
        no timing signal yet, depth alone still orders replicas."""
        per = self.ema_retire_s if self.ema_retire_s is not None \
            else self.ema_tick_s
        return self.depth * (per if per is not None else 1.0)

    def predicted_wait_s(self) -> float:
        """Predicted TTFT floor for a new arrival.  With a free slot the
        replica's measured first-token EMA (``ttft_ema_ms`` — real TTFT,
        queue + prefill included) is the honest estimate, the tick EMA a
        coarse pre-ISSUE-12 fallback; a backlog costs its drain estimate
        (the replica's own Retry-After figure when it published one)."""
        if self.queued == 0 and self.active_slots < self.max_slots:
            if self.ttft_ema_s is not None:
                return self.ttft_ema_s
            return self.ema_tick_s if self.ema_tick_s is not None else 0.0
        if self.retry_after_s is not None:
            return self.retry_after_s
        return self.drain_score()


class Replica:
    """One fleet member: breaker state + freshest accepted view."""

    def __init__(self, url: str, *, suspect_after: int = 1,
                 eject_after: int = 3, registered: bool = False):
        assert 1 <= suspect_after <= eject_after
        self.url = url
        self.suspect_after = suspect_after
        self.eject_after = eject_after
        # elastic discovery (ISSUE 18): True when this replica joined
        # via POST /admin/register rather than static --replica urls
        self.registered = registered
        self._lock = threading.Lock()
        self._state = HEALTHY  # guarded by _lock
        self._draining = False  # guarded by _lock
        self._failures = 0  # consecutive poll/forward failures — guarded by _lock
        self._view: Optional[ReplicaView] = None  # guarded by _lock
        self._last_error: Optional[str] = None  # guarded by _lock
        self._restarts = 0  # replica_id changes observed — guarded by _lock
        self._stale_discards = 0  # reordered payloads dropped — guarded by _lock

    # ---- breaker transitions (all under _lock) --------------------------

    def _advance_failure_locked(self) -> None:  # holds _lock
        self._failures += 1
        if self._draining:
            return  # drain is sticky; keep counting for the fleet summary
        if self._failures >= self.eject_after:
            self._state = EJECTED
        elif self._failures >= self.suspect_after:
            self._state = SUSPECT

    def record_failure(self, error: str) -> str:
        """A failed poll or forward; returns the resulting state."""
        with self._lock:
            self._last_error = error
            self._advance_failure_locked()
            return self._state

    def record_view(self, view: ReplicaView) -> bool:
        """Apply a successful poll.  Returns False when the payload was
        discarded as stale/reordered (same replica, seq not newer)."""
        with self._lock:
            prev = self._view
            if prev is not None and prev.replica_id and view.replica_id:
                if view.replica_id != prev.replica_id:
                    self._restarts += 1  # new process behind the same url
                elif view.seq <= prev.seq:
                    self._stale_discards += 1
                    return False
            self._view = view
            self._failures = 0
            self._last_error = None
            if not self._draining:
                self._state = HEALTHY
            return True

    def drain(self, on: bool = True) -> None:
        """Operator drain: no new traffic until undrained.  Poll results
        keep refreshing the view but cannot clear the state."""
        with self._lock:
            self._draining = on
            if on:
                self._state = DRAINING
            else:
                # re-enter through the breaker: healthy iff recently polled
                self._state = HEALTHY if self._failures == 0 else (
                    EJECTED if self._failures >= self.eject_after else SUSPECT)

    # ---- snapshots ------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def view(self) -> Optional[ReplicaView]:
        with self._lock:
            return self._view

    def routable(self, max_staleness_s: Optional[float] = None) -> bool:
        """May this replica receive new traffic?  HEALTHY/SUSPECT with a
        view no older than ``max_staleness_s`` (None = any view)."""
        with self._lock:
            if self._state not in (HEALTHY, SUSPECT):
                return False
            if self._view is None:
                return False
            if max_staleness_s is not None \
                    and self._view.age_s() > max_staleness_s:
                return False
            return True

    def summary(self) -> dict:
        """Fleet-summary row for the router's own /health."""
        with self._lock:
            v = self._view
            return {
                "url": self.url,
                "state": self._state,
                "registered": self.registered,
                "consecutive_failures": self._failures,
                "last_error": self._last_error,
                "restarts": self._restarts,
                "stale_discards": self._stale_discards,
                "replica_id": v.replica_id if v else None,
                "seq": v.seq if v else None,
                "view_age_s": round(v.age_s(), 3) if v else None,
                "queued": v.queued if v else None,
                "active_slots": v.active_slots if v else None,
                "pages_cached": v.pages_cached if v else None,
            }


class ReplicaRegistry:
    """The fleet: replicas keyed by base url, with routable-view snapshots
    for the policies and failure reporting for the proxy."""

    def __init__(self, urls: List[str], *, suspect_after: int = 1,
                 eject_after: int = 3, max_staleness_s: float = 10.0,
                 allow_empty: bool = False,
                 on_add: Optional[Callable[["Replica"], None]] = None):
        if not urls and not allow_empty:
            # allow_empty is the elastic-discovery mode (ISSUE 18): the
            # fleet starts empty and fills from /admin/register beats
            raise ValueError("a router needs at least one replica url")
        self.max_staleness_s = max_staleness_s
        self._suspect_after = suspect_after
        self._eject_after = eject_after
        # called (outside _lock) for every dynamically-added replica —
        # the router hooks it to spawn a poller thread + publish gauges
        self._on_add = on_add
        self._lock = threading.Lock()
        # url -> Replica; insertion order is the stable fleet order that
        # round_robin and the hash ring key on — guarded by _lock
        self._replicas: Dict[str, Replica] = {
            u: Replica(u, suspect_after=suspect_after,
                       eject_after=eject_after)
            for u in urls}

    def replicas(self) -> List[Replica]:
        with self._lock:
            return list(self._replicas.values())

    def get(self, url: str) -> Replica:
        with self._lock:
            return self._replicas[url]

    def register(self, url: str) -> Tuple[Replica, bool]:
        """A replica heartbeat (POST /admin/register): add ``url`` to the
        fleet if it's new, idempotent otherwise.  Returns ``(replica,
        added)``.  Registered replicas merge with the static fleet and
        ride the same breaker ladder — a replica that stops beating AND
        stops answering polls walks suspect→ejected like any other, and
        a restart on a new port simply registers the new url (the old
        one ejects on its own).  ``on_add`` runs outside the registry
        lock: it spawns a poller thread that immediately takes the
        replica's own lock."""
        with self._lock:
            rep = self._replicas.get(url)
            if rep is None:
                rep = Replica(url, suspect_after=self._suspect_after,
                              eject_after=self._eject_after,
                              registered=True)
                self._replicas[url] = rep
                added = True
            else:
                added = False
        if added and self._on_add is not None:
            self._on_add(rep)
        return rep, added

    def routable_views(self) -> List[ReplicaView]:
        """Fresh views of every replica currently accepting traffic, in
        stable fleet order — the policies' input."""
        views = []
        for rep in self.replicas():
            if rep.routable(self.max_staleness_s):
                v = rep.view
                if v is not None:
                    views.append(v)
        return views

    def record_forward_failure(self, url: str, error: str) -> None:
        """The data plane could not reach ``url`` — same breaker as a
        failed poll, so repeated forward failures eject without waiting
        for the next poll interval."""
        try:
            rep = self.get(url)
        except KeyError:
            return
        rep.record_failure(error)

    def drain(self, url: str, on: bool = True) -> bool:
        try:
            rep = self.get(url)
        except KeyError:
            return False
        rep.drain(on)
        return True

    def summary(self) -> dict:
        reps = self.replicas()
        states = [r.state for r in reps]
        return {
            "replicas": [r.summary() for r in reps],
            "fleet": {s: states.count(s)
                      for s in (HEALTHY, SUSPECT, EJECTED, DRAINING)},
            "routable": sum(r.routable(self.max_staleness_s) for r in reps),
        }


def fetch_health(url: str, timeout_s: float) -> dict:
    """One /health scrape (also the poller's probe)."""
    with urllib.request.urlopen(url.rstrip("/") + "/health",
                                timeout=timeout_s) as resp:
        return json.loads(resp.read())


class HealthPoller:
    """One daemon thread per replica scraping /health on an interval.

    EJECTED replicas are probed at ``recovery_interval`` (slower — they
    are likely down, and hammering them helps nobody); everything else at
    ``interval``.  A parse failure counts as a poll failure: a replica
    answering garbage should trip the breaker, not crash the router."""

    def __init__(self, registry: ReplicaRegistry, *, interval: float = 1.0,
                 recovery_interval: Optional[float] = None,
                 timeout_s: float = 5.0,
                 fetch: Callable[[str, float], dict] = fetch_health,
                 on_poll: Optional[Callable[[Replica, bool], None]] = None):
        self.registry = registry
        self.interval = interval
        self.recovery_interval = recovery_interval or max(interval * 5, 5.0)
        self.timeout_s = timeout_s
        self._fetch = fetch
        self._on_poll = on_poll  # observability hook (router server)
        self._stop = threading.Event()
        self._threads_lock = threading.Lock()
        self._threads: List[threading.Thread] = []  # guarded by _threads_lock
        self._started = False  # guarded by _threads_lock

    def poll_once(self, rep: Replica) -> bool:
        """Scrape one replica now; returns success.  Exposed for tests and
        for the router's synchronous warm-up poll."""
        from megatron_llm_tpu.observability.trace import span

        try:
            with span("router-poll", url=rep.url):
                payload = self._fetch(rep.url, self.timeout_s)
            if not isinstance(payload, dict):
                raise ValueError("health payload is not a JSON object")
            rep.record_view(ReplicaView.parse(rep.url, payload))
            ok = True
        except Exception as e:  # any failure shape trips the breaker
            rep.record_failure(f"{type(e).__name__}: {e}")
            ok = False
        if self._on_poll is not None:
            self._on_poll(rep, ok)
        return ok

    def _loop(self, rep: Replica) -> None:
        while not self._stop.is_set():
            self.poll_once(rep)
            wait = (self.recovery_interval if rep.state == EJECTED
                    else self.interval)
            if self._stop.wait(wait):
                return

    def _spawn_locked(self, rep: Replica) -> None:  # holds _threads_lock
        t = threading.Thread(target=self._loop, args=(rep,),
                             name=f"health-poll:{rep.url}", daemon=True)
        t.start()
        self._threads.append(t)

    def start(self) -> None:
        with self._threads_lock:
            assert not self._threads, "poller already started"
            self._started = True
            for rep in self.registry.replicas():
                self._spawn_locked(rep)

    def watch(self, rep: Replica) -> None:
        """Start polling a dynamically-registered replica (ISSUE 18).
        Before ``start()`` this is a no-op — start() picks up every
        replica the registry holds at that point."""
        with self._threads_lock:
            if not self._started:
                return
            self._spawn_locked(rep)

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        with self._threads_lock:
            threads, self._threads = self._threads, []
            self._started = False
        for t in threads:
            t.join(timeout=timeout)
