"""The five routing policies: round_robin, least_loaded, prefix_affinity,
slo_aware, disagg.

Each consumes :class:`ReplicaView` snapshots only (serving/router/
registry.py) and returns a preference-ordered candidate list; the proxy
walks it for failover.  Policy matrix + tuning guidance:
docs/guide/serving.md "Cross-replica routing".
"""

from __future__ import annotations

import bisect
import hashlib
import itertools
from typing import List, Optional, Sequence, Tuple

from megatron_llm_tpu.serving.router.policy import (
    FleetOverloaded,
    RouteRequest,
    RouterPolicy,
    register_router_policy,
)
from megatron_llm_tpu.serving.router.registry import ReplicaView

__all__ = [
    "DisaggPolicy",
    "LeastLoadedPolicy",
    "PrefixAffinityPolicy",
    "RoundRobinPolicy",
    "SloAwarePolicy",
    "prefix_key",
]


@register_router_policy
class RoundRobinPolicy(RouterPolicy):
    """Baseline: rotate through the routable fleet in stable order.

    The cursor advances per routed request, not per fleet position, so a
    replica leaving and rejoining does not skew the rotation."""

    name = "round_robin"

    def __init__(self):
        # itertools.count.__next__ is atomic under the GIL — the only
        # policy-internal state any of the four keeps
        self._cursor = itertools.count()

    def order(self, request: RouteRequest,
              views: Sequence[ReplicaView]) -> List[ReplicaView]:
        k = next(self._cursor) % len(views)
        return list(views[k:]) + list(views[:k])


def _kv_headroom(v: ReplicaView) -> float:
    """Capacity tie-break signal in BYTES (ISSUE 13 / ISSUE 19): an int8
    replica's free page holds half a bf16 replica's, so mixed-dtype
    fleets must compare byte headroom, never raw page counts.  Falls back
    to the page count only when the replica predates the byte budget
    (pre-ISSUE-13 /health payloads)."""
    b = v.free_kv_bytes
    return b if b is not None else float(v.free_pages)


def _drain_order(views: Sequence[ReplicaView]) -> List[ReplicaView]:
    """Ascending predicted-backlog order: queue-depth x drain-EMA, ties
    broken by occupancy, then by KV byte headroom descending (the
    dtype-honest capacity signal — see :func:`_kv_headroom`), then stable
    fleet order (enumerate keeps the sort deterministic when everything
    ties exactly)."""
    return [v for _, _, _, _, v in sorted(
        (v.drain_score(), v.load, -_kv_headroom(v), i, v)
        for i, v in enumerate(views))]


@register_router_policy
class LeastLoadedPolicy(RouterPolicy):
    """Send each request to the replica with the least predicted backlog
    seconds (its queue depth scaled by its own retirement EMA — a replica
    that drains twice as fast carries twice the queue for the same
    score)."""

    name = "least_loaded"

    def order(self, request: RouteRequest,
              views: Sequence[ReplicaView]) -> List[ReplicaView]:
        return _drain_order(views)


def prefix_key(text: str, prefix_chars: int) -> bytes:
    """Affinity key: hash of the request's leading ``prefix_chars``
    characters.  Page-ALIGNED affinity (token-exact page boundaries) lives
    in each replica's radix trie; the router only needs requests sharing a
    system prompt to agree on a key, and a fixed character horizon ~=
    4 chars/token x the fleet page size does that without a tokenizer.
    Requests shorter than the horizon hash what they have — identical
    short prompts still co-locate."""
    return hashlib.sha256(text[:prefix_chars].encode(
        "utf-8", errors="replace")).digest()


@register_router_policy
class PrefixAffinityPolicy(RouterPolicy):
    """Consistent hashing on the prompt-prefix key with a bounded-load
    escape valve.

    The hash ring carries ``vnodes`` points per replica keyed on
    ``replica_id`` (NOT the url: a restarted replica gets a new id and so
    a new ring position — its cache died with the old process, and the
    re-deal costs nothing that wasn't already lost).  A request walks the
    ring clockwise from its prefix key; the first routable replica wins —
    so every request sharing a system prompt lands where that prompt's KV
    pages already sit (generation/engine.py prefix cache).

    Bounded load (the "power of the ring, limits of the hotspot" rule): if
    the ring choice's depth exceeds ``load_factor`` x the fleet mean
    (minimum ``min_headroom`` over the mean, so tiny fleets don't spill on
    a depth-1 difference), the request spills to the least-loaded replica
    instead — a hot prefix saturating one replica degrades to load
    balancing rather than hotspotting.  Failover order after the primary:
    the remaining replicas in drain order, so a dead primary's traffic
    spreads by load, not ring adjacency alone."""

    name = "prefix_affinity"

    def __init__(self, *, prefix_chars: int = 256, vnodes: int = 64,
                 load_factor: float = 1.25, min_headroom: int = 2):
        if prefix_chars < 1 or vnodes < 1 or load_factor < 1.0:
            raise ValueError("prefix_chars/vnodes >= 1, load_factor >= 1.0")
        self.prefix_chars = prefix_chars
        self.vnodes = vnodes
        self.load_factor = load_factor
        self.min_headroom = min_headroom

    def _ring(self, views: Sequence[ReplicaView]
              ) -> Tuple[List[int], List[ReplicaView]]:
        points: List[Tuple[int, int, ReplicaView]] = []
        for i, v in enumerate(views):
            ident = v.replica_id or v.url
            for n in range(self.vnodes):
                h = hashlib.sha256(f"{ident}:{n}".encode()).digest()
                points.append((int.from_bytes(h[:8], "big"), i, v))
        points.sort()
        return [p[0] for p in points], [p[2] for p in points]

    def _ring_choice(self, request: RouteRequest,
                     views: Sequence[ReplicaView]) -> ReplicaView:
        keys, owners = self._ring(views)
        key = int.from_bytes(
            prefix_key(request.prefix_text, self.prefix_chars)[:8], "big")
        return owners[bisect.bisect_right(keys, key) % len(owners)]

    def order(self, request: RouteRequest,
              views: Sequence[ReplicaView]) -> List[ReplicaView]:
        chosen = self._ring_choice(request, views)
        rest = _drain_order([v for v in views if v is not chosen])
        mean_depth = sum(v.depth for v in views) / len(views)
        bound = max(self.load_factor * mean_depth,
                    mean_depth + self.min_headroom)
        if rest and chosen.depth > bound:
            # hot prefix: spill to the least-loaded replica; the ring
            # choice stays second so affinity resumes once it cools
            return [rest[0], chosen] + rest[1:]
        return [chosen] + rest


@register_router_policy
class SloAwarePolicy(RouterPolicy):
    """Pick the replica whose predicted wait meets the request's TTFT
    deadline; 503 the request with the fleet-minimum Retry-After when none
    can.

    ``margin`` discounts the deadline (a prediction exactly at the
    deadline misses it after forward + prefill cost).  Deadline-less
    requests degrade to least_loaded — predicted wait IS the drain order
    then.  The returned order is ascending predicted wait over the
    *feasible* set, then the infeasible ones (failover may still prefer a
    live slow replica over a dead fast one)."""

    name = "slo_aware"

    def __init__(self, *, margin: float = 0.8):
        if not 0.0 < margin <= 1.0:
            raise ValueError("margin must be in (0, 1]")
        self.margin = margin

    def order(self, request: RouteRequest,
              views: Sequence[ReplicaView]) -> List[ReplicaView]:
        ranked = sorted(
            (v.predicted_wait_s(), i, v) for i, v in enumerate(views))
        if request.ttft_deadline_ms is None:
            return [v for _, _, v in ranked]
        budget_s = request.ttft_deadline_ms / 1e3 * self.margin
        feasible = [(w, i, v) for w, i, v in ranked if w <= budget_s]
        if not feasible:
            waits = {v.url: round(w, 3) for w, _, v in ranked}
            soonest = max(ranked[0][0], 0.05)
            raise FleetOverloaded(
                f"no replica predicts TTFT within "
                f"{request.ttft_deadline_ms:.0f}ms "
                f"(fleet-min predicted wait {soonest:.3f}s)",
                retry_after=min(max(soonest, 1.0), 60.0),
                info={"predicted_wait_s": waits,
                      "ttft_deadline_ms": request.ttft_deadline_ms})
        infeasible = [(w, i, v) for w, i, v in ranked if w > budget_s]
        return [v for _, _, v in feasible + infeasible]


@register_router_policy
class DisaggPolicy(RouterPolicy):
    """Phase-aware routing for disaggregated prefill/decode fleets
    (ISSUE 19, serving/handoff/).

    ``order`` answers where the request should *decode*: decode-role
    replicas first (drain order), then unified, then prefill-role as the
    last-resort failover tier — a fleet with no decode-role replicas
    degrades to plain least_loaded, so the policy is safe as a default
    on role-less fleets.

    ``prefill_candidates`` answers whether the request should take the
    prefill→handoff→decode path first: only single-prompt, non-logprobs
    requests with at least ``long_prompt_chars`` characters of prompt
    (short prompts' prefill is cheaper than the hop), and only when the
    fleet has BOTH a prefill-role and a decode-role replica.  An empty
    list means "skip the hop" — the router then forwards exactly like
    least_loaded would."""

    name = "disagg"

    def __init__(self, *, long_prompt_chars: int = 2048):
        if long_prompt_chars < 1:
            raise ValueError("long_prompt_chars must be >= 1")
        self.long_prompt_chars = long_prompt_chars

    def order(self, request: RouteRequest,
              views: Sequence[ReplicaView]) -> List[ReplicaView]:
        decode = [v for v in views if v.role == "decode"]
        unified = [v for v in views if v.role == "unified"]
        prefill = [v for v in views if v.role == "prefill"]
        ordered = (_drain_order(decode) + _drain_order(unified)
                   + _drain_order(prefill))
        # roles the parser doesn't know stay routable, at the back
        known = set(ordered)
        return ordered + _drain_order([v for v in views if v not in known])

    def prefill_candidates(self, request: RouteRequest,
                           views: Sequence[ReplicaView]
                           ) -> List[ReplicaView]:
        if request.n_prompts != 1 or request.logprobs:
            return []
        if len(request.prefix_text) < self.long_prompt_chars:
            return []
        prefill = [v for v in views if v.role == "prefill"]
        if not prefill or not any(v.role == "decode" for v in views):
            return []
        return _drain_order(prefill)
