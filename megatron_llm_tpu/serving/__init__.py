"""Serving tiers above a single generation-server process.

``serving.router`` is the cross-replica request router: it fronts N
generation-server replicas (tools/run_text_generation_server.py), polls
their ``/health`` control plane, and load-balances ``PUT /api`` traffic
across them (tools/run_router.py, docs/guide/serving.md
"Cross-replica routing").
"""
