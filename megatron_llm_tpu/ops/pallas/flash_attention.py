"""Pallas TPU flash attention (FlashAttention-2 style), fwd + bwd.

Replaces the reference's external FlashAttention-2 CUDA dependency
(transformer.py:9,518-600: flash_attn_func with causal, GQA, sliding-window)
and the fused scaled-masked-softmax CUDA kernels (fused_kernels/, subsumed —
the softmax never materializes).

Design (blockwise online softmax, one pass over KV per Q block):

* layout [b, heads, seq, head_dim]; grid (b*n, num_q_blocks, num_kv_blocks)
  with the KV axis innermost — on TPU the grid is a sequential loop, so VMEM
  scratch (running max m, normalizer l, fp32 accumulator) carries across KV
  iterations for a fixed Q block.
* GQA native: K/V keep n_kv heads; the Q-head grid index maps to kv head
  ``h // group`` in the BlockSpec index map — no broadcast-expand (the
  reference expands K/V at transformer.py:459-466).
* causal + sliding-window + segment-id masking via broadcasted iota on
  *global* positions; fully-masked KV blocks are skipped with @pl.when.
* backward: two kernels (dq; dk/dv fused) recomputing p from the saved
  logsumexp — the standard flash-2 residual scheme (saves q,k,v,o,lse).

Numerics: logits and softmax in fp32 (matches attention_softmax_in_fp32 +
the XLA fallback in ops/attention.py); accumulators fp32; outputs cast to the
input dtype.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp

# compile-TARGET platform: AOT lowering for a TPU topology on a CPU
# host must compile the real kernel, not interpret mode
from megatron_llm_tpu.core.parallel_state import target_platform
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _run_block(q_off, kv_off, block_q, block_kv, causal, sliding_window):
    """Whether any (q, kv) pair in this block tile can be unmasked."""
    run = jnp.bool_(True)
    if causal:
        run = jnp.logical_and(run, q_off + block_q - 1 >= kv_off)
    if sliding_window is not None:
        run = jnp.logical_and(run, kv_off + block_kv - 1 > q_off - sliding_window)
    return run


def _mask(
    q_off, kv_off, block_q, block_kv, causal, sliding_window,
    seg_q, seg_kv,
):
    """Additive fp32 mask [block_q, block_kv] from global offsets."""
    q_ids = q_off + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 0)
    kv_ids = kv_off + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 1)
    allowed = jnp.ones((block_q, block_kv), jnp.bool_)
    if causal:
        allowed &= q_ids >= kv_ids
    if sliding_window is not None:
        allowed &= (q_ids - kv_ids) < sliding_window
    if seg_q is not None:
        allowed &= seg_q.reshape(block_q, 1) == seg_kv.reshape(1, block_kv)
    return jnp.where(allowed, 0.0, NEG_INF).astype(jnp.float32)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _fwd_kernel(
    # refs (segment refs present only when segmented)
    *refs,
    scale: float,
    causal: bool,
    sliding_window: Optional[int],
    block_q: int,
    block_kv: int,
    kv_seq_len: int,
    segmented: bool,
):
    if segmented:
        q_ref, k_ref, v_ref, segq_ref, segkv_ref, o_ref, lse_ref, m_s, l_s, acc_s = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, m_s, l_s, acc_s = refs
        segq_ref = segkv_ref = None

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    q_off = qi * block_q
    kv_off = ki * block_kv

    @pl.when(ki == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    # skip blocks entirely above the diagonal / outside the window
    run = _run_block(q_off, kv_off, block_q, block_kv, causal, sliding_window)

    @pl.when(run)
    def _step():
        q = q_ref[0, 0].astype(jnp.float32)  # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)  # [bkv, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bkv]
        seg_q = segq_ref[0, 0] if segmented else None
        seg_kv = segkv_ref[0, 0] if segmented else None
        if causal or sliding_window is not None or segmented:
            s = s + _mask(q_off, kv_off, block_q, block_kv, causal,
                          sliding_window, seg_q, seg_kv)

        m_prev = m_s[:, 0]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.exp(m_prev - m_cur)
        # guard rows that are fully masked SO FAR (m_cur still -inf — happens
        # under sliding window when early KV blocks are entirely out-of-window):
        # exp(-inf - -inf) would be 1, poisoning the accumulator.
        p = jnp.where(s <= NEG_INF * 0.5, 0.0, jnp.exp(s - m_cur[:, None]))
        l_cur = alpha * l_s[:, 0] + jnp.sum(p, axis=1)
        m_s[:, 0] = m_cur
        l_s[:, 0] = l_cur
        v = v_ref[0, 0].astype(jnp.float32)
        acc_s[:] = acc_s[:] * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ki == pl.num_programs(2) - 1)
    def _finish():
        l = l_s[:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> 0 output
        o_ref[0, 0] = (acc_s[:] / l_safe[:, None]).astype(o_ref.dtype)
        # trailing singleton keeps the (sublane, lane) tile legal on TPU
        lse_ref[0, 0, :, 0] = (m_s[:, 0] + jnp.log(l_safe)).astype(jnp.float32)


def _fwd(
    q, k, v, seg_q, seg_kv, scale, causal, sliding_window, block_q, block_kv,
    interpret, out_dtype=None,
):
    """``out_dtype``: ring callers (parallel/ring.py) accumulate per-chunk
    partials across cp steps and request fp32 to avoid one extra rounding
    per chunk; the default (q.dtype) is the plain-attention contract."""
    b, n, sq, d = q.shape
    _, nkv, skv, _ = k.shape
    g = n // nkv
    block_q = min(block_q, sq)
    block_kv = min(block_kv, skv)
    assert sq % block_q == 0 and skv % block_kv == 0, (
        f"seq lengths ({sq},{skv}) must divide blocks ({block_q},{block_kv})"
    )
    grid = (b * n, sq // block_q, skv // block_kv)

    in_specs = [
        pl.BlockSpec((1, 1, block_q, d),
                     lambda bh, qi, ki: (bh // n, bh % n, qi, 0)),
        pl.BlockSpec((1, 1, block_kv, d),
                     lambda bh, qi, ki: (bh // n, (bh % n) // g, ki, 0)),
        pl.BlockSpec((1, 1, block_kv, d),
                     lambda bh, qi, ki: (bh // n, (bh % n) // g, ki, 0)),
    ]
    args = [q, k, v]
    segmented = seg_q is not None
    if segmented:
        # [b, 1, s] layout: the unit middle dim keeps the block's
        # second-to-last dimension equal to the array's (TPU tiling rule)
        in_specs += [
            pl.BlockSpec((1, 1, block_q), lambda bh, qi, ki: (bh // n, 0, qi)),
            pl.BlockSpec((1, 1, block_kv), lambda bh, qi, ki: (bh // n, 0, ki)),
        ]
        args += [seg_q, seg_kv]

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, sliding_window=sliding_window,
        block_q=block_q, block_kv=block_kv, kv_seq_len=skv, segmented=segmented,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bh, qi, ki: (bh // n, bh % n, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda bh, qi, ki: (bh // n, bh % n, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, out_dtype or q.dtype),
            jax.ShapeDtypeStruct((b, n, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return out, lse


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(
    *refs, scale, causal, sliding_window, block_q, block_kv, segmented,
):
    if segmented:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, segq_ref, segkv_ref,
         dq_ref, dq_s) = refs
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_s = refs
        segq_ref = segkv_ref = None

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    q_off, kv_off = qi * block_q, ki * block_kv

    @pl.when(ki == 0)
    def _init():
        dq_s[:] = jnp.zeros_like(dq_s)

    run = _run_block(q_off, kv_off, block_q, block_kv, causal, sliding_window)

    @pl.when(run)
    def _step():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, :, 0]
        delta = delta_ref[0, 0, :, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        seg_q = segq_ref[0, 0] if segmented else None
        seg_kv = segkv_ref[0, 0] if segmented else None
        if causal or sliding_window is not None or segmented:
            s = s + _mask(q_off, kv_off, block_q, block_kv, causal,
                          sliding_window, seg_q, seg_kv)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[:, None]) * scale
        dq_s[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ki == pl.num_programs(2) - 1)
    def _finish():
        dq_ref[0, 0] = dq_s[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    *refs, scale, causal, sliding_window, block_q, block_kv, group, segmented,
):
    if segmented:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, segq_ref, segkv_ref,
         dk_ref, dv_ref, dk_s, dv_s) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_s, dv_s) = refs
        segq_ref = segkv_ref = None

    ki = pl.program_id(1)
    gi = pl.program_id(2)
    qi = pl.program_id(3)
    q_off, kv_off = qi * block_q, ki * block_kv

    @pl.when(jnp.logical_and(gi == 0, qi == 0))
    def _init():
        dk_s[:] = jnp.zeros_like(dk_s)
        dv_s[:] = jnp.zeros_like(dv_s)

    run = _run_block(q_off, kv_off, block_q, block_kv, causal, sliding_window)

    @pl.when(run)
    def _step():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, :, 0]
        delta = delta_ref[0, 0, :, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        seg_q = segq_ref[0, 0] if segmented else None
        seg_kv = segkv_ref[0, 0] if segmented else None
        if causal or sliding_window is not None or segmented:
            s = s + _mask(q_off, kv_off, block_q, block_kv, causal,
                          sliding_window, seg_q, seg_kv)
        p = jnp.exp(s - lse[:, None])  # [bq, bkv]
        dv_s[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[:, None]) * scale
        dk_s[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(jnp.logical_and(gi == pl.num_programs(2) - 1,
                             qi == pl.num_programs(3) - 1))
    def _finish():
        dk_ref[0, 0] = dk_s[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_s[:].astype(dv_ref.dtype)


def _bwd(
    scale, causal, sliding_window, block_q, block_kv, interpret,
    residuals, grads, delta=None, out_dtype=None,
):
    """``delta``/``out_dtype``: ring callers (parallel/ring.py) invoke this
    once per KV chunk inside a lax.scan — they precompute the loop-invariant
    delta = rowsum(do*o) once outside (XLA cannot CSE across scan
    iterations) and request fp32 gradients for cross-chunk accumulation."""
    q, k, v, o, lse, seg_q, seg_kv = residuals
    do = grads[0]
    b, n, sq, d = q.shape
    _, nkv, skv, _ = k.shape
    g = n // nkv
    block_q = min(block_q, sq)
    block_kv = min(block_kv, skv)

    if delta is None:
        delta = jnp.sum(
            do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1,
            keepdims=True
        )  # [b, n, sq, 1] — same tiled layout as lse

    segmented = seg_q is not None

    # ---- dq ----
    grid_dq = (b * n, sq // block_q, skv // block_kv)
    in_specs = [
        pl.BlockSpec((1, 1, block_q, d), lambda bh, qi, ki: (bh // n, bh % n, qi, 0)),
        pl.BlockSpec((1, 1, block_kv, d), lambda bh, qi, ki: (bh // n, (bh % n) // g, ki, 0)),
        pl.BlockSpec((1, 1, block_kv, d), lambda bh, qi, ki: (bh // n, (bh % n) // g, ki, 0)),
        pl.BlockSpec((1, 1, block_q, d), lambda bh, qi, ki: (bh // n, bh % n, qi, 0)),
        pl.BlockSpec((1, 1, block_q, 1),
                     lambda bh, qi, ki: (bh // n, bh % n, qi, 0)),
        pl.BlockSpec((1, 1, block_q, 1),
                     lambda bh, qi, ki: (bh // n, bh % n, qi, 0)),
    ]
    args = [q, k, v, do, lse, delta]
    if segmented:
        in_specs += [
            pl.BlockSpec((1, 1, block_q), lambda bh, qi, ki: (bh // n, 0, qi)),
            pl.BlockSpec((1, 1, block_kv), lambda bh, qi, ki: (bh // n, 0, ki)),
        ]
        args += [seg_q, seg_kv]
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal,
            sliding_window=sliding_window, block_q=block_q, block_kv=block_kv,
            segmented=segmented,
        ),
        grid=grid_dq,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda bh, qi, ki: (bh // n, bh % n, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, out_dtype or q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(*args)

    # ---- dk, dv ----
    grid_dkv = (b * nkv, skv // block_kv, g, sq // block_q)
    in_specs = [
        pl.BlockSpec((1, 1, block_q, d),
                     lambda bh, ki, gi, qi: (bh // nkv, (bh % nkv) * g + gi, qi, 0)),
        pl.BlockSpec((1, 1, block_kv, d),
                     lambda bh, ki, gi, qi: (bh // nkv, bh % nkv, ki, 0)),
        pl.BlockSpec((1, 1, block_kv, d),
                     lambda bh, ki, gi, qi: (bh // nkv, bh % nkv, ki, 0)),
        pl.BlockSpec((1, 1, block_q, d),
                     lambda bh, ki, gi, qi: (bh // nkv, (bh % nkv) * g + gi, qi, 0)),
        pl.BlockSpec((1, 1, block_q, 1),
                     lambda bh, ki, gi, qi: (bh // nkv, (bh % nkv) * g + gi, qi, 0)),
        pl.BlockSpec((1, 1, block_q, 1),
                     lambda bh, ki, gi, qi: (bh // nkv, (bh % nkv) * g + gi, qi, 0)),
    ]
    args = [q, k, v, do, lse, delta]
    if segmented:
        in_specs += [
            pl.BlockSpec((1, 1, block_q),
                         lambda bh, ki, gi, qi: (bh // nkv, 0, qi)),
            pl.BlockSpec((1, 1, block_kv),
                         lambda bh, ki, gi, qi: (bh // nkv, 0, ki)),
        ]
        args += [seg_q, seg_kv]
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal,
            sliding_window=sliding_window, block_q=block_q, block_kv=block_kv,
            group=g, segmented=segmented,
        ),
        grid=grid_dkv,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_kv, d),
                         lambda bh, ki, gi, qi: (bh // nkv, bh % nkv, ki, 0)),
            pl.BlockSpec((1, 1, block_kv, d),
                         lambda bh, ki, gi, qi: (bh // nkv, bh % nkv, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, out_dtype or k.dtype),
            jax.ShapeDtypeStruct(v.shape, out_dtype or v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_kv, d), jnp.float32),
            pltpu.VMEM((block_kv, d), jnp.float32),
        ],
        interpret=interpret,
    )(*args)

    dsq = dskv = None
    return dq, dk, dv, dsq, dskv


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10)
)
def _flash(q, k, v, seg_q, seg_kv, scale, causal, sliding_window,
           block_q, block_kv, interpret):
    out, _ = _fwd(q, k, v, seg_q, seg_kv, scale, causal, sliding_window,
                  block_q, block_kv, interpret)
    return out


def _flash_fwd(q, k, v, seg_q, seg_kv, scale, causal, sliding_window,
               block_q, block_kv, interpret):
    out, lse = _fwd(q, k, v, seg_q, seg_kv, scale, causal, sliding_window,
                    block_q, block_kv, interpret)
    return out, (q, k, v, out, lse, seg_q, seg_kv)


def _flash_bwd(scale, causal, sliding_window, block_q, block_kv, interpret,
               residuals, g):
    dq, dk, dv, dsq, dskv = _bwd(
        scale, causal, sliding_window, block_q, block_kv, interpret,
        residuals, (g,),
    )
    return dq, dk, dv, dsq, dskv


_flash.defvjp(_flash_fwd, _flash_bwd)


def _env_block(var: str, seq: int, cap: int = 1024) -> Optional[int]:
    """Sweep-only block-size override (tools/mfu_sweep.py retune rows).

    Ignored (with a one-line note — the override is process-wide, so a
    silently dropped value would make a sweep row measure the default)
    unless it
      * evenly divides ``seq`` — an override tuned for the bench shape must
        not break other call sites (e.g. a decode step with a different KV
        length) in the same process;
      * is a multiple of the minimum TPU tile (128 lanes; ADVICE r4 #2 — a
        non-tile value passes divisibility at some seqs and then dies as an
        opaque Mosaic compile error mid-sweep);
      * respects the same VMEM cap as :func:`_auto_block` (1024, or 512 at
        head_dim 256 — the caller passes the cap it would auto-pick under).
    """
    v = os.environ.get(var)
    if not v:
        return None
    blk = int(v)
    if blk % 128 != 0 or blk > cap or blk <= 0:
        # intrinsically invalid value: warn — silently measuring the
        # default mid-sweep is worse than the noise
        print(f"[flash_attention] ignoring {var}={blk} "
              f"(must be a positive multiple of 128 and <= VMEM cap {cap})",
              flush=True)
        return None
    if not (blk <= seq and seq % blk == 0):
        # by-design silent skip: an override tuned for the bench shape must
        # not break (or spam) other-seq call sites in the same process
        return None
    return blk


def _auto_block(seq: int, cap: int = 1024) -> int:
    """Largest power-of-two block <= cap dividing seq.

    Hardware sweep on TPU v5e (tools/tpu_kernel_check.py): 1024x1024 blocks
    are up to 2x faster than the old fixed 512 at seq >= 2048 (fewer grid
    iterations amortize the per-block mask/softmax bookkeeping), and within
    noise at seq 1024. VMEM at 1024x1024 fp32 scores is 4 MiB per score-
    sized intermediate — fine at head_dim 128, but the backward kernels keep
    ~4 such intermediates (s, p, dp, ds) plus q/k/v/do tiles, so the caller
    caps the block at 512 for head_dim 256 to stay inside the ~16 MiB/core
    VMEM budget.
    """
    for blk in (1024, 512, 256, 128):
        if blk <= cap and seq % blk == 0:
            return blk
    return seq


def pick_blocks(sq: int, skv: int, d: int) -> tuple:
    """THE block-size policy (VMEM cap by head_dim, sweep env overrides,
    auto fallback) — single source for flash_attention and the
    flash-in-ring path (parallel/ring.py), so MLT_FLASH_BLOCK_Q/KV sweeps
    apply to both and the cap never diverges.

    Measured (v5e, seq 8192, window 256): large KV blocks win even for
    small sliding windows — grid-iteration overhead outweighs the masked
    compute whole-tile pruning would save (1024x1024 98 ms vs 512x512
    109 ms vs 512x256 134 ms) — so no window-based cap."""
    cap = 1024 if d <= 128 else 512  # VMEM, see _auto_block
    block_q = (_env_block("MLT_FLASH_BLOCK_Q", sq, cap)
               or _auto_block(sq, cap))
    block_kv = (_env_block("MLT_FLASH_BLOCK_KV", skv, cap)
                or _auto_block(skv, cap))
    return block_q, block_kv


def flash_attention(
    q: jax.Array,  # [b, s, n, d]
    k: jax.Array,  # [b, s, nkv, d]
    v: jax.Array,
    *,
    causal: bool = True,
    sliding_window: Optional[int] = None,
    segment_ids: Optional[jax.Array] = None,  # [b, s]
    scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_kv: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention over [batch, seq, heads, head_dim] inputs."""
    b, sq, n, d = q.shape
    auto_q, auto_kv = pick_blocks(sq, k.shape[1], d)
    if block_q is None:
        block_q = auto_q
    if block_kv is None:
        block_kv = auto_kv
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = target_platform() == "cpu"
    qh = q.transpose(0, 2, 1, 3)
    kh = k.transpose(0, 2, 1, 3)
    vh = v.transpose(0, 2, 1, 3)
    seg = (
        segment_ids.astype(jnp.int32)[:, None, :]
        if segment_ids is not None else None
    )
    out = _flash(qh, kh, vh, seg, seg, scale, causal, sliding_window,
                 block_q, block_kv, interpret)
    return out.transpose(0, 2, 1, 3)
