"""Fused RMSNorm Pallas kernel (fwd + bwd).

Replaces the reference's fused LayerNorm CUDA kernel family
(megatron/fused_kernels/layer_norm_cuda_kernel.cu; RMSNorm itself is pure
torch at model/fused_layer_norm.py:125-139). One VMEM pass per row block:
computes the fp32 mean-square, normalizes, scales — no intermediate HBM
round-trips. Backward recomputes rstd (cheap) and reduces dW across the row
grid in an fp32 accumulator.

dx math (y = x * r * w, r = rsqrt(mean(x^2)+eps)):
    dx = r * (g*w) - x * r^3 * mean(x * g * w)
dw = sum over rows of g * x * r
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# compile-TARGET platform: AOT lowering for a TPU topology on a CPU
# host must compile the real kernel, not interpret mode
from megatron_llm_tpu.core.parallel_state import target_platform
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _fwd_kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    r = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    o_ref[:] = (x * r * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _bwd_kernel(x_ref, w_ref, g_ref, dx_ref, dw_ref, dw_acc, *, eps):
    i = pl.program_id(0)
    x = x_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    r = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    gw = g * w
    mean_xgw = jnp.mean(x * gw, axis=-1, keepdims=True)
    dx_ref[:] = (r * gw - x * (r ** 3) * mean_xgw).astype(dx_ref.dtype)

    @pl.when(i == 0)
    def _():
        dw_acc[:] = jnp.zeros_like(dw_acc)

    dw_acc[:] += jnp.sum(g * x * r, axis=0)

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        dw_ref[:] = dw_acc[:].astype(dw_ref.dtype)


def _reshape_2d(x):
    h = x.shape[-1]
    return x.reshape(-1, h)


def _fwd_call(x, w, eps, block_rows, interpret):
    x2 = _reshape_2d(x)
    rows, h = x2.shape
    block = min(block_rows, rows)
    if rows % block != 0:
        block = rows  # fall back to one block for ragged row counts
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=(rows // block,),
        in_specs=[
            pl.BlockSpec((block, h), lambda i: (i, 0)),
            pl.BlockSpec((h,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block, h), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x2.shape, x.dtype),
        interpret=interpret,
    )(x2, w)
    return out.reshape(x.shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def fused_rms_norm(x, w, eps: float = 1e-6, block_rows: int = 256,
                   interpret: bool | None = None):
    """RMSNorm over the last axis; any leading shape."""
    if interpret is None:
        interpret = target_platform() == "cpu"
    return _fwd_call(x, w, eps, block_rows, interpret)


def _vjp_fwd(x, w, eps, block_rows, interpret):
    if interpret is None:
        interpret = target_platform() == "cpu"
    return _fwd_call(x, w, eps, block_rows, interpret), (x, w)


def _vjp_bwd(eps, block_rows, interpret, res, g):
    if interpret is None:
        interpret = target_platform() == "cpu"
    x, w = res
    x2 = _reshape_2d(x)
    g2 = _reshape_2d(g)
    rows, h = x2.shape
    block = min(block_rows, rows)
    if rows % block != 0:
        block = rows
    dx, dw = pl.pallas_call(
        functools.partial(_bwd_kernel, eps=eps),
        grid=(rows // block,),
        in_specs=[
            pl.BlockSpec((block, h), lambda i: (i, 0)),
            pl.BlockSpec((h,), lambda i: (0,)),
            pl.BlockSpec((block, h), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block, h), lambda i: (i, 0)),
            pl.BlockSpec((h,), lambda i: (0,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(x2.shape, x.dtype),
            jax.ShapeDtypeStruct((h,), w.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((h,), jnp.float32)],
        interpret=interpret,
    )(x2, w, g2)
    return dx.reshape(x.shape), dw


fused_rms_norm.defvjp(_vjp_fwd, _vjp_bwd)
