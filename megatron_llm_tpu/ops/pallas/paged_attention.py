"""Pallas TPU paged-attention kernels (Ragged Paged Attention style).

Two kernels over the same layout: one DECODE step for a batch of sequences
whose KV lives in a shared page pool, and one PREFILL CHUNK (s query rows
of one sequence against its block-tabled prefix — the prefix-cache engine's
prefill-against-block-table mode, ISSUE 5).  The dense-cache decode
attention reads a contiguous [b, max_seq] cache; here the block table is a
*scalar-prefetch* operand (pltpu.PrefetchScalarGridSpec), so the BlockSpec
index map resolves ``page_id = block_table[seq, j]`` before the grid step
runs and the pipeline DMAs exactly that page from the HBM pool into VMEM —
the [b, max_pages*page_size] gather of the jnp fallback
(ops/paged_attention.py) never materializes.

Grid ``(b, n_kv_heads, max_pages_per_seq)``, pages innermost: on TPU the
grid is a sequential loop, so the online-softmax state (running max m,
normalizer l, fp32 accumulator) lives in VMEM scratch and carries across
page iterations of one (sequence, kv-head) pair — the same blockwise
scheme as ops/pallas/flash_attention.py, with pages playing the role of KV
blocks.  Pages past a row's context (``j*page_size > pos``) are skipped
with @pl.when; GQA is native (q grouped [b, nkv, group, d], no K/V
expansion).

Numerics match the fallback: fp32 logits/softmax/accumulator, outputs cast
to the query dtype.

Quantized pools (ops/kv_quant.QuantPagedKV, ``--kv_dtype int8/fp8``): the
page blocks arrive in their storage dtype and each grid step additionally
receives that (page, kv-head)'s scale as a ``[1, 1]`` block — the
int8/fp8 -> fp32 cast and the scale multiply happen right after the page
DMA, inside the same step that consumes the page, so HBM traffic is the
quantized bytes (the whole point: ~2x the pages per chip at the same
bandwidth).  The online-softmax math is unchanged — dequantized pages
enter the identical fp32 score/accumulate pipeline, matching the jnp
fallback's dequantize-at-gather numerics.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from megatron_llm_tpu.ops import kv_quant

NEG_INF = -1e30


def _split_quant(k_pool, v_pool):
    """(k_arr, v_arr, k_scale, v_scale) — scales are None for plain
    pools.  The wrappers pass scales as extra [1, 1]-blocked operands so
    the kernels dequantize in-register after the page DMA."""
    if kv_quant.is_quantized(k_pool):
        return k_pool.q, v_pool.q, k_pool.scale, v_pool.scale
    return k_pool, v_pool, None, None


def _decode_kernel(
    # scalar prefetch
    bt_ref,      # [b, max_pages] int32 block tables
    pos_ref,     # [b] int32 query positions
    # tensor refs: q, k-page, v-page [, k-scale, v-scale], out + scratch
    # (quantized pools add two [1, 1] scale blocks — see _split_quant)
    *refs,
    scale: float,
    page_size: int,
    sliding_window: Optional[int],
    quantized: bool = False,
):
    if quantized:
        q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, m_s, l_s, acc_s = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_s, l_s, acc_s = refs
        ks_ref = vs_ref = None
    i = pl.program_id(0)
    j = pl.program_id(2)
    first = j * page_size
    pos = pos_ref[i]

    @pl.when(j == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    run = first <= pos
    if sliding_window is not None:
        # page entirely below the window -> nothing to accumulate
        run = jnp.logical_and(run, first + page_size > pos - sliding_window + 1)

    @pl.when(run)
    def _step():
        q = q_ref[0, 0].astype(jnp.float32) * scale   # [g, d]
        k = k_ref[0, :, 0, :].astype(jnp.float32)      # [page, d]
        if quantized:
            # dequant fused into the page step: the DMA moved int8/fp8,
            # the cast+scale happen here in-register
            k = k * ks_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [g, page]
        kv_pos = first + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        mask = kv_pos <= pos
        if sliding_window is not None:
            mask = jnp.logical_and(mask, pos - kv_pos < sliding_window)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_s[:, 0]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.exp(m_prev - m_cur)
        # fully-masked-so-far guard (flash_attention.py:_fwd_kernel): without
        # it exp(NEG_INF - NEG_INF) = 1 would poison the accumulator
        p = jnp.where(s <= NEG_INF * 0.5, 0.0, jnp.exp(s - m_cur[:, None]))
        l_s[:, 0] = alpha * l_s[:, 0] + jnp.sum(p, axis=1)
        m_s[:, 0] = m_cur
        v = v_ref[0, :, 0, :].astype(jnp.float32)      # [page, d]
        if quantized:
            v = v * vs_ref[0, 0]
        acc_s[:] = acc_s[:] * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(j == pl.num_programs(2) - 1)
    def _finish():
        l = l_s[:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_s[:] / l_safe[:, None]).astype(o_ref.dtype)


def _prefill_kernel(
    # scalar prefetch
    bt_ref,      # [b, kv_pages] int32 block tables (chunk horizon)
    pos_ref,     # [b] int32 position of the chunk's first query
    # tensor refs: q [1,1,s*g,d], k/v pages [, k/v scales], out + scratch
    *refs,
    scale: float,
    page_size: int,
    group: int,
    sliding_window: Optional[int],
    quantized: bool = False,
):
    """Chunked-prefill sibling of :func:`_decode_kernel`: same grid layout
    and online-softmax page loop, but ``s*group`` query rows per
    (sequence, kv-head) pair, each at its own position ``pos0 + row//group``
    — the causal mask is per ROW, not per sequence.  Pages past the LAST
    query's position are skipped; rows whose own position is below a page
    mask it off inside the page step."""
    if quantized:
        q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, m_s, l_s, acc_s = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_s, l_s, acc_s = refs
        ks_ref = vs_ref = None
    i = pl.program_id(0)
    j = pl.program_id(2)
    first = j * page_size
    pos0 = pos_ref[i]
    rows = q_ref.shape[2]
    s_chunk = rows // group
    last_pos = pos0 + s_chunk - 1

    @pl.when(j == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    run = first <= last_pos
    if sliding_window is not None:
        # page entirely below every query row's window -> skip
        run = jnp.logical_and(
            run, first + page_size > pos0 - sliding_window + 1)

    @pl.when(run)
    def _step():
        q = q_ref[0, 0].astype(jnp.float32) * scale    # [rows, d]
        k = k_ref[0, :, 0, :].astype(jnp.float32)       # [page, d]
        if quantized:
            k = k * ks_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [rows, page]
        kv_pos = first + jax.lax.broadcasted_iota(
            jnp.int32, (rows, page_size), 1)
        q_pos = pos0 + jax.lax.broadcasted_iota(
            jnp.int32, (rows, page_size), 0) // group
        mask = kv_pos <= q_pos
        if sliding_window is not None:
            mask = jnp.logical_and(mask, q_pos - kv_pos < sliding_window)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_s[:, 0]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.where(s <= NEG_INF * 0.5, 0.0, jnp.exp(s - m_cur[:, None]))
        l_s[:, 0] = alpha * l_s[:, 0] + jnp.sum(p, axis=1)
        m_s[:, 0] = m_cur
        v = v_ref[0, :, 0, :].astype(jnp.float32)       # [page, d]
        if quantized:
            v = v * vs_ref[0, 0]
        acc_s[:] = acc_s[:] * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(j == pl.num_programs(2) - 1)
    def _finish():
        l = l_s[:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_s[:] / l_safe[:, None]).astype(o_ref.dtype)


def _ragged_kernel(
    # scalar prefetch — the per-row ragged metadata (ISSUE 11): the
    # tick's UNIQUE block tables, each row's table index, query position
    # and bucketed kv horizon all arrive as data-carried prefetch
    # operands, so ONE compiled launch serves any tick composition
    # (decode slots, verify blocks, prefill chunks)
    tbl_ref,     # [T, max_pages] int32 unique block tables
    idx_ref,     # [R] int32 row -> table
    pos_ref,     # [R] int32 query positions
    hor_ref,     # [R] int32 kv horizons (tokens, 0 = dead row)
    # tensor refs: q, k-page, v-page [, k-scale, v-scale], out + scratch
    *refs,
    scale: float,
    page_size: int,
    sliding_window: Optional[int],
    quantized: bool = False,
):
    """Ragged sibling of :func:`_decode_kernel`: one query row per grid
    step, same online-softmax page walk, but the page loop is bounded by
    the row's own data-carried horizon — a dead row (horizon 0, the fixed
    batch's padding) touches no page at all, and the accumulated work per
    row scales with that row's context, not the widest row's."""
    if quantized:
        q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, m_s, l_s, acc_s = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_s, l_s, acc_s = refs
        ks_ref = vs_ref = None
    i = pl.program_id(0)
    j = pl.program_id(2)
    first = j * page_size
    pos = pos_ref[i]
    hor = hor_ref[i]

    @pl.when(j == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    # first <= pos gives bitwise the decode kernel's page set for live
    # rows (hor >= pos + 1 by construction); first < hor kills dead rows
    run = jnp.logical_and(first <= pos, first < hor)
    if sliding_window is not None:
        run = jnp.logical_and(run, first + page_size > pos - sliding_window + 1)

    @pl.when(run)
    def _step():
        q = q_ref[0, 0].astype(jnp.float32) * scale   # [g, d]
        k = k_ref[0, :, 0, :].astype(jnp.float32)      # [page, d]
        if quantized:
            k = k * ks_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [g, page]
        kv_pos = first + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        mask = kv_pos <= pos
        if sliding_window is not None:
            mask = jnp.logical_and(mask, pos - kv_pos < sliding_window)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_s[:, 0]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.where(s <= NEG_INF * 0.5, 0.0, jnp.exp(s - m_cur[:, None]))
        l_s[:, 0] = alpha * l_s[:, 0] + jnp.sum(p, axis=1)
        m_s[:, 0] = m_cur
        v = v_ref[0, :, 0, :].astype(jnp.float32)      # [page, d]
        if quantized:
            v = v * vs_ref[0, 0]
        acc_s[:] = acc_s[:] * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(j == pl.num_programs(2) - 1)
    def _finish():
        l = l_s[:, 0]
        # dead rows never ran a page: l == 0 -> exact zeros out
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_s[:] / l_safe[:, None]).astype(o_ref.dtype)


def paged_ragged_kernel(
    q: jax.Array,             # [R, 1, n_heads, d]
    k_pool: jax.Array,        # [num_pages, page_size, n_kv_heads, d]
    v_pool: jax.Array,        # [num_pages, page_size, n_kv_heads, d]
    tables: jax.Array,        # [T, max_pages_per_seq] int32 unique tables
    table_index: jax.Array,   # [R] int32 row -> table
    positions: jax.Array,     # [R] int32
    horizons: jax.Array,      # [R] int32 bucketed kv horizon (0 = dead)
    *,
    scale: float,
    sliding_window: Optional[int] = None,
    interpret: bool = False,
) -> jax.Array:
    """Dispatch wrapper; returns [R, 1, n_heads, d] in q's dtype.

    ONE launch for a whole ragged tick: every row of a mixed decode /
    spec-verify / prefill batch is a grid step over its own block table
    — resolved as ``tables[table_index[row], page]`` in the BlockSpec
    index map, with (position, horizon) scalar-prefetched alongside.
    All four operands are traced data — composition changes re-dispatch
    the same executable, never recompile."""
    k_arr, v_arr, k_scale, v_scale = _split_quant(k_pool, v_pool)
    quantized = k_scale is not None
    b, _, n, d = q.shape
    num_pages, page_size, nkv, _ = k_arr.shape
    assert n % nkv == 0
    g = n // nkv
    max_pages = tables.shape[1]

    qg = q.reshape(b, nkv, g, d)
    grid = (b, nkv, max_pages)

    kernel = functools.partial(
        _ragged_kernel, scale=scale, page_size=page_size,
        sliding_window=sliding_window, quantized=quantized,
    )
    page_spec = pl.BlockSpec((1, page_size, 1, d),
                             lambda i, h, j, tbl, idx, pos, hor:
                             (tbl[idx[i], j], 0, h, 0))
    in_specs = [
        pl.BlockSpec((1, 1, g, d),
                     lambda i, h, j, tbl, idx, pos, hor: (i, h, 0, 0)),
        page_spec,
        page_spec,
    ]
    operands = [qg, k_arr, v_arr]
    if quantized:
        # per-(page, head) dequant scale rides the page DMA as a [1, 1]
        # block — the cast+multiply fuse into the page step
        scale_spec = pl.BlockSpec((1, 1),
                                  lambda i, h, j, tbl, idx, pos, hor:
                                  (tbl[idx[i], j], h))
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, g, d),
                               lambda i, h, j, tbl, idx, pos, hor:
                               (i, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((g, 1), jnp.float32),
            pltpu.VMEM((g, 1), jnp.float32),
            pltpu.VMEM((g, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, nkv, g, d), q.dtype),
        interpret=interpret,
    )(tables.astype(jnp.int32), table_index.astype(jnp.int32),
      positions.astype(jnp.int32), horizons.astype(jnp.int32),
      *operands)
    return out.reshape(b, 1, n, d)


def paged_prefill_kernel(
    q: jax.Array,             # [b, s, n_heads, d]
    k_pool: jax.Array,        # [num_pages, page_size, n_kv_heads, d]
    v_pool: jax.Array,        # [num_pages, page_size, n_kv_heads, d]
    block_tables: jax.Array,  # [b, kv_pages] int32 (chunk horizon)
    start: jax.Array,         # [b] int32 — position of q[:, 0]
    *,
    scale: float,
    sliding_window: Optional[int] = None,
    interpret: bool = False,
) -> jax.Array:
    """Dispatch wrapper; returns [b, s, n_heads, d] in q's dtype."""
    k_arr, v_arr, k_scale, v_scale = _split_quant(k_pool, v_pool)
    quantized = k_scale is not None
    b, s, n, d = q.shape
    num_pages, page_size, nkv, _ = k_arr.shape
    assert n % nkv == 0
    g = n // nkv
    kv_pages = block_tables.shape[1]

    # kv-head-major query rows: [b, nkv, s*g, d] so one grid step sees all
    # of a kv head's query rows for the chunk
    qg = q.reshape(b, s, nkv, g, d).transpose(0, 2, 1, 3, 4)
    qg = qg.reshape(b, nkv, s * g, d)
    grid = (b, nkv, kv_pages)

    kernel = functools.partial(
        _prefill_kernel, scale=scale, page_size=page_size, group=g,
        sliding_window=sliding_window, quantized=quantized,
    )
    page_spec = pl.BlockSpec((1, page_size, 1, d),
                             lambda i, h, j, bt, pos: (bt[i, j], 0, h, 0))
    in_specs = [
        pl.BlockSpec((1, 1, s * g, d),
                     lambda i, h, j, bt, pos: (i, h, 0, 0)),
        page_spec,
        page_spec,
    ]
    operands = [qg, k_arr, v_arr]
    if quantized:
        scale_spec = pl.BlockSpec((1, 1),
                                  lambda i, h, j, bt, pos: (bt[i, j], h))
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, s * g, d),
                               lambda i, h, j, bt, pos: (i, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((s * g, 1), jnp.float32),
            pltpu.VMEM((s * g, 1), jnp.float32),
            pltpu.VMEM((s * g, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, nkv, s * g, d), q.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), start.astype(jnp.int32),
      *operands)
    return out.reshape(b, nkv, s, g, d).transpose(0, 2, 1, 3, 4).reshape(
        b, s, n, d)


def paged_decode_kernel(
    q: jax.Array,             # [b, 1, n_heads, d]
    k_pool: jax.Array,        # [num_pages, page_size, n_kv_heads, d]
    v_pool: jax.Array,        # [num_pages, page_size, n_kv_heads, d]
    block_tables: jax.Array,  # [b, max_pages_per_seq] int32
    positions: jax.Array,     # [b] int32
    *,
    scale: float,
    sliding_window: Optional[int] = None,
    interpret: bool = False,
) -> jax.Array:
    """Dispatch wrapper; returns [b, 1, n_heads, d] in q's dtype."""
    k_arr, v_arr, k_scale, v_scale = _split_quant(k_pool, v_pool)
    quantized = k_scale is not None
    b, _, n, d = q.shape
    num_pages, page_size, nkv, _ = k_arr.shape
    assert n % nkv == 0
    g = n // nkv
    max_pages = block_tables.shape[1]

    qg = q.reshape(b, nkv, g, d)
    grid = (b, nkv, max_pages)

    kernel = functools.partial(
        _decode_kernel, scale=scale, page_size=page_size,
        sliding_window=sliding_window, quantized=quantized,
    )
    page_spec = pl.BlockSpec((1, page_size, 1, d),
                             lambda i, h, j, bt, pos: (bt[i, j], 0, h, 0))
    in_specs = [
        pl.BlockSpec((1, 1, g, d),
                     lambda i, h, j, bt, pos: (i, h, 0, 0)),
        page_spec,
        page_spec,
    ]
    operands = [qg, k_arr, v_arr]
    if quantized:
        scale_spec = pl.BlockSpec((1, 1),
                                  lambda i, h, j, bt, pos: (bt[i, j], h))
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, g, d),
                               lambda i, h, j, bt, pos: (i, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((g, 1), jnp.float32),
            pltpu.VMEM((g, 1), jnp.float32),
            pltpu.VMEM((g, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, nkv, g, d), q.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), positions.astype(jnp.int32),
      *operands)
    return out.reshape(b, 1, n, d)
