"""Quantized paged KV-cache storage: int8/fp8 pages with per-page scales.

Decode serving is pool-capacity-bound before it is FLOP-bound: every
concurrency, prefix-cache and speculative-depth limit in the engine traces
back to bf16 KV bytes per page (generation/engine.py), and the draft cache
(ISSUE 9) doubled the pressure.  This module stores the paged pool in int8
(or fp8 e4m3) with **per-page, per-KV-head symmetric absmax scales** — the
page is the pool's unit of allocation, sharing and eviction, so it is also
the right unit of quantization: a page that moves through the prefix trie,
a COW clone or a preemption park carries exactly one scale row with it.

Layout (:class:`QuantPagedKV`, a pytree NamedTuple):

* ``q``     — ``[..., num_pages, page_size, nkv, d]`` int8 / float8_e4m3fn
* ``scale`` — ``[..., num_pages, nkv]`` float32, ``x ~= q * scale``

Both leaves carry the same leading dims as the bf16 pool (the stacked
layer axis included), so ``lax.scan`` over layers, ``jax.tree.map`` page
copies and buffer donation all work unchanged.

Write path (:func:`paged_write`): the engine's three write shapes — the
decode/ragged tick (R single-token rows), chunked prefill (whole chunks
through the block table) and the spec draft scan — all reduce to "R rows,
each one token at ``(page_ids[r], offs[r])``".  Quantized writes must be
page-granular *and* collision-safe (consecutive rows of one chunk or one
verify block land in the SAME page), so the update runs in three phases
whose scatters are each well-defined under duplicate page ids:

1. **scale update** — a page receiving an ``offs == 0`` write is FRESH
   (its first token; any prior content is a previous tenant's garbage):
   its scale resets to this tick's contribution.  Otherwise the scale is
   ``max(old, incoming)`` — per-page absmax never shrinks while the page
   is live.  Both are scatter-``max`` reductions: duplicates compose.
2. **page requantize** — surviving content of written pages is re-rounded
   under the (possibly grown) scale: ``q' = round(q * old/new)``.  The
   rescale depends only on (old page content, old scale, new scale), so
   every duplicate gathered copy computes IDENTICAL bytes and the
   scatter-back is deterministic.  Unchanged scales round-trip exactly
   (``round(q * 1.0) == q``); fresh pages zero (``ratio == 0``).
3. **token write** — each row's value quantized under the new scale at
   its own ``(page, offset)``.  Live rows write disjoint slots by the
   engine's write-then-attend construction; only the reserved null page
   sees duplicates, and its content is garbage by design.

Error bound (tests/test_kv_quant.py): a single whole-page quantization is
the classic symmetric-absmax bound ``|x - q*s| <= s/2`` (``s =
absmax/QMAX``).  A decode append that GROWS the page scale re-rounds
prior tokens once more, each growth adding ``<= s_new/2`` — the exact
analytic bound for a token is ``s_at_write/2 + sum(s_g/2)`` over the
scale growths after it (whole-page writes — prefill chunks — see none of
this: they quantize in one shot).  In practice the re-rounding errors
random-walk rather than add, and measured append error stays under
``2 * s_final/2`` — the single-growth figure :func:`kv_error_bound`
reports as the rule of thumb.

Read path: the jnp fallbacks dequantize at the page gather
(:func:`dequant_gather`); the Pallas kernels take the scale as an extra
blockspec'd operand so the int8->f32 cast and the scale multiply fuse into
the page DMA — HBM traffic stays int8 (ops/pallas/paged_attention.py).

``kv_dtype="bf16"`` never touches this module: the engine keeps plain
arrays and every existing bitwise-parity suite holds byte for byte.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Union

import jax
import jax.numpy as jnp

from megatron_llm_tpu.ops.fp8 import E4M3

KV_DTYPES = ("bf16", "int8", "fp8")

# symmetric quantization ranges: int8 uses +-127 (round + clip), fp8 the
# forward format ops/fp8.py already standardizes on (e4m3fn; its
# saturation value — cast after clip, e4m3fn has no inf to absorb
# overflow, a clipped cast keeps garbage finite)
_QMAX = {"int8": 127.0, "fp8": float(jnp.finfo(E4M3).max)}
_QDTYPE = {"int8": jnp.int8, "fp8": E4M3}

# scale floor for divisions only (stored scales keep their true value —
# an all-zero page dequantizes to exact zeros)
_EPS = 1e-20


class QuantPagedKV(NamedTuple):
    """One quantized paged cache: values + per-page, per-head scales."""

    q: jax.Array       # [..., num_pages, page_size, nkv, d] int8/fp8
    scale: jax.Array   # [..., num_pages, nkv] float32


PagedKV = Union[jax.Array, QuantPagedKV]


def is_quantized(pool: PagedKV) -> bool:
    return isinstance(pool, QuantPagedKV)


def qmax_for(kv_dtype: str) -> float:
    return _QMAX[kv_dtype]


def storage_dtype(kv_dtype: str):
    return _QDTYPE[kv_dtype]


def make_pool(shape, kv_dtype: str, compute_dtype) -> PagedKV:
    """Zero-initialized pool of ``shape`` = [..., P, page, nkv, d]:
    a plain ``compute_dtype`` array for ``bf16``, a
    :class:`QuantPagedKV` otherwise."""
    assert kv_dtype in KV_DTYPES, f"kv_dtype must be one of {KV_DTYPES}"
    if kv_dtype == "bf16":
        return jnp.zeros(shape, compute_dtype)
    return QuantPagedKV(
        q=jnp.zeros(shape, _QDTYPE[kv_dtype]),
        scale=jnp.zeros(shape[:-3] + (shape[-2],), jnp.float32),
    )


def page_size_of(pool: PagedKV) -> int:
    arr = pool.q if is_quantized(pool) else pool
    return arr.shape[-3]


def pool_nbytes(pool: PagedKV) -> int:
    """Device bytes of the pool's KV storage (scales counted separately
    by :func:`scale_nbytes` — the capacity bench and /metrics report the
    split so the per-page overhead stays visible)."""
    arr = pool.q if is_quantized(pool) else pool
    return arr.size * arr.dtype.itemsize


def scale_nbytes(pool: PagedKV) -> int:
    return pool.scale.size * pool.scale.dtype.itemsize if is_quantized(
        pool) else 0


def _qmax_of(pool: QuantPagedKV) -> float:
    return _QMAX["int8"] if pool.q.dtype == jnp.int8 else _QMAX["fp8"]


def _cast_q(x32: jax.Array, qdtype) -> jax.Array:
    """fp32 -> storage rounding: round+clip for int8, clipped RNE cast
    for fp8 (saturation keeps even garbage pages finite)."""
    if qdtype == jnp.int8:
        return jnp.clip(jnp.round(x32), -127.0, 127.0).astype(jnp.int8)
    return jnp.clip(x32, -_QMAX["fp8"], _QMAX["fp8"]).astype(qdtype)


def quantize_pages(vals: jax.Array, kv_dtype: str) -> QuantPagedKV:
    """Whole-page quantization of ``vals`` [..., page, nkv, d]: the
    monolithic-prefill scatter path, and the single-shot form the error
    bound is stated against."""
    qmax = _QMAX[kv_dtype]
    v32 = vals.astype(jnp.float32)
    scale = jnp.max(jnp.abs(v32), axis=(-3, -1)) / qmax  # [..., nkv]
    den = jnp.maximum(scale, _EPS)
    q = _cast_q(v32 / den[..., None, :, None], _QDTYPE[kv_dtype])
    return QuantPagedKV(q=q, scale=scale)


def dequantize_pages(pages: QuantPagedKV, dtype) -> jax.Array:
    """[..., page, nkv, d] values back in ``dtype``."""
    return (pages.q.astype(jnp.float32)
            * pages.scale[..., None, :, None]).astype(dtype)


def kv_error_bound(vals: jax.Array, kv_dtype: str,
                   appends: bool = False) -> float:
    """Max absolute dequantization error for page content ``vals``
    [..., page, nkv, d]: ``scale/2`` per (page, head) for a single-shot
    page quantization; ``appends`` doubles it — the single-growth figure
    (one extra re-rounding under the final scale), the empirical rule of
    thumb for decode-append pages (module docstring; the exact
    multi-growth bound is the per-growth sum tracked in
    tests/test_kv_quant.py::test_append_requant_error_bound)."""
    qmax = _QMAX[kv_dtype]
    scale = jnp.max(jnp.abs(vals.astype(jnp.float32)), axis=(-3, -1)) / qmax
    bound = float(jnp.max(scale)) / 2.0
    return 2.0 * bound if appends else bound


# ---------------------------------------------------------------------------
# The write path
# ---------------------------------------------------------------------------


def paged_write(pool: PagedKV, page_ids: jax.Array, offs: jax.Array,
                vals: jax.Array) -> PagedKV:
    """Write ``vals[b, s, nkv, d]`` at ``(page_ids[b, s], offs[b, s])``.

    Plain pools keep the engine's original scatter expression byte for
    byte (the ``--kv_dtype bf16`` bitwise contract).  Quantized pools run
    the three-phase page-granular update from the module docstring."""
    if not is_quantized(pool):
        return pool.at[page_ids, offs].set(vals.astype(pool.dtype))
    b, s = page_ids.shape
    return _quant_write_rows(
        pool, page_ids.reshape(b * s), offs.reshape(b * s),
        vals.reshape(b * s, *vals.shape[2:]))


def _quant_write_rows(pool: QuantPagedKV, page_ids: jax.Array,
                      offs: jax.Array, vals: jax.Array) -> QuantPagedKV:
    """R rows, one token each; collision-safe (see module docstring)."""
    qdtype = pool.q.dtype
    qmax = _qmax_of(pool)
    num_pages = pool.q.shape[0]
    v32 = vals.astype(jnp.float32)                        # [R, nkv, d]
    s_row = jnp.max(jnp.abs(v32), axis=-1) / qmax         # [R, nkv]

    # 1) scale update.  offs == 0 marks the page's FIRST token: everything
    # in it is a previous tenant's garbage, so the old scale (and content)
    # must not leak into the new tenant's quantization.
    fresh_rows = (offs == 0).astype(jnp.int32)
    fresh = jnp.zeros((num_pages,), jnp.int32).at[page_ids].max(fresh_rows)
    old_scale = pool.scale                                 # [P, nkv]
    kept_scale = jnp.where(fresh[:, None] > 0, 0.0, old_scale)
    new_scale = kept_scale.at[page_ids].max(s_row)         # [P, nkv]
    den = jnp.maximum(new_scale, _EPS)

    # 2) requantize surviving content of the written pages.  ``ratio``
    # is per PAGE, so duplicate gathered copies rescale identically and
    # the scatter-back is deterministic; fresh pages zero out (ratio 0),
    # untouched positions under an unchanged scale round-trip exactly.
    ratio = (kept_scale / den)[page_ids]                   # [R, nkv]
    gathered = pool.q[page_ids].astype(jnp.float32)        # [R, page, nkv, d]
    requant = _cast_q(gathered * ratio[:, None, :, None], qdtype)
    q = pool.q.at[page_ids].set(requant)

    # 3) the tokens themselves, under the new scale
    tok_q = _cast_q(v32 / den[page_ids][..., None], qdtype)
    q = q.at[page_ids, offs].set(tok_q)
    return QuantPagedKV(q=q, scale=new_scale)


def scatter_whole_pages(pool: PagedKV, page_ids: jax.Array,
                        pages: jax.Array) -> PagedKV:
    """Replace whole pages: ``pages`` is [..., n, page, nkv, d] computed
    content for ``page_ids`` [n] — the monolithic-prefill path.  Plain
    pools keep the original ``.at[:, page_ids].set`` expression; quantized
    pools quantize each page in one shot (the tight error bound)."""
    if not is_quantized(pool):
        return pool.at[:, page_ids].set(pages.astype(pool.dtype))
    qp = quantize_pages(pages, "int8" if pool.q.dtype == jnp.int8 else "fp8")
    return QuantPagedKV(
        q=pool.q.at[:, page_ids].set(qp.q),
        scale=pool.scale.at[:, page_ids].set(qp.scale),
    )


# ---------------------------------------------------------------------------
# The read path (jnp fallbacks; the Pallas kernels dequant in-kernel)
# ---------------------------------------------------------------------------


def dequant_gather(pool: PagedKV, block_tables: jax.Array,
                   dtype: Optional[jnp.dtype] = None) -> jax.Array:
    """[T, W*page, nkv, d] dense view of the block-tabled pages.

    Plain pools return the engine's original gather untouched (bitwise);
    quantized pools dequantize at the gather — ``dtype`` (the query/compute
    dtype) is the dequant target."""
    T = block_tables.shape[0]
    if not is_quantized(pool):
        nkv, d = pool.shape[-2], pool.shape[-1]
        return pool[block_tables].reshape(T, -1, nkv, d)
    nkv, d = pool.q.shape[-2], pool.q.shape[-1]
    dt = dtype if dtype is not None else jnp.float32
    g = pool.q[block_tables].astype(jnp.float32)   # [T, W, page, nkv, d]
    s = pool.scale[block_tables]                   # [T, W, nkv]
    return (g * s[..., None, :, None]).astype(dt).reshape(T, -1, nkv, d)
