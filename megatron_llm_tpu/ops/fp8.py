"""FP8 mixed-precision matmuls — the TransformerEngine-path analog.

Reference surface: the optional ``--transformer_impl transformer_engine``
path with ``--fp8_e4m3`` / ``--fp8_hybrid`` / ``--fp8_margin`` flags
(transformer.py:1009-1028,1063-1090; arguments.py:372-392), which wraps
layers in TE modules doing fp8 GEMMs with per-tensor scaling.

TPU-native redesign:

* **Formats** follow the TE convention: e4m3 for forward tensors (weights,
  activations), and under ``hybrid``, e5m2 for gradients (wider range,
  less precision — gradients tolerate it).
* **Current scaling instead of delayed scaling.** TE keeps an amax history
  per tensor and scales with a lagged maximum because a fresh amax pass
  costs an extra kernel + sync on GPUs. Under XLA the amax reduction fuses
  into the producing op, so we compute the true amax of the tensor being
  quantized every time — simpler (no state threaded through the scan) and
  strictly more accurate. ``fp8_margin`` still backs the scale off by
  2^-margin as in TE.
* **custom_vjp**: forward runs Q(x)·Q(w) in fp8 with a bf16/fp32
  accumulator; backward quantizes the incoming gradient (e5m2 under
  hybrid, e4m3 otherwise) and runs the two transposed fp8 GEMMs. Scales
  are applied outside the dot so the quantized operands use the full fp8
  range.

On TPU generations without native fp8 MXU support (v5e and earlier) XLA
upcasts the operands — the path is functional (numerics tests run
everywhere) and becomes a throughput win on fp8-capable parts. This is the
same posture as the reference, where TE is optional and hardware-gated.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

E4M3 = jnp.float8_e4m3fn
E5M2 = jnp.float8_e5m2


def quantize(x: jax.Array, dtype, margin: int = 0):
    """Scale ``x`` to the full range of ``dtype`` and cast.

    Returns (x_q, inv_scale) with ``x ≈ x_q.astype(f32) * inv_scale``.
    The scale is a per-tensor power-of-two-free fp32 scalar, backed off by
    2^-margin (TE fp8_margin semantics).
    """
    fmax = float(jnp.finfo(dtype).max) * (2.0 ** -margin)
    amax = jnp.max(jnp.abs(x)).astype(jnp.float32)
    scale = fmax / jnp.maximum(amax, 1e-12)
    x_q = (x.astype(jnp.float32) * scale).astype(dtype)
    return x_q, 1.0 / scale


def _fp8_matmul(x, w, x_dtype, w_dtype, margin, out_dtype):
    """Q(x) @ Q(w) with the combined dequant scale applied to the output."""
    x_q, sx = quantize(x, x_dtype, margin)
    w_q, sw = quantize(w, w_dtype, margin)
    acc = jnp.dot(x_q, w_q, preferred_element_type=jnp.float32)
    return (acc * (sx * sw)).astype(out_dtype)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def fp8_dot(x: jax.Array, w: jax.Array, hybrid: bool = True, margin: int = 0):
    """``x @ w`` with both operands quantized to e4m3 (TE forward format).

    ``x``: [..., k]; ``w``: [k, n]. Backward quantizes the cotangent to
    e5m2 when ``hybrid`` (the reference's --fp8_hybrid) else e4m3
    (--fp8_e4m3), matching TE's format split.
    """
    lead = x.shape[:-1]
    x2 = x.reshape(-1, x.shape[-1])
    y = _fp8_matmul(x2, w, E4M3, E4M3, margin, x.dtype)
    return y.reshape(*lead, w.shape[-1])


def _fp8_dot_fwd(x, w, hybrid, margin):
    return fp8_dot(x, w, hybrid, margin), (x, w)


def _fp8_dot_bwd(hybrid, margin, res, dy):
    x, w = res
    g_dtype = E5M2 if hybrid else E4M3
    lead = x.shape[:-1]
    x2 = x.reshape(-1, x.shape[-1])
    dy2 = dy.reshape(-1, dy.shape[-1])
    # dx = dy @ w^T, dw = x^T @ dy — both as fp8 GEMMs
    dx = _fp8_matmul(dy2, w.T, g_dtype, E4M3, margin, x.dtype)
    dw = _fp8_matmul(x2.T, dy2, E4M3, g_dtype, margin, w.dtype)
    return dx.reshape(*lead, x.shape[-1]), dw


fp8_dot.defvjp(_fp8_dot_fwd, _fp8_dot_bwd)


def fp8_linear(p, x: jax.Array, hybrid: bool = True, margin: int = 0):
    """Drop-in fp8 variant of the transformer's ``_linear`` (kernel [k, n]
    or GLU [k, 2, n]; bias, if any, is added in the compute dtype outside
    the quantized GEMM, as TE does)."""
    kernel = p["kernel"].astype(x.dtype)
    glu = kernel.ndim == 3
    k = kernel.shape[0]
    w = kernel.reshape(k, -1) if glu else kernel
    y = fp8_dot(x, w, hybrid, margin)
    if glu:
        y = y.reshape(*y.shape[:-1], *kernel.shape[1:])
    if "bias" in p:
        y = y + p["bias"].astype(x.dtype)
    return y


def linear_for_config(cfg):
    """Return a ``linear(p, x)`` implementation per the config's fp8 mode
    (None | 'e4m3' | 'hybrid' — arguments.py:372-392 flag bundle), or None
    for the plain high-precision path.

    Scope: the dense projections (qkv/dense/fc1/fc2 and T5 cross-attention).
    MoE expert GEMMs (models/moe.py batched einsums) intentionally stay in
    the compute dtype — per-expert tensors need per-expert scales to
    quantize well, which would couple this module to the dispatch layout;
    documented in docs/guide/moe.md."""
    mode = getattr(cfg.model, "fp8", None)
    if mode is None:
        return None
    assert mode in ("e4m3", "hybrid"), f"unknown fp8 mode {mode!r}"
    margin = getattr(cfg.model, "fp8_margin", 0)
    return partial(fp8_linear, hybrid=(mode == "hybrid"), margin=margin)
