"""Paged KV-cache decode attention: block-table gather + masked softmax.

The serving engine (generation/engine.py) stores the KV cache as a pool of
fixed-size pages ``[num_pages, page_size, n_kv_heads, head_dim]`` shared by
all in-flight sequences; each sequence owns an ordered list of page ids (its
*block table*).  This module computes one decode step of attention for a
batch of sequences at heterogeneous positions — the Ragged-Paged-Attention
decomposition (PAPERS.md): a single fused program per tick regardless of the
per-sequence context lengths.

Two implementations with identical numerics:

* ``ops/pallas/paged_attention.py`` — the TPU kernel: the block table is a
  scalar-prefetch operand, so each grid step DMAs exactly one page from the
  HBM pool into VMEM (no [b, max_seq] gather ever materializes) and the
  online-softmax accumulator carries across pages.
* the jnp fallback below — gathers the block-tabled pages into a dense
  [b, max_seq] view and reuses :func:`ops.attention.xla_attention`.  It is
  bitwise-identical to the dense-cache decode path on the same context (the
  parity contract tier-1 enforces on CPU, tests/test_paged_engine.py).

Page 0 of the pool is reserved as the *null page*: the engine never
allocates it, inactive slots' block tables point at it, and writes routed
there are garbage by design (they are never attended to).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from megatron_llm_tpu.ops import attention as attn_ops


class PagedState(NamedTuple):
    """Per-call addressing state threaded through model_forward.

    Both leaves are traced arrays, so one compiled program serves any
    block-table/position contents (fixed engine shapes, variable routing).

    ``positions`` is the position of the FIRST token in the fed block: the
    decode tick feeds ``[b, 1]`` tokens (one per row at its own position);
    the chunked-prefill path feeds ``[1, chunk]`` tokens occupying positions
    ``positions[0] .. positions[0] + chunk - 1`` of one sequence.
    """

    block_tables: jax.Array  # [b, max_pages_per_seq] int32 page ids
    positions: jax.Array     # [b] int32 — position of tokens[:, 0] per row


def paged_gather_kv(k_pool: jax.Array, v_pool: jax.Array,
                    block_tables: jax.Array):
    """Dense [b, max_pages*page_size, nkv, d] view of each row's pages.

    The fallback's materialized gather — the tensor the Pallas kernel
    exists to avoid."""
    b = block_tables.shape[0]
    nkv, d = k_pool.shape[-2], k_pool.shape[-1]
    k_all = k_pool[block_tables].reshape(b, -1, nkv, d)
    v_all = v_pool[block_tables].reshape(b, -1, nkv, d)
    return k_all, v_all


def paged_attention_decode(
    q: jax.Array,             # [b, 1, n_heads, d] — queries at `positions`
    k_pool: jax.Array,        # [num_pages, page_size, n_kv_heads, d]
    v_pool: jax.Array,        # [num_pages, page_size, n_kv_heads, d]
    block_tables: jax.Array,  # [b, max_pages_per_seq] int32 page ids
    positions: jax.Array,     # [b] int32 — q's position; attends to <= it
    *,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    use_kernel: bool = True,
) -> jax.Array:
    """One decode step of paged attention; returns [b, 1, n_heads, d].

    Row ``i`` attends to cache positions ``[max(0, pos-W+1), pos]`` of its
    own block table (the current token's K/V must already be written to its
    page — the engine writes-then-attends, matching the dense decode path
    in models/transformer.attention_sublayer).
    """
    assert q.ndim == 4 and q.shape[1] == 1, "decode expects [b, 1, n, d]"
    b, _, n, d = q.shape
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    if use_kernel and _kernel_ok(q, k_pool):
        from megatron_llm_tpu.ops.pallas.paged_attention import (
            paged_decode_kernel,
        )

        return paged_decode_kernel(
            q, k_pool, v_pool, block_tables, positions,
            scale=scale, sliding_window=sliding_window,
        )

    k_all, v_all = paged_gather_kv(k_pool, v_pool, block_tables)
    kv_len = k_all.shape[1]
    kv_pos = jnp.arange(kv_len)[None, :]
    allowed = kv_pos <= positions[:, None]
    if sliding_window is not None:
        allowed &= positions[:, None] - kv_pos < sliding_window
    bias = jnp.where(allowed, 0.0, attn_ops.NEG_INF).astype(jnp.float32)
    return attn_ops.xla_attention(
        q, k_all, v_all, bias=bias[:, None, None, :], scale=scale)


def paged_attention_prefill(
    q: jax.Array,             # [b, s, n_heads, d] — chunk queries
    k_pool: jax.Array,        # [num_pages, page_size, n_kv_heads, d]
    v_pool: jax.Array,        # [num_pages, page_size, n_kv_heads, d]
    block_tables: jax.Array,  # [b, kv_pages] int32 — pages covering the chunk
    start: jax.Array,         # [b] int32 — position of q[:, 0]
    *,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    use_kernel: bool = True,
) -> jax.Array:
    """One prefill CHUNK of paged attention; returns [b, s, n_heads, d].

    Query row ``j`` of sequence ``i`` sits at position ``start[i] + j`` and
    attends to cache positions ``<= start[i] + j`` of ``i``'s block table —
    the prefix-length-aware prefill-against-block-table mode: earlier pages
    may have been written by a previous chunk, by a different request's
    prefill (shared prefix-cache pages), or by this very call (the engine
    writes the chunk's own K/V through the block table before attending,
    matching the decode tick's write-then-attend order).

    ``block_tables`` is normally SLICED to the chunk's page horizon
    (``ceil((start + s) / page_size)`` pages, possibly bucket-padded with
    null pages) so the gather/grid cost scales with the attended context,
    not the sequence budget.  Padding pages past a row's context are fully
    masked — exact zeros after softmax, identical numerics either way.
    """
    assert q.ndim == 4, "prefill expects [b, s, n, d]"
    b, s, n, d = q.shape
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    if use_kernel and _kernel_ok(q, k_pool):
        from megatron_llm_tpu.ops.pallas.paged_attention import (
            paged_prefill_kernel,
        )

        return paged_prefill_kernel(
            q, k_pool, v_pool, block_tables, start,
            scale=scale, sliding_window=sliding_window,
        )

    k_all, v_all = paged_gather_kv(k_pool, v_pool, block_tables)
    kv_len = k_all.shape[1]
    q_pos = start[:, None, None] + jnp.arange(s)[None, :, None]  # [b, s, 1]
    kv_pos = jnp.arange(kv_len)[None, None, :]
    allowed = kv_pos <= q_pos
    if sliding_window is not None:
        allowed &= q_pos - kv_pos < sliding_window
    bias = jnp.where(allowed, 0.0, attn_ops.NEG_INF).astype(jnp.float32)
    return attn_ops.xla_attention(
        q, k_all, v_all, bias=bias[:, None, :, :], scale=scale)


def _kernel_ok(q: jax.Array, k_pool: jax.Array) -> bool:
    """Kernel dispatch predicate — mirrors ops/attention.attention: TPU
    compile target, supported head_dim, lane-aligned page."""
    from megatron_llm_tpu.core.parallel_state import target_platform

    d = q.shape[-1]
    page_size = k_pool.shape[1]
    try:
        from megatron_llm_tpu.ops.pallas import paged_attention  # noqa: F401
    except ImportError:
        return False
    return (
        target_platform() == "tpu"
        and d in (64, 128, 256)
        and page_size % 8 == 0
    )
