"""Paged KV-cache decode attention: block-table gather + masked softmax.

The serving engine (generation/engine.py) stores the KV cache as a pool of
fixed-size pages ``[num_pages, page_size, n_kv_heads, head_dim]`` shared by
all in-flight sequences; each sequence owns an ordered list of page ids (its
*block table*).  This module computes one decode step of attention for a
batch of sequences at heterogeneous positions — the Ragged-Paged-Attention
decomposition (PAPERS.md): a single fused program per tick regardless of the
per-sequence context lengths.

Two implementations with identical numerics:

* ``ops/pallas/paged_attention.py`` — the TPU kernel: the block table is a
  scalar-prefetch operand, so each grid step DMAs exactly one page from the
  HBM pool into VMEM (no [b, max_seq] gather ever materializes) and the
  online-softmax accumulator carries across pages.
* the jnp fallback below — gathers the block-tabled pages into a dense
  [b, max_seq] view and reuses :func:`ops.attention.xla_attention`.  It is
  bitwise-identical to the dense-cache decode path on the same context (the
  parity contract tier-1 enforces on CPU, tests/test_paged_engine.py).

Page 0 of the pool is reserved as the *null page*: the engine never
allocates it, inactive slots' block tables point at it, and writes routed
there are garbage by design (they are never attended to).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from megatron_llm_tpu.ops import attention as attn_ops
from megatron_llm_tpu.ops import kv_quant


class PagedState(NamedTuple):
    """Per-call addressing state threaded through model_forward.

    Both leaves are traced arrays, so one compiled program serves any
    block-table/position contents (fixed engine shapes, variable routing).

    ``positions`` is the position of the FIRST token in the fed block: the
    decode tick feeds ``[b, 1]`` tokens (one per row at its own position);
    the chunked-prefill path feeds ``[1, chunk]`` tokens occupying positions
    ``positions[0] .. positions[0] + chunk - 1`` of one sequence.
    """

    block_tables: jax.Array  # [b, max_pages_per_seq] int32 page ids
    positions: jax.Array     # [b] int32 — position of tokens[:, 0] per row
    # RAGGED batch metadata (ISSUE 11).  None selects the legacy
    # decode/prefill dispatch; traced arrays route s == 1 batches to
    # paged_attention_ragged — the single-launch form a mixed
    # prefill+decode+verify tick runs on.  Data-carried, never static:
    # tick composition changes never recompile.
    #
    # ``horizons`` is each row's kv horizon in tokens, bucketed to
    # BUCKET(64)-token multiples (0 = dead padding row, touches no page).
    # When ``table_index`` is set, ``block_tables`` is COMPRESSED to the
    # tick's unique tables [T, max_pages] (one per decode slot + one per
    # packed prefilling request + the null table) and ``table_index``
    # maps each of the R rows to its table — rows of one span share one
    # table, so the fallback gathers each table's pages once instead of
    # once per row.
    horizons: Optional[jax.Array] = None     # [R] int32 or None
    table_index: Optional[jax.Array] = None  # [R] int32 into block_tables


def paged_gather_kv(k_pool, v_pool, block_tables: jax.Array,
                    dtype=None):
    """Dense [b, max_pages*page_size, nkv, d] view of each row's pages.

    The fallback's materialized gather — the tensor the Pallas kernel
    exists to avoid.  Quantized pools (ops/kv_quant.QuantPagedKV)
    dequantize at the gather, into ``dtype`` (the query/compute dtype);
    plain pools return the original gather bitwise."""
    if kv_quant.is_quantized(k_pool):
        return (kv_quant.dequant_gather(k_pool, block_tables, dtype),
                kv_quant.dequant_gather(v_pool, block_tables, dtype))
    b = block_tables.shape[0]
    nkv, d = k_pool.shape[-2], k_pool.shape[-1]
    k_all = k_pool[block_tables].reshape(b, -1, nkv, d)
    v_all = v_pool[block_tables].reshape(b, -1, nkv, d)
    return k_all, v_all


def paged_attention_decode(
    q: jax.Array,             # [b, 1, n_heads, d] — queries at `positions`
    k_pool: jax.Array,        # [num_pages, page_size, n_kv_heads, d]
    v_pool: jax.Array,        # [num_pages, page_size, n_kv_heads, d]
    block_tables: jax.Array,  # [b, max_pages_per_seq] int32 page ids
    positions: jax.Array,     # [b] int32 — q's position; attends to <= it
    *,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    use_kernel: bool = True,
) -> jax.Array:
    """One decode step of paged attention; returns [b, 1, n_heads, d].

    Row ``i`` attends to cache positions ``[max(0, pos-W+1), pos]`` of its
    own block table (the current token's K/V must already be written to its
    page — the engine writes-then-attends, matching the dense decode path
    in models/transformer.attention_sublayer).
    """
    assert q.ndim == 4 and q.shape[1] == 1, "decode expects [b, 1, n, d]"
    b, _, n, d = q.shape
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    if use_kernel and _kernel_ok(q, k_pool):
        from megatron_llm_tpu.ops.pallas.paged_attention import (
            paged_decode_kernel,
        )

        return paged_decode_kernel(
            q, k_pool, v_pool, block_tables, positions,
            scale=scale, sliding_window=sliding_window,
        )

    k_all, v_all = paged_gather_kv(k_pool, v_pool, block_tables, q.dtype)
    kv_len = k_all.shape[1]
    kv_pos = jnp.arange(kv_len)[None, :]
    allowed = kv_pos <= positions[:, None]
    if sliding_window is not None:
        allowed &= positions[:, None] - kv_pos < sliding_window
    bias = jnp.where(allowed, 0.0, attn_ops.NEG_INF).astype(jnp.float32)
    return attn_ops.xla_attention(
        q, k_all, v_all, bias=bias[:, None, None, :], scale=scale)


def paged_attention_ragged(
    q: jax.Array,             # [R, 1, n_heads, d] — one query row per entry
    k_pool: jax.Array,        # [num_pages, page_size, n_kv_heads, d]
    v_pool: jax.Array,        # [num_pages, page_size, n_kv_heads, d]
    tables: jax.Array,        # [T, max_pages_per_seq] int32 — UNIQUE tables
    table_index: jax.Array,   # [R] int32 — each row's table
    positions: jax.Array,     # [R] int32 — each row's own position
    horizons: jax.Array,      # [R] int32 — bucketed kv horizon (0 = dead row)
    *,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    use_kernel: bool = True,
) -> jax.Array:
    """One RAGGED batch of paged attention; returns [R, 1, n_heads, d].

    The ragged decomposition (PAPERS.md "Ragged Paged Attention"): a tick's
    heterogeneous work — decode slots (query span 1), speculative-verify
    blocks (span k+1) and prefill chunks (span = chunk rows) — is flattened
    into R single-token rows, each carrying its own (position, kv horizon,
    block table).  Block tables arrive COMPRESSED: rows of one span share
    one entry of ``tables`` and ``table_index`` names it, so a 64-row
    chunk walks its pages once, not 64 times.  One launch serves any mix;
    the composition lives entirely in the data-carried metadata, so
    changing it never recompiles.

    Numerics contract (tests/test_ragged_tick.py): row ``i`` computes the
    s=1 decode attention at ``positions[i]`` over its own table — bitwise
    what :func:`paged_attention_decode` produces for that row (per-row
    bits are batch-size invariant, and batching scores over the unique
    tables then selecting a row's table is bitwise the per-row gather),
    which is also bitwise what a chunked prefill produces for the same
    (tokens, positions) because masked attention is invariant to
    query-row partitioning when kv horizons stay on the BUCKET(64) grid.
    ``horizons`` bounds the page walk in the Pallas kernel (a dead row —
    horizon 0 — skips every page); the fallback's mask ``kv_pos <=
    positions`` subsumes them.
    """
    assert q.ndim == 4 and q.shape[1] == 1, "ragged rows are [R, 1, n, d]"
    b, _, n, d = q.shape
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    if use_kernel and _kernel_ok(q, k_pool):
        from megatron_llm_tpu.ops.pallas.paged_attention import (
            paged_ragged_kernel,
        )

        return paged_ragged_kernel(
            q, k_pool, v_pool, tables, table_index, positions, horizons,
            scale=scale, sliding_window=sliding_window,
        )

    # fallback: gather each UNIQUE table's pages once, batch the score
    # matmul over all T tables, select each row's table, softmax only the
    # selected scores, then scatter the probs back through a one-hot so
    # the context matmul keeps the shared [T, kv] v layout (no per-row
    # gather ever materializes) — bitwise the per-row-gathered
    # xla_attention decode fallback (same contractions, same per-(row,
    # table) reduction order; only the batching layout moves)
    T = tables.shape[0]
    nkv = (k_pool.q if kv_quant.is_quantized(k_pool) else k_pool).shape[2]
    g = n // nkv
    # [T, kv, nkv, d]
    k_all, v_all = paged_gather_kv(k_pool, v_pool, tables, q.dtype)
    kv_len = k_all.shape[1]
    qg = q.reshape(b, 1, nkv, g, d)
    # [R, T, nkv, g, 1, kv] — the decode fallback's "bqhgd,bkhd->bhgqk"
    # with the table dim batched
    scores = jnp.einsum("bqhgd,tkhd->bthgqk", qg * scale, k_all)
    scores = scores.astype(jnp.float32)
    idx6 = table_index[:, None, None, None, None, None]
    s_sel = jnp.take_along_axis(scores, idx6, axis=1)[:, 0]
    kv_pos = jnp.arange(kv_len)[None, :]
    allowed = kv_pos <= positions[:, None]
    if sliding_window is not None:
        allowed &= positions[:, None] - kv_pos < sliding_window
    bias = jnp.where(allowed, 0.0, attn_ops.NEG_INF).astype(jnp.float32)
    s_sel = s_sel + bias[:, None, None, None, :]
    p_sel = jax.nn.softmax(s_sel, axis=-1).astype(v_all.dtype)
    onehot = (jnp.arange(T)[None, :]
              == table_index[:, None]).astype(v_all.dtype)      # [R, T]
    p_full = p_sel[:, None] * onehot[:, :, None, None, None, None]
    out = jnp.einsum("bthgqk,tkhd->bthgqd", p_full, v_all)
    sel = jnp.take_along_axis(out, idx6, axis=1)[:, 0]
    # [R, nkv, g, 1, d] -> [R, 1, n, d]
    return sel.transpose(0, 3, 1, 2, 4).reshape(b, 1, n, d)


def paged_attention_prefill(
    q: jax.Array,             # [b, s, n_heads, d] — chunk queries
    k_pool: jax.Array,        # [num_pages, page_size, n_kv_heads, d]
    v_pool: jax.Array,        # [num_pages, page_size, n_kv_heads, d]
    block_tables: jax.Array,  # [b, kv_pages] int32 — pages covering the chunk
    start: jax.Array,         # [b] int32 — position of q[:, 0]
    *,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    use_kernel: bool = True,
) -> jax.Array:
    """One prefill CHUNK of paged attention; returns [b, s, n_heads, d].

    Query row ``j`` of sequence ``i`` sits at position ``start[i] + j`` and
    attends to cache positions ``<= start[i] + j`` of ``i``'s block table —
    the prefix-length-aware prefill-against-block-table mode: earlier pages
    may have been written by a previous chunk, by a different request's
    prefill (shared prefix-cache pages), or by this very call (the engine
    writes the chunk's own K/V through the block table before attending,
    matching the decode tick's write-then-attend order).

    ``block_tables`` is normally SLICED to the chunk's page horizon
    (``ceil((start + s) / page_size)`` pages, possibly bucket-padded with
    null pages) so the gather/grid cost scales with the attended context,
    not the sequence budget.  Padding pages past a row's context are fully
    masked — exact zeros after softmax, identical numerics either way.
    """
    assert q.ndim == 4, "prefill expects [b, s, n, d]"
    b, s, n, d = q.shape
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    if use_kernel and _kernel_ok(q, k_pool):
        from megatron_llm_tpu.ops.pallas.paged_attention import (
            paged_prefill_kernel,
        )

        return paged_prefill_kernel(
            q, k_pool, v_pool, block_tables, start,
            scale=scale, sliding_window=sliding_window,
        )

    k_all, v_all = paged_gather_kv(k_pool, v_pool, block_tables, q.dtype)
    kv_len = k_all.shape[1]
    q_pos = start[:, None, None] + jnp.arange(s)[None, :, None]  # [b, s, 1]
    kv_pos = jnp.arange(kv_len)[None, None, :]
    allowed = kv_pos <= q_pos
    if sliding_window is not None:
        allowed &= q_pos - kv_pos < sliding_window
    bias = jnp.where(allowed, 0.0, attn_ops.NEG_INF).astype(jnp.float32)
    return attn_ops.xla_attention(
        q, k_all, v_all, bias=bias[:, None, :, :], scale=scale)


def _kernel_ok(q: jax.Array, k_pool) -> bool:
    """Kernel dispatch predicate — mirrors ops/attention.attention: TPU
    compile target, supported head_dim, lane-aligned page."""
    from megatron_llm_tpu.core.parallel_state import target_platform

    d = q.shape[-1]
    page_size = kv_quant.page_size_of(k_pool)
    try:
        from megatron_llm_tpu.ops.pallas import paged_attention  # noqa: F401
    except ImportError:
        return False
    return (
        target_platform() == "tpu"
        and d in (64, 128, 256)
        and page_size % 8 == 0
    )
