"""Normalization layers.

Replaces the reference's fused CUDA LayerNorm (model/fused_layer_norm.py:26-61,
layer_norm_cuda_kernel.cu) and pure-torch RMSNorm (fused_layer_norm.py:125-139).
On TPU, XLA fuses these elementwise chains well; a Pallas fused RMSNorm kernel
(ops/pallas/rmsnorm.py) is used on TPU for the hot path when enabled.

Math matches the reference: internal computation in fp32, cast back to the
input dtype (RMSNorm: ``x * rsqrt(mean(x^2) + eps) * w``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-6) -> jax.Array:
    """RMSNorm (fused_layer_norm.py:125-139 semantics: fp32 internal math)."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    out = xf * jax.lax.rsqrt(var + eps)
    return (out * weight.astype(jnp.float32)).astype(dtype)


def layer_norm(
    x: jax.Array, weight: jax.Array, bias: jax.Array | None, eps: float = 1e-5
) -> jax.Array:
    """Affine LayerNorm with fp32 internal math (MixedFusedLayerNorm semantics)."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + eps)
    out = out * weight.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(dtype)


def norm(x, params: dict, eps: float, use_rms: bool) -> jax.Array:
    """Dispatch on norm family given a params dict {'scale': ..., 'bias': ...?}."""
    if use_rms:
        return rms_norm(x, params["scale"], eps)
    return layer_norm(x, params["scale"], params.get("bias"), eps)


def init_norm_params(hidden_size: int, use_rms: bool, dtype=jnp.float32) -> dict:
    p = {"scale": jnp.ones((hidden_size,), dtype=dtype)}
    if not use_rms:
        p["bias"] = jnp.zeros((hidden_size,), dtype=dtype)
    return p
