"""Weight-only int8 quantization for inference (beyond-reference).

Decode on TPU is HBM-bandwidth-bound: every generated token re-reads all
transformer weights, so halving the bytes (bf16 -> int8) is a near-2x
lever on tokens/sec (the reference has no quantized-inference path; its
decode reads fp16 weights, text_generation/generation.py:89).

Scheme: symmetric per-output-channel absmax (the standard W8A16 recipe) —
``q = round(w / scale)`` with ``scale = absmax(w, contraction_axis)/127``
— applied ONLY to the transformer-layer linears (``params["layers"]``).
Embeddings, norms, and the lm_head keep their dtype: the head is ~10% of
the 470M decode traffic, and every head consumer (tied path, chunked CE,
pp-vocab pipeline head) reads ``lm_head.kernel`` directly.

At matmul time the int8 kernel is cast to the activation dtype *inside*
the GEMM (models/transformer.py:_linear) — XLA fuses the convert into the
matmul read, so HBM sees int8 and the MXU sees bf16. The per-channel
scale multiplies the GEMM output, after the GLU chunk-axis reshape.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _contraction_axis(kernel, name: str = "") -> int:
    """The contraction (input) axis: -2 for plain kernels ([..., in, out],
    incl. a stacked leading layer axis), -3 for GLU fc1 kernels
    ([..., in, 2, ffn] — the chunk axis of size 2 sits between in and ffn,
    see init_layer_params). The GLU case is keyed on the param PATH (only
    ``fc1`` kernels carry the chunk axis) AND the shape — a bare shape
    sniff would mis-route any non-GLU stacked kernel whose penultimate dim
    happens to be 2 (ADVICE r4 #1)."""
    is_glu_fc1 = name == "fc1" and kernel.ndim >= 3 and kernel.shape[-2] == 2
    return -3 if is_glu_fc1 else -2


def _channel_scale(kernel: jax.Array, axis: int) -> jax.Array:
    scale = jnp.max(jnp.abs(kernel.astype(jnp.float32)), axis=axis) / 127.0
    return jnp.maximum(scale, 1e-8)  # all-zero channels stay harmless


@functools.partial(jax.jit, static_argnames="axis")
def _quant_jit(kernel: jax.Array, axis: int):
    # jitted so XLA fuses the fp32 upcast into the absmax reduction and the
    # round — a 7B stacked fc1 must not materialize a full fp32 copy next
    # to the bf16 weights on a 16 GiB chip
    scale = _channel_scale(kernel, axis)
    q = jnp.round(kernel.astype(jnp.float32)
                  / jnp.expand_dims(scale, axis)).astype(jnp.int8)
    return q, scale


def _quantize_kernel(kernel: jax.Array, name: str = "") -> dict:
    """Per-output-channel symmetric int8 (see :func:`_contraction_axis`)."""
    q, scale = _quant_jit(kernel, _contraction_axis(kernel, name))
    return {"kernel_q": q, "kernel_scale": scale}


def quantize_layer_weights_int8(params: dict) -> dict:
    """Return params with every ``{"kernel": ...}`` linear under
    ``params["layers"]`` replaced by ``{"kernel_q", "kernel_scale"}``
    (biases and everything outside the layer stack untouched).

    Inference-only: the quantized tree is for generation; training
    (and ``cfg.model.fp8``) expects the original ``kernel`` leaves.
    """

    def walk(node, name=""):
        if isinstance(node, dict):
            if "kernel" in node and getattr(node["kernel"], "ndim", 0) >= 2:
                out = {k: v for k, v in node.items() if k != "kernel"}
                out.update(_quantize_kernel(node["kernel"], name))
                return out
            # MoE: expert FFN stacks quantize (their [E,...] kernels carry
            # per-expert channel scales; models/moe.py:_expert_kernel
            # consumes them); the router stays fp32 — routing logits are
            # precision-sensitive and the [h, E] kernel is negligible HBM
            return {k: (v if k == "router" else walk(v, k))
                    for k, v in node.items()}
        return node

    out = dict(params)
    out["layers"] = walk(params["layers"])
    return out


def resolve_kernel(p_lin: dict, dt) -> tuple:
    """(weight-in-dt, optional per-channel scale) for a linear's param dict
    — THE quantized-leaf contract, consumed by transformer._linear and
    moe._expert_kernel; the int8->dt cast fuses into the downstream GEMM
    read so HBM sees int8."""
    if "kernel_q" in p_lin:
        return p_lin["kernel_q"].astype(dt), p_lin["kernel_scale"]
    return p_lin["kernel"].astype(dt), None


def int8_quant_error_bound(kernel: jax.Array, name: str = "") -> float:
    """Max absolute dequantization error = scale/2 per channel (useful in
    tests: |w - q*scale| <= absmax/254 + eps)."""
    scale = _channel_scale(kernel, _contraction_axis(kernel, name))
    return float(jnp.max(scale) / 2.0)
