"""Rotary position embeddings (RoPE) with linear position interpolation.

Reference: megatron/model/positional_embeddings.py — complex-number RoPE with
*interleaved-pair* convention (Meta/Llama native layout: dims (0,1), (2,3), ...
form the rotated pairs), ``precompute_freqs_cis`` at :7 with the 32K-context
linear scaling ``t /= scaling_factor`` at :11, and non-monotonic position_ids
support for packed sequences at :38-47.

We compute in real arithmetic (TPU has no complex MXU path): for each pair
(x_even, x_odd) rotate by angle theta_i * pos. cos/sin are precomputed in
fp32 and applied in fp32 for accuracy, output cast back to input dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def llama3_scale_freqs(
    freqs: jax.Array,
    factor: float,
    low_freq_factor: float = 1.0,
    high_freq_factor: float = 4.0,
    original_max_position: int = 8192,
) -> jax.Array:
    """Llama-3.1 frequency remap (HF ``rope_type: "llama3"``).

    Published piecewise rule: frequencies whose wavelength fits well inside
    the original context (wavelen < orig/high_freq_factor) are kept;
    frequencies whose wavelength exceeds it (wavelen > orig/low_freq_factor)
    are divided by ``factor`` (pure position interpolation); the band in
    between is smoothly interpolated. Beyond-reference: the reference's
    positional_embeddings.py:11 only implements the linear rule.
    """
    wavelen = 2.0 * jnp.pi / freqs
    low_wavelen = original_max_position / low_freq_factor
    high_wavelen = original_max_position / high_freq_factor
    smooth = (original_max_position / wavelen - low_freq_factor) / (
        high_freq_factor - low_freq_factor)
    interp = (1.0 - smooth) * freqs / factor + smooth * freqs
    out = jnp.where(wavelen > low_wavelen, freqs / factor, interp)
    return jnp.where(wavelen < high_wavelen, freqs, out)


def precompute_freqs(
    dim: int,
    max_len: int,
    theta: float = 10000.0,
    scaling_factor: float = 1.0,
    scaling_type: str = "linear",
    llama3_params: dict | None = None,
    dtype=jnp.float32,
):
    """Return (cos, sin), each [max_len, dim//2], fp32.

    positional_embeddings.py:7-21 semantics incl. position interpolation
    (positions divided by scaling_factor). ``scaling_type="llama3"``
    instead remaps the frequencies per :func:`llama3_scale_freqs`
    (positions undivided), matching HF Llama-3.1+ checkpoints.
    """
    if scaling_type not in ("linear", "llama3"):
        # fail-loudly posture (same as hf_to_native's rope_scaling check):
        # an unknown type silently falling back to linear would produce
        # wrong frequencies with no diagnostic
        raise ValueError(f"unknown rope scaling_type {scaling_type!r}; "
                         "expected 'linear' or 'llama3'")
    freqs = 1.0 / (theta ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    if scaling_type == "llama3" and scaling_factor != 1.0:
        freqs = llama3_scale_freqs(freqs, scaling_factor,
                                   **(llama3_params or {}))
        t = jnp.arange(max_len, dtype=jnp.float32)
    else:
        t = jnp.arange(max_len, dtype=jnp.float32) / scaling_factor
    angles = jnp.outer(t, freqs)  # [max_len, dim//2]
    return jnp.cos(angles).astype(dtype), jnp.sin(angles).astype(dtype)


def apply_rotary_emb(
    x: jax.Array,
    cos: jax.Array,
    sin: jax.Array,
    position_ids: jax.Array | None = None,
) -> jax.Array:
    """Rotate ``x`` [batch, seq, heads, head_dim] (interleaved-pair convention).

    ``position_ids`` [batch, seq] gathers rows of cos/sin — supports packed
    sequences with restarting positions (positional_embeddings.py:38-47).
    Without it, positions 0..seq-1 are used.
    """
    b, s, h, d = x.shape
    if position_ids is None:
        c = cos[:s][None, :, None, :]  # [1, s, 1, d/2]
        sn = sin[:s][None, :, None, :]
    else:
        c = cos[position_ids][:, :, None, :]  # [b, s, 1, d/2]
        sn = sin[position_ids][:, :, None, :]
    xf = x.astype(jnp.float32).reshape(b, s, h, d // 2, 2)
    x_even, x_odd = xf[..., 0], xf[..., 1]
    out_even = x_even * c - x_odd * sn
    out_odd = x_odd * c + x_even * sn
    out = jnp.stack([out_even, out_odd], axis=-1).reshape(b, s, h, d)
    return out.astype(x.dtype)


def apply_rotary_emb_half(
    x: jax.Array,
    cos: jax.Array,
    sin: jax.Array,
    position_ids: jax.Array | None = None,
) -> jax.Array:
    """HF-convention RoPE (rotate_half: first/second half are the pairs).

    Provided for logit-parity testing against HuggingFace checkpoints without
    re-permuting weights; the two conventions are related by a fixed head-dim
    permutation (reference weights_conversion/utils/permute_qkv.py).
    """
    b, s, h, d = x.shape
    if position_ids is None:
        idx = jnp.arange(s)
        c, sn = cos[idx], sin[idx]
        c = c[None, :, None, :]
        sn = sn[None, :, None, :]
    else:
        c = cos[position_ids][:, :, None, :]
        sn = sin[position_ids][:, :, None, :]
    c = jnp.concatenate([c, c], axis=-1)  # [.., d]
    sn = jnp.concatenate([sn, sn], axis=-1)
    xf = x.astype(jnp.float32)
    x1, x2 = jnp.split(xf, 2, axis=-1)
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    return (xf * c + rotated * sn).astype(x.dtype)
