"""Vocab-parallel cross entropy.

Reference: megatron/core/tensor_parallel/cross_entropy.py:14-175 — computes
softmax-CE over vocab-sharded logits without materializing the full-vocab
softmax on any rank, using three TP all-reduces (max, predicted-logit, sum-exp),
plus optional label smoothing and ``vocab_parallel_max_indices`` for accuracy
metrics.

Two TPU paths:

* :func:`softmax_cross_entropy` — pure jnp, used under ``pjit`` where logits
  carry a vocab-axis sharding; XLA lowers the reductions to the same psum
  pattern automatically. This is the default path.
* :func:`vocab_parallel_cross_entropy` — explicit shard_map formulation over a
  named tp axis, semantics matched line-for-line to the reference for testing
  and for use inside hand-sharded regions.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from megatron_llm_tpu.parallel.compat import axis_index as _axis_index


def softmax_cross_entropy(
    logits: jax.Array,
    labels: jax.Array,
    label_smoothing: float = 0.0,
) -> jax.Array:
    """Per-token CE loss; logits [..., vocab] (possibly vocab-sharded), labels [...].

    fp32 internal math regardless of logits dtype (the reference upcasts via
    ``fp16_lm_cross_entropy=False`` default, gpt_model.py:34-40).
    """
    logits = logits.astype(jnp.float32)
    vocab = logits.shape[-1]
    m = jax.lax.stop_gradient(jnp.max(logits, axis=-1, keepdims=True))
    shifted = logits - m
    sum_exp = jnp.sum(jnp.exp(shifted), axis=-1)
    log_z = jnp.log(sum_exp)
    predicted = jnp.take_along_axis(shifted, labels[..., None], axis=-1)[..., 0]
    loss = log_z - predicted
    if label_smoothing > 0.0:
        # reference cross_entropy.py:95-115: J = (1-eps)ce + eps/K * sum(-logprob)
        smoothing = label_smoothing * vocab / (vocab - 1)
        log_probs = shifted - log_z[..., None]
        mean_log = jnp.mean(log_probs, axis=-1)
        loss = (1.0 - smoothing) * loss - smoothing * mean_log
    return loss


def chunked_softmax_cross_entropy_from_hidden(
    hidden: jax.Array,      # [..., h] final-normed hidden states
    head_kernel: jax.Array,  # [h, v] (tied embedding passed transposed)
    labels: jax.Array,       # [...] int
    num_chunks: int,
    head_bias: jax.Array | None = None,  # [v]
) -> jax.Array:
    """Per-token CE fused with the LM-head matmul, scanned over vocab chunks.

    The default path materializes the full [..., v] fp32 logits before
    :func:`softmax_cross_entropy`; at large vocab x long seq x big
    micro-batch that tensor dominates activation memory (vocab 32k, mbs 16,
    seq 1024 -> 2 GiB fp32). Here a ``lax.scan`` over ``num_chunks`` vocab
    slices keeps only [..., v/num_chunks] logits live at a time, carrying
    the running (max, sum-exp, target-logit) triple — the same three
    quantities the reference's vocab-PARALLEL CE tracks across TP ranks
    (cross_entropy.py:21-60), re-cut along the vocab axis sequentially
    instead of spatially. The chunk body is rematerialized so the backward
    also never holds more than one chunk's logits.

    Gradient-exact (not an approximation): d(loss)/d(logits_c) is recomputed
    per chunk from the carried log-partition.
    """
    v = head_kernel.shape[-1]
    assert num_chunks > 0 and v % num_chunks == 0, (v, num_chunks)
    vc = v // num_chunks
    lead = hidden.shape[:-1]

    @jax.checkpoint  # bwd re-runs the chunk GEMM instead of saving logits
    def chunk(carry, off):
        m, s, tgt = carry
        # slice in place: the kernel keeps its native layout/sharding (a
        # pre-reshaped [nc, h, vc] xs would copy + re-lay-out the whole
        # kernel every loss call and fight the tp vocab sharding)
        wc = jax.lax.dynamic_slice_in_dim(head_kernel, off, vc, axis=1)
        logits_c = (hidden @ wc).astype(jnp.float32)
        if head_bias is not None:
            logits_c = logits_c + jax.lax.dynamic_slice_in_dim(
                head_bias, off, vc, axis=0
            )
        m_c = jax.lax.stop_gradient(jnp.max(logits_c, axis=-1))
        m_new = jnp.maximum(m, m_c)
        scale_old = jnp.exp(m - m_new)
        s = s * scale_old + jnp.sum(
            jnp.exp(logits_c - m_new[..., None]), axis=-1
        )
        local = labels - off
        in_chunk = (local >= 0) & (local < vc)
        safe = jnp.clip(local, 0, vc - 1)
        picked = jnp.take_along_axis(logits_c, safe[..., None], -1)[..., 0]
        tgt = jnp.where(in_chunk, picked, tgt)
        return (m_new, s, tgt), None

    init = (
        jnp.full(lead, -jnp.inf, jnp.float32),
        jnp.zeros(lead, jnp.float32),
        jnp.zeros(lead, jnp.float32),
    )
    (m, s, tgt), _ = jax.lax.scan(chunk, init, jnp.arange(num_chunks) * vc)
    return jnp.log(s) + m - tgt


def vocab_parallel_cross_entropy(
    logits_shard: jax.Array,
    labels: jax.Array,
    axis_name: str = "tp",
    label_smoothing: float = 0.0,
) -> jax.Array:
    """Explicit TP formulation for use inside shard_map over ``axis_name``.

    ``logits_shard`` [..., vocab/t] is this rank's contiguous vocab slice
    (rank r owns [r*vp, (r+1)*vp)); ``labels`` are global vocab ids,
    replicated. Three psums mirror cross_entropy.py:21,52,60.
    """
    logits_shard = logits_shard.astype(jnp.float32)
    vp = logits_shard.shape[-1]
    rank = _axis_index(axis_name)
    vocab_start = rank * vp

    # stop_gradient BEFORE pmax: the max shift is gradient-free anyway and
    # pmax has no differentiation rule (hit by the pp-vocab head's vjp)
    local_max = jax.lax.stop_gradient(jnp.max(logits_shard, axis=-1))
    global_max = jax.lax.pmax(local_max, axis_name)
    shifted = logits_shard - global_max[..., None]

    exp = jnp.exp(shifted)
    sum_exp = jax.lax.psum(jnp.sum(exp, axis=-1), axis_name)
    log_z = jnp.log(sum_exp)

    # predicted logit: mask labels outside this rank's slice, gather, psum.
    local_labels = labels - vocab_start
    in_range = (local_labels >= 0) & (local_labels < vp)
    safe = jnp.clip(local_labels, 0, vp - 1)
    picked = jnp.take_along_axis(shifted, safe[..., None], axis=-1)[..., 0]
    predicted = jax.lax.psum(jnp.where(in_range, picked, 0.0), axis_name)

    loss = log_z - predicted
    if label_smoothing > 0.0:
        vocab = vp * jax.lax.psum(jnp.ones((), jnp.float32), axis_name)
        smoothing = label_smoothing * vocab / (vocab - 1.0)
        log_probs = shifted - log_z[..., None]
        mean_log = jax.lax.psum(jnp.sum(log_probs, axis=-1), axis_name) / vocab
        loss = (1.0 - smoothing) * loss - smoothing * mean_log
    return loss


def vocab_parallel_max_indices(
    logits_shard: jax.Array, axis_name: str = "tp"
) -> jax.Array:
    """Global argmax over vocab-sharded logits (cross_entropy.py:146-175),
    used by the accuracy metric. Returns global vocab ids."""
    vp = logits_shard.shape[-1]
    rank = _axis_index(axis_name)
    local_max = jnp.max(logits_shard, axis=-1)
    local_idx = jnp.argmax(logits_shard, axis=-1) + rank * vp
    # combine (max, idx) across ranks: pick idx of the global max
    all_max = jax.lax.all_gather(local_max, axis_name)  # [t, ...]
    all_idx = jax.lax.all_gather(local_idx, axis_name)
    winner = jnp.argmax(all_max, axis=0)
    return jnp.take_along_axis(all_idx, winner[None], axis=0)[0]
