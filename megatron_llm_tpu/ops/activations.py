"""Activation functions: GLU family + gated variants.

Reference: megatron/model/glu_activations.py:8-48 (LiGLU/GEGLU/ReGLU/SwiGLU as
chunk-2 gating over the doubled fc1 output) and fused_bias_gelu.py (tanh-approx
gelu). XLA fuses these into the surrounding matmuls, so no custom kernels.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp


def gelu_tanh(x: jax.Array) -> jax.Array:
    """Tanh-approximated GeLU (fused_bias_gelu.py:10-17 formula)."""
    return 0.5 * x * (1.0 + jnp.tanh(0.7978845608028654 * x * (1.0 + 0.044715 * x * x)))


def squared_relu(x: jax.Array) -> jax.Array:
    return jnp.square(jax.nn.relu(x))


ACTIVATIONS: Dict[str, Callable] = {
    "gelu": jax.nn.gelu,
    "gelu_tanh": gelu_tanh,
    "relu": jax.nn.relu,
    "squared_relu": squared_relu,
    "silu": jax.nn.silu,
}


def _glu(x: jax.Array, act: Callable) -> jax.Array:
    """Chunk-2 gating on the last dim: x1 * act(x2).

    Convention matches the reference (glu_activations.py:14-16: the activation
    applies to the *second* half of fc1's doubled output) so that fc1 weight
    layouts from converted checkpoints load without reshuffling.
    """
    x1, x2 = jnp.split(x, 2, axis=-1)
    return x1 * act(x2)


def liglu(x):
    return _glu(x, lambda a: a)


def geglu(x):
    return _glu(x, jax.nn.gelu)


def reglu(x):
    return _glu(x, jax.nn.relu)


def swiglu(x):
    return _glu(x, jax.nn.silu)


GLU_ACTIVATIONS: Dict[str, Callable] = {
    "liglu": liglu,
    "geglu": geglu,
    "reglu": reglu,
    "swiglu": swiglu,
}

# Base (non-gated) activation for each GLU variant, for the [h, 2, ffn]
# fc1 layout where the gate applies as x[..., 0, :] * act(x[..., 1, :]).
GLU_BASE_ACTIVATIONS: Dict[str, Callable] = {
    "liglu": lambda a: a,
    "geglu": jax.nn.gelu,
    "reglu": jax.nn.relu,
    "swiglu": jax.nn.silu,
}


def get_mlp_activation(glu_activation: Optional[str], activation: str = "gelu") -> Callable:
    """Resolve the MLP activation; GLU variants expect a doubled fc1 output."""
    if glu_activation is not None:
        return GLU_ACTIVATIONS[glu_activation]
    return ACTIVATIONS[activation]
