"""Attention core ops: XLA reference path + dispatch to the Pallas flash kernel.

Replaces the reference's ``CoreAttention`` (model/transformer.py:144-278 —
baddbmm scores + fused scale-mask-softmax + bmm context) and its
FlashAttention-2 path (transformer.py:518-600, incl. sliding-window kwargs and
GQA). TPU-native differences:

* GQA is computed *without* broadcast-expanding K/V (the reference expands at
  transformer.py:459-466); we reshape Q to [.., kv_heads, group, ..] and let
  the MXU batch over (kv_heads, group).
* masking is built from static causal/sliding-window structure plus an
  optional per-document segment-id tensor (packed sequences), instead of
  materialized 4D byte masks.
* the hot path on TPU is the Pallas flash kernel (ops/pallas/flash_attention);
  this module provides the numerically-identical XLA fallback and the
  dispatcher.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def _flash_available() -> bool:
    try:
        from megatron_llm_tpu.ops.pallas import flash_attention  # noqa: F401

        return True
    except ImportError:
        return False


def _flash_sharded(q, k, v, segment_ids, scale, sliding_window, block_q,
                   block_kv, causal=True):
    """Run the Pallas kernel, wrapped in shard_map when a non-trivial mesh is
    active.

    pallas_call is opaque to the GSPMD partitioner, so under pjit the kernel
    must be mapped explicitly: batch over dp, heads over tp (attention is
    embarrassingly parallel over both — the same decomposition the reference
    gets from per-rank processes). Sequence stays whole here; context
    parallelism (ring attention) shards it separately in parallel/ring.
    """
    from megatron_llm_tpu.core import parallel_state as ps
    from megatron_llm_tpu.ops.pallas.flash_attention import flash_attention

    kwargs = dict(causal=causal, sliding_window=sliding_window, scale=scale,
                  block_q=block_q, block_kv=block_kv)
    if not ps.mesh_is_initialized():
        return flash_attention(q, k, v, segment_ids=segment_ids, **kwargs)
    mesh = ps.get_global_mesh()
    if (mesh.shape.get(ps.DP_AXIS, 1) == 1 and mesh.shape.get(ps.TP_AXIS, 1) == 1
            and mesh.shape.get(ps.EP_AXIS, 1) == 1):
        return flash_attention(q, k, v, segment_ids=segment_ids, **kwargs)

    from jax.sharding import PartitionSpec as P

    from megatron_llm_tpu.parallel import compat
    from megatron_llm_tpu.parallel.compat import shard_map

    # Nested-manual composition: called from inside an enclosing shard_map
    # (the pipeline engine manualizes pp/cp), the inner shard_map must bind
    # the CONTEXT abstract mesh — passing the concrete global mesh raises a
    # mesh-mismatch. The specs below reference only dp/ep/tp, which remain
    # Auto in that context (same pattern as parallel/ring.cp_is_manual).
    # Manualize every axis not already manual in the enclosing context:
    # Mosaic kernels reject being left under ANY auto axis (even size-1),
    # and an enclosing pipeline shard_map has already manualized pp/cp.
    abstract = compat.get_abstract_mesh()
    if abstract is not None and not abstract.empty and abstract.manual_axes:
        mesh = abstract
        names = set(mesh.axis_names) - set(mesh.manual_axes)
    else:
        names = set(mesh.axis_names)

    qs = P(ps.DATA_AXES, None, ps.TP_AXIS, None)
    kvs = P(ps.DATA_AXES, None, ps.TP_AXIS, None)
    segs = P(ps.DATA_AXES, None)
    if segment_ids is None:
        fn = shard_map(
            lambda q_, k_, v_: flash_attention(q_, k_, v_, **kwargs),
            mesh=mesh, in_specs=(qs, kvs, kvs), out_specs=qs,
            axis_names=names, check_vma=False,
        )
        return fn(q, k, v)
    fn = shard_map(
        lambda q_, k_, v_, s_: flash_attention(q_, k_, v_, segment_ids=s_, **kwargs),
        mesh=mesh, in_specs=(qs, kvs, kvs, segs), out_specs=qs,
        axis_names=names, check_vma=False,
    )
    return fn(q, k, v, segment_ids)


def make_attention_bias(
    seq_len: int,
    kv_len: Optional[int] = None,
    causal: bool = True,
    sliding_window: Optional[int] = None,
    segment_ids_q: Optional[jax.Array] = None,
    segment_ids_kv: Optional[jax.Array] = None,
    token_idx: Optional[jax.Array] = None,
    dtype=jnp.float32,
) -> jax.Array:
    """Build an additive attention bias [*, 1, q_len, kv_len].

    ``segment_ids`` [batch, seq] gate cross-document attention for packed
    sequences (reference --reset_attention_mask / attention_mask_in_length
    varlen path, instruction_dataset.py + transformer.py:540-582).
    """
    kv_len = kv_len if kv_len is not None else seq_len
    if token_idx is not None:
        # zigzag/permuted layouts: causal structure follows the original
        # token order, not the storage order (parallel/ring.py)
        q_pos = token_idx[:, None]
        kv_pos = token_idx[None, :]
    else:
        q_pos = jnp.arange(seq_len)[:, None]
        kv_pos = jnp.arange(kv_len)[None, :]
    allowed = jnp.ones((seq_len, kv_len), dtype=bool)
    if causal:
        allowed &= q_pos >= kv_pos
    if sliding_window is not None:
        # Mistral sliding window: attend to at most the last W positions
        # (transformer.py:529-537).
        allowed &= q_pos - kv_pos < sliding_window
    bias = jnp.where(allowed, 0.0, NEG_INF).astype(dtype)[None, None]
    if segment_ids_q is not None:
        same = segment_ids_q[:, :, None] == segment_ids_kv[:, None, :]
        bias = bias + jnp.where(same, 0.0, NEG_INF).astype(dtype)[:, None]
    return bias


def xla_attention(
    q: jax.Array,  # [b, sq, n_heads, d]
    k: jax.Array,  # [b, skv, n_kv_heads, d]
    v: jax.Array,  # [b, skv, n_kv_heads, d]
    bias: Optional[jax.Array] = None,  # [b or 1, 1, sq, skv]
    scale: Optional[float] = None,
    softmax_fp32: bool = True,
    dropout_rate: float = 0.0,
    dropout_key: Optional[jax.Array] = None,
) -> jax.Array:
    """Grouped-query attention via einsum; exact softmax. Returns [b, sq, n, d]."""
    b, sq, n, d = q.shape
    _, skv, nkv, _ = k.shape
    assert n % nkv == 0
    g = n // nkv
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    qg = q.reshape(b, sq, nkv, g, d)
    # scores [b, nkv, g, sq, skv]
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg * scale, k)
    if softmax_fp32:
        scores = scores.astype(jnp.float32)
    if bias is not None:
        scores = scores + bias[:, :, None]  # broadcast over group dim
    probs = jax.nn.softmax(scores, axis=-1)
    if dropout_rate > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_rate, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
    probs = probs.astype(v.dtype)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs, v)
    return out.reshape(b, sq, n, d)


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    sliding_window: Optional[int] = None,
    segment_ids: Optional[jax.Array] = None,
    token_idx: Optional[jax.Array] = None,
    bias: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    use_flash: bool = True,
    dropout_rate: float = 0.0,
    dropout_key: Optional[jax.Array] = None,
    block_q: Optional[int] = None,
    block_kv: Optional[int] = None,
    zigzag: bool = False,
) -> jax.Array:
    """Dispatch between ring attention (cp > 1), the Pallas flash kernel,
    and the XLA fallback. ``zigzag`` declares the standard apply_zigzag
    token layout (cfg --cp_zigzag), which lets the ring path use the
    striped flash kernels instead of the jnp fallback."""
    sq = q.shape[1]

    from megatron_llm_tpu.core import parallel_state as ps

    # compile-TARGET platform, not the host backend: AOT lowering for a TPU
    # topology on a CPU host must still pick the flash kernel
    on_tpu = ps.target_platform() == "tpu"

    cp = (
        ps.get_context_parallel_world_size()
        if ps.mesh_is_initialized()
        else 1
    )
    if cp > 1:
        assert bias is None and dropout_rate == 0.0, (
            "context parallelism supports structural masking only "
            "(causal/sliding-window/segment), no bias or attention dropout"
        )
        from megatron_llm_tpu.parallel.ring import ring_attention

        return ring_attention(
            q, k, v, segment_ids=segment_ids, token_idx=token_idx,
            causal=causal, sliding_window=sliding_window, scale=scale,
            zigzag=zigzag,
        )
    flash_ok = (
        use_flash
        and bias is None
        and dropout_rate == 0.0
        # bidirectional (BERT / T5 encoder) runs the kernel with causal
        # masking off — full or segment-gated attention
        and token_idx is None  # kernel masks by storage order only
        and on_tpu
        and sq >= 128
        and q.shape[-1] in (64, 128, 256)
        and _flash_available()
        # Round-4 note: pp x dp>1 x tp>1 used to fall back to xla_attention
        # here — an XLA scatter-partitioner CHECK crash that turned out to
        # be the EMBEDDING-grad scatter-add inside the pipeline tick loop,
        # not the nested flash shard_map itself. Fixed at the root by the
        # matmul-backward embedding under pp
        # (models/language_model.py:_take_rows_matmul_bwd,
        # tools/flash_nested_repro.py) — flash now dispatches at every
        # sharding incl. the tp8 x pp8 x dp4 north star.
    )
    if flash_ok:
        return _flash_sharded(
            q, k, v, segment_ids, scale, sliding_window, block_q, block_kv,
            causal=causal,
        )
    if bias is None:
        seg_q = seg_kv = segment_ids
        bias = make_attention_bias(
            sq, k.shape[1], causal=causal, sliding_window=sliding_window,
            segment_ids_q=seg_q, segment_ids_kv=seg_kv, token_idx=token_idx,
        )
    return xla_attention(
        q, k, v, bias=bias, scale=scale,
        dropout_rate=dropout_rate, dropout_key=dropout_key,
    )
