"""Tokenizers — megatron/tokenizer analog."""

from megatron_llm_tpu.tokenizer.tokenizer import (
    AbstractTokenizer,
    build_tokenizer,
)

__all__ = ["AbstractTokenizer", "build_tokenizer"]
