"""Tokenizers — megatron/tokenizer analog."""

from types import SimpleNamespace

from megatron_llm_tpu.tokenizer.tokenizer import (
    AbstractTokenizer,
    build_tokenizer,
)


def build_tokenizer_flat(args) -> AbstractTokenizer:
    """Adapter for flat argparse namespaces (the ``tools/preprocess_*`` CLIs),
    which carry tokenizer flags at top level rather than under ``cfg.data``."""
    cfg = SimpleNamespace(data=args, model=SimpleNamespace(vocab_size=None))
    return build_tokenizer(cfg)


__all__ = ["AbstractTokenizer", "build_tokenizer", "build_tokenizer_flat"]
