"""Tokenizers — megatron/tokenizer analog."""

from types import SimpleNamespace

from megatron_llm_tpu.tokenizer.tokenizer import (
    AbstractTokenizer,
    build_tokenizer,
)


def build_tokenizer_flat(args) -> AbstractTokenizer:
    """Adapter for flat argparse namespaces (the ``tools/preprocess_*`` CLIs),
    which carry tokenizer flags at top level rather than under ``cfg.data``."""
    cfg = SimpleNamespace(data=args, model=SimpleNamespace(vocab_size=None))
    return build_tokenizer(cfg)


def add_tokenizer_args(parser):
    """Shared tokenizer flag group for the preprocessing CLIs."""
    g = parser.add_argument_group("tokenizer")
    g.add_argument("--tokenizer_type", type=str, required=True)
    g.add_argument("--vocab_file", type=str, default=None)
    g.add_argument("--merge_file", type=str, default=None)
    g.add_argument("--tokenizer_model", type=str, default=None)
    g.add_argument("--vocab_extra_ids", type=int, default=0)
    g.add_argument("--vocab_extra_ids_list", type=str, default=None)
    g.add_argument("--no_new_tokens", action="store_true")
    return g


def finalize_tokenizer_args(args):
    """Post-parse fixups shared by the preprocessing CLIs: the reference's
    ``--vocab_file`` spelling aliases the sentencepiece model path, and
    ``build_tokenizer`` expects a rank/TP context."""
    if args.tokenizer_model is None and args.vocab_file is not None:
        args.tokenizer_model = args.vocab_file
    args.rank = 0
    args.make_vocab_size_divisible_by = 128
    args.tensor_model_parallel_size = 1
    return args


__all__ = [
    "AbstractTokenizer",
    "add_tokenizer_args",
    "build_tokenizer",
    "build_tokenizer_flat",
    "finalize_tokenizer_args",
]
