"""Self-contained GPT-2 byte-level BPE and BERT WordPiece tokenizers.

The reference vendors its own implementations of both standard algorithms
(megatron/tokenizer/gpt2_tokenization.py, bert_tokenization.py, ~752 LoC)
so an air-gapped cluster can tokenize from local vocab files alone. This
module provides the same capability: no `transformers`/`tokenizers`
packages at runtime — only the published file formats (GPT-2
vocab.json + merges.txt; BERT vocab.txt) and the standard algorithms,
re-implemented from their specs:

* GPT-2 byte-level BPE (Radford et al. 2019; the byte<->unicode table and
  greedy lowest-rank pair merging are fixed by the released files),
* BERT BasicTokenizer + greedy longest-match-first WordPiece
  (Devlin et al. 2018).

`tests/test_vendored_tokenizers.py` checks both against tiny hand-built
vocabularies and (when HF is importable) against the HF implementations.
"""

from __future__ import annotations

import json
import unicodedata
from functools import lru_cache
from typing import Dict, List, Tuple

from megatron_llm_tpu.tokenizer.tokenizer import AbstractTokenizer

# ---------------------------------------------------------------------------
# GPT-2 byte-level BPE
# ---------------------------------------------------------------------------


@lru_cache()
def bytes_to_unicode() -> Dict[int, str]:
    """The fixed GPT-2 byte -> printable-unicode table.

    Printable ASCII/latin bytes map to themselves; the rest are assigned
    code points 256+ in order — a reversible encoding that makes every
    byte sequence a string the BPE merges can operate on."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(ord("\xa1"), ord("\xac") + 1))
          + list(range(ord("\xae"), ord("\xff") + 1)))
    cs = bs[:]
    n = 0
    for b in range(2 ** 8):
        if b not in bs:
            bs.append(b)
            cs.append(2 ** 8 + n)
            n += 1
    return dict(zip(bs, [chr(c) for c in cs]))


def get_pairs(word: Tuple[str, ...]):
    pairs = set()
    prev = word[0]
    for ch in word[1:]:
        pairs.add((prev, ch))
        prev = ch
    return pairs


# the GPT-2 pretokenizer split pattern (needs the `regex` module for \p{L})
_GPT2_SPLIT = (r"'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+|"
               r" ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+")


class GPT2BPETokenizer(AbstractTokenizer):
    """Byte-level BPE from local vocab.json + merges.txt — no HF runtime."""

    def __init__(self, vocab_file: str, merges_file: str):
        super().__init__("GPT2 BPE (vendored)")
        import regex  # baked in; unicode-category classes for the split

        with open(vocab_file, encoding="utf-8") as f:
            self.encoder: Dict[str, int] = json.load(f)
        self.decoder = {v: k for k, v in self.encoder.items()}
        with open(merges_file, encoding="utf-8") as f:
            lines = f.read().split("\n")
        # merges.txt: optional "#version" header, one "a b" pair per line
        merges = [tuple(line.split()) for line in lines
                  if line and not line.startswith("#version") and len(
                      line.split()) == 2]
        self.bpe_ranks = dict(zip(merges, range(len(merges))))
        self.byte_encoder = bytes_to_unicode()
        self.byte_decoder = {v: k for k, v in self.byte_encoder.items()}
        self.pat = regex.compile(_GPT2_SPLIT)
        self.cache: Dict[str, str] = {}

    def bpe(self, token: str) -> str:
        if token in self.cache:
            return self.cache[token]
        word = tuple(token)
        pairs = get_pairs(word) if len(word) > 1 else set()
        while pairs:
            # merge the lowest-rank pair present, repeat until none apply
            bigram = min(
                pairs, key=lambda p: self.bpe_ranks.get(p, float("inf")))
            if bigram not in self.bpe_ranks:
                break
            first, second = bigram
            new_word: List[str] = []
            i = 0
            while i < len(word):
                try:
                    j = word.index(first, i)
                except ValueError:
                    new_word.extend(word[i:])
                    break
                new_word.extend(word[i:j])
                i = j
                if (i < len(word) - 1 and word[i] == first
                        and word[i + 1] == second):
                    new_word.append(first + second)
                    i += 2
                else:
                    new_word.append(word[i])
                    i += 1
            word = tuple(new_word)
            if len(word) == 1:
                break
            pairs = get_pairs(word)
        out = " ".join(word)
        self.cache[token] = out
        return out

    @property
    def vocab_size(self) -> int:
        return len(self.encoder)

    def tokenize(self, text: str) -> List[int]:
        # unknown pieces (possible with trimmed/custom vocab.json files)
        # map to a dedicated unk id — NEVER eod: OOV text masquerading as
        # document separators would silently corrupt corpus boundaries
        # (the reference gpt2_tokenization likewise falls back to a
        # distinct unk id via .get)
        unk = self.unk
        ids: List[int] = []
        for token in self.pat.findall(text):
            mapped = "".join(self.byte_encoder[b]
                             for b in token.encode("utf-8"))
            ids.extend(self.encoder.get(t, unk)
                       for t in self.bpe(mapped).split(" "))
        return ids

    def detokenize(self, token_ids: List[int]) -> str:
        text = "".join(self.decoder[int(t)] for t in token_ids)
        return bytearray(
            self.byte_decoder[c] for c in text).decode("utf-8",
                                                       errors="replace")

    @property
    def eod(self) -> int:
        try:
            return self.encoder["<|endoftext|>"]
        except KeyError:
            raise ValueError(
                "vocab.json has no '<|endoftext|>' entry; a GPT-2 BPE "
                "vocab without an end-of-document token cannot delimit "
                "documents — add the token or use a different tokenizer"
            ) from None

    @property
    def unk(self) -> int:
        # explicit unk entries first (trimmed/custom vocabs often carry
        # one); the full released GPT-2 vocab covers all 256 bytes so BPE
        # pieces are never OOV there and this id is never emitted for it.
        for tok in ("<unk>", "<|unk|>", "[UNK]"):
            if tok in self.encoder:
                return self.encoder[tok]
        # no explicit unk entry: fall back to the lowest id that is not
        # eod — aliasing some real token is the honest cost of a trimmed
        # vocab, but aliasing the DOCUMENT BOUNDARY is never acceptable
        fallback = 0
        if self.encoder.get("<|endoftext|>") == fallback:
            fallback = 1
        return fallback


# ---------------------------------------------------------------------------
# BERT WordPiece
# ---------------------------------------------------------------------------


def _is_whitespace(ch: str) -> bool:
    return ch in " \t\n\r" or unicodedata.category(ch) == "Zs"


def _is_control(ch: str) -> bool:
    if ch in ("\t", "\n", "\r"):
        return False
    return unicodedata.category(ch).startswith("C")


def _is_punctuation(ch: str) -> bool:
    cp = ord(ch)
    # ASCII non-alphanumeric printable ranges count as punctuation (the
    # BERT convention — includes chars like $ and ^ outside unicode P*)
    if (33 <= cp <= 47 or 58 <= cp <= 64 or 91 <= cp <= 96
            or 123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_cjk(cp: int) -> bool:
    return (0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF
            or 0x20000 <= cp <= 0x2A6DF or 0x2A700 <= cp <= 0x2B73F
            or 0x2B740 <= cp <= 0x2B81F or 0x2B820 <= cp <= 0x2CEAF
            or 0xF900 <= cp <= 0xFAFF or 0x2F800 <= cp <= 0x2FA1F)


class WordPieceTokenizer(AbstractTokenizer):
    """BERT BasicTokenizer + WordPiece from a local vocab.txt.

    Greedy longest-match-first subword split with the ## continuation
    prefix; basic cleanup, optional lower-casing + accent stripping, CJK
    chars tokenized individually."""

    def __init__(self, vocab_file: str, lower_case: bool = True,
                 max_chars_per_word: int = 200):
        super().__init__("BERT WordPiece (vendored)")
        # dense sequential ids over non-blank lines (the reference
        # bert_tokenization loader's behavior): a stray blank line must
        # not leave an id gap that indexes past the embedding table
        self.vocab: Dict[str, int] = {}
        with open(vocab_file, encoding="utf-8") as f:
            for line in f:
                tok = line.rstrip("\n")
                if tok:
                    self.vocab[tok] = len(self.vocab)
        self.inv_vocab = {v: k for k, v in self.vocab.items()}
        self.lower_case = lower_case
        self.max_chars = max_chars_per_word

    # -- basic tokenization --------------------------------------------
    def _clean(self, text: str) -> str:
        out = []
        for ch in text:
            cp = ord(ch)
            if cp == 0 or cp == 0xFFFD or _is_control(ch):
                continue
            out.append(" " if _is_whitespace(ch) else ch)
        return "".join(out)

    def _basic_split(self, text: str) -> List[str]:
        text = self._clean(text)
        # CJK chars become standalone tokens
        spaced = []
        for ch in text:
            if _is_cjk(ord(ch)):
                spaced.append(f" {ch} ")
            else:
                spaced.append(ch)
        words = "".join(spaced).split()
        out: List[str] = []
        for w in words:
            if self.lower_case:
                w = w.lower()
                w = "".join(c for c in unicodedata.normalize("NFD", w)
                            if unicodedata.category(c) != "Mn")
            # split punctuation into standalone tokens
            cur: List[str] = []
            for ch in w:
                if _is_punctuation(ch):
                    if cur:
                        out.append("".join(cur))
                        cur = []
                    out.append(ch)
                else:
                    cur.append(ch)
            if cur:
                out.append("".join(cur))
        return out

    # -- wordpiece ------------------------------------------------------
    def _wordpiece(self, word: str) -> List[str]:
        if len(word) > self.max_chars:
            return ["[UNK]"]
        out: List[str] = []
        start = 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:  # longest-match-first
                piece = word[start:end]
                if start > 0:
                    piece = "##" + piece
                if piece in self.vocab:
                    cur = piece
                    break
                end -= 1
            if cur is None:
                return ["[UNK]"]
            out.append(cur)
            start = end
        return out

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    def tokenize(self, text: str) -> List[int]:
        pieces: List[str] = []
        for word in self._basic_split(text):
            pieces.extend(self._wordpiece(word))
        unk = self.vocab.get("[UNK]", 0)
        return [self.vocab.get(p, unk) for p in pieces]

    def detokenize(self, token_ids: List[int]) -> str:
        pieces = [self.inv_vocab[int(t)] for t in token_ids]
        text = " ".join(pieces).replace(" ##", "")
        return text

    @property
    def cls(self) -> int:
        return self.vocab["[CLS]"]

    @property
    def sep(self) -> int:
        return self.vocab["[SEP]"]

    @property
    def pad(self) -> int:
        return self.vocab["[PAD]"]

    @property
    def mask(self) -> int:
        return self.vocab["[MASK]"]

    @property
    def eod(self) -> int:
        return self.sep
