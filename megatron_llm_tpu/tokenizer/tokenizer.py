"""Tokenizer factory.

Reference: megatron/tokenizer/tokenizer.py — ``build_tokenizer``:12 dispatching
on ``--tokenizer_type`` (BertWordPiece, GPT2BPE, SentencePieceTokenizer for
Llama, FalconTokenizer via HF AutoTokenizer), plus vocab padding to
``make_vocab_size_divisible_by * tp`` (:49-62).

TPU-native notes: nothing here touches devices — but unlike the reference we
don't vendor BPE/WordPiece implementations; HuggingFace ``transformers``
(always available in the image) provides all of them. The raw
``sentencepiece`` path is kept behind an import gate for environments that
have it.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import List, Optional


class AbstractTokenizer(ABC):
    """Interface matching the reference's AbstractTokenizer (tokenizer.py:65)."""

    def __init__(self, name: str):
        self.name = name

    @property
    @abstractmethod
    def vocab_size(self) -> int: ...

    @abstractmethod
    def tokenize(self, text: str) -> List[int]: ...

    def detokenize(self, token_ids: List[int]) -> str:
        raise NotImplementedError(f"detokenize not provided for {self.name}")

    @property
    def cls(self):
        raise NotImplementedError

    @property
    def sep(self):
        raise NotImplementedError

    @property
    def pad(self):
        raise NotImplementedError

    @property
    def eod(self):
        raise NotImplementedError

    @property
    def mask(self):
        raise NotImplementedError


class HFTokenizer(AbstractTokenizer):
    """Any HuggingFace tokenizer (FalconTokenizer analog, tokenizer.py:428-470;
    also serves Llama/Mistral/CodeLlama via their HF tokenizers)."""

    def __init__(self, model_name_or_path: str, vocab_extra_ids_list=None):
        super().__init__(f"HF({model_name_or_path})")
        from transformers import AutoTokenizer

        self._t = AutoTokenizer.from_pretrained(model_name_or_path)
        if vocab_extra_ids_list:
            self._t.add_tokens(vocab_extra_ids_list.split(","))
        self._eod = self._t.eos_token_id
        if self._eod is None:
            self._eod = self._t.pad_token_id

    @property
    def vocab_size(self) -> int:
        return len(self._t)

    @property
    def vocab(self):
        return self._t.get_vocab()

    @property
    def inv_vocab(self):
        return {v: k for k, v in self._t.get_vocab().items()}

    def tokenize(self, text: str) -> List[int]:
        return self._t.encode(text)

    def detokenize(self, token_ids) -> str:
        return self._t.decode(token_ids)

    @property
    def eod(self):
        return self._eod

    @property
    def eos_token_id(self):
        return self._t.eos_token_id

    @property
    def bos_token_id(self):
        return self._t.bos_token_id


class SentencePieceTokenizer(AbstractTokenizer):
    """Llama-style sentencepiece model (tokenizer.py:305-426): BOS/EOS ids,
    optional new special tokens unless ``no_new_tokens``."""

    def __init__(self, model_file: str, vocab_extra_ids_list=None,
                 new_tokens: bool = True):
        super().__init__("SentencePieceTokenizer")
        try:
            import sentencepiece as spm

            self._sp = spm.SentencePieceProcessor(model_file=model_file)
            self._backend = "spm"
        except ImportError:
            # transformers' (rust) tokenizer can load sentencepiece models
            from transformers import LlamaTokenizerFast

            self._sp = LlamaTokenizerFast(vocab_file=model_file)
            self._backend = "hf"
        self._extra = {}
        if new_tokens and vocab_extra_ids_list:
            base = self.base_vocab_size
            for i, tok in enumerate(vocab_extra_ids_list.split(",")):
                self._extra[tok] = base + i

    @property
    def base_vocab_size(self) -> int:
        return (self._sp.get_piece_size() if self._backend == "spm"
                else len(self._sp))

    @property
    def vocab_size(self) -> int:
        return self.base_vocab_size + len(self._extra)

    def _encode_plain(self, text: str) -> List[int]:
        if self._backend == "spm":
            return self._sp.encode_as_ids(text)
        return self._sp.encode(text, add_special_tokens=False)

    def tokenize(self, text: str) -> List[int]:
        bos = [self.bos_token_id] if self.bos_token_id is not None else []
        if not self._extra:
            return bos + self._encode_plain(text)
        # split on registered special tokens so they map to their own ids
        # (reference SentencePieceTokenizer special-token scan, tokenizer.py:360-392)
        ids: List[int] = []
        rest = text
        while rest:
            positions = [
                (rest.find(tok), tok) for tok in self._extra if rest.find(tok) != -1
            ]
            if not positions:
                ids.extend(self._encode_plain(rest))
                break
            pos, tok = min(positions)
            if pos > 0:
                ids.extend(self._encode_plain(rest[:pos]))
            ids.append(self._extra[tok])
            rest = rest[pos + len(tok):]
        return bos + ids

    def detokenize(self, token_ids) -> str:
        inv_extra = {v: k for k, v in self._extra.items()}
        pieces: List[str] = []
        chunk: List[int] = []

        def flush():
            if chunk:
                pieces.append(
                    self._sp.decode_ids(chunk) if self._backend == "spm"
                    else self._sp.decode(chunk)
                )
                chunk.clear()

        for t in token_ids:
            t = int(t)
            if t in inv_extra:
                flush()
                pieces.append(inv_extra[t])
            elif t < self.base_vocab_size:
                chunk.append(t)
        flush()
        return "".join(pieces)

    @property
    def eod(self):
        return self._sp.eos_id() if self._backend == "spm" else self._sp.eos_token_id

    @property
    def bos_token_id(self):
        return self._sp.bos_id() if self._backend == "spm" else self._sp.bos_token_id

    @property
    def eos_token_id(self):
        return self.eod


class _NullTokenizer(AbstractTokenizer):
    """Fixed-size integer tokenizer for tests/benchmarks (no files needed)."""

    def __init__(self, vocab_size: int = 32000):
        super().__init__("NullTokenizer")
        self._n = vocab_size

    @property
    def vocab_size(self):
        return self._n

    def tokenize(self, text: str):
        return [int(t) % self._n for t in text.split()]

    def detokenize(self, token_ids):
        return " ".join(str(int(t)) for t in token_ids)

    @property
    def eod(self):
        return 0


def build_tokenizer(cfg) -> AbstractTokenizer:
    """Reference build_tokenizer (tokenizer.py:12-46) analog."""
    d = cfg.data
    t = d.tokenizer_type
    if t == "SentencePieceTokenizer":
        assert d.tokenizer_model is not None, "--tokenizer_model required"
        tok = SentencePieceTokenizer(
            d.tokenizer_model, d.vocab_extra_ids_list, new_tokens=not d.no_new_tokens
        )
    elif t in ("FalconTokenizer", "HFTokenizer"):
        name = d.tokenizer_model or ("tiiuae/falcon-40b" if t == "FalconTokenizer"
                                     else None)
        assert name, "--tokenizer_model (HF name or path) required"
        tok = HFTokenizer(name, d.vocab_extra_ids_list)
    elif t == "GPT2BPETokenizer":
        if d.vocab_file and d.merge_file:
            # air-gapped path: vendored byte-level BPE from local files
            # (reference gpt2_tokenization.py capability — no HF runtime)
            from megatron_llm_tpu.tokenizer.vendored import GPT2BPETokenizer

            tok = GPT2BPETokenizer(d.vocab_file, d.merge_file)
        else:
            tok = HFTokenizer(d.tokenizer_model or "gpt2")
    elif t in ("BertWordPieceLowerCase", "BertWordPieceCase"):
        # vendored WordPiece (reference bert_tokenization.py capability)
        assert d.vocab_file, "--vocab_file required for BertWordPiece*"
        from megatron_llm_tpu.tokenizer.vendored import WordPieceTokenizer

        tok = WordPieceTokenizer(
            d.vocab_file, lower_case=(t == "BertWordPieceLowerCase"))
    elif t == "NullTokenizer":
        tok = _NullTokenizer(cfg.model.vocab_size or 32000)
    else:
        raise NotImplementedError(f"tokenizer type {t} not implemented")
    # set padded vocab on the model config (reference stores padded_vocab_size)
    if cfg.model.vocab_size is None:
        cfg.model.vocab_size = tok.vocab_size
    return tok
