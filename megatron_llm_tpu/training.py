"""Training driver: the ``pretrain`` orchestration loop.

Reference: megatron/training.py — ``pretrain``:55, ``train_step``:393 (ours is
jitted whole in training_step.py), ``_train`` loop:654 (eval :713,
signal-exit :731, save :739, time/iter exits :746-767), ``evaluate``:773,
``training_log``:462 with tokens/sec (:591-609).

Single-controller redesign: no rank gymnastics (is-last-rank printing, TP-rank
data broadcast, all-reduced exit flags) — one process drives the mesh; exit
decisions are plain Python.

Async loop (ISSUE 2): the hot loop rides JAX's async dispatch so the host
never sits between device steps — metrics stay on device in a bounded
in-flight deque (--async_dispatch_depth) and are fetched in ONE batched
``jax.device_get`` at log_interval boundaries; batches are collated and
placed ahead of time on a background thread (data/prefetch.py,
--prefetch_depth); checkpoint writes are deferred to a writer thread behind
a host snapshot (--async_save, checkpointing.AsyncCheckpointSaver).  The
numerical trajectory is bitwise-identical to the synchronous loop
(tests/test_async_loop.py) — only WHEN the host observes results changes,
never what the device computes.
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Any, Callable, Dict, Iterator, Optional

import jax
import numpy as np

from megatron_llm_tpu.checkpointing import (
    AsyncCheckpointSaver,
    load_checkpoint,
    save_checkpoint,
)
from megatron_llm_tpu.core.parallel_state import build_mesh_from_config, global_mesh
from megatron_llm_tpu.core import rng as rng_mod
from megatron_llm_tpu.data.batch_utils import get_ltor_batch
from megatron_llm_tpu.models import init_model_params
from megatron_llm_tpu.models.language_model import loss_from_batch, make_rope_cache
from megatron_llm_tpu.optimizer.optimizer import opt_state_shardings
from megatron_llm_tpu.parallel.tp import make_sp_constraint, param_shardings
from megatron_llm_tpu.observability import flight as flight_mod
from megatron_llm_tpu.observability import flops as flops_mod
from megatron_llm_tpu.observability import registry as registry_mod
from megatron_llm_tpu.observability import trace as trace_mod
from megatron_llm_tpu.tokenizer.tokenizer import build_tokenizer
from megatron_llm_tpu.training_step import (
    make_jitted_train_step,
    measure_span_breakdown,
)
from megatron_llm_tpu.utils.logging_utils import (
    SignalHandler,
    build_writer,
    print0,
    set_global,
)
from megatron_llm_tpu.utils.timers import Timers


# window of fetched (iteration, lm loss) pairs the loop keeps for the
# result dict — bounded, like every other per-step record in the driver
_LOSS_SERIES_MAXLEN = 512


def model_flops_per_token(cfg) -> float:
    """Matmul FLOPs/token for fwd+bwd — now delegated to the shared
    accounting in observability/flops.py (kept here for the tools that
    import it from the driver)."""
    return flops_mod.flops_per_token(cfg)


def _device_kind() -> str:
    try:
        return getattr(jax.devices()[0], "device_kind", "cpu")
    except Exception:
        return "cpu"


def _train_valid_test_num_samples(cfg):
    """Sample counts for the three splits (training.py:877-961 math)."""
    t = cfg.training
    gbs = t.global_batch_size
    train_samples = (t.train_samples or (t.train_iters or 0) * gbs)
    eval_samples = t.eval_iters * gbs * (
        1 + (t.train_iters or 0) // max(t.eval_interval, 1)
    )
    return train_samples, eval_samples, t.eval_iters * gbs


def _loader_granularity(cfg) -> int:
    """Batches the loader yields: the full global batch normally, or one
    micro_batch*dp chunk under batch-size ramp-up (the loop then pulls
    gbs_t/chunk chunks per iteration as the ramp grows, microbatches.py)."""
    if cfg.training.rampup_batch_size is not None:
        return cfg.training.micro_batch_size * (
            cfg.parallel.data_parallel_size or 1
        )
    return cfg.training.global_batch_size


def _make_loader_factory(cfg, collate):
    from megatron_llm_tpu.data.samplers import build_pretraining_data_loader

    def loader(ds, consumed, batch_size=None):
        return build_pretraining_data_loader(
            ds, consumed, batch_size or _loader_granularity(cfg),
            cfg.data.dataloader_type, cfg.training.seed, collate_fn=collate,
            process_sliced=True,
        )

    return loader


def build_gpt_data_iterators(cfg, tokenizer):
    """Default dataset provider: GPT pretraining over --data_path."""
    from megatron_llm_tpu.data.gpt_dataset import build_train_valid_test_datasets

    if not cfg.data.data_path:
        raise ValueError(
            "--data_type gpt requires --data_path (per-split "
            "--train_data_path is only supported with --data_type instruction)"
        )
    train_ds, valid_ds, test_ds = build_train_valid_test_datasets(
        cfg.data.data_path,
        cfg.data.split,
        _train_valid_test_num_samples(cfg),
        cfg.data.seq_length,
        cfg.training.seed,
        data_impl=cfg.data.data_impl,
    )

    eod = getattr(tokenizer, "eod", None) if tokenizer else None

    def collate(samples):
        text = np.stack([s["text"] for s in samples])
        return get_ltor_batch(
            text,
            eod_token=eod,
            reset_position_ids=cfg.data.reset_position_ids,
            reset_attention_mask=cfg.data.reset_attention_mask,
            eod_mask_loss=cfg.data.eod_mask_loss,
        )

    return _make_loader_factory(cfg, collate), (train_ds, valid_ds, test_ds)


def build_instruction_data_iterators(cfg, tokenizer):
    """Instruction-tuning dataset provider (--data_type instruction)."""
    from megatron_llm_tpu.data.instruction_dataset import (
        build_train_valid_test_datasets as build_instruct,
        instruction_collator,
    )

    train_ds, valid_ds, test_ds = build_instruct(
        cfg.data.data_path,
        cfg.data.split,
        _train_valid_test_num_samples(cfg),
        cfg.data.seq_length,
        cfg.training.seed,
        train_data_prefix=cfg.data.train_data_path,
        valid_data_prefix=cfg.data.valid_data_path,
        test_data_prefix=cfg.data.test_data_path,
    )

    try:
        pad = tokenizer.pad
    except (NotImplementedError, AttributeError):
        pad = getattr(tokenizer, "eod", 0)

    def collate(samples):
        return instruction_collator(
            samples,
            seq_length=cfg.data.seq_length,
            pad_id=pad,
            loss_role=cfg.data.loss_role,
            scalar_loss_mask=cfg.data.scalar_loss_mask,
            variable_seq_lengths=cfg.data.variable_seq_lengths,
        )

    return _make_loader_factory(cfg, collate), (train_ds, valid_ds, test_ds)


def build_data_iterators(cfg, tokenizer):
    """Dispatch on --data_type (gpt | instruction)."""
    if cfg.data.data_type == "instruction":
        return build_instruction_data_iterators(cfg, tokenizer)
    return build_gpt_data_iterators(cfg, tokenizer)


def make_eval_step(cfg, loss_fn=None):
    sp_c = make_sp_constraint(cfg)
    names = list(cfg.logging.metrics or [])
    if loss_fn is None:
        loss_fn = loss_from_batch

    if names and loss_fn is not loss_from_batch:
        raise ValueError(
            "--metrics currently supports the GPT-family LM loss path only "
            f"(requested {names} with a custom loss_fn)"
        )

    def eval_step(params, batch):
        if not names:
            loss, metrics = loss_fn(
                cfg, params, batch, deterministic=True, sp_constraint=sp_c
            )
            return metrics
        # --metrics path (reference metrics registry computed in loss_func
        # during validation, finetune.py:183-187): keep the logits around
        # for argmax metrics.
        from megatron_llm_tpu.metrics import (
            MetricInput,
            compute_metrics,
            needs_logits,
        )
        from megatron_llm_tpu.models.language_model import model_forward
        from megatron_llm_tpu.ops.cross_entropy import softmax_cross_entropy

        import jax.numpy as jnp

        logits, _ = model_forward(
            cfg, params, batch["tokens"],
            position_ids=batch.get("position_ids"),
            segment_ids=batch.get("segment_ids"),
            token_idx=batch.get("token_idx"),
            deterministic=True, sp_constraint=sp_c,
        )
        per_token = softmax_cross_entropy(logits, batch["labels"])
        mask = batch["loss_mask"].astype(jnp.float32)
        loss = (per_token * mask).sum() / jnp.maximum(mask.sum(), 1.0)
        inp = MetricInput(
            batch=batch, per_token_loss=per_token,
            logits=logits if needs_logits(names) else None,
        )
        metrics = {"lm loss": loss}
        metrics.update(compute_metrics(names, inp))
        return metrics

    return jax.jit(eval_step)


# eval steps dispatch back-to-back and their metric dicts drain in one
# batched device_get per this many iterations (bounds device memory for
# pending eval programs) — instead of a blocking float(v) per metric per
# iteration, which serialized host and device every eval step
_EVAL_DRAIN_EVERY = 32


def evaluate(cfg, params, eval_step, data_iterator,
             max_iters: Optional[int] = None, place_batch=None):
    """evaluate analog (training.py:773-860): mean loss over eval_iters.

    ``place_batch`` (the training step's placer) must be passed in
    multi-host runs: eval loaders are process-sliced like training loaders,
    so the local rows need the same global-array assembly."""
    totals: Dict[str, float] = {}
    n = 0
    pending: list = []
    max_iters = max_iters or cfg.training.eval_iters

    def drain():
        for host in jax.device_get(pending):
            for k, v in host.items():
                totals[k] = totals.get(k, 0.0) + float(v)
        pending.clear()

    for _ in range(max_iters):
        try:
            batch = next(data_iterator)
        except StopIteration:
            break
        if place_batch is not None:
            batch = place_batch(batch)
        pending.append(eval_step(params, batch))
        n += 1
        if len(pending) >= _EVAL_DRAIN_EVERY:
            drain()
    drain()
    return {k: v / max(n, 1) for k, v in totals.items()}


def training_log(cfg, metrics, iteration, step_time, writer, timers,
                 consumed_samples, global_batch_size=None):
    """training_log analog (training.py:462-641)."""
    t = cfg.training
    gbs = global_batch_size or t.global_batch_size
    tokens_per_step = gbs * cfg.data.seq_length
    tps = tokens_per_step / step_time if step_time > 0 else 0.0
    flops = model_flops_per_token(cfg) * tps
    loss = float(metrics.get("lm loss", float("nan")))
    lr = float(metrics.get("learning_rate", 0.0))
    gnorm = float(metrics.get("grad_norm", 0.0))
    msg = (
        f"iteration {iteration:8d}/{t.train_iters or 0:8d} | "
        f"consumed samples: {consumed_samples:12d} | "
        f"elapsed time per iteration (ms): {step_time * 1000:.1f} | "
        f"learning rate: {lr:.3E} | global batch size: {gbs:5d} | "
        f"lm loss: {loss:.6E} | grad norm: {gnorm:.3f} | "
        f"tokens/sec: {tps:,.0f} | TFLOP/s (model): {flops / 1e12:.1f}"
    )
    if "loss_scale" in metrics:
        msg += (f" | loss scale: {float(metrics['loss_scale']):.1f} | "
                f"skipped iterations: {int(metrics['skipped_iterations']):4d}")
    if "num_zeros" in metrics:
        msg += f" | num zeros: {float(metrics['num_zeros']):.0f}"
    if "params_norm" in metrics:
        msg += f" | params norm: {float(metrics['params_norm']):.3f}"
    print0(msg, flush=True)
    if writer is not None:
        writer.add_scalar("lm-loss-training/lm loss", loss, iteration)
        if cfg.logging.log_learning_rate_to_tensorboard:
            writer.add_scalar("learning-rate/learning-rate", lr, iteration)
        writer.add_scalar("grad-norm/grad-norm", gnorm, iteration)
        writer.add_scalar("throughput/tokens-per-sec", tps, iteration)
        writer.add_scalar("batch-size/batch-size", gbs, iteration)
        if "num_zeros" in metrics:
            writer.add_scalar("num-zeros/num-zeros",
                              float(metrics["num_zeros"]), iteration)
        if "params_norm" in metrics:
            writer.add_scalar("params-norm/params-norm",
                              float(metrics["params_norm"]), iteration)
        if cfg.logging.log_memory_to_tensorboard:
            # report_memory analog (reference utils.py:82-96 +
            # training.py:573-589): device memory_stats -> tensorboard
            try:
                stats = jax.local_devices()[0].memory_stats() or {}
            except Exception:
                stats = {}
            for key in ("bytes_in_use", "peak_bytes_in_use"):
                if key in stats:
                    writer.add_scalar(f"memory/{key}", stats[key], iteration)
        if cfg.logging.log_timers_to_tensorboard and timers is not None:
            timers.write(writer, iteration)
    if registry_mod.publishing():
        # mirror the log line into the process-wide registry so a live
        # scrape of /metrics sees what the console sees (sync-free: all
        # inputs are the host floats computed above)
        reg = registry_mod.get_registry()
        reg.gauge("mlt_iteration", help="training iteration").set(iteration)
        reg.gauge("mlt_consumed_samples",
                  help="samples consumed").set(consumed_samples)
        reg.gauge("mlt_lm_loss", help="last fetched lm loss").set(loss)
        reg.gauge("mlt_learning_rate", help="current learning rate").set(lr)
        reg.gauge("mlt_tokens_per_sec",
                  help="training throughput over the last interval").set(tps)
        reg.gauge("mlt_step_time_seconds",
                  help="mean step time over the last interval").set(step_time)
        frac = flops_mod.mfu(cfg, tps, device_kind=_device_kind())
        reg.gauge("mlt_steady_mfu",
                  help="model flops utilization over the last interval "
                       "(0 when no device peak is known)").set(frac or 0.0)
    if timers is not None and cfg.logging.timing_log_level > 0:
        log = timers.log()
        if log:
            print0(f"    timers(ms): {log}", flush=True)


def pretrain(
    cfg,
    data_iterators_provider: Optional[Callable] = None,
    params_provider: Optional[Callable] = None,
    loss_fn: Optional[Callable] = None,
    pipeline_hooks: Optional[Callable] = None,
    pipeline_loss: Optional[Callable] = None,
) -> Dict[str, Any]:
    """End-to-end training (pretrain analog, training.py:55-196).

    Returns final state dict for programmatic use/testing.
    """
    t0 = time.time()
    from megatron_llm_tpu.core.distributed import initialize_distributed

    initialize_distributed()  # no-op single-host; pod autodetect multi-host
    mesh = build_mesh_from_config(cfg)
    print0(f"mesh: {dict(mesh.shape)}")
    for _ax, _size in dict(mesh.shape).items():
        registry_mod.get_registry().gauge(
            "mlt_mesh_axis_size", help="mesh axis size",
            labels={"axis": str(_ax)}).set(_size)
    tokenizer = None
    if cfg.data.tokenizer_type and (cfg.data.data_path or cfg.data.tokenizer_model
                                    or cfg.data.tokenizer_type == "NullTokenizer"):
        tokenizer = build_tokenizer(cfg)
        set_global("tokenizer", tokenizer)

    timers = Timers(cfg.logging.timing_log_level, cfg.logging.timing_log_option)
    writer = build_writer(cfg)
    sig = SignalHandler() if cfg.training.exit_signal_handler else None

    # ---- observability (megatron_llm_tpu/observability/,
    # docs/guide/observability.md): span tracer, metrics endpoint,
    # on-demand profiler.  All host-side and sync-free — the async loop's
    # overlap (and its bitwise loss guarantee) survives instrumentation.
    obs = cfg.logging
    profile_dir = obs.profile_dir or os.path.join(
        obs.tensorboard_dir or ".", "profile"
    )
    tracer = None
    if obs.trace_dir:
        os.makedirs(obs.trace_dir, exist_ok=True)
        tracer = trace_mod.configure(capacity=obs.trace_buffer_events)
        print0(f"observability: span tracing -> {obs.trace_dir} "
               f"(window {obs.trace_steps} steps, ring "
               f"{obs.trace_buffer_events} events)")
    from megatron_llm_tpu.observability.profiler import (
        ProfileTrigger,
        install_sigusr2,
    )

    profile_trigger = ProfileTrigger(
        os.path.join(profile_dir, "ondemand"),
        max_captures=obs.profile_max_captures,
    )
    prev_usr2 = install_sigusr2(profile_trigger)
    exporter = None
    if obs.metrics_port is not None:
        from megatron_llm_tpu.observability.exporter import MetricsExporter

        exporter = MetricsExporter(registry_mod.get_registry(),
                                   profile_trigger, port=obs.metrics_port)
        print0(f"observability: /metrics + /profile on port "
               f"{exporter.start()}")

    with global_mesh(mesh):
        # ---- model + optimizer ----
        init_fn = params_provider or (lambda key: init_model_params(cfg, key))
        key = rng_mod.init_key(cfg.training.seed)
        shapes = jax.eval_shape(init_fn, key)
        p_shardings = param_shardings(mesh, shapes)
        timers("model-setup", 0).start()
        params = jax.jit(init_fn, out_shardings=p_shardings)(key)
        step_fn, optimizer, shardings = make_jitted_train_step(
            cfg, mesh, params, loss_fn=loss_fn, pipeline_hooks=pipeline_hooks,
            pipeline_loss=pipeline_loss,
        )
        opt_state = shardings["opt_state_value"]
        timers("model-setup").stop()
        if cfg.parallel.pipeline_model_parallel_size > 1:
            from megatron_llm_tpu.parallel.pipeline import (
                pipeline_bubble_fraction,
            )

            ppl = cfg.parallel
            bubble = pipeline_bubble_fraction(
                ppl.num_micro_batches or 1,
                ppl.pipeline_model_parallel_size,
                ppl.virtual_pipeline_model_parallel_size or 1,
            )
            # a batch-size ramp runs fewer microbatches early on — this is
            # the steady-state (full global batch) figure
            print0(f"pipeline: schedule={ppl.pipeline_schedule} "
                  f"vpp={ppl.virtual_pipeline_model_parallel_size or 1} "
                  f"steady-state bubble fraction={bubble:.3f}", flush=True)
        if cfg.optimizer.use_distributed_optimizer:
            from megatron_llm_tpu.core.parallel_state import DP_AXIS, EP_AXIS
            from megatron_llm_tpu.optimizer.optimizer import (
                zero1_sharded_fraction,
            )

            dp_ax = mesh.shape.get(DP_AXIS, 1)
            ep_ax = mesh.shape.get(EP_AXIS, 1)
            frac = zero1_sharded_fraction(
                cfg, params, opt_state, dp_ax, ep_size=ep_ax
            )
            over = f"dp={dp_ax}" + (f" x ep={ep_ax}" if ep_ax > 1 else "")
            print0(f"ZeRO-1: {frac * 100:.1f}% of optimizer-state elements "
                  f"sharded over {over}", flush=True)

        iteration, consumed_samples = 0, 0
        if cfg.checkpoint.load:
            try:
                o_shardings = opt_state_shardings(cfg, mesh, params, opt_state)
                params, loaded_opt, iteration, consumed_samples, _ = load_checkpoint(
                    cfg, cfg.checkpoint.load, params, opt_state,
                    p_shardings, o_shardings,
                )
                if loaded_opt is not None:
                    opt_state = loaded_opt
                print0(f"loaded checkpoint from {cfg.checkpoint.load} "
                      f"at iteration {iteration}")
            except FileNotFoundError as e:
                if cfg.checkpoint.exit_on_missing_checkpoint:
                    raise
                print0(f"WARNING: {e}; training from scratch")

        # ---- resilience: goodput accounting + hang watchdog ----
        # (docs/guide/resilience.md) The supervisor (tools/run_resilient.py)
        # exports MLT_RESIL_DIR; standalone runs fall back to a subdir of
        # the save dir so goodput/progress records always have a home when
        # checkpoints do.
        from megatron_llm_tpu.resilience import goodput as gp_mod
        from megatron_llm_tpu.resilience.watchdog import StepWatchdog

        resil_dir = os.environ.get("MLT_RESIL_DIR") or (
            os.path.join(cfg.checkpoint.save, "resilience")
            if cfg.checkpoint.save else None
        )
        goodput = gp_mod.GoodputTracker(t0)
        goodput.run_started(iteration, gp_mod.read_progress(resil_dir))
        if goodput.replayed_steps:
            print0(f"resilience: replaying {goodput.replayed_steps} steps "
                   f"(progress high-water {goodput.prev_progress_iteration}, "
                   f"resumed at {iteration})")

        watchdog = None
        if cfg.resilience.watchdog:
            def _emergency_snapshot():
                # host snapshot of the last COMPLETED state the driver
                # holds; bounded by the watchdog (a wedged device hangs
                # device_get too), and safe: the tracker only advances
                # past a verified manifest, so a torn write is never
                # referenced
                if cfg.checkpoint.save:
                    save_checkpoint(cfg, cfg.checkpoint.save, iteration,
                                    params, opt_state, consumed_samples)

            r = cfg.resilience
            watchdog = StepWatchdog(
                multiplier=r.watchdog_multiplier,
                min_deadline=r.watchdog_min_deadline,
                first_deadline=r.watchdog_first_deadline,
                snapshot_fn=_emergency_snapshot,
                snapshot_timeout=r.emergency_save_timeout,
                gauge_fn=lambda: timers.gauge("watchdog-expired", 1.0),
                # a hang report should carry a timeline: the span ring
                # buffer dumps next to the thread-stack dump (satellite;
                # without --trace_dir the watchdog falls back to a text
                # tail of the global tracer, if any)
                trace_dump_fn=(
                    (lambda: tracer.dump(
                        os.path.join(obs.trace_dir, "trace_watchdog.json"),
                        drain=False))
                    if tracer is not None else None),
                # and the in-flight request flight records next to it
                # (ISSUE 12): a hang report should name the request
                # state, not just the thread stacks.  Resolved at expiry
                # time — an engine constructed after the watchdog (e.g.
                # a serving sidecar) still gets its records dumped.
                flight_dump_fn=(
                    (lambda: (flight_mod.get_recorder().dump(
                        os.path.join(obs.trace_dir,
                                     "flight_watchdog.json"))
                        if flight_mod.get_recorder() is not None
                        and flight_mod.get_recorder().enabled else None))
                    if tracer is not None else None),
            ).start()
            print0(f"resilience: watchdog armed per step "
                   f"(deadline {r.watchdog_multiplier}x EMA, floor "
                   f"{r.watchdog_min_deadline:.0f}s, first step "
                   f"{r.watchdog_first_deadline:.0f}s)")

        # ---- data ----
        rebuild_full_loader = None
        if data_iterators_provider is not None:
            if cfg.training.rampup_batch_size is not None:
                raise ValueError(
                    "rampup_batch_size requires the built-in data path: "
                    "provider loaders yield fixed global_batch_size batches, "
                    "which the ramp's chunked accounting would mis-count"
                )
            train_iter, valid_iter_factory = data_iterators_provider(
                cfg, tokenizer, consumed_samples
            )
        elif cfg.data.data_path or cfg.data.train_data_path:
            loader, (train_ds, valid_ds, _) = build_data_iterators(cfg, tokenizer)
            train_iter = loader(train_ds, consumed_samples)
            # validation always runs at the FULL global batch size (the ramp
            # only chunks the training loader)
            valid_iter_factory = (
                (lambda: loader(valid_ds, 0, cfg.training.global_batch_size))
                if valid_ds else None
            )
            # once a batch-size ramp completes, drop back to full-global-batch
            # loading (no per-iteration chunk concatenation)
            rebuild_full_loader = lambda consumed: loader(  # noqa: E731
                train_ds, consumed, cfg.training.global_batch_size
            )
        else:
            raise ValueError("no data: set cfg.data.data_path or pass a provider")

        eval_step = make_eval_step(cfg, loss_fn=loss_fn)

        # ---- train loop (_train analog, training.py:654-770) ----
        # Overlapped: dispatch runs ahead of completion (bounded by
        # --async_dispatch_depth), data is staged by a prefetch thread
        # (--prefetch_depth), checkpoint writes go to a writer thread
        # (--async_save). Dispatch order — and so the numerical
        # trajectory — is identical to the synchronous loop.
        from megatron_llm_tpu.microbatches import build_num_microbatches_calculator

        t = cfg.training
        calc = build_num_microbatches_calculator(cfg)
        rampup = t.rampup_batch_size is not None
        chunk = _loader_granularity(cfg)
        # one compiled step per num-microbatches stage (constant: exactly one)
        step_cache = {cfg.parallel.num_micro_batches or 1: step_fn}
        train_iters = t.train_iters or 0
        exit_reason = "train_iters reached"
        metrics: Dict[str, Any] = {}
        log_interval = max(cfg.logging.log_interval, 1)
        depth = max(int(t.async_dispatch_depth or 0), 0)
        # bounded (the old list grew for the whole run): host-side
        # dispatch-to-dispatch deltas, kept for the last interval only
        step_times: deque = deque(maxlen=log_interval)
        loss_series: deque = deque(maxlen=_LOSS_SERIES_MAXLEN)
        in_flight: deque = deque()  # (iteration, metrics-on-device)
        warmup_time = None  # first dispatched step = compile + warmup
        interval_t0 = time.perf_counter()
        interval_steps = 0
        steady_t0 = None
        steady_steps = 0
        last_dispatch = None
        placed = None

        def _retire(n: Optional[int] = None):
            """Completion probe: fetch the oldest ``n`` in-flight metric
            dicts (all when None) in ONE batched device_get — this is the
            only place the host waits on the device."""
            nonlocal metrics
            take = len(in_flight) if n is None else min(n, len(in_flight))
            if take == 0:
                return metrics
            entries = [in_flight.popleft() for _ in range(take)]
            with trace_mod.span("metric-drain", count=take):
                hosts = jax.device_get([m for _, m in entries])
            for (it, _), host in zip(entries, hosts):
                loss_series.append((it, float(host.get("lm loss", np.nan))))
                metrics = host
            return metrics

        prefetcher = None
        if (t.prefetch_depth and int(t.prefetch_depth) > 0
                and not t.skip_train and iteration < train_iters):
            from megatron_llm_tpu.data.prefetch import BatchPrefetcher

            shadow = build_num_microbatches_calculator(cfg)

            def _gbs_fn(consumed):
                # shadow of the driver's ramp schedule: a pure function of
                # consumed samples, so worker and driver stay in lockstep
                shadow.update(consumed, False)
                return shadow.get_current_global_batch_size()

            prefetcher = BatchPrefetcher(
                train_iter,
                depth=int(t.prefetch_depth),
                # multi-host placement assembles global arrays from every
                # process — keep it on the driver thread there
                place_fn=(shardings["place_batch"]
                          if jax.process_count() == 1 else None),
                gbs_fn=_gbs_fn,
                chunk_size=chunk if rampup else None,
                consumed_samples=consumed_samples,
                max_steps=train_iters - iteration,
                switch_source=rebuild_full_loader,
                full_gbs=t.global_batch_size,
            )

        saver = None
        if cfg.checkpoint.async_save:
            if jax.process_count() == 1:
                saver = AsyncCheckpointSaver()
            else:
                print0("WARNING: --async_save is single-host only (the "
                       "snapshot of multi-host sharded arrays needs every "
                       "process in the orbax save); saving synchronously")

        def _save(it):
            timers("save-checkpoint", 0).start()
            # "ckpt-flush" = what the DRIVER pays at a save point: under
            # --async_save the previous write's flush barrier + the host
            # snapshot; synchronously the whole write (the writer thread's
            # own span is "ckpt-write", checkpointing.py)
            with trace_mod.span("ckpt-flush", iteration=it):
                if saver is not None:
                    waited = saver.save(cfg, cfg.checkpoint.save, it, params,
                                        opt_state, consumed_samples)
                    timers.gauge("ckpt-flush-wait-ms", waited * 1e3)
                else:
                    save_checkpoint(cfg, cfg.checkpoint.save, it, params,
                                    opt_state, consumed_samples)
            timers("save-checkpoint").stop()

        profiling = False
        profile_stop_at = None  # set when the trace starts
        spans_printed = False

        try:
            while iteration < train_iters:
                if t.skip_train:
                    break
                # watchdog window covers the loop body (data wait, dispatch,
                # completion probe, log drain) — the places a wedged device
                # or dead loader silently blocks the host.  Eval and
                # checkpoint saves run disarmed: legitimately slow.
                if watchdog is not None:
                    watchdog.arm(first=warmup_time is None)
                iter_t0 = time.perf_counter()
                trace_mod.instant("step-begin", iteration=iteration)
                # on-demand capture (SIGUSR2 / GET /profile?steps=N) starts
                # at a step boundary — never from a handler frame, never
                # inside the static --profile window
                if not profiling and profile_trigger.maybe_start(iteration):
                    print0(f"profiler: on-demand capture started at "
                           f"iteration {iteration}", flush=True)
                # xplane tracing over [profile_step_start, profile_step_end)
                # (SURVEY §5: jax-profiler analog of the reference's span
                # timers). >= not ==: a resumed run past the start step still
                # gets a trace (of at least one step, even past the window)
                if (cfg.logging.profile and profile_stop_at is None
                        and not profile_trigger.active
                        and iteration >= cfg.logging.profile_step_start):
                    jax.profiler.start_trace(profile_dir)
                    profiling = True
                    profile_stop_at = max(cfg.logging.profile_step_end,
                                          iteration + 1)
                calc.update(consumed_samples)
                gbs = calc.get_current_global_batch_size()
                num_micro = calc.get()
                if (prefetcher is None and rampup
                        and gbs == t.global_batch_size and rebuild_full_loader):
                    # ramp finished: switch to full-global-batch loading so
                    # steady state pays no per-iteration chunk concatenation
                    # (the prefetch worker makes this same switch itself)
                    train_iter = rebuild_full_loader(consumed_samples)
                    rampup = False
                if num_micro not in step_cache:
                    step_cache[num_micro] = make_jitted_train_step(
                        cfg, mesh, params, num_micro=num_micro,
                        optimizer=optimizer, opt_state=opt_state,
                        loss_fn=loss_fn, pipeline_hooks=pipeline_hooks,
                        pipeline_loss=pipeline_loss,
                    )[0]
                cur_step_fn = step_cache[num_micro]
                try:
                    timers("batch-generator", 1).start()
                    wait_t0 = time.perf_counter()
                    with trace_mod.span("data-wait", iteration=iteration):
                        if prefetcher is not None:
                            pre_gbs, placed = next(prefetcher)
                            if pre_gbs is not None and pre_gbs != gbs:
                                raise RuntimeError(
                                    f"prefetch schedule diverged: worker "
                                    f"staged gbs {pre_gbs}, driver expects "
                                    f"{gbs}")
                            if prefetcher.place_fn is None:  # multi-host
                                placed = shardings["place_batch"](placed)
                        else:
                            if rampup:
                                chunks = [next(train_iter)
                                          for _ in range(gbs // chunk)]
                                # token_idx is batch-invariant [s] — never
                                # concatenated
                                batch = {
                                    k: (chunks[0][k] if k == "token_idx"
                                        else np.concatenate(
                                            [c[k] for c in chunks]))
                                    for k in chunks[0]
                                }
                            else:
                                batch = next(train_iter)
                            placed = shardings["place_batch"](batch)
                    timers.gauge("data-wait-ms",
                                 (time.perf_counter() - wait_t0) * 1e3)
                    timers("batch-generator").stop()
                except StopIteration:
                    exit_reason = "data exhausted"
                    break

                timers("train-step", 0).start()
                dispatch_t0 = time.perf_counter()
                if last_dispatch is not None:
                    step_times.append(dispatch_t0 - last_dispatch)
                last_dispatch = dispatch_t0
                first_step = False
                if iteration not in (t.skip_iters or []):
                    # --skip_iters skips the update (training.py:397-399)
                    with trace_mod.span("dispatch", iteration=iteration):
                        params, opt_state, metrics_dev = cur_step_fn(
                            params, opt_state, placed, iteration,
                        )
                        in_flight.append((iteration + 1, metrics_dev))
                    timers.gauge("in-flight-depth", len(in_flight))
                    if warmup_time is None:
                        # fence the compile step out of throughput so the
                        # first training_log line is honest
                        _retire()
                        warmup_time = time.perf_counter() - dispatch_t0
                        first_step = True
                        print0(f"first step (compile + warmup): "
                               f"{warmup_time:.2f}s — excluded from "
                               f"throughput averages", flush=True)
                    else:
                        while len(in_flight) > depth:
                            _retire(1)
                timers("train-step").stop()
                iteration += 1
                consumed_samples += gbs
                if first_step:
                    interval_t0 = steady_t0 = time.perf_counter()
                    interval_steps = 0
                else:
                    interval_steps += 1
                    steady_steps += 1

                if profiling and iteration >= profile_stop_at:
                    jax.profiler.stop_trace()
                    profiling = False
                    print0(f"profiler: xplane trace written to {profile_dir}",
                           flush=True)
                if profile_trigger.step_done():
                    print0(f"profiler: on-demand capture written to "
                           f"{profile_trigger.capture_dirs[-1]}", flush=True)
                if (tracer is not None and obs.trace_steps > 0
                        and iteration % obs.trace_steps == 0):
                    # one Chrome-trace file per N-step window (drains the
                    # ring, so windows are disjoint)
                    tracer.dump(os.path.join(
                        obs.trace_dir, f"trace_{iteration:08d}.json"))

                if iteration % log_interval == 0:
                    # drain: one batched fetch for the whole interval
                    _retire()
                    now = time.perf_counter()
                    avg = ((now - interval_t0) / interval_steps
                           if interval_steps > 0 else (warmup_time or 0.0))
                    training_log(cfg, metrics, iteration, avg, writer, timers,
                                 consumed_samples, global_batch_size=gbs)
                    if cfg.logging.timing_log_level >= 2 and not spans_printed:
                        spans_printed = True  # once per run, incl. resumed
                        spans = measure_span_breakdown(
                            cfg, params, placed, avg, loss_fn=loss_fn,
                        )
                        if spans:
                            print0("    span breakdown (ms): " + " | ".join(
                                f"{k}: {v * 1e3:.1f}"
                                for k, v in spans.items()), flush=True)
                    if registry_mod.publishing():
                        # live goodput snapshot for /metrics scrapes (the
                        # exit path overwrites these with final numbers;
                        # report() publishes its fields to the registry)
                        goodput.record_compile(warmup_time or 0.0)
                        if steady_t0 is not None:
                            goodput.record_productive(
                                steady_steps, now - steady_t0)
                        goodput.report()
                    interval_t0 = time.perf_counter()
                    interval_steps = 0
                    if resil_dir:
                        # progress high-water mark: what a restart would
                        # have to replay from the last checkpoint
                        gp_mod.write_progress(resil_dir, iteration)

                if watchdog is not None:
                    watchdog.disarm(None if first_step
                                    else time.perf_counter() - iter_t0)

                if (cfg.training.eval_interval and valid_iter_factory
                        and iteration % cfg.training.eval_interval == 0):
                    with trace_mod.span("eval", iteration=iteration):
                        ev = evaluate(cfg, params, eval_step,
                                      valid_iter_factory(),
                                      place_batch=shardings["place_batch"])
                    print0(f" validation loss at iteration {iteration}: "
                           + " | ".join(f"{k}: {v:.6E}" for k, v in ev.items()),
                           flush=True)
                    if writer:
                        for k, v in ev.items():
                            writer.add_scalar(f"lm-loss-validation/{k}", v,
                                              iteration)

                if (cfg.checkpoint.save and cfg.checkpoint.save_interval
                        and iteration % cfg.checkpoint.save_interval == 0):
                    _save(iteration)

                # exit conditions (training.py:731-767) — checked on the
                # deferred state: breaking with steps still in flight is
                # fine, the drain below lands their metrics
                if sig is not None and sig.signals_received():
                    exit_reason = "signal"
                    break
                if t.exit_interval and iteration % t.exit_interval == 0:
                    exit_reason = "exit_interval"
                    break
                if t.exit_duration_in_mins and (
                    (time.time() - t0) / 60.0 > t.exit_duration_in_mins
                ):
                    exit_reason = "exit_duration"
                    break

            # land any still-deferred metrics before leaving the loop
            if watchdog is not None:
                watchdog.disarm()  # StopIteration breaks exit armed
            _retire()
            steady_end = time.perf_counter()
        finally:
            # watchdog first: cleanup below (close/join/flush) is
            # legitimately slow and must not trip a stale deadline
            if watchdog is not None:
                watchdog.stop()
            if prefetcher is not None:
                prefetcher.close()
            if profiling:  # early exit mid-window: don't leak an open trace
                jax.profiler.stop_trace()
                profiling = False
            profile_trigger.close()  # nor an open on-demand window
            if saver is not None:
                # exit barrier: never leave the loop (even on an exception
                # or a signal) with checkpoint bytes half-written
                saver.wait()
            # goodput report on EVERY exit path (normal, exception,
            # signal-break) so the supervisor can aggregate what this
            # attempt kept vs. lost
            goodput.record_compile(warmup_time or 0.0)
            if steady_t0 is not None:
                goodput.record_productive(
                    steady_steps, time.perf_counter() - steady_t0)
            goodput_report = goodput.report()
            if resil_dir:
                gp_mod.write_report(resil_dir, goodput_report)
            print0("goodput: "
                   f"{goodput_report['goodput_fraction'] * 100:.1f}% "
                   f"({goodput_report['productive_seconds']:.1f}s productive"
                   f" / {goodput_report['wall_seconds']:.1f}s wall, "
                   f"compile {goodput_report['lost_compile_seconds']:.1f}s, "
                   f"replay {goodput_report['replayed_steps']} steps)",
                   flush=True)
            if tracer is not None:
                # whatever the exit path, the tail of the timeline lands
                # on disk (the window dumps drained everything older)
                print0("observability: final trace window -> " + tracer.dump(
                    os.path.join(obs.trace_dir,
                                 f"trace_final_{iteration:08d}.json")))
            if exporter is not None:
                exporter.stop()
            if prev_usr2 is not None:
                import signal as signal_mod

                signal_mod.signal(signal_mod.SIGUSR2, prev_usr2)

        steady_sps = None
        if steady_t0 is not None and steady_steps > 0:
            steady_sps = steady_steps / max(steady_end - steady_t0, 1e-9)
        steady_tps = steady_mfu_val = None
        if steady_sps is not None:
            # config-derived flops (observability/flops.py) feed the result
            # dict and the registry: the Megatron-style MFU signal
            steady_tps = (steady_sps * t.global_batch_size
                          * cfg.data.seq_length)
            steady_mfu_val = flops_mod.mfu(cfg, steady_tps,
                                           device_kind=_device_kind())
            if registry_mod.publishing():
                reg = registry_mod.get_registry()
                reg.gauge("mlt_tokens_per_sec").set(steady_tps)
                reg.gauge("mlt_steady_mfu").set(steady_mfu_val or 0.0)

        if cfg.checkpoint.save and exit_reason != "train_iters reached":
            _save(iteration)
            if saver is not None:
                saver.wait()
        if writer is not None and hasattr(writer, "flush"):
            writer.flush()

        return {
            "params": params,
            "opt_state": opt_state,
            "iteration": iteration,
            "consumed_samples": consumed_samples,
            "exit_reason": exit_reason,
            "last_metrics": metrics,
            "mesh": mesh,
            # async-loop observability (bench_train_loop.py evidence):
            # compile+warmup wall time, post-warmup steps/sec, and the
            # fetched (iteration, lm loss) trajectory (bounded window)
            "warmup_time": warmup_time,
            "steady_steps_per_sec": steady_sps,
            # observability (docs/guide/observability.md): steady-state
            # throughput in tokens and model-flops terms (MFU is None on
            # hosts with no known peak, e.g. CPU), and the bound /metrics
            # port when --metrics_port was set (0 binds ephemerally)
            "tokens_per_sec": steady_tps,
            "steady_mfu": steady_mfu_val,
            "metrics_port": exporter.port if exporter is not None else None,
            "loss_series": list(loss_series),
            # resilience observability (docs/guide/resilience.md): what this
            # run kept vs. lost to compile/replay — also persisted to
            # <resil_dir>/goodput_last.json for the supervisor
            "goodput": goodput_report,
        }
