"""TPU-native LLM training framework with the capabilities of Megatron-LLM.

JAX/XLA SPMD over a (dp, pp, cp, tp) device mesh; Pallas kernels for the hot
ops; functional models; orbax checkpoints. See SURVEY.md for the blueprint.
"""

__version__ = "0.1.0"
