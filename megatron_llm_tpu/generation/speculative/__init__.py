"""Speculative decoding — draft-model propose, target-model verify.

At low batch the engine is latency-bound on one target forward per token.
Speculative decoding breaks that bound: a small *draft* model proposes
``k`` tokens autoregressively (cheap forwards), then the target model
scores all ``k+1`` positions in ONE forward and a lossless acceptance
rule keeps the longest prefix the target agrees with — one target-model
dispatch now yields between 1 and ``k+1`` tokens.

The subsystem lives in three pieces:

* :mod:`~megatron_llm_tpu.generation.speculative.draft` — the draft model
  bundle: a separate (same-family, smaller) config + params that share
  the target's tokenizer/vocab, resolved from ``--spec_draft`` and
  sharded by the same tp.py rules when a mesh is present.
* :mod:`~megatron_llm_tpu.generation.speculative.verify` — the lossless
  acceptance rule (greedy: bitwise-identical to non-speculative decode;
  sampled: residual rejection sampling whose output distribution provably
  equals the target model's) and the disjoint key-stream discipline.  The
  fused draft-k-then-verify tick program itself lives in
  :mod:`~megatron_llm_tpu.generation.ragged` (ISSUE 11): verify blocks
  are ordinary span-(k+1) entries of the engine's single-launch ragged
  tick, not a special-cased program.
* the engine integration (generation/engine.py): draft K/V lives in the
  SAME :class:`~megatron_llm_tpu.generation.engine.PagedKVPool` — every
  page id indexes both the target and the draft pools, so one block
  table, one refcount, one commitment ledger and one prefix trie govern
  both models' cache, and preempting a speculating slot releases draft
  pages through exactly the same trie-park path as target pages.

See docs/guide/serving.md ("Speculative decoding") for the flag table,
acceptance semantics and the losslessness contract.
"""

from megatron_llm_tpu.generation.speculative.draft import (
    DraftModel,
    check_draft_compat,
    extend_params_identity,
    resolve_draft,
)
from megatron_llm_tpu.generation.speculative.verify import (
    speculative_acceptance,
)

__all__ = [
    "DraftModel",
    "check_draft_compat",
    "extend_params_identity",
    "resolve_draft",
    "speculative_acceptance",
]
