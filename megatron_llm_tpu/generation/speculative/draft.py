"""Draft-model bundle for speculative decoding.

The draft is a full model in its own right — same architecture family as
the target, its own (smaller) config and params — but it serves one
purpose: proposing tokens the target then verifies.  Three contracts keep
it honest:

* **Shared token space.** Draft and target must agree on the vocab (and
  therefore the tokenizer): acceptance compares token ids, and the
  residual rejection sampler subtracts the draft distribution from the
  target's over the SAME vocab axis.  ``check_draft_compat`` enforces it.
* **Shared page geometry.** The draft's K/V lives in the same
  :class:`~megatron_llm_tpu.generation.engine.PagedKVPool` as the
  target's — same page ids, same block tables, same refcounts — so the
  draft only needs a per-layer/head shape of its own, which the pool
  allocates alongside the target arrays.
* **Same sharding rules.** Under a tensor-parallel mesh the draft params
  shard by the identical parallel/tp.py rules as the target (the engine
  applies them at construction), so one mesh serves both models.

``resolve_draft`` turns the ``--spec_draft`` flag into a bundle:

* ``"llama2:num_layers=2,hidden_size=256"`` — a make_config spec,
  random-initialized (smoke/bench shape; inherits the target's vocab
  when the spec does not name one);
* ``"llama2:num_layers=2,...@/path/to/ckpt"`` — same, with params loaded
  from a checkpoint directory instead of random init.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class DraftModel:
    """A (config, params) pair the engine speculates with."""

    cfg: Any
    params: Any

    @property
    def num_params(self) -> int:
        return sum(int(x.size) for x in jax.tree_util.tree_leaves(self.params))


def check_draft_compat(target_cfg, draft_cfg, *, max_seq: int) -> None:
    """Assert the draft can propose for the target: same token space, a
    position horizon covering the engine's sequence budget, and a KV shape
    the shared page pool can host alongside the target's."""
    t, d = target_cfg.model, draft_cfg.model
    if t.vocab_size != d.vocab_size:
        raise ValueError(
            f"draft vocab {d.vocab_size} != target vocab {t.vocab_size} — "
            "speculative acceptance compares token ids, the models must "
            "share a tokenizer")
    if d.max_position_embeddings < max_seq:
        raise ValueError(
            f"draft max_position_embeddings {d.max_position_embeddings} < "
            f"engine max_seq {max_seq}")
    if getattr(d, "sliding_window_size", None) != getattr(
            t, "sliding_window_size", None):
        raise ValueError(
            "draft and target must agree on sliding_window_size: the "
            "verify step replays draft-advanced positions through the "
            "target's attention horizon")
    from megatron_llm_tpu.models.language_model import padded_vocab_size

    if padded_vocab_size(t.vocab_size, target_cfg) != padded_vocab_size(
            d.vocab_size, draft_cfg):
        raise ValueError(
            "draft and target padded vocab widths differ — the residual "
            "rejection sampler subtracts q from p over the same axis")


def _parse_override(raw: str):
    raw = raw.strip()
    low = raw.lower()
    if low in ("true", "false"):
        return low == "true"
    if low in ("none", "null"):
        return None
    try:
        return int(raw)
    except ValueError:
        pass
    try:
        return float(raw)
    except ValueError:
        return raw


def parse_draft_spec(spec: str):
    """``"family:key=val,...[@/ckpt/dir]"`` -> (family, overrides, load)."""
    load: Optional[str] = None
    if "@" in spec:
        spec, load = spec.rsplit("@", 1)
    family, _, kvs = spec.partition(":")
    overrides = {}
    for part in kvs.split(","):
        if not part.strip():
            continue
        k, _, v = part.partition("=")
        if not _:
            raise ValueError(f"--spec_draft override {part!r} is not key=val")
        overrides[k.strip()] = _parse_override(v)
    return family.strip(), overrides, load


def resolve_draft(spec: str, target_cfg, *, seed: int = 0) -> DraftModel:
    """Build the draft bundle the ``--spec_draft`` flag names."""
    from megatron_llm_tpu.models import init_model_params, make_config

    family, overrides, load = parse_draft_spec(spec)
    t = target_cfg.model
    overrides.setdefault("vocab_size", t.vocab_size)
    overrides.setdefault("seq_length", target_cfg.data.seq_length)
    overrides.setdefault("max_position_embeddings", t.max_position_embeddings)
    overrides.setdefault("params_dtype", target_cfg.training.params_dtype)
    overrides.setdefault("use_flash_attn", target_cfg.training.use_flash_attn)
    overrides.setdefault("micro_batch_size", 1)
    overrides.setdefault("global_batch_size", 1)
    overrides.setdefault("train_iters", 1)
    cfg = make_config(family, **overrides)

    key = jax.random.PRNGKey(seed)
    if load is None:
        params = init_model_params(cfg, key)
    else:
        from megatron_llm_tpu.checkpointing import load_checkpoint

        template = jax.eval_shape(lambda k: init_model_params(cfg, k), key)
        params, _, _, _, _ = load_checkpoint(cfg, load, template)
    return DraftModel(cfg, params)


def extend_params_identity(draft_cfg, draft_params, target_cfg,
                           key: jax.Array):
    """Target params whose first ``L_draft`` layers ARE the draft and whose
    remaining layers are exact identities (zeroed attention-output and
    fc2 projections: both residual branches contribute exactly 0.0, so the
    extra layers pass hidden states through bit-for-bit).

    This is the bench/test construction for a draft the target provably
    agrees with: greedy acceptance is 100% while the target still pays for
    ``L_target`` layers of compute — the honest way to exercise the
    speculative pipeline's mechanics on random-init weights, where an
    independently initialized draft would accept ~nothing.
    Requires equal hidden/head/ffn dims; only ``num_layers`` may differ.
    """
    from megatron_llm_tpu.models import init_model_params

    d, t = draft_cfg.model, target_cfg.model
    for f in ("hidden_size", "num_attention_heads", "num_attention_heads_kv",
              "kv_channels", "ffn_hidden_size", "vocab_size"):
        assert getattr(d, f) == getattr(t, f), (
            f"identity extension needs equal {f}")
    L_d, L_t = d.num_layers, t.num_layers
    assert L_t >= L_d
    target = init_model_params(target_cfg, key)
    # non-layer leaves come straight from the draft (same shapes)
    for k in draft_params:
        if k != "layers":
            target[k] = jax.tree_util.tree_map(lambda x: x, draft_params[k])

    def splice(d_leaf, t_leaf, path):
        ext = t_leaf[L_d:]
        if path[:2] in (("attention", "dense"), ("mlp", "fc2")):
            ext = jnp.zeros_like(ext)
        return jnp.concatenate([d_leaf, ext], axis=0)

    def walk(dn, tn, path=()):
        if isinstance(dn, dict):
            return {k: walk(dn[k], tn[k], path + (k,)) for k in dn}
        return splice(dn, tn, path)

    target["layers"] = walk(draft_params["layers"], target["layers"])
    return target
