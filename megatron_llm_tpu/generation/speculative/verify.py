"""The fused draft-then-verify tick and the lossless acceptance rule.

One compiled program per engine geometry does all of:

1. **Draft k tokens** — ``spec_k`` autoregressive s=1 forwards of the
   draft model (a ``lax.scan``), each writing draft K/V through the SAME
   block tables as the target and sampling with the slot's own filters
   (temperature / top-k / top-p), so the proposal distribution ``q`` is
   exactly the distribution a non-speculative draft-model decode would
   sample from.

2. **Verify k+1 positions in one target forward** — the key numerics
   design: the verify does NOT run the ``s = k+1`` prefill-attention
   path.  On the CPU fallback (and in general), a program with a
   different query-span shape reassociates reductions differently and
   drifts from the decode tick by a last-ulp — which would break the
   bitwise-losslessness contract.  Instead the k+1 query positions are
   **flattened into the batch dimension**: row ``(slot i, offset j)``
   feeds one token at position ``pos_i + j`` with slot ``i``'s block
   table — every op in the forward is then *structurally identical* to
   the non-speculative decode tick (an s=1 paged decode, just with a
   larger batch), and per-row bits are batch-size invariant.  The target
   logits at each verified position are therefore bitwise what the
   decode tick would have produced, and greedy speculative decode emits
   bitwise-identical tokens AND log-probs (tests/test_speculative.py).
   K/V writes land first (each row a distinct (page, offset) — rows of a
   slot write consecutive positions, different slots own disjoint
   writable pages), then every row attends causally ``<= its position``:
   write-then-attend, exactly the decode tick's order.

3. **Lossless acceptance** (:func:`speculative_acceptance`) — greedy
   rows accept a draft token iff it equals the target argmax, and emit
   the target argmax at the first mismatch (so the emitted stream IS the
   greedy target stream, whatever the draft proposed); sampled rows run
   standard residual rejection sampling: accept ``d_j`` with probability
   ``min(1, p(d_j)/q(d_j))``, on rejection emit from the residual
   ``max(p - q, 0)/Z``, and after k acceptances emit a bonus token from
   ``p`` — the emitted distribution provably equals the target model's
   (the classic speculative-sampling theorem; distribution-matched in
   tests/test_speculative.py).

Key discipline: every random draw derives from
``base = fold_in(request_key, steps)`` (``steps`` = tokens emitted so
far, strictly increasing, pinned across preemption) fanned out through
*disjoint* streams — ``fold_in(fold_in(base, DRAFT_STREAM), j)`` for the
j-th draft draw, one ``fold_in(base, ACCEPT_STREAM)`` key consumed for
the k acceptance uniforms, ``fold_in(base, EMIT_STREAM)`` consumed for
the single rejection/bonus draw.  No key is ever consumed twice
(graftcheck's rng-key-reuse rule analyzes this module; the
draft/verify-split reuse bug is pinned as a historical fixture in
tests/test_graftcheck.py).

Rejected-draft K/V (positions past the accepted frontier) is left in
place: it is only ever attended by a query at an equal-or-later position,
and every such query belongs to a later block that rewrites those
positions first — write-then-attend makes stale speculative K/V
unreachable by construction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from megatron_llm_tpu.generation import generation as gen
from megatron_llm_tpu.generation.sampling import (
    NEG_INF,
    filtered_logits_per_slot,
)
from megatron_llm_tpu.models.language_model import (
    make_rope_cache,
    model_forward,
)
from megatron_llm_tpu.ops.paged_attention import PagedState

# disjoint key streams fanned out of the per-(request, step) base key
DRAFT_STREAM = 1   # j-th draft sampling draw
ACCEPT_STREAM = 2  # the k acceptance uniforms (one key, one draw of [k])
EMIT_STREAM = 3    # the single rejection-residual / bonus draw


def speculative_acceptance(
    draft_toks: jax.Array,   # [b, K] int32 — proposed tokens d_1..d_K
    q_filt: jax.Array,       # [b, K, v] fp32 — draft filtered logits per draw
    t_filt: jax.Array,       # [b, K+1, v] fp32 — target filtered logits
    t_greedy: jax.Array,     # [b, K+1] int32 — target argmax per position
    greedy_row: jax.Array,   # [b] bool — slots decoding greedily (top_k == 1)
    k_eff: jax.Array,        # [b] int32 — per-slot speculation depth (0..K)
    u: jax.Array,            # [b, K] fp32 — acceptance uniforms in [0, 1)
    emit_keys: jax.Array,    # [b, 2] uint32 — one consumed key per row
):
    """The lossless acceptance rule; pure so tests can drive it with
    synthetic distributions.

    Returns ``(accepted, counts, emit)``: per-slot accepted draft count
    ``a`` in [0, k_eff], emitted token count ``m = a + 1``, and the
    emitted tokens ``emit[b, K+1]`` (positions >= m are padding).  Row
    semantics: ``emit[:, t] = d_{t+1}`` for ``t < a``; ``emit[:, a]`` is
    the correction/bonus token — greedy: the target argmax at that
    position; sampled: a residual-rejection draw (or a draw from the full
    target distribution when every valid draft was accepted).
    """
    b, K = draft_toks.shape
    p = jax.nn.softmax(t_filt, axis=-1)          # [b, K+1, v]
    q = jax.nn.softmax(q_filt, axis=-1)          # [b, K, v]
    p_d = jnp.take_along_axis(
        p[:, :K], draft_toks[..., None], axis=-1)[..., 0]   # [b, K]
    q_d = jnp.take_along_axis(
        q, draft_toks[..., None], axis=-1)[..., 0]          # [b, K]
    # u < min(1, p/q) without the division: q_d > 0 for any token the
    # draft actually sampled, and u*q < p is the same event
    acc_sampled = u * q_d < p_d
    acc_greedy = draft_toks == t_greedy[:, :K]
    acc = jnp.where(greedy_row[:, None], acc_greedy, acc_sampled)
    acc &= jnp.arange(K)[None, :] < k_eff[:, None]
    # longest accepted prefix (a rejection kills everything after it)
    accepted = jnp.sum(jnp.cumprod(acc.astype(jnp.int32), axis=1), axis=1)
    counts = accepted + 1

    # correction/bonus token at index `accepted`
    is_bonus = accepted >= k_eff   # every valid draft accepted
    p_at = jnp.take_along_axis(
        p, accepted[:, None, None], axis=1)[:, 0]           # [b, v]
    q_at = jnp.take_along_axis(
        q, jnp.minimum(accepted, K - 1)[:, None, None], axis=1)[:, 0]
    resid = jnp.maximum(
        p_at - jnp.where(is_bonus[:, None], 0.0, q_at), 0.0)
    z = resid.sum(axis=-1, keepdims=True)
    # rejection implies p < q somewhere, so z > 0 up to float rounding;
    # the guard keeps the all-rounded-to-zero corner a draw from p
    resid = jnp.where(z > 0, resid, p_at)
    resid_logits = jnp.where(resid > 0, jnp.log(resid), NEG_INF)
    drawn = jax.vmap(lambda k_, row: jax.random.categorical(k_, row))(
        emit_keys, resid_logits)
    greedy_emit = jnp.take_along_axis(
        t_greedy, accepted[:, None], axis=1)[:, 0]
    emit_at = jnp.where(greedy_row, greedy_emit, drawn).astype(jnp.int32)

    d_pad = jnp.concatenate(
        [draft_toks, jnp.zeros((b, 1), jnp.int32)], axis=1)  # [b, K+1]
    t_idx = jnp.arange(K + 1)[None, :]
    emit = jnp.where(t_idx < accepted[:, None], d_pad,
                     jnp.where(t_idx == accepted[:, None],
                               emit_at[:, None], 0)).astype(jnp.int32)
    return accepted, counts, emit


def make_spec_tick_fn(cfg, draft_cfg, spec_k: int, *, tp: int = 1):
    """Build the fused speculative tick the engine compiles once.

    Signature of the returned function::

        (params, draft_params, pool_k, pool_v, draft_k, draft_v,
         block_tables, positions, tokens, req_keys, steps,
         temperature, top_k, top_p, k_eff)
        -> (pool_k, pool_v, draft_k, draft_v,
            emit [b, K+1], emit_logp [b, K+1],
            accepted [b], counts [b], new_pos, new_tok, new_steps)

    ``k_eff`` caps each slot's ACCEPTED depth; the draft loop still runs
    the static ``spec_k`` steps for every row (one compiled program),
    rows past their ``k_eff`` just produce writes the acceptance mask
    discards and later blocks overwrite-before-attend.
    """
    K = spec_k
    assert K >= 1
    vocab = cfg.model.vocab_size
    scope_t = "verify-fwd" if tp == 1 else f"verify-fwd-tp{tp}"
    scope_d = "draft-fwd" if tp == 1 else f"draft-fwd-tp{tp}"

    def spec_tick(params, draft_params, pool_k, pool_v, draft_k, draft_v,
                  block_tables, positions, tokens, req_keys, steps,
                  temperature, top_k, top_p, k_eff):
        b = tokens.shape[0]
        rope_t = make_rope_cache(cfg)
        rope_d = make_rope_cache(draft_cfg)
        base = jax.vmap(jax.random.fold_in)(req_keys, steps)   # [b, 2]
        greedy_row = top_k == 1

        # ---- 1) draft k tokens (sequential s=1 draft forwards) ----
        # The scan runs K+1 steps, not K: step j < K samples draft token
        # d_{j+1}; the final step feeds d_K at position pos+K purely for
        # its K/V WRITE (its sample is discarded).  Without it, an
        # all-accepted-plus-bonus tick leaves a permanent hole in the
        # draft cache at d_K's position — the next tick starts past it,
        # the draft forever attends garbage there, and acceptance decays
        # (the bug showed up as ~78% acceptance on a draft the target
        # provably agrees with).
        def draft_step(carry, j):
            tok, dk, dv = carry
            pos_j = positions + j
            # rows past their own depth write to the NULL page: a clipped
            # write at the end of the sequence budget would otherwise land
            # inside the row's LAST real page and corrupt live K/V (the
            # engine only allocates pages up to pos + k_eff)
            bt_j = jnp.where((j <= k_eff)[:, None], block_tables, 0)
            with jax.named_scope(scope_d):
                logits, (dk, dv) = model_forward(
                    draft_cfg, draft_params, tok[:, None],
                    position_ids=pos_j[:, None], rope_cache=rope_d,
                    kv_caches=(dk, dv),
                    paged=PagedState(bt_j, pos_j))
            filt, greedy = filtered_logits_per_slot(
                logits[:, -1], top_k=top_k, top_p=top_p,
                temperature=temperature, vocab_size=vocab)
            keys_j = jax.vmap(lambda kb: jax.random.fold_in(
                jax.random.fold_in(kb, DRAFT_STREAM), j))(base)
            drawn = jax.vmap(lambda k_, row: jax.random.categorical(k_, row))(
                keys_j, filt)
            nxt = jnp.where(greedy_row, greedy, drawn).astype(jnp.int32)
            return (nxt, dk, dv), (nxt, filt)

        (_, draft_k, draft_v), (draft_seq, q_seq) = jax.lax.scan(
            draft_step, (tokens, draft_k, draft_v), jnp.arange(K + 1))
        draft_toks = jnp.moveaxis(draft_seq[:K], 0, 1)   # [b, K]
        q_filt = jnp.moveaxis(q_seq[:K], 0, 1)           # [b, K, v]

        # ---- 2) target verify: k+1 positions flattened into the batch ----
        S = K + 1
        block = jnp.concatenate([tokens[:, None], draft_toks], axis=1)
        flat_tok = block.reshape(b * S)
        flat_pos = (positions[:, None]
                    + jnp.arange(S)[None, :]).reshape(b * S)
        # same null-page routing as the draft loop: verify rows past a
        # slot's depth are discarded by the acceptance mask, and their
        # writes must never clip into a live page at the budget edge
        live = (jnp.arange(S)[None, :] <= k_eff[:, None]).reshape(b * S)
        flat_bt = jnp.where(live[:, None],
                            jnp.repeat(block_tables, S, axis=0), 0)
        with jax.named_scope(scope_t):
            logits, (pool_k, pool_v) = model_forward(
                cfg, params, flat_tok[:, None],
                position_ids=flat_pos[:, None], rope_cache=rope_t,
                kv_caches=(pool_k, pool_v),
                paged=PagedState(flat_bt, flat_pos))
        t_logits = logits[:, 0].reshape(b, S, -1)      # [b, K+1, v_padded]

        rep = lambda x: jnp.repeat(x, S, axis=0)  # noqa: E731
        t_filt_flat, t_greedy_flat = filtered_logits_per_slot(
            t_logits.reshape(b * S, -1), top_k=rep(top_k), top_p=rep(top_p),
            temperature=rep(temperature), vocab_size=vocab)
        t_filt = t_filt_flat.reshape(b, S, -1)
        t_greedy = t_greedy_flat.reshape(b, S)

        # ---- 3) lossless acceptance ----
        u = jax.vmap(lambda kb: jax.random.uniform(
            jax.random.fold_in(kb, ACCEPT_STREAM), (K,)))(base)
        emit_keys = jax.vmap(
            lambda kb: jax.random.fold_in(kb, EMIT_STREAM))(base)
        accepted, counts, emit = speculative_acceptance(
            draft_toks, q_filt, t_filt, t_greedy, greedy_row, k_eff,
            u, emit_keys)

        # reported per-token log-probs come from the RAW target logits,
        # exactly like the non-speculative tick's gather
        emit_logp = gen._gather_token_log_probs(t_logits, emit)

        new_pos = positions + counts
        new_steps = steps + counts
        new_tok = jnp.take_along_axis(
            emit, (counts - 1)[:, None], axis=1)[:, 0]
        return (pool_k, pool_v, draft_k, draft_v, emit, emit_logp,
                accepted, counts, new_pos, new_tok, new_steps)

    return spec_tick
