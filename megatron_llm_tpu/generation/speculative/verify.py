"""The lossless speculative acceptance rule and its key-stream discipline.

The fused draft-then-verify tick that used to live here moved to
``generation/ragged.py`` (ISSUE 11): verify's k+1 query positions are no
longer a special-cased flattened-batch program — they are ordinary
span-(k+1) entries in the engine's RAGGED tick batch, which also carries
the decode slots and the tick's prefill-chunk rows in the same single
launch.  ``make_ragged_tick_fn(cfg, draft_cfg, spec_k, prefill_rows=0)``
is byte-for-byte the program this module used to build.  The design that
program implements:

1. **Draft k tokens** — ``spec_k`` autoregressive s=1 forwards of the
   draft model (a ``lax.scan``), each writing draft K/V through the SAME
   block tables as the target and sampling with the slot's own filters
   (temperature / top-k / top-p), so the proposal distribution ``q`` is
   exactly the distribution a non-speculative draft-model decode would
   sample from.

2. **Verify k+1 positions in one target forward** — the key numerics
   design: the verify does NOT run the ``s = k+1`` prefill-attention
   path.  On the CPU fallback (and in general), a program with a
   different query-span shape reassociates reductions differently and
   drifts from the decode tick by a last-ulp — which would break the
   bitwise-losslessness contract.  Instead the k+1 query positions are
   **flattened into the batch dimension**: row ``(slot i, offset j)``
   feeds one token at position ``pos_i + j`` with slot ``i``'s block
   table — every op in the forward is then *structurally identical* to
   the non-speculative decode tick (an s=1 paged decode, just with a
   larger batch), and per-row bits are batch-size invariant.  The target
   logits at each verified position are therefore bitwise what the
   decode tick would have produced, and greedy speculative decode emits
   bitwise-identical tokens AND log-probs (tests/test_speculative.py).
   K/V writes land first (each row a distinct (page, offset) — rows of a
   slot write consecutive positions, different slots own disjoint
   writable pages), then every row attends causally ``<= its position``:
   write-then-attend, exactly the decode tick's order.

3. **Lossless acceptance** (:func:`speculative_acceptance`) — greedy
   rows accept a draft token iff it equals the target argmax, and emit
   the target argmax at the first mismatch (so the emitted stream IS the
   greedy target stream, whatever the draft proposed); sampled rows run
   standard residual rejection sampling: accept ``d_j`` with probability
   ``min(1, p(d_j)/q(d_j))``, on rejection emit from the residual
   ``max(p - q, 0)/Z``, and after k acceptances emit a bonus token from
   ``p`` — the emitted distribution provably equals the target model's
   (the classic speculative-sampling theorem; distribution-matched in
   tests/test_speculative.py).

Key discipline: every random draw derives from
``base = fold_in(request_key, steps)`` (``steps`` = tokens emitted so
far, strictly increasing, pinned across preemption) fanned out through
*disjoint* streams — ``fold_in(fold_in(base, DRAFT_STREAM), j)`` for the
j-th draft draw, one ``fold_in(base, ACCEPT_STREAM)`` key consumed for
the k acceptance uniforms, ``fold_in(base, EMIT_STREAM)`` consumed for
the single rejection/bonus draw.  No key is ever consumed twice
(graftcheck's rng-key-reuse rule analyzes this module; the
draft/verify-split reuse bug is pinned as a historical fixture in
tests/test_graftcheck.py).

Rejected-draft K/V (positions past the accepted frontier) is left in
place: it is only ever attended by a query at an equal-or-later position,
and every such query belongs to a later block that rewrites those
positions first — write-then-attend makes stale speculative K/V
unreachable by construction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from megatron_llm_tpu.generation.sampling import NEG_INF

# disjoint key streams fanned out of the per-(request, step) base key
DRAFT_STREAM = 1   # j-th draft sampling draw
ACCEPT_STREAM = 2  # the k acceptance uniforms (one key, one draw of [k])
EMIT_STREAM = 3    # the single rejection-residual / bonus draw


def speculative_acceptance(
    draft_toks: jax.Array,   # [b, K] int32 — proposed tokens d_1..d_K
    q_filt: jax.Array,       # [b, K, v] fp32 — draft filtered logits per draw
    t_filt: jax.Array,       # [b, K+1, v] fp32 — target filtered logits
    t_greedy: jax.Array,     # [b, K+1] int32 — target argmax per position
    greedy_row: jax.Array,   # [b] bool — slots decoding greedily (top_k == 1)
    k_eff: jax.Array,        # [b] int32 — per-slot speculation depth (0..K)
    u: jax.Array,            # [b, K] fp32 — acceptance uniforms in [0, 1)
    emit_keys: jax.Array,    # [b, 2] uint32 — one consumed key per row
):
    """The lossless acceptance rule; pure so tests can drive it with
    synthetic distributions.

    Returns ``(accepted, counts, emit)``: per-slot accepted draft count
    ``a`` in [0, k_eff], emitted token count ``m = a + 1``, and the
    emitted tokens ``emit[b, K+1]`` (positions >= m are padding).  Row
    semantics: ``emit[:, t] = d_{t+1}`` for ``t < a``; ``emit[:, a]`` is
    the correction/bonus token — greedy: the target argmax at that
    position; sampled: a residual-rejection draw (or a draw from the full
    target distribution when every valid draft was accepted).
    """
    b, K = draft_toks.shape
    p = jax.nn.softmax(t_filt, axis=-1)          # [b, K+1, v]
    q = jax.nn.softmax(q_filt, axis=-1)          # [b, K, v]
    p_d = jnp.take_along_axis(
        p[:, :K], draft_toks[..., None], axis=-1)[..., 0]   # [b, K]
    q_d = jnp.take_along_axis(
        q, draft_toks[..., None], axis=-1)[..., 0]          # [b, K]
    # u < min(1, p/q) without the division: q_d > 0 for any token the
    # draft actually sampled, and u*q < p is the same event
    acc_sampled = u * q_d < p_d
    acc_greedy = draft_toks == t_greedy[:, :K]
    acc = jnp.where(greedy_row[:, None], acc_greedy, acc_sampled)
    acc &= jnp.arange(K)[None, :] < k_eff[:, None]
    # longest accepted prefix (a rejection kills everything after it)
    accepted = jnp.sum(jnp.cumprod(acc.astype(jnp.int32), axis=1), axis=1)
    counts = accepted + 1

    # correction/bonus token at index `accepted`
    is_bonus = accepted >= k_eff   # every valid draft accepted
    p_at = jnp.take_along_axis(
        p, accepted[:, None, None], axis=1)[:, 0]           # [b, v]
    q_at = jnp.take_along_axis(
        q, jnp.minimum(accepted, K - 1)[:, None, None], axis=1)[:, 0]
    resid = jnp.maximum(
        p_at - jnp.where(is_bonus[:, None], 0.0, q_at), 0.0)
    z = resid.sum(axis=-1, keepdims=True)
    # rejection implies p < q somewhere, so z > 0 up to float rounding;
    # the guard keeps the all-rounded-to-zero corner a draw from p
    resid = jnp.where(z > 0, resid, p_at)
    resid_logits = jnp.where(resid > 0, jnp.log(resid), NEG_INF)
    drawn = jax.vmap(lambda k_, row: jax.random.categorical(k_, row))(
        emit_keys, resid_logits)
    greedy_emit = jnp.take_along_axis(
        t_greedy, accepted[:, None], axis=1)[:, 0]
    emit_at = jnp.where(greedy_row, greedy_emit, drawn).astype(jnp.int32)

    d_pad = jnp.concatenate(
        [draft_toks, jnp.zeros((b, 1), jnp.int32)], axis=1)  # [b, K+1]
    t_idx = jnp.arange(K + 1)[None, :]
    emit = jnp.where(t_idx < accepted[:, None], d_pad,
                     jnp.where(t_idx == accepted[:, None],
                               emit_at[:, None], 0)).astype(jnp.int32)
    return accepted, counts, emit
