"""The fused RAGGED engine tick: one compiled program per engine geometry
runs a whole tick's heterogeneous work — decode slots, speculative-verify
blocks, and prefill chunks — as a single flattened row batch (ISSUE 11,
PAPERS.md "Ragged Paged Attention").

The legacy split dispatch compiles up to three shapes of the same
computation per tick: the decode batch (``engine._tick``), one program per
prefill-chunk geometry (``engine._chunk_prefill``), and the speculative
verify.  Here the tick is ONE ragged batch of single-token rows; each row
carries its own data-carried ``(token, position, block-table row, kv
horizon)``:

* a **decode slot** contributes 1 row (span 1) at its own position;
* a **speculative-verify block** contributes ``spec_k + 1`` consecutive
  rows (span k+1) — the PR 9 flattened-batch construction, now just an
  ordinary span in the ragged batch rather than a special-cased program;
* a **prefill chunk** contributes ``rows`` consecutive rows (span =
  chunk), one per prompt position, writing K/V through the request's
  block table exactly like the chunked-prefill path.

Every op in the forward is then structurally an s=1 paged decode over a
larger batch, and per-row bits are BATCH-SIZE INVARIANT (the PR 9 key
numerics fact) — so decode rows are bitwise the legacy decode tick, verify
rows are bitwise the legacy flattened verify, and prefill rows are bitwise
the legacy chunk rows (masked attention is invariant to query-row
partitioning when kv horizons stay on the BUCKET(64) grid — the PR 5
contract).  That is what makes ragged output — tokens AND log-probs,
greedy AND sampled, cache on/off — bitwise-identical to the legacy split
path (tests/test_ragged_tick.py).

``prefill_rows`` is the COMPILED prefill-row capacity (a static, like
``max_slots``); which rows are live each tick is pure data.  With
``prefill_rows=0`` the builders reduce exactly to the legacy programs:
``make_ragged_tick_fn(cfg, None, 0, 0)`` is the decode tick and
``make_ragged_tick_fn(cfg, draft_cfg, k, 0)`` is byte-for-byte the
flattened spec verify this module absorbed from ``speculative/verify.py``.

Write-then-attend causality holds across the whole ragged batch: all R
rows' K/V lands first (each row a distinct (page, offset) — different
requests own disjoint writable pages, consecutive rows of one request
write consecutive positions), then every row attends causally ``<= its
position``.  A prefill row may therefore attend K/V written by an earlier
row of the SAME tick (its own chunk's prefix, or an earlier chunk of the
same request packed into the same tick) — the property that lets the
token-level prefill budget run multiple chunks per tick in one launch.

Key discipline is unchanged from speculative/verify.py: every random draw
derives from ``base = fold_in(request_key, steps)`` fanned out through
disjoint DRAFT/ACCEPT/EMIT streams; no key is ever consumed twice.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from megatron_llm_tpu.generation import generation as gen
from megatron_llm_tpu.generation.sampling import (
    filtered_logits_per_slot,
    sample_per_slot,
)
from megatron_llm_tpu.generation.speculative.verify import (
    ACCEPT_STREAM,
    DRAFT_STREAM,
    EMIT_STREAM,
    speculative_acceptance,
)
from megatron_llm_tpu.models.language_model import (
    make_rope_cache,
    model_forward,
)
from megatron_llm_tpu.ops.paged_attention import PagedState


def row_horizons(positions: jax.Array) -> jax.Array:
    """Per-row kv horizon for LIVE rows: ``position + 1`` bucketed up to
    the BUCKET(64) grid — the same bucketing the chunked-prefill path
    applies to its attended page horizon, kept here so ragged bits depend
    only on (tokens, positions), never on tick composition."""
    b = gen.BUCKET
    return ((positions // b) + 1) * b


def make_ragged_tick_fn(cfg, draft_cfg, spec_k: int, prefill_rows: int,
                        *, tp: int = 1, mesh=None):
    """Build the fused ragged tick the engine compiles once per geometry.

    ``mesh`` (with ``--tp_overlap ring``) activates the chunked
    collective-matmul interception (parallel/overlap.py) for every
    forward in the tick — target, draft and prefill rows alike; the
    engine keys its compiled-program cache on the effective mode, so
    overlap and non-overlap engines never share executables.

    Returned signature, ``spec_k >= 1`` (draft model present)::

        (params, draft_params, pool_k, pool_v, draft_k, draft_v,
         block_tables, positions, tokens, req_keys, steps,
         temperature, top_k, top_p, k_eff
         [, pre_tok, pre_pos, pre_tables, pre_index, pre_hor])
        -> (pool_k, pool_v, draft_k, draft_v,
            emit [b, K+1], emit_logp [b, K+1], accepted [b], counts [b],
            new_pos, new_tok, new_steps)

    and ``spec_k == 0`` (no draft args, plain per-slot sampling)::

        (params, pool_k, pool_v, block_tables, positions, tokens,
         req_keys, steps, temperature, top_k, top_p
         [, pre_tok, pre_pos, pre_tables, pre_index, pre_hor])
        -> (pool_k, pool_v, next_tok, logp, new_pos, new_steps)

    The ``pre_*`` operands exist iff ``prefill_rows > 0``: ``pre_tok`` /
    ``pre_pos`` / ``pre_hor`` are ``[prefill_rows]``; block tables come
    COMPRESSED — ``pre_tables`` is ``[T_pre, max_pages_per_seq]`` (one
    row per packed prefilling request) and ``pre_index`` maps each
    prefill row to its request's table (``-1`` = dead row).  Inside, the
    program assembles the tick's unique-table set ``[null] + slot tables
    + pre_tables`` and a per-row index — rows of one span share one
    table, so the jnp fallback gathers each table's pages exactly once
    (ops/paged_attention.paged_attention_ragged) and the Pallas kernel
    resolves ``tables[index[row], page]`` in its scalar-prefetch index
    map.  Dead prefill rows carry horizon 0, the null table and position
    0 — their writes land in garbage that is never attended, exactly
    like idle decode slots.  All of it is traced data: ANY tick
    composition — 6 decoding slots + 1 prefilling chunk + 1 verify
    block, or all-decode, or all-prefill — re-dispatches the same
    executable.
    """
    from megatron_llm_tpu.parallel import overlap as tp_overlap_mod
    from megatron_llm_tpu.parallel import pp_serve as pp_serve_mod

    ovl = tp_overlap_mod.overlap_params(cfg, mesh)
    ppc = pp_serve_mod.serve_params(cfg, mesh)
    K = spec_k
    vocab = cfg.model.vocab_size
    scope_t = ("ragged-fwd" if tp == 1 else f"ragged-fwd-tp{tp}") \
        if prefill_rows else \
        (("verify-fwd" if tp == 1 else f"verify-fwd-tp{tp}") if K
         else ("decode-fwd" if tp == 1 else f"decode-fwd-tp{tp}"))
    scope_d = "draft-fwd" if tp == 1 else f"draft-fwd-tp{tp}"

    def target_forward(params, pool_k, pool_v, tbl, idx, pos, tok, hor):
        """ONE target forward over the full ragged batch — the single
        attention launch of the tick.  ``tbl`` is the tick's compressed
        unique-table set, ``idx`` each row's table."""
        with jax.named_scope(scope_t):
            logits, (pool_k, pool_v) = model_forward(
                cfg, params, tok[:, None],
                position_ids=pos[:, None],
                rope_cache=make_rope_cache(cfg),
                kv_caches=(pool_k, pool_v),
                paged=PagedState(tbl, pos, hor, idx),
            )
        return logits[:, 0], pool_k, pool_v

    def spec_tick(params, draft_params, pool_k, pool_v, draft_k, draft_v,
                  block_tables, positions, tokens, req_keys, steps,
                  temperature, top_k, top_p, k_eff,
                  pre_tok=None, pre_pos=None, pre_tables=None,
                  pre_index=None, pre_hor=None):
        b = tokens.shape[0]
        W = block_tables.shape[1]
        null_tbl = jnp.zeros((1, W), block_tables.dtype)
        rope_d = make_rope_cache(draft_cfg)
        base = jax.vmap(jax.random.fold_in)(req_keys, steps)   # [b, 2]
        greedy_row = top_k == 1

        # ---- draft prefill rows (speculating engines keep BOTH caches
        # filled for every prefilled page, so trie-matched pages carry
        # valid draft K/V — the chunk_spec contract, fused in-program) ----
        if prefill_rows:
            d_idx = jnp.where(pre_index >= 0, 1 + pre_index, 0)
            with jax.named_scope(scope_d):
                _, (draft_k, draft_v) = model_forward(
                    draft_cfg, draft_params, pre_tok[:, None],
                    position_ids=pre_pos[:, None], rope_cache=rope_d,
                    kv_caches=(draft_k, draft_v),
                    paged=PagedState(
                        jnp.concatenate([null_tbl, pre_tables]),
                        pre_pos, pre_hor, d_idx))

        # ---- 1) draft k tokens (sequential s=1 draft forwards) ----
        # The scan runs K+1 steps, not K: step j < K samples draft token
        # d_{j+1}; the final step feeds d_K at position pos+K purely for
        # its K/V WRITE (its sample is discarded) — without it an
        # all-accepted-plus-bonus tick leaves a permanent hole in the
        # draft cache at d_K's position (the PR 9 acceptance-decay bug).
        def draft_step(carry, j):
            tok, dk, dv = carry
            pos_j = positions + j
            # rows past their own depth write to the NULL page: a clipped
            # write at the end of the sequence budget would otherwise land
            # inside the row's LAST real page and corrupt live KV
            bt_j = jnp.where((j <= k_eff)[:, None], block_tables, 0)
            with jax.named_scope(scope_d):
                logits, (dk, dv) = model_forward(
                    draft_cfg, draft_params, tok[:, None],
                    position_ids=pos_j[:, None], rope_cache=rope_d,
                    kv_caches=(dk, dv),
                    paged=PagedState(bt_j, pos_j))
            filt, greedy = filtered_logits_per_slot(
                logits[:, -1], top_k=top_k, top_p=top_p,
                temperature=temperature, vocab_size=vocab)
            keys_j = jax.vmap(lambda kb: jax.random.fold_in(
                jax.random.fold_in(kb, DRAFT_STREAM), j))(base)
            drawn = jax.vmap(lambda k_, row: jax.random.categorical(k_, row))(
                keys_j, filt)
            nxt = jnp.where(greedy_row, greedy, drawn).astype(jnp.int32)
            return (nxt, dk, dv), (nxt, filt)

        (_, draft_k, draft_v), (draft_seq, q_seq) = jax.lax.scan(
            draft_step, (tokens, draft_k, draft_v), jnp.arange(K + 1))
        draft_toks = jnp.moveaxis(draft_seq[:K], 0, 1)   # [b, K]
        q_filt = jnp.moveaxis(q_seq[:K], 0, 1)           # [b, K, v]

        # ---- 2) target verify + prefill: ONE ragged forward ----
        # verify blocks are ordinary span-(K+1) entries: row (slot i,
        # offset j) feeds one token at position pos_i + j with slot i's
        # block table; prefill rows append after them.
        S = K + 1
        block = jnp.concatenate([tokens[:, None], draft_toks], axis=1)
        flat_tok = block.reshape(b * S)
        flat_pos = (positions[:, None]
                    + jnp.arange(S)[None, :]).reshape(b * S)
        # compressed tables: [null] + the b slot tables (+ the packed
        # prefilling requests' tables).  Null-table routing replaces the
        # old per-row bt masking: verify rows past a slot's depth are
        # discarded by the acceptance mask, and their writes must never
        # clip into a live page at the budget edge
        live = (jnp.arange(S)[None, :] <= k_eff[:, None]).reshape(b * S)
        slot_ids = jnp.repeat(jnp.arange(b, dtype=jnp.int32), S)
        flat_idx = jnp.where(live, 1 + slot_ids, 0)
        flat_hor = row_horizons(flat_pos)
        if prefill_rows:
            all_tok = jnp.concatenate([flat_tok, pre_tok])
            all_pos = jnp.concatenate([flat_pos, pre_pos])
            all_idx = jnp.concatenate(
                [flat_idx,
                 jnp.where(pre_index >= 0, 1 + b + pre_index, 0)])
            all_tbl = jnp.concatenate([null_tbl, block_tables, pre_tables])
            all_hor = jnp.concatenate([flat_hor, pre_hor])
        else:
            all_tok, all_pos, all_idx, all_hor = (
                flat_tok, flat_pos, flat_idx, flat_hor)
            all_tbl = jnp.concatenate([null_tbl, block_tables])
        out, pool_k, pool_v = target_forward(
            params, pool_k, pool_v, all_tbl, all_idx, all_pos, all_tok,
            all_hor)
        t_logits = out[: b * S].reshape(b, S, -1)      # [b, K+1, v_padded]

        rep = lambda x: jnp.repeat(x, S, axis=0)  # noqa: E731
        t_filt_flat, t_greedy_flat = filtered_logits_per_slot(
            t_logits.reshape(b * S, -1), top_k=rep(top_k), top_p=rep(top_p),
            temperature=rep(temperature), vocab_size=vocab)
        t_filt = t_filt_flat.reshape(b, S, -1)
        t_greedy = t_greedy_flat.reshape(b, S)

        # ---- 3) lossless acceptance ----
        u = jax.vmap(lambda kb: jax.random.uniform(
            jax.random.fold_in(kb, ACCEPT_STREAM), (K,)))(base)
        emit_keys = jax.vmap(
            lambda kb: jax.random.fold_in(kb, EMIT_STREAM))(base)
        accepted, counts, emit = speculative_acceptance(
            draft_toks, q_filt, t_filt, t_greedy, greedy_row, k_eff,
            u, emit_keys)

        # reported per-token log-probs come from the RAW target logits,
        # exactly like the non-speculative tick's gather
        emit_logp = gen._gather_token_log_probs(t_logits, emit)

        new_pos = positions + counts
        new_steps = steps + counts
        new_tok = jnp.take_along_axis(
            emit, (counts - 1)[:, None], axis=1)[:, 0]
        return (pool_k, pool_v, draft_k, draft_v, emit, emit_logp,
                accepted, counts, new_pos, new_tok, new_steps)

    def tick(params, pool_k, pool_v, block_tables, positions, tokens,
             req_keys, steps, temperature, top_k, top_p,
             pre_tok=None, pre_pos=None, pre_tables=None,
             pre_index=None, pre_hor=None):
        b = tokens.shape[0]
        W = block_tables.shape[1]
        null_tbl = jnp.zeros((1, W), block_tables.dtype)
        idx = 1 + jnp.arange(b, dtype=jnp.int32)
        hor = row_horizons(positions)
        if prefill_rows:
            all_tok = jnp.concatenate([tokens, pre_tok])
            all_pos = jnp.concatenate([positions, pre_pos])
            all_idx = jnp.concatenate(
                [idx, jnp.where(pre_index >= 0, 1 + b + pre_index, 0)])
            all_tbl = jnp.concatenate([null_tbl, block_tables, pre_tables])
            all_hor = jnp.concatenate([hor, pre_hor])
        else:
            all_tok, all_pos, all_idx, all_hor = (
                tokens, positions, idx, hor)
            all_tbl = jnp.concatenate([null_tbl, block_tables])
        out, pool_k, pool_v = target_forward(
            params, pool_k, pool_v, all_tbl, all_idx, all_pos, all_tok,
            all_hor)
        last = out[:b]
        keys = jax.vmap(jax.random.fold_in)(req_keys, steps)
        next_tok = sample_per_slot(
            keys, last, top_k=top_k, top_p=top_p,
            temperature=temperature, vocab_size=cfg.model.vocab_size)
        logp = gen._gather_token_log_probs(last, next_tok)
        return (pool_k, pool_v, next_tok, logp,
                positions + 1, steps + 1)

    base_fn = spec_tick if K else tick
    if ovl is None and ppc is None:
        return base_fn

    def overlapped(*args, **kw):
        # trace-time contexts: every model_forward in the tick — target,
        # draft scan, prefill rows — routes its row-parallel projections
        # through the ring and/or its layer stack through the pp stage
        # pipeline while this builder's closure is being traced
        with tp_overlap_mod.activate(ovl), pp_serve_mod.activate(ppc):
            return base_fn(*args, **kw)

    return overlapped


def make_chained_tick_fn(cfg, chain: int, *, tp: int = 1, mesh=None):
    """Build the CHAINED steady-state decode tick (ISSUE 17): ``chain``
    consecutive non-speculative decode ticks as ONE compiled program — a
    ``lax.scan`` over the spec-0 ragged tick body, so position advance,
    sampling, stop-token detection and the remaining-token budget all
    run device-to-device and the host is consulted once per *chain*
    instead of once per tick (``--tick_pipeline_depth``).

    Per-tick bits are the depth-0 tick's bits exactly: each scan step is
    the same forward/sample/gather over the same ``[b]`` row batch, keys
    derive from the same ``fold_in(req_key, step)`` stream with ``steps``
    advancing in the carry, and per-row output is batch-composition
    invariant (the PR 9/PR 11 numerics fact) — so masking a finished
    row's table never changes a live row's tokens or log-probs.

    In-program stop/freeze discipline (mirrors the host's
    ``engine._stopped_by_token`` + length limits bit for bit):

    * ``stop_modes[i]``: 0 = stop on ``term_ids[i]`` (−1 = never), 1 =
      stop on EOL/double-EOL, 2 = stop on double-EOL (consecutive-EOL
      detection uses the carried input token as ``prev`` — identical to
      the host's ``generated[-2]`` at apply time);
    * ``remaining[i]`` is the row's exact token budget (``max_new`` and
      ``max_seq`` folded together by the host at the chain boundary);
      it decrements per emitted token and freezes the row at 0 — a row
      can therefore NEVER advance past its pre-granted final page;
    * a ``done`` row is frozen: its position/token/step/budget stop
      advancing and its reads AND writes route to the null table (index
      0), so an in-flight chain cannot touch pages the host has since
      released — sampled garbage for frozen rows is discarded at the
      host's apply boundary.

    Signature::

        (params, pool_k, pool_v, block_tables, positions, tokens,
         req_keys, steps, temperature, top_k, top_p,
         term_ids, stop_modes, done, remaining)
        -> (pool_k, pool_v, toks [chain, b], logps [chain, b],
            new_pos, new_tok, new_steps, new_done, new_remaining)

    The final carry is the NEXT launch's input — consecutive chains hand
    slot state device-to-device; the host re-uploads only at boundaries
    (admission/preemption/prefill) and when pre-granting pages changes
    the block-table operand.
    """
    from megatron_llm_tpu.parallel import overlap as tp_overlap_mod
    from megatron_llm_tpu.parallel import pp_serve as pp_serve_mod

    ovl = tp_overlap_mod.overlap_params(cfg, mesh)
    ppc = pp_serve_mod.serve_params(cfg, mesh)
    vocab = cfg.model.vocab_size
    scope_t = "decode-fwd" if tp == 1 else f"decode-fwd-tp{tp}"

    def target_forward(params, pool_k, pool_v, tbl, idx, pos, tok, hor):
        with jax.named_scope(scope_t):
            logits, (pool_k, pool_v) = model_forward(
                cfg, params, tok[:, None],
                position_ids=pos[:, None],
                rope_cache=make_rope_cache(cfg),
                kv_caches=(pool_k, pool_v),
                paged=PagedState(tbl, pos, hor, idx),
            )
        return logits[:, 0], pool_k, pool_v

    def chained(params, pool_k, pool_v, block_tables, positions, tokens,
                req_keys, steps, temperature, top_k, top_p,
                term_ids, stop_modes, done, remaining):
        b = tokens.shape[0]
        W = block_tables.shape[1]
        null_tbl = jnp.zeros((1, W), block_tables.dtype)
        all_tbl = jnp.concatenate([null_tbl, block_tables])
        live_idx = 1 + jnp.arange(b, dtype=jnp.int32)

        def body(carry, _):
            pool_k, pool_v, pos, tok, stp, dn, rem = carry
            # frozen rows null-route (reads garbage, writes page 0) —
            # exactly how dead prefill rows are already handled
            idx = jnp.where(dn, 0, live_idx)
            hor = row_horizons(pos)
            out, pk, pv = target_forward(
                params, pool_k, pool_v, all_tbl, idx, pos, tok, hor)
            keys = jax.vmap(jax.random.fold_in)(req_keys, stp)
            next_tok = sample_per_slot(
                keys, out, top_k=top_k, top_p=top_p,
                temperature=temperature, vocab_size=vocab)
            logp = gen._gather_token_log_probs(out, next_tok)
            # stop detection AFTER the emit, like the host's apply; the
            # carried input token is the host's generated[-2] (or the
            # last prompt token on the first generated position)
            is_eol = next_tok == gen.GPT2_EOL
            is_deol = next_tok == gen.GPT2_DOUBLE_EOL
            stop = jnp.where(
                stop_modes == 2,
                is_deol | (is_eol & (tok == gen.GPT2_EOL)),
                jnp.where(stop_modes == 1, is_eol | is_deol,
                          (term_ids >= 0) & (next_tok == term_ids)))
            rem2 = jnp.where(dn, rem, rem - 1)
            dn2 = dn | stop | (rem2 <= 0)
            # freeze: done rows stop advancing (their re-draws discard)
            pos2 = jnp.where(dn, pos, pos + 1)
            tok2 = jnp.where(dn, tok, next_tok)
            stp2 = jnp.where(dn, stp, stp + 1)
            return (pk, pv, pos2, tok2, stp2, dn2, rem2), (next_tok, logp)

        carry0 = (pool_k, pool_v, positions, tokens, steps, done,
                  remaining)
        carry, (toks, logps) = jax.lax.scan(
            body, carry0, None, length=chain)
        (pool_k, pool_v, new_pos, new_tok, new_steps, new_done,
         new_rem) = carry
        return (pool_k, pool_v, toks, logps, new_pos, new_tok,
                new_steps, new_done, new_rem)

    if ovl is None and ppc is None:
        return chained

    def overlapped_chain(*args, **kw):
        with tp_overlap_mod.activate(ovl), pp_serve_mod.activate(ppc):
            return chained(*args, **kw)

    return overlapped_chain
