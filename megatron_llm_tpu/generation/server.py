"""REST text-generation server — megatron/text_generation_server.py analog.

Same wire contract (PUT /api, identical request fields/validation messages,
``{"text", "segments", "logprobs"}`` / ``{"text", "segments", "scores"}``
responses, GET / serves the static UI).  Differences by design:

* stdlib ``http.server`` (ThreadingHTTPServer) instead of Flask (not baked
  into the TPU image).
* No ``send_do_generate``/``send_do_beam_search`` rank broadcasts
  (text_generation_server.py:21-27): SPMD has one controller process, so
  the server just calls the engine.
* Errors are structured JSON (``{"error": msg}``) with proper status codes
  — a malformed payload can never surface as a bare-traceback 500.
* With the legacy dense engine the request lock serializes generations
  (programs are single-stream on the chip).  With the continuous-batching
  engine (generation/engine.py) the lock is NOT taken on the generate
  path: each handler thread enqueues its request and blocks on its future,
  so concurrent HTTP requests share decode ticks — the whole point of the
  engine.  Beam search stays behind the lock on either engine.
"""

from __future__ import annotations

import json
import socket
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Optional
from urllib.parse import parse_qs

from megatron_llm_tpu.generation.engine import EngineOverloaded
from megatron_llm_tpu.generation.scheduling import RequestShed
from megatron_llm_tpu.observability import trace as obs_trace
from megatron_llm_tpu.serving.streaming import SSE_CONTENT_TYPE, sse_encode

_STATIC_DIR = Path(__file__).parent / "static"


def _validate(payload: dict):
    """Field validation with the reference's messages
    (text_generation_server.py:31-178). Returns (params dict, error str)."""
    if "prompts" not in payload:
        return None, "prompts argument required"
    if "max_len" in payload:
        return None, "max_len is no longer used.  Replace with tokens_to_generate"
    if "sentences" in payload:
        return None, "sentences is no longer used.  Replace with prompts"
    prompts = payload["prompts"]
    if not isinstance(prompts, list):
        return None, "prompts is not a list of strings"
    if len(prompts) == 0:
        return None, "prompts is empty"
    if len(prompts) > 128:
        return None, "Maximum number of prompts is 128"

    p = {"prompts": prompts}

    tokens_to_generate = payload.get("tokens_to_generate", 64)
    if not isinstance(tokens_to_generate, int) or tokens_to_generate < 0:
        return None, "tokens_to_generate must be an integer greater than or equal to 0"
    p["tokens_to_generate"] = tokens_to_generate

    logprobs = payload.get("logprobs", False)
    if not isinstance(logprobs, bool):
        return None, "logprobs must be a boolean value"
    if tokens_to_generate == 0 and not logprobs:
        return None, "tokens_to_generate=0 implies logprobs should be True"
    p["logprobs"] = logprobs

    temperature = payload.get("temperature", 1.0)
    if not isinstance(temperature, (int, float)) or not 0.0 < temperature <= 100.0:
        return None, "temperature must be a positive number less than or equal to 100.0"
    p["temperature"] = float(temperature)

    top_k = payload.get("top_k", 0)
    if not isinstance(top_k, int) or not 0 <= top_k <= 1000:
        return None, ("top_k must be equal to or greater than 0 and less "
                      "than or equal to 1000")
    p["top_k"] = top_k

    top_p = payload.get("top_p", 0.0)
    if isinstance(top_p, int):
        top_p = float(top_p)
    if not isinstance(top_p, float) or not 0 <= top_p <= 1.0:
        return None, "top_p must be less than or equal to 1.0"
    if top_p > 0.0 and top_k > 0:
        return None, "cannot set both top-k and top-p samplings."
    p["top_p"] = top_p

    add_BOS = payload.get("add_BOS", False)
    if not isinstance(add_BOS, bool):
        return None, "add_BOS must be a boolean value"
    if any(len(prompt) == 0 for prompt in prompts) and not add_BOS:
        return None, "Empty prompts require add_BOS=true"
    p["add_BOS"] = add_BOS

    for flag in ("stop_on_double_eol", "stop_on_eol", "no_log"):
        val = payload.get(flag, False)
        if not isinstance(val, bool):
            return None, f"{flag} must be a boolean value"
        p[flag] = val

    random_seed = payload.get("random_seed", -1)
    if not isinstance(random_seed, int):
        return None, "random_seed must be integer"
    if random_seed < -1:
        return None, "random_seed must be a positive integer"
    p["random_seed"] = random_seed

    beam_width = payload.get("beam_width")
    if beam_width is not None:
        if not isinstance(beam_width, int) or beam_width < 1:
            return None, "beam_width must be an integer > 1"
        if len(prompts) > 1:
            return None, "When doing beam_search, batch size must be 1"
    p["beam_width"] = beam_width

    stop_token = payload.get("stop_token", 50256)
    if not isinstance(stop_token, int):
        return None, "stop_token must be an integer"
    p["stop_token"] = stop_token

    length_penalty = payload.get("length_penalty", 1.0)
    if isinstance(length_penalty, int):
        length_penalty = float(length_penalty)
    if not isinstance(length_penalty, float):
        return None, "length_penalty must be a float"
    p["length_penalty"] = length_penalty

    # scheduling control plane (generation/scheduling/): priority class
    # for --sched_policy priority, soft deadlines for --sched_policy slo
    priority = payload.get("priority", 1)
    if not isinstance(priority, int) or not 0 <= priority <= 9:
        return None, "priority must be an integer between 0 and 9"
    p["priority"] = priority
    for field in ("ttft_deadline_ms", "tpot_deadline_ms"):
        val = payload.get(field)
        if val is not None and (not isinstance(val, (int, float))
                                or isinstance(val, bool) or val <= 0):
            return None, f"{field} must be a positive number of milliseconds"
        p[field] = None if val is None else float(val)

    # token streaming (ISSUE 18, serving/streaming/): SSE response
    # instead of a buffered body; transport-only, so the sampled tokens
    # are identical either way
    stream = payload.get("stream", False)
    if not isinstance(stream, bool):
        return None, "stream must be a boolean value"
    p["stream"] = stream
    return p, None


def _validate_stream(params: dict):
    """The extra constraints a ``"stream": true`` request must meet —
    streaming multiplexes ONE generation onto the response socket."""
    if len(params["prompts"]) != 1:
        return "streaming requires exactly one prompt"
    if params["beam_width"] is not None:
        return "beam search cannot stream"
    if params["tokens_to_generate"] == 0:
        return "streaming requires tokens_to_generate >= 1"
    return None


class _NullLock:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


class MegatronServer:
    """text_generation_server.MegatronServer analog (:234-241)."""

    def __init__(self, engine, *, register_url: Optional[str] = None,
                 register_interval_s: float = 2.0,
                 advertise_url: Optional[str] = None,
                 role: str = "unified"):
        # the lock-relevant type (the legacy InferenceEngine has no
        # locks): the annotation below lets graftcheck's lock-order
        # graph resolve `with eng._lock:` in health()/metrics_text()
        self.engine = engine  # instance of ContinuousBatchingEngine
        self.lock = threading.Lock()
        # continuous-batching engines serialize device access internally
        # (enqueue + future); a server-level lock would undo the batching
        self.batching = hasattr(engine, "submit")
        self._httpd: Optional[ThreadingHTTPServer] = None
        # replica identity for the cross-replica router (serving/router/):
        # replica_id survives for the process lifetime, so a router sees a
        # restart as an id change; seq orders /health payloads so a stale
        # poll can never overwrite a fresher view; uptime_s is the
        # restart-detection cross-check (it must only move forward for the
        # same replica_id).  Schema: docs/guide/serving.md "/health payload".
        self.replica_id = uuid.uuid4().hex
        self._t_start = time.monotonic()
        self._health_seq = 0  # guarded by _seq_lock
        self._seq_lock = threading.Lock()
        # elastic discovery (ISSUE 18): with --register_url the replica
        # POSTs /admin/register heartbeats to the router, so the fleet
        # learns about it (and a restart on a new port) with no static
        # config; the router's breaker expires it when it goes silent
        self.register_url = register_url
        self.register_interval_s = register_interval_s
        self.advertise_url = advertise_url
        self._register_stop = threading.Event()
        self._register_thread: Optional[threading.Thread] = None
        # disaggregated prefill/decode (ISSUE 19, serving/handoff/): the
        # advertised serving role.  Roles steer the router's ``disagg``
        # policy; /api stays fully functional on every role (a role-less
        # or mixed fleet degrades to unified serving), but a prefill-role
        # replica refuses /admin/kv_push — it is a KV sender, not a sink.
        if role not in ("unified", "prefill", "decode"):
            raise ValueError(
                f"role must be 'unified', 'prefill' or 'decode', got {role!r}")
        self.role = role

    def handle_request(self, payload, trace_id: str = ""):
        """Core PUT /api logic; returns (status_code, response dict).

        ``trace_id`` is the request's ``X-MLT-Trace-Id`` (minted by the
        HTTP handler when the caller/router sent none); it threads into
        the engine's flight record and spans, and 200 responses from
        batching engines carry a ``timing`` block derived from the
        flight record — the server-side first-token and latency
        decomposition the router's honest TTFT metric reads."""
        if not isinstance(payload, dict):
            return 400, {"error": "request body must be a JSON object"}
        if payload.get("handoff_to") is not None:
            # disaggregated prefill (ISSUE 19): prefill + export + push
            # instead of decoding; returns a migration receipt
            return self._prefill_handoff(payload, trace_id=trace_id)
        params, err = _validate(payload)
        if err:
            return 400, {"error": err}
        beam = params["beam_width"] is not None
        lock = self.lock if (beam or not self.batching) else _NullLock()
        with lock:
            try:
                if beam:
                    texts, segments, scores = self.engine.beam_search_and_post_process(
                        params["prompts"],
                        tokens_to_generate=params["tokens_to_generate"],
                        beam_size=params["beam_width"],
                        add_BOS=params["add_BOS"],
                        stop_token=params["stop_token"],
                        num_return_gen=params["beam_width"],
                        length_penalty=params["length_penalty"],
                    )
                    return 200, {"text": texts, "segments": segments,
                                 "scores": scores}
                kw = {}
                if self.batching:
                    # scheduling fields only exist on the batching engine
                    kw = dict(priority=params["priority"],
                              ttft_deadline_ms=params["ttft_deadline_ms"],
                              tpot_deadline_ms=params["tpot_deadline_ms"],
                              trace_id=trace_id)
                texts, segments, logprobs, _ = self.engine.generate_and_post_process(
                    params["prompts"],
                    tokens_to_generate=params["tokens_to_generate"],
                    return_output_log_probs=params["logprobs"],
                    top_k_sampling=params["top_k"],
                    top_p_sampling=params["top_p"],
                    temperature=params["temperature"],
                    add_BOS=params["add_BOS"],
                    stop_on_double_eol=params["stop_on_double_eol"],
                    stop_on_eol=params["stop_on_eol"],
                    random_seed=params["random_seed"],
                    **kw,
                )
                body = {"text": texts, "segments": segments,
                        "logprobs": logprobs}
                if self.batching and trace_id:
                    timing = self.request_timing(trace_id)
                    if timing is not None:
                        body["timing"] = timing
                return 200, body
            except EngineOverloaded as eo:
                # backpressure instead of unbounded queueing: structured
                # 503 + machine-readable retry hint (the HTTP handler turns
                # retry_after into a Retry-After header).  retry_after is
                # the engine's EMA drain estimate for the current queue
                # depth, and info carries the queue snapshot behind it.
                return 503, {"error": str(eo),
                             "retry_after": getattr(eo, "retry_after", 1.0),
                             **getattr(eo, "info", {})}
            except RequestShed as rs:
                # the scheduler refused the request (unmeetable deadline /
                # load shed) — retryable load feedback, not a client error
                return 503, {"error": str(rs), "shed": True,
                             "retry_after": getattr(rs, "retry_after", 1.0)}
            except (ValueError, AssertionError) as ve:
                return 400, {"error": str(ve.args[0] if ve.args else ve)}
            except Exception as e:  # engine failure must still answer the client
                import traceback

                traceback.print_exc()
                return 500, {"error": f"internal error: {type(e).__name__}: {e}"}

    def _prefill_handoff(self, payload: dict, trace_id: str = ""):
        """Serve a ``"handoff_to": url`` request (ISSUE 19): run chunked
        prefill locally, export the prompt's full KV pages and push them
        to the decode replica at ``url``; the 200 answer is a migration
        receipt, not a generation.  The router sends these for long
        prompts (``disagg`` policy) and then forwards the original
        request to the decode replica, which finds the pushed pages in
        its prefix cache.  A failed push is a 502 so the router can fall
        back to unified serving — the request is never half-served."""
        from megatron_llm_tpu.serving.handoff.transfer import (
            KVPushError, push_pages)

        target = payload.get("handoff_to")
        if not isinstance(target, str) or not target.strip():
            return 400, {"error": "handoff_to must be a replica base URL"}
        if not self.batching or not hasattr(self.engine, "prefill_and_export"):
            return 400, {"error":
                         "handoff requires the continuous-batching engine"}
        params, err = _validate(
            {k: v for k, v in payload.items() if k != "handoff_to"})
        if err:
            return 400, {"error": err}
        if len(params["prompts"]) != 1:
            return 400, {"error": "handoff requires exactly one prompt"}
        if params["beam_width"] is not None:
            return 400, {"error": "beam search cannot hand off"}
        if params["logprobs"]:
            # logprobs requests bypass the prefix trie on the decode
            # side, so pushed pages could never be used — refuse rather
            # than do the work for nothing
            return 400, {"error": "handoff cannot serve logprobs requests"}
        try:
            blob, info = self.engine.prefill_and_export(
                params["prompts"][0], add_BOS=params["add_BOS"],
                trace_id=trace_id)
        except EngineOverloaded as eo:
            return 503, {"error": str(eo),
                         "retry_after": getattr(eo, "retry_after", 1.0),
                         **getattr(eo, "info", {})}
        except RequestShed as rs:
            return 503, {"error": str(rs), "shed": True,
                         "retry_after": getattr(rs, "retry_after", 1.0)}
        except (ValueError, AssertionError) as ve:
            return 400, {"error": str(ve.args[0] if ve.args else ve)}
        except Exception as e:
            import traceback

            traceback.print_exc()
            return 500, {"error": f"internal error: {type(e).__name__}: {e}"}
        receipt = {"target": target, "pages": info["pages"],
                   "bytes": info["bytes"], "tokens": info["tokens"],
                   "hit_tokens": info["hit_tokens"],
                   "replica_id": self.replica_id, "pushed": False}
        if info["pages"] == 0:
            # prompt shorter than one full page: nothing worth shipping
            return 200, {"handoff": receipt}
        try:
            receipt["receipt"] = push_pages(target, blob, trace_id=trace_id)
        except KVPushError as ke:
            body = {"error": str(ke), "handoff_failed": True}
            if ke.retry_after is not None:
                body["retry_after"] = ke.retry_after
            return 502, body
        receipt["pushed"] = True
        return 200, {"handoff": receipt}

    def kv_push(self, blob: bytes, trace_id: str = ""):
        """Core ``POST /admin/kv_push`` logic: install a handoff blob
        into this replica's pool/prefix cache (engine.import_kv) and
        answer with the import receipt.  Pool pressure is a structured
        503 + retry hint (the sender degrades to unified serving), a
        malformed or incompatible blob is a 400."""
        if self.role == "prefill":
            return 400, {"error":
                         "prefill-role replica does not accept KV pushes"}
        if not self.batching or not hasattr(self.engine, "import_kv"):
            return 400, {"error":
                         "kv_push requires the continuous-batching engine"}
        if not blob:
            return 400, {"error": "empty kv_push body"}
        try:
            receipt = self.engine.import_kv(blob, trace_id=trace_id)
        except EngineOverloaded as eo:
            return 503, {"error": str(eo),
                         "retry_after": getattr(eo, "retry_after", 1.0),
                         **getattr(eo, "info", {})}
        except ValueError as ve:
            return 400, {"error": str(ve.args[0] if ve.args else ve)}
        except Exception as e:
            import traceback

            traceback.print_exc()
            return 500, {"error": f"internal error: {type(e).__name__}: {e}"}
        receipt["replica_id"] = self.replica_id
        return 200, receipt

    def stream_response(self, handler, payload: dict, trace_id: str = ""):
        """Serve one ``"stream": true`` request as SSE on ``handler``'s
        socket (serving/streaming/, docs/guide/serving.md "Streaming").

        Returns None when the stream was served (headers + body written
        here), or ``(status, body)`` for a pre-stream failure — nothing
        has touched the socket yet, so the caller answers with the
        ordinary buffered path (same status codes, Retry-After, headers
        as a non-streamed request).

        The response headers (trace id + ``X-MLT-TTFT-S``) are sent at
        the moment the FIRST token event arrives — the stamp and the
        first flushed byte describe the same instant, which is the
        property the streaming bench gates on."""
        params, err = _validate(payload)
        if err is None:
            err = _validate_stream(params)
        if err:
            return 400, {"error": err}
        eng = self.engine
        if not self.batching or not hasattr(eng, "submit_stream_request"):
            return 400, {"error":
                         "streaming requires the continuous-batching engine"}
        try:
            req, q = eng.submit_stream_request(
                params["prompts"][0], params["tokens_to_generate"],
                return_output_log_probs=params["logprobs"],
                top_k_sampling=params["top_k"],
                top_p_sampling=params["top_p"],
                temperature=params["temperature"],
                add_BOS=params["add_BOS"],
                stop_on_double_eol=params["stop_on_double_eol"],
                stop_on_eol=params["stop_on_eol"],
                random_seed=params["random_seed"],
                priority=params["priority"],
                ttft_deadline_ms=params["ttft_deadline_ms"],
                tpot_deadline_ms=params["tpot_deadline_ms"],
                trace_id=trace_id)
        except EngineOverloaded as eo:
            return 503, {"error": str(eo),
                         "retry_after": getattr(eo, "retry_after", 1.0),
                         **getattr(eo, "info", {})}
        except RequestShed as rs:
            return 503, {"error": str(rs), "shed": True,
                         "retry_after": getattr(rs, "retry_after", 1.0)}
        except (ValueError, AssertionError) as ve:
            return 400, {"error": str(ve.args[0] if ve.args else ve)}
        first = q.next_event(timeout=600.0)
        if first is None:
            q.abandon()
            return 500, {"error": "stream produced no event within 600s"}
        if first.kind == "error":
            # terminal before any byte was written: still a buffered
            # answer — shed stays retryable (503), failure is a 500
            data = first.data
            if data.get("shed"):
                return 503, {"error": data.get("error", "request shed"),
                             "shed": True,
                             "retry_after": data.get("retry_after", 1.0)}
            return 500, {"error": data.get("error", "generation failed")}
        headers = {"X-MLT-Trace-Id": trace_id} if trace_id else {}
        ttft = req.ttft
        if ttft is not None:
            headers["X-MLT-TTFT-S"] = str(round(ttft, 6))
        tok = getattr(eng, "tokenizer", None)
        try:
            handler._begin(200, SSE_CONTENT_TYPE, headers)
            ev = first
            flushed_first = False
            while True:
                if ev.kind == "token":
                    frame = {"tokens": ev.tokens, "logprobs": ev.log_probs}
                    if tok is not None:
                        frame["text"] = tok.detokenize(ev.tokens)
                    handler._send_chunk(sse_encode("token", frame))
                    if not flushed_first:
                        # flight-record event: the instant the first
                        # token actually left for the client
                        flushed_first = True
                        req._flight.event("first_byte_flushed")
                elif ev.kind == "done":
                    if ev.data.get("dropped_events"):
                        # honest drop-to-terminal: the incremental
                        # events above are incomplete, the done body
                        # below is not
                        handler._send_chunk(sse_encode("dropped", {
                            "dropped_events": ev.data["dropped_events"]}))
                    texts, segments, log_probs = eng.finalize_stream_request(
                        req, return_output_log_probs=params["logprobs"])
                    body = {"text": texts, "segments": segments,
                            "logprobs": log_probs}
                    if trace_id:
                        timing = self.request_timing(trace_id)
                        if timing is not None:
                            body["timing"] = timing
                    handler._send_chunk(sse_encode("done", body))
                    return None
                else:  # terminal error after bytes were written:
                    # structured SSE error frame, never silent truncation
                    data = dict(ev.data)
                    data.setdefault("error", "generation failed")
                    handler._send_chunk(sse_encode("error", data))
                    return None
                ev = q.next_event(timeout=600.0)
                if ev is None:
                    handler._send_chunk(sse_encode("error", {
                        "error": "stream stalled (no event within 600s)"}))
                    return None
        except (BrokenPipeError, ConnectionError, OSError):
            # client went away mid-stream: shed future publishes and let
            # the generation finish on its own (it may be shared work)
            q.abandon()
            return None

    def _make_handler(server):  # noqa: N805 — `server` is the enclosing object
        class Handler(BaseHTTPRequestHandler):
            def _begin(self, code: int, content_type="application/json",
                       headers=None, length: Optional[int] = None):
                """THE write-path entry for buffered AND streamed
                responses: status line + headers.  A streamed response
                (``length=None``) carries no Content-Length — the body
                is delimited by EOF (HTTP/1.0 semantics) — and disables
                Nagle coalescing so each flushed SSE frame hits the wire
                immediately instead of waiting out the delayed-ACK timer
                (first-byte latency is the whole point of streaming)."""
                if length is None:
                    self.connection.setsockopt(
                        socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                if length is not None:
                    self.send_header("Content-Length", str(length))
                else:
                    self.send_header("Connection", "close")
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()

            def _send(self, code: int, body, content_type="application/json",
                      headers=None):
                data = (json.dumps(body) if content_type == "application/json"
                        else body).encode()
                self._begin(code, content_type, headers, length=len(data))
                self.wfile.write(data)

            def _send_chunk(self, data: bytes):
                """One streamed body write, flushed to the socket."""
                self.wfile.write(data)
                self.wfile.flush()

            def do_PUT(self):
                if self.path.rstrip("/") != "/api":
                    return self._send(404, {"error": "not found"})
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(length) or b"{}")
                except (ValueError, json.JSONDecodeError):
                    return self._send(400, {"error": "invalid JSON"})
                # distributed tracing (ISSUE 12): accept the caller's /
                # router's trace id, mint one otherwise; every response
                # echoes it so untraced callers can still correlate
                trace_id = (self.headers.get("X-MLT-Trace-Id", "").strip()
                            or uuid.uuid4().hex)
                try:
                    if isinstance(payload, dict) and payload.get("stream"):
                        # SSE path; a None return means the stream was
                        # served (headers + body already written), else
                        # fall through to the buffered answer below
                        with obs_trace.span("serve-api-stream",
                                            trace_id=trace_id):
                            fallback = server.stream_response(
                                self, payload, trace_id=trace_id)
                        if fallback is None:
                            return
                        code, body = fallback
                    else:
                        with obs_trace.span("serve-api", trace_id=trace_id):
                            code, body = server.handle_request(
                                payload, trace_id=trace_id)
                except Exception as e:  # last-resort: still a JSON answer
                    code, body = 500, {
                        "error": f"internal error: {type(e).__name__}: {e}"}
                if isinstance(body, str):  # legacy engines may return text
                    return self._send(code, body, "text/plain")
                headers = {"X-MLT-Trace-Id": trace_id}
                if code == 503 and isinstance(body, dict) \
                        and "retry_after" in body:
                    headers["Retry-After"] = str(
                        max(1, int(body["retry_after"])))
                if code == 200 and isinstance(body, dict) \
                        and body.get("timing", {}).get("ttft_s") is not None:
                    # server-side first-token seconds as a header, so the
                    # router's TTFT metric never has to parse the body
                    headers["X-MLT-TTFT-S"] = str(body["timing"]["ttft_s"])
                return self._send(code, body, headers=headers)

            def do_POST(self):
                # replica admin plane (ISSUE 19): the cross-replica KV
                # push lands here as raw octet-stream; everything else
                # keeps the reference's PUT semantics (POST /api works
                # as a convenience; reference is PUT-only)
                if self.path.rstrip("/") == "/admin/kv_push":
                    try:
                        length = int(self.headers.get("Content-Length", 0))
                    except ValueError:
                        return self._send(
                            400, {"error": "invalid Content-Length"})
                    blob = self.rfile.read(length)
                    trace_id = (self.headers.get("X-MLT-Trace-Id", "").strip()
                                or uuid.uuid4().hex)
                    try:
                        with obs_trace.span("serve-kv-push",
                                            trace_id=trace_id):
                            code, body = server.kv_push(
                                blob, trace_id=trace_id)
                    except Exception as e:
                        code, body = 500, {
                            "error":
                            f"internal error: {type(e).__name__}: {e}"}
                    headers = {"X-MLT-Trace-Id": trace_id}
                    if code == 503 and isinstance(body, dict) \
                            and "retry_after" in body:
                        headers["Retry-After"] = str(
                            max(1, int(body["retry_after"])))
                    return self._send(code, body, headers=headers)
                return self.do_PUT()

            def do_GET(self):
                path, _, query = self.path.partition("?")
                path = path.rstrip("/")
                if path == "/health":
                    return self._send(200, server.health())
                if path == "/metrics":
                    # Prometheus exposition (observability/registry.py),
                    # alongside /health on the same port — the serving
                    # analog of pretrain's --metrics_port endpoint
                    return self._send(
                        200, server.metrics_text(),
                        "text/plain; version=0.0.4; charset=utf-8")
                if path == "/debug/requests":
                    # recent flight records (observability/flight.py):
                    # ?n= caps the count, ?trace_id= filters.  Schema:
                    # docs/guide/observability.md "Request tracing"
                    qs = parse_qs(query)
                    try:
                        n = int(qs["n"][0]) if "n" in qs else None
                    except ValueError:
                        return self._send(
                            400, {"error": "n must be an integer"})
                    tid = qs.get("trace_id", [None])[0]
                    return self._send(
                        200, server.debug_requests(n=n, trace_id=tid))
                index = _STATIC_DIR / "index.html"
                if self.path in ("/", "/index.html") and index.exists():
                    return self._send(200, index.read_text(), "text/html")
                return self._send(404, {"error": "not found"})

            def log_message(self, fmt, *args):  # quiet by default
                pass

        return Handler

    def health(self) -> dict:
        """Liveness + replica identity + engine occupancy + prefix-cache
        state (continuous-batching engines only).  The full payload schema
        lives in docs/guide/serving.md ("/health payload") — keep the two
        in sync; the router's ReplicaView (serving/router/registry.py) is
        the consumer."""
        with self._seq_lock:
            self._health_seq += 1
            seq = self._health_seq
        info = {
            "status": "ok",
            "batching": self.batching,
            # streaming capability + elastic-discovery mode (ISSUE 18):
            # the router's ReplicaView parses both, so a fleet can tell
            # which replicas serve "stream": true and which arrived via
            # /admin/register heartbeats rather than static config
            "streaming": bool(self.batching
                              and hasattr(self.engine, "submit_stream")),
            "registered": self.register_url is not None,
            # disaggregated serving (ISSUE 19): the advertised role the
            # router's disagg policy steers by; "unified" replicas serve
            # both phases (the pre-disagg behavior, byte for byte)
            "role": self.role,
            "replica_id": self.replica_id,
            "seq": seq,
            "uptime_s": round(time.monotonic() - self._t_start, 3),
        }
        eng = self.engine
        if self.batching:
            with eng._lock:
                cache = getattr(eng, "cache", None)
                info.update(
                    active_slots=sum(r is not None for r in eng._slots),
                    peak_active_slots=eng.peak_active_slots,
                    max_slots=eng.max_slots,
                    queued=len(eng._queue),
                    prefilling=sum(
                        r is not None and r._phase == "prefill"
                        for r in eng._slots),
                    free_pages=eng.pool.num_free,
                    total_pages=eng.pool.num_pages - 1,
                    pages_cached=len(cache) if cache is not None else 0,
                    available_pages=eng.pool.num_available,
                    prefix_hit_tokens=eng.prefix_hit_tokens,
                    prefix_miss_tokens=eng.prefix_miss_tokens,
                    ticks=eng.ticks,
                    page_size=eng.page_size,
                    # quantized paged KV (ISSUE 13): storage mode + byte
                    # budget, so the router can route capacity-aware in
                    # bytes rather than pages of unknown width
                    kv_dtype=getattr(eng, "kv_dtype", "bf16"),
                    kv_pool_bytes=eng.pool.kv_pool_bytes(),
                    kv_scale_bytes=eng.pool.kv_scale_bytes(),
                    # pipelined dispatch (ISSUE 17): the chained-ticks-
                    # per-launch depth this engine runs steady-state
                    # decode at (0 = unpipelined)
                    tick_pipeline_depth=getattr(
                        eng, "pipeline_depth", 0),
                )
            mesh = getattr(eng, "mesh", None)
            info["mesh"] = ({str(k): int(v) for k, v in dict(mesh.shape).items()}
                            if mesh is not None else {})
            info["tp"] = getattr(eng, "_tp", 1)
            # pipeline-parallel serving (ISSUE 20): stage count of the
            # compiled tick; "stages" aliases "pp" for dashboards that
            # speak stage language.  1 = flat TP-only replica.
            info["pp"] = getattr(eng, "_pp", 1)
            info["stages"] = getattr(eng, "_pp", 1)
            info["kv_stage_bytes"] = eng.pool.kv_stage_bytes()
            if hasattr(eng, "scheduler_stats"):
                # control-plane view: policy, per-priority queue depths,
                # preemption/shed/deadline-miss totals, drain EMAs
                info["scheduler"] = eng.scheduler_stats()
            if hasattr(eng, "spec_stats"):
                # speculative decoding: depth cap, acceptance rate,
                # tokens per tick (generation/speculative/)
                info["spec"] = eng.spec_stats()
        return info

    def request_timing(self, trace_id: str) -> Optional[dict]:
        """Server-side timing block for a 200 response, read from the
        engine's flight records for ``trace_id`` (one per prompt in the
        request): the real first-token time (the minimum across prompts
        — the instant the response started existing) and the matching
        latency decomposition.  None when the recorder is off or the
        records already aged out of the ring."""
        flight = getattr(self.engine, "flight", None)
        if flight is None or not flight.enabled:
            return None
        recs = flight.lookup(trace_id)
        if not recs:
            return None
        with_ttft = [r for r in recs if r.get("ttft_s") is not None]
        first = (min(with_ttft, key=lambda r: r["ttft_s"])
                 if with_ttft else None)
        timing = {
            "trace_id": trace_id,
            "replica_id": self.replica_id,
            "requests": len(recs),
            "ttft_s": first["ttft_s"] if first else None,
            "latency_s": max((r["latency_s"] or 0.0) for r in recs),
        }
        if first is not None and "ttft_decomposition" in first:
            timing["ttft_decomposition"] = first["ttft_decomposition"]
        return timing

    def debug_requests(self, n: Optional[int] = None,
                       trace_id: Optional[str] = None) -> dict:
        """``GET /debug/requests``: recent flight records as JSON (in-
        flight first, then retired newest-first), plus replica identity
        so a fleet aggregation stays attributable."""
        flight = getattr(self.engine, "flight", None)
        enabled = flight is not None and flight.enabled
        recs = flight.snapshot(n=n, trace_id=trace_id) if enabled else []
        return {
            "replica_id": self.replica_id,
            "flight_recorder": enabled,
            "count": len(recs),
            "requests": recs,
        }

    def metrics_text(self) -> str:
        """Prometheus text for GET /metrics: refresh the engine-occupancy
        gauges from live engine state (scrape-time pull — the engine also
        pushes them per tick), then render the process-wide registry."""
        from megatron_llm_tpu.observability.registry import get_registry

        reg = get_registry()
        eng = self.engine
        if self.batching:
            with eng._lock:
                reg.gauge("mlt_engine_active_slots").set(
                    sum(r is not None for r in eng._slots))
                reg.gauge("mlt_engine_free_pages").set(eng.pool.num_free)
                reg.gauge("mlt_engine_max_slots").set(eng.max_slots)
                reg.gauge("mlt_engine_pool_pages").set(eng.pool.num_pages - 1)
                cache = getattr(eng, "cache", None)
                reg.gauge("mlt_engine_pages_cached").set(
                    len(cache) if cache is not None else 0)
                # queue-depth gauges (total + per-priority) have ONE owner:
                # the engine's scheduler update point
                eng._publish_queued_locked(force=True)
        return reg.render()

    def _start_engine(self):
        if self.batching and hasattr(self.engine, "start"):
            self.engine.start()  # background scheduler drives shared ticks

    # ---- elastic discovery (ISSUE 18) -----------------------------------

    def _heartbeat_loop(self, advertised: str) -> None:
        """POST ``/admin/register`` to the router until stopped.  Every
        beat carries the advertised url + replica_id; failures are
        swallowed (the router may be down or restarting — the whole
        point of heartbeats is that it catches up on the next one)."""
        import urllib.request

        target = self.register_url.rstrip("/") + "/admin/register"
        body = json.dumps({"replica": advertised,
                           "replica_id": self.replica_id}).encode()
        while True:
            req = urllib.request.Request(
                target, data=body,
                headers={"Content-Type": "application/json"}, method="POST")
            try:
                with urllib.request.urlopen(req, timeout=5.0) as resp:
                    resp.read()
            except Exception:
                pass
            if self._register_stop.wait(self.register_interval_s):
                return

    def _start_heartbeat(self, port: int) -> None:
        if not self.register_url or self._register_thread is not None:
            return
        advertised = self.advertise_url or f"http://127.0.0.1:{port}"
        self._register_stop.clear()
        t = threading.Thread(target=self._heartbeat_loop, args=(advertised,),
                             name="replica-register", daemon=True)
        self._register_thread = t
        t.start()

    # ---- lifecycle ------------------------------------------------------

    def bind(self, host: str = "0.0.0.0", port: int = 5000) -> int:
        """Bind the listening socket (without serving) and return the bound
        port — with ``port=0`` the OS picks a free one, which is how local
        fleets (tests, bench_decode --mode router) avoid port races.  Call
        ``serve()`` afterwards to block."""
        self._httpd = ThreadingHTTPServer((host, port), self._make_handler())
        return self._httpd.server_address[1]

    def serve(self):
        """Serve on the socket from ``bind()`` (blocking)."""
        assert self._httpd is not None, "call bind() first"
        self._start_engine()
        self._start_heartbeat(self._httpd.server_address[1])
        self._httpd.serve_forever()

    def run(self, host: str = "0.0.0.0", port: int = 5000):
        self.bind(host, port)
        self.serve()

    def start_background(self, host: str = "127.0.0.1", port: int = 5000):
        """Run in a daemon thread (used by tests); returns the bound port."""
        bound = self.bind(host, port)
        self._start_engine()
        self._start_heartbeat(bound)
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t.start()
        return bound

    def stop(self):
        self._register_stop.set()
        if self._register_thread is not None:
            self._register_thread.join(timeout=5.0)
            self._register_thread = None
        if self._httpd is not None:
            self._httpd.shutdown()
            # close the listening socket too: new connections must be
            # REFUSED (a router fails over on that), not sit in a backlog
            # nobody will ever accept
            self._httpd.server_close()
            self._httpd = None
        if self.batching and hasattr(self.engine, "stop"):
            self.engine.stop()
