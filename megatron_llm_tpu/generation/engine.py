"""Continuous-batching decode engine on a prefix-cached paged KV cache.

The legacy serving shape (generation/api.InferenceEngine) is the paper's:
one request at a time, a dense ``[L, b, max_seq, nkv, d]`` cache allocated
per call, and a program compiled per (batch, max_seq) bucket.  This engine
is the TPU-serving shape the Ragged-Paged-Attention and Gemma-on-Cloud-TPU
studies (PAPERS.md) converge on: keep ONE fixed-shape decode program
resident, keep its batch full, and never compute the same prefix twice.

* **Paged KV pool** (:class:`PagedKVPool`): all in-flight sequences share a
  ``[L, num_pages, page_size, nkv, d]`` pool; a sequence owns an ordered
  page list (its block table).  Pages are REFERENCE-COUNTED: several
  sequences may share the pages of a common prompt prefix.  Page 0 is the
  reserved *null page*: idle slots' block tables point at it and their
  writes land there, never attended.

* **Prefix cache** (:class:`PrefixCache`): a host-side radix/trie keyed on
  page-aligned token chunks.  Admission walks the trie, takes a ref on
  every matched full page, and only prefills the uncovered suffix; when a
  request's first tick must rewrite a shared page (page-aligned full match)
  the page is copied first — copy-on-write, shared pages are never mutated.
  Pages whose refcount drops to zero STAY in the cache until the free list
  runs dry, then an LRU leaf-first eviction recycles them — pool exhaustion
  no longer means rejection while reusable pages sit idle.

* **On-demand pages**: admission allocates only the prompt-suffix pages
  (plus the first decode page); decode grabs one page at each page-boundary
  crossing.  A commitment ledger keeps ``free + evictable`` at least the
  worst-case remaining demand of every admitted request (plus a
  ``page_watermark`` slack), so an in-flight slot can never deadlock on the
  pool — admission defers instead.

* **Chunked prefill**: the uncovered suffix runs in fixed-size chunks that
  write K/V through the block table and attend through it too
  (ops/paged_attention.paged_attention_prefill — the prefix-length-aware
  prefill-against-block-table mode, Pallas kernel on TPU).  The scheduler
  interleaves ONE chunk per decode tick instead of stalling the whole batch
  for a monolithic prompt, so queued requests' time-to-first-token stops
  scaling with the longest admitted prompt.  Chunk boundaries are aligned
  to absolute-position multiples of ``prefill_chunk`` and the attended page
  horizon is bucketed per chunk, so the K/V bits a chunk produces depend
  only on (tokens, absolute positions) — a cache hit replays bitwise the
  pages a cold prefill would compute (the cache-on/off parity contract,
  tests/test_prefix_cache.py).  ``prefill_chunk=0`` restores the PR 1
  monolithic dense prefill (and disables the prefix cache, which needs the
  block-table prefill path).

* **Slots + fixed shapes**: the decode tick runs ``max_slots`` rows every
  time, active or not.  Block tables, positions, per-slot sampling params
  and per-slot PRNG keys are *traced* inputs, so the tick compiles ONCE;
  prefill compiles once per (chunk rows, page horizon) pair.  Slots mid
  prefill keep their device block-table row at the null page, so tick
  writes from not-yet-active rows land in garbage that is never attended.

* **Decode tick**: one fused jitted step — embed [slots, 1] tokens, write
  each row's K/V into its current page, paged attention over block tables
  (Pallas kernel on TPU, jnp gather fallback elsewhere —
  ops/paged_attention.py), per-slot sampling (sampling.sample_per_slot),
  token log-probs.  Pool buffers are donated, so the cache updates in
  place.

* **Scheduling control plane** (generation/scheduling/): every scheduling
  DECISION — admission order, the per-tick prefill-chunk budget,
  preemption victims, load shedding — delegates to a pluggable
  :class:`~megatron_llm_tpu.generation.scheduling.SchedulerPolicy`
  (``--sched_policy``: ``fcfs`` default / ``priority`` / ``slo``), while
  the MECHANISMS (pages, slots, the commitment ledger) stay here.
  Preemption works by page release: the victim's finished KV pages are
  parked in the prefix trie, its pages released, and the request
  re-queued — re-admission matches the pages back out of the trie and
  resume is bitwise-identical to never having been preempted.  Admission
  control is metrics-driven: overload 503s carry an EMA-drain Retry-After,
  per-priority queue bounds gate the classes independently, and the slo
  policy sheds requests whose deadline is already unmeetable.

* **Speculative decoding** (generation/speculative/, ``--spec_k`` +
  ``--spec_draft``): a small draft model proposes up to k tokens per
  tick, the target verifies all k+1 positions in ONE forward (the k+1
  query positions flattened into the batch so every op is the decode
  tick's shape — per-row bits are batch-size invariant, which is what
  makes greedy speculation BITWISE-identical to ``spec_k=0``), and a
  lossless acceptance rule emits 1..k+1 tokens.  Draft K/V lives in the
  SAME pool (one page id addresses both caches), so block tables,
  refcounts, the commitment ledger, the prefix trie, COW and
  preemption-by-page-release all govern both models unchanged.

Threading: ``submit`` may be called from any thread (e.g. concurrent HTTP
handlers — generation/server.py); device work happens on whichever thread
drives :meth:`step`, either the built-in background loop (:meth:`start`) or
a caller loop (:meth:`run_until_idle`).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from megatron_llm_tpu.core.parallel_state import PP_AXIS, TP_AXIS
from megatron_llm_tpu.generation import generation as gen
from megatron_llm_tpu.generation.sampling import sample_per_slot
from megatron_llm_tpu.generation.scheduling import (
    RequestShed,
    SchedulerPolicy,
    SchedulerState,
    get_policy,
)
from megatron_llm_tpu.observability import flight as obs_flight
from megatron_llm_tpu.observability import registry as obs_registry
from megatron_llm_tpu.observability import trace as obs_trace
from megatron_llm_tpu.generation.tokenization import detokenize_generations
from megatron_llm_tpu.models.language_model import (
    _compute_dtype,
    make_rope_cache,
    model_forward,
)
from megatron_llm_tpu.ops import kv_quant
from megatron_llm_tpu.ops.paged_attention import PagedState

NULL_PAGE = 0


def _bucket_up(n: int, bucket: int = gen.BUCKET) -> int:
    return -(-n // bucket) * bucket


class EngineOverloaded(RuntimeError):
    """Submit-time backpressure: the request queue is at capacity.

    The server maps this to a structured 503 with a ``Retry-After`` header
    instead of queueing unboundedly (generation/server.py).  ``retry_after``
    is metrics-driven — the engine's EMA drain estimate for the current
    queue depth, not a constant — and ``info`` carries the queue snapshot
    the server includes in the 503 body."""

    def __init__(self, msg: str, retry_after: float = 1.0,
                 info: Optional[dict] = None):
        super().__init__(msg)
        self.retry_after = retry_after
        self.info = info or {}


class PagedKVPool:
    """Device page pool + host refcounting allocator.

    The device arrays are plain stacked pytrees ``[L, P, page, nkv, d]``
    (scanned over L exactly like the dense cache); the allocator is
    host-side python — alloc/release happen at request admission/retirement
    and page-boundary crossings, far below tick frequency.

    Page states (disjoint, tests/test_prefix_cache.py invariants):

    * **free** — on the free list, refcount 0, not cached;
    * **referenced** — refcount > 0 (held by >= 1 request's block table),
      possibly ALSO registered in the prefix cache;
    * **cached-idle** — refcount 0 but registered in the prefix cache
      (``cached``): reusable by a future match, reclaimable by
      ``evict_hook`` (PrefixCache.evict, LRU leaf-first) when ``alloc``
      outruns the free list.

    With ``draft_cfg`` (speculative decoding, generation/speculative/),
    the pool carries a SECOND pair of device arrays shaped by the draft
    model — same ``num_pages``, same page ids.  A page id then addresses
    both models' K/V for the same token positions: one block table, one
    refcount, one commitment ledger and one prefix trie govern both
    caches, so admission/preemption accounting stays deadlock-proof with
    zero new allocator states.
    """

    def __init__(self, cfg, num_pages: int, page_size: int, dtype=None,
                 mesh: Optional[Mesh] = None, draft_cfg=None,
                 kv_dtype: str = "bf16"):
        m = cfg.model
        dtype = dtype or _compute_dtype(cfg)
        assert kv_dtype in kv_quant.KV_DTYPES, (
            f"kv_dtype must be one of {kv_quant.KV_DTYPES}, got {kv_dtype!r}")
        # --kv_dtype (ISSUE 13): "bf16" keeps plain compute-dtype arrays —
        # byte-for-byte today's pool, every bitwise parity suite intact;
        # int8/fp8 store QuantPagedKV containers (values + per-page,
        # per-head scales, ops/kv_quant.py) for ~2x pages per chip.
        self.kv_dtype = kv_dtype
        self.compute_dtype = dtype
        shape = (m.num_layers, num_pages, page_size,
                 m.num_attention_heads_kv, m.kv_channels)

        def _make(shp):
            return kv_quant.make_pool(shp, kv_dtype, dtype)

        # Tensor parallelism shards the pool over the KV-heads dim (each tp
        # rank attends its own heads — the same decomposition as the qkv
        # column-parallel rule in parallel/tp.py). Block tables and the
        # allocator below stay host-side and apply to every shard alike;
        # tp=1 (or no mesh) degrades to a single-device replicated pool.
        # Quantized pools shard the scale leaf over the same heads dim
        # ([L, P, nkv] -> tp on nkv), so a page's values and its scales
        # always live on the same shard.
        # Pipeline parallelism (ISSUE 20) additionally shards the pool
        # over the LAYER dim: each pp stage holds only its own L/pp
        # layers' pages — per-stage pool bytes are 1/pp of the tp-only
        # pool (the servable-model-size multiplier).  Page ids address
        # the same slot of every stage's slice, so block tables, the
        # trie, the allocator and the commitment ledger below stay
        # host-side and stage-agnostic, untouched.
        self.mesh = mesh
        tp = mesh.shape.get(TP_AXIS, 1) if mesh is not None else 1
        pp = mesh.shape.get(PP_AXIS, 1) if mesh is not None else 1
        self.pp = pp
        if pp > 1:
            assert m.num_layers % pp == 0, (
                f"num_layers {m.num_layers} not divisible by pp {pp}")
        if tp > 1 or pp > 1:
            if tp > 1:
                assert m.num_attention_heads_kv % tp == 0, (
                    f"kv heads {m.num_attention_heads_kv} not divisible by "
                    f"tp {tp}")
            layer_ax = PP_AXIS if pp > 1 else None
            heads_ax = TP_AXIS if tp > 1 else None
            self.kv_sharding = NamedSharding(
                mesh, P(layer_ax, None, None, heads_ax, None))
            self._scale_sharding = NamedSharding(
                mesh, P(layer_ax, None, heads_ax))
            self.k = self._place(_make(shape))
            self.v = self._place(_make(shape))
        else:
            self.kv_sharding = (NamedSharding(mesh, P())
                                if mesh is not None else None)
            self._scale_sharding = self.kv_sharding
            self.k = _make(shape)
            self.v = _make(shape)
        self.draft_cfg = draft_cfg
        self.draft_k = self.draft_v = None
        if draft_cfg is not None:
            dm = draft_cfg.model
            ddtype = _compute_dtype(draft_cfg)
            dshape = (dm.num_layers, num_pages, page_size,
                      dm.num_attention_heads_kv, dm.kv_channels)

            def _make_d(shp):
                return kv_quant.make_pool(shp, kv_dtype, ddtype)

            if pp > 1:
                assert dm.num_layers % pp == 0, (
                    f"draft num_layers {dm.num_layers} not divisible by "
                    f"pp {pp}")
            if tp > 1 or pp > 1:
                if tp > 1:
                    assert dm.num_attention_heads_kv % tp == 0, (
                        f"draft kv heads {dm.num_attention_heads_kv} not "
                        f"divisible by tp {tp}")
                self.draft_k = self._place(_make_d(dshape))
                self.draft_v = self._place(_make_d(dshape))
            else:
                self.draft_k = _make_d(dshape)
                self.draft_v = _make_d(dshape)
        self.num_pages = num_pages
        self.page_size = page_size
        self.refcounts = np.zeros((num_pages,), np.int32)
        # pages owned by the prefix cache (trie nodes); maintained by
        # PrefixCache, read here for release/eviction accounting
        self.cached: Set[int] = set()
        self.evict_hook = None  # PrefixCache.evict: (n) -> freed page list
        # page 0 reserved as the null page (never allocated)
        self._free: deque = deque(range(1, num_pages))

    def _place(self, pool):
        """device_put a pool (plain array or QuantPagedKV) under the tp
        sharding — values over the heads dim, scales over their heads
        dim."""
        if kv_quant.is_quantized(pool):
            return jax.device_put(pool, kv_quant.QuantPagedKV(
                q=self.kv_sharding, scale=self._scale_sharding))
        return jax.device_put(pool, self.kv_sharding)

    @property
    def kv_statics(self) -> Tuple:
        """Compiled-program cache-key component for the KV storage mode
        (ISSUE 13): kv-quantization mode, storage dtype AND scale dtype —
        an int8 engine must never reuse a bf16 executable (and vice
        versa), and a future scale-dtype change re-keys too.  Replaces
        the old ``str(pool.k.dtype)`` key entry, which could not tell a
        container apart from its storage array."""
        if kv_quant.is_quantized(self.k):
            return ("kv", self.kv_dtype, str(self.k.q.dtype),
                    str(self.k.scale.dtype))
        return ("kv", self.kv_dtype, str(self.k.dtype))

    @property
    def draft_kv_statics(self) -> Tuple:
        if self.draft_k is None:
            return ("draft_kv", None)
        if kv_quant.is_quantized(self.draft_k):
            return ("draft_kv", self.kv_dtype, str(self.draft_k.q.dtype),
                    str(self.draft_k.scale.dtype))
        return ("draft_kv", self.kv_dtype, str(self.draft_k.dtype))

    def kv_pool_bytes(self) -> int:
        """Device bytes of the KV value storage, target + draft caches —
        the fixed budget the capacity bench holds constant while the
        kv_dtype varies (published as ``mlt_engine_kv_pool_bytes``)."""
        n = kv_quant.pool_nbytes(self.k) + kv_quant.pool_nbytes(self.v)
        if self.draft_k is not None:
            n += (kv_quant.pool_nbytes(self.draft_k)
                  + kv_quant.pool_nbytes(self.draft_v))
        return n

    def kv_stage_bytes(self) -> int:
        """Per-stage device bytes of the KV value storage: the layer dim
        is sharded over pp, so each stage holds ``kv_pool_bytes / pp`` —
        the number a pp=N replica's HBM budget actually pays (published
        as ``mlt_engine_kv_stage_bytes``; bench --mode pp evidence)."""
        return self.kv_pool_bytes() // self.pp

    def kv_scale_bytes(self) -> int:
        """Per-page scale overhead bytes (0 for bf16)."""
        n = kv_quant.scale_nbytes(self.k) + kv_quant.scale_nbytes(self.v)
        if self.draft_k is not None:
            n += (kv_quant.scale_nbytes(self.draft_k)
                  + kv_quant.scale_nbytes(self.draft_v))
        return n

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_evictable(self) -> int:
        """Cached pages no request references — reclaimable on demand."""
        return sum(1 for p in self.cached if self.refcounts[p] == 0)

    @property
    def num_available(self) -> int:
        """Pages an ``alloc`` could produce right now (free + evictable)."""
        return self.num_free + self.num_evictable

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` fresh pages at refcount 1, or None if free + evictable
        can't satisfy the request.  Evicts cached-idle pages (LRU,
        leaf-first) only when the free list alone runs short."""
        if n > self.num_available:
            return None
        if n > len(self._free) and self.evict_hook is not None:
            self._free.extend(self.evict_hook(n - len(self._free)))
        if n > len(self._free):
            return None
        pages = [self._free.popleft() for _ in range(n)]
        for p in pages:
            assert self.refcounts[p] == 0 and p not in self.cached
            self.refcounts[p] = 1
        return pages

    def incref(self, pages: Sequence[int]) -> None:
        for p in pages:
            assert p != NULL_PAGE
            self.refcounts[p] += 1

    def release(self, pages: Sequence[int]) -> None:
        """Drop one reference per page.  Unreferenced pages return to the
        free list unless the prefix cache still holds them (those stay
        cached-idle until matched again or evicted)."""
        for p in pages:
            assert p != NULL_PAGE, "null page is never allocated"
            self.refcounts[p] -= 1
            assert self.refcounts[p] >= 0, f"page {p} over-released"
            if self.refcounts[p] == 0 and p not in self.cached:
                self._free.append(p)

    # ---- cross-replica page transfer (ISSUE 19, serving/handoff/) ----

    def _leaf_items(self) -> List[Tuple[str, object]]:
        """(wire name, device array) pairs of every storage leaf, in
        wire order: plain pools contribute one leaf per cache, quantized
        pools their value bytes AND per-page scale rows, draft caches
        (speculation) ride along under their own names — exactly the
        set a receiving pool must install for a migrated page to be
        bit-identical to a locally prefilled one."""
        items: List[Tuple[str, object]] = []
        for name, pool in (("k", self.k), ("v", self.v),
                           ("draft_k", self.draft_k),
                           ("draft_v", self.draft_v)):
            if pool is None:
                continue
            if kv_quant.is_quantized(pool):
                items.append((name + ".q", pool.q))
                items.append((name + ".scale", pool.scale))
            else:
                items.append((name, pool))
        return items

    def export_pages(self, pages: Sequence[int]) -> Dict[str, np.ndarray]:
        """Gather ``pages`` from every storage leaf to the host: ONE
        batched ``device_get`` over all leaves (k/v values, scale rows,
        draft caches), so a multi-page export pays one transfer sync.
        The caller must hold page refs on ``pages`` and serialize
        against tick dispatch (the engine's ``_drive_lock``) — ticks
        rebind the pool arrays with donated buffers."""
        ids = np.asarray(list(pages), np.int32)
        names, gathers = [], []
        for name, arr in self._leaf_items():
            names.append(name)
            gathers.append(arr[:, ids])
        host = jax.device_get(gathers)
        return dict(zip(names, host))

    def import_pages(self, pages: Sequence[int],
                     leaves: Dict[str, np.ndarray]) -> None:
        """Install exported leaf bytes into freshly allocated ``pages``
        VERBATIM — quantized leaves set ``q`` and ``scale`` directly,
        never re-quantizing, so the imported page is byte-identical to
        the sender's (tests/test_handoff.py round-trip).  Leaf names,
        dtypes and shapes must match this pool exactly (a bf16 pool
        cannot install an int8 export; a speculating sender's draft
        leaves need a speculating receiver).  Caller serializes against
        tick dispatch, same as :meth:`export_pages`."""
        ids = np.asarray(list(pages), np.int32)
        mine = dict(self._leaf_items())
        if sorted(mine) != sorted(leaves):
            raise ValueError(
                f"handoff leaves {sorted(leaves)} do not match this "
                f"pool's storage leaves {sorted(mine)} "
                f"(kv_dtype={self.kv_dtype!r}, "
                f"draft={'yes' if self.draft_k is not None else 'no'})")
        for name, arr in mine.items():
            val = leaves[name]
            want_shape = arr.shape[:1] + (len(ids),) + arr.shape[2:]
            if tuple(val.shape) != want_shape or val.dtype != arr.dtype:
                raise ValueError(
                    f"handoff leaf {name!r} is {val.dtype}{val.shape}, "
                    f"pool needs {arr.dtype}{want_shape}")

        def _install(pool, name):
            if kv_quant.is_quantized(pool):
                return kv_quant.QuantPagedKV(
                    q=pool.q.at[:, ids].set(
                        jnp.asarray(leaves[name + ".q"])),
                    scale=pool.scale.at[:, ids].set(
                        jnp.asarray(leaves[name + ".scale"])))
            return pool.at[:, ids].set(jnp.asarray(leaves[name]))

        self.k = _install(self.k, "k")
        self.v = _install(self.v, "v")
        if self.draft_k is not None:
            self.draft_k = _install(self.draft_k, "draft_k")
            self.draft_v = _install(self.draft_v, "draft_v")


class _TrieNode:
    __slots__ = ("key", "page", "parent", "children", "last_use")

    def __init__(self, key, page, parent):
        self.key = key
        self.page = page
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_TrieNode"] = {}
        self.last_use = 0


class PrefixCache:
    """Host-side radix/trie over page-aligned token chunks -> pool pages.

    Each node owns one FULL page of prompt K/V, keyed by that page's
    ``page_size`` token ids; a path from the root spells a prompt prefix.
    ``match`` walks the trie and takes a pool reference on every matched
    page (the caller's block table will point at them); ``insert`` registers
    a freshly prefilled request's full prompt pages so later requests can
    share them.  Because a request that matches a page has, by
    construction, matched ALL its ancestors too, a refcount-0 node's
    descendants are also refcount-0 — so eviction can always proceed
    leaf-first through cached-idle subtrees, and ``PagedKVPool.num_evictable``
    (a flat count) is exactly the number of reclaimable pages.
    """

    def __init__(self, pool: PagedKVPool, page_size: int):
        self.pool = pool
        self.page_size = page_size
        self.root = _TrieNode(None, NULL_PAGE, None)
        self._nodes: Dict[int, _TrieNode] = {}  # page id -> node
        self._clock = 0
        pool.evict_hook = self.evict

    def __len__(self) -> int:
        return len(self._nodes)

    def _key(self, tokens: Sequence[int], i: int) -> Tuple[int, ...]:
        ps = self.page_size
        return tuple(tokens[i * ps:(i + 1) * ps])

    def match(self, tokens: Sequence[int], max_pages: int) -> List[int]:
        """Longest cached prefix of ``tokens`` in whole pages (capped at
        ``max_pages``); takes one pool ref per matched page."""
        self._clock += 1
        node, pages = self.root, []
        for i in range(max_pages):
            child = node.children.get(self._key(tokens, i))
            if child is None:
                break
            child.last_use = self._clock
            pages.append(child.page)
            node = child
        self.pool.incref(pages)
        return pages

    def insert(self, tokens: Sequence[int], pages: Sequence[int],
               n_pages: int) -> int:
        """Register the first ``n_pages`` full pages of a prefilled prompt;
        pages already cached at a position keep the incumbent (the
        request's duplicate page simply stays private).  Returns the number
        of pages newly cached."""
        self._clock += 1
        node, added = self.root, 0
        for i in range(n_pages):
            key = self._key(tokens, i)
            child = node.children.get(key)
            if child is None:
                p = pages[i]
                if p in self._nodes:  # defensive: one node per page
                    break
                child = _TrieNode(key, p, node)
                node.children[key] = child
                self._nodes[p] = child
                self.pool.cached.add(p)
                added += 1
            child.last_use = self._clock
            node = child
        return added

    def evict(self, n: int) -> List[int]:
        """Reclaim up to ``n`` cached-idle pages, least-recently-used
        leaves first (removing a leaf may expose its parent next round)."""
        freed: List[int] = []
        while len(freed) < n:
            victim = None
            for node in self._nodes.values():
                if node.children or self.pool.refcounts[node.page] != 0:
                    continue
                if victim is None or node.last_use < victim.last_use:
                    victim = node
            if victim is None:
                break
            del victim.parent.children[victim.key]
            del self._nodes[victim.page]
            self.pool.cached.discard(victim.page)
            freed.append(victim.page)
        return freed


@dataclasses.dataclass
class EngineRequest:
    """One in-flight generation; ``result()`` blocks until finished."""

    prompt: List[int]
    max_new_tokens: int
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 0.0
    termination_id: Optional[int] = None
    use_eod_for_termination: bool = True
    stop_on_double_eol: bool = False
    stop_on_eol: bool = False
    seed: Optional[int] = None
    return_log_probs: bool = False
    # scheduling (generation/scheduling/): priority class (0 = most
    # urgent, the `priority` policy) and soft deadlines (the `slo`
    # policy); all ignored by fcfs
    priority: int = 1
    ttft_deadline_ms: Optional[float] = None
    tpot_deadline_ms: Optional[float] = None
    # distributed tracing (ISSUE 12): the X-MLT-Trace-Id the router or
    # caller minted; correlates this request across router spans,
    # replica spans and flight records ("" = untraced direct submit)
    trace_id: str = ""
    # disaggregated serving (ISSUE 19): stop after chunked prefill and
    # park in the `handoff` phase with page refs held — the export path
    # (prefill_and_export) ships the pages and retires the request; the
    # request never takes a decode tick
    prefill_only: bool = False

    # engine-filled state
    generated: List[int] = dataclasses.field(default_factory=list)
    log_probs: List[float] = dataclasses.field(default_factory=list)
    prompt_log_probs: Optional[List[float]] = None
    finished: bool = False
    error: Optional[str] = None
    shed: bool = False  # dropped by the scheduler, never served
    shed_retry_after: float = 1.0
    _done: threading.Event = dataclasses.field(
        default_factory=threading.Event, repr=False)
    _pages: List[int] = dataclasses.field(default_factory=list, repr=False)
    _step: int = 0  # decode ticks taken (== len(generated))
    # scheduler state: queued -> prefill -> decode -> finished
    _phase: str = dataclasses.field(default="queued", repr=False)
    _slot: int = dataclasses.field(default=-1, repr=False)
    _fill_pos: int = dataclasses.field(default=0, repr=False)
    _max_pages: int = dataclasses.field(default=0, repr=False)
    _hit_tokens: int = dataclasses.field(default=0, repr=False)
    _t_submit: float = dataclasses.field(default=0.0, repr=False)
    _t_first: float = dataclasses.field(default=0.0, repr=False)
    _t_done: float = dataclasses.field(default=0.0, repr=False)
    _seqno: int = dataclasses.field(default=0, repr=False)
    _preemptions: int = dataclasses.field(default=0, repr=False)
    # PRNG key resolved at FIRST activation and pinned: a preempted
    # request resumes the same sampling stream (fold_in(key, _step))
    _key: Optional[np.ndarray] = dataclasses.field(default=None, repr=False)
    # speculative decoding: acceptance EMA drives the per-slot adaptive
    # depth (starts optimistic; shrinks when the draft keeps missing)
    _spec_ema: float = dataclasses.field(default=1.0, repr=False)
    # flight record (observability/flight.py); the shared null record
    # when the recorder is disabled, so every call site stays branch-free
    _flight: object = dataclasses.field(
        default=obs_flight.NULL_RECORD, repr=False)
    # token streaming (serving/streaming/): the per-request emission
    # queue submit_stream attached, fed by the apply/retire paths under
    # _lock; None = plain request/response submit
    _stream: object = dataclasses.field(default=None, repr=False)

    def result(self, timeout: Optional[float] = None):
        """Wait for completion; returns (full token list, gen log-probs)."""
        if not self._done.wait(timeout):
            raise TimeoutError("generation did not finish in time")
        if self.shed:
            raise RequestShed(self.error or "request shed",
                              retry_after=self.shed_retry_after)
        if self.error:
            raise RuntimeError(self.error)
        return list(self.prompt) + self.generated, list(self.log_probs)

    @property
    def seq_tokens(self) -> List[int]:
        """Prompt + tokens generated so far — the effective prompt a
        preempted request re-admits with (fresh requests: the prompt)."""
        return list(self.prompt) + self.generated

    @property
    def ttft(self) -> Optional[float]:
        """Seconds from submit to first generated token (bench telemetry)."""
        if self._t_first == 0.0:
            return None
        return self._t_first - self._t_submit

    @property
    def latency(self) -> Optional[float]:
        """Seconds from submit to retirement (bench telemetry)."""
        if self._t_done == 0.0:
            return None
        return self._t_done - self._t_submit


class ContinuousBatchingEngine:
    """Shared-tick decode over a prefix-cached paged pool."""

    def __init__(self, cfg, params, tokenizer=None, *,
                 max_slots: Optional[int] = None,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 max_seq: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 prefill_chunk: Optional[int] = None,
                 page_watermark: Optional[int] = None,
                 max_queue: Optional[int] = None,
                 sched_policy=None,
                 spec_k: Optional[int] = None,
                 spec_draft=None,
                 spec_adaptive: Optional[bool] = None,
                 ragged: Optional[bool] = None,
                 prefill_budget: Optional[int] = None,
                 flight_records: Optional[int] = None,
                 flight_events: Optional[int] = None,
                 kv_dtype: Optional[str] = None,
                 tick_pipeline_depth: Optional[int] = None,
                 mesh: Optional[Mesh] = None):
        inf = cfg.inference
        self.cfg = cfg
        if inf.int8_weights:
            # same decode-weight quantization contract as api.InferenceEngine
            from megatron_llm_tpu.ops.quant import quantize_layer_weights_int8

            params = quantize_layer_weights_int8(params)
        # Tensor-parallel serving: params shard by the parallel/tp.py rules
        # (qkv/lm_head column-parallel, dense/fc2 row-parallel, vocab-
        # parallel embedding), the KV pool shards over the heads dim, and
        # every jitted program (tick / prefill chunk / page copy) follows
        # its committed input shardings — XLA inserts the row-parallel
        # all-reduces. mesh=None (or an all-1 mesh) is today's single-chip
        # engine, byte for byte.
        self.mesh = mesh
        self._tp = mesh.shape.get(TP_AXIS, 1) if mesh is not None else 1
        # Pipeline-parallel serving (ISSUE 20, parallel/pp_serve.py): a
        # pp>1 mesh runs the tick's layer stack as pp stages over
        # microbatched rows, with the paged pool sharded per stage over
        # its own layers.  pp == 1 (or no mesh) resolves the context to
        # None — the flag is inert and every program is byte-for-byte
        # today's TP-only engine.
        self._pp = mesh.shape.get(PP_AXIS, 1) if mesh is not None else 1
        # --tp_overlap ring (parallel/overlap.py): the decode/ragged-tick
        # forwards route their row-parallel projections through the
        # chunked collective-matmul ring.  None = off (byte-for-byte
        # today's implicitly-inserted collectives); resolves to None at
        # tp == 1 regardless of the flag (single-chip degradation).
        # --vocab_ring rides in the same context: the head GEMM's logits
        # all-gather becomes an all-gather matmul ring (ISSUE 20).
        from megatron_llm_tpu.parallel import overlap as tp_overlap_mod
        from megatron_llm_tpu.parallel import pp_serve as pp_serve_mod

        self._overlap = tp_overlap_mod.overlap_params(cfg, mesh)
        self._overlap_mode = ("ring" if self._overlap is not None
                              and self._overlap.ring_rows else "off")
        self._vocab_ring = bool(self._overlap is not None
                                and self._overlap.vocab_ring)
        self._ppc = pp_serve_mod.serve_params(cfg, mesh)
        if self._pp > 1:
            # pp stages own contiguous layer slices of params AND pool —
            # checked before param placement so the friendly assert wins
            # over the sharding divisibility ValueError
            assert cfg.model.num_layers % self._pp == 0, (
                f"num_layers {cfg.model.num_layers} not divisible by "
                f"pp {self._pp}")
            # ppermute inside a partial-manual region crashes the GSPMD
            # partitioner on jax 0.4.37 — hold the shardy flag for the
            # engine's lifetime (it participates in jit trace keys, so
            # flat-mesh executables are never reused; compat.py story).
            from megatron_llm_tpu.parallel import compat as compat_mod

            compat_mod.enable_partitioner_for(mesh)
        if mesh is not None:
            from megatron_llm_tpu.parallel.tp import param_shardings

            m = cfg.model
            if self._tp > 1:
                from megatron_llm_tpu.models.language_model import (
                    padded_vocab_size,
                )

                assert m.num_attention_heads % self._tp == 0, (
                    f"attention heads {m.num_attention_heads} not divisible "
                    f"by tp {self._tp}")
                assert padded_vocab_size(m.vocab_size, cfg) % self._tp == 0, (
                    "padded vocab not divisible by tp")
            params = jax.device_put(params, param_shardings(mesh, params))
            self._repl = NamedSharding(mesh, P())
        else:
            self._repl = None
        self.params = params
        self.tokenizer = tokenizer
        self.max_slots = max_slots or inf.max_batch_slots
        self.page_size = page_size or inf.page_size
        self.max_seq = (max_seq or inf.engine_max_seq
                        or min(cfg.data.seq_length,
                               cfg.model.max_position_embeddings))
        assert self.max_seq <= cfg.model.max_position_embeddings
        assert gen.BUCKET % self.page_size == 0, (
            "page_size must divide the prefill bucket so bucketed prefills "
            "scatter whole pages")
        self.prefill_chunk = (prefill_chunk if prefill_chunk is not None
                              else getattr(inf, "prefill_chunk", gen.BUCKET))
        if self.prefill_chunk:
            assert self.prefill_chunk % self.page_size == 0, (
                "prefill_chunk must be a whole number of pages")
        use_cache = (prefix_cache if prefix_cache is not None
                     else getattr(inf, "prefix_cache", True))
        self.page_watermark = (page_watermark if page_watermark is not None
                               else getattr(inf, "page_watermark", 0))
        self.max_queue = (max_queue if max_queue is not None
                          else getattr(inf, "max_queued_requests", 256))
        # scheduling policy (generation/scheduling/): decisions delegate
        # to it, mechanisms stay here.  A string resolves through the
        # registry; tests may hand a policy instance directly.
        sched = (sched_policy if sched_policy is not None
                 else getattr(inf, "sched_policy", "fcfs"))
        if isinstance(sched, SchedulerPolicy):
            self.policy = sched
        else:
            self.policy = get_policy(sched)(
                aging_s=getattr(inf, "sched_aging_s", 5.0),
                preemption=getattr(inf, "sched_preemption", True))
        # per-priority queue bounds ("0:64,2:16"); classes without a quota
        # share only the global max_queue bound
        self._quota: Dict[int, int] = {}
        for part in (getattr(inf, "sched_quota", None) or "").split(","):
            if part.strip():
                prio, bound = part.split(":")
                self._quota[int(prio)] = int(bound)
        # speculative decoding (generation/speculative/): a draft model
        # proposes spec_k tokens per tick, the target verifies all of them
        # in one flattened-batch forward, and a lossless acceptance rule
        # keeps the longest agreed prefix.  spec_k=0 is today's one-token
        # tick, byte for byte (the spec path never compiles).
        self.spec_k = spec_k if spec_k is not None else getattr(
            inf, "spec_k", 0)
        self.spec_adaptive = (spec_adaptive if spec_adaptive is not None
                              else getattr(inf, "spec_adaptive", True))
        self.draft_cfg = self.draft_params = None
        if self.spec_k:
            from megatron_llm_tpu.generation.speculative import (
                DraftModel,
                check_draft_compat,
                resolve_draft,
            )

            draft = (spec_draft if spec_draft is not None
                     else getattr(inf, "spec_draft", None))
            if draft is None:
                raise ValueError(
                    "spec_k > 0 requires a draft model (--spec_draft)")
            assert self.prefill_chunk, (
                "speculative decoding requires chunked prefill "
                "(prefill_chunk > 0): draft K/V is populated through the "
                "block-table prefill path")
            if isinstance(draft, str):
                draft = resolve_draft(draft, cfg)
            elif isinstance(draft, tuple):
                draft = DraftModel(*draft)
            check_draft_compat(cfg, draft.cfg, max_seq=self.max_seq)
            draft_params = draft.params
            if mesh is not None:
                from megatron_llm_tpu.parallel.tp import param_shardings

                draft_params = jax.device_put(
                    draft_params, param_shardings(mesh, draft_params))
            self.draft_cfg, self.draft_params = draft.cfg, draft_params
        # ragged tick (generation/ragged.py, ISSUE 11): ONE compiled
        # launch per tick carries the decode slots, the speculative-verify
        # blocks AND up to prefill_rows prefill-chunk rows — bitwise-
        # identical output to the legacy split dispatch, minus its per-tick
        # program launches.  Needs the block-table prefill path, so
        # prefill_chunk=0 (monolithic) implies the legacy dispatch.
        self.ragged = bool(
            (ragged if ragged is not None
             else getattr(inf, "ragged_tick", True))
            and self.prefill_chunk)
        budget_cap = (prefill_budget if prefill_budget is not None
                      else getattr(inf, "prefill_budget", 0))
        # compiled prefill-row capacity of the ragged tick (a geometry
        # static, like max_slots); the policy's token budget is capped here
        self.prefill_rows = (max(self.prefill_chunk, int(budget_cap or 0))
                             if self.ragged else 0)
        # distinct prefilling requests packable into one tick — the
        # compressed-table capacity of the ragged program (one table row
        # per request; rows of a request share it)
        self._pre_tables_cap = (self.prefill_rows // self.prefill_chunk + 1
                                if self.ragged else 0)
        self.pages_per_seq = -(-self.max_seq // self.page_size)
        num_pages = (num_pages or inf.kv_pool_pages
                     or self.max_slots * self.pages_per_seq + 1)
        # quantized paged KV (ISSUE 13, ops/kv_quant.py): int8/fp8 pages
        # with per-page scales multiply the concurrent slots a fixed pool
        # byte budget carries; bf16 (default) is byte-for-byte today's
        # engine.  Target AND draft caches quantize together — one flag,
        # one storage discipline for every page.
        self.kv_dtype = (kv_dtype if kv_dtype is not None
                         else getattr(inf, "kv_dtype", "bf16"))
        # pipelined multi-tick dispatch (ISSUE 17): keep one N-tick
        # CHAINED launch in flight and apply its results at a one-launch
        # lag, so per-tick host work (scheduling, emission fetch, apply)
        # amortizes 1/N.  0 = today's one-tick-per-launch driver, byte
        # for byte.  Speculative decoding keeps depth-0 stepping — its
        # adaptive k_eff needs per-tick acceptance counts on the host.
        self.pipeline_depth = max(0, int(
            tick_pipeline_depth if tick_pipeline_depth is not None
            else getattr(inf, "tick_pipeline_depth", 0)))
        if self._pp > 1:
            # the monolithic dense prefill (init_kv_caches + cache_index)
            # has no stage decomposition — pp serving requires the
            # block-table chunked prefill path
            assert self.prefill_chunk, (
                "pipeline-parallel serving requires chunked prefill "
                "(prefill_chunk > 0)")
            if self.draft_cfg is not None:
                assert self.draft_cfg.model.num_layers % self._pp == 0, (
                    f"draft num_layers {self.draft_cfg.model.num_layers} "
                    f"not divisible by pp {self._pp}")
        self.pool = PagedKVPool(cfg, num_pages, self.page_size, mesh=mesh,
                                draft_cfg=self.draft_cfg,
                                kv_dtype=self.kv_dtype)
        # the prefix cache needs the block-table prefill path: a monolithic
        # dense prefill recomputes and rewrites the whole prompt, shared
        # pages included
        self.cache = (PrefixCache(self.pool, self.page_size)
                      if use_cache and self.prefill_chunk else None)

        # host-side slot state + scheduler queues: every attribute marked
        # "guarded by _lock" below is shared between submitter threads,
        # the background scheduler and drive-through callers — graftcheck's
        # lock-discipline rule enforces the with-blocks / '# holds'
        # annotations (docs/guide/static-analysis.md)
        s = self.max_slots
        # guarded by _lock
        self._block_tables = np.zeros((s, self.pages_per_seq), np.int32)
        self._positions = np.zeros((s,), np.int32)    # guarded by _lock
        self._tokens = np.zeros((s,), np.int32)       # guarded by _lock
        self._temperature = np.ones((s,), np.float32)  # guarded by _lock
        # idle slots decode greedy — guarded by _lock
        self._top_k = np.ones((s,), np.int32)
        self._top_p = np.zeros((s,), np.float32)      # guarded by _lock
        self._keys = np.zeros((s, 2), np.uint32)      # guarded by _lock
        self._steps = np.zeros((s,), np.int32)        # guarded by _lock
        # guarded by _lock
        self._slots: List[Optional[EngineRequest]] = [None] * s

        self._queue: deque = deque()  # guarded by _lock
        # admitted, prompt not yet filled — guarded by _lock
        self._prefill_q: deque = deque()
        # worst-case pages admitted-but-not-yet-held; admission keeps
        # free + evictable >= committed (+ watermark) so decode-time allocs
        # can never deadlock an in-flight slot — guarded by _lock
        self._committed = 0
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        # serializes device-driving (step) across caller threads; state
        # mutation is under _lock, device dispatch under _drive_lock
        self._drive_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stopping = False  # guarded by _lock

        self._tick_fn = None
        self._spec_tick_fn = None
        # ragged tick executables keyed by bucketed live-prefill-row
        # count — bounded at 1 + prefill_rows // prefill_chunk entries
        self._ragged_fns: Dict[int, object] = {}
        self._prefill_fns: Dict[Tuple[int, bool], object] = {}
        self._chunk_fns: Dict[Tuple[int, int, bool], object] = {}
        self._copy_fn = None
        # device mirror of the per-slot arrays; rebuilt from the host copies
        # whenever admission/retirement changes the slot layout
        self._dev_state: Optional[Tuple] = None  # guarded by _lock
        self._dirty = True  # guarded by _lock
        # pipelined dispatch state (ISSUE 17).  _inflight holds launched-
        # but-unapplied chained launches as (active slots, request
        # identities, device tokens [C,b], device log-probs [C,b],
        # launch time); _pipe_state is the device-resident
        # (term_ids, stop_modes, done, remaining) carry the next chain
        # consumes — None means the next launch must rebuild it from the
        # (then-current) host mirrors — guarded by _lock
        self._inflight: deque = deque()
        self._pipe_state: Optional[Tuple] = None  # guarded by _lock
        self._chained_fn = None
        # inter-launch host-gap samples for the pipeline bench (bounded;
        # host_gap_stats() summarizes) — guarded by _lock
        self._host_gaps: deque = deque(maxlen=4096)
        # wall time the last device dispatch call returned (driver-thread
        # only; reads/writes serialize under _drive_lock)
        self._last_dispatch_end: Optional[float] = None
        # tick/cache telemetry for the decode bench
        self.ticks = 0
        self.ticked_tokens = 0
        # attention-program launches in the tick phase (ISSUE 11): ragged
        # ticks dispatch ONE compiled program per tick; the legacy split
        # path dispatches the decode/spec tick plus one program per
        # prefill chunk.  last_tick_launches is the most recent step's
        # count — the single-launch claim tests assert on.
        self.tick_launches = 0
        self.last_tick_launches = 0
        # capacity telemetry (ISSUE 13): the high-water mark of
        # concurrently-decoding slots — THE "concurrent users per chip"
        # number the fixed-pool-bytes capacity bench and /health report
        self.peak_active_slots = 0  # guarded by _lock
        self.prefill_tokens_computed = 0  # rows pushed through prefill
        self.prefix_hit_tokens = 0
        self.prefix_miss_tokens = 0
        self.cow_copies = 0
        # scheduler telemetry (bench_decode --mode slo + /health payload)
        self.preemptions = 0
        self.shed_requests = 0
        self.deadline_misses = 0
        # speculative-decoding telemetry (bench_decode --mode spec +
        # /health spec payload)
        self.spec_ticks = 0
        self.spec_draft_tokens = 0     # drafts proposed (sum of k_eff)
        self.spec_accepted_tokens = 0  # drafts the target accepted
        self.spec_emitted_tokens = 0   # tokens emitted by spec ticks
        # submit order, stable policy tie-break — guarded by _lock
        self._seqno = 0
        # decode-tick wall EMA — guarded by _lock
        self._ema_tick_s: Optional[float] = None
        # inter-retire EMA — guarded by _lock
        self._ema_retire_s: Optional[float] = None
        self._last_retire_t: Optional[float] = None  # guarded by _lock
        # submit-to-first-token EMA: the replica's REAL first-token time
        # (published in /health so the router's slo_aware predictions use
        # measured TTFT, not time-to-response) — guarded by _lock
        self._ema_ttft_s: Optional[float] = None
        # flight recorder (ISSUE 12, observability/flight.py): one
        # bounded event log + latency decomposition per request, served
        # on /debug/requests and dumped by the watchdog.  0 records
        # disables it (every call site degrades to the null record).
        n_rec = (flight_records if flight_records is not None
                 else getattr(inf, "flight_records", 256))
        n_ev = (flight_events if flight_events is not None
                else getattr(inf, "flight_events", 64))
        self.flight = obs_flight.FlightRecorder(
            capacity=n_rec, events_per_request=n_ev, enabled=n_rec > 0)
        obs_flight.set_recorder(self.flight)
        # label sets ever published — guarded by _lock
        self._queued_prios: Set[int] = set()
        # registry instruments, resolved once (observability/registry.py):
        # per-tick updates must stay dict-free on the scheduler thread
        reg = obs_registry.get_registry()
        self._m_requests = reg.counter(
            "mlt_engine_requests_total", help="generations submitted")
        self._m_ticks = reg.counter(
            "mlt_engine_ticks_total", help="fused decode ticks run")
        self._m_tokens = reg.counter(
            "mlt_engine_ticked_tokens_total",
            help="slot-steps advanced (tokens sampled) across ticks")
        self._m_active = reg.gauge(
            "mlt_engine_active_slots", help="decode slots occupied")
        self._m_queued = reg.gauge(
            "mlt_engine_queued_requests", help="requests awaiting a slot")
        self._m_free_pages = reg.gauge(
            "mlt_engine_free_pages", help="KV pool pages free")
        self._m_hit_tokens = reg.counter(
            "mlt_engine_prefix_hit_tokens_total",
            help="prompt tokens served from the prefix cache")
        self._m_miss_tokens = reg.counter(
            "mlt_engine_prefix_miss_tokens_total",
            help="prompt tokens that had to be prefilled")
        self._m_pages_cached = reg.gauge(
            "mlt_engine_pages_cached",
            help="pool pages registered in the prefix cache")
        self._m_cow = reg.counter(
            "mlt_engine_pages_cow_copies_total",
            help="copy-on-write page copies (shared page would be written)")
        self._m_prefill_tokens = reg.counter(
            "mlt_engine_prefill_tokens_total",
            help="token rows pushed through prefill (chunked or monolithic)")
        self._m_launches = reg.counter(
            "mlt_engine_tick_launches_total",
            help="attention-program launches in the tick phase (ragged "
                 "mode: exactly one per non-idle tick)")
        self._m_prefill_per_tick = reg.histogram(
            "mlt_engine_prefill_tokens_per_tick",
            help="prompt tokens prefilled per tick (token-level "
                 "prefill_budget control; observed on ticks that prefill)",
            buckets=[16.0, 32.0, 64.0, 128.0, 192.0, 256.0, 512.0,
                     1024.0])
        self._m_preempt = reg.counter(
            "mlt_engine_preemptions_total",
            help="decoding requests preempted by page release")
        self._m_shed = reg.counter(
            "mlt_engine_shed_total",
            help="queued requests shed (unmeetable deadline / load)")
        self._m_ttft = reg.histogram(
            "mlt_engine_ttft_seconds",
            help="submit-to-first-token latency of retired requests")
        self._m_miss_ttft = reg.counter(
            "mlt_engine_deadline_miss_total",
            help="retired requests that missed a declared deadline",
            labels={"kind": "ttft"})
        self._m_miss_tpot = reg.counter(
            "mlt_engine_deadline_miss_total",
            help="retired requests that missed a declared deadline",
            labels={"kind": "tpot"})
        # honest TTFT decomposition (ISSUE 12): where retired requests'
        # first-token latency actually went.  The phase-attributed
        # deadline-miss children ({kind,phase}) are created lazily at
        # miss time; the {kind}-only children above stay the totals.
        self._m_queue_wait = reg.histogram(
            "mlt_engine_queue_wait_seconds",
            help="submit-to-admission wait of retired requests (flight-"
                 "recorder queued-phase bucket)")
        self._m_prefill_compute = reg.histogram(
            "mlt_engine_prefill_compute_seconds",
            help="prefill-phase seconds of retired requests (admission "
                 "to decode activation)")
        self._m_preempted_s = reg.histogram(
            "mlt_engine_preempted_seconds",
            help="seconds retired requests spent preempted (observed "
                 "only for requests that were preempted at least once)")
        # pipelined-dispatch telemetry (ISSUE 17): the host gap is the
        # wall time between one tick launch returning and the next being
        # dispatched — scheduling + emission fetch + apply, THE overhead
        # --tick_pipeline_depth amortizes across a chain
        self._m_host_gap = reg.histogram(
            "mlt_engine_host_gap_seconds",
            help="host time between consecutive tick-program dispatches "
                 "(fetch + apply + scheduling; pipelining amortizes it)",
            buckets=[1e-5, 3e-5, 1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2,
                     0.1, 0.3])
        self._m_inflight = reg.gauge(
            "mlt_engine_inflight_ticks",
            help="device ticks launched but not yet applied "
                 "(--tick_pipeline_depth chains in flight)")
        # token streaming (ISSUE 18, serving/streaming/): live
        # subscriptions + incremental events shed by slow consumers
        # (drop-to-terminal — the terminal event is never shed)
        self._stream_subs = 0  # live submit_stream queues — guarded by _lock
        self._m_stream_subs = reg.gauge(
            "mlt_engine_stream_subscribers",
            help="live submit_stream subscriptions (emission queues "
                 "attached to in-flight requests)")
        self._m_stream_dropped = reg.counter(
            "mlt_engine_stream_dropped_events_total",
            help="incremental stream events shed because a consumer "
                 "fell behind its bounded emission queue")
        reg.gauge("mlt_engine_tick_pipeline_depth",
                  help="configured chained-ticks-per-launch depth "
                       "(--tick_pipeline_depth; 0 = unpipelined)"
                  ).set(self.pipeline_depth)
        # cross-replica KV handoff (ISSUE 19, serving/handoff/): pages
        # and wire bytes this engine exported (prefill role) / imported
        # (decode role, /admin/kv_push)
        self._m_kv_export_pages = reg.counter(
            "mlt_engine_kv_export_pages_total",
            help="KV pool pages exported for cross-replica handoff")
        self._m_kv_export_bytes = reg.counter(
            "mlt_engine_kv_export_bytes_total",
            help="wire bytes of exported KV handoff blobs")
        self._m_kv_import_pages = reg.counter(
            "mlt_engine_kv_import_pages_total",
            help="KV pool pages installed from pushed handoff blobs "
                 "(deduped pages excluded)")
        self._m_kv_import_bytes = reg.counter(
            "mlt_engine_kv_import_bytes_total",
            help="wire bytes of imported KV handoff blobs")
        # speculative-decoding instruments, registered only when the spec
        # path can run (mlt_engine_spec_* stays absent from scrapes of
        # non-speculating engines)
        self._m_spec_draft = self._m_spec_accepted = None
        self._m_spec_ratio = self._m_spec_len = None
        if self.spec_k:
            self._m_spec_draft = reg.counter(
                "mlt_engine_spec_draft_tokens_total",
                help="draft tokens proposed to the verifier")
            self._m_spec_accepted = reg.counter(
                "mlt_engine_spec_accepted_tokens_total",
                help="draft tokens the target model accepted")
            self._m_spec_ratio = reg.histogram(
                "mlt_engine_spec_acceptance_ratio",
                help="per-slot-tick accepted/drafted fraction",
                buckets=[0.0, 0.125, 0.25, 0.375, 0.5, 0.625, 0.75,
                         0.875, 1.0])
            self._m_spec_len = reg.histogram(
                "mlt_engine_spec_accepted_length",
                help="tokens emitted per slot per speculative tick",
                buckets=[float(i) for i in range(1, self.spec_k + 2)])
            reg.gauge("mlt_engine_spec_k",
                      help="speculation depth cap (--spec_k)"
                      ).set(self.spec_k)
        reg.gauge("mlt_engine_sched_policy_info",
                  help="active scheduling policy (value always 1)",
                  labels={"policy": self.policy.name}).set(1)
        reg.gauge("mlt_engine_max_slots",
                  help="decode slots in the tick program").set(self.max_slots)
        reg.gauge("mlt_engine_pool_pages",
                  help="allocatable KV pool pages (null page excluded)"
                  ).set(self.pool.num_pages - 1)
        # quantized-KV capacity telemetry (ISSUE 13): the byte budget the
        # pool occupies (values, target + draft) and the per-page scale
        # overhead, so capacity dashboards and the router can reason in
        # bytes; the kv_dtype info gauge names the storage mode
        reg.gauge("mlt_engine_kv_pool_bytes",
                  help="device bytes of KV value storage (target + draft)"
                  ).set(self.pool.kv_pool_bytes())
        reg.gauge("mlt_engine_kv_scale_bytes",
                  help="device bytes of per-page quantization scales "
                       "(0 for bf16)").set(self.pool.kv_scale_bytes())
        reg.gauge("mlt_engine_kv_dtype_info",
                  help="KV storage mode (value always 1)",
                  labels={"kv_dtype": self.kv_dtype}).set(1)
        # pipeline-parallel serving telemetry (ISSUE 20): stage count of
        # the compiled tick (1 = flat TP-only engine) and the per-stage
        # slice of the pool byte budget — the number a pp replica's HBM
        # actually holds (the servable-model-size multiplier)
        reg.gauge("mlt_engine_pp_stages",
                  help="pipeline stages in the serving tick "
                       "(pp mesh axis; 1 = unpipelined)").set(self._pp)
        reg.gauge("mlt_engine_kv_stage_bytes",
                  help="per-stage device bytes of KV value storage "
                       "(kv_pool_bytes / pp)"
                  ).set(self.pool.kv_stage_bytes())
        if mesh is not None:
            for ax, size in dict(mesh.shape).items():
                reg.gauge("mlt_mesh_axis_size", help="mesh axis size",
                          labels={"axis": str(ax)}).set(size)
        # compute/collective overlap telemetry (ISSUE 15): which overlap
        # mode this engine's compiled programs were built with — asserted
        # by the /metrics scrape test and the bench_tp overlap arm
        reg.gauge("mlt_tp_overlap_info",
                  help="TP compute/collective overlap mode of the "
                       "compiled forward (value always 1)",
                  labels={"mode": self._overlap_mode,
                          "tp": str(self._tp)}).set(1)

    def _asarray(self, x):
        """Host -> device for tick/prefill operands: mesh-replicated when a
        mesh is active (slot vectors, block tables, token rows are identical
        on every shard), plain asarray otherwise."""
        a = jnp.asarray(x)
        if self._repl is not None:
            a = jax.device_put(a, self._repl)
        return a

    def _overlap_span(self):
        """Tracer span marking an overlapped forward dispatch
        (``forward-tp{N}-overlap`` — the observable the ISSUE 15
        acceptance asserts in trace dumps); a no-op context when overlap
        is off, so plain engines emit nothing new."""
        import contextlib

        if self._overlap is None or not self._overlap.ring_rows:
            return contextlib.nullcontext()
        from megatron_llm_tpu.parallel.overlap import overlap_scope_name

        return obs_trace.span(overlap_scope_name(self._tp), mode="ring",
                              tp=self._tp)

    def _pp_span(self):
        """Tracer span marking a pipeline-parallel tick dispatch
        (``engine-pp-tick`` with pp/stages/tp attrs — the observable the
        ISSUE 20 satellite asserts in trace dumps); a no-op context on
        flat engines, so pp=1 dispatch emits nothing new."""
        import contextlib

        if self._pp <= 1:
            return contextlib.nullcontext()
        return obs_trace.span("engine-pp-tick", pp=self._pp,
                              stages=self._pp, tp=self._tp)

    @property
    def _mesh_statics(self) -> Tuple:
        """Compiled-program cache key extension: engines on different mesh
        layouts must not share executables (gen.cached_jit is process-wide).
        The EFFECTIVE overlap modes ride in the key too — an overlap (or
        vocab-ring) engine's ring programs and a plain engine's GSPMD
        programs have identical signatures, and the fingerprint alone
        cannot separate engines whose cfg matches but whose mesh makes the
        flag inert.  pp geometry needs no extra component: build_mesh
        always materializes the pp axis, so a pp=2 engine's shape tuple
        (("cp",1),("dp",1),("ep",1),("pp",2),("tp",1)) already diverges
        from every flat engine's — pinned by tests/test_pp_serve.py."""
        if self.mesh is None:
            return ("mesh", None, "vocab_ring", "off", "tp_overlap", "off")
        return ("mesh", tuple(sorted(dict(self.mesh.shape).items())),
                "vocab_ring", "ring" if self._vocab_ring else "off",
                "tp_overlap", self._overlap_mode)

    # -- compiled programs -------------------------------------------------

    def _tick(self):
        """The fused decode-tick program, compiled once per (config, engine
        geometry) — shared ACROSS engine instances via the fingerprint-keyed
        generation cache, so rebuilding an engine never recompiles."""
        if self._tick_fn is not None:
            return self._tick_fn
        cfg = self.cfg
        m = cfg.model

        # scope name carries the tp degree: the row-parallel all-reduces
        # GSPMD inserts under a tp>1 mesh inherit it in HLO op metadata,
        # so device profiles attribute them to the decode forward
        scope = ("decode-fwd" if self._tp == 1
                 else f"decode-fwd-tp{self._tp}")
        from megatron_llm_tpu.parallel import overlap as tp_overlap_mod
        from megatron_llm_tpu.parallel import pp_serve as pp_serve_mod

        ovl = self._overlap
        ppc = self._ppc

        def tick(params, pool_k, pool_v, block_tables, positions, tokens,
                 req_keys, steps, temperature, top_k, top_p):
            rope = make_rope_cache(cfg)
            with jax.named_scope(scope), tp_overlap_mod.activate(ovl), \
                    pp_serve_mod.activate(ppc):
                logits, (pool_k, pool_v) = model_forward(
                    cfg, params, tokens[:, None],
                    position_ids=positions[:, None],
                    rope_cache=rope, kv_caches=(pool_k, pool_v),
                    paged=PagedState(block_tables, positions),
                )
            last = logits[:, -1]
            keys = jax.vmap(jax.random.fold_in)(req_keys, steps)
            next_tok = sample_per_slot(
                keys, last, top_k=top_k, top_p=top_p,
                temperature=temperature, vocab_size=m.vocab_size)
            logp = gen._gather_token_log_probs(last, next_tok)
            # advance the device-resident slot state in-program so steady
            # ticks need no host->device uploads (step() re-uploads from the
            # host copy only after admit/retire dirties the layout)
            return (pool_k, pool_v, next_tok, logp,
                    positions + 1, steps + 1)

        statics = ("engine_tick", self.max_slots, self.pages_per_seq,
                   self.page_size, self.pool.num_pages,
                   self.pool.kv_statics, self._mesh_statics)
        self._tick_fn = gen.cached_jit(
            self.cfg, "engine_tick", statics, lambda: tick,
            donate_argnums=(1, 2))
        return self._tick_fn

    def _spec_tick(self):
        """The fused draft-k-then-verify tick for the LEGACY split
        dispatch: the ragged builder at prefill-row capacity 0 — one
        compiled program drafts ``spec_k`` tokens per slot, verifies all
        k+1 positions in a single flattened-batch target forward, and
        applies the lossless acceptance rule.  Cache key carries the
        DRAFT config fingerprint too — engines speculating with different
        drafts must not share executables."""
        if self._spec_tick_fn is not None:
            return self._spec_tick_fn
        from megatron_llm_tpu.generation.ragged import make_ragged_tick_fn

        statics = ("engine_spec_tick", self.max_slots, self.pages_per_seq,
                   self.page_size, self.pool.num_pages,
                   self.pool.kv_statics, self.spec_k,
                   gen.config_fingerprint(self.draft_cfg),
                   self.pool.draft_kv_statics, self._mesh_statics)
        self._spec_tick_fn = gen.cached_jit(
            self.cfg, "engine_spec_tick", statics,
            lambda: make_ragged_tick_fn(self.cfg, self.draft_cfg,
                                        self.spec_k, 0, tp=self._tp,
                                        mesh=self.mesh),
            donate_argnums=(2, 3, 4, 5))
        return self._spec_tick_fn

    def _ragged_tick(self, pre_rows: int):
        """THE ragged-mode tick (generation/ragged.py): decode slots,
        verify blocks and ``pre_rows`` prefill-chunk rows in ONE compiled
        launch.  Every piece of tick composition — which slots decode,
        per-slot speculation depth, which prompt positions prefill, their
        block tables and kv horizons — is a traced operand (never a
        static; graftcheck's recompile-hazard rule flags ragged metadata
        that strays into the statics key).  The ONLY shape is
        ``pre_rows``, the live prefill-row count bucketed to
        ``prefill_chunk`` multiples: a BOUNDED set of at most
        ``1 + prefill_rows // prefill_chunk`` executables (0 rows = the
        pure decode/verify tick, byte-identical shape to the legacy tick),
        so a decode-heavy tick never pays for dead prefill rows and tick
        composition changes re-dispatch, never recompile
        (tests/test_ragged_tick.py pins the bound)."""
        fn = self._ragged_fns.get(pre_rows)
        if fn is not None:
            return fn
        from megatron_llm_tpu.generation.ragged import make_ragged_tick_fn

        if self.spec_k:
            statics = ("engine_ragged_tick", self.max_slots,
                       self.pages_per_seq, self.page_size,
                       self.pool.num_pages, self.pool.kv_statics,
                       self.spec_k, pre_rows, self._pre_tables_cap,
                       gen.config_fingerprint(self.draft_cfg),
                       self.pool.draft_kv_statics, self._mesh_statics)
            fn = gen.cached_jit(
                self.cfg, "engine_ragged_tick", statics,
                lambda: make_ragged_tick_fn(
                    self.cfg, self.draft_cfg, self.spec_k,
                    pre_rows, tp=self._tp, mesh=self.mesh),
                donate_argnums=(2, 3, 4, 5))
        else:
            statics = ("engine_ragged_tick", self.max_slots,
                       self.pages_per_seq, self.page_size,
                       self.pool.num_pages, self.pool.kv_statics,
                       0, pre_rows, self._pre_tables_cap,
                       self._mesh_statics)
            fn = gen.cached_jit(
                self.cfg, "engine_ragged_tick", statics,
                lambda: make_ragged_tick_fn(
                    self.cfg, None, 0, pre_rows, tp=self._tp,
                    mesh=self.mesh),
                donate_argnums=(1, 2))
        self._ragged_fns[pre_rows] = fn
        return fn

    def _chained_tick(self):
        """The CHAINED steady-state tick (ISSUE 17,
        generation/ragged.py:make_chained_tick_fn): ``pipeline_depth``
        consecutive decode ticks as one compiled program, with position
        advance, stop detection and the remaining-token budget running
        device-to-device.  Chain length is a geometry static (one
        executable per depth); everything else — which rows are live,
        their stop rules, budgets and tables — is traced data."""
        if self._chained_fn is not None:
            return self._chained_fn
        from megatron_llm_tpu.generation.ragged import make_chained_tick_fn

        statics = ("engine_chained_tick", self.max_slots,
                   self.pages_per_seq, self.page_size,
                   self.pool.num_pages, self.pool.kv_statics,
                   self.pipeline_depth, self._mesh_statics)
        self._chained_fn = gen.cached_jit(
            self.cfg, "engine_chained_tick", statics,
            lambda: make_chained_tick_fn(self.cfg, self.pipeline_depth,
                                         tp=self._tp, mesh=self.mesh),
            donate_argnums=(1, 2))
        return self._chained_fn

    def _prefill(self, s_pre: int, with_log_probs: bool):
        """Monolithic dense prefill (the ``prefill_chunk=0`` legacy path):
        one dense-cache forward over the bucketed prompt, scattered into the
        request's pages as whole pages."""
        key = (s_pre, with_log_probs)
        fn = self._prefill_fns.get(key)
        if fn is not None:
            return fn
        cfg = self.cfg
        L = cfg.model.num_layers
        nkv, d = cfg.model.num_attention_heads_kv, cfg.model.kv_channels
        page = self.page_size
        npg = s_pre // page
        # the dense scratch cache always computes in the compute dtype;
        # quantized pools quantize whole pages at the scatter (bf16 pools:
        # pool dtype == compute dtype, the original expression bitwise)
        cache_dtype = self.pool.compute_dtype

        from megatron_llm_tpu.parallel import overlap as tp_overlap_mod

        ovl = self._overlap

        def prefill(params, tokens, pool_k, pool_v, page_ids):
            caches = gen.init_kv_caches(cfg, 1, s_pre, cache_dtype)
            with tp_overlap_mod.activate(ovl):
                out, (ck, cv) = model_forward(
                    cfg, params, tokens,
                    position_ids=jnp.arange(s_pre)[None, :],
                    rope_cache=make_rope_cache(cfg),
                    kv_caches=caches, cache_index=jnp.int32(0),
                    logits_postprocess=with_log_probs,
                )
            pages_k = ck.reshape(L, npg, page, nkv, d)
            pages_v = cv.reshape(L, npg, page, nkv, d)
            pool_k = kv_quant.scatter_whole_pages(pool_k, page_ids, pages_k)
            pool_v = kv_quant.scatter_whole_pages(pool_v, page_ids, pages_v)
            if with_log_probs:
                # teacher-forced prompt log-probs (api logprobs contract)
                lp = gen._gather_token_log_probs(out[:, :-1], tokens[:, 1:])
                return pool_k, pool_v, lp[0]
            return pool_k, pool_v

        statics = (s_pre, with_log_probs, self.page_size,
                   self.pool.num_pages, self.pool.kv_statics,
                   self._mesh_statics)
        fn = gen.cached_jit(self.cfg, "engine_prefill", statics,
                            lambda: prefill, donate_argnums=(2, 3))
        self._prefill_fns[key] = fn
        return fn

    def _chunk_prefill(self, rows: int, kv_pages: int, with_log_probs: bool):
        """One prefill CHUNK: feed ``rows`` prompt tokens at positions
        ``start..start+rows-1`` through the block table (write K/V into the
        owned pages, attend over the first ``kv_pages`` pages).  Compiled
        per (rows, page horizon) — both page-aligned and horizon bucketed,
        so a server sees a handful of shapes."""
        key = (rows, kv_pages, with_log_probs)
        fn = self._chunk_fns.get(key)
        if fn is not None:
            return fn
        cfg = self.cfg
        draft_cfg = self.draft_cfg
        from megatron_llm_tpu.parallel import overlap as tp_overlap_mod
        from megatron_llm_tpu.parallel import pp_serve as pp_serve_mod

        ovl = self._overlap
        ppc = self._ppc

        def chunk(params, tokens, start, bt, pool_k, pool_v, targets):
            with tp_overlap_mod.activate(ovl), pp_serve_mod.activate(ppc):
                out, (pool_k, pool_v) = model_forward(
                    cfg, params, tokens,
                    position_ids=start[:, None] + jnp.arange(rows)[None, :],
                    rope_cache=make_rope_cache(cfg),
                    kv_caches=(pool_k, pool_v),
                    paged=PagedState(bt, start),
                    logits_postprocess=with_log_probs,
                )
            if with_log_probs:
                lp = gen._gather_token_log_probs(out, targets)
                return pool_k, pool_v, lp[0]
            return pool_k, pool_v

        def chunk_spec(params, draft_params, tokens, start, bt,
                       pool_k, pool_v, draft_k, draft_v, targets):
            # target chunk plus the DRAFT model's chunk through the same
            # block table: a speculating engine keeps both caches filled
            # for every prefilled page, so trie-matched pages (prefix hits,
            # preemption resume) carry valid draft K/V too
            res = chunk(params, tokens, start, bt, pool_k, pool_v, targets)
            with tp_overlap_mod.activate(ovl), pp_serve_mod.activate(ppc):
                _, (draft_k, draft_v) = model_forward(
                    draft_cfg, draft_params, tokens,
                    position_ids=start[:, None] + jnp.arange(rows)[None, :],
                    rope_cache=make_rope_cache(draft_cfg),
                    kv_caches=(draft_k, draft_v),
                    paged=PagedState(bt, start),
                    logits_postprocess=False,
                )
            return res[:2] + (draft_k, draft_v) + res[2:]

        statics = ("engine_prefill_chunk", rows, kv_pages, with_log_probs,
                   self.page_size, self.pool.num_pages,
                   self.pool.kv_statics, self._mesh_statics)
        if self.spec_k:
            statics += ("spec", gen.config_fingerprint(draft_cfg))
            fn = gen.cached_jit(self.cfg, "engine_prefill_chunk", statics,
                                lambda: chunk_spec,
                                donate_argnums=(5, 6, 7, 8))
        else:
            fn = gen.cached_jit(self.cfg, "engine_prefill_chunk", statics,
                                lambda: chunk, donate_argnums=(4, 5))
        self._chunk_fns[key] = fn
        return fn

    def _copy_page(self):
        """Device page copy for copy-on-write (src/dst are traced scalars —
        one compile serves every copy)."""
        if self._copy_fn is not None:
            return self._copy_fn

        def copy(pool_k, pool_v, src, dst):
            # tree-mapped so quantized pools clone the page's scale row
            # together with its values (plain pools: one leaf, the
            # original expression bitwise) — a COW page is byte-identical
            # to its source in BOTH leaves, so the refeed rewrite sees
            # exactly the shared page's quantization state
            pool_k = jax.tree.map(
                lambda a: a.at[:, dst].set(a[:, src]), pool_k)
            pool_v = jax.tree.map(
                lambda a: a.at[:, dst].set(a[:, src]), pool_v)
            return pool_k, pool_v

        def copy_spec(pool_k, pool_v, draft_k, draft_v, src, dst):
            # COW must clone the page in BOTH caches: the refeed tick
            # rewrites the draft K/V at the same position too
            pool_k, pool_v = copy(pool_k, pool_v, src, dst)
            draft_k, draft_v = copy(draft_k, draft_v, src, dst)
            return pool_k, pool_v, draft_k, draft_v

        statics = ("engine_copy_page", self.pool.num_pages, self.page_size,
                   self.pool.kv_statics, self._mesh_statics)
        if self.spec_k:
            statics += ("spec", gen.config_fingerprint(self.draft_cfg))
            self._copy_fn = gen.cached_jit(
                self.cfg, "engine_copy_page", statics, lambda: copy_spec,
                donate_argnums=(0, 1, 2, 3))
        else:
            self._copy_fn = gen.cached_jit(
                self.cfg, "engine_copy_page", statics, lambda: copy,
                donate_argnums=(0, 1))
        return self._copy_fn

    # -- request lifecycle -------------------------------------------------

    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               **kw) -> EngineRequest:
        """Enqueue a generation; returns the request future.

        Raises ValueError for requests that can never fit (the legacy
        engine's request-size guard, generation/api._check_limits) and
        :class:`EngineOverloaded` when the queue is at capacity."""
        prompt = [int(t) for t in prompt]
        if len(prompt) < 1:
            raise ValueError("prompt must contain at least one token")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(prompt) + max_new_tokens > self.max_seq:
            raise ValueError(
                "Length of prompt + tokens_to_generate longer than allowed")
        req = EngineRequest(prompt=prompt, max_new_tokens=max_new_tokens, **kw)
        req._t_submit = time.monotonic()
        # flight record + enqueue event (observability/flight.py): a
        # request turned away at the door still leaves a record, so an
        # overload burst is reconstructable from /debug/requests
        req._flight = self.flight.open(
            req.trace_id, prompt_tokens=len(prompt),
            max_new_tokens=max_new_tokens, priority=req.priority,
            t_submit=req._t_submit)
        with obs_trace.span("engine-enqueue", prompt_len=len(prompt),
                            trace_id=req.trace_id):
            with self._work:
                if self.max_queue and len(self._queue) >= self.max_queue:
                    req._flight.finish("overload",
                                       queued=len(self._queue))
                    self.flight.close(req._flight)
                    raise EngineOverloaded(
                        f"request queue full ({self.max_queue} waiting)",
                        retry_after=self._drain_eta(len(self._queue)),
                        info=self._overload_info())
                quota = self._quota.get(req.priority)
                if quota is not None:
                    depth = sum(1 for r in self._queue
                                if r.priority == req.priority)
                    if depth >= quota:
                        req._flight.finish("overload", queued=depth,
                                           quota=quota)
                        self.flight.close(req._flight)
                        raise EngineOverloaded(
                            f"priority-{req.priority} queue full "
                            f"({quota} waiting)",
                            retry_after=self._drain_eta(depth),
                            info=self._overload_info())
                self._seqno += 1
                req._seqno = self._seqno
                self._queue.append(req)
                req._flight.event("enqueue", queued=len(self._queue))
                if req._stream is not None:
                    self._stream_subs += 1
                    if obs_registry.publishing():
                        self._m_stream_subs.set(self._stream_subs)
                if obs_registry.publishing():
                    self._m_requests.inc()
                self._publish_queued_locked()
                self._work.notify()
        return req

    def submit_stream(self, prompt: Sequence[int], max_new_tokens: int,
                      *, stream_events: int = 256, **kw):
        """Enqueue a generation with a live token stream attached.

        Returns ``(req, queue)`` — the same :class:`EngineRequest` future
        ``submit`` returns plus the :class:`StreamQueue
        <megatron_llm_tpu.serving.streaming.StreamQueue>` the apply paths
        feed under the engine lock: one ``token`` event per applied batch
        (chained dispatch retires several tokens per flush), then exactly
        one terminal ``done``/``error`` event carrying the flight-record
        timing payload.  ``stream_events`` bounds the queue; a consumer
        that falls behind sheds incremental events (counted in
        ``mlt_engine_stream_dropped_events_total`` and in the terminal
        event's ``dropped_events``) but always gets the terminal —
        drop-to-terminal, never backpressure into the tick loop."""
        from megatron_llm_tpu.serving.streaming import StreamQueue

        q = StreamQueue(maxsize=stream_events)
        req = self.submit(prompt, max_new_tokens, _stream=q, **kw)
        return req, q

    def _stream_emit_locked(self, req: EngineRequest, tokens,
                            log_probs) -> None:  # holds _lock
        """Publish one incremental token batch to the request's stream
        (no-op for plain submits).  The queue is a leaf lock and the
        publish never blocks — the committed lock-order edge
        ContinuousBatchingEngine._lock -> StreamQueue._lock mirrors the
        engine→FlightRecorder discipline."""
        q = req._stream
        if q is None or not tokens:
            return
        shed = q.publish_tokens(tokens, log_probs)
        if shed and obs_registry.publishing():
            self._m_stream_dropped.inc(shed)

    def _stream_finish_locked(self, req: EngineRequest, kind: str,
                              **data) -> None:  # holds _lock
        """Publish the terminal stream event and detach the queue (a
        request reaches exactly one of _retire/_fail_locked/_shed_locked,
        but detaching keeps a double finish structurally impossible)."""
        q = req._stream
        if q is None:
            return
        req._stream = None
        self._stream_subs -= 1
        if obs_registry.publishing():
            self._m_stream_subs.set(self._stream_subs)
        from megatron_llm_tpu.serving.streaming import StreamEvent

        q.publish_terminal(StreamEvent(kind, data=data))

    def _drain_eta(self, depth: int) -> float:  # holds _lock
        """Seconds until ``depth`` queued requests likely drain — the
        EMA retirement interval (tick EMA before any retirement), clamped
        to [1, 60].  This is the Retry-After a 503 carries, so it tracks
        load instead of being a constant."""
        per = (self._ema_retire_s if self._ema_retire_s is not None
               else self._ema_tick_s)
        if per is None:
            return 1.0
        return min(60.0, max(1.0, depth * per))

    def _overload_info(self) -> dict:  # holds _lock
        return {"queued": len(self._queue), "policy": self.policy.name,
                "active_slots": sum(r is not None for r in self._slots)}

    def _note_ttft_locked(self, ttft_s: float) -> None:  # holds _lock
        """Feed the real first-token EMA (published in /health; the
        router's slo_aware wait predictions consume it)."""
        self._ema_ttft_s = (ttft_s if self._ema_ttft_s is None
                            else 0.7 * self._ema_ttft_s + 0.3 * ttft_s)

    def _publish_queued_locked(self, force: bool = False) -> None:  # holds _lock
        """THE queue-depth gauge update point (total + per-priority
        labels) — every enqueue/admit/preempt/shed path funnels here, so
        the gauges can never disagree with each other.  ``force`` is the
        scrape-time pull (server metrics_text), which refreshes even with
        per-tick publishing switched off."""
        if not (force or obs_registry.publishing()):
            return
        self._m_queued.set(len(self._queue))
        by_prio: Dict[int, int] = {}
        for r in self._queue:
            by_prio[r.priority] = by_prio.get(r.priority, 0) + 1
        self._queued_prios |= set(by_prio)
        reg = obs_registry.get_registry()
        for prio in self._queued_prios:  # stale labels drop to 0
            reg.gauge("mlt_engine_queued_requests",
                      help="requests awaiting a slot",
                      labels={"priority": str(prio)}
                      ).set(by_prio.get(prio, 0))

    def _max_pages_for(self, req: EngineRequest) -> int:
        total = min(len(req.prompt) + req.max_new_tokens, self.max_seq)
        return -(-total // self.page_size)

    def _sched_state(self, now: float) -> SchedulerState:  # holds _lock
        """Read-only snapshot for policy decisions (under _lock)."""
        return SchedulerState(
            now=now,
            ema_tick_s=self._ema_tick_s,
            ema_retire_s=self._ema_retire_s,
            free_slots=sum(r is None for r in self._slots),
            queue_depth=len(self._queue),
            can_preempt=bool(self.prefill_chunk),
            prefill_chunk=self.prefill_chunk,
            ttft_ema_s=self._ema_ttft_s,
        )

    def _admit(self) -> None:
        """Move queued requests into slots while the policy and pages
        allow.

        The policy owns the DECISIONS: which queued request to try next
        (``admission_order``; fcfs = queue head with nothing skipping it,
        ``barrier_admission``), which queued requests to shed outright,
        and which decoding victim to preempt when the best candidate
        can't get a slot or its page budget.  The engine owns the
        MECHANISMS: chunked mode reserves only the uncovered prompt
        suffix (plus the first decode page) and books the worst-case rest
        in the commitment ledger; monolithic mode reserves the full
        budget up front (PR 1 semantics).  Planning (trie match, budget
        check, allocation, slot assignment) happens under ``_lock``; only
        the device work (COW copy / monolithic prefill) runs outside it,
        with every owned page ref tracked in ``req._pages`` throughout so
        a failure path releases exactly what is held."""
        while True:
            with self._lock:
                if not self._queue:
                    return
                now = time.monotonic()
                state = self._sched_state(now)
                shed = self.policy.shed(list(self._queue), state)
                for victim, reason in shed:
                    if victim in self._queue:  # defensive vs policy bugs
                        self._queue.remove(victim)
                        self._shed_locked(victim, reason)
                if shed:
                    self._publish_queued_locked()
                    if not self._queue:
                        return
                order = self.policy.admission_order(list(self._queue),
                                                    state)
                req = plan = None
                try:
                    slot = self._slots.index(None)
                except ValueError:
                    slot = None
                if slot is not None:
                    for cand in order:
                        p = (self._plan_chunked(cand, slot)
                             if self.prefill_chunk
                             else self._plan_monolithic(cand, slot))
                        if p is not None:
                            req, plan = cand, p
                            break
                        if self.policy.barrier_admission:
                            break  # page pressure: head waits, no skips
                if plan is None:
                    # blocked on a slot or on pages: the policy may evict
                    # the lowest-value decoding request — its pages go
                    # back to the pool (prefix-covered ones stay in the
                    # trie) and it re-queues for a cached-page resume
                    victim = None
                    if order and state.can_preempt:
                        decoding = [r for r in self._slots
                                    if r is not None
                                    and r._phase == "decode"
                                    and not r.return_log_probs]
                        victim = self.policy.preempt_victim(
                            order[0], decoding, state)
                    if victim is None:
                        return
                    self._preempt_locked(victim)
                    continue
                self._queue.remove(req)
                self._publish_queued_locked()
            try:
                if self.prefill_chunk:
                    self._place_chunked(req, plan)
                else:
                    self._place_monolithic(req)
            except Exception as e:  # noqa: BLE001 — surface to the waiter
                self._fail(req, e)

    def _preempt_locked(self, victim: EngineRequest) -> None:  # holds _lock
        """Preemption by page release: park the victim's finished KV
        pages in the prefix-cache trie, release every page it holds
        (trie-registered ones go cached-idle, the rest go free), return
        its unused worst-case commitment, and re-queue it.  On
        re-admission the trie match re-takes the SAME physical pages, so
        resume recomputes only the partial last page — bitwise identical
        to never having been preempted (tests/test_scheduler.py)."""
        assert victim._phase == "decode" and victim._slot >= 0
        slot = victim._slot
        seq = victim.seq_tokens
        if self.cache is not None:
            # every page fully covered by seq[:-1] is finished K/V the
            # resume's refeed tick will never write — safe to share
            self.cache.insert(seq, victim._pages,
                              (len(seq) - 1) // self.page_size)
        self._slots[slot] = None
        self._block_tables[slot] = NULL_PAGE
        self._positions[slot] = 0
        self._tokens[slot] = 0
        self._top_k[slot] = 1
        self._top_p[slot] = 0.0
        self._temperature[slot] = 1.0
        pages, victim._pages = victim._pages, []
        self._committed -= max(0, victim._max_pages - len(pages))
        self.pool.release(pages)
        victim._phase = "queued"
        victim._slot = -1
        victim._fill_pos = 0
        victim._preemptions += 1
        victim._flight.note_preemption()
        victim._flight.set_phase("preempted", step=victim._step,
                                 pages_released=len(pages))
        self.preemptions += 1
        self._queue.append(victim)  # position is policy-ordered anyway
        if obs_registry.publishing():
            self._m_preempt.inc()
        self._publish_queued_locked()
        self._dirty = True

    def _shed_locked(self, req: EngineRequest,
                     reason: str) -> None:  # holds _lock
        """Drop a QUEUED request (owns no pages): fail its future with a
        retryable :class:`RequestShed` carrying the drain estimate."""
        req.shed = True
        req.shed_retry_after = self._drain_eta(len(self._queue))
        req._phase = "finished"
        req.error = f"request shed: {reason}"
        req.finished = True
        req._flight.finish("shed", reason=reason)
        self.flight.close(req._flight)
        self._stream_finish_locked(req, "error", error=req.error, shed=True,
                                   retry_after=req.shed_retry_after)
        self.shed_requests += 1
        if obs_registry.publishing():
            self._m_shed.inc()
        req._done.set()

    def preempt(self, req: EngineRequest) -> bool:
        """Force-preempt one decoding request (ops/test hook — policy-
        driven preemption runs the same ``_preempt_locked`` path during
        admission).  False if the request isn't currently decoding."""
        with self._lock:
            if req._phase != "decode" or not self.prefill_chunk:
                return False
            self._preempt_locked(req)
            return True

    def scheduler_stats(self) -> dict:
        """Control-plane snapshot for ``/health`` (generation/server.py)
        and the slo bench."""
        with self._lock:
            by_prio: Dict[str, int] = {}
            for r in self._queue:
                k = str(r.priority)
                by_prio[k] = by_prio.get(k, 0) + 1
            return {
                "policy": self.policy.name,
                "queued": len(self._queue),
                "queued_by_priority": by_prio,
                "preemptions": self.preemptions,
                "shed": self.shed_requests,
                "deadline_misses": self.deadline_misses,
                "ema_tick_ms": (None if self._ema_tick_s is None
                                else round(self._ema_tick_s * 1e3, 3)),
                "ema_retire_ms": (None if self._ema_retire_s is None
                                  else round(self._ema_retire_s * 1e3, 3)),
                # measured submit-to-first-token EMA (ISSUE 12): the
                # honest TTFT signal the router's wait predictions use
                "ttft_ema_ms": (None if self._ema_ttft_s is None
                                else round(self._ema_ttft_s * 1e3, 3)),
                "retry_after_s": round(self._drain_eta(len(self._queue)), 3),
            }

    # ---- chunked admission ----

    def _plan_chunked(self, req: EngineRequest,
                      slot: int) -> Optional[dict]:  # holds _lock
        """Under _lock: match the prefix cache, check the page budget,
        allocate the suffix pages, and reserve the slot.  None = can't
        admit now (matched refs undone).  Works on the request's
        EFFECTIVE prompt (prompt + generated): a preempted request
        re-admits here and its parked pages match straight back out of
        the trie."""
        ps = self.page_size
        seq = req.seq_tokens
        prompt_len = len(seq)
        max_total = self._max_pages_for(req)
        matched: List[int] = []
        if self.cache is not None and not req.return_log_probs:
            # log-prob requests recompute the whole prompt (the teacher-
            # forced scores need every position's logits), so they take no
            # shared pages — their pages still feed the cache afterwards
            matched = self.cache.match(seq, prompt_len // ps)
        covered = len(matched) * ps
        # full page-aligned match: the first tick re-feeds the last prompt
        # token and would WRITE the final shared page -> copy-on-write
        cow = bool(matched) and covered == prompt_len
        n_keep = len(matched) - (1 if cow else 0)
        fill_end = _bucket_up(prompt_len, ps)
        suffix_pages = (fill_end - covered) // ps
        held_core = n_keep + (1 if cow else 0) + suffix_pages
        extra = 1 if max_total > held_core else 0  # first decode page
        need_now = (1 if cow else 0) + suffix_pages + extra
        remaining = max_total - held_core - extra
        if (self.pool.num_available - need_now
                < self._committed + remaining + self.page_watermark):
            self.pool.release(matched)
            return None
        fresh = self.pool.alloc(need_now)
        if fresh is None:  # unreachable given the check; stay safe
            self.pool.release(matched)
            return None
        self._committed += remaining
        # every ref this request owns lives in _pages from here on, so any
        # failure path releases exactly the right set; the COW page swap
        # reorders the list after the device copy lands
        req._pages = matched + fresh
        req._max_pages = max_total
        req._fill_pos = prompt_len if cow else covered
        req._hit_tokens = covered
        req._slot = slot
        self._slots[slot] = req
        self.prefix_hit_tokens += covered
        self.prefix_miss_tokens += prompt_len - covered
        req._flight.note_hit_tokens(covered)
        req._flight.set_phase(
            "prefill", kind="resume" if req._preemptions else "admit",
            slot=slot, hit_tokens=covered, pages=len(req._pages))
        if obs_registry.publishing():
            self._m_hit_tokens.inc(covered)
            self._m_miss_tokens.inc(prompt_len - covered)
        return {"matched": matched, "fresh": fresh, "cow": cow,
                "n_keep": n_keep}

    def _place_chunked(self, req: EngineRequest, plan: dict) -> None:
        matched, fresh = plan["matched"], plan["fresh"]
        n_keep, cow = plan["n_keep"], plan["cow"]
        if cow:
            src, dst = matched[-1], fresh[0]
            # device copy OUTSIDE the lock (driver thread; serialized with
            # ticks via _drive_lock), then drop our ref on the shared page
            if self.spec_k:
                (self.pool.k, self.pool.v, self.pool.draft_k,
                 self.pool.draft_v) = self._copy_page()(
                    self.pool.k, self.pool.v, self.pool.draft_k,
                    self.pool.draft_v, self._asarray(np.int32(src)),
                    self._asarray(np.int32(dst)))
            else:
                self.pool.k, self.pool.v = self._copy_page()(
                    self.pool.k, self.pool.v, self._asarray(np.int32(src)),
                    self._asarray(np.int32(dst)))
        with self._lock:
            if cow:
                # block-table order: kept shared pages, the private COW
                # copy, then the first decode page
                req._pages = matched[:n_keep] + [fresh[0]] + fresh[1:]
                self.pool.release([matched[-1]])
                self.cow_copies += 1
                if obs_registry.publishing():
                    self._m_cow.inc()
            if req._fill_pos >= len(req.seq_tokens):
                # fully served from cache: straight to decode (or, for
                # a prefill_only request, straight to handoff)
                self._activate_or_handoff(req, req._slot)
            else:
                req._phase = "prefill"
                self._prefill_q.append(req)

    # ---- monolithic admission (prefill_chunk=0, PR 1 semantics) ----

    def _plan_monolithic(self, req: EngineRequest,
                         slot: int) -> Optional[dict]:  # holds _lock
        pages = self.pool.alloc(self._max_pages_for(req))
        if pages is None:
            return None
        req._pages = pages
        req._max_pages = len(pages)
        req._slot = slot
        self._slots[slot] = req
        req._flight.set_phase("prefill", kind="admit", slot=slot,
                              pages=len(pages))
        return {"pages": pages}

    def _place_monolithic(self, req: EngineRequest) -> None:
        """Prefill the whole prompt into the request's pages and activate
        the slot."""
        pages = req._pages
        prompt_len = len(req.prompt)
        s_pre = min(_bucket_up(prompt_len), _bucket_up(self.max_seq))
        tokens = np.zeros((1, s_pre), np.int32)
        tokens[0, :prompt_len] = req.prompt
        # pages for the bucket-padded tail beyond the request's budget route
        # to the null page; decode overwrites in-budget positions one by one
        page_ids = np.full((s_pre // self.page_size,), NULL_PAGE, np.int32)
        n = min(len(pages), len(page_ids))
        page_ids[:n] = pages[:n]

        out = self._prefill(s_pre, req.return_log_probs)(
            self.params, self._asarray(tokens), self.pool.k, self.pool.v,
            self._asarray(page_ids))
        if req.return_log_probs:
            self.pool.k, self.pool.v, prompt_lp = out
            req.prompt_log_probs = [
                float(x) for x in np.asarray(prompt_lp)[: prompt_len - 1]]
        else:
            self.pool.k, self.pool.v = out

        with self._lock:
            req._fill_pos = prompt_len
            self.prefix_miss_tokens += prompt_len
            self.prefill_tokens_computed += s_pre
            if obs_registry.publishing():
                self._m_miss_tokens.inc(prompt_len)
                self._m_prefill_tokens.inc(s_pre)
            self._activate_or_handoff(req, req._slot)

    # ---- shared lifecycle tail ----

    def _activate(self, req: EngineRequest,
                  slot: int) -> None:  # holds _lock
        """Under _lock: install the slot's decode state (effective prompt
        fully in pages); the next tick samples the next token by
        re-feeding the last token at position len(seq) - 1 — identical
        K/V rewrite into a PRIVATE page (COW guarantees it).  A resumed
        request re-enters with its ORIGINAL key and step count, so its
        sampling stream continues exactly where preemption cut it."""
        seq = req.seq_tokens
        if req._key is None:
            seed = req.seed
            if seed is None:
                seed = int.from_bytes(os.urandom(4), "little")
            req._key = np.asarray(jax.random.PRNGKey(seed), np.uint32)
        bt = np.full((self.pages_per_seq,), NULL_PAGE, np.int32)
        bt[: len(req._pages)] = req._pages
        self._block_tables[slot] = bt
        self._positions[slot] = len(seq) - 1
        self._tokens[slot] = seq[-1]
        self._temperature[slot] = req.temperature
        self._top_k[slot] = req.top_k
        self._top_p[slot] = req.top_p
        self._keys[slot] = req._key
        self._steps[slot] = req._step
        req._phase = "decode"
        req._flight.set_phase("decode", pos=len(seq) - 1)
        self._dirty = True

    def _activate_or_handoff(self, req: EngineRequest,
                             slot: int) -> None:  # holds _lock
        """Prefill-completion dispatch: normal requests activate into
        decode; ``prefill_only`` requests (disaggregated serving, ISSUE
        19) park for export instead — they never take a decode tick."""
        if req.prefill_only:
            self._handoff_ready_locked(req, slot)
        else:
            self._activate(req, slot)

    def _handoff_ready_locked(self, req: EngineRequest,
                              slot: int) -> None:  # holds _lock
        """Prefill finished for a ``prefill_only`` request: free the
        slot (the scheduler is done with it), KEEP the page refs (the
        export must read stable bytes), return the never-needed decode
        commitment, and wake the exporter waiting on ``_done``.  The
        flight record enters the ``handoff`` phase bucket here, so the
        migrated request's latency decomposition still provably sums
        (PR 12 invariant across the hop)."""
        self._slots[slot] = None
        self._block_tables[slot] = NULL_PAGE
        self._positions[slot] = 0
        self._tokens[slot] = 0
        self._top_k[slot] = 1
        self._top_p[slot] = 0.0
        self._temperature[slot] = 1.0
        self._dirty = True
        # a handoff request never decodes: its worst-case decode-page
        # commitment returns to the ledger now
        self._committed -= max(0, req._max_pages - len(req._pages))
        req._max_pages = len(req._pages)
        req._slot = -1
        req._phase = "handoff"
        req._flight.set_phase("handoff", pages=len(req._pages))
        req._done.set()

    def _finish_handoff_locked(self, req: EngineRequest,
                               **args) -> None:  # holds _lock
        """Retire a handoff-phase request after (attempted) export:
        release every held page — trie-registered prompt pages go
        cached-idle, exactly like a preemption park, so a later local
        request (or a second export) still hits them."""
        if req._phase != "handoff":
            return  # failed/shed earlier; _fail/_shed already cleaned up
        pages, req._pages = req._pages, []
        self._committed -= max(0, req._max_pages - len(pages))
        self.pool.release(pages)
        req._phase = "finished"
        req.finished = True
        req._t_done = time.monotonic()
        req._flight.finish("handoff", **args)
        self.flight.close(req._flight)
        req._done.set()

    def _fail(self, req: EngineRequest, e: Exception) -> None:
        with self._lock:
            self._fail_locked(req, e)

    def _fail_locked(self, req: EngineRequest,
                     e: Exception) -> None:  # holds _lock
        if 0 <= req._slot < len(self._slots) \
                and self._slots[req._slot] is req:
            self._slots[req._slot] = None
            self._block_tables[req._slot] = NULL_PAGE
            self._dirty = True
        pages, req._pages = req._pages, []
        self._committed -= max(0, req._max_pages - len(pages))
        self.pool.release(pages)
        req._phase = "finished"
        req.error = f"{type(e).__name__}: {e}"
        req.finished = True
        req._flight.finish("error", error=req.error)
        self.flight.close(req._flight)
        self._stream_finish_locked(req, "error", error=req.error)
        req._done.set()

    def _retire(self, slot: int) -> None:  # holds _lock
        req = self._slots[slot]
        self._slots[slot] = None
        self._block_tables[slot] = NULL_PAGE
        self._positions[slot] = 0
        self._tokens[slot] = 0
        self._top_k[slot] = 1
        self._top_p[slot] = 0.0
        self._temperature[slot] = 1.0
        pages, req._pages = req._pages, []
        # early termination returns its unneeded worst-case commitment
        self._committed -= max(0, req._max_pages - len(pages))
        self.pool.release(pages)
        self._dirty = True
        req._phase = "finished"
        req.finished = True
        # drain-rate EMA (feeds Retry-After + slo shed predictions) and
        # SLO outcome accounting
        now = time.monotonic()
        if self._last_retire_t is not None:
            dt = now - self._last_retire_t
            self._ema_retire_s = (dt if self._ema_retire_s is None
                                  else 0.7 * self._ema_retire_s + 0.3 * dt)
        self._last_retire_t = now
        req._t_done = now
        rec = req._flight
        rec.finish("ok", now=now, tokens=len(req.generated))
        self.flight.close(rec)
        ttft = req.ttft
        if req._stream is not None:
            # terminal stream event: the flight-record timing payload
            # (what the buffered response's "timing" block is built from)
            timing = {"ttft_s": None if ttft is None else round(ttft, 6),
                      "latency_s": round(now - req._t_submit, 6),
                      "tokens": len(req.generated)}
            if rec.enabled:
                timing["decomposition"] = rec.to_dict()["decomposition"]
            self._stream_finish_locked(req, "done", outcome="ok",
                                       timing=timing)
        missed = False
        publishing = obs_registry.publishing()
        if rec.enabled and publishing:
            # honest TTFT/latency decomposition (ISSUE 12): the flight
            # record's phase buckets sum to the measured latency, so
            # these histograms attribute it instead of re-measuring it
            d = rec.to_dict()["decomposition"]
            self._m_queue_wait.observe(d["queue_wait_s"])
            self._m_prefill_compute.observe(d["prefill_s"])
            if req._preemptions:
                self._m_preempted_s.observe(d["preempted_s"])
        if ttft is not None:
            if publishing:
                self._m_ttft.observe(ttft)
            if (req.ttft_deadline_ms is not None
                    and ttft > req.ttft_deadline_ms / 1e3):
                missed = True
                if publishing:
                    self._m_miss_ttft.inc()
                    if rec.enabled:
                        # attribution: blame the phase that ate the
                        # largest TTFT share ({kind}-only stays total)
                        obs_registry.get_registry().counter(
                            "mlt_engine_deadline_miss_total",
                            help="retired requests that missed a "
                                 "declared deadline",
                            labels={"kind": "ttft",
                                    "phase": rec.miss_phase()}).inc()
            if (req.tpot_deadline_ms is not None and req._step > 1
                    and ((now - req._t_first) / (req._step - 1)
                         > req.tpot_deadline_ms / 1e3)):
                missed = True
                if publishing:
                    self._m_miss_tpot.inc()
        if missed:
            self.deadline_misses += 1
        req._done.set()

    def _stopped_by_token(self, req: EngineRequest, tok: int) -> bool:
        if req.stop_on_double_eol:
            prev = (req.generated[-2] if len(req.generated) > 1
                    else req.prompt[-1])
            return tok == gen.GPT2_DOUBLE_EOL or (
                tok == gen.GPT2_EOL and prev == gen.GPT2_EOL)
        if req.stop_on_eol:
            return tok in (gen.GPT2_EOL, gen.GPT2_DOUBLE_EOL)
        if not req.use_eod_for_termination or req.termination_id is None:
            return False
        return tok == req.termination_id

    # -- speculative decoding ----------------------------------------------

    def _apply_spec_locked(self, active, k_eff, emit_np, lp_np, acc_np,
                           m_np, now) -> int:  # holds _lock
        """Fold one speculative tick's results into the slots: append each
        row's emitted block (truncating at stop tokens / length limits —
        exactly where non-speculative decode would have stopped), advance
        the host mirrors by the KEPT count, update acceptance EMAs and
        spec telemetry, retire finished rows.  Returns tokens emitted
        (the tick's slot-step count for throughput accounting — a spec
        slot reports k-token progress, so SLO/tpot math sees real token
        timestamps, not tick counts)."""
        emitted = 0
        publishing = obs_registry.publishing()
        for i in active:
            req = self._slots[i]
            k_i = int(k_eff[i])
            m_i = int(m_np[i])
            took = 0
            done = False
            for t in range(m_i):
                tok = int(emit_np[i, t])
                req.generated.append(tok)
                req.log_probs.append(float(lp_np[i, t]))
                took += 1
                done = (self._stopped_by_token(req, tok)
                        or len(req.generated) >= req.max_new_tokens
                        or len(req.prompt) + len(req.generated)
                        >= self.max_seq)
                if done:
                    break
            if req._step == 0:
                req._t_first = now
                req._flight.mark_first_token(now)
                self._note_ttft_locked(now - req._t_submit)
            if took:
                self._stream_emit_locked(req, req.generated[-took:],
                                         req.log_probs[-took:])
            req._step += took
            self._positions[i] += took
            self._tokens[i] = int(emit_np[i, took - 1])
            self._steps[i] += took
            emitted += took
            self.spec_emitted_tokens += took
            if k_i > 0:
                a_i = int(acc_np[i])
                self.spec_draft_tokens += k_i
                self.spec_accepted_tokens += a_i
                req._spec_ema = 0.7 * req._spec_ema + 0.3 * (a_i / k_i)
                req._flight.add_spec(k_i, a_i)
                req._flight.event("spec_tick", k=k_i, accepted=a_i,
                                  emitted=took)
                if publishing:
                    self._m_spec_draft.inc(k_i)
                    self._m_spec_accepted.inc(a_i)
                    self._m_spec_ratio.observe(a_i / k_i)
            if publishing:
                self._m_spec_len.observe(took)
            if took != m_i:
                # a stop token cut the block short: the device mirror ran
                # ahead of the kept sequence — force a re-upload
                self._dirty = True
            if done:
                self._retire(i)
        self.spec_ticks += 1
        return emitted

    def _apply_plain_locked(self, active, next_np, logp_np,
                            now) -> int:  # holds _lock
        """Fold one non-speculative tick's sampled tokens into the slots;
        returns tokens emitted (== len(active))."""
        for i in active:
            req = self._slots[i]
            tok = int(next_np[i])
            req.generated.append(tok)
            req.log_probs.append(float(logp_np[i]))
            req._step += 1
            if req._step == 1:
                req._t_first = now
                req._flight.mark_first_token(now)
                self._note_ttft_locked(now - req._t_submit)
            self._stream_emit_locked(req, (tok,), (req.log_probs[-1],))
            self._positions[i] += 1
            self._tokens[i] = tok
            self._steps[i] += 1
            done = (self._stopped_by_token(req, tok)
                    or len(req.generated) >= req.max_new_tokens
                    or len(req.prompt) + len(req.generated)
                    >= self.max_seq)
            if done:
                self._retire(i)
        return len(active)

    def spec_stats(self) -> dict:
        """Speculative-decoding snapshot for ``/health`` and the spec
        bench (generation/server.py, bench_decode.py --mode spec)."""
        if not self.spec_k:
            return {"enabled": False}
        with self._lock:
            drafted = self.spec_draft_tokens
            accepted = self.spec_accepted_tokens
            emitted = self.spec_emitted_tokens
            ticks = self.spec_ticks
        return {
            "enabled": True,
            "spec_k": self.spec_k,
            "adaptive": self.spec_adaptive,
            "draft_layers": self.draft_cfg.model.num_layers,
            "draft_tokens": drafted,
            "accepted_tokens": accepted,
            "acceptance_rate": round(accepted / drafted, 4) if drafted else None,
            "emitted_tokens": emitted,
            "tokens_per_tick": round(emitted / ticks, 3) if ticks else None,
        }

    # -- chunked prefill scheduling ---------------------------------------

    def _advance_prefill(self, only_log_probs: bool = False) -> bool:
        """Run ONE prefill chunk for the policy's chosen prefilling
        request (fcfs: the oldest).  Returns True if a chunk ran — the
        policy's token budget bounds how many run back to back, so decode
        slots keep ticking while long prompts fill in the gaps.

        ``only_log_probs`` is the ragged-mode carve-out: teacher-forced
        prompt log-probs need every chunk position's logits from the
        s>1 prefill program (their bits are pinned by the api scoring
        contract), so ``return_log_probs`` prompts keep this legacy chunk
        path even when everything else rides the fused ragged tick."""
        with self._lock:
            live = [r for r in self._prefill_q if r._phase == "prefill"]
            if len(live) != len(self._prefill_q):  # failed/cancelled
                self._prefill_q = deque(live)
            if only_log_probs:
                live = [r for r in live if r.return_log_probs]
            if not live:
                return False
            req = self.policy.prefill_order(
                live, self._sched_state(time.monotonic()))[0]
            ps = self.page_size
            chunk = self.prefill_chunk
            seq = req.seq_tokens  # resumed requests re-prefill their tail
            prompt_len = len(seq)
            start = req._fill_pos
            fill_end = _bucket_up(prompt_len, ps)
            # chunk boundaries are ABSOLUTE-position grid multiples of
            # prefill_chunk (first/last chunks may be short): the K/V bits a
            # chunk writes then depend only on (tokens, positions), never on
            # how much prefix the cache covered — the bitwise cache-on/off
            # parity contract
            end = min(fill_end, (start // chunk + 1) * chunk)
            rows = end - start
            # attention horizon: every page the chunk's queries can see,
            # bucketed (multiples of BUCKET tokens) to bound compile count
            kv_pages = min(self.pages_per_seq, _bucket_up(end) // ps)
            tokens = np.zeros((1, rows), np.int32)
            n_real = min(end, prompt_len) - start
            tokens[0, :n_real] = seq[start:start + n_real]
            bt = np.full((1, kv_pages), NULL_PAGE, np.int32)
            n_bt = min(len(req._pages), kv_pages)
            bt[0, :n_bt] = req._pages[:n_bt]
            targets = np.zeros((1, rows), np.int32)
            n_lp = max(0, min(rows, prompt_len - 1 - start))
            if req.return_log_probs and n_lp:
                targets[0, :n_lp] = seq[start + 1:start + 1 + n_lp]

        t_chunk = time.monotonic()
        try:
            with obs_trace.span("engine-prefill-chunk", start=start,
                                rows=rows, tp=self._tp,
                                trace_id=req.trace_id):
                if self.spec_k:
                    out = self._chunk_prefill(rows, kv_pages,
                                              req.return_log_probs)(
                        self.params, self.draft_params,
                        self._asarray(tokens),
                        self._asarray(np.asarray([start], np.int32)),
                        self._asarray(bt), self.pool.k, self.pool.v,
                        self.pool.draft_k, self.pool.draft_v,
                        self._asarray(targets))
                    (self.pool.k, self.pool.v, self.pool.draft_k,
                     self.pool.draft_v) = out[:4]
                    out = (self.pool.k, self.pool.v) + out[4:]
                else:
                    out = self._chunk_prefill(rows, kv_pages,
                                              req.return_log_probs)(
                        self.params, self._asarray(tokens),
                        self._asarray(np.asarray([start], np.int32)),
                        self._asarray(bt),
                        self.pool.k, self.pool.v, self._asarray(targets))
            if req.return_log_probs:
                self.pool.k, self.pool.v, lp = out
                if req.prompt_log_probs is None:
                    req.prompt_log_probs = []
                req.prompt_log_probs.extend(
                    float(x) for x in np.asarray(lp)[:n_lp])
            else:
                self.pool.k, self.pool.v = out
        except Exception as e:  # noqa: BLE001 — surface to the waiter
            self._fail(req, e)
            return True

        with self._lock:
            req._fill_pos = end
            self.prefill_tokens_computed += rows
            req._flight.event("prefill_chunk", start=start, end=end,
                              rows=rows, fill_end=fill_end)
            req._flight.add_prefill_compute(time.monotonic() - t_chunk)
            if obs_registry.publishing():
                self._m_prefill_tokens.inc(rows)
            if end >= fill_end:
                self._prefill_q.remove(req)
                if self.cache is not None:
                    # cache every page FULLY covered by prompt tokens that
                    # the refeed tick will never write: (prompt_len-1)//page
                    # excludes the refeed page, so shared pages are
                    # immutable from birth
                    self.cache.insert(seq, req._pages,
                                      (prompt_len - 1) // ps)
                self._activate_or_handoff(req, req._slot)
        return True

    # -- the tick ----------------------------------------------------------

    def step(self) -> int:
        """Admit what fits, advance prefill under the policy's token
        budget, run the tick, and retire finished requests.  Returns the
        number of slots advanced (decode rows ticked, +1 per prefill
        phase that ran; 0 = idle, nothing ran).  Call from one driver at
        a time (:meth:`run_until_idle` / the background loop serialize
        via ``_drive_lock``).

        Ragged mode (the default): the whole tick — decode slots, verify
        blocks, prefill-chunk rows — is ONE compiled launch
        (:meth:`_step_ragged`).  Legacy split mode dispatches the
        decode/spec tick plus one program per prefill chunk.

        Pipelined mode (``--tick_pipeline_depth N``, ISSUE 17): steady-
        state steps chain N ticks per launch and apply results at a one-
        launch lag (:meth:`_step_pipelined`); any boundary — admission,
        prefill, preemption fallout — drains the pipeline and runs this
        depth-0 path for that step.  Speculative engines always step at
        depth 0 (adaptive k_eff needs per-tick acceptance)."""
        if self.pipeline_depth and not self.spec_k:
            n = self._step_pipelined()
            if n is not None:
                return n
        with obs_trace.span("engine-admit"):
            self._admit()
        if self.ragged:
            return self._step_ragged()
        return self._step_legacy()

    def _prefill_budget_tokens(self) -> int:  # holds _lock
        """The policy's per-tick prefill budget, validated as TOKENS
        (ISSUE 11: the unit is pinned — a chunk-count return is a policy
        bug) and floored to one chunk so prefill always advances."""
        budget = self.policy.prefill_budget(
            [r for r in self._prefill_q if r._phase == "prefill"],
            self._sched_state(time.monotonic()))
        if not isinstance(budget, int) or budget < 0:
            raise ValueError(
                f"prefill_budget must be a non-negative int of TOKENS, "
                f"got {budget!r}")
        return max(budget, self.prefill_chunk)

    def _prepare_decode_locked(self, active) -> np.ndarray:  # holds _lock
        """On-demand paging + per-slot speculation depth for the decode
        rows of this tick; mutates ``active`` in place when a row must be
        failed.

        A row crossing into a page it doesn't own yet gets one allocated
        now (commitment ledger guarantees this can't fail while the slot
        is in flight).  A speculating slot writes up to k_eff positions
        past its own, so its horizon covers the whole verify block; k_eff
        itself is per-slot and per-tick — capped by --spec_k, the tokens
        the request still owes, and (adaptive mode) the acceptance EMA.
        Writes past a row's k_eff land on the null page or above the
        accepted frontier — discarded by the acceptance mask, rewritten
        before ever being attended."""
        k_eff = np.zeros((self.max_slots,), np.int32)
        for i in list(active):
            req = self._slots[i]
            if self.spec_k:
                remaining = req.max_new_tokens - len(req.generated)
                k_i = min(self.spec_k, remaining - 1)
                if self.spec_adaptive:
                    k_i = min(k_i, max(1, int(round(
                        req._spec_ema * self.spec_k))))
                k_eff[i] = max(k_i, 0)
            p0 = int(self._positions[i]) // self.page_size
            p1 = (int(self._positions[i]) + int(k_eff[i])) \
                // self.page_size
            for idx in range(p0, min(p1, self.pages_per_seq - 1) + 1):
                if self._block_tables[i][idx] != NULL_PAGE:
                    continue
                got = self.pool.alloc(1)
                if got is None:  # ledger-unreachable; fail just the row
                    self._fail_locked(req, RuntimeError(
                        "KV pool exhausted for an in-flight slot — "
                        "commitment ledger violated"))
                    active.remove(i)
                    break
                self._block_tables[i][idx] = got[0]
                req._pages.append(got[0])
                self._committed -= 1
                self._dirty = True
        return k_eff

    def _dev_state_locked(self) -> Tuple:  # holds _lock
        """The device mirror of the per-slot arrays, re-uploaded from the
        host copies only when admission/retirement dirtied the layout."""
        if self._dirty:
            self._dev_state = (self._asarray(self._block_tables),
                               self._asarray(self._positions),
                               self._asarray(self._tokens),
                               self._asarray(self._keys),
                               self._asarray(self._steps),
                               self._asarray(self._temperature),
                               self._asarray(self._top_k),
                               self._asarray(self._top_p))
            self._dirty = False
        return self._dev_state

    def _note_launches_locked(self, n: int,
                              prefill_tokens: int) -> None:  # holds _lock
        """Tick-phase launch accounting (ISSUE 11): ``n`` compiled
        attention programs were dispatched this step."""
        self.tick_launches += n
        self.last_tick_launches = n
        if obs_registry.publishing():
            if n:
                self._m_launches.inc(n)
            if prefill_tokens:
                self._m_prefill_per_tick.observe(prefill_tokens)

    # -- pipelined multi-tick dispatch (ISSUE 17) --------------------------

    def _note_host_gap(self, gap: Optional[float]) -> None:
        """Record one inter-launch host gap (scheduling + emission fetch
        + apply time between device dispatches — the overhead pipelining
        amortizes; fed to the bench via :meth:`host_gap_stats`)."""
        if gap is None:
            return
        with self._lock:
            self._host_gaps.append(gap)
        if obs_registry.publishing():
            self._m_host_gap.observe(gap)

    def host_gap_stats(self) -> dict:
        """Inter-launch host-gap summary (bench_decode --mode pipeline
        reports the p50/p99 reduction as depth grows)."""
        with self._lock:
            gaps = sorted(self._host_gaps)
        if not gaps:
            return {"count": 0, "total_s": 0.0,
                    "p50_ms": None, "p99_ms": None}

        def q(p: float) -> float:
            return gaps[min(len(gaps) - 1, int(p * (len(gaps) - 1)))]

        return {"count": len(gaps),
                "total_s": round(sum(gaps), 4),
                "p50_ms": round(q(0.50) * 1e3, 4),
                "p99_ms": round(q(0.99) * 1e3, 4)}

    def _pregrant_locked(self, active,
                         horizon: int) -> bool:  # holds _lock
        """Pre-grant every page the next ``horizon`` chained positions
        may write, per active row: page slots covering the HOST position
        through ``host position + horizon - 1`` (capped at the row's
        worst-case budget) are allocated now and debited from the
        commitment ledger — the in-program position advance then crosses
        page boundaries without consulting the host, and the device-
        resident ``remaining`` budget freezes a row before it can outrun
        its final granted page.  The ledger's admission invariant makes
        the allocs infallible while the slot is in flight, exactly as
        for :meth:`_prepare_decode_locked`.  Rows that stop early via a
        stop token simply retire holding a few unwritten pages — they
        release with the rest.  Returns True when any block table
        changed (the launch then re-uploads ONLY the table operand;
        positions/tokens/steps keep chaining on device)."""
        changed = False
        for i in list(active):
            req = self._slots[i]
            p0 = int(self._positions[i]) // self.page_size
            last_pos = min(int(self._positions[i]) + horizon - 1,
                           req._max_pages * self.page_size - 1)
            p1 = last_pos // self.page_size
            for idx in range(p0, min(p1, self.pages_per_seq - 1) + 1):
                if self._block_tables[i][idx] != NULL_PAGE:
                    continue
                got = self.pool.alloc(1)
                if got is None:  # ledger-unreachable; fail just the row
                    self._fail_locked(req, RuntimeError(
                        "KV pool exhausted for an in-flight slot — "
                        "commitment ledger violated"))
                    active.remove(i)
                    changed = True
                    break
                self._block_tables[i][idx] = got[0]
                req._pages.append(got[0])
                self._committed -= 1
                changed = True
        return changed

    def _apply_chain_locked(self, active, reqs, toks_np, logps_np,
                            now) -> int:  # holds _lock
        """Fold one in-flight chain's results into the slots — the spec
        apply's block shape over the chain axis: each surviving row
        appends its whole column up to the first stop in ONE pass, so
        host apply cost is per CHAIN, not per tick (the pipelined mode's
        other half: chains amortize dispatch, this amortizes apply).
        Bit-for-bit the per-tick ``_apply_plain_locked`` fold: same stop
        rules in the same order, rows discarded when their slot no
        longer holds the launched request."""
        chain = toks_np.shape[0]
        emitted = 0
        for i, req in zip(active, reqs):
            if self._slots[i] is not req or req._phase != "decode":
                continue  # retired / preempted / failed at the boundary
            col = toks_np[:, i].tolist()
            room = min(req.max_new_tokens - len(req.generated),
                       self.max_seq - len(req.prompt)
                       - len(req.generated))
            if (not req.stop_on_eol and not req.stop_on_double_eol
                    and (not req.use_eod_for_termination
                         or req.termination_id is None)):
                # length-limited row: bulk-extend the column
                took = min(chain, room)
                done = took == room
                req.generated.extend(col[:took])
                req.log_probs.extend(logps_np[:took, i].tolist())
            else:
                lcol = logps_np[:, i].tolist()
                took = 0
                done = False
                for t in range(chain):
                    tok = col[t]
                    req.generated.append(tok)
                    req.log_probs.append(lcol[t])
                    took += 1
                    done = (self._stopped_by_token(req, tok)
                            or took >= room)
                    if done:
                        break
            if not took:
                continue
            if req._step == 0:
                req._t_first = now
                req._flight.mark_first_token(now)
                self._note_ttft_locked(now - req._t_submit)
            self._stream_emit_locked(req, req.generated[-took:],
                                     req.log_probs[-took:])
            req._step += took
            self._positions[i] += took
            self._tokens[i] = col[took - 1]
            self._steps[i] += took
            emitted += took
            if done:
                self._retire(i)
        return emitted

    def _apply_oldest(self) -> int:
        """Fetch and fold the OLDEST in-flight chain: ONE batched
        ``jax.device_get`` for all of its ticks' tokens and log-probs
        (the drain point), then per-tick application under the host's
        own stop rules — the lag boundary where admission/stop/
        preemption decisions land.  A row whose slot no longer holds the
        launched request (retired, preempted or failed meanwhile) is
        discarded tick by tick; a preempted victim's discarded tokens
        regenerate bitwise on resume because its sampling stream is
        ``fold_in(key, step)`` replay.  Returns tokens emitted."""
        with self._lock:
            if not self._inflight:
                return 0
            active, reqs, ctoks, clogps, t0 = self._inflight.popleft()
        toks_np, logps_np = jax.device_get((ctoks, clogps))
        now = time.monotonic()
        emitted = 0
        with self._lock:
            chain = toks_np.shape[0]
            dt = (now - t0) / max(chain, 1)
            self._ema_tick_s = (dt if self._ema_tick_s is None
                                else 0.8 * self._ema_tick_s + 0.2 * dt)
            emitted = self._apply_chain_locked(active, reqs, toks_np,
                                               logps_np, now)
            self.ticks += chain
            self.ticked_tokens += emitted
            if obs_registry.publishing():
                self._m_ticks.inc(chain)
                self._m_tokens.inc(emitted)
                self._m_inflight.set(
                    self.pipeline_depth * len(self._inflight))
                self._m_active.set(
                    sum(r is not None and r._phase == "decode"
                        for r in self._slots))
                self._m_free_pages.set(self.pool.num_free)
                self._m_pages_cached.set(
                    len(self.cache) if self.cache else 0)
            self._publish_queued_locked()
        return emitted

    def _drain_pipeline(self) -> int:
        """Apply every in-flight chain and invalidate the device-resident
        pipeline carry — the boundary synchronization point: after this
        the host mirrors are exact and depth-0 stepping (admission,
        prefill, preemption) may run.  Returns tokens emitted."""
        emitted = 0
        while True:
            with self._lock:
                pending = bool(self._inflight)
            if not pending:
                break
            emitted += self._apply_oldest()
        with self._lock:
            self._pipe_state = None
            if obs_registry.publishing():
                self._m_inflight.set(0)
        return emitted

    def _step_pipelined(self) -> Optional[int]:
        """One pipelined driver step (``--tick_pipeline_depth N > 0``):
        launch the next N-tick chained program from DEVICE-RESIDENT slot
        state FIRST, then apply the previous launch's results while the
        device computes — scheduler decisions land at a one-launch
        (up-to-N-tick) lag.  Steady state only: any queued admission,
        live prefill or non-decode slot drains the pipeline and returns
        None, and the caller falls back to the depth-0 step for that
        boundary.

        Losslessness rests on three facts: the in-program stop/budget
        rules mirror the host's apply rules bit for bit, so a row the
        host retires was already frozen (null-routed) on device from the
        same tick onward — an in-flight chain never writes a page the
        host has released; the per-row sampling stream is
        ``fold_in(key, step)``, so discarded overrun draws replay
        bitwise after preemption; and per-row bits are batch-composition
        invariant, so freezing one row never changes another's tokens."""
        with self._lock:
            steady = (not self._queue and not self._prefill_q
                      and all(r is None or r._phase == "decode"
                              for r in self._slots))
            active = [i for i, r in enumerate(self._slots)
                      if r is not None and r._phase == "decode"]
        if not steady or not active:
            self._drain_pipeline()
            return None
        C = self.pipeline_depth
        with self._lock:
            # pre-grant pages out to TWO chains past the host's applied
            # frontier: the launch below starts up to C device ticks
            # ahead of the host positions (one unapplied chain) and runs
            # C more
            n0 = len(active)
            changed = self._pregrant_locked(active, 2 * C)
            if len(active) < n0:
                # a ledger-unreachable alloc failure just mutated slot
                # state under us — the device carry no longer matches the
                # host; resynchronize through the depth-0 boundary path
                active = []
            if not active:
                pass
            elif self._pipe_state is None:
                # boundary rebuild: the pipeline is drained, host
                # mirrors are exact — upload the full device state and
                # the per-row stop rules/budgets fresh
                if changed:
                    self._dirty = True
                (bt, pos, toks, keys, steps, temp, tk,
                 tp) = self._dev_state_locked()
                term = np.full((self.max_slots,), -1, np.int32)
                mode = np.zeros((self.max_slots,), np.int32)
                rem = np.zeros((self.max_slots,), np.int32)
                done = np.ones((self.max_slots,), np.bool_)
                for i in active:
                    req = self._slots[i]
                    done[i] = False
                    rem[i] = min(
                        req.max_new_tokens - len(req.generated),
                        self.max_seq - len(req.seq_tokens))
                    if req.stop_on_double_eol:
                        mode[i] = 2
                    elif req.stop_on_eol:
                        mode[i] = 1
                    elif (req.use_eod_for_termination
                          and req.termination_id is not None):
                        term[i] = req.termination_id
                self._pipe_state = (
                    self._asarray(term), self._asarray(mode),
                    self._asarray(done), self._asarray(rem))
            else:
                # steady chain: slot state and the stop/budget carry are
                # the previous launch's outputs, device-to-device; only
                # a pre-grant refreshes the (host-owned) table operand
                (bt, pos, toks, keys, steps, temp, tk,
                 tp) = self._dev_state
                if changed:
                    bt = self._asarray(self._block_tables)
                    self._dev_state = (bt, pos, toks, keys, steps,
                                       temp, tk, tp)
            if active:
                self.peak_active_slots = max(self.peak_active_slots,
                                             len(active))
                term_d, mode_d, done_d, rem_d = self._pipe_state
                reqs = [self._slots[i] for i in active]
        if not active:
            self._drain_pipeline()
            return None

        t0 = time.monotonic()
        gap = (None if self._last_dispatch_end is None
               else t0 - self._last_dispatch_end)
        with obs_trace.span("engine-chained-tick", active=len(active),
                            chain=C, tp=self._tp,
                            host_gap_ms=(None if gap is None
                                         else round(gap * 1e3, 4))), \
                self._overlap_span(), self._pp_span():
            (self.pool.k, self.pool.v, ctoks, clogps, new_pos, new_tok,
             new_steps, new_done, new_rem) = self._chained_tick()(
                self.params, self.pool.k, self.pool.v, bt, pos, toks,
                keys, steps, temp, tk, tp, term_d, mode_d, done_d,
                rem_d)
            self._last_dispatch_end = time.monotonic()
        self._note_host_gap(gap)
        with self._lock:
            self._dev_state = (bt, new_pos, new_tok, keys, new_steps,
                               temp, tk, tp)
            self._pipe_state = (term_d, mode_d, new_done, new_rem)
            self._inflight.append((active, reqs, ctoks, clogps, t0))
            depth_now = len(self._inflight)
            self._note_launches_locked(1, 0)
            if obs_registry.publishing():
                self._m_inflight.set(C * depth_now)
        if depth_now > 1:
            # apply the previous launch WHILE the device runs this one —
            # the overlap the whole mode exists for
            self._apply_oldest()
        return len(active)

    def _step_legacy(self) -> int:
        with self._lock:
            budget = self._prefill_budget_tokens()
            pre0 = self.prefill_tokens_computed
        did_prefill = 0
        for _ in range(max(1, budget // max(self.prefill_chunk, 1))):
            if not self._advance_prefill():
                break
            did_prefill += 1
        with self._lock:
            active = [i for i, r in enumerate(self._slots)
                      if r is not None and r._phase == "decode"]
            if not active:
                self._note_launches_locked(
                    did_prefill, self.prefill_tokens_computed - pre0)
                if obs_registry.publishing():
                    self._m_active.set(0)
                    self._m_free_pages.set(self.pool.num_free)
                    self._m_pages_cached.set(
                        len(self.cache) if self.cache else 0)
                self._publish_queued_locked()
                return did_prefill
            k_eff = self._prepare_decode_locked(active)
            if not active:
                self._note_launches_locked(
                    did_prefill, self.prefill_tokens_computed - pre0)
                return did_prefill
            self.peak_active_slots = max(self.peak_active_slots,
                                         len(active))
            bt, pos, toks, keys, steps, temp, tk, tp = \
                self._dev_state_locked()

        t_tick = time.monotonic()
        gap = (None if self._last_dispatch_end is None
               else t_tick - self._last_dispatch_end)
        gap_ms = None if gap is None else round(gap * 1e3, 4)
        if self.spec_k:
            with obs_trace.span("engine-spec-tick", active=len(active),
                                k=self.spec_k, tp=self._tp,
                                host_gap_ms=gap_ms), \
                    self._overlap_span(), self._pp_span():
                (self.pool.k, self.pool.v, self.pool.draft_k,
                 self.pool.draft_v, emit, emit_lp, acc, cnt,
                 new_pos, next_tok, new_steps) = self._spec_tick()(
                    self.params, self.draft_params,
                    self.pool.k, self.pool.v,
                    self.pool.draft_k, self.pool.draft_v,
                    bt, pos, toks, keys, steps, temp, tk, tp,
                    self._asarray(k_eff))
                self._last_dispatch_end = time.monotonic()
                # ONE batched host sync for the tick's emissions
                emit_np, lp_np, acc_np, m_np = jax.device_get(
                    (emit, emit_lp, acc, cnt))
        else:
            with obs_trace.span("engine-tick", active=len(active),
                                tp=self._tp, host_gap_ms=gap_ms), \
                    self._overlap_span(), self._pp_span():
                (self.pool.k, self.pool.v, next_tok, logp,
                 new_pos, new_steps) = self._tick()(
                    self.params, self.pool.k, self.pool.v,
                    bt, pos, toks, keys, steps, temp, tk, tp)
                self._last_dispatch_end = time.monotonic()
                next_np, logp_np = jax.device_get((next_tok, logp))
        self._note_host_gap(gap)

        now = time.monotonic()
        with self._lock:
            dt = now - t_tick  # feeds Retry-After/shed drain estimates
            self._ema_tick_s = (dt if self._ema_tick_s is None
                                else 0.8 * self._ema_tick_s + 0.2 * dt)
            if not self._dirty:
                # steady state: the tick already advanced the device mirror
                self._dev_state = (bt, new_pos, next_tok, keys, new_steps,
                                   temp, tk, tp)
            self.ticks += 1
            if self.spec_k:
                emitted = self._apply_spec_locked(
                    active, k_eff, emit_np, lp_np, acc_np, m_np, now)
            else:
                emitted = self._apply_plain_locked(
                    active, next_np, logp_np, now)
            self.ticked_tokens += emitted
            self._note_launches_locked(
                did_prefill + 1, self.prefill_tokens_computed - pre0)
            if obs_registry.publishing():
                self._m_ticks.inc()
                self._m_tokens.inc(emitted)
            if obs_registry.publishing():
                self._m_active.set(
                    sum(r is not None and r._phase == "decode"
                        for r in self._slots))
                self._m_free_pages.set(self.pool.num_free)
                self._m_pages_cached.set(
                    len(self.cache) if self.cache else 0)
            self._publish_queued_locked()
        return len(active) + did_prefill

    # -- the ragged tick (ISSUE 11) ----------------------------------------

    def _plan_ragged_prefill(self):  # holds _lock
        """Pack prefill-chunk rows for this tick under the policy's
        token budget.

        Chunks stay on the absolute ``prefill_chunk`` grid; multiple
        chunks — from one request or several, in the policy's prefill
        order — pack into the tick until the budget, the compiled row
        capacity, or the work runs out.  A later chunk of the same
        request may attend K/V a same-tick earlier chunk writes
        (write-then-attend holds across the whole ragged batch).  Row
        bits depend only on (token, position, horizon bucket), so ANY
        packing produces the bitwise output the one-chunk-per-tick
        legacy interleave produces.

        Returns ``(spans, pre_tok, pre_pos, pre_tables, pre_index,
        pre_hor, lp_live)`` where spans is ``[(req, start, end), ...]``,
        ``pre_tables``/``pre_index`` are the COMPRESSED block tables (one
        table per packed request, ``-1`` index = dead row), and
        ``lp_live`` flags return_log_probs prompts that must take the
        legacy teacher-forced chunk path instead."""
        Rp = self.prefill_rows
        pre_tok = np.zeros((Rp,), np.int32)
        pre_pos = np.zeros((Rp,), np.int32)
        pre_tables = np.full((self._pre_tables_cap, self.pages_per_seq),
                             NULL_PAGE, np.int32)
        pre_index = np.full((Rp,), -1, np.int32)
        pre_hor = np.zeros((Rp,), np.int32)
        spans: List[Tuple[EngineRequest, int, int]] = []
        live = [r for r in self._prefill_q if r._phase == "prefill"]
        if len(live) != len(self._prefill_q):  # failed/cancelled
            self._prefill_q = deque(live)
        lp_live = any(r.return_log_probs for r in live)
        live = [r for r in live if not r.return_log_probs]
        if not live:
            return (spans, pre_tok, pre_pos, pre_tables, pre_index,
                    pre_hor, lp_live)
        budget = min(self._prefill_budget_tokens(), Rp)
        order = self.policy.prefill_order(
            live, self._sched_state(time.monotonic()))
        used = 0
        n_req = 0
        ps = self.page_size
        chunk = self.prefill_chunk
        for req in order:
            if n_req >= self._pre_tables_cap:
                break  # table slots exhausted; the rest wait a tick
            seq = req.seq_tokens  # resumed requests re-prefill their tail
            prompt_len = len(seq)
            fill_end = _bucket_up(prompt_len, ps)
            pos = req._fill_pos
            if pos >= fill_end or used >= budget:
                continue
            pre_tables[n_req, : len(req._pages)] = req._pages
            while pos < fill_end and used < budget:
                # absolute-grid chunk boundary (first/last may be short);
                # a budget cut mid-chunk is fine — the next tick's chunk
                # re-anchors on the grid
                end = min(fill_end, (pos // chunk + 1) * chunk,
                          pos + (budget - used))
                for p in range(pos, end):
                    pre_tok[used] = seq[p] if p < prompt_len else 0
                    pre_pos[used] = p
                    pre_index[used] = n_req
                    pre_hor[used] = _bucket_up(p + 1)
                    used += 1
                spans.append((req, pos, end))
                pos = end
            n_req += 1
            if used >= budget:
                break
        return (spans, pre_tok, pre_pos, pre_tables, pre_index,
                pre_hor, lp_live)

    def _apply_ragged_prefill_locked(self, spans, tick_s: float = 0.0,
                                     work_rows: int = 0
                                     ) -> None:  # holds _lock
        """Advance the packed requests' fill frontiers; a request whose
        bucketed prompt completed inserts its full pages into the prefix
        trie (refeed page excluded — shared pages immutable from birth)
        and activates into decode, exactly like _advance_prefill's
        completion tail.  ``tick_s``/``work_rows`` attribute the fused
        launch's wall time to each request's flight record
        proportionally to its rows — an estimate by construction (the
        launch is ONE program), documented as such."""
        ps = self.page_size
        for req, start, end in spans:
            if req._phase != "prefill":  # failed mid-step (defensive)
                continue
            req._fill_pos = end
            rows = end - start
            self.prefill_tokens_computed += rows
            req._flight.event("prefill_chunk", start=start, end=end,
                              rows=rows,
                              fill_end=_bucket_up(len(req.seq_tokens), ps))
            if work_rows > 0:
                req._flight.add_prefill_compute(tick_s * rows / work_rows)
            if obs_registry.publishing():
                self._m_prefill_tokens.inc(rows)
            seq = req.seq_tokens
            if end >= _bucket_up(len(seq), ps):
                self._prefill_q.remove(req)
                if self.cache is not None:
                    self.cache.insert(seq, req._pages,
                                      (len(seq) - 1) // ps)
                self._activate_or_handoff(req, req._slot)

    def _step_ragged(self) -> int:
        """One fused ragged tick: decode slots + verify blocks + packed
        prefill-chunk rows, ONE compiled attention launch
        (generation/ragged.py).  return_log_probs prompts are the one
        carve-out — their teacher-forced chunk keeps the legacy program
        (counted honestly in the launch telemetry)."""
        with self._lock:
            pre0 = self.prefill_tokens_computed
            (spans, pre_tok, pre_pos, pre_tables, pre_index, pre_hor,
             lp_live) = self._plan_ragged_prefill()
        did_lp = 1 if lp_live and self._advance_prefill(
            only_log_probs=True) else 0
        with self._lock:
            active = [i for i, r in enumerate(self._slots)
                      if r is not None and r._phase == "decode"]
            if active:
                k_eff = self._prepare_decode_locked(active)
            else:
                k_eff = np.zeros((self.max_slots,), np.int32)
            if not active and not spans:
                self._note_launches_locked(
                    did_lp, self.prefill_tokens_computed - pre0)
                if obs_registry.publishing():
                    self._m_active.set(0)
                    self._m_free_pages.set(self.pool.num_free)
                    self._m_pages_cached.set(
                        len(self.cache) if self.cache else 0)
                self._publish_queued_locked()
                return did_lp
            self.peak_active_slots = max(self.peak_active_slots,
                                         len(active))
            bt, pos, toks, keys, steps, temp, tk, tp = \
                self._dev_state_locked()

        n_pre = sum(end - start for _, start, end in spans)
        # live prefill rows bucketed to chunk multiples: the program's one
        # shape knob (a dead-row-free decode tick at 0; composition within
        # a bucket is pure data)
        n_bucket = (min(self.prefill_rows,
                        _bucket_up(n_pre, self.prefill_chunk))
                    if n_pre else 0)
        t_tick = time.monotonic()
        gap = (None if self._last_dispatch_end is None
               else t_tick - self._last_dispatch_end)
        with obs_trace.span("engine-ragged-tick", active=len(active),
                            prefill_tokens=n_pre, launches=1,
                            k=self.spec_k, tp=self._tp,
                            host_gap_ms=(None if gap is None
                                         else round(gap * 1e3, 4))), \
                self._overlap_span(), self._pp_span():
            pre_args = () if not n_bucket else (
                self._asarray(pre_tok[:n_bucket]),
                self._asarray(pre_pos[:n_bucket]),
                self._asarray(pre_tables),
                self._asarray(pre_index[:n_bucket]),
                self._asarray(pre_hor[:n_bucket]))
            tick_fn = self._ragged_tick(n_bucket)
            if self.spec_k:
                (self.pool.k, self.pool.v, self.pool.draft_k,
                 self.pool.draft_v, emit, emit_lp, acc, cnt,
                 new_pos, next_tok, new_steps) = tick_fn(
                    self.params, self.draft_params,
                    self.pool.k, self.pool.v,
                    self.pool.draft_k, self.pool.draft_v,
                    bt, pos, toks, keys, steps, temp, tk, tp,
                    self._asarray(k_eff), *pre_args)
                self._last_dispatch_end = time.monotonic()
                # ONE batched host sync for the tick's emissions
                emit_np, lp_np, acc_np, m_np = jax.device_get(
                    (emit, emit_lp, acc, cnt))
            else:
                (self.pool.k, self.pool.v, next_tok, logp,
                 new_pos, new_steps) = tick_fn(
                    self.params, self.pool.k, self.pool.v,
                    bt, pos, toks, keys, steps, temp, tk, tp,
                    *pre_args)
                self._last_dispatch_end = time.monotonic()
                next_np, logp_np = jax.device_get((next_tok, logp))
        self._note_host_gap(gap)

        now = time.monotonic()
        with self._lock:
            dt = now - t_tick  # feeds Retry-After/shed drain estimates
            self._ema_tick_s = (dt if self._ema_tick_s is None
                                else 0.8 * self._ema_tick_s + 0.2 * dt)
            if not self._dirty:
                # steady state: the tick already advanced the device mirror
                self._dev_state = (bt, new_pos, next_tok, keys, new_steps,
                                   temp, tk, tp)
            self.ticks += 1
            if self.spec_k:
                emitted = self._apply_spec_locked(
                    active, k_eff, emit_np, lp_np, acc_np, m_np, now)
            else:
                emitted = self._apply_plain_locked(
                    active, next_np, logp_np, now)
            self._apply_ragged_prefill_locked(
                spans, tick_s=dt, work_rows=n_pre + len(active))
            self.ticked_tokens += emitted
            self._note_launches_locked(
                1 + did_lp, self.prefill_tokens_computed - pre0)
            if obs_registry.publishing():
                self._m_ticks.inc()
                self._m_tokens.inc(emitted)
                self._m_active.set(
                    sum(r is not None and r._phase == "decode"
                        for r in self._slots))
                self._m_free_pages.set(self.pool.num_free)
                self._m_pages_cached.set(
                    len(self.cache) if self.cache else 0)
            self._publish_queued_locked()
        return len(active) + (1 if spans else 0) + did_lp

    def run_until_idle(self) -> None:
        """Drive ticks on the calling thread until queue and slots drain.
        Safe under concurrent callers: one drives at a time, the rest take
        over as the lock frees (their requests are served either way)."""
        while True:
            with self._drive_lock:
                n = self.step()
            if n == 0:
                with self._lock:
                    if not self._queue and all(
                            r is None for r in self._slots):
                        return

    # -- background scheduler ---------------------------------------------

    def start(self) -> None:
        """Run the scheduler loop in a daemon thread (server mode)."""
        if self._thread is not None:
            return
        # under _work: a racing stop() must not interleave between this
        # write and the thread starting (found by graftcheck's
        # lock-discipline rule — the write was bare)
        with self._work:
            self._stopping = False
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        with self._work:
            self._stopping = True
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    def _loop(self) -> None:
        while True:
            with self._work:
                # an in-flight chained launch keeps the loop stepping:
                # its apply may retire rows (and must not be stranded
                # when every slot empties before it lands)
                while (not self._stopping and not self._queue
                       and all(r is None for r in self._slots)
                       and not self._inflight):
                    self._work.wait()
                if self._stopping:
                    break
            with self._drive_lock:
                self.step()
        with self._drive_lock:
            self._drain_pipeline()

    # -- server-facing API (api.InferenceEngine surface) -------------------

    def generate_and_post_process(
        self,
        prompts: Sequence[str],
        tokens_to_generate: int = 0,
        return_output_log_probs: bool = False,
        top_k_sampling: int = 0,
        top_p_sampling: float = 0.0,
        temperature: float = 1.0,
        add_BOS: bool = False,
        use_eod_token_for_early_termination: bool = True,
        stop_on_double_eol: bool = False,
        stop_on_eol: bool = False,
        random_seed: int = -1,
        priority: int = 1,
        ttft_deadline_ms: Optional[float] = None,
        tpot_deadline_ms: Optional[float] = None,
        trace_id: str = "",
    ):
        """Drop-in for api.generate_and_post_process: tokenize, submit each
        prompt as its own request (all of them share decode ticks), wait,
        detokenize.  ``tokens_to_generate == 0`` (scoring mode) delegates to
        the dense-path scorer."""
        tok = self.tokenizer
        if tokens_to_generate == 0:
            return self._legacy().generate_and_post_process(
                prompts, 0, return_output_log_probs=True, add_BOS=add_BOS)

        termination_id = getattr(self.cfg.model, "eos_id", None) or tok.eod
        bos = getattr(tok, "bos_token_id", None) or getattr(tok, "bos", None)
        reqs = []
        for i, prompt in enumerate(prompts):
            ids = tok.tokenize(prompt)
            if add_BOS:
                ids = [bos if bos is not None else tok.eod] + ids
            reqs.append(self.submit(
                ids, tokens_to_generate,
                temperature=temperature, top_k=top_k_sampling,
                top_p=top_p_sampling, termination_id=termination_id,
                use_eod_for_termination=use_eod_token_for_early_termination,
                stop_on_double_eol=stop_on_double_eol,
                stop_on_eol=stop_on_eol,
                seed=None if random_seed == -1 else random_seed + i,
                return_log_probs=return_output_log_probs,
                priority=priority,
                ttft_deadline_ms=ttft_deadline_ms,
                tpot_deadline_ms=tpot_deadline_ms,
                trace_id=trace_id,
            ))
        if self._thread is None:
            self.run_until_idle()
        rows = [r.result(timeout=600) for r in reqs]

        lengths = [len(t) for t, _ in rows]
        width = max(lengths)
        tokens = np.zeros((len(rows), width), np.int32)
        for i, (t, _) in enumerate(rows):
            tokens[i, : len(t)] = t
        tokens, texts, segments = detokenize_generations(
            tok, tokens, np.asarray(lengths), True)
        if return_output_log_probs:
            log_probs = [
                (r.prompt_log_probs or []) + r.log_probs for r in reqs]
            log_probs = [
                lp[: len(seg) - 1] for lp, seg in zip(log_probs, segments)]
        else:
            log_probs = None
        return texts, segments, log_probs, tokens

    def submit_stream_request(
        self,
        prompt: str,
        tokens_to_generate: int,
        return_output_log_probs: bool = False,
        top_k_sampling: int = 0,
        top_p_sampling: float = 0.0,
        temperature: float = 1.0,
        add_BOS: bool = False,
        stop_on_double_eol: bool = False,
        stop_on_eol: bool = False,
        random_seed: int = -1,
        priority: int = 1,
        ttft_deadline_ms: Optional[float] = None,
        tpot_deadline_ms: Optional[float] = None,
        trace_id: str = "",
        stream_events: int = 256,
    ):
        """``submit_stream`` with ``generate_and_post_process``'s exact
        tokenization and submit kwargs for ONE prompt — the streamed
        request must sample the identical token sequence the buffered
        path would (same seed handling, same termination id), or the
        ``done`` event could not carry the identical body."""
        tok = self.tokenizer
        if tokens_to_generate < 1:
            raise ValueError("streaming requires tokens_to_generate >= 1")
        termination_id = getattr(self.cfg.model, "eos_id", None) or tok.eod
        bos = getattr(tok, "bos_token_id", None) or getattr(tok, "bos", None)
        ids = tok.tokenize(prompt)
        if add_BOS:
            ids = [bos if bos is not None else tok.eod] + ids
        return self.submit_stream(
            ids, tokens_to_generate,
            stream_events=stream_events,
            temperature=temperature, top_k=top_k_sampling,
            top_p=top_p_sampling, termination_id=termination_id,
            stop_on_double_eol=stop_on_double_eol,
            stop_on_eol=stop_on_eol,
            seed=None if random_seed == -1 else random_seed,
            return_log_probs=return_output_log_probs,
            priority=priority,
            ttft_deadline_ms=ttft_deadline_ms,
            tpot_deadline_ms=tpot_deadline_ms,
            trace_id=trace_id,
        )

    def finalize_stream_request(self, req: EngineRequest,
                                return_output_log_probs: bool = False):
        """Post-process one FINISHED streamed request with the exact
        ``generate_and_post_process`` tail (same padding, detokenization
        and log-prob slicing), so a streamed ``done`` body and the
        buffered response for the same request are token-identical.
        Returns ``(texts, segments, log_probs)``."""
        assert req.finished and not req.error, "request not cleanly finished"
        tok = self.tokenizer
        row = list(req.prompt) + req.generated
        tokens = np.zeros((1, len(row)), np.int32)
        tokens[0, :] = row
        tokens, texts, segments = detokenize_generations(
            tok, tokens, np.asarray([len(row)]), True)
        if return_output_log_probs:
            log_probs = [(req.prompt_log_probs or []) + req.log_probs]
            log_probs = [
                lp[: len(seg) - 1] for lp, seg in zip(log_probs, segments)]
        else:
            log_probs = None
        return texts, segments, log_probs

    # -- cross-replica KV handoff (ISSUE 19, serving/handoff/) -------------

    def prefill_and_export(self, prompt, *, add_BOS: bool = False,
                           trace_id: str = "", timeout_s: float = 600.0):
        """Prefill ``prompt`` (str — tokenized exactly like
        ``generate_and_post_process`` — or token ids) WITHOUT decoding,
        and export its full KV pages as a handoff wire blob.

        The request runs the normal admission/chunked-prefill path
        (trie hits included) but parks in the ``handoff`` phase instead
        of activating into decode; the export reads its pages under
        ``_drive_lock`` (serialized against tick dispatch — ticks
        donate the pool buffers) while the request's refs keep the
        bytes stable, then retires it — prompt pages stay in the trie
        cached-idle, so repeated long prompts skip recompute on the
        prefill tier too.  Only FULL pages the refeed tick never writes
        are exported (``(len(prompt) - 1) // page_size``, the exact
        ``PrefixCache.insert`` rule), so the receiving trie can share
        them as immutable from birth.

        Returns ``(blob, info)`` — ``info`` has ``tokens`` / ``pages``
        / ``bytes`` / ``hit_tokens`` for the migration receipt."""
        from megatron_llm_tpu.serving.handoff import wire

        tok = self.tokenizer
        if isinstance(prompt, str):
            bos = (getattr(tok, "bos_token_id", None)
                   or getattr(tok, "bos", None))
            ids = tok.tokenize(prompt)
            if add_BOS:
                ids = [bos if bos is not None else tok.eod] + ids
        else:
            ids = [int(t) for t in prompt]
        req = self.submit(ids, 1, top_k=1, use_eod_for_termination=False,
                          prefill_only=True, trace_id=trace_id)
        if self._thread is None:
            self.run_until_idle()
        if not req._done.wait(timeout_s):
            raise TimeoutError("handoff prefill did not finish in time")
        if req.shed:
            raise RequestShed(req.error or "request shed",
                              retry_after=req.shed_retry_after)
        if req.error:
            raise RuntimeError(req.error)
        ps = self.page_size
        n = (len(ids) - 1) // ps
        blob = None
        pages: List[int] = []
        try:
            with self._drive_lock:
                with self._lock:
                    pages = list(req._pages[:n])
                leaves = self.pool.export_pages(pages)
            blob = wire.encode_pages(ids[: len(pages) * ps], ps,
                                     self.kv_dtype, leaves)
        finally:
            with self._lock:
                if blob is not None:
                    req._flight.event("kv_export", pages=len(pages),
                                      bytes=len(blob))
                    if obs_registry.publishing():
                        self._m_kv_export_pages.inc(len(pages))
                        self._m_kv_export_bytes.inc(len(blob))
                self._finish_handoff_locked(req, pages=len(pages))
        return blob, {"tokens": len(pages) * ps, "pages": len(pages),
                      "bytes": len(blob), "hit_tokens": req._hit_tokens}

    def export_cached_kv(self, tokens, *, trace_id: str = ""):
        """Export the longest trie-cached prefix of ``tokens`` (ids) as
        a handoff blob — the migration path for state that is already
        parked in the prefix cache (e.g. a preempted request's finished
        pages).  Returns ``(blob, n_pages)``; ``n_pages`` may be 0 when
        nothing is cached."""
        from megatron_llm_tpu.serving.handoff import wire

        if self.cache is None:
            raise ValueError("prefix cache disabled; nothing to export")
        tokens = [int(t) for t in tokens]
        ps = self.page_size
        with self._drive_lock:
            with self._lock:
                matched = self.cache.match(tokens, len(tokens) // ps)
            try:
                leaves = self.pool.export_pages(matched)
            finally:
                with self._lock:
                    self.pool.release(matched)
        blob = wire.encode_pages(tokens[: len(matched) * ps], ps,
                                 self.kv_dtype, leaves)
        if matched and obs_registry.publishing():
            with self._lock:
                self._m_kv_export_pages.inc(len(matched))
                self._m_kv_export_bytes.inc(len(blob))
        return blob, len(matched)

    def import_kv(self, blob: bytes, *, trace_id: str = "") -> dict:
        """Install a pushed handoff blob: decode the wire format,
        allocate pages for the UNCACHED suffix (trie incumbents win —
        dedup is free), upload the exact bytes, and register the pages
        via ``PrefixCache.insert`` + release — they end cached-idle,
        indistinguishable from a locally prefilled-then-parked prefix,
        so COW/refcount/eviction invariants hold unchanged.  Raises
        :class:`EngineOverloaded` (→ 503 + Retry-After) when the pool
        cannot hold the pages.  Returns the import receipt."""
        from megatron_llm_tpu.serving.handoff import wire

        payload = wire.decode_pages(blob)
        if self.cache is None:
            raise ValueError("prefix cache disabled; cannot import KV pages")
        if payload.page_size != self.page_size:
            raise ValueError(
                f"handoff page_size {payload.page_size} != engine "
                f"page_size {self.page_size}")
        if payload.kv_dtype != self.kv_dtype:
            raise ValueError(
                f"handoff kv_dtype {payload.kv_dtype!r} != engine "
                f"kv_dtype {self.kv_dtype!r}")
        n = payload.n_pages
        rec = self.flight.open(trace_id, kind="kv_import", pages=n)
        try:
            if n == 0:
                return {"pages": 0, "installed": 0, "deduped": 0,
                        "tokens": 0}
            with obs_trace.span("kv-import", pages=n, trace_id=trace_id):
                with self._drive_lock:
                    with self._lock:
                        matched = self.cache.match(payload.tokens, n)
                        covered = len(matched)
                        fresh = (self.pool.alloc(n - covered)
                                 if covered < n else [])
                        if fresh is None:
                            self.pool.release(matched)
                            raise EngineOverloaded(
                                f"KV pool cannot hold {n - covered} "
                                f"pushed pages",
                                retry_after=self._drain_eta(
                                    len(self._queue)),
                                info=self._overload_info())
                    try:
                        if fresh:
                            # device upload outside _lock: the fresh
                            # pages are refcount-1 and unshared, and
                            # _drive_lock serializes vs tick dispatch
                            self.pool.import_pages(fresh, {
                                name: arr[:, covered:]
                                for name, arr in payload.leaves.items()})
                    except Exception:
                        with self._lock:
                            self.pool.release(matched)
                            self.pool.release(fresh)
                        raise
                    with self._lock:
                        installed = self.cache.insert(
                            payload.tokens, matched + fresh, n)
                        # inserted pages go cached-idle; duplicates
                        # (trie incumbents won the position) go free
                        self.pool.release(matched)
                        self.pool.release(fresh)
                        if obs_registry.publishing():
                            self._m_kv_import_pages.inc(installed)
                            self._m_kv_import_bytes.inc(len(blob))
            receipt = {"pages": n, "installed": installed,
                       "deduped": n - installed,
                       "tokens": len(payload.tokens)}
            rec.event("kv_import", bytes=len(blob), **receipt)
            rec.finish("ok")
            return receipt
        except Exception as e:  # noqa: BLE001 — record then surface
            rec.finish("error", error=f"{type(e).__name__}: {e}")
            raise
        finally:
            self.flight.close(rec)

    def _legacy(self):
        """A dense-path InferenceEngine view over the SAME (already
        quantized) params — bypasses __init__ so int8 weights are not
        re-quantized."""
        from megatron_llm_tpu.generation.api import InferenceEngine

        legacy = InferenceEngine.__new__(InferenceEngine)
        legacy.cfg, legacy.params, legacy.tokenizer = (
            self.cfg, self.params, self.tokenizer)
        return legacy

    def beam_search_and_post_process(self, *args, **kw):
        """Beam search stays on the dense single-stream path (api.py)."""
        return self._legacy().beam_search_and_post_process(*args, **kw)
