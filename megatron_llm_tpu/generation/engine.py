"""Continuous-batching decode engine on a paged KV cache.

The legacy serving shape (generation/api.InferenceEngine) is the paper's:
one request at a time, a dense ``[L, b, max_seq, nkv, d]`` cache allocated
per call, and a program compiled per (batch, max_seq) bucket.  This engine
is the TPU-serving shape the Ragged-Paged-Attention and Gemma-on-Cloud-TPU
studies (PAPERS.md) converge on: keep ONE fixed-shape decode program
resident and keep its batch full.

* **Paged KV pool** (:class:`PagedKVPool`): all in-flight sequences share a
  ``[L, num_pages, page_size, nkv, d]`` pool; a sequence owns an ordered
  page list (its block table).  Admission allocates the full page budget
  ``ceil(min(prompt+max_new, max_seq)/page_size)`` up front — no mid-flight
  preemption — and frees it the moment the request finishes, so short
  requests return pages while long ones keep decoding.  Page 0 is the
  reserved *null page*: idle slots' block tables point at it and their
  writes land there, never attended.

* **Slots + fixed shapes**: the decode tick runs ``max_slots`` rows every
  time, active or not.  Block tables, positions, per-slot sampling params
  and per-slot PRNG keys are *traced* inputs, so the tick compiles ONCE;
  prefill compiles once per prompt-length bucket (BUCKET multiples, same
  policy as generation/api.py).  Off-by-default slots cost one row of
  wasted FLOPs — the price of never recompiling.

* **Scheduler**: ``submit`` enqueues; admission fills free slots whenever
  slots+pages allow (FCFS).  A prefill runs the prompt through the dense
  cache path once (no logits head — ``logits_postprocess=False``) and
  scatters the resulting K/V into the request's pages; the slot then joins
  the shared per-tick decode.  The first generated token is sampled by the
  slot's first tick, which re-feeds the last prompt token at position
  ``prompt_len - 1`` (rewriting that K/V entry with identical values), so
  every sampled token flows through the same tick program.

* **Decode tick**: one fused jitted step — embed [slots, 1] tokens, write
  each row's K/V into its current page, paged attention over block tables
  (Pallas kernel on TPU, jnp gather fallback elsewhere —
  ops/paged_attention.py), per-slot sampling (sampling.sample_per_slot),
  token log-probs.  Pool buffers are donated, so the cache updates in
  place.

Threading: ``submit`` may be called from any thread (e.g. concurrent HTTP
handlers — generation/server.py); device work happens on whichever thread
drives :meth:`step`, either the built-in background loop (:meth:`start`) or
a caller loop (:meth:`run_until_idle`).
"""

from __future__ import annotations

import dataclasses
import os
import threading
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from megatron_llm_tpu.generation import generation as gen
from megatron_llm_tpu.generation.sampling import sample_per_slot
from megatron_llm_tpu.observability import registry as obs_registry
from megatron_llm_tpu.observability import trace as obs_trace
from megatron_llm_tpu.generation.tokenization import detokenize_generations
from megatron_llm_tpu.models.language_model import (
    _compute_dtype,
    make_rope_cache,
    model_forward,
)
from megatron_llm_tpu.ops.paged_attention import PagedState

NULL_PAGE = 0


def _bucket_up(n: int, bucket: int = gen.BUCKET) -> int:
    return -(-n // bucket) * bucket


class PagedKVPool:
    """Device page pool + host free-list allocator.

    The device arrays are plain stacked pytrees ``[L, P, page, nkv, d]``
    (scanned over L exactly like the dense cache); the allocator is
    host-side python — alloc/free happen at request admission/retirement,
    thousands of times below tick frequency.
    """

    def __init__(self, cfg, num_pages: int, page_size: int, dtype=None):
        m = cfg.model
        dtype = dtype or _compute_dtype(cfg)
        shape = (m.num_layers, num_pages, page_size,
                 m.num_attention_heads_kv, m.kv_channels)
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        self.num_pages = num_pages
        self.page_size = page_size
        # page 0 reserved as the null page (never allocated)
        self._free: deque = deque(range(1, num_pages))

    @property
    def num_free(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` pages, or None if the pool can't satisfy the request."""
        if n > len(self._free):
            return None
        return [self._free.popleft() for _ in range(n)]

    def free(self, pages: Sequence[int]) -> None:
        for p in pages:
            assert p != NULL_PAGE, "null page is never allocated"
            self._free.append(p)


@dataclasses.dataclass
class EngineRequest:
    """One in-flight generation; ``result()`` blocks until finished."""

    prompt: List[int]
    max_new_tokens: int
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 0.0
    termination_id: Optional[int] = None
    use_eod_for_termination: bool = True
    stop_on_double_eol: bool = False
    stop_on_eol: bool = False
    seed: Optional[int] = None
    return_log_probs: bool = False

    # engine-filled state
    generated: List[int] = dataclasses.field(default_factory=list)
    log_probs: List[float] = dataclasses.field(default_factory=list)
    prompt_log_probs: Optional[List[float]] = None
    finished: bool = False
    error: Optional[str] = None
    _done: threading.Event = dataclasses.field(
        default_factory=threading.Event, repr=False)
    _pages: List[int] = dataclasses.field(default_factory=list, repr=False)
    _step: int = 0  # decode ticks taken (== len(generated))

    def result(self, timeout: Optional[float] = None):
        """Wait for completion; returns (full token list, gen log-probs)."""
        if not self._done.wait(timeout):
            raise TimeoutError("generation did not finish in time")
        if self.error:
            raise RuntimeError(self.error)
        return list(self.prompt) + self.generated, list(self.log_probs)


class ContinuousBatchingEngine:
    """Shared-tick decode over a paged pool; the serving tentpole."""

    def __init__(self, cfg, params, tokenizer=None, *,
                 max_slots: Optional[int] = None,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 max_seq: Optional[int] = None):
        inf = cfg.inference
        self.cfg = cfg
        if inf.int8_weights:
            # same decode-weight quantization contract as api.InferenceEngine
            from megatron_llm_tpu.ops.quant import quantize_layer_weights_int8

            params = quantize_layer_weights_int8(params)
        self.params = params
        self.tokenizer = tokenizer
        self.max_slots = max_slots or inf.max_batch_slots
        self.page_size = page_size or inf.page_size
        self.max_seq = (max_seq or inf.engine_max_seq
                        or min(cfg.data.seq_length,
                               cfg.model.max_position_embeddings))
        assert self.max_seq <= cfg.model.max_position_embeddings
        assert gen.BUCKET % self.page_size == 0, (
            "page_size must divide the prefill bucket so bucketed prefills "
            "scatter whole pages")
        self.pages_per_seq = -(-self.max_seq // self.page_size)
        num_pages = (num_pages or inf.kv_pool_pages
                     or self.max_slots * self.pages_per_seq + 1)
        self.pool = PagedKVPool(cfg, num_pages, self.page_size)

        s = self.max_slots
        self._block_tables = np.zeros((s, self.pages_per_seq), np.int32)
        self._positions = np.zeros((s,), np.int32)
        self._tokens = np.zeros((s,), np.int32)
        self._temperature = np.ones((s,), np.float32)
        self._top_k = np.ones((s,), np.int32)  # idle slots decode greedy
        self._top_p = np.zeros((s,), np.float32)
        self._keys = np.zeros((s, 2), np.uint32)
        self._steps = np.zeros((s,), np.int32)
        self._slots: List[Optional[EngineRequest]] = [None] * s

        self._queue: deque = deque()
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        # serializes device-driving (step) across caller threads; state
        # mutation is under _lock, device dispatch under _drive_lock
        self._drive_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stopping = False

        self._tick_fn = None
        self._prefill_fns: Dict[Tuple[int, bool], object] = {}
        # device mirror of the per-slot arrays; rebuilt from the host copies
        # whenever admission/retirement changes the slot layout
        self._dev_state: Optional[Tuple] = None
        self._dirty = True
        # tick telemetry for the decode bench
        self.ticks = 0
        self.ticked_tokens = 0
        # registry instruments, resolved once (observability/registry.py):
        # per-tick updates must stay dict-free on the scheduler thread
        reg = obs_registry.get_registry()
        self._m_requests = reg.counter(
            "mlt_engine_requests_total", help="generations submitted")
        self._m_ticks = reg.counter(
            "mlt_engine_ticks_total", help="fused decode ticks run")
        self._m_tokens = reg.counter(
            "mlt_engine_ticked_tokens_total",
            help="slot-steps advanced (tokens sampled) across ticks")
        self._m_active = reg.gauge(
            "mlt_engine_active_slots", help="decode slots occupied")
        self._m_queued = reg.gauge(
            "mlt_engine_queued_requests", help="requests awaiting a slot")
        self._m_free_pages = reg.gauge(
            "mlt_engine_free_pages", help="KV pool pages free")
        reg.gauge("mlt_engine_max_slots",
                  help="decode slots in the tick program").set(self.max_slots)
        reg.gauge("mlt_engine_pool_pages",
                  help="allocatable KV pool pages (null page excluded)"
                  ).set(self.pool.num_pages - 1)

    # -- compiled programs -------------------------------------------------

    def _tick(self):
        """The fused decode-tick program, compiled once per (config, engine
        geometry) — shared ACROSS engine instances via the fingerprint-keyed
        generation cache, so rebuilding an engine never recompiles."""
        if self._tick_fn is not None:
            return self._tick_fn
        cfg = self.cfg
        m = cfg.model

        def tick(params, pool_k, pool_v, block_tables, positions, tokens,
                 req_keys, steps, temperature, top_k, top_p):
            rope = make_rope_cache(cfg)
            logits, (pool_k, pool_v) = model_forward(
                cfg, params, tokens[:, None],
                position_ids=positions[:, None],
                rope_cache=rope, kv_caches=(pool_k, pool_v),
                paged=PagedState(block_tables, positions),
            )
            last = logits[:, -1]
            keys = jax.vmap(jax.random.fold_in)(req_keys, steps)
            next_tok = sample_per_slot(
                keys, last, top_k=top_k, top_p=top_p,
                temperature=temperature, vocab_size=m.vocab_size)
            logp = gen._gather_token_log_probs(last, next_tok)
            # advance the device-resident slot state in-program so steady
            # ticks need no host->device uploads (step() re-uploads from the
            # host copy only after admit/retire dirties the layout)
            return (pool_k, pool_v, next_tok, logp,
                    positions + 1, steps + 1)

        statics = ("engine_tick", self.max_slots, self.pages_per_seq,
                   self.page_size, self.pool.num_pages, str(self.pool.k.dtype))
        self._tick_fn = gen.cached_jit(
            self.cfg, "engine_tick", statics, lambda: tick,
            donate_argnums=(1, 2))
        return self._tick_fn

    def _prefill(self, s_pre: int, with_log_probs: bool):
        key = (s_pre, with_log_probs)
        fn = self._prefill_fns.get(key)
        if fn is not None:
            return fn
        cfg = self.cfg
        L = cfg.model.num_layers
        nkv, d = cfg.model.num_attention_heads_kv, cfg.model.kv_channels
        page = self.page_size
        npg = s_pre // page

        def prefill(params, tokens, pool_k, pool_v, page_ids):
            caches = gen.init_kv_caches(cfg, 1, s_pre, pool_k.dtype)
            out, (ck, cv) = model_forward(
                cfg, params, tokens,
                position_ids=jnp.arange(s_pre)[None, :],
                rope_cache=make_rope_cache(cfg),
                kv_caches=caches, cache_index=jnp.int32(0),
                logits_postprocess=with_log_probs,
            )
            pages_k = ck.reshape(L, npg, page, nkv, d)
            pages_v = cv.reshape(L, npg, page, nkv, d)
            pool_k = pool_k.at[:, page_ids].set(pages_k)
            pool_v = pool_v.at[:, page_ids].set(pages_v)
            if with_log_probs:
                # teacher-forced prompt log-probs (api logprobs contract)
                lp = gen._gather_token_log_probs(out[:, :-1], tokens[:, 1:])
                return pool_k, pool_v, lp[0]
            return pool_k, pool_v

        statics = (s_pre, with_log_probs, self.page_size,
                   self.pool.num_pages, str(self.pool.k.dtype))
        fn = gen.cached_jit(self.cfg, "engine_prefill", statics,
                            lambda: prefill, donate_argnums=(2, 3))
        self._prefill_fns[key] = fn
        return fn

    # -- request lifecycle -------------------------------------------------

    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               **kw) -> EngineRequest:
        """Enqueue a generation; returns the request future.

        Raises ValueError for requests that can never fit (the legacy
        engine's request-size guard, generation/api._check_limits)."""
        prompt = [int(t) for t in prompt]
        if len(prompt) < 1:
            raise ValueError("prompt must contain at least one token")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(prompt) + max_new_tokens > self.max_seq:
            raise ValueError(
                "Length of prompt + tokens_to_generate longer than allowed")
        req = EngineRequest(prompt=prompt, max_new_tokens=max_new_tokens, **kw)
        with obs_trace.span("engine-enqueue", prompt_len=len(prompt)):
            with self._work:
                self._queue.append(req)
                if obs_registry.publishing():
                    self._m_requests.inc()
                    self._m_queued.set(len(self._queue))
                self._work.notify()
        return req

    def _pages_needed(self, req: EngineRequest) -> int:
        total = min(len(req.prompt) + req.max_new_tokens, self.max_seq)
        return -(-total // self.page_size)

    def _admit(self) -> None:
        """Move queued requests into free slots while slots+pages allow.

        FCFS admission: blocks behind the queue head rather than starving
        large requests (pages for the whole request are reserved here, so an
        admitted request can always run to its budget)."""
        while True:
            with self._lock:
                if not self._queue:
                    return
                try:
                    slot = self._slots.index(None)
                except ValueError:
                    return
                req = self._queue[0]
                pages = self.pool.alloc(self._pages_needed(req))
                if pages is None:
                    return
                self._queue.popleft()
            try:
                self._place(req, slot, pages)
            except Exception as e:  # noqa: BLE001 — surface to the waiter
                self.pool.free(pages)
                req.error = f"{type(e).__name__}: {e}"
                req.finished = True
                req._done.set()

    def _place(self, req: EngineRequest, slot: int, pages: List[int]) -> None:
        """Prefill the prompt into ``pages`` and activate the slot."""
        prompt_len = len(req.prompt)
        s_pre = min(_bucket_up(prompt_len), _bucket_up(self.max_seq))
        tokens = np.zeros((1, s_pre), np.int32)
        tokens[0, :prompt_len] = req.prompt
        # pages for the bucket-padded tail beyond the request's budget route
        # to the null page; decode overwrites in-budget positions one by one
        page_ids = np.full((s_pre // self.page_size,), NULL_PAGE, np.int32)
        n = min(len(pages), len(page_ids))
        page_ids[:n] = pages[:n]

        out = self._prefill(s_pre, req.return_log_probs)(
            self.params, jnp.asarray(tokens), self.pool.k, self.pool.v,
            jnp.asarray(page_ids))
        if req.return_log_probs:
            self.pool.k, self.pool.v, prompt_lp = out
            req.prompt_log_probs = [
                float(x) for x in np.asarray(prompt_lp)[: prompt_len - 1]]
        else:
            self.pool.k, self.pool.v = out

        seed = req.seed
        if seed is None:
            seed = int.from_bytes(os.urandom(4), "little")
        key = np.asarray(jax.random.PRNGKey(seed), np.uint32)

        with self._lock:
            req._pages = pages
            self._slots[slot] = req
            bt = np.full((self.pages_per_seq,), NULL_PAGE, np.int32)
            bt[: len(pages)] = pages
            self._block_tables[slot] = bt
            # first tick re-feeds the last prompt token at prompt_len-1:
            # identical K/V rewrite, and the tick samples generated token #1
            self._positions[slot] = prompt_len - 1
            self._tokens[slot] = req.prompt[-1]
            self._temperature[slot] = req.temperature
            self._top_k[slot] = req.top_k
            self._top_p[slot] = req.top_p
            self._keys[slot] = key
            self._steps[slot] = 0
            self._dirty = True

    def _retire(self, slot: int) -> None:
        req = self._slots[slot]
        self._slots[slot] = None
        self._block_tables[slot] = NULL_PAGE
        self._positions[slot] = 0
        self._tokens[slot] = 0
        self._top_k[slot] = 1
        self._top_p[slot] = 0.0
        self._temperature[slot] = 1.0
        pages, req._pages = req._pages, []
        self.pool.free(pages)
        self._dirty = True
        req.finished = True
        req._done.set()

    def _stopped_by_token(self, req: EngineRequest, tok: int) -> bool:
        if req.stop_on_double_eol:
            prev = (req.generated[-2] if len(req.generated) > 1
                    else req.prompt[-1])
            return tok == gen.GPT2_DOUBLE_EOL or (
                tok == gen.GPT2_EOL and prev == gen.GPT2_EOL)
        if req.stop_on_eol:
            return tok in (gen.GPT2_EOL, gen.GPT2_DOUBLE_EOL)
        if not req.use_eod_for_termination or req.termination_id is None:
            return False
        return tok == req.termination_id

    # -- the tick ----------------------------------------------------------

    def step(self) -> int:
        """Admit what fits, run one fused decode tick over every slot, and
        retire finished requests.  Returns the number of active slots the
        tick advanced (0 = idle, nothing ran).  Call from one driver at a
        time (:meth:`run_until_idle` / the background loop serialize via
        ``_drive_lock``)."""
        with obs_trace.span("engine-admit"):
            self._admit()
        with self._lock:
            active = [i for i, r in enumerate(self._slots) if r is not None]
            if not active:
                if obs_registry.publishing():
                    self._m_active.set(0)
                    self._m_queued.set(len(self._queue))
                    self._m_free_pages.set(self.pool.num_free)
                return 0
            if self._dirty:
                self._dev_state = (jnp.asarray(self._block_tables),
                                   jnp.asarray(self._positions),
                                   jnp.asarray(self._tokens),
                                   jnp.asarray(self._keys),
                                   jnp.asarray(self._steps),
                                   jnp.asarray(self._temperature),
                                   jnp.asarray(self._top_k),
                                   jnp.asarray(self._top_p))
                self._dirty = False
            bt, pos, toks, keys, steps, temp, tk, tp = self._dev_state

        with obs_trace.span("engine-tick", active=len(active)):
            (self.pool.k, self.pool.v, next_tok, logp,
             new_pos, new_steps) = self._tick()(
                self.params, self.pool.k, self.pool.v,
                bt, pos, toks, keys, steps, temp, tk, tp)
            next_np = np.asarray(next_tok)
            logp_np = np.asarray(logp)

        with self._lock:
            if not self._dirty:
                # steady state: the tick already advanced the device mirror
                self._dev_state = (bt, new_pos, next_tok, keys, new_steps,
                                   temp, tk, tp)
            self.ticks += 1
            self.ticked_tokens += len(active)
            if obs_registry.publishing():
                self._m_ticks.inc()
                self._m_tokens.inc(len(active))
            for i in active:
                req = self._slots[i]
                tok = int(next_np[i])
                req.generated.append(tok)
                req.log_probs.append(float(logp_np[i]))
                req._step += 1
                self._positions[i] += 1
                self._tokens[i] = tok
                self._steps[i] += 1
                done = (self._stopped_by_token(req, tok)
                        or len(req.generated) >= req.max_new_tokens
                        or len(req.prompt) + len(req.generated) >= self.max_seq)
                if done:
                    self._retire(i)
            if obs_registry.publishing():
                self._m_active.set(
                    sum(r is not None for r in self._slots))
                self._m_queued.set(len(self._queue))
                self._m_free_pages.set(self.pool.num_free)
        return len(active)

    def run_until_idle(self) -> None:
        """Drive ticks on the calling thread until queue and slots drain.
        Safe under concurrent callers: one drives at a time, the rest take
        over as the lock frees (their requests are served either way)."""
        while True:
            with self._drive_lock:
                n = self.step()
            if n == 0:
                with self._lock:
                    if not self._queue and all(
                            r is None for r in self._slots):
                        return

    # -- background scheduler ---------------------------------------------

    def start(self) -> None:
        """Run the scheduler loop in a daemon thread (server mode)."""
        if self._thread is not None:
            return
        self._stopping = False
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        with self._work:
            self._stopping = True
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    def _loop(self) -> None:
        while True:
            with self._work:
                while (not self._stopping and not self._queue
                       and all(r is None for r in self._slots)):
                    self._work.wait()
                if self._stopping:
                    return
            with self._drive_lock:
                self.step()

    # -- server-facing API (api.InferenceEngine surface) -------------------

    def generate_and_post_process(
        self,
        prompts: Sequence[str],
        tokens_to_generate: int = 0,
        return_output_log_probs: bool = False,
        top_k_sampling: int = 0,
        top_p_sampling: float = 0.0,
        temperature: float = 1.0,
        add_BOS: bool = False,
        use_eod_token_for_early_termination: bool = True,
        stop_on_double_eol: bool = False,
        stop_on_eol: bool = False,
        random_seed: int = -1,
    ):
        """Drop-in for api.generate_and_post_process: tokenize, submit each
        prompt as its own request (all of them share decode ticks), wait,
        detokenize.  ``tokens_to_generate == 0`` (scoring mode) delegates to
        the dense-path scorer."""
        tok = self.tokenizer
        if tokens_to_generate == 0:
            return self._legacy().generate_and_post_process(
                prompts, 0, return_output_log_probs=True, add_BOS=add_BOS)

        termination_id = getattr(self.cfg.model, "eos_id", None) or tok.eod
        bos = getattr(tok, "bos_token_id", None) or getattr(tok, "bos", None)
        reqs = []
        for i, prompt in enumerate(prompts):
            ids = tok.tokenize(prompt)
            if add_BOS:
                ids = [bos if bos is not None else tok.eod] + ids
            reqs.append(self.submit(
                ids, tokens_to_generate,
                temperature=temperature, top_k=top_k_sampling,
                top_p=top_p_sampling, termination_id=termination_id,
                use_eod_for_termination=use_eod_token_for_early_termination,
                stop_on_double_eol=stop_on_double_eol,
                stop_on_eol=stop_on_eol,
                seed=None if random_seed == -1 else random_seed + i,
                return_log_probs=return_output_log_probs,
            ))
        if self._thread is None:
            self.run_until_idle()
        rows = [r.result(timeout=600) for r in reqs]

        lengths = [len(t) for t, _ in rows]
        width = max(lengths)
        tokens = np.zeros((len(rows), width), np.int32)
        for i, (t, _) in enumerate(rows):
            tokens[i, : len(t)] = t
        tokens, texts, segments = detokenize_generations(
            tok, tokens, np.asarray(lengths), True)
        if return_output_log_probs:
            log_probs = [
                (r.prompt_log_probs or []) + r.log_probs for r in reqs]
            log_probs = [
                lp[: len(seg) - 1] for lp, seg in zip(log_probs, segments)]
        else:
            log_probs = None
        return texts, segments, log_probs, tokens

    def _legacy(self):
        """A dense-path InferenceEngine view over the SAME (already
        quantized) params — bypasses __init__ so int8 weights are not
        re-quantized."""
        from megatron_llm_tpu.generation.api import InferenceEngine

        legacy = InferenceEngine.__new__(InferenceEngine)
        legacy.cfg, legacy.params, legacy.tokenizer = (
            self.cfg, self.params, self.tokenizer)
        return legacy

    def beam_search_and_post_process(self, *args, **kw):
        """Beam search stays on the dense single-stream path (api.py)."""
        return self._legacy().beam_search_and_post_process(*args, **kw)
