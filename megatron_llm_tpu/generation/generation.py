"""Autoregressive generation — TPU-native redesign of
megatron/text_generation/generation.py + forward_step.py.

Reference design: a python loop over positions, one forward per token, with
per-step host synchronization and PP broadcasts
(generation.py:89-285, forward_step.py:44-204).

TPU design: the whole decode — prefill + token loop + early termination —
is ONE jitted program built around ``lax.while_loop``; tokens never leave
the device until generation finishes, so there is no host round-trip per
token.  The KV cache is a stacked ``[L, b, max_seq, nkv, d]`` pytree
(InferenceParams analog, forward_step.py:17-41) threaded through
``lax.scan`` over layers.

Shape policy: programs specialize on (batch, padded max_seq, padded prefill
length, sampling config).  Prefill length is bucketed DOWN and max_seq
bucketed UP to multiples of ``BUCKET`` by the API layer so arbitrary prompt
lengths reuse a small set of compiled programs — numerically identical,
because positions between the bucketed prefill and the true prompt length
are teacher-forced from the prompt (generation.py:211-214 semantics).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from megatron_llm_tpu.generation.sampling import sample
from megatron_llm_tpu.models.language_model import (
    _compute_dtype,
    make_rope_cache,
    model_forward,
)

BUCKET = 64

# GPT-2 BPE newline conventions used by the reference's stop_on_eol /
# stop_on_double_eol options (generation.py:241-251).
GPT2_EOL = 198
GPT2_DOUBLE_EOL = 628

# compiled-program cache: (config fingerprint, fn name, static arg tuple)
# -> jitted fn.  Keying on the VALUE of the config (not ``id(cfg)``) means
# (a) a config object rebuilt with identical contents — a fresh server
# process section, a test building the same toy config twice — reuses the
# compiled program instead of recompiling, and (b) there is no id-recycling
# hazard: CPython reuses a freed object's id, so an id-keyed cache can serve
# a *different* config's program after the original is GC'd.
_JIT_CACHE: Dict[Tuple, Any] = {}


def config_fingerprint(cfg) -> str:
    """Stable content hash of a Config dataclass tree.

    ``asdict`` flattens the nested dataclasses in deterministic field order;
    repr covers the leaf types configs actually hold (ints, floats, strings,
    bools, None, lists/tuples).  Two configs with equal contents fingerprint
    identically across processes and GC cycles.
    """
    import dataclasses
    import hashlib

    if dataclasses.is_dataclass(cfg):
        payload = repr(dataclasses.asdict(cfg))
    else:  # duck-typed test doubles
        payload = repr(sorted(vars(cfg).items()))
    return hashlib.sha256(payload.encode()).hexdigest()


def cached_jit(cfg, name: str, statics: Tuple, build, **jit_kwargs):
    key = (config_fingerprint(cfg), name, statics)
    fn = _JIT_CACHE.get(key)
    if fn is None:
        fn = jax.jit(build(), **jit_kwargs)
        _JIT_CACHE[key] = fn
    return fn


def clear_jit_cache() -> None:
    """Drop all cached generation programs (frees compiled executables and
    unpins their configs)."""
    _JIT_CACHE.clear()


def init_kv_caches(cfg, batch_size: int, max_seq: int, dtype) -> Tuple[jax.Array, jax.Array]:
    """Pre-allocated stacked KV cache (InferenceParams.key_value_memory_dict
    analog, forward_step.py:17-41)."""
    m = cfg.model
    shape = (m.num_layers, batch_size, max_seq, m.num_attention_heads_kv, m.kv_channels)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)




def _gather_token_log_probs(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """log_softmax(logits)[..., token] — fp32 (generation.py:71-81)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.take_along_axis(logp, tokens[..., None], axis=-1)[..., 0]


class GenerateResult(NamedTuple):
    tokens: jax.Array            # [b, S] int32, prompt + generations
    lengths: jax.Array           # [b] int32, total generated length incl. prompt
    output_log_probs: jax.Array  # [b, S-1] fp32, logprob of tokens[:, 1:]


class _Carry(NamedTuple):
    context: jax.Array      # position being generated this step
    tokens: jax.Array
    caches: Tuple[jax.Array, jax.Array]
    last_logits: jax.Array
    is_done: jax.Array      # [b] bool
    gen_lengths: jax.Array  # [b] int32
    log_probs: jax.Array
    key: jax.Array


def generate_tokens_fn(
    cfg,
    *,
    prefill_len: int,
    top_k: int = 0,
    top_p: float = 0.0,
    temperature: float = 1.0,
    use_eod_for_termination: bool = True,
    stop_on_double_eol: bool = False,
    stop_on_eol: bool = False,
):
    """Build the one-program analog of
    generate_tokens_probs_and_return_on_first_stage (generation.py:89-285):
    prefill ``prefill_len`` positions, then a while_loop sampling one token
    per step with KV-cached single-position forwards, teacher-forcing
    positions still inside a row's prompt, and terminating early once every
    row has emitted the termination id.

    The returned function has signature
    ``(params, tokens [b,S], lengths [b], samples_length scalar,
       termination_id scalar, sample_key) -> GenerateResult``.
    """
    m = cfg.model

    def run(params, tokens, lengths, samples_length, termination_id, sample_key):
        b, S = tokens.shape
        assert 1 <= prefill_len < S
        rope = make_rope_cache(cfg)
        caches = init_kv_caches(cfg, b, S, _compute_dtype(cfg))

        # --- prefill positions [0, prefill_len) ----------------------------
        prompt = tokens[:, :prefill_len]
        logits, caches = model_forward(
            cfg, params, prompt,
            position_ids=jnp.arange(prefill_len)[None, :].repeat(b, 0),
            rope_cache=rope, kv_caches=caches, cache_index=jnp.int32(0),
        )
        # log-probs of teacher-forced prompt tokens (generation.py:227-239)
        log_probs0 = jnp.zeros((b, S - 1), jnp.float32)
        if prefill_len > 1:
            lp = _gather_token_log_probs(logits[:, :-1], prompt[:, 1:])
            log_probs0 = log_probs0.at[:, : prefill_len - 1].set(lp)
        last_logits = logits[:, -1]  # predicts position prefill_len

        def cond(c: _Carry):
            keep_going = c.context < samples_length
            if use_eod_for_termination:
                keep_going &= ~jnp.all(c.is_done)
            return keep_going

        def body(c: _Carry) -> _Carry:
            key, sub = jax.random.split(c.key)
            new_sample = sample(
                sub, c.last_logits, top_k=top_k, top_p=top_p,
                temperature=temperature, vocab_size=m.vocab_size,
            )
            started = lengths <= c.context  # rows already past their prompt
            prev_col = jax.lax.dynamic_slice_in_dim(
                c.tokens, c.context, 1, axis=1)[:, 0]
            new_col = jnp.where(started, new_sample, prev_col)
            tokens_ = jax.lax.dynamic_update_slice(
                c.tokens, new_col[:, None], (0, c.context)
            )
            # logprob of the token actually placed at `context`
            lp = _gather_token_log_probs(c.last_logits, new_col)
            log_probs_ = jax.lax.dynamic_update_slice(
                c.log_probs, lp[:, None], (0, c.context - 1)
            )
            # termination bookkeeping (generation.py:241-263)
            if stop_on_double_eol:
                prev_tok = jax.lax.dynamic_slice_in_dim(
                    tokens_, c.context - 1, 1, axis=1)[:, 0]
                done_token = ((new_col == GPT2_DOUBLE_EOL)
                              | ((new_col == GPT2_EOL) & (prev_tok == GPT2_EOL))
                              ) & started
            elif stop_on_eol:
                done_token = ((new_col == GPT2_DOUBLE_EOL)
                              | (new_col == GPT2_EOL)) & started
            else:
                done_token = (new_col == termination_id) & started
            just_finished = done_token & ~c.is_done
            gen_lengths_ = jnp.where(just_finished, c.context + 1, c.gen_lengths)
            is_done_ = c.is_done | done_token

            # feed the new token -> logits for position context+1
            logits, caches_ = model_forward(
                cfg, params, new_col[:, None],
                position_ids=jnp.full((b, 1), c.context, jnp.int32),
                rope_cache=rope, kv_caches=c.caches, cache_index=c.context,
            )
            return _Carry(c.context + 1, tokens_, caches_, logits[:, -1],
                          is_done_, gen_lengths_, log_probs_, key)

        init = _Carry(
            jnp.int32(prefill_len), tokens, caches, last_logits,
            jnp.zeros((b,), bool), jnp.full((b,), S, jnp.int32),
            log_probs0, sample_key,
        )
        final = jax.lax.while_loop(cond, body, init)
        gen_lengths = jnp.minimum(final.gen_lengths, samples_length)
        return GenerateResult(final.tokens, gen_lengths, final.log_probs)

    return run


def generate_tokens(cfg, params, tokens, lengths, samples_length, *,
                    prefill_len: int, termination_id, sample_key,
                    top_k: int = 0, top_p: float = 0.0, temperature: float = 1.0,
                    use_eod_for_termination: bool = True,
                    stop_on_double_eol: bool = False,
                    stop_on_eol: bool = False) -> GenerateResult:
    """Compile-cached entry over :func:`generate_tokens_fn`."""
    statics = (prefill_len, top_k, top_p, temperature, use_eod_for_termination,
               stop_on_double_eol, stop_on_eol, tokens.shape)
    fn = cached_jit(cfg, "generate", statics, lambda: generate_tokens_fn(
        cfg, prefill_len=prefill_len, top_k=top_k, top_p=top_p,
        temperature=temperature, use_eod_for_termination=use_eod_for_termination,
        stop_on_double_eol=stop_on_double_eol, stop_on_eol=stop_on_eol,
    ))
    return fn(params, jnp.asarray(tokens, jnp.int32),
              jnp.asarray(lengths, jnp.int32), jnp.asarray(samples_length, jnp.int32),
              jnp.asarray(termination_id, jnp.int32), sample_key)


def score_tokens(cfg, params, tokens: jax.Array) -> jax.Array:
    """score_and_return_on_first_stage analog (generation.py:20-88):
    teacher-forced log-probs of tokens[:, 1:].  Returns [b, s-1] fp32."""
    def build():
        def run(params, tokens):
            b, s = tokens.shape
            logits, _ = model_forward(
                cfg, params, tokens,
                position_ids=jnp.arange(s)[None, :].repeat(b, 0),
                rope_cache=make_rope_cache(cfg),
            )
            return _gather_token_log_probs(logits[:, :-1], tokens[:, 1:])
        return run

    fn = cached_jit(cfg, "score", (tuple(tokens.shape),), build)
    return fn(params, jnp.asarray(tokens, jnp.int32))


# ---------------------------------------------------------------------------
# Beam search
# ---------------------------------------------------------------------------


def _mask_padded_vocab(cfg, logits: jax.Array) -> jax.Array:
    """-inf the vocab-padding region so beams never contain OOV ids (the
    reference leaves padding logits live, generation.py:333)."""
    v = cfg.model.vocab_size
    if v is not None and v < logits.shape[-1]:
        logits = jnp.where(jnp.arange(logits.shape[-1])[None, :] >= v,
                           -jnp.inf, logits)
    return logits


def _beam_prefill(cfg, params, tokens, prefill_len: int):
    """Prefill the beam-size batch (all rows share the same prompt); returns
    next-position log-probs [beam, v] and the caches."""
    beam, S = tokens.shape

    def build():
        def run(params, tokens):
            rope = make_rope_cache(cfg)
            caches = init_kv_caches(cfg, beam, S, _compute_dtype(cfg))
            prompt = tokens[:, :prefill_len]
            logits, caches = model_forward(
                cfg, params, prompt,
                position_ids=jnp.arange(prefill_len)[None, :].repeat(beam, 0),
                rope_cache=rope, kv_caches=caches, cache_index=jnp.int32(0),
            )
            logits = _mask_padded_vocab(cfg, logits[:, -1].astype(jnp.float32))
            return jax.nn.log_softmax(logits, -1), caches
        return run

    return cached_jit(cfg, "beam_prefill", (beam, S, prefill_len), build)(
        params, tokens)


def _beam_step(cfg, params, token_col, context, caches):
    """Feed one token per beam at position ``context``; return next-position
    log-probs [beam, v] and updated caches."""
    beam = token_col.shape[0]

    def build():
        def run(params, token_col, context, caches):
            logits, caches = model_forward(
                cfg, params, token_col[:, None],
                position_ids=jnp.full((beam, 1), context, jnp.int32),
                rope_cache=make_rope_cache(cfg),
                kv_caches=caches, cache_index=context,
            )
            logits = _mask_padded_vocab(cfg, logits[:, -1].astype(jnp.float32))
            return jax.nn.log_softmax(logits, -1), caches
        return run

    return cached_jit(cfg, "beam_step", (beam, caches[0].shape), build)(
        params, token_col, context, caches)


def _beam_topk(cfg, log_probs, scores, first: bool, k: int):
    """Device top-k over the beam*vocab score matrix (the reference's
    torch.topk/sort step, generation.py:335-339) — transfers 2*beam values
    to the host instead of the full [beam, v] matrix."""
    shape = tuple(log_probs.shape)

    def build():
        def run(log_probs, scores):
            new = log_probs + scores[:, None]
            flat = new[0] if first else new.reshape(-1)
            return jax.lax.top_k(flat, k)
        return run

    return cached_jit(cfg, "beam_topk", (shape, first, k), build)(
        log_probs, jnp.asarray(scores, jnp.float32))


def _reorder_beams(cfg, caches, beam_ids):
    """swap_key_value_dict analog (forward_step.py:29-41): reorder the beam
    axis of the stacked caches after beam reranking."""
    fn = cached_jit(cfg, "beam_reorder", (caches[0].shape,),
                    lambda: (lambda c, i: jax.tree.map(lambda a: a[:, i], c)))
    return fn(caches, beam_ids)


def beam_search(
    cfg,
    params,
    tokens,            # [1, S] int array, prompt padded with eod
    prompt_length: int,
    *,
    beam_size: int,
    stop_token: int,
    num_return_gen: int = 1,
    length_penalty: float = 1.0,
    samples_length: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """beam_search_and_return_on_first_stage analog (generation.py:290-417).

    Hypothesis management (the BeamHypotheses heap) is host-side python
    exactly like the reference; the per-token model step and the beam-axis
    cache reorder are jitted device programs.  ``samples_length`` bounds the
    decode horizon (prompt + tokens_to_generate) when ``tokens`` is padded
    wider for compile-cache bucketing.

    The prefill program is compiled at a bucketed length; the remaining
    prompt positions are teacher-forced through the (single, shape-stable)
    per-token step so any prompt length reuses two compiled programs.

    Returns (tokens [num_return_gen, S], scores [num_return_gen]).
    """
    from megatron_llm_tpu.generation.beam_utils import BeamHypotheses

    assert tokens.shape[0] == 1, "beam search supports batch size 1"
    S = int(tokens.shape[1])
    horizon = S if samples_length is None else min(int(samples_length), S)
    if prompt_length >= horizon:
        raise ValueError("context length + tokens_to_generate too large")

    beam_hyp = BeamHypotheses(beam_size, length_penalty)
    tokens = jnp.broadcast_to(jnp.asarray(tokens, jnp.int32), (beam_size, S))
    scores = np.zeros((beam_size,), np.float64)

    # bucketed prefill + teacher-forced catch-up to the true prompt length
    prefill_len = max(1, (prompt_length // BUCKET) * BUCKET)
    log_probs, caches = _beam_prefill(cfg, params, tokens, prefill_len)
    for pos in range(prefill_len, prompt_length):
        log_probs, caches = _beam_step(
            cfg, params, tokens[:, pos], jnp.int32(pos), caches)

    vocab = log_probs.shape[-1]
    tokens_np = np.asarray(tokens)
    done = False
    context_length = prompt_length
    for context_length in range(prompt_length, horizon):
        first = context_length == prompt_length  # beams identical on step 1
        vals, idx = _beam_topk(cfg, log_probs, scores, first, 2 * beam_size)
        order = np.asarray(idx, np.int64)
        best_scores = np.asarray(vals, np.float64)
        best_beam_ids = (np.zeros(2 * beam_size, np.int64) if first
                         else order // vocab)
        best_words = order % vocab

        next_beams = []
        for rank, (token_id, beam_score, beam_id) in enumerate(
            zip(best_words, best_scores, best_beam_ids)
        ):
            if int(token_id) == stop_token:
                if rank < beam_size:  # worse-than-top-beam eos is dropped
                    beam_hyp.add(
                        tokens_np[beam_id].copy(), float(beam_score),
                        context_length + 1 - prompt_length,
                    )
            else:
                next_beams.append((int(token_id), float(beam_score), int(beam_id)))
            if len(next_beams) == beam_size:
                break

        if beam_hyp.is_done(float(best_scores.max()),
                            context_length + 1 - prompt_length):
            done = True
            break

        best_batches = np.array([nb[2] for nb in next_beams], np.int64)
        tokens_np = tokens_np[best_batches]
        tokens_np[:, context_length] = [nb[0] for nb in next_beams]
        scores = np.array([nb[1] for nb in next_beams], np.float64)

        if context_length == horizon - 1:
            break
        caches = _reorder_beams(cfg, caches, jnp.asarray(best_batches))
        log_probs, caches = _beam_step(
            cfg, params,
            jnp.asarray(tokens_np[:, context_length], jnp.int32),
            jnp.int32(context_length), caches,
        )

    if not done:
        for beam_id in range(beam_size):
            beam_hyp.add(tokens_np[beam_id].copy(), float(scores[beam_id]),
                         context_length + 1 - prompt_length)

    sorted_hyps = sorted(beam_hyp.beams, key=lambda x: x[0], reverse=True)
    num_return_gen = min(num_return_gen, len(sorted_hyps))
    out_scores = jnp.asarray([sorted_hyps[i][0] for i in range(num_return_gen)])
    out_tokens = jnp.asarray(
        np.stack([sorted_hyps[i][1] for i in range(num_return_gen)])
    )
    return out_tokens, out_scores
