"""Sampling utilities — functional JAX analog of
megatron/text_generation/sampling.py (sample:45, top-k filter:14, top-p
filter:22).

All functions are pure and jit-safe with *static* top_k/top_p/temperature
(the jit cache is keyed per sampling config; a config change recompiles
once, which matches how a generation server runs in practice).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e10


def modify_logits_for_top_k_filtering(logits: jax.Array, top_k: int) -> jax.Array:
    """Keep only the top-k logits, set the rest to -inf (sampling.py:14-18)."""
    kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
    return jnp.where(logits < kth, NEG_INF, logits)


def modify_logits_for_top_p_filtering(logits: jax.Array, top_p: float) -> jax.Array:
    """Nucleus filtering (sampling.py:22-41), including the reference's
    shift-by-one so the first token crossing the threshold is kept."""
    sorted_idx = jnp.argsort(logits, axis=-1)[..., ::-1]
    sorted_logits = jnp.take_along_axis(logits, sorted_idx, axis=-1)
    cum_probs = jnp.cumsum(jax.nn.softmax(sorted_logits, axis=-1), axis=-1)
    filter_sorted = cum_probs > top_p
    # shift right: token at the boundary stays selectable
    filter_sorted = jnp.concatenate(
        [jnp.zeros_like(filter_sorted[..., :1]), filter_sorted[..., :-1]], axis=-1
    )
    # un-sort the filter back to vocab order
    inv = jnp.argsort(sorted_idx, axis=-1)
    filter_ = jnp.take_along_axis(filter_sorted, inv, axis=-1)
    return jnp.where(filter_, NEG_INF, logits)


def sample(
    key: Optional[jax.Array],
    logits: jax.Array,  # [b, v]
    *,
    top_k: int = 0,
    top_p: float = 0.0,
    temperature: float = 1.0,
    vocab_size: Optional[int] = None,
) -> jax.Array:
    """Sample one token per row (sampling.py:45-95). ``top_k == 1`` is greedy;
    top-k and top-p are mutually exclusive.

    ``vocab_size`` masks the vocab-padding region to -inf before selection.
    (The reference instead CLAMPS the sample into [0, vocab) after selection,
    sampling.py:90-93 — which can spuriously emit token vocab-1 whenever a
    padding logit wins; masking picks the best *valid* token instead.)"""
    assert logits.ndim == 2, "expected [b, v] logits"
    if vocab_size and vocab_size < logits.shape[-1]:
        logits = jnp.where(
            jnp.arange(logits.shape[-1])[None, :] >= vocab_size, NEG_INF, logits
        )
    if top_k == 1:
        assert top_p == 0.0, "cannot set both greedy and top-p sampling"
        samples = jnp.argmax(logits, axis=-1)
    else:
        logits = logits.astype(jnp.float32)
        if temperature != 1.0:
            logits = logits / temperature
        if top_k > 1:
            assert top_p == 0.0, "cannot set both top-k and top-p sampling"
            assert top_k <= logits.shape[-1], "top-k larger than logit size"
            logits = modify_logits_for_top_k_filtering(logits, top_k)
        elif top_p > 0.0:
            assert top_p <= 1.0, "top-p should be in (0, 1]"
            logits = modify_logits_for_top_p_filtering(logits, top_p)
        assert key is not None, "non-greedy sampling needs a PRNG key"
        samples = jax.random.categorical(key, logits, axis=-1)
    return samples.astype(jnp.int32)
