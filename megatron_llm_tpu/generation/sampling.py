"""Sampling utilities — functional JAX analog of
megatron/text_generation/sampling.py (sample:45, top-k filter:14, top-p
filter:22).

All functions are pure and jit-safe with *static* top_k/top_p/temperature
(the jit cache is keyed per sampling config; a config change recompiles
once, which matches how a generation server runs in practice).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e10


def modify_logits_for_top_k_filtering(logits: jax.Array, top_k: int) -> jax.Array:
    """Keep only the top-k logits, set the rest to -inf (sampling.py:14-18)."""
    kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
    return jnp.where(logits < kth, NEG_INF, logits)


def modify_logits_for_top_p_filtering(logits: jax.Array, top_p: float) -> jax.Array:
    """Nucleus filtering (sampling.py:22-41), including the reference's
    shift-by-one so the first token crossing the threshold is kept."""
    sorted_idx = jnp.argsort(logits, axis=-1)[..., ::-1]
    sorted_logits = jnp.take_along_axis(logits, sorted_idx, axis=-1)
    cum_probs = jnp.cumsum(jax.nn.softmax(sorted_logits, axis=-1), axis=-1)
    filter_sorted = cum_probs > top_p
    # shift right: token at the boundary stays selectable
    filter_sorted = jnp.concatenate(
        [jnp.zeros_like(filter_sorted[..., :1]), filter_sorted[..., :-1]], axis=-1
    )
    # un-sort the filter back to vocab order
    inv = jnp.argsort(sorted_idx, axis=-1)
    filter_ = jnp.take_along_axis(filter_sorted, inv, axis=-1)
    return jnp.where(filter_, NEG_INF, logits)


def sample(
    key: Optional[jax.Array],
    logits: jax.Array,  # [b, v]
    *,
    top_k: int = 0,
    top_p: float = 0.0,
    temperature: float = 1.0,
    vocab_size: Optional[int] = None,
) -> jax.Array:
    """Sample one token per row (sampling.py:45-95). ``top_k == 1`` is greedy;
    top-k and top-p are mutually exclusive.

    ``vocab_size`` masks the vocab-padding region to -inf before selection.
    (The reference instead CLAMPS the sample into [0, vocab) after selection,
    sampling.py:90-93 — which can spuriously emit token vocab-1 whenever a
    padding logit wins; masking picks the best *valid* token instead.)"""
    assert logits.ndim == 2, "expected [b, v] logits"
    if vocab_size and vocab_size < logits.shape[-1]:
        logits = jnp.where(
            jnp.arange(logits.shape[-1])[None, :] >= vocab_size, NEG_INF, logits
        )
    if top_k == 1:
        assert top_p == 0.0, "cannot set both greedy and top-p sampling"
        samples = jnp.argmax(logits, axis=-1)
    else:
        logits = logits.astype(jnp.float32)
        if temperature != 1.0:
            logits = logits / temperature
        if top_k > 1:
            assert top_p == 0.0, "cannot set both top-k and top-p sampling"
            assert top_k <= logits.shape[-1], "top-k larger than logit size"
            logits = modify_logits_for_top_k_filtering(logits, top_k)
        elif top_p > 0.0:
            assert top_p <= 1.0, "top-p should be in (0, 1]"
            logits = modify_logits_for_top_p_filtering(logits, top_p)
        assert key is not None, "non-greedy sampling needs a PRNG key"
        samples = jax.random.categorical(key, logits, axis=-1)
    return samples.astype(jnp.int32)


def filtered_logits_per_slot(
    logits: jax.Array,       # [b, v]
    *,
    top_k: jax.Array,        # [b] int32 (0 = off, 1 = greedy, >1 = filter)
    top_p: jax.Array,        # [b] fp32  (0 = off; ignored where top_k acts)
    temperature: jax.Array,  # [b] fp32  (ignored for greedy rows)
    vocab_size: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """The per-row filter pipeline :func:`sample_per_slot` samples from.

    Returns ``(filtered, greedy)``: the vocab-masked, temperature-scaled,
    top-k/top-p-filtered fp32 logits [b, v] (softmax of a row is exactly
    the categorical distribution a non-greedy slot draws from) and the
    greedy argmax [b] over the vocab-masked RAW logits (no temperature —
    :func:`sample`'s greedy branch).  The speculative-decoding verify step
    (generation/speculative/verify.py) consumes both: draft/target
    distributions for residual rejection sampling must be the SAME
    distributions the non-speculative tick samples from, or acceptance
    stops being lossless.
    """
    assert logits.ndim == 2, "expected [b, v] logits"
    b, v = logits.shape
    if vocab_size and vocab_size < v:
        logits = jnp.where(jnp.arange(v)[None, :] >= vocab_size, NEG_INF, logits)
    greedy = jnp.argmax(logits, axis=-1)

    l32 = logits.astype(jnp.float32)
    safe_temp = jnp.where(temperature > 0, temperature, 1.0).astype(jnp.float32)
    l32 = l32 / safe_temp[:, None]

    def apply_filters(x):
        # one descending sort serves both filters
        sorted_idx = jnp.argsort(x, axis=-1)[..., ::-1]
        sorted_logits = jnp.take_along_axis(x, sorted_idx, axis=-1)

        # dynamic top-k: keep values >= the row's k-th largest
        kth = jnp.take_along_axis(
            sorted_logits, jnp.clip(top_k - 1, 0, v - 1)[:, None], axis=-1)
        l_topk = jnp.where(x < kth, NEG_INF, x)

        # dynamic top-p with the shift-by-one boundary convention of
        # modify_logits_for_top_p_filtering
        cum_probs = jnp.cumsum(jax.nn.softmax(sorted_logits, axis=-1), axis=-1)
        filter_sorted = cum_probs > top_p[:, None]
        filter_sorted = jnp.concatenate(
            [jnp.zeros_like(filter_sorted[..., :1]), filter_sorted[..., :-1]],
            axis=-1)
        inv = jnp.argsort(sorted_idx, axis=-1)
        filter_ = jnp.take_along_axis(filter_sorted, inv, axis=-1)
        l_topp = jnp.where(filter_, NEG_INF, x)

        use_k = (top_k > 1)[:, None]
        use_p = (top_p > 0)[:, None] & ~use_k
        return jnp.where(use_k, l_topk, jnp.where(use_p, l_topp, x))

    # all-greedy / pure-temperature ticks skip the two vocab sorts entirely
    # (the common serving mix; greedy decode bench ticks hit this branch)
    filtered = jax.lax.cond(
        jnp.any((top_k > 1) | (top_p > 0)), apply_filters, lambda x: x, l32)
    return filtered, greedy


def sample_per_slot(
    keys: jax.Array,         # [b, 2] uint32 — one PRNG key per row
    logits: jax.Array,       # [b, v]
    *,
    top_k: jax.Array,        # [b] int32 (0 = off, 1 = greedy, >1 = filter)
    top_p: jax.Array,        # [b] fp32  (0 = off; ignored where top_k acts)
    temperature: jax.Array,  # [b] fp32  (ignored for greedy rows)
    vocab_size: Optional[int] = None,
) -> jax.Array:
    """One batched sampling step with *per-row* sampling params and keys.

    The continuous-batching engine decodes many requests in one tick, each
    with its own (temperature, top_k, top_p) — so unlike :func:`sample`,
    where the config is static and baked into the compiled program, here the
    params are traced arrays and one program serves every mix.  Per-row keys
    keep each request's sample stream a function of (its seed, its step
    index) alone — independent of which slot it landed in or which other
    requests share the tick.  Greedy rows (``top_k == 1``) reproduce
    :func:`sample`'s greedy branch exactly: argmax over the vocab-masked
    logits, no temperature.

    Returns [b] int32 token ids.
    """
    filtered, greedy = filtered_logits_per_slot(
        logits, top_k=top_k, top_p=top_p, temperature=temperature,
        vocab_size=vocab_size)
    sampled = jax.vmap(lambda k, row: jax.random.categorical(k, row))(
        keys, filtered)
    return jnp.where(top_k == 1, greedy, sampled).astype(jnp.int32)
