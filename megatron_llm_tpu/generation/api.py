"""Inference API — megatron/text_generation/api.py analog.

``InferenceEngine`` bundles (cfg, params, tokenizer) — the state the
reference keeps in process-globals — and exposes the same surface:
``generate_and_post_process`` (api.py:19-68) and
``beam_search_and_post_process`` (api.py:152-178).  No parameter broadcasts
(api.py:93-117): SPMD means one controller process.

Compile-cache policy: prompt batches are padded UP to a BUCKET multiple and
the prefill is bucketed DOWN, so a server sees a handful of compilations,
then reuses them for any prompt mix.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np

from megatron_llm_tpu.generation import generation as gen
from megatron_llm_tpu.generation.tokenization import (
    detokenize_generations,
    tokenize_prompts_and_batch,
)


def _bucket_down(n: int, bucket: int = gen.BUCKET) -> int:
    return max(1, (n // bucket) * bucket)


def _next_pow2(n: int) -> int:
    return 1 << (n - 1).bit_length()


class InferenceEngine:
    """Holds a model + tokenizer and serves generation requests."""

    def __init__(self, cfg, params, tokenizer):
        self.cfg = cfg
        if cfg.inference.int8_weights:
            if getattr(cfg.model, "fp8", None):
                raise ValueError(
                    "int8_weights and fp8 are mutually exclusive: the fp8 "
                    "linear path reads the unquantized 'kernel' leaves "
                    "(ops/fp8.py)")
            from megatron_llm_tpu.ops.quant import quantize_layer_weights_int8

            params = quantize_layer_weights_int8(params)
        self.params = params
        self.tokenizer = tokenizer

    def _check_limits(self, batch_size: int, samples_length: int,
                      run_length: Optional[int] = None) -> None:
        """Request-size guards (generation.py:133-138): position range on the
        logical length, token budget on the (bucket-padded) size that runs."""
        max_pos = self.cfg.model.max_position_embeddings
        if samples_length > max_pos:
            raise ValueError(
                "Length of prompt + tokens_to_generate longer than allowed")
        budget = self.cfg.inference.max_tokens_to_oom
        run_tokens = (run_length or samples_length) * batch_size
        if run_tokens > budget:
            raise ValueError(
                f"Too many tokens.  {run_tokens} is greater than {budget}")

    # -- generate ----------------------------------------------------------

    def generate(
        self,
        prompts: Sequence[str],
        tokens_to_generate: int = 0,
        return_output_log_probs: bool = False,
        top_k_sampling: int = 0,
        top_p_sampling: float = 0.0,
        temperature: float = 1.0,
        add_BOS: bool = False,
        use_eod_token_for_early_termination: bool = True,
        stop_on_double_eol: bool = False,
        stop_on_eol: bool = False,
        random_seed: int = -1,
    ):
        """api.generate analog (api.py:70-151): returns (tokens [b, S] np,
        lengths [b] np, output_log_probs [b, S-1] np or None)."""
        tok = self.tokenizer
        tokens, lengths, samples_length = tokenize_prompts_and_batch(
            tok, prompts, tokens_to_generate, add_BOS,
            pad_to_multiple=gen.BUCKET,
        )
        # pad the batch dim up to a power of two so the decode program is
        # compiled per size *bucket*, not per request size; padded rows are
        # copies of row 0 and are sliced off before returning.  The OOM
        # budget is checked against the padded size that actually runs.
        b = len(prompts)
        b_pad = _next_pow2(b)
        self._check_limits(b_pad, samples_length, tokens.shape[1])
        if b_pad != b:
            tokens = np.concatenate(
                [tokens, np.tile(tokens[:1], (b_pad - b, 1))], axis=0)
            lengths = np.concatenate(
                [lengths, np.tile(lengths[:1], b_pad - b)], axis=0)

        if tokens_to_generate == 0:
            # scoring mode (api.py:129-131): teacher-forced log-probs.
            # Score on the bucket-padded batch (stable compile cache) and
            # slice the result back to the true length.
            log_probs = np.asarray(gen.score_tokens(self.cfg, self.params, tokens))
            return (tokens[:b, :samples_length], lengths[:b],
                    log_probs[:b, : samples_length - 1])

        termination_id = getattr(self.cfg.model, "eos_id", None) or tok.eod
        prefill_len = min(_bucket_down(int(lengths.min())), tokens.shape[1] - 1)
        if random_seed == -1:
            # unseeded request: fresh entropy per call (the reference leaves
            # the torch RNG stream running, api.py:119-120)
            import os

            random_seed = int.from_bytes(os.urandom(4), "little")
        key = jax.random.PRNGKey(random_seed)
        result = gen.generate_tokens(
            self.cfg, self.params, tokens, lengths, samples_length,
            prefill_len=prefill_len, termination_id=termination_id,
            sample_key=key, top_k=top_k_sampling, top_p=top_p_sampling,
            temperature=temperature,
            use_eod_for_termination=use_eod_token_for_early_termination,
            stop_on_double_eol=stop_on_double_eol, stop_on_eol=stop_on_eol,
        )
        out_tokens = np.asarray(result.tokens)[:b, :samples_length]
        out_lengths = np.asarray(result.lengths)[:b]
        out_log_probs = (
            np.asarray(result.output_log_probs)[:b, : samples_length - 1]
            if return_output_log_probs else None
        )
        return out_tokens, out_lengths, out_log_probs

    def generate_and_post_process(
        self,
        prompts: Sequence[str],
        tokens_to_generate: int = 0,
        return_output_log_probs: bool = False,
        top_k_sampling: int = 0,
        top_p_sampling: float = 0.0,
        temperature: float = 1.0,
        add_BOS: bool = False,
        use_eod_token_for_early_termination: bool = True,
        stop_on_double_eol: bool = False,
        stop_on_eol: bool = False,
        random_seed: int = -1,
    ):
        """api.generate_and_post_process analog (api.py:19-68): returns
        (prompts_plus_generations, segments, output_log_probs, tokens)."""
        tokens, lengths, log_probs = self.generate(
            prompts, tokens_to_generate,
            return_output_log_probs=return_output_log_probs or tokens_to_generate == 0,
            top_k_sampling=top_k_sampling, top_p_sampling=top_p_sampling,
            temperature=temperature, add_BOS=add_BOS,
            use_eod_token_for_early_termination=use_eod_token_for_early_termination,
            stop_on_double_eol=stop_on_double_eol, stop_on_eol=stop_on_eol,
            random_seed=random_seed,
        )
        tokens, texts, segments = detokenize_generations(
            self.tokenizer, tokens, lengths, True)
        if return_output_log_probs and log_probs is not None:
            log_probs = [
                list(map(float, row[: len(seg) - 1]))
                for row, seg in zip(log_probs, segments)
            ]
        else:
            log_probs = None
        return texts, segments, log_probs, tokens

    # -- beam search -------------------------------------------------------

    def beam_search_and_post_process(
        self,
        prompts: Sequence[str],
        tokens_to_generate: int = 0,
        beam_size: int = 0,
        add_BOS: bool = False,
        stop_token: Optional[int] = None,
        num_return_gen: int = 1,
        length_penalty: float = 1.0,
    ):
        """api.beam_search_and_post_process analog (api.py:152-201)."""
        if len(prompts) != 1:
            raise ValueError("beam search supports exactly one prompt")
        tok = self.tokenizer
        stop_token = tok.eod if stop_token is None else stop_token
        tokens, lengths, samples_length = tokenize_prompts_and_batch(
            tok, prompts, tokens_to_generate, add_BOS,
            pad_to_multiple=gen.BUCKET,
        )
        self._check_limits(1, samples_length, tokens.shape[1])
        out_tokens, scores = gen.beam_search(
            self.cfg, self.params, tokens[:1], int(lengths[0]),
            beam_size=beam_size, stop_token=stop_token,
            num_return_gen=num_return_gen, length_penalty=length_penalty,
            samples_length=samples_length,
        )
        out_tokens = np.asarray(out_tokens)[:, :samples_length]
        out_lengths = np.full((out_tokens.shape[0],), samples_length, np.int64)
        _, texts, segments = detokenize_generations(
            tok, out_tokens, out_lengths, True)
        return texts, segments, [float(s) for s in np.asarray(scores)]
