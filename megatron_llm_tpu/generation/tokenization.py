"""Prompt tokenization / generation detokenization —
megatron/text_generation/tokenization.py analog.

No broadcast plumbing: under SPMD a single host process feeds the program,
so the reference's rank-0 tokenize + broadcast (tokenization.py:47-79) is
just a function call.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


def tokenize_prompts_and_batch(
    tokenizer,
    prompts: Sequence[str],
    tokens_to_generate: int,
    add_BOS: bool = False,
    pad_to_multiple: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Tokenize, right-pad with eod to max(prompt)+tokens_to_generate
    (tokenization.py:84-119). ``pad_to_multiple`` rounds the padded length up
    to a bucket multiple so jit programs are reused across prompt lengths."""
    if add_BOS:
        bos = getattr(tokenizer, "bos_token_id", None)
        if bos is None:
            bos = getattr(tokenizer, "bos", None)
        if bos is None:
            bos = tokenizer.eod  # reference behavior: BOS falls back to eod
        prompts_tokens = [[bos] + tokenizer.tokenize(p) for p in prompts]
    else:
        prompts_tokens = [tokenizer.tokenize(p) for p in prompts]

    lengths = [len(t) for t in prompts_tokens]
    samples_length = max(lengths) + tokens_to_generate
    padded_length = samples_length
    if pad_to_multiple:
        padded_length = -(-padded_length // pad_to_multiple) * pad_to_multiple
    tokens = np.full((len(prompts), padded_length), tokenizer.eod, np.int32)
    for row, t in enumerate(prompts_tokens):
        tokens[row, : len(t)] = t
    return tokens, np.asarray(lengths, np.int32), samples_length


def detokenize_generations(
    tokenizer,
    tokens,     # [b, S] array-like
    lengths,    # [b]
    return_segments: bool,
):
    """Detokenize (tokenization.py:13-44). Segments are per-token text pieces;
    we use the tokenizer's id->token mapping when available (HF fast
    tokenizers) and fall back to one-id detokenize."""
    tokens = np.asarray(tokens).tolist()
    lengths = np.asarray(lengths).tolist()

    prompts_plus_generations: List[str] = []
    segments: List[List[str]] = []
    for sequence_tokens, length in zip(tokens, lengths):
        sequence_tokens = sequence_tokens[: int(length)]
        prompts_plus_generations.append(tokenizer.detokenize(sequence_tokens))
        if return_segments:
            hf = getattr(tokenizer, "tokenizer", None)
            if hf is not None and hasattr(hf, "convert_ids_to_tokens"):
                words = [
                    hf.convert_tokens_to_string([piece])
                    for piece in hf.convert_ids_to_tokens(sequence_tokens)
                ]
            else:
                words = [tokenizer.detokenize([t]) for t in sequence_tokens]
            segments.append(words)

    if return_segments:
        return tokens, prompts_plus_generations, segments
    return tokens, prompts_plus_generations
