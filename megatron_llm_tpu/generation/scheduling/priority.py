"""Priority policy — per-request classes with an anti-starvation bound.

Requests carry an integer ``priority`` (0 = most urgent; default 1).
Ordering uses the AGED effective priority

    effective(r) = r.priority - waited_seconds / aging_s

so a request climbs one class per ``aging_s`` seconds in the queue: a
class-``p`` request is guaranteed to outrank fresh class-0 arrivals after
at most ``p * aging_s`` seconds — the starvation bound
(tests/test_scheduler.py::test_priority_starvation_bound).

Preemption compares aged values on BOTH sides: a candidate may only evict
a decoding request whose effective priority is strictly worse, so an aged
low-class request that finally admitted cannot be bounced back out by the
next fresh high-class arrival (no preemption livelock), and among eligible
victims the least-progressed one loses (cheapest resume: fewest pages to
re-match, fewest suffix tokens to re-prefill).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from megatron_llm_tpu.generation.scheduling.policy import (
    SchedulerPolicy,
    SchedulerState,
    register_policy,
)

__all__ = ["PriorityPolicy"]


@register_policy
class PriorityPolicy(SchedulerPolicy):
    name = "priority"
    barrier_admission = False  # a small request may fill around a big one

    def effective(self, req, now: float) -> float:
        """Aged priority: lower = more urgent; falls one class per
        ``aging_s`` seconds waited."""
        return req.priority - (now - req._t_submit) / self.aging_s

    def _order(self, reqs: Sequence, now: float) -> List:
        return sorted(reqs, key=lambda r: (self.effective(r, now),
                                           r._seqno))

    def admission_order(self, queued: Sequence,
                        state: SchedulerState) -> List:
        return self._order(queued, state.now)

    def prefill_order(self, prefilling: Sequence,
                      state: SchedulerState) -> List:
        # an urgent prompt's chunks jump ahead of a batch prompt's
        return self._order(prefilling, state.now)

    def preempt_victim(self, candidate, decoding: Sequence,
                       state: SchedulerState) -> Optional[object]:
        if not (self.preemption and state.can_preempt):
            return None
        cand_eff = self.effective(candidate, state.now)
        victims = [r for r in decoding
                   if self.effective(r, state.now) > cand_eff + 1e-9]
        if not victims:
            return None
        # lowest value first; among those, least progress lost
        return max(victims, key=lambda r: (self.effective(r, state.now),
                                           -len(r.generated)))
