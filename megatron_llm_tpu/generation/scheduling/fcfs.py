"""FCFS policy — the pre-policy engine's behavior, verbatim.

Admission tries exactly the queue head and blocks behind it under page
pressure (``barrier_admission``), prefill feeds the oldest prefilling
request one chunk per tick, nothing is ever preempted or shed.  This is
the default policy and MUST stay bitwise-equivalent to the inlined
scheduler it replaced: tests/test_scheduler.py locks tokens and log-probs
against the monolithic reference, and the PR 5 parity suites
(tests/test_prefix_cache.py) run through it unchanged.
"""

from __future__ import annotations

from megatron_llm_tpu.generation.scheduling.policy import (
    SchedulerPolicy,
    register_policy,
)

__all__ = ["FcfsPolicy"]


@register_policy
class FcfsPolicy(SchedulerPolicy):
    name = "fcfs"
    barrier_admission = True  # head waits; nothing skips it
