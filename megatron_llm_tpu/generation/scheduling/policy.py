"""SchedulerPolicy — the decision interface the engine delegates to.

A policy never touches engine mechanisms: it sees request objects and a
read-only :class:`SchedulerState` snapshot and answers four questions —

* **admission order**: which queued requests should admission try, in
  what order, and does a blocked best-candidate block everyone behind it
  (``barrier_admission``, the FCFS no-starvation property)?
* **prefill schedule**: which prefilling request gets the next chunk, and
  how many prompt TOKENS may prefill this tick (``prefill_budget`` —
  token-denominated, NOT a chunk count; see its docstring)?
* **preemption**: when the best queued candidate cannot admit (no slot,
  or the page budget is short), which decoding request — if any — should
  release its pages and re-queue?  The engine only calls this when
  preemption can resume bitwise (chunked-prefill mode) and the victim set
  already excludes non-preemptible requests (``return_log_probs``).
* **shedding**: which queued requests should be dropped outright (answer
  now with a retryable error) because serving them would only miss their
  deadline and waste pool pages?

Policies must be side-effect free: every method takes snapshots and
returns decisions; the engine applies them under its own lock.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple, Type

__all__ = [
    "RequestShed",
    "SchedulerPolicy",
    "SchedulerState",
    "available_policies",
    "get_policy",
    "register_policy",
]


class RequestShed(RuntimeError):
    """The scheduler dropped this request before serving it.

    Raised from ``EngineRequest.result()``; the server maps it to a
    structured 503 with a Retry-After hint (generation/server.py) — the
    client's signal to back off or relax its deadline."""

    def __init__(self, msg: str, retry_after: float = 1.0,
                 info: Optional[dict] = None):
        super().__init__(msg)
        self.retry_after = retry_after
        self.info = info or {}


@dataclasses.dataclass(frozen=True)
class SchedulerState:
    """Read-only engine snapshot for policy decisions (built under the
    engine lock — policies must not call back into the engine)."""

    now: float                       # time.monotonic() at decision time
    ema_tick_s: Optional[float]      # EMA decode-tick wall time
    ema_retire_s: Optional[float]    # EMA interval between retirements
    free_slots: int
    queue_depth: int
    can_preempt: bool                # chunked mode + policy allows it
    prefill_chunk: int = 0           # engine chunk size in tokens (0 = off)
    # measured submit-to-first-token EMA (ISSUE 12, flight-recorder
    # derived): the REAL first-token latency of recent requests —
    # includes queue + prefill, unlike the tick/retire EMAs.  None until
    # the first token ever lands.  Policies may use it to ground their
    # wait predictions in observed TTFT rather than drain arithmetic.
    ttft_ema_s: Optional[float] = None

    def drain_eta(self, depth: int) -> Optional[float]:
        """Predicted seconds until ``depth`` queued requests drain, from
        the retirement EMA (tick EMA as a coarse floor before the first
        retirement).  None until any timing signal exists."""
        per = self.ema_retire_s if self.ema_retire_s is not None \
            else self.ema_tick_s
        if per is None:
            return None
        return depth * per


class SchedulerPolicy:
    """Base policy: FCFS-shaped defaults; subclasses override decisions.

    ``aging_s`` is the anti-starvation horizon (priority: one class per
    ``aging_s`` seconds waited); ``preemption`` gates preempt_victim for
    policies that support it."""

    name = "base"
    #: True = admission stops at the first blocked candidate (strict FCFS:
    #: nothing skips the queue head); False = admission keeps trying the
    #: rest of the order, so a small request can fill around a big one.
    barrier_admission = False

    def __init__(self, *, aging_s: float = 5.0, preemption: bool = True):
        if aging_s <= 0:
            raise ValueError("aging_s must be positive")
        self.aging_s = aging_s
        self.preemption = preemption

    # ---- admission -----------------------------------------------------

    def admission_order(self, queued: Sequence, state: SchedulerState
                        ) -> List:
        """Queued requests in the order admission should try them."""
        return list(queued)

    # ---- prefill -------------------------------------------------------

    def prefill_order(self, prefilling: Sequence, state: SchedulerState
                      ) -> List:
        """Prefilling requests; the first gets the next chunk."""
        return list(prefilling)

    def prefill_budget(self, prefilling: Sequence,
                       state: SchedulerState) -> int:
        """Prompt TOKENS the engine may prefill this tick.

        The unit is TOKENS, not chunks (ISSUE 11 pinned the ambiguity):
        the engine floors the budget to at least one chunk
        (``state.prefill_chunk``) so prefill always advances, and caps it
        at its compiled prefill-row capacity; a budget of N tokens may
        therefore admit MULTIPLE chunks from MULTIPLE prefilling requests
        into one tick (tests/test_ragged_tick.py pins the regression).
        The default — exactly one chunk's worth — matches the pre-policy
        one-chunk-per-tick interleave, so decode never stalls behind
        prefill.  Negative returns are a policy bug and raise."""
        return max(state.prefill_chunk, 1)

    # ---- shedding ------------------------------------------------------

    def shed(self, queued: Sequence, state: SchedulerState
             ) -> List[Tuple[object, str]]:
        """(request, reason) pairs to drop from the queue right now."""
        return []

    # ---- preemption ----------------------------------------------------

    def preempt_victim(self, candidate, decoding: Sequence,
                       state: SchedulerState) -> Optional[object]:
        """The decoding request that should release its pages so
        ``candidate`` can admit — or None to wait instead.  Must only
        return a victim STRICTLY less valuable than the candidate, or
        admission livelocks on mutual preemption."""
        return None


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

_POLICIES: Dict[str, Type[SchedulerPolicy]] = {}


def register_policy(cls: Type[SchedulerPolicy]) -> Type[SchedulerPolicy]:
    """Class decorator: make ``cls`` reachable as --sched_policy <name>."""
    if not cls.name or cls.name == "base":
        raise ValueError("policy classes must set a unique `name`")
    _POLICIES[cls.name] = cls
    return cls


def get_policy(name: str) -> Type[SchedulerPolicy]:
    try:
        return _POLICIES[name]
    except KeyError:
        raise ValueError(
            f"unknown scheduling policy {name!r}; available: "
            f"{', '.join(sorted(_POLICIES))}") from None


def available_policies() -> List[str]:
    return sorted(_POLICIES)
