"""Serving control plane: pluggable scheduling policies for the engine.

The continuous-batching engine (generation/engine.py) delegates every
scheduling *decision* here — admission ordering, the per-tick prefill-chunk
budget, preemption victims, and load shedding — while keeping every
scheduling *mechanism* (page allocation, slot state, the commitment
ledger) in the engine.  Three policies ship:

* ``fcfs`` (default) — strict submission order, the head blocks admission
  under page pressure, never preempts, never sheds.  Reproduces the
  pre-policy engine token-for-token (tests/test_scheduler.py).
* ``priority`` — per-request integer priority classes (0 = most urgent)
  ordered by an aging-adjusted effective priority, so a starved request
  climbs one class per ``--sched_aging_s`` seconds; may preempt a
  strictly lower-value decoding request.
* ``slo`` — per-request TTFT / per-token deadlines, earliest-deadline-
  first, sheds requests whose deadline is already unmeetable instead of
  burning pool pages on a guaranteed miss.

Preemption works by page release: the victim's full KV pages re-enter the
prefix-cache trie before its pages are released, so re-admission matches
them back and resume is bitwise-identical to never having been preempted
(the PR 5 grid-aligned chunk invariant).
"""

from megatron_llm_tpu.generation.scheduling.policy import (
    RequestShed,
    SchedulerPolicy,
    SchedulerState,
    available_policies,
    get_policy,
    register_policy,
)
from megatron_llm_tpu.generation.scheduling.fcfs import FcfsPolicy
from megatron_llm_tpu.generation.scheduling.priority import PriorityPolicy
from megatron_llm_tpu.generation.scheduling.slo import SloPolicy

__all__ = [
    "FcfsPolicy",
    "PriorityPolicy",
    "RequestShed",
    "SchedulerPolicy",
    "SchedulerState",
    "SloPolicy",
    "available_policies",
    "get_policy",
    "register_policy",
]
