"""SLO policy — earliest-deadline-first with unmeetable-deadline shedding.

Requests carry soft deadlines: ``ttft_deadline_ms`` (first token within
this many ms of submit) and ``tpot_deadline_ms`` (per-token cadence after
the first).  Scheduling is EDF on each request's NEXT obligation:

* queued / prefilling — the absolute TTFT deadline (``inf`` when unset,
  so best-effort traffic runs after all deadlined traffic, FCFS among
  itself);
* decoding (victim ranking only) — the next token's cadence deadline
  ``t_first + tpot * (steps + 1)`` when a per-token deadline is set, else
  ``inf`` (a best-effort decoder is always the first preemption victim).

Shedding answers a request whose deadline cannot be met *now* instead of
spending pool pages on a guaranteed miss: a queued request is dropped when
its TTFT deadline has already passed, or when the predicted queue wait —
EDF position x the engine's retirement EMA — overshoots it.  Shed
requests fail with :class:`RequestShed` (HTTP 503 + Retry-After), which a
client should treat as load feedback, not an error in its request.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

from megatron_llm_tpu.generation.scheduling.policy import (
    SchedulerPolicy,
    SchedulerState,
    register_policy,
)

__all__ = ["SloPolicy", "next_obligation_deadline", "ttft_deadline"]


def ttft_deadline(req) -> float:
    """Absolute first-token deadline (monotonic seconds; inf if unset)."""
    if req.ttft_deadline_ms is None:
        return math.inf
    return req._t_submit + req.ttft_deadline_ms / 1e3


def next_obligation_deadline(req) -> float:
    """The deadline of the request's next token: TTFT until the first
    token lands, then the per-token cadence.  A decoding request with a
    TTFT deadline but no cadence deadline keeps its TTFT deadline as its
    value — NOT ``inf`` — so a freshly queued request from the same burst
    (necessarily a later deadline) cannot preempt it; only genuinely
    best-effort decoders rank as ``inf`` (first victims)."""
    if req._t_first == 0.0:
        return ttft_deadline(req)
    if req.tpot_deadline_ms is not None:
        return req._t_first + (req._step + 1) * req.tpot_deadline_ms / 1e3
    return ttft_deadline(req)


@register_policy
class SloPolicy(SchedulerPolicy):
    name = "slo"
    barrier_admission = False

    def _order(self, reqs: Sequence) -> List:
        return sorted(reqs, key=lambda r: (ttft_deadline(r), r._seqno))

    def admission_order(self, queued: Sequence,
                        state: SchedulerState) -> List:
        return self._order(queued)

    def prefill_order(self, prefilling: Sequence,
                      state: SchedulerState) -> List:
        return self._order(prefilling)

    def shed(self, queued: Sequence, state: SchedulerState
             ) -> List[Tuple[object, str]]:
        out = []
        for pos, req in enumerate(self._order(queued)):
            dl = ttft_deadline(req)
            if dl is math.inf:
                continue  # best-effort requests never shed on deadline
            if state.now > dl:
                out.append((req, "ttft deadline already passed"))
                continue
            eta = state.drain_eta(pos)
            if eta is not None and state.now + eta > dl:
                out.append((req, "predicted queue wait exceeds ttft "
                                 "deadline"))
        return out

    def preempt_victim(self, candidate, decoding: Sequence,
                       state: SchedulerState) -> Optional[object]:
        if not (self.preemption and state.can_preempt):
            return None
        cand_dl = ttft_deadline(candidate)
        if cand_dl is math.inf:
            return None  # best-effort work never preempts anyone
        victims = [r for r in decoding
                   if next_obligation_deadline(r) > cand_dl]
        if not victims:
            return None
        # latest obligation (inf = best-effort) loses; among equals the
        # least-progressed resume is cheapest
        return max(victims, key=lambda r: (next_obligation_deadline(r),
                                           -len(r.generated)))
