"""Text generation — megatron/text_generation analog."""

from megatron_llm_tpu.generation.api import InferenceEngine
from megatron_llm_tpu.generation.generation import (
    beam_search,
    generate_tokens,
    score_tokens,
)
from megatron_llm_tpu.generation.sampling import sample

__all__ = [
    "InferenceEngine",
    "beam_search",
    "generate_tokens",
    "score_tokens",
    "sample",
]
