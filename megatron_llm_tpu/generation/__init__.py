"""Text generation — megatron/text_generation analog, plus the
continuous-batching serving engine (generation/engine.py)."""

from megatron_llm_tpu.generation.api import InferenceEngine
from megatron_llm_tpu.generation.engine import (
    ContinuousBatchingEngine,
    EngineOverloaded,
    EngineRequest,
    PagedKVPool,
    PrefixCache,
)
from megatron_llm_tpu.generation.generation import (
    beam_search,
    generate_tokens,
    score_tokens,
)
from megatron_llm_tpu.generation.sampling import sample, sample_per_slot
from megatron_llm_tpu.generation.scheduling import (
    RequestShed,
    SchedulerPolicy,
    get_policy,
)
from megatron_llm_tpu.generation.speculative import DraftModel, resolve_draft

__all__ = [
    "ContinuousBatchingEngine",
    "DraftModel",
    "EngineOverloaded",
    "EngineRequest",
    "InferenceEngine",
    "PagedKVPool",
    "PrefixCache",
    "RequestShed",
    "SchedulerPolicy",
    "beam_search",
    "generate_tokens",
    "get_policy",
    "resolve_draft",
    "sample",
    "sample_per_slot",
    "score_tokens",
]
