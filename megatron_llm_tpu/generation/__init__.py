"""Text generation — megatron/text_generation analog, plus the
continuous-batching serving engine (generation/engine.py)."""

from megatron_llm_tpu.generation.api import InferenceEngine
from megatron_llm_tpu.generation.engine import (
    ContinuousBatchingEngine,
    EngineRequest,
    PagedKVPool,
)
from megatron_llm_tpu.generation.generation import (
    beam_search,
    generate_tokens,
    score_tokens,
)
from megatron_llm_tpu.generation.sampling import sample, sample_per_slot

__all__ = [
    "ContinuousBatchingEngine",
    "EngineRequest",
    "InferenceEngine",
    "PagedKVPool",
    "beam_search",
    "generate_tokens",
    "sample",
    "sample_per_slot",
    "score_tokens",
]
