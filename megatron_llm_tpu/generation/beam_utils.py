"""Top-k beam hypothesis container for beam search decoding.

Keeps the k best finished hypotheses by length-normalized score
(score = sum_logprobs / length**length_penalty) in a min-heap, so insertion
is O(log k) and the current admission threshold (the worst kept score) is
the heap root. ``is_done`` implements the standard beam-search stopping
rule: once k hypotheses are kept and even the best possible completion of
any open beam (optimistically length-normalized at the current length)
cannot beat the worst kept score, decoding can stop.

Role analog: megatron/text_generation/beam_utils.py (whose container is the
HuggingFace list-based implementation); this one is an independent
heap-based design around the same decode loop contract
(add / is_done / beams).
"""

from __future__ import annotations

import heapq
from itertools import count
from typing import Any, List, Tuple


class BeamHypotheses:
    def __init__(self, num_beams: int, length_penalty: float = 1.0,
                 early_stopping: bool = False):
        self.num_beams = num_beams
        self.length_penalty = length_penalty
        self.early_stopping = early_stopping
        # min-heap of (normalized_score, tiebreak, tokens): the root is the
        # worst kept hypothesis, i.e. the admission threshold
        self._heap: List[Tuple[float, int, Any]] = []
        self._tiebreak = count()  # token arrays are not orderable

    def __len__(self) -> int:
        return len(self._heap)

    @property
    def beams(self) -> List[Tuple[float, Any]]:
        """Kept hypotheses as (normalized_score, tokens), unordered."""
        return [(score, tokens) for score, _, tokens in self._heap]

    def _threshold(self) -> float:
        return self._heap[0][0] if self._heap else float("-inf")

    def add(self, hyp, sum_logprobs: float, length: int) -> None:
        score = sum_logprobs / length ** self.length_penalty
        entry = (score, next(self._tiebreak), hyp)
        if len(self._heap) < self.num_beams:
            heapq.heappush(self._heap, entry)
        elif score > self._threshold():
            heapq.heapreplace(self._heap, entry)

    def is_done(self, best_sum_logprobs: float, cur_len: int) -> bool:
        """True when no open beam can still improve the kept set."""
        if len(self._heap) < self.num_beams:
            return False
        if self.early_stopping:
            return True
        optimistic = best_sum_logprobs / cur_len ** self.length_penalty
        return self._threshold() >= optimistic
