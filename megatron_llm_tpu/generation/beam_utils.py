"""Beam hypothesis container — megatron/text_generation/beam_utils.py analog
(BeamHypotheses:19-64, itself from HuggingFace). Host-side bookkeeping; holds
numpy token arrays."""

from __future__ import annotations


class BeamHypotheses:
    def __init__(self, num_beams: int, length_penalty: float = 1.0,
                 early_stopping: bool = False):
        self.length_penalty = length_penalty
        self.early_stopping = early_stopping
        self.num_beams = num_beams
        self.beams = []  # list of (score, tokens)
        self.worst_score = 1e9

    def __len__(self) -> int:
        return len(self.beams)

    def add(self, hyp, sum_logprobs: float, length: int) -> None:
        score = sum_logprobs / length ** self.length_penalty
        if len(self) < self.num_beams or score > self.worst_score:
            self.beams.append((score, hyp))
            if len(self) > self.num_beams:
                sorted_scores = sorted(
                    (s, idx) for idx, (s, _) in enumerate(self.beams)
                )
                del self.beams[sorted_scores[0][1]]
                self.worst_score = sorted_scores[1][0]
            else:
                self.worst_score = min(score, self.worst_score)

    def is_done(self, best_sum_logprobs: float, cur_len: int) -> bool:
        """No remaining open beam can beat the worst kept hypothesis."""
        if len(self) < self.num_beams:
            return False
        if self.early_stopping:
            return True
        return self.worst_score >= best_sum_logprobs / cur_len ** self.length_penalty
