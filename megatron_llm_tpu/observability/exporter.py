"""Lightweight HTTP exposition endpoint: ``/metrics`` + ``/profile``.

Stdlib ``ThreadingHTTPServer`` (same choice as generation/server.py —
Flask is not baked into the TPU image) on a daemon thread, so scraping
never rides the training loop's thread.  Routes:

* ``GET /metrics``   — Prometheus text (registry.render()), version 0.0.4;
* ``GET /healthz``   — liveness JSON;
* ``GET|POST /profile?steps=N`` — arm an on-demand ``jax.profiler`` window
  (observability/profiler.py); the driver starts the capture at its next
  step boundary.  409 when a capture is already pending/active or the
  bounded capture budget is spent; 503 when no trigger is wired (e.g. the
  generation server, which exposes ``/metrics`` on its own port instead).

``pretrain`` starts one when ``--metrics_port`` is set (port 0 binds an
ephemeral port — tests and multi-job hosts) and stops it on every exit
path.  The generation server does NOT use this class: it serves
``/metrics`` from its existing handler alongside ``/health``.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from megatron_llm_tpu.observability.registry import (
    MetricsRegistry,
    get_registry,
)

__all__ = ["MetricsExporter", "PROM_CONTENT_TYPE", "active_exporter"]

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_ACTIVE: Optional["MetricsExporter"] = None


def active_exporter() -> Optional["MetricsExporter"]:
    """The most recently started exporter (None when stopped) — lets
    in-process probes find the bound port without plumbing it around."""
    return _ACTIVE


class MetricsExporter:
    """Serve a metrics registry (and optionally a profile trigger)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 profile_trigger=None, host: str = "0.0.0.0",
                 port: int = 0):
        self.registry = registry or get_registry()
        self.profile_trigger = profile_trigger
        self.host = host
        self.port = int(port)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # ---- handler ----

    def _make_handler(exporter):  # noqa: N805 — enclosing-object idiom
        class Handler(BaseHTTPRequestHandler):
            def _send(self, code: int, body: str, content_type: str):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _send_json(self, code: int, obj) -> None:
                self._send(code, json.dumps(obj), "application/json")

            def do_GET(self):
                url = urlparse(self.path)
                route = url.path.rstrip("/") or "/"
                if route == "/metrics":
                    return self._send(200, exporter.registry.render(),
                                      PROM_CONTENT_TYPE)
                if route == "/healthz":
                    return self._send_json(200, {"status": "ok"})
                if route == "/profile":
                    return self._profile(url)
                return self._send_json(404, {"error": "not found"})

            do_POST = do_GET  # /profile is natural as POST too

            def _profile(self, url) -> None:
                trig = exporter.profile_trigger
                if trig is None:
                    return self._send_json(
                        503, {"error": "no profiler wired on this endpoint"})
                qs = parse_qs(url.query)
                steps = None
                if "steps" in qs:
                    try:
                        steps = int(qs["steps"][0])
                    except ValueError:
                        return self._send_json(
                            400, {"error": "steps must be an integer"})
                res = trig.request(steps)
                return self._send_json(200 if res.get("accepted") else 409,
                                       res)

            def log_message(self, fmt, *args):  # scrapes are chatty
                pass

        return Handler

    # ---- lifecycle ----

    def start(self) -> int:
        """Bind + serve on a daemon thread; returns the bound port."""
        global _ACTIVE
        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          self._make_handler())
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="metrics-exporter")
        self._thread.start()
        _ACTIVE = self
        return self.port

    def stop(self) -> None:
        global _ACTIVE
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if _ACTIVE is self:
            _ACTIVE = None
