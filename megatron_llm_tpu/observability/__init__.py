"""Observability: structured step tracing, unified metrics, profiling.

The cross-cutting layer (docs/guide/observability.md) that makes the
async training loop (training.py), the continuous-batching engine
(generation/engine.py) and the resilience subsystem visible while they
run:

* ``trace``    — sync-free host span tracer -> Chrome/Perfetto JSON;
* ``registry`` — process-wide counters/gauges/histograms -> Prometheus
  text;
* ``exporter`` — HTTP ``/metrics`` + ``/profile`` endpoint
  (``--metrics_port``);
* ``profiler`` — on-demand ``jax.profiler`` windows (SIGUSR2 or
  ``/profile?steps=N``);
* ``flops``    — config-derived flops/MFU math shared by driver, bench
  and registry;
* ``flight``   — per-request flight recorder: bounded event logs with an
  exact latency decomposition, served on ``/debug/requests`` and dumped
  by the watchdog.

Package-wide contract, enforced by the ``obs-no-sync`` graftcheck rule
(docs/guide/static-analysis.md): nothing in here may sync the device —
no ``jax.device_get``, no ``block_until_ready`` — because observability
must never perturb the overlap it measures (the PR-2
bitwise-identical-loss guarantee includes running with every instrument
on).  This docstring can name those calls only because the rule is
AST-based: prose is prose, a call is a finding.
"""

from megatron_llm_tpu.observability import flight, flops, registry, trace

__all__ = ["flight", "flops", "registry", "trace"]
