"""On-demand ``jax.profiler`` window capture — the real ``utils/profiler``
the timers.py docstring promised since the seed.

The static ``--profile`` window (training.py) answers "what does step 11
look like"; this module answers the operational question "what does the
job look like RIGHT NOW" without restarting it.  Two triggers arm a
capture:

* ``kill -USR2 <pid>``                    (install_sigusr2)
* ``GET /profile?steps=N`` on the metrics endpoint (exporter.py)

Both only set a flag — the actual ``start_trace``/``stop_trace`` happen
on the driver thread at step boundaries (``maybe_start``/``step_done``),
because the profiler must bracket whole dispatched steps and must never
run from a signal-handler frame.  Output is bounded: at most
``max_captures`` windows per process, each in its own subdirectory of
``out_dir`` (xplane format — open with xprof / tensorboard-profile).
"""

from __future__ import annotations

import os
import signal
import threading
from typing import Callable, Dict, List, Optional

__all__ = ["ProfileTrigger", "install_sigusr2"]


def _jax_start(logdir: str) -> None:
    import jax

    jax.profiler.start_trace(logdir)


def _jax_stop() -> None:
    import jax

    jax.profiler.stop_trace()


class ProfileTrigger:
    """Arm-from-anywhere, capture-on-the-driver profiling window.

    Thread-safe: ``request`` may be called from HTTP handler threads or a
    signal handler; ``maybe_start``/``step_done``/``close`` belong to the
    driver thread (the one dispatching steps).

    Args:
      out_dir: parent directory for capture subdirs (created lazily).
      default_steps: window length when a request names none.
      max_captures: process-lifetime budget — the output dir stays bounded
        no matter how often someone curls ``/profile``.
      start_fn / stop_fn: injection points for tests; default to
        ``jax.profiler.start_trace`` / ``stop_trace``.
    """

    def __init__(self, out_dir: str, default_steps: int = 2,
                 max_captures: int = 8,
                 start_fn: Callable[[str], None] = _jax_start,
                 stop_fn: Callable[[], None] = _jax_stop):
        self.out_dir = out_dir
        self.default_steps = max(int(default_steps), 1)
        self.max_captures = max(int(max_captures), 1)
        self._start_fn = start_fn
        self._stop_fn = stop_fn
        self._lock = threading.Lock()
        # steps wanted, not started — guarded by _lock
        self._requested: Optional[int] = None
        # steps left in live capture — guarded by _lock
        self._remaining: Optional[int] = None
        self.captures = 0
        self.capture_dirs: List[str] = []

    # ---- trigger side (any thread) ----

    def request(self, steps: Optional[int] = None) -> Dict:
        """Arm a capture of ``steps`` steps; returns a status dict (the
        /profile response body)."""
        steps = self.default_steps if steps is None else int(steps)
        if steps < 1:
            return {"accepted": False, "error": "steps must be >= 1"}
        with self._lock:
            if self._requested is not None or self._remaining is not None:
                return {"accepted": False,
                        "error": "a capture is already pending or active"}
            if self.captures >= self.max_captures:
                return {"accepted": False,
                        "error": f"capture budget exhausted "
                                 f"(max_captures={self.max_captures})"}
            self._requested = steps
            return {"accepted": True, "steps": steps,
                    "capture_index": self.captures,
                    "out_dir": self.out_dir}

    @property
    def active(self) -> bool:
        with self._lock:
            return self._remaining is not None

    @property
    def pending(self) -> bool:
        with self._lock:
            return self._requested is not None

    # ---- driver side (step boundaries) ----

    def maybe_start(self, iteration: int) -> Optional[str]:
        """Start a requested capture before dispatching ``iteration``.
        Returns the capture dir when one starts, else None."""
        with self._lock:
            if self._requested is None or self._remaining is not None:
                return None
            steps = self._requested
            self._requested = None
            logdir = os.path.join(
                self.out_dir,
                f"ondemand_{self.captures:03d}_iter{iteration:08d}")
            self.captures += 1
            self.capture_dirs.append(logdir)
            self._remaining = steps
        os.makedirs(logdir, exist_ok=True)
        self._start_fn(logdir)
        return logdir

    def step_done(self) -> bool:
        """Count one finished step against a live window; stops the
        capture when the window completes.  Returns True on stop."""
        with self._lock:
            if self._remaining is None:
                return False
            self._remaining -= 1
            if self._remaining > 0:
                return False
            self._remaining = None
        self._stop_fn()
        return True

    def close(self) -> None:
        """Stop a live capture (early driver exit must not leak one)."""
        with self._lock:
            live, self._remaining = self._remaining is not None, None
            self._requested = None
        if live:
            self._stop_fn()


def install_sigusr2(trigger: ProfileTrigger,
                    steps: Optional[int] = None):
    """Route ``SIGUSR2`` to ``trigger.request``; returns the previous
    handler (restore it when the loop exits), or None when signals cannot
    be installed here (only the main thread may set handlers — tests and
    library embedders call ``pretrain`` from worker threads)."""
    if threading.current_thread() is not threading.main_thread():
        return None

    def _handler(signum, frame):
        trigger.request(steps)  # flag only; capture starts on the driver

    try:
        return signal.signal(signal.SIGUSR2, _handler)
    except (ValueError, OSError, AttributeError):
        return None
