"""Model-config flops accounting: tokens/sec -> TFLOP/s -> MFU.

Megatron-LM's scaling methodology (Narayanan et al., PAPERS.md) treats
per-step time/flops as a first-class training signal; this module is the
single home for that arithmetic — the driver's log line, the ``pretrain``
result dict (``steady_mfu`` / ``tokens_per_sec``), the metrics registry
gauges, and bench.py's measured-MFU line all divide by the same numbers.

Everything here is pure host math over the static model config — no
device contact (lint-enforced for this package).
"""

from __future__ import annotations

from typing import Optional

__all__ = [
    "PEAK_BF16_FLOPS_BY_KIND",
    "PEAK_BF16_FLOPS_SUBSTR",
    "device_peak_flops",
    "flops_per_step",
    "flops_per_token",
    "mfu",
    "param_count",
]

PEAK_BF16_FLOPS_BY_KIND = {
    # per-chip peak dense bf16 FLOP/s, by EXACT device_kind string — the
    # single source of truth (bench.py re-exports; tools/aot_scale_check.py
    # estimates divide by the same numbers the measured MFU divides by)
    "TPU v5 lite": 197e12,
    "TPU v5": 459e12,     # v5p
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,  # Trillium
    "TPU v6e": 918e12,
}
PEAK_BF16_FLOPS_SUBSTR = {
    # substring fallback on normalized device_kind (live-device probing)
    "v5litepod": 197e12,
    "v5lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6e": 918e12,
}


def device_peak_flops(device_kind: str) -> Optional[float]:
    """Peak dense bf16 FLOP/s for a device-kind string, or None when the
    kind is unknown (CPU hosts: an 'MFU' over a nominal CPU peak is not a
    measurement — callers report 0/None instead)."""
    if device_kind in PEAK_BF16_FLOPS_BY_KIND:  # exact kind first (v5p is
        return PEAK_BF16_FLOPS_BY_KIND[device_kind]  # "TPU v5", no substr)
    kind = device_kind.lower().replace(" ", "")
    for key, val in PEAK_BF16_FLOPS_SUBSTR.items():
        if key in kind:
            return val
    return None


def param_count(cfg) -> int:
    """Approximate parameter count from the model config (attention +
    MLP + embeddings; the reference FLOP-estimate family,
    language_model.py:370-384)."""
    m = cfg.model
    h, L = m.hidden_size, m.num_layers
    d = m.kv_channels or h // m.num_attention_heads
    n, nkv = m.num_attention_heads, m.num_attention_heads_kv or n
    ffn = m.ffn_hidden_size
    glu = 2 if m.glu_activation else 1
    per_layer = h * (n + 2 * nkv) * d + n * d * h + h * ffn * glu + ffn * h
    v = m.vocab_size or 32000
    emb = v * h * (1 if m.tie_embed_logits else 2)
    return per_layer * L + emb


def flops_per_token(cfg) -> float:
    """Matmul FLOPs per token, fwd+bwd: ``6*N`` dense plus the causal
    attention matmuls (QK^T and AV: 4*s^2*h per layer per sequence
    non-causal fwd, /2 causal, x3 fwd+bwd => 6*L*s*h per token)."""
    m = cfg.model
    attn = 6.0 * m.num_layers * m.hidden_size * cfg.data.seq_length
    return 6.0 * param_count(cfg) + attn


def flops_per_step(cfg, global_batch_size: Optional[int] = None) -> float:
    """Whole-step (all microbatches) matmul FLOPs from the config."""
    gbs = global_batch_size or cfg.training.global_batch_size or 1
    return flops_per_token(cfg) * gbs * cfg.data.seq_length


def mfu(cfg, tokens_per_sec: float,
        peak: Optional[float] = None,
        device_kind: Optional[str] = None,
        n_devices: int = 1) -> Optional[float]:
    """Model flops utilization (fraction) at a measured token rate.

    ``peak`` wins when given; otherwise it is looked up from
    ``device_kind``.  Returns None when no peak is known (CPU) — the
    callers publish 0.0 / omit the field rather than a made-up number."""
    if peak is None and device_kind is not None:
        peak = device_peak_flops(device_kind)
    if not peak or tokens_per_sec <= 0:
        return None
    return flops_per_token(cfg) * tokens_per_sec / (peak * max(n_devices, 1))
