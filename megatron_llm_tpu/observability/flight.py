"""Per-request flight recorder: a bounded, lock-disciplined event log.

PR 4 gave the process a span ring and aggregate counters; what it could
not answer is the per-request question operators actually ask: *this*
request missed its SLO / 503'd / hung — what happened to it?  The flight
recorder answers that.  Every request the decode engine touches gets a
:class:`RequestRecord`: a bounded event log (enqueue, admit, prefill
chunks, preempt/resume, speculative ticks, first token, stop/shed) with
monotonic timestamps, plus an exact **latency decomposition** — every
second between submit and retirement falls into exactly one of four
phase buckets (``queued`` / ``prefill`` / ``decode`` / ``preempted``),
so the components provably sum to the measured TTFT and total latency.

The same hot-path contract as trace.py and registry.py (enforced by the
``obs-no-sync`` graftcheck rule): pure host arithmetic, O(1) per event,
never any device work.  Values recorded must already live on the host —
the ``span-device-attr`` rule flags device arrays passed as event attrs,
because a traced jax array would force a host sync at dump time.

Bounding: the recorder keeps at most ``capacity`` retired records (a
ring — oldest drop) plus whatever is genuinely in flight; each record
keeps at most ``events_per_request`` events (oldest drop, with an honest
``dropped_events`` count — terminal events are the newest, so they
always survive).

Consumers:

* ``GET /debug/requests`` on the generation server serves recent records
  as JSON; the router aggregates every replica's endpoint fleet-wide
  (docs/guide/observability.md "Request tracing & flight recorder").
* The step watchdog dumps in-flight records next to its thread-stack and
  trace dumps, so a hang is attributable to a specific request state
  (resilience/watchdog.py).
* The engine derives its honest TTFT decomposition histograms
  (``mlt_engine_queue_wait_seconds`` etc.) from retired records.

One lock (the recorder's) covers the recorder *and* every record it
issued: record mutators run under it, so a ``/debug/requests`` snapshot
taken mid-tick can never see a half-updated record.  The engine calls
into the recorder while holding its own lock; the recorder never calls
back out, so the lock order is engine -> recorder, acyclic.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = [
    "FlightRecorder",
    "NULL_RECORD",
    "RequestRecord",
    "get_recorder",
    "set_recorder",
]

#: Phase buckets of the latency decomposition.  A request is in exactly
#: one at any instant: ``queued`` (submitted, no slot yet), ``prefill``
#: (admitted, prompt K/V filling), ``decode`` (emitting tokens),
#: ``preempted`` (pages released, waiting to re-admit), ``handoff``
#: (prefill done on a prefill-role replica, pages being exported to
#: the decode target — ISSUE 19; such requests never enter ``decode``).
PHASES = ("queued", "prefill", "decode", "preempted", "handoff")


class RequestRecord:
    """One request's flight log + phase-bucketed latency accounting.

    Mutators take the owning recorder's lock (shared — see module doc);
    ``*_locked`` readers document the callers that already hold it."""

    __slots__ = (
        "_lock", "trace_id", "meta", "t_submit", "wall_submit",
        "events", "dropped_events", "phase", "_phase_since", "phase_s",
        "prefill_compute_s", "hit_tokens", "preemptions",
        "spec_drafted", "spec_accepted", "t_first", "t_done",
        "ttft_phase_s", "outcome", "finished", "enabled",
    )

    def __init__(self, trace_id: str, lock: threading.Lock,
                 events_cap: int, t_submit: Optional[float] = None,
                 **meta: Any):
        # the owner hands one lock to every record it issues; the
        # annotation below merges the two nodes in graftcheck's
        # lock-order graph
        self._lock = lock  # shared lock: FlightRecorder._lock
        self.enabled = True
        self.trace_id = trace_id
        self.meta = meta
        self.t_submit = (time.monotonic() if t_submit is None
                         else float(t_submit))
        self.wall_submit = time.time()
        # newest events win the bounded ring: terminal events (first
        # token, stop, shed) are by construction the newest, so a chatty
        # spec-tick history can never push them out — guarded by _lock
        self.events: deque = deque(maxlen=max(int(events_cap), 4))
        self.dropped_events = 0          # guarded by _lock
        self.phase = "queued"            # guarded by _lock
        self._phase_since = self.t_submit  # guarded by _lock
        # seconds spent per phase; the decomposition — guarded by _lock
        self.phase_s: Dict[str, float] = {p: 0.0 for p in PHASES}
        # device wall attributed to this request's prefill work (exact
        # for the legacy one-chunk dispatch; a proportional share of the
        # fused launch in ragged mode) — guarded by _lock
        self.prefill_compute_s = 0.0
        self.hit_tokens = 0              # guarded by _lock
        self.preemptions = 0             # guarded by _lock
        self.spec_drafted = 0            # guarded by _lock
        self.spec_accepted = 0           # guarded by _lock
        self.t_first = 0.0               # guarded by _lock
        self.t_done = 0.0                # guarded by _lock
        # decomposition frozen at first token (sums to TTFT exactly)
        self.ttft_phase_s: Optional[Dict[str, float]] = None  # guarded by _lock
        self.outcome: Optional[str] = None  # guarded by _lock
        self.finished = False            # guarded by _lock

    # ---- recording (engine hot path) ----

    def _fold_locked(self, now: float) -> None:  # holds _lock
        """Credit the time since the last transition to the current
        phase.  Every instant lands in exactly one bucket, which is what
        makes the decomposition sum to the measured latency."""
        self.phase_s[self.phase] += max(0.0, now - self._phase_since)
        self._phase_since = now

    def _event_locked(self, kind: str, now: float,
                      args: Optional[Dict[str, Any]]) -> None:  # holds _lock
        if len(self.events) == self.events.maxlen:
            self.dropped_events += 1  # append evicts the oldest
        self.events.append((now - self.t_submit, kind, args))

    def event(self, kind: str, **args: Any) -> None:
        now = time.monotonic()
        with self._lock:
            self._event_locked(kind, now, args or None)

    def set_phase(self, phase: str, **args: Any) -> None:
        """Transition phases, folding elapsed time into the old bucket
        and recording the transition as an event."""
        now = time.monotonic()
        with self._lock:
            self._fold_locked(now)
            self.phase = phase
            self._event_locked(phase, now, args or None)

    def note_hit_tokens(self, n: int) -> None:
        with self._lock:
            self.hit_tokens = int(n)

    def note_preemption(self) -> None:
        with self._lock:
            self.preemptions += 1

    def add_prefill_compute(self, seconds: float) -> None:
        with self._lock:
            self.prefill_compute_s += max(0.0, float(seconds))

    def add_spec(self, drafted: int, accepted: int) -> None:
        with self._lock:
            self.spec_drafted += int(drafted)
            self.spec_accepted += int(accepted)

    def mark_first_token(self, now: Optional[float] = None) -> None:
        """First generated token: freeze the TTFT decomposition (the
        live buckets keep accumulating toward total latency)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if self.t_first:
                return
            self._fold_locked(now)
            self.t_first = now
            self.ttft_phase_s = dict(self.phase_s)
            self._event_locked("first_token", now, None)

    def finish(self, outcome: str, now: Optional[float] = None,
               **args: Any) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            if self.finished:
                return
            self._fold_locked(now)
            self.t_done = now
            self.outcome = outcome
            self.finished = True
            self._event_locked(outcome, now, args or None)

    # ---- derived views ----

    def ttft_s(self) -> Optional[float]:
        with self._lock:
            return (self.t_first - self.t_submit) if self.t_first else None

    def latency_s(self) -> Optional[float]:
        with self._lock:
            return (self.t_done - self.t_submit) if self.t_done else None

    def ttft_decomposition(self) -> Optional[Dict[str, float]]:
        """The frozen-at-first-token phase buckets (sum == TTFT)."""
        with self._lock:
            return dict(self.ttft_phase_s) if self.ttft_phase_s else None

    def miss_phase(self) -> str:
        """Which phase to blame for a TTFT deadline miss: the bucket
        that ate the largest share of the TTFT.  Time spent preempted is
        time spent *waiting for a slot again*, so it attributes to
        ``queue`` (the exported label set is queue|prefill|decode)."""
        d = self.ttft_decomposition()
        if not d:
            return "queue"
        merged = {
            "queue": d.get("queued", 0.0) + d.get("preempted", 0.0),
            "prefill": d.get("prefill", 0.0),
            "decode": d.get("decode", 0.0),
        }
        return max(merged, key=merged.get)  # type: ignore[arg-type]

    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            return self._to_dict_locked(time.monotonic())

    def _to_dict_locked(self, now: float) -> Dict[str, Any]:  # holds _lock
        live = dict(self.phase_s)
        if not self.finished:  # include the still-open bucket honestly
            live[self.phase] = (live.get(self.phase, 0.0)
                                + max(0.0, now - self._phase_since))
        d: Dict[str, Any] = {
            "trace_id": self.trace_id,
            "phase": "finished" if self.finished else self.phase,
            "outcome": self.outcome,
            "submitted_unix": round(self.wall_submit, 6),
            "age_s": round((self.t_done or now) - self.t_submit, 6),
            "ttft_s": (round(self.t_first - self.t_submit, 6)
                       if self.t_first else None),
            "latency_s": (round(self.t_done - self.t_submit, 6)
                          if self.t_done else None),
            "decomposition": {
                "queue_wait_s": round(live.get("queued", 0.0), 6),
                "prefill_s": round(live.get("prefill", 0.0), 6),
                "decode_s": round(live.get("decode", 0.0), 6),
                "preempted_s": round(live.get("preempted", 0.0), 6),
                "handoff_s": round(live.get("handoff", 0.0), 6),
            },
            "prefill_compute_s": round(self.prefill_compute_s, 6),
            "hit_tokens": self.hit_tokens,
            "preemptions": self.preemptions,
            "dropped_events": self.dropped_events,
            "events": [
                {"t_s": round(t, 6), "kind": kind,
                 **({"args": args} if args else {})}
                for t, kind, args in self.events],
        }
        if self.ttft_phase_s is not None:
            d["ttft_decomposition"] = {
                "queue_wait_s": round(self.ttft_phase_s.get("queued", 0.0), 6),
                "prefill_s": round(self.ttft_phase_s.get("prefill", 0.0), 6),
                "decode_s": round(self.ttft_phase_s.get("decode", 0.0), 6),
                "preempted_s": round(
                    self.ttft_phase_s.get("preempted", 0.0), 6),
                "handoff_s": round(
                    self.ttft_phase_s.get("handoff", 0.0), 6),
            }
        if self.spec_drafted:
            d["spec"] = {"drafted": self.spec_drafted,
                         "accepted": self.spec_accepted}
        if self.meta:
            d["meta"] = dict(self.meta)
        return d


class _NullRecord:
    """Shared no-op record: the disabled-recorder fast path.  Every
    mutator is a no-op and every derived view is empty, so engine code
    stays branch-free (it only checks ``enabled`` before paying for a
    histogram observation)."""

    __slots__ = ()
    enabled = False
    trace_id = ""
    hit_tokens = 0
    preemptions = 0
    prefill_compute_s = 0.0

    def event(self, kind, **args):
        pass

    def set_phase(self, phase, **args):
        pass

    def note_hit_tokens(self, n):
        pass

    def note_preemption(self):
        pass

    def add_prefill_compute(self, seconds):
        pass

    def add_spec(self, drafted, accepted):
        pass

    def mark_first_token(self, now=None):
        pass

    def finish(self, outcome, now=None, **args):
        pass

    def ttft_s(self):
        return None

    def latency_s(self):
        return None

    def ttft_decomposition(self):
        return None

    def miss_phase(self):
        return "queue"

    def to_dict(self):
        return {}


NULL_RECORD = _NullRecord()


class FlightRecorder:
    """Bounded per-request record store: in-flight dict + retired ring.

    ``capacity`` bounds retired records (ring; oldest drop with an
    honest counter), ``events_per_request`` bounds each record's event
    log.  ``enabled=False`` (or capacity 0) makes :meth:`open` hand out
    the shared :data:`NULL_RECORD` — the engine's recording calls become
    no-ops and nothing allocates."""

    def __init__(self, capacity: int = 256, events_per_request: int = 64,
                 enabled: bool = True):
        self.capacity = max(int(capacity), 0)
        self.events_per_request = max(int(events_per_request), 4)
        self.enabled = bool(enabled) and self.capacity > 0
        self._lock = threading.Lock()
        # open (not yet closed) records, insertion-ordered — guarded by _lock
        self._inflight: Dict[int, RequestRecord] = {}
        # retired records, newest last — guarded by _lock
        self._done: deque = deque(maxlen=self.capacity or 1)
        self._seq = 0           # guarded by _lock
        self._ids: Dict[int, int] = {}  # id(record) -> seq — guarded by _lock
        self._evicted = 0       # retired records pushed out — guarded by _lock

    # ---- lifecycle (engine calls) ----

    def open(self, trace_id: str, **meta: Any):
        """Start a record for a just-submitted request.  Returns the
        shared null record when disabled."""
        if not self.enabled:
            return NULL_RECORD
        rec = RequestRecord(trace_id, self._lock,
                            self.events_per_request, **meta)
        with self._lock:
            self._seq += 1
            self._inflight[self._seq] = rec
            self._ids[id(rec)] = self._seq
        return rec

    def close(self, rec) -> None:
        """Move a finished record from in-flight to the retired ring."""
        if rec is None or not getattr(rec, "enabled", False):
            return
        with self._lock:
            seq = self._ids.pop(id(rec), None)
            if seq is not None:
                self._inflight.pop(seq, None)
            if len(self._done) == self._done.maxlen:
                self._evicted += 1
            self._done.append(rec)

    # ---- inspection / export ----

    @property
    def inflight(self) -> int:
        with self._lock:
            return len(self._inflight)

    @property
    def evicted(self) -> int:
        with self._lock:
            return self._evicted

    def _records(self) -> List[RequestRecord]:
        """In-flight first (oldest submit first), then retired newest
        first — the order ``/debug/requests`` serves."""
        with self._lock:
            return list(self._inflight.values()) + list(
                reversed(self._done))

    def snapshot(self, n: Optional[int] = None,
                 trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
        """JSON-ready dicts of recent records (see :meth:`_records` for
        the order), optionally filtered by trace id and capped at ``n``."""
        recs = self._records()
        if trace_id is not None:
            recs = [r for r in recs if r.trace_id == trace_id]
        if n is not None:
            recs = recs[: max(int(n), 0)]
        return [r.to_dict() for r in recs]

    def lookup(self, trace_id: str) -> List[Dict[str, Any]]:
        """All records carrying ``trace_id`` (a multi-prompt request
        opens one per prompt, sharing the id)."""
        return self.snapshot(trace_id=trace_id)

    def dump(self, path: str) -> str:
        """Atomic JSON dump (the watchdog's emergency format): every
        in-flight and retired record, plus the bound-honesty counters."""
        doc = {
            "records": self.snapshot(),
            "inflight": self.inflight,
            "capacity": self.capacity,
            "evicted_records": self.evicted,
        }
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path

    def write_text(self, stream, limit: int = 32) -> None:
        """Human-readable tail for hang reports without a dump dir: the
        in-flight records' phase + decomposition, newest activity last."""
        recs = self.snapshot(n=limit)
        if not recs:
            return
        print(f"FLIGHT: {len(recs)} request records "
              f"({self.inflight} in flight):", file=stream)
        for r in recs:
            d = r["decomposition"]
            print(f"  [{r['trace_id'] or '-'}] phase={r['phase']} "
                  f"age={r['age_s']:.3f}s queue={d['queue_wait_s']:.3f} "
                  f"prefill={d['prefill_s']:.3f} "
                  f"decode={d['decode_s']:.3f} "
                  f"preempted={d['preempted_s']:.3f} "
                  f"events={len(r['events'])}", file=stream)
        stream.flush()


# ---------------------------------------------------------------------------
# Process-wide recorder (the watchdog's fallback dump source)
# ---------------------------------------------------------------------------

_RECORDER: Optional[FlightRecorder] = None


def set_recorder(rec: Optional[FlightRecorder]) -> None:
    """Register the process's flight recorder (the engine does this at
    construction) so the watchdog's emergency dump can find it without
    plumbing."""
    global _RECORDER
    _RECORDER = rec


def get_recorder() -> Optional[FlightRecorder]:
    return _RECORDER
